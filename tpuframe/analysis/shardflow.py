"""Static detectors over the collective-flow graph + derived budgets.

``hlo_audit`` polices *volume* (bytes per collective class against the
declared ceilings).  This module polices *structure*, on the typed graph
:mod:`tpuframe.analysis.collective_graph` builds from the same optimized
HLO:

  (a) :func:`detect_redundant_pairs` — an all-gather feeding a
      reduce-scatter of the same value over the same groups (the pair is
      a resharding no-op GSPMD should have cancelled), and duplicate
      all-reduces on one def (same operands, groups, and reduce fn —
      the sharding-annotation mistake that syncs a gradient twice).
  (b) :func:`detect_wire_dtype` — a floating collective wider than the
      strategy's declared wire dtype (an f32 gradient on a wire the
      strategy declares bf16 silently doubles every budget).  Quantized
      wire formats register through :func:`register_wire_format` — the
      allowlist seam the EQuARX-style compressed collectives (ROADMAP
      item 2, arXiv:2506.17615) will occupy, so the quantization wire
      contract is declared here once instead of per-detector.
  (c) :func:`detect_replication` — a tensor the strategy declares
      sharded showing up among the entry parameters at its full
      (replicated) shape above a size floor: the accidental-replication
      failure GSPMD commits silently when one in_sharding is missing.
  (d) :func:`detect_replica_groups` — structural validity of every
      collective's replica groups against the strategy's declared mesh
      (equal sizes, disjoint, complete cover, group size a product of
      declared mesh axes) — the consistency check hierarchical
      ICI×DCN meshes (ROADMAP item 3, arXiv:2011.03641) will need
      per-slice.

From the same program the *exact* per-kind communication budget is
derived (:func:`derive_budget`, measured by ``hlo_audit``'s wire-traffic
ruler so derivation and ceiling audits never disagree) and diffed
against the checked-in declarations in ``derived_budgets.json`` —
drift in either direction fails the gate, and ``python -m
tpuframe.analysis --emit-budgets`` regenerates the file from one source
of truth.  ``budgets.py``'s hand-declared class ceilings stay as policy
(which *kinds* may exist at what order of magnitude); the derived file
is the byte-exact record of what the compiler actually emits today.

Analysis v3 adds the *schedule* plane on top of the structural one:

  (e) :func:`detect_exposed_comm` — async collective starts consumed
      back-to-back (zero overlap window).  Pairing failures (a start
      whose ``-done`` the chase cannot find) surface unconditionally;
      the zero-window finding itself only FAILS strategies that declare
      themselves overlapped (``StrategyMeta.declared_overlapped``) —
      CPU-compiled audit programs have no async scheduler, so today's
      strategies are reported exposed, not failed.
  (f) the per-strategy schedule/liveness record
      (:func:`derive_schedule_entry` — peak live bytes, un-donated
      doubled-residency inputs, window census) is pinned in
      ``derived_schedule.json`` under the exact ``--emit-budgets``
      contract: jax-version-stamped, drift in either direction fails,
      ``python -m tpuframe.analysis --emit-schedule`` regenerates it.
  (g) :func:`overlap_score` — hideable-comm milliseconds (roofline ICI
      model over each collective's wire bytes, capped by the HBM
      roofline over the compute legally interleavable with it) as a
      fraction of total comm: the ranked target list the bucketed-fusion
      work (ROADMAP item 4, arXiv:1802.05799) starts from, and the
      regression sentry it will be judged against.

Stdlib-only at import time (the ``hlo_audit`` contract); jax is touched
only inside the gate entry points that already run under the analysis
CLI's scrubbed child process.
"""

from __future__ import annotations

import itertools
import json
import os
import re
from collections import Counter

from tpuframe.analysis import collective_graph as cg
from tpuframe.analysis import hlo_audit

#: schema version of both the --json report and derived_budgets.json.
#: v2: per-strategy "schedule" (liveness/window census), "overlap"
#: (roofline overlap-potential score), and the exposed_comm detector.
#: v3: per-strategy "comm_split" — ICI vs DCN byte attribution from the
#: materialized replica groups against the declared hierarchical mesh.
REPORT_SCHEMA = 3

DERIVED_BUDGETS_PATH = os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "derived_budgets.json")

DERIVED_SCHEDULE_PATH = os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "derived_schedule.json")

#: golden --compare pair the jax-free selfcheck validates (pins both the
#: report schema and the schedule section of the differ).
SAMPLES_COMPARE_DIR = os.path.normpath(os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    "..", "..", "docs", "samples", "analysis_compare"))

#: floating wire dtypes by width; integer/pred collectives are index
#: bookkeeping and never wire-dtype findings.
_FLOAT_WIDTHS = {"f64": 8, "f32": 4, "bf16": 2, "f16": 2}

#: size floor for the replication detector — below this a replicated
#: tensor is a scalar/norm/metric, not the HBM-capacity failure class.
REPLICATION_FLOOR = 4096

# ---------------------------------------------------------------------------
# The quantized-wire allowlist seam (ROADMAP item 2's registration point).
# ---------------------------------------------------------------------------

_WIRE_FORMATS: dict[str, frozenset] = {}


def register_wire_format(name: str, dtypes) -> None:
    """Declare a compressed/quantized wire format: collectives carrying
    only ``dtypes`` are then exempt from the wire-dtype audit regardless
    of the strategy's declared dtype (EQuARX-style int8/bf16 blocks ride
    under the name they registered, not under a silent exemption)."""
    _WIRE_FORMATS[name] = frozenset(dtypes)


def registered_wire_formats() -> dict[str, frozenset]:
    return dict(_WIRE_FORMATS)


def _wire_exempt(dtypes: frozenset) -> str | None:
    """Name of the registered wire format covering ``dtypes``, if any."""
    for name, allowed in _WIRE_FORMATS.items():
        if dtypes <= allowed:
            return name
    return None


# The EQuARX-style block-quantized wire (tpuframe.parallel.quantwire,
# arXiv:2506.17615): s8 payload collectives are the declared compressed
# format.  The f32 block scales ride their own small collectives and are
# deliberately NOT exempted — a registration containing f32 would cover
# every full-precision collective and blind the detector (the seeded
# positive below pins that).
register_wire_format("int8-block", {"s8"})


# A minimal optimized-HLO program with one gradient-sized f32 all-reduce.
# Under a declared bf16 wire this MUST stay a finding even with quantized
# formats registered — proves registration exempts only its own payload
# dtype, never full-precision strays.
_SEEDED_WIRE_HLO = """\
HloModule seeded_wire_positive

%add (a: f32[], b: f32[]) -> f32[] {
  %a = f32[] parameter(0)
  %b = f32[] parameter(1)
  ROOT %r = f32[] add(f32[] %a, f32[] %b)
}

ENTRY %main (p0: f32[65536]) -> f32[65536] {
  %p0 = f32[65536]{0} parameter(0)
  ROOT %ar = f32[65536]{0} all-reduce(f32[65536]{0} %p0), replica_groups={}, to_apply=%add
}
"""


def seeded_wire_positive() -> list[str]:
    """Self-test of the wire-dtype detector: the seeded f32-under-bf16
    program must yield exactly one finding.  Zero findings means a wire
    registration (e.g. an int8 format accidentally including f32) has
    silently blinded the detector; returns problem strings for the gate."""
    graph = cg.parse_graph(_SEEDED_WIRE_HLO)
    found = detect_wire_dtype(graph, "bf16")
    if len(found) != 1:
        return [f"seeded wire-dtype positive: expected exactly 1 finding "
                f"for an f32 all-reduce under a declared bf16 wire, got "
                f"{len(found)} — a registered wire format "
                f"({sorted(_WIRE_FORMATS)}) is exempting full-precision "
                f"payloads: {found}"]
    return []


# ---------------------------------------------------------------------------
# Detectors.  Each takes the graph (plus strategy facts) and returns
# finding strings; empty list == clean.
# ---------------------------------------------------------------------------


def _groups_key(node: cg.Node):
    if node.replica_groups is not None:
        return tuple(tuple(g) for g in node.replica_groups)
    return node.iota_groups


def detect_redundant_pairs(graph: cg.CollectiveGraph) -> list[str]:
    """(a) all-gather → reduce-scatter of one value over one group set,
    and duplicate all-reduces on one def."""
    findings: list[str] = []
    for comp in graph.computations.values():
        for node in comp.collectives():
            if node.kind != "reduce-scatter":
                continue
            for operand in node.operands:
                src_name = comp.resolve_value(operand)
                src = comp.nodes.get(src_name)
                if (src is not None and src.kind == "all-gather"
                        and _groups_key(src) == _groups_key(node)):
                    findings.append(
                        f"redundant pair in %{comp.name}: "
                        f"reduce-scatter %{node.name} consumes all-gather "
                        f"%{src.name} over the same replica groups — the "
                        f"gather/scatter round-trip is a no-op resharding "
                        f"({node.line})")
        by_def: dict[tuple, list[cg.Node]] = {}
        for node in comp.collectives():
            if node.kind != "all-reduce":
                continue
            roots = tuple(comp.resolve_value(o) for o in node.operands)
            reduce_fn = _reduce_fn(graph, node)
            by_def.setdefault((roots, _groups_key(node), reduce_fn),
                              []).append(node)
        for (roots, _, fn), nodes in sorted(by_def.items()):
            if len(nodes) > 1:
                names = ", ".join(f"%{n.name}" for n in nodes)
                findings.append(
                    f"duplicate all-reduce in %{comp.name}: {names} all "
                    f"{fn}-reduce the same def(s) "
                    f"{', '.join('%' + r for r in roots)} over the same "
                    f"groups — one collective's result should be reused")
    return findings


def _reduce_fn(graph: cg.CollectiveGraph, node: cg.Node) -> str:
    """Root opcode of the collective's to_apply computation ('add',
    'maximum', ...) — the semantic reduce fn, stable across the
    compiler's region-name suffixes."""
    for called in node.called:
        comp = graph.computations.get(called)
        if comp is not None and comp.root and comp.root in comp.nodes:
            return comp.nodes[comp.root].op
    return "?"


def detect_wire_dtype(graph: cg.CollectiveGraph, wire_dtype: str,
                      *, ignore_below: int = 0) -> list[str]:
    """(b) collectives carrying a float dtype wider than declared."""
    declared_w = _FLOAT_WIDTHS.get(wire_dtype)
    if declared_w is None:
        return [f"unknown declared wire dtype {wire_dtype!r} "
                f"(expected one of {sorted(_FLOAT_WIDTHS)})"]
    findings: list[str] = []
    for comp, node in graph.collectives():
        if node.result_bytes < ignore_below:
            continue
        wide = sorted(dt for dt in node.dtypes
                      if _FLOAT_WIDTHS.get(dt, 0) > declared_w)
        if not wide:
            continue
        fmt = _wire_exempt(node.dtypes)
        if fmt is not None:
            continue  # registered quantized wire format
        findings.append(
            f"wire dtype in %{comp.name}: {node.kind} %{node.name} "
            f"carries {'/'.join(wide)} where the strategy declares "
            f"{wire_dtype} on the wire ({node.line})")
    return findings


def detect_replication(graph: cg.CollectiveGraph, declared_leaves,
                       *, floor: int = REPLICATION_FLOOR) -> list[str]:
    """(c) declared-sharded tensors appearing replicated at entry.

    ``declared_leaves``: iterable of ``(dtype, full_dims, shard_dims)``
    for every state leaf the strategy declares a sharding for (HLO dtype
    spelling, dim tuples).  A leaf whose per-device shape should differ
    from its full shape must NOT appear among the entry parameters at
    the full shape more often than other leaves legitimately land there.
    """
    entry = graph.entry_computation
    if entry is None or not declared_leaves:
        return []
    expected: Counter = Counter()
    for dt, _full, shard in declared_leaves:
        expected[(dt, tuple(shard))] += 1
    actual: Counter = Counter()
    for node in entry.parameters():
        if node.shapes:
            dt, dims = node.shapes[0]
            actual[(dt, tuple(dims))] += 1
    findings: list[str] = []
    flagged: set = set()
    for dt, full, shard in sorted(declared_leaves):
        full, shard = tuple(full), tuple(shard)
        if full == shard or (dt, full) in flagged:
            continue
        n = 1
        for d in full:
            n *= d
        if n * hlo_audit._DTYPE_BYTES.get(dt, 4) < floor:
            continue
        if actual.get((dt, full), 0) > expected.get((dt, full), 0):
            flagged.add((dt, full))
            findings.append(
                f"accidental replication: a {dt}[{','.join(map(str, full))}] "
                f"entry parameter sits at the FULL shape of a leaf this "
                f"strategy declares sharded to "
                f"[{','.join(map(str, shard))}] — one in_sharding is "
                f"missing or GSPMD dropped it")
    return findings


def detect_replica_groups(graph: cg.CollectiveGraph,
                          mesh_shape: dict) -> list[str]:
    """(d) structural validity of replica groups against the mesh."""
    if not mesh_shape:
        return []  # no declared mesh — nothing to check against
    sizes = [int(s) for s in mesh_shape.values()]
    n_devices = 1
    for s in sizes:
        n_devices *= s
    valid_sizes = set()
    for r in range(len(sizes) + 1):
        for combo in itertools.combinations(sizes, r):
            p = 1
            for s in combo:
                p *= s
            valid_sizes.add(p)
    findings: list[str] = []
    for comp, node in graph.collectives():
        where = f"{node.kind} %{node.name} in %{comp.name}"
        if node.kind == "collective-permute":
            pairs = node.source_target_pairs or ()
            srcs = [p[0] for p in pairs]
            dsts = [p[1] for p in pairs]
            if len(set(srcs)) != len(srcs) or len(set(dsts)) != len(dsts):
                findings.append(
                    f"replica groups: {where} has a duplicate "
                    f"source or target in source_target_pairs={pairs}")
            if any(d >= n_devices for p in pairs for d in p):
                findings.append(
                    f"replica groups: {where} names a device outside the "
                    f"declared {n_devices}-device mesh {mesh_shape}")
            continue
        if node.iota_groups is not None:
            count, size = node.iota_groups
            if count * size != n_devices:
                findings.append(
                    f"replica groups: {where} iota groups "
                    f"[{count},{size}] do not cover the declared "
                    f"{n_devices}-device mesh {mesh_shape}")
            elif size not in valid_sizes:
                findings.append(
                    f"replica groups: {where} group size {size} is not a "
                    f"product of declared mesh axes {mesh_shape}")
            continue
        groups = node.replica_groups
        if not groups:
            continue  # absent/empty groups = all devices, always valid
        flat = [d for g in groups for d in g]
        if len({len(g) for g in groups}) != 1:
            findings.append(
                f"replica groups: {where} has unequal group sizes "
                f"{[len(g) for g in groups]}")
            continue
        if len(set(flat)) != len(flat):
            findings.append(
                f"replica groups: {where} groups overlap (a device "
                f"appears twice): {groups}")
            continue
        if set(flat) != set(range(n_devices)):
            findings.append(
                f"replica groups: {where} groups cover {sorted(set(flat))}"
                f", not the declared {n_devices}-device mesh {mesh_shape}")
            continue
        if len(groups[0]) not in valid_sizes:
            findings.append(
                f"replica groups: {where} group size {len(groups[0])} is "
                f"not a product of declared mesh axes {mesh_shape} — the "
                f"collective spans a device set no mesh axis explains")
    return findings


def census_cross_check(graph: cg.CollectiveGraph,
                       report: hlo_audit.CollectiveReport) -> list[str]:
    """The two parsers must agree on the collective count per kind —
    a graph-parser regression must not silently blind the detectors."""
    g, r = graph.count_by_kind(), report.count_by_kind()
    if g == r:
        return []
    return [f"parser census mismatch: graph sees {g} but hlo_audit sees "
            f"{r} — collective_graph and hlo_audit disagree on what the "
            f"program contains"]


def detect_exposed_comm(graph: cg.CollectiveGraph,
                        declared_overlapped: bool,
                        *, ignore_below: int = 0) -> list[str]:
    """(e) exposed communication — a LIVE gate for declared-overlapped
    strategies, report-only for everyone else.

    Async pairing problems — a ``-start`` whose ``-done`` the chase
    cannot find — are findings REGARDLESS of the declaration: a blind
    window is a parser/schedule bug, not a policy choice.  For a
    declared-overlapped strategy the gate polices what the fusion pass
    CONTROLS, not what the backend chooses to lower:

    - an async start consumed back-to-back (zero-op window) always
      fails — the pass opened a window and wasted it;
    - a synchronous collective fails when the same program contains ANY
      async window — the backend demonstrably can split, so an unsplit
      collective is the pass's miss;
    - on an all-synchronous program (CPU XLA emits no async collective
      forms at all — PERF §21/§26) sync emission is not attributable to
      the pass, so it fails only when the window ALSO has zero legally
      interleavable compute: a declaration with nothing to hide behind
      is vacuously false.  Exposure still lands in the schedule record
      and the overlap score either way.

    Undeclared strategies only get the counts in the schedule record —
    never a gate failure."""
    findings: list[str] = []
    views = []
    for comp in graph.computations.values():
        view = cg.schedule_view(comp)
        findings.extend(view.problems)
        views.append((comp, view))
    if not declared_overlapped:
        return findings
    backend_splits = any(w.is_async for _, v in views for w in v.windows)
    for comp, view in views:
        for w in view.windows:
            if w.bytes < ignore_below or not w.exposed:
                continue
            if w.is_async:
                what = "consumed back-to-back (zero-op start->done window)"
            elif backend_splits:
                what = ("emitted synchronous (no start/done split) in a "
                        "program whose backend emits async forms")
            elif w.interleavable_compute == 0:
                what = ("emitted synchronous with ZERO legally "
                        "interleavable compute — nothing to overlap with")
            else:
                # Sync-only backend, interleavable compute present: the
                # declaration is honest about the program; exposure is
                # recorded and scored, not gated.
                continue
            findings.append(
                f"exposed communication in %{comp.name}: {w.kind} "
                f"%{w.name} ({w.bytes} B) is {what} but the strategy "
                f"declares its collectives overlapped — "
                f"{w.interleavable_compute} compute op(s) "
                f"({w.interleavable_bytes} B) were legally interleavable")
    return findings


# A minimal scheduled module whose async all-reduce is consumed
# back-to-back — zero ops inside the start->done window — while an
# independent fusion sits RIGHT THERE, legally interleavable.  The
# exposed-comm detector must flag it under a declared-overlapped
# strategy, and the liveness pass must reproduce its hand-computed peak.
_SEEDED_EXPOSED_HLO = """\
HloModule seeded_exposed_positive, is_scheduled=true

%add (a: f32[], b: f32[]) -> f32[] {
  %a = f32[] parameter(0)
  %b = f32[] parameter(1)
  ROOT %r = f32[] add(f32[] %a, f32[] %b)
}

ENTRY %main (p0: f32[65536], p1: f32[65536]) -> (f32[65536], f32[65536]) {
  %p0 = f32[65536]{0} parameter(0)
  %p1 = f32[65536]{0} parameter(1)
  %ars = f32[65536]{0} all-reduce-start(f32[65536]{0} %p0), replica_groups={}, to_apply=%add
  %ard = f32[65536]{0} all-reduce-done(f32[65536]{0} %ars)
  %fus = f32[65536]{0} fusion(f32[65536]{0} %p1), kind=kLoop, calls=%add
  ROOT %out = (f32[65536]{0}, f32[65536]{0}) tuple(%ard, %fus)
}
"""

#: hand-computed liveness of ``_SEEDED_EXPOSED_HLO``'s entry: at the
#: all-reduce-start, its input p0 is still live alongside p1 and the
#: start's own 256 KiB result buffer (the done merely aliases it) — three
#: buffers; p0 then dies, and the fusion's result brings it back to three
#: (p1 + in-flight ars + fus, the latter two escaping through the root
#: tuple).  Peak is 3 x 262144 bytes.
_SEEDED_PEAK_BYTES = 3 * 262144


def seeded_schedule_positive() -> list[str]:
    """Self-test of the schedule plane — the gate refuses to run blind.

    Three invariants over the seeded zero-overlap program: the
    exposed-comm detector must flag it under a declared-overlapped
    strategy (and stay quiet under an undeclared one), the liveness
    estimator must reproduce the hand-computed peak, and the
    schedule-drift differ must catch a tampered peak declaration."""
    problems: list[str] = []
    graph = cg.parse_graph(_SEEDED_EXPOSED_HLO)
    found = detect_exposed_comm(graph, True)
    if len(found) != 1 or "back-to-back" not in found[0]:
        problems.append(
            f"seeded exposed-comm positive: expected exactly 1 zero-window "
            f"finding for a back-to-back all-reduce-start under a "
            f"declared-overlapped strategy, got {found!r} — the detector "
            f"is blind")
    if detect_exposed_comm(graph, False):
        problems.append(
            "seeded exposed-comm positive: an UNdeclared strategy must "
            "not fail on exposure (report-only contract broken)")
    entry = graph.entry_computation
    lv = cg.liveness(entry, graph.aliased_params)
    if lv.peak_bytes != _SEEDED_PEAK_BYTES:
        problems.append(
            f"seeded liveness positive: hand-computed peak "
            f"{_SEEDED_PEAK_BYTES} B but the estimator says "
            f"{lv.peak_bytes} B — the sweep is mis-measuring")
    fresh = derive_schedule_entry(graph, ignore_below=1024)
    tampered = dict(fresh, peak_live_bytes=fresh["peak_live_bytes"] + 4096)
    if not _schedule_entry_drift("seeded", fresh, tampered):
        problems.append(
            "seeded liveness-drift positive: a +4096 B tampered "
            "peak_live_bytes declaration produced no drift finding — "
            "the drift gate is blind")
    if _schedule_entry_drift("seeded", fresh, dict(fresh)):
        problems.append(
            "seeded liveness-drift positive: an identical declaration "
            "produced a drift finding — the differ is unstable")
    return problems


# ---------------------------------------------------------------------------
# Derived budgets: the exact per-kind record, emitted and drift-checked.
# ---------------------------------------------------------------------------


def derive_budget(report: hlo_audit.CollectiveReport,
                  ignore_below: int) -> dict:
    """Exact per-kind {bytes, count} of a program, measured by the same
    wire-traffic ruler as the ceiling audits (``hlo_audit``).

    ``kinds`` is the FULL census (no floor) — the drift gate pins every
    collective the compiler emits, not just the budget-relevant slice.
    ``above_floor`` is the slice the hand-declared ceiling actually
    polices (filtered at the budget's ``ignore_below``)."""
    counts = report.count_by_kind()
    above = report.filter(ignore_below)
    return {
        "ignore_below": int(ignore_below),
        "kinds": {k: {"bytes": int(b), "count": int(counts[k])}
                  for k, b in sorted(report.bytes_by_kind().items())},
        "above_floor": {k: int(b)
                        for k, b in sorted(above.bytes_by_kind().items())},
        "total_bytes": int(report.total_bytes),
    }


def elastic_transitions(n_devices: int = 8) -> tuple[tuple[int, int], ...]:
    """The membership transitions the gate pins: shrink to half the
    world and grow back — the 8→4→8 chaos tier's legs."""
    half = max(1, int(n_devices) // 2)
    return ((int(n_devices), half), (half, int(n_devices)))


def derive_resize(n_devices: int = 8) -> dict:
    """Exact shard-movement bytes of the elastic n→n′ resharding map —
    the resize priced like any other wire.

    Census: the flagship tiny-LM param tree the strategy audits compile
    (``strategies._lm_pieces``), under adamw's two flat moment vectors
    per leaf.  Movement comes from ``elastic.resharding``'s interval
    arithmetic over zero1's pad-to-multiple layout — pure shape math, no
    compile — so the pinned numbers are byte-exact and deterministic."""
    import jax
    import numpy as np

    from tpuframe.analysis import strategies
    from tpuframe.elastic import resharding

    _m, _l, _tx, (state, _b), _pb, _ab = strategies._lm_pieces()
    flat, _ = jax.tree_util.tree_flatten_with_path(state.params)
    leaves = [(jax.tree_util.keystr(path),
               int(np.prod(leaf.shape)) if leaf.shape else 1,
               np.dtype(leaf.dtype).itemsize)
              for path, leaf in flat]
    out = {}
    for n_from, n_to in elastic_transitions(n_devices):
        mv = resharding.resize_movement(leaves, n_from, n_to,
                                        moment_vectors=2)
        mv.pop("leaves")  # totals pin; per-leaf rows stay derivable
        out[f"{n_from}->{n_to}"] = mv
    return out


def resize_drift(derived_file: dict | None, *,
                 n_devices: int = 8) -> list[str]:
    """Diff the fresh resize derivation against the checked-in record —
    the same drift contract every collective budget lives under."""
    if derived_file is None:
        return []  # budget_drift already reports the missing file
    if derived_file.get("jax") != _jax_version():
        return []  # pinned to the emitting jax, like budget_drift
    declared = derived_file.get("elastic_resize")
    if declared is None:
        return ["elastic-resize budget missing from derived_budgets.json "
                "— run `python -m tpuframe.analysis --emit-budgets` to "
                "declare the resharding-map movement bytes"]
    fresh = derive_resize(n_devices)
    problems = []
    for key in sorted(set(fresh) | set(declared)):
        if fresh.get(key) != declared.get(key):
            problems.append(
                f"elastic-resize drift on {key}: derived "
                f"{fresh.get(key) or 'nothing'} but derived_budgets.json "
                f"declares {declared.get(key) or 'nothing'} — fix the "
                f"regression or re-emit with --emit-budgets")
    return problems


def load_derived(path: str = DERIVED_BUDGETS_PATH) -> dict | None:
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(data, dict) or "strategies" not in data:
        return None
    return data


def emit_derived(audits, *, n_devices: int, path: str =
                 DERIVED_BUDGETS_PATH) -> dict:
    """Regenerate ``derived_budgets.json`` from fresh audits — the
    one-source-of-truth half of the drift contract."""
    data = {
        "schema": REPORT_SCHEMA,
        "jax": _jax_version(),
        "n_devices": int(n_devices),
        "elastic_resize": derive_resize(n_devices),
        "strategies": {
            a.name: derive_budget(a.report, a.budget.ignore_below)
            for a in audits
            if a.status in ("ok", "violation") and a.report is not None
        },
    }
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(data, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return data


def budget_drift(audit, derived_file: dict | None) -> list[str]:
    """Diff a fresh derivation against the checked-in declaration.
    Either direction of drift is a finding; a strategy this jax can
    compile that has no declaration is one too."""
    if derived_file is None:
        return ["derived_budgets.json missing/unreadable — run "
                "`python -m tpuframe.analysis --emit-budgets`"]
    if derived_file.get("jax") != _jax_version():
        # Another jax emits different programs; the drift contract is
        # pinned to the version that emitted the file.  Not a finding —
        # the strategy audits still police the class ceilings here.
        return []
    declared = derived_file.get("strategies", {}).get(audit.name)
    if declared is None:
        return [f"[{audit.name}] compiles here but has no entry in "
                f"derived_budgets.json — run `python -m tpuframe.analysis "
                f"--emit-budgets` to declare its derived budget"]
    fresh = derive_budget(audit.report, audit.budget.ignore_below)
    problems = []
    for kind in sorted(set(fresh["kinds"]) | set(declared["kinds"])):
        f_e, d_e = fresh["kinds"].get(kind), declared["kinds"].get(kind)
        if f_e == d_e:
            continue
        problems.append(
            f"[{audit.name}] derived-budget drift on {kind}: compiled "
            f"program has {f_e or 'nothing'} but derived_budgets.json "
            f"declares {d_e or 'nothing'} — fix the regression or "
            f"re-emit with --emit-budgets")
    return problems


def derived_for(name: str, *, path: str = DERIVED_BUDGETS_PATH
                ) -> dict | None:
    """Checked-in derived budget for one strategy (tests assert against
    this instead of hand-copying byte constants)."""
    data = load_derived(path)
    if data is None:
        return None
    return data.get("strategies", {}).get(name)


# ---------------------------------------------------------------------------
# Derived schedule: liveness + window census, emitted and drift-checked
# (the --emit-budgets idiom, one file per plane).
# ---------------------------------------------------------------------------


def derive_schedule_entry(graph: cg.CollectiveGraph, *,
                          ignore_below: int) -> dict:
    """Integer-exact schedule/liveness record of one compiled program —
    what ``derived_schedule.json`` pins per strategy.

    ``peak_live_bytes``/``undonated_doubles`` come from the entry
    computation's liveness sweep (the floor for the donation flag is the
    budget's ``ignore_below`` — one ruler per strategy); the window
    census spans every computation, so collectives inside while bodies
    count.  All values are ints, so emission is byte-exactly
    reproducible."""
    entry = graph.entry_computation
    lv = (cg.liveness(entry, graph.aliased_params,
                      undonated_floor=max(int(ignore_below), 1))
          if entry is not None else None)
    n_coll = n_pairs = n_exposed = inter_bytes = 0
    for comp in graph.computations.values():
        pairs, _ = comp.pair_async()
        n_pairs += len(pairs)
        n_coll += len(comp.collectives())
        for w in cg.schedule_view(comp).windows:
            if w.bytes < ignore_below:
                continue
            if w.exposed:
                n_exposed += 1
            inter_bytes += w.interleavable_bytes
    return {
        "ignore_below": int(ignore_below),
        "peak_live_bytes": int(lv.peak_bytes) if lv else 0,
        "undonated_doubles": len(lv.undonated) if lv else 0,
        "collectives": int(n_coll),
        "async_pairs": int(n_pairs),
        "exposed_above_floor": int(n_exposed),
        "interleavable_bytes": int(inter_bytes),
    }


def load_derived_schedule(path: str = DERIVED_SCHEDULE_PATH
                          ) -> dict | None:
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(data, dict) or "strategies" not in data:
        return None
    return data


def emit_schedule(audits, *, n_devices: int,
                  path: str = DERIVED_SCHEDULE_PATH) -> dict:
    """Regenerate ``derived_schedule.json`` from fresh audits —
    ``python -m tpuframe.analysis --emit-schedule``."""
    data = {
        "schema": REPORT_SCHEMA,
        "jax": _jax_version(),
        "n_devices": int(n_devices),
        "strategies": {
            a.name: derive_schedule_entry(
                cg.parse_graph(a.compiled.as_text()),
                ignore_below=a.budget.ignore_below)
            for a in audits
            if a.status in ("ok", "violation") and a.compiled is not None
        },
    }
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(data, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return data


def _schedule_entry_drift(name: str, fresh: dict,
                          declared: dict) -> list[str]:
    """Field-by-field diff of one strategy's schedule record — either
    direction is a finding (a peak that *improved* silently is a stale
    declaration, same as a regression)."""
    problems = []
    for key in sorted(set(fresh) | set(declared)):
        if fresh.get(key) != declared.get(key):
            problems.append(
                f"[{name}] derived-schedule drift on {key}: compiled "
                f"program has {fresh.get(key)!r} but "
                f"derived_schedule.json declares {declared.get(key)!r} — "
                f"fix the regression or re-emit with --emit-schedule")
    return problems


def schedule_drift(audit, schedule_file: dict | None, *,
                   graph: cg.CollectiveGraph | None = None) -> list[str]:
    """Diff a fresh schedule derivation against the checked-in record —
    the budget_drift contract: missing file/entry is a finding, version
    skew is a skip (pinned to the emitting jax), drift either way
    fails."""
    if schedule_file is None:
        return ["derived_schedule.json missing/unreadable — run "
                "`python -m tpuframe.analysis --emit-schedule`"]
    if schedule_file.get("jax") != _jax_version():
        return []  # another jax schedules differently; pinned to emitter
    declared = schedule_file.get("strategies", {}).get(audit.name)
    if declared is None:
        return [f"[{audit.name}] compiles here but has no entry in "
                f"derived_schedule.json — run `python -m tpuframe."
                f"analysis --emit-schedule` to declare its schedule "
                f"record"]
    if graph is None:
        graph = cg.parse_graph(audit.compiled.as_text())
    fresh = derive_schedule_entry(graph,
                                  ignore_below=audit.budget.ignore_below)
    return _schedule_entry_drift(audit.name, fresh, declared)


def schedule_for(name: str, *, path: str = DERIVED_SCHEDULE_PATH
                 ) -> dict | None:
    """Checked-in schedule record for one strategy (tests assert against
    this instead of hand-copying byte constants)."""
    data = load_derived_schedule(path)
    if data is None:
        return None
    return data.get("strategies", {}).get(name)


def overlap_score(graph: cg.CollectiveGraph, report, *,
                  n_devices: int, ignore_below: int,
                  generation: str = "v5e") -> dict:
    """Overlap-potential score of one compiled program.

    Per above-floor collective window: its wire milliseconds come from
    the roofline ICI ring model over the bytes ``hlo_audit`` counted for
    that instruction (matched by source line, so the wire ruler — s8
    payloads, halved starts — carries over; result bytes are the
    fallback for ops the census floor dropped), and the compute
    *legally interleavable* with it is priced by the HBM roofline.  The
    hideable share of each window is ``min(comm, interleavable)``;
    ``overlap_potential`` is total hideable over total comm (1.0 when
    there is no above-floor comm — nothing to hide).  Floats, report
    plane only — the drift gate pins the integer schedule record, not
    this score."""
    from tpuframe.tune import roofline

    line_bytes: dict[str, list] = {}
    if report is not None:
        for op in report.ops:
            line_bytes.setdefault(op.line, []).append(int(op.bytes))
    comm = inter = hide = 0.0
    n_exposed = n_above = 0
    for comp in graph.computations.values():
        for w in cg.schedule_view(comp).windows:
            node = comp.nodes[w.name]
            matched = line_bytes.get(node.line)
            nbytes = matched.pop(0) if matched else w.bytes
            if nbytes < ignore_below:
                continue
            n_above += 1
            c_ms = roofline.comm_ms(generation, w.kind, nbytes, n_devices)
            i_ms = roofline.hbm_ms(generation, w.interleavable_bytes)
            comm += c_ms
            inter += i_ms
            hide += min(c_ms, i_ms)
            if w.exposed:
                n_exposed += 1
    return {
        "generation": generation,
        "comm_ms": round(comm, 6),
        "interleavable_ms": round(inter, 6),
        "hideable_ms": round(hide, 6),
        "overlap_potential": round(hide / comm, 4) if comm else 1.0,
        "exposed": int(n_exposed),
        "collectives_above_floor": int(n_above),
    }


def comm_split(graph: cg.CollectiveGraph, report, *, mesh_shape: dict,
               n_devices: int, generation: str = "v5e") -> dict:
    """ICI vs DCN byte attribution from replica groups.

    On a hierarchical mesh the ``slice`` axis is outermost, so logical
    device ``d`` lives in slice ``d // (n_devices / slices)`` — a
    collective whose materialized replica groups (or permute pairs)
    contain members of more than one slice must leave the ICI torus,
    and its FULL wire bytes are charged to DCN (conservative: the slow
    hop bounds the op).  Bytes use the census ruler (``hlo_audit`` op
    bytes matched by source line, like :func:`overlap_score`; result
    bytes as fallback), so quantized wires split at their real payload.
    Single-slice meshes attribute everything to ICI by construction.
    ``unattributed`` counts collectives whose iota group spec could not
    be materialized — those are charged to DCN, never dropped."""
    from tpuframe.tune import roofline

    # "slice" is mesh.SLICE_AXIS; spelled literally so the report stays
    # buildable without jax (mesh imports it).
    slices = int(mesh_shape.get("slice", 1)) if mesh_shape else 1
    if slices < 1 or n_devices % max(slices, 1):
        slices = 1
    inner = n_devices // slices
    line_bytes: dict[str, list] = {}
    if report is not None:
        for op in report.ops:
            line_bytes.setdefault(op.line, []).append(int(op.bytes))
    ici: dict[str, int] = {}
    dcn: dict[str, int] = {}
    unattributed = 0
    for _comp, node in graph.collectives():
        matched = line_bytes.get(node.line)
        nbytes = matched.pop(0) if matched else node.result_bytes
        crossing = False
        if slices > 1:
            if node.kind == "collective-permute":
                pairs = node.source_target_pairs or ()
                crossing = any(s // inner != t // inner
                               for s, t, *_ in pairs)
            else:
                groups = cg.materialized_groups(node, n_devices)
                if groups is None:
                    unattributed += 1
                    crossing = True
                else:
                    crossing = any(
                        len({d // inner for d in g}) > 1 for g in groups)
        bucket = dcn if crossing else ici
        bucket[node.kind] = bucket.get(node.kind, 0) + int(nbytes)
    ici_bytes = sum(ici.values())
    dcn_bytes = sum(dcn.values())
    return {
        "slices": slices,
        "ici": {k: int(v) for k, v in sorted(ici.items())},
        "dcn": {k: int(v) for k, v in sorted(dcn.items())},
        "ici_bytes": int(ici_bytes),
        "dcn_bytes": int(dcn_bytes),
        "unattributed": int(unattributed),
        "t_ici_ms": round(sum(
            roofline.comm_ms(generation, k, b, n_devices)
            for k, b in ici.items()), 6),
        "t_dcn_ms": round(sum(
            roofline.dcn_ms(generation, k, b, slices)
            for k, b in dcn.items()), 6),
        "generation": generation,
    }


#: one MegaScale DCN transfer: a host-transfer ``send`` whose payload is
#: the first tuple element and whose rendezvous tag names the collective
#: it carries, e.g. ``%send = (f32[1025,8,128]{...}, u32[], token[])
#: send(...), is_host_transfer=true, frontend_attributes={...
#: _xla_host_transfer_rendezvous="all-reduce.73_3"...}``.
_MEGASCALE_PAYLOAD_RE = re.compile(
    r"=\s*\((" + hlo_audit._DTYPE_RE + r")\[([0-9,]*)\]")
_MEGASCALE_KIND_RE = re.compile(
    r'_xla_host_transfer_rendezvous="([a-z\-]+)')


def megascale_split(hlo_text: str) -> dict:
    """Cross-slice (DCN) bytes the XLA:TPU backend moved through the
    MegaScale transport instead of plain collectives.

    On real multi-slice topologies the TPU compiler decomposes a
    slice-spanning collective itself: the in-slice legs stay HLO
    collectives (``comm_split`` attributes those) but the DCN hop is
    lowered to paired host-transfer ``send``/``recv`` custom channels
    tagged ``_xla_host_transfer_handler_name="xla_megascale_runtime"``
    — invisible to both the collective graph and ``hlo_audit``.  This
    counts each such send's payload bytes (s8 payloads count one byte
    per element — a quantized DCN leg shows its real 4x drop) keyed by
    the collective kind its rendezvous tag names.  Returns
    ``{kind: bytes}``; empty for CPU-compiled or single-slice programs,
    so folding this into a ``comm_split`` DCN column is a no-op there.
    """
    out: dict[str, int] = {}
    for line in hlo_text.splitlines():
        if " send(" not in line or "is_host_transfer=true" not in line \
                or "xla_megascale_runtime" not in line:
            continue
        payload = _MEGASCALE_PAYLOAD_RE.search(line)
        kind = _MEGASCALE_KIND_RE.search(line)
        if not payload or not kind:
            continue
        nbytes = hlo_audit._shape_bytes(payload.group(1), payload.group(2))
        out[kind.group(1)] = out.get(kind.group(1), 0) + int(nbytes)
    return {k: int(v) for k, v in sorted(out.items())}


# ---------------------------------------------------------------------------
# Per-audit flow check + the gate entry point.
# ---------------------------------------------------------------------------


def audit_flow(audit, *, derived_file: dict | None = None,
               schedule_file: dict | None = None,
               graph: cg.CollectiveGraph | None = None,
               n_devices: int = 8, drift: bool = True) -> dict:
    """All structural detectors over one strategy audit.  Returns the
    per-strategy report fragment; ``problems`` is the flattened finding
    list the gate counts.  ``drift=False`` skips the derived-file pin
    comparison — the planner's ad-hoc spec candidates have no pinned
    declaration, only the structural detectors apply."""
    if graph is None:
        graph = cg.parse_graph(audit.compiled.as_text())
    meta = getattr(audit, "meta", None)
    detectors = {
        "redundant_pair": detect_redundant_pairs(graph),
        "wire_dtype": detect_wire_dtype(
            graph, meta.wire_dtype if meta else "f32",
            ignore_below=audit.budget.ignore_below),
        "replication": detect_replication(
            graph, meta.declared_leaves if meta else ()),
        "replica_groups": detect_replica_groups(
            graph, meta.mesh_dict if meta else {}),
        "census": census_cross_check(graph, audit.report),
        "exposed_comm": detect_exposed_comm(
            graph, bool(meta.declared_overlapped) if meta else False,
            ignore_below=audit.budget.ignore_below),
    }
    drift_p = budget_drift(audit, derived_file) if drift else []
    sched_drift = (schedule_drift(audit, schedule_file, graph=graph)
                   if drift else [])
    problems = ([f"[{audit.name}] {f}"
                 for fs in detectors.values() for f in fs]
                + drift_p + sched_drift)
    return {
        "graph": graph.summary(),
        "detectors": detectors,
        "derived": derive_budget(audit.report, audit.budget.ignore_below),
        "drift": drift_p,
        "schedule": derive_schedule_entry(
            graph, ignore_below=audit.budget.ignore_below),
        "schedule_drift": sched_drift,
        "overlap": overlap_score(
            graph, audit.report, n_devices=n_devices,
            ignore_below=audit.budget.ignore_below),
        "comm_split": comm_split(
            graph, audit.report,
            mesh_shape=meta.mesh_dict if meta else {},
            n_devices=n_devices),
        "problems": problems,
    }


def check(audits=None, *, n_devices: int = 8,
          derived_path: str = DERIVED_BUDGETS_PATH,
          schedule_path: str = DERIVED_SCHEDULE_PATH) -> list[str]:
    """Gate entry point: structural detectors + derived-budget and
    derived-schedule drift for every strategy this environment can
    compile.  ``audits`` reuses the CLI's already-compiled audit objects
    (one compile pays for both the ceiling audit and the flow check)."""
    if audits is None:
        from tpuframe.analysis import strategies

        audits = strategies.audit_all(n_devices)
    derived_file = load_derived(derived_path)
    schedule_file = load_derived_schedule(schedule_path)
    problems: list[str] = seeded_wire_positive()
    problems.extend(seeded_schedule_positive())
    for audit in audits:
        if audit.status == "unavailable" or audit.compiled is None:
            continue
        problems.extend(audit_flow(audit, derived_file=derived_file,
                                   schedule_file=schedule_file,
                                   n_devices=n_devices)["problems"])
    problems.extend(resize_drift(derived_file, n_devices=n_devices))
    return problems


# ---------------------------------------------------------------------------
# The --json report + obs-compare-style structural diffing.
# ---------------------------------------------------------------------------


def build_report(audits, *, lint_findings=(), n_devices: int = 8,
                 derived_path: str = DERIVED_BUDGETS_PATH,
                 schedule_path: str = DERIVED_SCHEDULE_PATH) -> dict:
    """Machine-readable gate report (schema pinned by tests — the
    ``--compare`` differ diffs two of these the way ``obs compare``
    diffs step times)."""
    derived_file = load_derived(derived_path)
    schedule_file = load_derived_schedule(schedule_path)
    strategies_out = []
    for audit in audits:
        entry = {
            "name": audit.name,
            "status": audit.status,
            "reason": audit.reason,
            "violations": list(audit.violations),
        }
        if audit.status != "unavailable" and audit.report is not None:
            flow = audit_flow(audit, derived_file=derived_file,
                              schedule_file=schedule_file,
                              n_devices=n_devices)
            entry.update({
                "collectives": flow["derived"]["kinds"],
                "total_bytes": flow["derived"]["total_bytes"],
                "derived": flow["derived"],
                "drift": flow["drift"],
                "detectors": {k: list(v)
                              for k, v in flow["detectors"].items()},
                "graph": flow["graph"],
                "schedule": flow["schedule"],
                "schedule_drift": flow["schedule_drift"],
                "overlap": flow["overlap"],
                "comm_split": flow["comm_split"],
            })
        strategies_out.append(entry)
    return {
        "schema": REPORT_SCHEMA,
        "jax": _jax_version(),
        "n_devices": int(n_devices),
        "lint": [{"rule": f.rule, "path": f.path, "line": f.line,
                  "message": f.message} for f in lint_findings],
        "strategies": strategies_out,
    }


def compare_reports(a: dict, b: dict, *,
                    bytes_tol: float = 0.10) -> tuple[int, list[str]]:
    """Structural diff of two --json reports (A = baseline, B =
    candidate).  rc 1 on a structural regression, 0 clean, 2 when no
    strategy overlaps — the ``obs compare`` return-code contract.

    Regression = a collective kind appears/disappears, a per-kind op
    count changes, per-kind bytes move more than ``bytes_tol``
    (relative), or a detector that was clean now finds something.

    Schedule section (participates only when BOTH reports carry it, so
    a schema-1 baseline still compares on the structural metrics): more
    exposed above-floor collectives, peak live bytes moving more than
    ``bytes_tol`` (relative), or overlap potential dropping by more
    than 0.10 are regressions.

    Comm-split section (same both-reports gate): DCN bytes growing more
    than ``bytes_tol`` (relative) — or any collective newly crossing
    slices on a strategy whose baseline DCN column was zero — is a
    regression.  One-sided by design: the DCN term is the one the
    hierarchical lowering exists to crush (PERF §23/§28), so a drop is
    the intended direction, never flagged.
    """
    lines: list[str] = []
    a_s = {s["name"]: s for s in a.get("strategies", [])
           if s.get("status") in ("ok", "violation") and "derived" in s}
    b_s = {s["name"]: s for s in b.get("strategies", [])
           if s.get("status") in ("ok", "violation") and "derived" in s}
    common = sorted(set(a_s) & set(b_s))
    if not common:
        return 2, ["no strategy audited in both reports — nothing to "
                   "compare"]
    regression = False
    for name in common:
        ka = a_s[name]["derived"]["kinds"]
        kb = b_s[name]["derived"]["kinds"]
        for kind in sorted(set(ka) | set(kb)):
            ea, eb = ka.get(kind), kb.get(kind)
            if ea is None:
                regression = True
                lines.append(f"REGRESSION {name}: new collective kind "
                             f"{kind} ({eb})")
                continue
            if eb is None:
                regression = True
                lines.append(f"REGRESSION {name}: collective kind {kind} "
                             f"disappeared (was {ea})")
                continue
            if ea["count"] != eb["count"]:
                regression = True
                lines.append(
                    f"REGRESSION {name}: {kind} op count "
                    f"{ea['count']} -> {eb['count']}")
            elif ea["bytes"] and (abs(eb["bytes"] - ea["bytes"])
                                  / ea["bytes"]) > bytes_tol:
                regression = True
                lines.append(
                    f"REGRESSION {name}: {kind} bytes "
                    f"{ea['bytes']} -> {eb['bytes']} "
                    f"({(eb['bytes'] - ea['bytes']) / ea['bytes']:+.1%} "
                    f"> ±{bytes_tol:.0%})")
        da = a_s[name].get("detectors", {})
        db = b_s[name].get("detectors", {})
        for det in sorted(set(da) | set(db)):
            na, nb = len(da.get(det, [])), len(db.get(det, []))
            if nb > na:
                regression = True
                lines.append(f"REGRESSION {name}: detector {det} findings "
                             f"{na} -> {nb}")
        sa, sb = a_s[name].get("schedule"), b_s[name].get("schedule")
        if sa and sb:
            ea = int(sa.get("exposed_above_floor", 0))
            eb = int(sb.get("exposed_above_floor", 0))
            if eb > ea:
                regression = True
                lines.append(f"REGRESSION {name}: exposed above-floor "
                             f"collectives {ea} -> {eb}")
            pa = int(sa.get("peak_live_bytes", 0))
            pb = int(sb.get("peak_live_bytes", 0))
            if pa and abs(pb - pa) / pa > bytes_tol:
                regression = True
                lines.append(
                    f"REGRESSION {name}: peak live bytes {pa} -> {pb} "
                    f"({(pb - pa) / pa:+.1%} > ±{bytes_tol:.0%})")
        oa, ob = a_s[name].get("overlap"), b_s[name].get("overlap")
        if oa and ob:
            va = float(oa.get("overlap_potential", 1.0))
            vb = float(ob.get("overlap_potential", 1.0))
            if va - vb > 0.10:
                regression = True
                lines.append(
                    f"REGRESSION {name}: overlap potential "
                    f"{va:.2f} -> {vb:.2f} (dropped > 0.10)")
        ca, cb = a_s[name].get("comm_split"), b_s[name].get("comm_split")
        if ca and cb:
            dcn_a = int(ca.get("dcn_bytes", 0))
            dcn_b = int(cb.get("dcn_bytes", 0))
            if dcn_a and (dcn_b - dcn_a) / dcn_a > bytes_tol:
                regression = True
                lines.append(
                    f"REGRESSION {name}: DCN bytes {dcn_a} -> {dcn_b} "
                    f"({(dcn_b - dcn_a) / dcn_a:+.1%} > +{bytes_tol:.0%})")
            elif not dcn_a and dcn_b:
                regression = True
                lines.append(
                    f"REGRESSION {name}: DCN bytes 0 -> {dcn_b} — "
                    f"collectives newly cross slices")
        if not any(ln.startswith(f"REGRESSION {name}:") for ln in lines):
            lines.append(f"ok {name}: collective structure unchanged")
    return (1 if regression else 0), lines


#: the keys every compiled strategy entry of a schema-2 report carries —
#: pinned here once so the selfcheck and the tests share one spelling.
STRATEGY_REPORT_KEYS = frozenset({
    "name", "status", "reason", "violations", "collectives",
    "total_bytes", "derived", "drift", "detectors", "graph",
    "schedule", "schedule_drift", "overlap", "comm_split",
})


def selfcheck(samples_dir: str = SAMPLES_COMPARE_DIR) -> list[str]:
    """Jax-free gate leg: the checked-in golden compare pair must keep
    exercising the differ's whole contract — base vs. base is rc 0,
    base vs. candidate is rc 1 *including a schedule-section line*, and
    the base report carries every schema-2 strategy key.  A report
    schema change that strands the differ fails CI before it ships."""
    base_path = os.path.join(samples_dir, "base.json")
    cand_path = os.path.join(samples_dir, "candidate.json")
    try:
        with open(base_path) as f:
            base = json.load(f)
        with open(cand_path) as f:
            cand = json.load(f)
    except (OSError, ValueError) as e:
        return [f"compare selfcheck: golden pair unreadable "
                f"({samples_dir}): {e}"]
    problems: list[str] = []
    if base.get("schema") != REPORT_SCHEMA:
        problems.append(
            f"compare selfcheck: golden base.json is schema "
            f"{base.get('schema')!r}, differ is at {REPORT_SCHEMA} — "
            f"regenerate the pair with --json")
    for s in base.get("strategies", []):
        if s.get("status") == "unavailable":
            continue
        missing = STRATEGY_REPORT_KEYS - set(s)
        if missing:
            problems.append(
                f"compare selfcheck: golden base.json strategy "
                f"{s.get('name')!r} lacks report keys {sorted(missing)}")
    rc, _ = compare_reports(base, base)
    if rc != 0:
        problems.append(
            f"compare selfcheck: base vs. base must be rc 0, got {rc}")
    rc, lines = compare_reports(base, cand)
    if rc != 1:
        problems.append(
            f"compare selfcheck: base vs. candidate must be rc 1 "
            f"(seeded regression), got {rc}")
    wanted = ("exposed above-floor", "peak live bytes",
              "overlap potential")
    if not any(any(w in ln for w in wanted) for ln in lines):
        problems.append(
            "compare selfcheck: base vs. candidate found no "
            "schedule-section regression — the differ lost the "
            "schedule plane")
    if not any("DCN bytes" in ln for ln in lines):
        problems.append(
            "compare selfcheck: base vs. candidate found no comm-split "
            "regression — the differ lost the DCN plane (the golden "
            "candidate seeds a slice-crossing dp all-reduce)")
    return problems


def _jax_version() -> str:
    try:
        import jax

        return jax.__version__
    except Exception:  # noqa: BLE001 — report stays buildable without jax
        return "unknown"
