"""Static SPMD/collective analysis — the no-chip CI gate.

Rounds 4-5 established that this framework's worst failure mode is
*silent*: interpret-mode pallas kernels masquerading as Mosaic compiles,
GSPMD materializing an unplanned all-gather from one wrong sharding
annotation, a VMEM gate quietly excluding the one shape the docs said it
covered.  All of those are *static* properties of the traced/compiled
program — visible on a CPU host with AOT lowering, before any chip time
is spent (the same argument as GSPMD's weight-update-sharding analysis
and Horovod's tensor-order consistency checks: in SPMD systems the
communication structure is decided at compile time, so check it there).

Four layers, all offline:

  1. :mod:`tpuframe.analysis.hlo_audit` — parse every collective
     (all-reduce, all-gather, reduce-scatter, all-to-all,
     collective-permute) out of compiled-HLO / StableHLO text with
     shapes, dtypes and replica groups; compute per-step byte volumes;
     check them against the per-strategy communication budgets declared
     in :mod:`tpuframe.analysis.budgets`.
  2. :mod:`tpuframe.analysis.jaxpr_checks` — audit the traced program:
     f32 upcasts inside bf16 regions, huge trace-time constant capture,
     donation leaks (declared-donated buffers the compiled module does
     not alias).
  3. :mod:`tpuframe.analysis.source_lint` — an AST pass over the source
     catching the JAX footguns rounds 4-5 hit by hand: host conversions
     on tracers, Python control flow on tracer values, timing without
     ``block_until_ready``, pallas calls without an explicit
     interpret/Mosaic decision.
  4. :mod:`tpuframe.analysis.collective_graph` +
     :mod:`tpuframe.analysis.shardflow` — the *structural* layer
     (analysis v2): the optimized HLO parsed into a typed def-use graph
     of collectives/parameters, detectors for redundant collective
     pairs, wire-dtype violations, accidental replication and
     replica-group/mesh inconsistency, and per-strategy derived budgets
     drift-checked against the checked-in ``derived_budgets.json``.
     Analysis v3 adds the *schedule* plane on the same graph: async
     start/done overlap windows, an exposed-communication detector, a
     buffer-liveness peak-HBM estimator pinned in
     ``derived_schedule.json``, and a roofline overlap-potential score
     per strategy.

CLI: ``python -m tpuframe.analysis`` (see ``__main__.py``) runs all
four layers CPU-only and exits non-zero on any finding — the CI gate.
Runtime registration: ``tpuframe.obs.spmd_check.check_step_program``
accepts a ``budget=`` so the startup hash check and the collective
audit run off the same lowering.
"""

from tpuframe.analysis.budgets import (  # noqa: F401
    CommBudget,
    KNOWN_VMEM_EXCLUSIONS,
    check_budget,
    strategy_budget,
)
from tpuframe.analysis.collective_graph import (  # noqa: F401
    CollectiveGraph,
    CollectiveWindow,
    Computation,
    LivenessReport,
    Node,
    ScheduleView,
    graph_of_compiled,
    liveness,
    parse_graph,
    schedule_view,
)
from tpuframe.analysis.hlo_audit import (  # noqa: F401
    CollectiveOp,
    CollectiveReport,
    allreduce_payload,
    audit_compiled,
    audit_jitted,
    parse_collectives,
)
from tpuframe.analysis.jaxpr_checks import (  # noqa: F401
    DonationReport,
    audit_donation,
    find_f32_matmuls,
    find_large_constants,
    parse_input_output_alias,
)
from tpuframe.analysis.shardflow import (  # noqa: F401
    build_report,
    compare_reports,
    derive_budget,
    derived_for,
    overlap_score,
    register_wire_format,
    schedule_for,
)
from tpuframe.analysis.source_lint import (  # noqa: F401
    LintFinding,
    lint_paths,
    lint_source,
)
