"""Layer 3: AST lint over tpuframe source for known JAX footguns.

Each rule institutionalizes a defect class rounds 4-5 found by hand:

  TF101  host conversion on a traced value — ``float(x)``,
         ``np.asarray(x)``, ``x.item()`` inside a jitted/shard_mapped
         function forces a trace-time concretization error (or, worse,
         silently bakes a constant when the value happens to be static).
  TF102  Python control flow on a traced value — ``if jnp.any(mask):``
         inside traced code raises ConcretizationTypeError at trace
         time; the fix is ``lax.cond``/``jnp.where``.  Only tests that
         syntactically involve array computation (``jnp.``/``lax.``
         calls, ``.any()``/``.all()``) are flagged — ``if axes:`` on
         static config is fine and common.
  TF103  timing without a sync — a ``t1 - t0`` duration around a
         dispatched step measures *dispatch* (async!) unless something
         in the function forces completion (``block_until_ready``,
         ``device_get``, ``float()``/``.item()`` on the result).  The
         round-4 perf rigs hit exactly this.
  TF104  ``pallas_call`` without an explicit ``interpret=`` decision —
         the silent-interpret failure mode: a kernel that never went
         through Mosaic presenting itself as a TPU kernel.  Every call
         site must say how it decides (the ``_auto_interpret()``
         pattern).
  TF105  resilience bypass — (a) a raw GCS client call
         (``download_as_bytes``/``upload_from_string``/``list_blobs``/
         ...) anywhere outside ``data/gcs.py``: every storage op must go
         through the retry-wrapped layer, or it silently loses backoff,
         timeouts, fault seams and retry metrics; (b) a ``while True:``
         loop that sleeps but never compares, raises, or reads a clock —
         an unbounded retry loop with no exit condition, the shape that
         wedges a supervisor forever (use RetryPolicy).
  TF107  ad-hoc step instrumentation in a hot path — a bare ``print()``
         or ``time.time()``/``perf_counter()`` timer inside per-step
         code (the train step in ``parallel/step.py``, the data
         pipeline in ``data/pipeline.py``) bypasses the structured
         event log: it costs host time every step, interleaves across
         hosts, and is invisible to the offline analyzer.  Route it
         through ``tpuframe.obs`` (``events.emit``/``metrics.bump`` —
         the host loop in train.py owns the one sanctioned timer).
         Also fires on ``print()`` inside *traced* code anywhere: a
         print under jit runs at trace time only, so it is not the
         instrumentation it looks like (use ``jax.debug.print``).
  TF108  bare rematerialization in model/step code — a direct
         ``jax.checkpoint``/``jax.remat``/``nn.remat`` call inside
         ``models/`` or ``parallel/`` bypasses the ``tpuframe.mem``
         policy registry (same registry-seam rule as TF105's GCS
         check): the remat decision becomes invisible to the offline
         policy search, the tuning DB and the run-event record.  Route
         modules through ``mem.remat_module`` and loss functions
         through ``mem.wrap`` / the step factories' ``remat_policy=``.
  TF109  un-bucketed compile in the serving path — a ``jax.jit``/
         ``pjit``/``pmap`` call or a raw ``model.apply`` anywhere in
         ``serve/`` except ``serve/engine.py`` (the one sanctioned
         compile seam).  The scheduler/loadgen layers run per request;
         a novel shape reaching the compiler there is a silent
         multi-second stall mid-serving — every serving program must
         come from the engine's bucketed AOT table.
  TF110  optimizer update outside the weight-update seam — a
         ``tx.update(...)``/``optax.apply_updates(...)`` call in
         ``parallel/`` or ``train.py`` outside ``parallel/step.py`` /
         ``parallel/zero1.py`` (the seam ``TPUFRAME_WEIGHT_UPDATE``
         switches) silently bypasses ZeRO-1 weight-update sharding:
         the stray site updates replicated params against sharded
         optimizer state, or re-materializes the full state the zero1
         layout exists to avoid.  ``parallel/hvd.py`` is seam-adjacent
         (it *composes* an ``optax.GradientTransformation``; step.py
         still applies it) and exempt.
  TF111  background thread outside the sanctioned modules — a
         ``threading.Thread`` created anywhere but ``ckpt/``,
         ``data/pipeline.py``, ``obs/heartbeat.py`` or ``launch/``.
         Background threads issuing collectives is the ordering hazard
         ``ckpt/checkpoint.py`` documents (a worker's collective
         interleaving with the main loop's compiled steps); the
         sanctioned modules are the ones audited to never do that.
         Threads that provably never touch jax suppress with a reason.
  TF116  world-size read cached at module import — a module-level
         ``N = jax.device_count()`` (or ``process_count``/
         ``local_device_count``/``process_index``) outside the
         sanctioned seams (``elastic/``, ``launch/``, ``parallel/``)
         snapshots the world before the run resolves it: under elastic
         resizing the world changes across relaunch attempts, and the
         import-time constant silently disagrees with the mesh the
         attempt actually built.  Resolve per run via
         ``tpuframe.elastic.current_world()``; provably-static uses
         suppress with a reason.
  TF106  compiler-env mutation that can run after jax backend init —
         ``os.environ["XLA_FLAGS"] = ...`` (or ``LIBTPU_INIT_ARGS``,
         via assignment/setdefault/update/putenv) is snapshotted by the
         backend at init and silently ignored afterwards: the exact
         footgun ``parallel/tuning.py:apply()`` can only catch at
         runtime with a warning.  Fires on any such write inside a
         function (functions run at arbitrary times) unless the
         function probes backend init first (references ``xla_bridge``
         or ``_backends``, tuning.apply's pattern), and on
         module-level writes placed *after* a module-level
         ``import jax``.  Per-compile ``compiler_options``
         (``TPUFRAME_XLA_OPTS`` / tpuframe.tune) is the safe carrier —
         it travels inside the compile request.
  TF114  lock discipline in the background-thread modules — inside the
         TF111-sanctioned modules that actually run worker threads
         (``ckpt/``, ``obs/exporter.py``, ``obs/flight.py``,
         ``data/pipeline.py``), shared state guarded by a lock must
         only be mutated under ``with <lock>:``.  The rule is opt-in
         by construction: a class that owns a ``threading.Lock``/
         ``RLock``/``Condition`` attribute (or a module that owns a
         module-level one) has declared its state shared, so every
         unlocked mutation of instance attributes (or lock-guarded
         module globals) is a statically visible race — the hammer
         PR 9 applied to the obs counters, made a checked invariant.
         Constructor bodies (``__init__``/``__post_init__``/
         ``__new__``) are happens-before publication and exempt;
         call-site-serialized lifecycle mutations suppress with
         ``# tf-lint: ok[TF114]`` and a reason.
  TF118  raw network client call outside the router/exporter seams — a
         ``urllib.request.urlopen``/``http.client.HTTPConnection``/
         ``socket.socket``/``socket.create_connection`` call anywhere
         but ``serve/router.py`` (the fleet's one HTTP client, where
         every request rides a RetryPolicy: decorrelated jitter, attempt
         timeout, deadline) or ``obs/exporter.py`` (the one server).  An
         ad-hoc client call elsewhere has no retry budget, no fault
         seams and no obs counters — the same bypass class as TF105's
         raw-GCS check, at the fleet seam.  Local non-fleet socket use
         (ephemeral-port probes) suppresses with ``# tf-lint: ok[TF118]``
         and a reason.
  TF119  raw mesh construction outside the mesh seam — a
         ``jax.sharding.Mesh(...)``/``jax.make_mesh(...)`` call anywhere
         but ``parallel/mesh.py`` (the one module that knows the axis
         order) or ``parallel/pspec.py`` (the declarative spec that
         lowers onto it).  A hand-built mesh silently re-decides the
         axis names and the ICI/DCN ordering that every replica-group
         validation, batch partition and DCN-split attribution keys on —
         the exact drift class the hierarchical ``slice`` axis makes
         fatal (an inner-out slice axis puts model traffic on DCN).
         Build through ``mesh.make_mesh(MeshSpec(...))`` or a parsed
         ``ParallelSpec``; degenerate single-purpose meshes (the
         process-axis host mesh, topology probes) suppress with
         ``# tf-lint: ok[TF119]`` and a reason.
  TF120  strategy registration outside the spec seam — a hand-built
         ``StrategyMeta(...)`` or a write into the ``STRATEGIES``
         registry (subscript assignment, ``.update(...)``,
         ``.setdefault(...)``) anywhere but ``analysis/strategies.py``.
         Since the grammar closed over all nine strategies, the one
         sanctioned way to add a strategy is
         ``register_spec_strategy("name", "spec", ...)`` — a hand-wired
         builder bypasses spec lowering, so its CommBudget/schedule
         record is no longer auto-derived from the grammar and the
         planner cannot enumerate it.  Out-of-repo experiment plugins
         suppress with ``# tf-lint: ok[TF120]`` and a reason.
  TF121  live weight mutation outside the sanctioned swap seam — an
         assignment to (or ``setattr`` of) a ``.params`` attribute in
         the rollout-bearing modules (``serve/rollout.py``,
         ``serve/replica.py``).  ``LMEngine.swap_params()`` is the ONE
         way live weights change: it validates tree structure and
         leaf shapes/dtypes against what the AOT table was compiled
         for, so the zero-recompile hot-swap floor holds by
         construction.  A raw ``engine.params = ...`` skips that check
         and can silently poison every compiled program; test fixtures
         suppress with ``# tf-lint: ok[TF121]`` and a reason.
  TF122  ``declared_overlapped=True`` signed outside the strategy seam —
         the keyword passed (truthy) to ``StrategyMeta(...)`` or
         ``register_spec_strategy(...)`` anywhere but
         ``analysis/strategies.py``.  The declaration is a live
         contract, not metadata: ``shardflow.detect_exposed_comm``
         turns from report-only into a hard gate for strategies that
         carry it, so signing it is reserved to the one module whose
         registrations the fixture/schedule pins actually cover.  A
         strategy signed elsewhere would flip the gate on a program
         nothing pins; seeded-positive test rigs suppress with
         ``# tf-lint: ok[TF122]`` and a reason.
  TF123  raw span event emitted outside the tracing seam — an
         ``events.emit("span_open"/"span_close"/"span_note", ...)``
         call anywhere but ``obs/tracing.py``.  Span records carry
         invariants the schema alone cannot express: every open must
         have a matching close (``obs anomalies`` reports leaks), ids
         come from the process-unique minting counter, and the
         open-span registry behind the ``tpuframe_open_spans`` gauge
         is only maintained by ``tracing.open_span``/``close_span``.
         A hand-rolled emit produces spans the verifier counts as
         leaked or orphaned; use ``tracing.open_span``/``close_span``/
         ``span``/``note``, or suppress with ``# tf-lint: ok[TF123]``
         and a reason (seeded-positive test rigs).
  TF124  raw cross-slice collective outside the hierarchical seam — a
         ``lax`` collective whose axis argument names the ``slice``
         mesh axis (the string literal) anywhere but
         ``parallel/hier.py``.  The slice axis is the DCN fabric:
         ``hier.py`` owns every collective that crosses it, because
         that is where the two-level lowering (in-slice reduce-scatter
         → 1/n cross-slice exchange → in-slice all-gather) and the
         per-fabric wire format (``TPUFRAME_WIRE_FORMAT_DCN``) are
         applied.  A raw ``lax.pmean(g, ("data", "slice"))`` elsewhere
         ships full-size traffic over DCN behind the seam's back —
         exactly the term the hierarchy exists to crush — and is
         invisible to the DCN byte budgets the comm-split auditor
         pins.  Collectives over computed axis variables are untouched
         (the seam's own helpers pass those); deliberate raw crossings
         (scalar control beacons) suppress with ``# tf-lint:
         ok[TF124]`` and a reason.

Scope: TF101/TF102 only fire *inside functions known to be traced*
(decorated with ``jax.jit``/``pmap``/``shard_map`` or passed to
``jax.jit(...)`` by name, plus their nested defs) — host code is
allowed, and encouraged, to call ``float()``.  TF103/TF104 are
function-/call-site-local and apply everywhere.

Suppression: append ``# tf-lint: ok[TF103]`` (or bare ``# tf-lint: ok``
for all rules) to the offending line or to the enclosing ``def`` line,
with a reason in a neighbouring comment.  Suppressions are grep-able
policy, the same contract as the VMEM known-exclusion registry.

Structure: the shared scaffolding — suppression-comment parsing,
path-scope flags, the traced-function walk, finding emission — lives in
:class:`FileContext` plus three registries (``_NODE_RULES`` run on every
non-def node with the enclosing function's traced-ness, ``_FN_RULES``
once per function, ``_FILE_RULES`` once per file).  A new rule is one
registered function reading ``ctx``/``node``/``fn`` — it never copies
the walk or the suppression plumbing (TF114 below is the template).
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from pathlib import Path

RULES = {
    "TF101": "host conversion on a traced value inside traced code",
    "TF102": "Python control flow on a traced (array) value",
    "TF103": "duration measured around device work without a sync",
    "TF104": "pallas_call without an explicit interpret= decision",
    "TF105": "storage call or retry loop bypassing the resilience layer",
    "TF106": "compiler-env (XLA_FLAGS/LIBTPU_INIT_ARGS) mutation that can "
             "run after jax backend init",
    "TF107": "print()/time.time() step instrumentation in a hot path "
             "bypassing tpuframe.obs",
    "TF108": "bare jax.checkpoint/jax.remat/nn.remat in model/step code "
             "bypassing the tpuframe.mem policy registry",
    "TF109": "jit/apply in the serving path outside the engine's "
             "bucketed AOT table (serve/engine.py)",
    "TF110": "optimizer update (tx.update/optax.apply_updates) outside "
             "the weight-update seam (parallel/step.py, parallel/zero1.py)",
    "TF111": "threading.Thread created outside the sanctioned background-"
             "work modules (ckpt/, data/pipeline.py, obs/heartbeat.py, "
             "launch/)",
    "TF112": "events.emit() with an event type not registered in "
             "obs/events.py's REQUIRED_FIELDS schema contract",
    "TF113": "http.server used outside the sanctioned telemetry endpoint "
             "(obs/exporter.py)",
    "TF114": "lock-guarded shared state mutated outside `with <lock>:` in "
             "a background-thread module (ckpt/, obs/exporter.py, "
             "obs/flight.py, data/pipeline.py)",
    "TF115": "raw lax collective (psum/ppermute/all_gather/psum_scatter) "
             "in the wire-format seam (parallel/step.py, "
             "parallel/zero1.py) bypassing the resolved wire format",
    "TF116": "world-size read (jax.process_count/device_count/"
             "local_device_count/process_index) cached at module import "
             "outside the elastic/launch/parallel seams — stale after an "
             "elastic resize",
    "TF117": "jax.block_until_ready()/jax.device_get() inside a traced "
             "hot path (parallel/, serve/engine.py) — forces a schedule "
             "barrier that destroys collective/compute overlap",
    "TF118": "raw network client call (urllib.request.urlopen/"
             "http.client/socket.socket) outside the sanctioned fleet "
             "seams (serve/router.py, obs/exporter.py) — bypasses the "
             "RetryPolicy transport",
    "TF119": "raw mesh construction (jax.sharding.Mesh/jax.make_mesh) "
             "outside the mesh seam (parallel/mesh.py, "
             "parallel/pspec.py) — re-decides axis names and ICI/DCN "
             "ordering behind the spec grammar's back",
    "TF120": "strategy registration (StrategyMeta(...)/STRATEGIES "
             "write) outside analysis/strategies.py's "
             "register_spec_strategy seam — a hand-wired builder "
             "bypasses spec lowering and the planner's enumeration",
    "TF121": "live weight mutation (.params assignment / setattr) in "
             "the rollout modules (serve/rollout.py, serve/replica.py) "
             "outside the engine.swap_params() seam — skips the "
             "tree/shape/dtype validation that keeps hot swaps "
             "recompile-free",
    "TF122": "declared_overlapped=True signed outside "
             "analysis/strategies.py — the overlap declaration arms "
             "shardflow's exposed-comm hard gate, and only the strategy "
             "seam's registrations are covered by the pinned "
             "fixtures/schedules",
    "TF123": "raw span event (span_open/span_close/span_note) emitted "
             "outside obs/tracing.py — bypasses span-id minting and "
             "the open-span registry, producing spans the trace "
             "verifier counts as leaked or orphaned; use the "
             "tracing.open_span/close_span/span/note API",
    "TF124": "raw cross-slice collective (a lax collective naming the "
             "'slice' axis) outside the hierarchical seam "
             "(parallel/hier.py) — ships full-size traffic over DCN "
             "behind the two-level lowering and the per-fabric wire "
             "format, invisible to the pinned DCN byte budgets",
}

# TF107: per-step code — every call here runs once per step/batch, so
# ad-hoc prints and timers belong in obs.events/obs.metrics instead.
_HOT_PATH_SUFFIXES = ("parallel/step.py", "data/pipeline.py")

# TF107: clock reads that look like hand-rolled step timing.
_CLOCK_CALLS = {"time.time", "time.perf_counter", "time.monotonic"}

# TF106: env keys the backend snapshots at init — a later write is dead.
_COMPILER_ENV_KEYS = {"XLA_FLAGS", "LIBTPU_INIT_ARGS"}

# TF108: model/step code where every remat decision must route through
# tpuframe.mem; the registry itself is the one sanctioned call site.
_REMAT_SCOPE_PARTS = ("models/", "parallel/")
_REMAT_EXEMPT_PARTS = ("mem/",)
_BARE_REMAT_CALLEES = {
    "jax.checkpoint", "jax.remat", "nn.remat", "flax.linen.remat",
    "linen.remat", "jax.ad_checkpoint.checkpoint",
    "ad_checkpoint.checkpoint",
}

# TF109: the serving path above the compile seam — request-rate code
# where an unplanned compile is a user-visible stall.  engine.py owns
# the bucketed AOT table and is the one sanctioned call site.
_SERVE_SCOPE_PART = "serve/"
_SERVE_EXEMPT_SUFFIX = "serve/engine.py"
_SERVE_COMPILE_TAILS = {"jit", "pjit", "pmap"}

# TF110: the weight-update seam.  Optimizer math in parallel/ or
# train.py must go through step.py's _reduce_and_apply (which dispatches
# on TPUFRAME_WEIGHT_UPDATE) or zero1.py's sharded_update; hvd.py only
# composes a GradientTransformation (step.py applies it) and is exempt.
_WU_SCOPE_PART = "parallel/"
_WU_SCOPE_SUFFIX = "train.py"
_WU_EXEMPT_SUFFIXES = ("parallel/step.py", "parallel/zero1.py",
                       "parallel/hvd.py")
# Receivers whose ``.update(grads, state, ...)`` is optimizer math rather
# than a dict/metric update — the optax transformation naming convention.
_WU_OPTIMIZER_RECEIVERS = {"tx", "optimizer", "opt", "inner_tx"}

# TF111: modules sanctioned to spawn background threads.  Everywhere
# else a thread is the collective-ordering hazard checkpoint.py
# documents: a background thread issuing (or transitively triggering)
# collectives interleaves with the main loop's compiled steps, and the
# sanctioned modules are exactly the ones audited to never do that
# (ckpt's worker polls sidecar files instead of a barrier; the prefetch
# thread only device_puts; heartbeat only reads a counter; launch runs
# before any backend exists).
_THREAD_SANCTIONED_PARTS = ("ckpt/", "data/pipeline.py",
                            "obs/heartbeat.py", "launch/")

# TF112: receivers whose ``.emit("type", ...)`` is the structured event
# log — the in-tree import aliases for ``tpuframe.obs.events``.  A string
# literal first argument must name a type registered in REQUIRED_FIELDS,
# or the record fails schema validation at read time (the selfcheck
# gate); this catches it at lint time instead.  Computed first arguments
# are skipped (the registry can't resolve them statically).
_EMIT_RECEIVERS = {"events", "events_lib", "obs_events"}

# TF113: the one module allowed to stand up an HTTP endpoint.  Ad-hoc
# http.server use anywhere else forks the telemetry plane: unauthenticated
# sockets with no OpenMetrics contract, invisible to the exporter's
# health/port knobs.
_HTTP_EXEMPT_SUFFIX = "obs/exporter.py"

# TF114: the modules whose threads actually share mutable host state —
# the subset of the TF111-sanctioned list with a writer thread (ckpt's
# async save worker, the exporter's HTTP server thread, the flight
# recorder's dump-on-crash path, the pipeline's prefetch producer).
_LOCK_DISCIPLINE_PARTS = ("ckpt/", "obs/exporter.py", "obs/flight.py",
                          "data/pipeline.py")

# TF114: lock-type constructors whose assignment declares shared state,
# and container methods that mutate their receiver in place.
_LOCK_CTOR_TAILS = {"Lock", "RLock", "Condition"}
_MUTATING_METHODS = {
    "append", "extend", "insert", "remove", "pop", "clear", "update",
    "add", "discard", "popitem", "setdefault", "appendleft", "popleft",
}
_CTOR_METHODS = {"__init__", "__post_init__", "__new__"}

# TF115: the wire-format seam.  step.py and zero1.py resolve the wire
# format (fp vs int8-block) per strategy and must route gradient-path
# collectives through that dispatch — a raw lax.psum/all_gather here is
# a call site the quantized wire silently never reaches.  lax.pmean is
# deliberately NOT in the tails: it IS the fp wire's dispatch target.
# Sanctioned raw uses (scalar reductions under every wire's size floor)
# carry ``# tf-lint: ok[TF115]`` and a reason.
_WIRE_SEAM_SUFFIXES = ("parallel/step.py", "parallel/zero1.py")
_WIRE_RAW_TAILS = {"psum", "ppermute", "all_gather", "psum_scatter"}

# TF116: the seams sanctioned to read the world size directly — the
# elastic resolver itself, the launcher (sizes the cluster before jax
# exists in the children) and parallel/ (mesh construction).  Everywhere
# else a module-import-time world read is a constant baked before the
# attempt resolved its world: under elastic resizing (TPUFRAME_ELASTIC)
# the device count changes across relaunch attempts, so the cache
# silently disagrees with the mesh the run actually built.  Per-run code
# goes through ``tpuframe.elastic.current_world()``.
_WORLD_SANCTIONED_PARTS = ("elastic/", "launch/", "parallel/")
_WORLD_READ_TAILS = {"process_count", "device_count",
                     "local_device_count", "process_index"}

# TF117: the overlap-critical hot paths — the strategy step programs
# (parallel/) and the serving engine.  A host sync inside TRACED code
# there pins a schedule barrier into every compiled step: the collective
# scheduler cannot move work across it, so the exposed-communication
# windows the schedule auditor polices reappear at the source level.
# Host-side synchronization (checkpoint flush, benchmark harness) is
# untraced and untouched.
_SYNC_SCOPE_PART = "parallel/"
_SYNC_SCOPE_SUFFIX = "serve/engine.py"
_SYNC_BARRIER_TAILS = {"block_until_ready", "device_get"}

# TF118: the fleet's network client seams.  router.py owns the one HTTP
# client (http_transport, always called under a RetryPolicy) and
# exporter.py the one server; a raw client call anywhere else skips
# retries, fault seams and the dispatch/scrape obs counters — the TF105
# raw-GCS bypass class at the fleet boundary.  ``socket.gethostname``
# and friends are not client calls and are untouched; local ephemeral-
# port probes suppress with a reason.
_NET_EXEMPT_SUFFIXES = ("serve/router.py", "obs/exporter.py")

# TF119: the mesh seam.  mesh.py owns axis names/order (slice axis
# OUTERMOST so cross-slice collectives ride DCN); pspec.py is the
# declarative grammar that lowers onto it.  Everything else builds
# through them.
_MESH_EXEMPT_SUFFIXES = ("parallel/mesh.py", "parallel/pspec.py")

# TF120: the strategy seam.  strategies.py owns the registry; every
# entry goes through register_spec_strategy so its budget/schedule
# record derives from the spec grammar and `tune plan` can enumerate it.
_STRATEGY_EXEMPT_SUFFIXES = ("analysis/strategies.py",)

# TF121: the live weight-swap seam.  engine.py hosts swap_params() (the
# validating setter); the rollout-bearing modules above it must never
# rebind a ``.params`` attribute directly — that is exactly the bypass
# that turns a checkpoint from the wrong model into a silent poisoning
# of every compiled program.
_SWAP_SCOPE_SUFFIXES = ("serve/rollout.py", "serve/replica.py")

# TF123: the one module allowed to emit raw span records.  The literals
# mirror obs/tracing.py's SPAN_EVENT_TYPES — no import (same
# importable-anywhere constraint as _event_type_registry below), and
# trace.check() cross-pins the two copies via the schema registry.
_TRACE_SEAM_SUFFIXES = ("obs/tracing.py",)
_SPAN_EVENT_LITERALS = ("span_open", "span_close", "span_note")

# TF124: the hierarchical-collective seam.  hier.py owns every
# collective that names the ``slice`` (DCN) axis — the two-level
# lowering and the per-fabric wire format live there; pmean IS in the
# tails (unlike TF115) because a raw cross-slice pmean is precisely the
# full-size DCN transfer the seam exists to shrink.  Only the string
# literal ``"slice"`` is matched: computed axis tuples are how the
# seam's callers hand their axes down, and those stay untouched.
_HIER_SEAM_SUFFIXES = ("parallel/hier.py",)
_HIER_COLLECTIVE_TAILS = {
    "psum", "pmean", "pmax", "pmin", "ppermute", "all_gather",
    "psum_scatter", "all_to_all",
}

_NET_CALL_DOTTED = {"socket.socket", "socket.create_connection"}
_NET_CALL_TAILS = {"urlopen", "HTTPConnection", "HTTPSConnection"}

# TF105a: google.cloud.storage blob/bucket methods — allowed only inside
# the retry-wrapped data/gcs.py layer.
_RAW_GCS_METHODS = {
    "download_as_bytes", "download_as_string", "download_to_filename",
    "upload_from_string", "upload_from_file", "upload_from_filename",
    "list_blobs", "rename_blob",
}

# Decorators that make a function body traced code.
_TRACING_DECORATORS = {"jit", "pmap", "pjit", "shard_map", "vmap"}

# Call-expression shapes treated as host conversions (TF101).
_HOST_CONVERTERS = {"float", "int", "bool", "complex"}
_NP_CONVERTERS = {"asarray", "array"}
_METHOD_CONVERTERS = {"item", "tolist"}

# TF103: callee names that look like dispatched device work...
_DEVICE_WORK_RE = re.compile(
    r"(step|apply|update|forward|jit|compile|sample|generate)", re.I)
# ...and callee/attribute names that force completion.
_SYNC_MARKERS = {"block_until_ready", "device_get", "item", "tolist",
                 "asarray", "array", "float"}

_SUPPRESS_RE = re.compile(r"#\s*tf-lint:\s*ok(?:\[([A-Z0-9, ]+)\])?")


_EVENT_REGISTRY_CACHE: frozenset | None = None


def _event_type_registry() -> frozenset:
    """Event types registered in ``obs/events.py``'s REQUIRED_FIELDS,
    extracted by AST parse — NOT by import: importing ``tpuframe.obs``
    pulls jax, and ``--lint-only`` must stay importable-anywhere.  An
    unreadable/refactored events.py yields an empty set, which makes
    TF112 inert rather than noisy."""
    global _EVENT_REGISTRY_CACHE
    if _EVENT_REGISTRY_CACHE is not None:
        return _EVENT_REGISTRY_CACHE
    types: frozenset = frozenset()
    try:
        src = (Path(__file__).resolve().parent.parent / "obs"
               / "events.py").read_text()
        tree = ast.parse(src)
    except (OSError, SyntaxError):
        tree = None
    if tree is not None:
        for node in ast.walk(tree):
            target = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target = node.targets[0]
            elif isinstance(node, ast.AnnAssign):
                target = node.target
            if (isinstance(target, ast.Name)
                    and target.id == "REQUIRED_FIELDS"
                    and isinstance(node.value, ast.Dict)):
                types = frozenset(k.value for k in node.value.keys
                                  if isinstance(k, ast.Constant))
                break
    _EVENT_REGISTRY_CACHE = types
    return types


@dataclass
class LintFinding:
    rule: str
    path: str
    line: int
    message: str

    def __str__(self):
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


def _dotted(node: ast.AST) -> str:
    """'jax.jit' for Attribute(Name('jax'),'jit'); '' when not a name."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def _is_tracing_decorator(dec: ast.AST) -> bool:
    # @jax.jit / @jit / @shard_map ...
    tail = _dotted(dec).rsplit(".", 1)[-1]
    if tail in _TRACING_DECORATORS:
        return True
    if isinstance(dec, ast.Call):
        # @partial(jax.jit, ...) / @jax.jit(...) / @shard_map(...)
        if _is_tracing_decorator(dec.func):
            return True
        if _dotted(dec.func).rsplit(".", 1)[-1] == "partial" and dec.args:
            return _is_tracing_decorator(dec.args[0])
    return False


def _jitted_names(tree: ast.Module) -> set[str]:
    """Function names passed to jax.jit(...)/jit(...) anywhere."""
    names: set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        callee = _dotted(node.func).rsplit(".", 1)[-1]
        if callee not in _TRACING_DECORATORS:
            continue
        for arg in node.args[:1]:
            if isinstance(arg, ast.Name):
                names.add(arg.id)
            elif (isinstance(arg, ast.Call)
                  and _dotted(arg.func).rsplit(".", 1)[-1] == "partial"
                  and arg.args and isinstance(arg.args[0], ast.Name)):
                names.add(arg.args[0].id)
    return names


def _test_touches_arrays(test: ast.AST) -> bool:
    """True when an `if` test syntactically involves array computation."""
    for node in ast.walk(test):
        if isinstance(node, ast.Call):
            d = _dotted(node.func)
            if d.startswith(("jnp.", "lax.", "jax.numpy.", "jax.lax.")):
                return True
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("any", "all")
                    and not _dotted(node.func).startswith(("np.", "numpy."))):
                return True
    return False


class _FnInfo:
    def __init__(self, node, traced: bool, probes_backend: bool = False):
        self.node = node
        self.traced = traced
        self.probes_backend = probes_backend


def _probes_backend(fn_node) -> bool:
    """TF106 exemption: the function checks whether the backend already
    initialized (``jax._src.xla_bridge._backends`` — tuning.apply's
    pattern) or replaces the process outright (``os.execvpe``: the next
    process re-initializes from the new env)."""
    for sub in ast.walk(fn_node):
        if isinstance(sub, ast.Attribute) and sub.attr in ("_backends",
                                                           "xla_bridge"):
            return True
        if isinstance(sub, ast.Name) and sub.id == "xla_bridge":
            return True
        if (isinstance(sub, ast.Call) and _dotted(sub.func)
                .rsplit(".", 1)[-1] in ("execv", "execve", "execvp",
                                        "execvpe")):
            return True
    return False


def _iter_local(node):
    """Child nodes of ``node`` excluding nested function subtrees (each
    nested def is checked in its own visit with its own traced-ness)."""
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield child
        yield from _iter_local(child)


def _nested_defs(node):
    out = []

    def rec(n):
        for child in ast.iter_child_nodes(n):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append(child)
            else:
                rec(child)

    rec(node)
    return out


class FileContext:
    """Everything one lint pass shares across rules: the parsed tree,
    the raw lines (suppression comments live there), the path-derived
    scope flags, and the emit/suppression plumbing.  Rules receive this
    instead of re-deriving any of it."""

    def __init__(self, tree: ast.Module, src: str, path: str):
        self.tree = tree
        self.lines = src.splitlines()
        self.path = path
        norm = path.replace("\\", "/")
        self.norm_path = norm
        self.findings: list[LintFinding] = []
        self.jitted = _jitted_names(tree)
        self.hot_path = norm.endswith(_HOT_PATH_SUFFIXES)
        self.remat_scope = (any(p in norm for p in _REMAT_SCOPE_PARTS)
                            and not any(p in norm
                                        for p in _REMAT_EXEMPT_PARTS))
        self.serve_scope = (_SERVE_SCOPE_PART in norm
                            and not norm.endswith(_SERVE_EXEMPT_SUFFIX))
        self.wu_scope = ((_WU_SCOPE_PART in norm
                          or norm.endswith(_WU_SCOPE_SUFFIX))
                         and not norm.endswith(_WU_EXEMPT_SUFFIXES))
        self.thread_scope = not any(p in norm
                                    for p in _THREAD_SANCTIONED_PARTS)
        self.http_scope = not norm.endswith(_HTTP_EXEMPT_SUFFIX)
        self.net_scope = not norm.endswith(_NET_EXEMPT_SUFFIXES)
        self.mesh_scope = not norm.endswith(_MESH_EXEMPT_SUFFIXES)
        self.strategy_scope = not norm.endswith(
            _STRATEGY_EXEMPT_SUFFIXES)
        self.swap_scope = norm.endswith(_SWAP_SCOPE_SUFFIXES)
        self.trace_scope = not norm.endswith(_TRACE_SEAM_SUFFIXES)
        self.lock_scope = any(p in norm for p in _LOCK_DISCIPLINE_PARTS)
        self.wire_scope = norm.endswith(_WIRE_SEAM_SUFFIXES)
        self.hier_scope = not norm.endswith(_HIER_SEAM_SUFFIXES)
        self.world_scope = not any(p in norm
                                   for p in _WORLD_SANCTIONED_PARTS)
        self.sync_scope = (_SYNC_SCOPE_PART in norm
                           or norm.endswith(_SYNC_SCOPE_SUFFIX))
        # TF106: a module-level compiler-env write is safe only BEFORE
        # the module-level jax import (the conftest/bootstrap pattern).
        self.jax_import_line = None
        for top in tree.body:
            if isinstance(top, ast.Import) and any(
                    a.name == "jax" or a.name.startswith("jax.")
                    for a in top.names):
                self.jax_import_line = top.lineno
                break
            if isinstance(top, ast.ImportFrom) and top.module and (
                    top.module == "jax"
                    or top.module.startswith("jax.")):
                self.jax_import_line = top.lineno
                break

    def suppressed(self, rule: str, *linenos: int) -> bool:
        for ln in linenos:
            if not (1 <= ln <= len(self.lines)):
                continue
            m = _SUPPRESS_RE.search(self.lines[ln - 1])
            if m and (m.group(1) is None
                      or rule in re.split(r"[,\s]+", m.group(1))):
                return True
        return False

    def emit(self, rule: str, node: ast.AST, msg: str,
             fn: _FnInfo | None = None) -> None:
        def_line = fn.node.lineno if fn is not None else node.lineno
        if not self.suppressed(rule, node.lineno, def_line):
            self.findings.append(
                LintFinding(rule, self.path, node.lineno, msg))


# ---------------------------------------------------------------------------
# Rule registries.  _NODE_RULES run on every non-def node (module level
# with fn=None, then once per enclosing function with its _FnInfo);
# _FN_RULES once per function def; _FILE_RULES once per file, last.
# Registration order is emission order — tests pin it.
# ---------------------------------------------------------------------------

_NODE_RULES: list = []
_FN_RULES: list = []
_FILE_RULES: list = []


def _node_rule(fn):
    _NODE_RULES.append(fn)
    return fn


def _fn_rule(fn):
    _FN_RULES.append(fn)
    return fn


def _file_rule(fn):
    _FILE_RULES.append(fn)
    return fn


@_node_rule
def _tf113_http_server(ctx: FileContext, node, fn):
    if not ctx.http_scope:
        return
    if isinstance(node, (ast.Import, ast.ImportFrom)):
        modules = ([a.name for a in node.names]
                   if isinstance(node, ast.Import)
                   else [node.module or ""])
        if any(m == "http.server" or m.startswith("http.server.")
               for m in modules):
            ctx.emit("TF113", node,
                     "http.server imported outside obs/exporter.py — the "
                     "exporter is the one sanctioned HTTP endpoint "
                     "(OpenMetrics contract, health probe, port knobs); "
                     "register gauges/collectors on it instead of "
                     "standing up another server", fn)
    if (isinstance(node, ast.Attribute)
            and _dotted(node) == "http.server"):
        ctx.emit("TF113", node,
                 "http.server used outside obs/exporter.py — route the "
                 "endpoint through the telemetry exporter", fn)


@_node_rule
def _tf118_raw_network(ctx: FileContext, node, fn):
    if not ctx.net_scope or not isinstance(node, ast.Call):
        return
    dotted = _dotted(node.func)
    if not dotted:
        return
    tail = dotted.rsplit(".", 1)[-1]
    if dotted in _NET_CALL_DOTTED or dotted in _NET_CALL_TAILS or (
            tail in _NET_CALL_TAILS
            and dotted.startswith(("urllib.", "http.client.",
                                   "request.", "client."))):
        ctx.emit("TF118", node,
                 f"raw network client call {dotted}() outside "
                 f"serve/router.py / obs/exporter.py — fleet traffic must "
                 f"ride router.http_transport under a RetryPolicy "
                 f"(backoff, attempt timeout, deadline, obs counters); "
                 f"local non-fleet socket use suppresses with a reason",
                 fn)


def _tf106_emit(ctx: FileContext, node, key, fn):
    if fn is not None:
        if fn.probes_backend:
            return  # checked backend init / re-execs — tuning.apply
    elif (ctx.jax_import_line is None
          or node.lineno < ctx.jax_import_line):
        return  # module-level write before the jax import: safe
    ctx.emit("TF106", node,
             f"os.environ[{key!r}] written where the jax backend may "
             f"already be initialized — the backend snapshots compiler "
             f"env at init and later writes are silently dead; pass "
             f"per-compile compiler_options (TPUFRAME_XLA_OPTS / "
             f"tpuframe.tune) or probe xla_bridge._backends first", fn)


@_node_rule
def _tf106_compiler_env(ctx: FileContext, node, fn):
    if isinstance(node, (ast.Assign, ast.AugAssign)):
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        for t in targets:
            if (isinstance(t, ast.Subscript)
                    and _dotted(t.value) == "os.environ"
                    and isinstance(t.slice, ast.Constant)
                    and t.slice.value in _COMPILER_ENV_KEYS):
                _tf106_emit(ctx, node, t.slice.value, fn)
    if isinstance(node, ast.Call):
        callee = _dotted(node.func)
        if (callee in ("os.environ.setdefault", "os.putenv")
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and node.args[0].value in _COMPILER_ENV_KEYS):
            _tf106_emit(ctx, node, node.args[0].value, fn)
        elif callee == "os.environ.update":
            keys = [kw.arg for kw in node.keywords
                    if kw.arg in _COMPILER_ENV_KEYS]
            for a in node.args:
                if isinstance(a, ast.Dict):
                    keys += [k.value for k in a.keys
                             if isinstance(k, ast.Constant)
                             and k.value in _COMPILER_ENV_KEYS]
            for key in keys:
                _tf106_emit(ctx, node, key, fn)


@_node_rule
def _tf_call_rules(ctx: FileContext, node, fn):
    """The per-call rules (TF101/104/105a/107/108/109/110/111/112), in
    the historical emission order for any single call node."""
    if not isinstance(node, ast.Call):
        return
    traced = fn is not None and fn.traced
    callee = _dotted(node.func)
    tail = callee.rsplit(".", 1)[-1]
    if traced:
        if (tail in _HOST_CONVERTERS and callee == tail
                and node.args
                and not isinstance(node.args[0], ast.Constant)):
            ctx.emit("TF101", node,
                     f"{tail}() on a possibly-traced value inside "
                     f"traced code — concretizes at trace time", fn)
        elif (callee.startswith(("np.", "numpy.", "onp."))
              and tail in _NP_CONVERTERS):
            ctx.emit("TF101", node,
                     f"{callee}() pulls a traced value to host — "
                     f"use jnp inside traced code", fn)
        elif (isinstance(node.func, ast.Attribute)
              and node.func.attr in _METHOD_CONVERTERS
              and not callee.startswith(("np.", "numpy."))):
            ctx.emit("TF101", node,
                     f".{node.func.attr}() on a possibly-traced "
                     f"value inside traced code", fn)
    if tail == "pallas_call" and not any(
            kw.arg == "interpret" for kw in node.keywords):
        ctx.emit("TF104", node,
                 "pallas_call without interpret= — decide "
                 "Mosaic-vs-interpret explicitly (_auto_interpret())",
                 fn)
    if ctx.serve_scope and (
            tail in _SERVE_COMPILE_TAILS
            or (isinstance(node.func, ast.Attribute)
                and node.func.attr == "apply")):
        what = (f"{callee}()" if tail in _SERVE_COMPILE_TAILS
                else f".apply()")
        ctx.emit("TF109", node,
                 f"{what} in the serving path above the compile seam "
                 f"— every serving program must come from "
                 f"serve/engine.py's bucketed AOT table (an "
                 f"un-bucketed shape compiling mid-serving is a "
                 f"multi-second stall)", fn)
    if ctx.wu_scope and (
            callee in ("optax.apply_updates", "apply_updates")
            or (isinstance(node.func, ast.Attribute)
                and node.func.attr == "update"
                and _dotted(node.func.value).rsplit(".", 1)[-1]
                in _WU_OPTIMIZER_RECEIVERS
                and len(node.args) >= 2)):
        ctx.emit("TF110", node,
                 f"{callee}() optimizer update outside the "
                 f"weight-update seam — route it through "
                 f"parallel/step.py's _reduce_and_apply (or "
                 f"parallel/zero1.py's sharded_update) so "
                 f"TPUFRAME_WEIGHT_UPDATE=zero1 still shards the "
                 f"update and optimizer state", fn)
    if (ctx.thread_scope
            and callee in ("threading.Thread", "Thread")):
        ctx.emit("TF111", node,
                 f"{callee}() outside the sanctioned background-work "
                 f"modules (ckpt/, data/pipeline.py, "
                 f"obs/heartbeat.py, launch/) — a background thread "
                 f"that issues collectives interleaves with the main "
                 f"loop's compiled steps (the ordering hazard "
                 f"ckpt/checkpoint.py documents); if the thread "
                 f"provably never touches jax, suppress with "
                 f"tf-lint: ok[TF111] and a reason", fn)
    if (isinstance(node.func, ast.Attribute)
            and node.func.attr == "emit"
            and _dotted(node.func.value).rsplit(".", 1)[-1]
            in _EMIT_RECEIVERS
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)):
        registry = _event_type_registry()
        if registry and node.args[0].value not in registry:
            ctx.emit("TF112", node,
                     f"events.emit({node.args[0].value!r}) — type not "
                     f"registered in obs/events.py REQUIRED_FIELDS; "
                     f"unregistered types fail schema validation at "
                     f"read time (the selfcheck CI gate), so register "
                     f"the type (with its required fields) first", fn)
    if ctx.remat_scope and callee in _BARE_REMAT_CALLEES:
        ctx.emit("TF108", node,
                 f"{callee}() bare rematerialization in model/step "
                 f"code bypasses the tpuframe.mem policy registry — "
                 f"use mem.remat_module for modules, mem.wrap / the "
                 f"step factories' remat_policy= for loss functions",
                 fn)
    if (isinstance(node.func, ast.Attribute)
            and node.func.attr in _RAW_GCS_METHODS
            and not ctx.norm_path.endswith("data/gcs.py")):
        ctx.emit("TF105", node,
                 f".{node.func.attr}() raw GCS client call outside "
                 f"data/gcs.py — route it through the retry-wrapped "
                 f"gcs layer (tpuframe.resilience)", fn)
    if callee == "print":
        if traced:
            ctx.emit("TF107", node,
                     "print() inside traced code runs at trace time "
                     "only, not per step — use jax.debug.print, or "
                     "emit from the host loop via tpuframe.obs", fn)
        elif ctx.hot_path and fn is not None:
            ctx.emit("TF107", node,
                     "print() in per-step hot-path code bypasses the "
                     "structured event log — use tpuframe.obs "
                     "(events.emit / metrics.bump)", fn)
    elif ctx.hot_path and fn is not None and callee in _CLOCK_CALLS:
        ctx.emit("TF107", node,
                 f"{callee}() hand-rolled step timing in a hot path "
                 f"— the train loop's goodput meter owns step "
                 f"timing; route measurements through tpuframe.obs",
                 fn)


def _tf105_unbounded_retry(ctx: FileContext, node: ast.While, fn):
    """TF105b: ``while True`` + sleep with no comparison, raise, or
    clock read in the loop's own body is a retry loop that can never
    give up — it outlives deadlines, watchdogs and operators."""
    sleeps = False
    bounded = False
    for child in node.body:
        for sub in [child, *_iter_local(child)]:
            if isinstance(sub, (ast.Compare, ast.Raise)):
                bounded = True
            elif isinstance(sub, ast.Call):
                tail = _dotted(sub.func).rsplit(".", 1)[-1]
                if tail == "sleep":
                    sleeps = True
                elif tail in ("time", "monotonic", "perf_counter"):
                    bounded = True
    if sleeps and not bounded:
        ctx.emit("TF105", node,
                 "unbounded `while True` retry loop: sleeps but never "
                 "compares, raises, or reads a clock — use "
                 "resilience.RetryPolicy (bounded attempts + deadline)",
                 fn)


@_node_rule
def _tf102_control_flow(ctx: FileContext, node, fn):
    traced = fn is not None and fn.traced
    if isinstance(node, ast.While):
        if (isinstance(node.test, ast.Constant)
                and node.test.value is True):
            _tf105_unbounded_retry(ctx, node, fn)
        if traced and _test_touches_arrays(node.test):
            ctx.emit("TF102", node,
                     "Python branch on an array-valued test inside "
                     "traced code — use lax.cond/jnp.where", fn)
    elif traced and isinstance(node, (ast.If, ast.IfExp)):
        if _test_touches_arrays(node.test):
            ctx.emit("TF102", node,
                     "Python branch on an array-valued test inside "
                     "traced code — use lax.cond/jnp.where", fn)


@_node_rule
def _tf115_wire_seam(ctx: FileContext, node, fn):
    if not ctx.wire_scope or not isinstance(node, ast.Call):
        return
    callee = _dotted(node.func)
    if not callee.startswith(("lax.", "jax.lax.")):
        return
    if callee.rsplit(".", 1)[-1] in _WIRE_RAW_TAILS:
        ctx.emit("TF115", node,
                 f"raw `{callee}` in the wire-format seam bypasses the "
                 f"resolved wire format — route through the wire "
                 f"dispatch (quantwire/collectives helpers) or suppress "
                 f"with tf-lint: ok[TF115] and a reason", fn)


@_node_rule
def _tf124_slice_seam(ctx: FileContext, node, fn):
    """A lax collective whose arguments contain the string literal
    ``"slice"`` — the DCN mesh axis — outside parallel/hier.py.  The
    literal-only match is deliberate: the seam's callers (step.py,
    zero1.py) pass computed axis tuples resolved from the mesh, so a
    bare ``"slice"`` in a collective call is someone hand-routing
    traffic across the DCN fabric."""
    if not ctx.hier_scope or not isinstance(node, ast.Call):
        return
    callee = _dotted(node.func)
    if not callee.startswith(("lax.", "jax.lax.")):
        return
    if callee.rsplit(".", 1)[-1] not in _HIER_COLLECTIVE_TAILS:
        return
    for arg in list(node.args) + [kw.value for kw in node.keywords]:
        for sub in ast.walk(arg):
            if isinstance(sub, ast.Constant) and sub.value == "slice":
                ctx.emit("TF124", node,
                         f"raw cross-slice `{callee}` names the 'slice' "
                         f"(DCN) axis outside parallel/hier.py — route "
                         f"through hier.hier_mean/scatter_mean/gather so "
                         f"the two-level lowering and the DCN wire "
                         f"format apply, or suppress with tf-lint: "
                         f"ok[TF124] and a reason", fn)
                return


@_node_rule
def _tf116_cached_world(ctx: FileContext, node, fn):
    """Module-level (fn is None) assignment whose value reads the world
    size from jax.  Reads inside functions are fine — they run when the
    attempt does, after the world is resolved."""
    if fn is not None or not ctx.world_scope:
        return
    if not isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
        return
    if node.value is None:
        return
    for sub in ast.walk(node.value):
        if not isinstance(sub, ast.Call):
            continue
        callee = _dotted(sub.func)
        tail = callee.rsplit(".", 1)[-1]
        if tail in _WORLD_READ_TAILS and callee == f"jax.{tail}":
            ctx.emit("TF116", node,
                     f"{callee}() cached in a module-level binding — "
                     f"the value is snapshotted at import, before the "
                     f"attempt resolves its world, and goes stale when "
                     f"an elastic resize (TPUFRAME_ELASTIC) changes the "
                     f"device count across relaunches; resolve per run "
                     f"via tpuframe.elastic.current_world(), or "
                     f"suppress with tf-lint: ok[TF116] and a reason "
                     f"if the binding is provably world-invariant", fn)
            return


@_node_rule
def _tf117_traced_sync(ctx: FileContext, node, fn):
    """A host synchronization point inside code that is itself traced:
    ``jax.block_until_ready`` / ``.block_until_ready()`` /
    ``jax.device_get`` under a jit/pmap/shard_map decorator in the
    overlap-critical paths.  Untraced host functions (checkpoint sync,
    bench harnesses) are exactly where these calls belong and are not
    in scope."""
    if not ctx.sync_scope or fn is None or not fn.traced:
        return
    if not isinstance(node, ast.Call):
        return
    callee = _dotted(node.func)
    if callee.rsplit(".", 1)[-1] in _SYNC_BARRIER_TAILS:
        ctx.emit("TF117", node,
                 f"`{callee}()` inside traced hot-path code forces a "
                 f"schedule barrier — the compiled step stalls until "
                 f"every in-flight collective drains, so nothing can "
                 f"overlap across this point; sync on the host after "
                 f"the step returns, or suppress with tf-lint: "
                 f"ok[TF117] and a reason", fn)


@_node_rule
def _tf119_raw_mesh(ctx: FileContext, node, fn):
    """A mesh constructed by hand outside the mesh seam:
    ``Mesh(...)`` in any dotted spelling, or jax's own
    ``make_mesh(...)`` (``jax.make_mesh``/``jax.sharding.make_mesh`` —
    NOT ``mesh_lib.make_mesh``, which IS the seam).  Axis names and the
    outermost-slice ordering are the contract every downstream consumer
    keys on (replica-group validation, ``batch_axes``, the ICI/DCN
    byte split); a raw construction opts out of all of it silently."""
    if not ctx.mesh_scope or not isinstance(node, ast.Call):
        return
    callee = _dotted(node.func)
    tail = callee.rsplit(".", 1)[-1]
    raw = (tail == "Mesh"
           or (tail == "make_mesh"
               and callee in ("jax.make_mesh", "jax.sharding.make_mesh",
                              "sharding.make_mesh")))
    if raw:
        ctx.emit("TF119", node,
                 f"raw `{callee}(...)` outside parallel/mesh.py — a "
                 f"hand-built mesh re-decides axis names and the "
                 f"ICI/DCN slice ordering behind the spec grammar's "
                 f"back; build through mesh.make_mesh(MeshSpec(...)) / "
                 f"ParallelSpec.make_mesh(), or suppress with tf-lint: "
                 f"ok[TF119] and a reason", fn)


@_node_rule
def _tf120_strategy_seam(ctx: FileContext, node, fn):
    """A strategy registered behind the spec seam's back: a hand-built
    ``StrategyMeta(...)`` or any write into the ``STRATEGIES`` registry
    (``STRATEGIES[name] = ...``, ``STRATEGIES.update(...)``,
    ``STRATEGIES.setdefault(...)``) outside ``analysis/strategies.py``.
    The registry's contract since the grammar closed is that every
    entry lowers from a ``ParallelSpec`` via ``register_spec_strategy``
    — that is what keeps the derived budgets/schedules auto-derivable
    and the ``tune plan`` candidate space equal to the strategy space."""
    if not ctx.strategy_scope:
        return
    if isinstance(node, ast.Call):
        callee = _dotted(node.func)
        tail = callee.rsplit(".", 1)[-1]
        if tail == "StrategyMeta":
            ctx.emit("TF120", node,
                     f"hand-built `{callee}(...)` outside "
                     f"analysis/strategies.py — register through "
                     f"strategies.register_spec_strategy(name, spec) so "
                     f"the budget/schedule derive from the grammar and "
                     f"the planner can enumerate it, or suppress with "
                     f"tf-lint: ok[TF120] and a reason", fn)
            return
        if (tail in ("update", "setdefault")
                and callee.rsplit(".", 2)[-2:-1] == ["STRATEGIES"]):
            ctx.emit("TF120", node,
                     f"`{callee}(...)` writes the strategy registry "
                     f"outside analysis/strategies.py — use "
                     f"strategies.register_spec_strategy(name, spec), "
                     f"or suppress with tf-lint: ok[TF120] and a "
                     f"reason", fn)
        return
    if isinstance(node, ast.Assign):
        for tgt in node.targets:
            if (isinstance(tgt, ast.Subscript)
                    and _dotted(tgt.value).rsplit(".", 1)[-1]
                    == "STRATEGIES"):
                ctx.emit("TF120", node,
                         "subscript write into STRATEGIES outside "
                         "analysis/strategies.py — use "
                         "strategies.register_spec_strategy(name, "
                         "spec), or suppress with tf-lint: ok[TF120] "
                         "and a reason", fn)
                return


@_node_rule
def _tf121_swap_seam(ctx: FileContext, node, fn):
    """Live weights mutated behind the swap seam's back: an assignment
    to any ``.params`` attribute — or a ``setattr(x, "params", ...)`` —
    inside the rollout-bearing modules.  The engine's ``swap_params()``
    is the one sanctioned setter because it validates the incoming tree
    structure and every leaf's shape/dtype against what the AOT table
    was compiled for; a raw rebind skips that and the compile-cache
    hit floor (and worse, numerical sanity) silently goes with it."""
    if not ctx.swap_scope:
        return
    if isinstance(node, (ast.Assign, ast.AugAssign)):
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        for tgt in targets:
            if isinstance(tgt, ast.Attribute) and tgt.attr == "params":
                ctx.emit(
                    "TF121", node,
                    f"direct write to `{_dotted(tgt)}` bypasses the "
                    f"validating swap seam — go through "
                    f"engine.swap_params(new_params) (checks tree "
                    f"structure and leaf shapes/dtypes against the "
                    f"compiled AOT table), or suppress with tf-lint: "
                    f"ok[TF121] and a reason", fn)
                return
        return
    if isinstance(node, ast.Call):
        callee = _dotted(node.func)
        if (callee.rsplit(".", 1)[-1] == "setattr" and len(node.args) >= 2
                and isinstance(node.args[1], ast.Constant)
                and node.args[1].value == "params"):
            ctx.emit(
                "TF121", node,
                "setattr(..., \"params\", ...) bypasses the validating "
                "swap seam — go through engine.swap_params(new_params), "
                "or suppress with tf-lint: ok[TF121] and a reason", fn)


@_node_rule
def _tf122_overlap_contract(ctx: FileContext, node, fn):
    """``declared_overlapped`` signed behind the strategy seam's back: a
    truthy (or dynamic) value for the keyword in a ``StrategyMeta(...)``
    or ``register_spec_strategy(...)`` call outside
    ``analysis/strategies.py``.  The declaration arms
    ``detect_exposed_comm`` as a hard gate, so the ONLY sanctioned call
    sites are the seam's own registrations — the ones whose compiled
    schedules the fixture pins actually watch.  Shares TF120's scope
    flag: the seam module itself is exempt."""
    if not ctx.strategy_scope or not isinstance(node, ast.Call):
        return
    callee = _dotted(node.func)
    tail = callee.rsplit(".", 1)[-1]
    if tail not in ("StrategyMeta", "register_spec_strategy"):
        return
    for kw in node.keywords:
        if kw.arg != "declared_overlapped":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and not v.value:
            return  # explicit False/None — not a signing
        ctx.emit("TF122", node,
                 f"`{callee}(..., declared_overlapped=...)` signs the "
                 f"overlap contract outside analysis/strategies.py — "
                 f"the declaration turns shardflow's exposed-comm "
                 f"detector into a hard gate, and only the strategy "
                 f"seam's registrations are covered by the pinned "
                 f"schedule fixtures; register through the seam, or "
                 f"suppress with tf-lint: ok[TF122] and a reason", fn)
        return


@_node_rule
def _tf123_span_seam(ctx: FileContext, node, fn):
    """Raw span emission behind the tracing seam's back: an
    ``events.emit("span_open"/"span_close"/"span_note", ...)`` call
    outside ``obs/tracing.py``.  Span records carry pairing invariants
    the schema cannot express — a hand-rolled emit skips span-id
    minting and the open-span registry, so the verifier counts its
    spans as leaked/orphaned and the ``tpuframe_open_spans`` gauge
    drifts.  Matches the same receiver shapes as TF112."""
    if (not ctx.trace_scope
            or not isinstance(node, ast.Call)
            or not isinstance(node.func, ast.Attribute)
            or node.func.attr != "emit"
            or _dotted(node.func.value).rsplit(".", 1)[-1]
            not in _EMIT_RECEIVERS
            or not node.args
            or not isinstance(node.args[0], ast.Constant)
            or node.args[0].value not in _SPAN_EVENT_LITERALS):
        return
    ctx.emit("TF123", node,
             f"events.emit({node.args[0].value!r}) outside "
             f"obs/tracing.py — raw span records bypass span-id "
             f"minting and the open-span registry (the verifier will "
             f"count them leaked/orphaned); use tracing.open_span/"
             f"close_span/span/note, or suppress with "
             f"tf-lint: ok[TF123] and a reason", fn)


@_fn_rule
def _tf103_timing(ctx: FileContext, fn: _FnInfo):
    node = fn.node
    timing_names: set[str] = set()
    has_device_work = False
    has_sync = False
    durations = []

    def is_timing_call(c):
        return (isinstance(c, ast.Call)
                and _dotted(c.func).rsplit(".", 1)[-1]
                in ("time", "perf_counter", "monotonic"))

    local = list(_iter_local(node))
    for child in local:
        if isinstance(child, ast.Assign) and is_timing_call(child.value):
            for t in child.targets:
                if isinstance(t, ast.Name):
                    timing_names.add(t.id)
        if isinstance(child, ast.Call):
            callee = _dotted(child.func)
            tail = callee.rsplit(".", 1)[-1]
            if tail in _SYNC_MARKERS:
                has_sync = True
            elif _DEVICE_WORK_RE.search(tail):
                has_device_work = True
    for child in local:
        if isinstance(child, ast.BinOp) and isinstance(
                child.op, ast.Sub):
            sides = (child.left, child.right)
            if all(is_timing_call(s)
                   or (isinstance(s, ast.Name)
                       and s.id in timing_names)
                   for s in sides) and (
                    timing_names or any(map(is_timing_call, sides))):
                durations.append(child)
    if durations and has_device_work and not has_sync:
        for d in durations:
            ctx.emit("TF103", d,
                     "duration measured around dispatched device work "
                     "with no block_until_ready/sync in scope — this "
                     "times dispatch, not execution", fn)


# ---------------------------------------------------------------------------
# TF114 — lock discipline (file rule: needs the class-level view).
# ---------------------------------------------------------------------------


def _is_lock_ctor(value) -> bool:
    return (isinstance(value, ast.Call)
            and _dotted(value.func).rsplit(".", 1)[-1] in _LOCK_CTOR_TAILS)


def _assign_target_attrs(node):
    """Flattened assignment-target list for Assign/AugAssign/Delete —
    tuple targets (``a, self.b = ...``) included."""
    if isinstance(node, ast.Assign):
        targets = list(node.targets)
    elif isinstance(node, ast.AugAssign):
        targets = [node.target]
    elif isinstance(node, ast.Delete):
        targets = list(node.targets)
    else:
        return
    while targets:
        t = targets.pop()
        if isinstance(t, (ast.Tuple, ast.List)):
            targets.extend(t.elts)
        else:
            yield t


def _locked_by(with_node: ast.With, lock_exprs: set[str]) -> bool:
    return any(_dotted(item.context_expr) in lock_exprs
               for item in with_node.items)


def _tf114_walk(ctx, lock_exprs, mutated_cb, node, locked):
    """Walk one subtree tracking ``with <lock>:`` nesting.  Nested defs
    are descended with ``locked=False`` — their bodies run whenever the
    function is *called* (usually on the worker thread), not where it
    is defined, so a lock held at definition time proves nothing."""
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        for sub in node.body:
            _tf114_walk(ctx, lock_exprs, mutated_cb, sub, False)
        return
    if isinstance(node, ast.With):
        inner = locked or _locked_by(node, lock_exprs)
        for sub in node.body:
            _tf114_walk(ctx, lock_exprs, mutated_cb, sub, inner)
        return
    if not locked:
        mutated_cb(node)
    for child in ast.iter_child_nodes(node):
        _tf114_walk(ctx, lock_exprs, mutated_cb, child, locked)


@_file_rule
def _tf114_lock_discipline(ctx: FileContext):
    """Within _LOCK_DISCIPLINE_PARTS: a class owning a lock attribute
    (``self._lock = threading.Lock()``) must mutate its other instance
    attributes only under ``with self._lock:``; a module owning a
    module-level lock must mutate its ``global``-declared state only
    under that lock.  ~30 lines of logic on top of the shared
    scaffolding — the template for future rules."""
    if not ctx.lock_scope:
        return
    for cls in [n for n in ast.walk(ctx.tree)
                if isinstance(n, ast.ClassDef)]:
        locks = {t.attr for m in ast.walk(cls)
                 if isinstance(m, ast.Assign) and _is_lock_ctor(m.value)
                 for t in m.targets
                 if isinstance(t, ast.Attribute)
                 and isinstance(t.value, ast.Name) and t.value.id == "self"}
        if not locks:
            continue
        lock_exprs = {f"self.{name}" for name in locks}
        for meth in [m for m in cls.body
                     if isinstance(m, (ast.FunctionDef,
                                       ast.AsyncFunctionDef))
                     and m.name not in _CTOR_METHODS]:
            info = _FnInfo(meth, traced=False)

            def mutated(stmt, meth=meth, info=info):
                for t in _assign_target_attrs(stmt):
                    base = t.value if isinstance(t, ast.Subscript) else t
                    if (isinstance(base, ast.Attribute)
                            and isinstance(base.value, ast.Name)
                            and base.value.id == "self"
                            and base.attr not in locks):
                        ctx.emit("TF114", stmt,
                                 f"self.{base.attr} mutated outside "
                                 f"`with self.{sorted(locks)[0]}:` in "
                                 f"{cls.name}.{meth.name}() — this class "
                                 f"declares its state shared by owning a "
                                 f"lock, and this module runs background "
                                 f"threads; hold the lock, or suppress "
                                 f"with tf-lint: ok[TF114] and a reason "
                                 f"if the site is provably "
                                 f"caller-serialized", info)
                if (isinstance(stmt, ast.Call)
                        and isinstance(stmt.func, ast.Attribute)
                        and stmt.func.attr in _MUTATING_METHODS
                        and isinstance(stmt.func.value, ast.Attribute)
                        and isinstance(stmt.func.value.value, ast.Name)
                        and stmt.func.value.value.id == "self"):
                    ctx.emit("TF114", stmt,
                             f"self.{stmt.func.value.attr}."
                             f"{stmt.func.attr}() mutates shared "
                             f"container state outside `with self."
                             f"{sorted(locks)[0]}:` in {cls.name}."
                             f"{meth.name}() — hold the lock, or "
                             f"suppress with tf-lint: ok[TF114] and a "
                             f"reason", info)

            _tf114_walk(ctx, lock_exprs, mutated, meth, False)
    # Module-level locks guard module globals the same way.
    mod_locks = {t.id for stmt in ctx.tree.body
                 if isinstance(stmt, ast.Assign)
                 and _is_lock_ctor(stmt.value)
                 for t in stmt.targets if isinstance(t, ast.Name)}
    if not mod_locks:
        return
    for func in _nested_defs(ctx.tree):
        declared = {n for s in ast.walk(func)
                    if isinstance(s, ast.Global) for n in s.names}
        if not declared:
            continue
        info = _FnInfo(func, traced=False)

        def g_mutated(stmt, func=func, info=info, declared=declared):
            for t in _assign_target_attrs(stmt):
                base = t.value if isinstance(t, ast.Subscript) else t
                if (isinstance(base, ast.Name) and base.id in declared
                        and base.id not in mod_locks):
                    ctx.emit("TF114", stmt,
                             f"global {base.id} mutated outside "
                             f"`with {sorted(mod_locks)[0]}:` in "
                             f"{func.name}() — this module guards its "
                             f"globals with a module-level lock; hold "
                             f"it, or suppress with tf-lint: ok[TF114] "
                             f"and a reason", info)

        _tf114_walk(ctx, mod_locks, g_mutated, func, False)


# ---------------------------------------------------------------------------
# Driver.
# ---------------------------------------------------------------------------


def _visit_fn(ctx: FileContext, node, enclosing_traced: bool):
    traced = (enclosing_traced
              or node.name in ctx.jitted
              or any(_is_tracing_decorator(d)
                     for d in node.decorator_list))
    info = _FnInfo(node, traced, probes_backend=_probes_backend(node))
    for rule in _FN_RULES:
        rule(ctx, info)
    for child in _iter_local(node):
        for rule in _NODE_RULES:
            rule(ctx, child, info)
    for sub in _nested_defs(node):
        _visit_fn(ctx, sub, traced)


def lint_source(src: str, path: str = "<string>") -> list[LintFinding]:
    """Run every rule over one source blob; suppressions already applied."""
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [LintFinding("TF100", path, e.lineno or 0,
                            f"syntax error: {e.msg}")]
    ctx = FileContext(tree, src, path)
    for top in _iter_local(tree):
        for rule in _NODE_RULES:
            rule(ctx, top, None)   # module level: TF104 still applies
    for top in _nested_defs(tree):
        _visit_fn(ctx, top, False)
    for rule in _FILE_RULES:
        rule(ctx)
    return ctx.findings


def lint_paths(paths, exclude: tuple[str, ...] = ()) -> list[LintFinding]:
    """Lint every ``.py`` under each path (file or directory tree)."""
    findings: list[LintFinding] = []
    for p in paths:
        p = Path(p)
        files = sorted(p.rglob("*.py")) if p.is_dir() else [p]
        for f in files:
            rel = str(f)
            if any(part in rel for part in exclude):
                continue
            try:
                src = f.read_text()
            except OSError as e:
                findings.append(LintFinding("TF100", rel, 0, str(e)))
                continue
            findings.extend(lint_source(src, rel))
    return findings
