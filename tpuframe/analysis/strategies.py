"""Auditable step programs, one per MULTICHIP parallelism strategy.

Each builder constructs the *real* framework step — the same
``make_train_step``/``pp_lm`` machinery production uses — over a tiny
model and a shapes-only state (``jax.eval_shape``; no parameter math
runs), lowers it AOT, and pairs the compiled program with the strategy's
declared :class:`~tpuframe.analysis.budgets.CommBudget`.  That makes the
communication-structure contract of every strategy checkable in seconds
on a CPU host: ``audit_strategy("lm-tensor-parallel")`` is the static
equivalent of burning a pod slice to discover a mis-sharding.

Capability gating: strategies whose step code needs jax features this
interpreter lacks (the vma/pcast machinery behind ring/Ulysses sequence
parallelism, GPipe PP and adasum on jax < 0.6) raise
:class:`Unavailable` with the missing-API reason instead of failing —
the CLI reports them as SKIP, tests ``pytest.skip`` on them, and on a
current jax they audit for real.  An Unavailable is a *capability*
statement, never a budget verdict.

Everything here expects a multi-device backend; on a plain CPU host run
under ``XLA_FLAGS=--xla_force_host_platform_device_count=8`` (the CLI's
child process sets this up — see ``tpuframe.analysis.__main__``).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field

from tpuframe.analysis import budgets as budgets_lib
from tpuframe.analysis import hlo_audit

# Exception types that signal "this jax cannot express the strategy",
# as opposed to a real defect in the step program.
_CAPABILITY_ERRORS = (AttributeError, ImportError, NotImplementedError)


class Unavailable(Exception):
    """The strategy cannot be built in this environment (missing jax
    feature or too few devices) — a skip, not a failure."""


@dataclass(frozen=True)
class StrategyMeta:
    """What a strategy *declares* about itself, for the shardflow
    detectors: the mesh its replica groups must decompose over, the
    dtype its collectives are allowed to carry on the wire, and the
    per-leaf (dtype, full_dims, shard_dims) sharding expectations the
    accidental-replication detector checks entry parameters against."""

    mesh_shape: tuple[tuple[str, int], ...]
    wire_dtype: str = "f32"
    declared_leaves: tuple = ()    # ((hlo_dtype, full_dims, shard_dims),)
    #: resolved gradient-path wire format ("fp" | "int8-block") — what
    #: the roofline comm model reads to pick payload bytes per element,
    #: instead of guessing from the accumulation dtype.
    wire_format: str = "fp"
    #: the strategy claims its collectives overlap with compute (async
    #: start/done windows with work inside).  The exposed-communication
    #: detector FAILS a declared-overlapped strategy whose compiled
    #: program consumes a collective start back-to-back; undeclared
    #: strategies only get the exposure *reported* (CPU-compiled audits
    #: have no async scheduler, so nothing today may declare this —
    #: the future bucketed-fusion strategy is who the flag is for).
    declared_overlapped: bool = False

    @property
    def mesh_dict(self) -> dict:
        return dict(self.mesh_shape)


@dataclass
class StrategyAudit:
    """Outcome of auditing one strategy's step program."""

    name: str
    status: str                    # "ok" | "violation" | "unavailable"
    reason: str = ""               # set when unavailable
    violations: list[str] = field(default_factory=list)
    report: hlo_audit.CollectiveReport | None = None
    budget: budgets_lib.CommBudget | None = None
    param_bytes: int = 0
    compiled: object = None        # the AOT executable, for chained checks
    meta: StrategyMeta | None = None

    def __str__(self):
        if self.status == "unavailable":
            return f"SKIP {self.name}: {self.reason}"
        head = "PASS" if self.status == "ok" else "FAIL"
        body = self.report.summary() if self.report else "no report"
        tail = "".join(f"\n    {v}" for v in self.violations)
        return f"{head} {self.name}: {body}{tail}"


def _tree_bytes(tree) -> int:
    import jax
    import numpy as np

    return int(sum(np.prod(l.shape or (1,)) * np.dtype(l.dtype).itemsize
                   for l in jax.tree.leaves(tree)))


#: numpy dtype name -> optimized-HLO spelling (what parse_graph sees).
_HLO_DTYPES = {
    "float64": "f64", "float32": "f32", "float16": "f16",
    "bfloat16": "bf16", "int64": "s64", "int32": "s32", "int16": "s16",
    "int8": "s8", "uint64": "u64", "uint32": "u32", "uint16": "u16",
    "uint8": "u8", "bool": "pred",
}


def _meta(mesh, *, wire_dtype: str = "f32",
          declared_leaves: tuple = (),
          wire_format: str = "fp",
          declared_overlapped: bool = False) -> StrategyMeta:
    return StrategyMeta(
        mesh_shape=tuple((str(a), int(s)) for a, s in mesh.shape.items()),
        wire_dtype=wire_dtype, declared_leaves=declared_leaves,
        wire_format=wire_format,
        declared_overlapped=declared_overlapped)


def _declared_leaves(tree, shardings) -> tuple:
    """(hlo_dtype, full_dims, shard_dims) per state leaf — what the
    accidental-replication detector expects entry parameters to look
    like.  ``shardings`` is a matching pytree of NamedSharding."""
    import jax

    out = []
    for leaf, sh in zip(jax.tree.leaves(tree), jax.tree.leaves(shardings)):
        dt = _HLO_DTYPES.get(str(getattr(leaf, "dtype", "")))
        if dt is None or not hasattr(sh, "shard_shape"):
            continue
        full = tuple(int(d) for d in leaf.shape)
        shard = tuple(int(d) for d in sh.shard_shape(full))
        out.append((dt, full, shard))
    return tuple(out)


def _leaves_from_sds(tree) -> tuple:
    """Same, for trees of ShapeDtypeStruct that carry their sharding."""
    import jax

    annotated = [(l, l.sharding) for l in jax.tree.leaves(tree)
                 if getattr(l, "sharding", None) is not None]
    return _declared_leaves([l for l, _ in annotated],
                            [s for _, s in annotated])


def _require_devices(n: int):
    import jax

    have = len(jax.devices())
    if have < n:
        raise Unavailable(
            f"needs {n} devices, have {have} — run under "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={n} "
            f"(python -m tpuframe.analysis does this automatically)")


def _lm_pieces(batch: int = 8, seq: int = 32, **cfg_kw):
    """Tiny TransformerLM + shapes-only state/batch for AOT lowering."""
    import jax
    import jax.numpy as jnp
    import optax

    from tpuframe import models
    from tpuframe.models import losses
    from tpuframe.parallel import step as step_lib

    model = models.get_model("transformer-lm", tiny=True, vocab_size=64,
                             max_seq=seq, **cfg_kw)
    variables = jax.eval_shape(model.init, jax.random.key(0),
                               jax.ShapeDtypeStruct((1, seq), jnp.int32))
    tx = optax.adamw(1e-3)

    def loss_fn(params, model_state, b, rng):
        logits = model.apply({"params": params}, b["input_ids"],
                             train=True, rngs={"dropout": rng})
        return losses.softmax_cross_entropy(logits, b["labels"]), ({}, {})

    state = jax.eval_shape(lambda p: step_lib.TrainState.create(p, tx),
                           variables["params"])
    ids = jax.ShapeDtypeStruct((batch, seq), jnp.int32)
    example = (state, {"input_ids": ids, "labels": ids})
    param_bytes = _tree_bytes(variables["params"])
    # one activation tensor [B, S, H] in compute dtype (f32 for tiny)
    act_bytes = batch * seq * 64 * 4
    return model, loss_fn, tx, example, param_bytes, act_bytes


# --------------------------------------------------------------------------
# Builders.  Each returns
# (jitted_step, example_args, budget, param_bytes, meta).
#
# Every training parallelism strategy is SPEC-LOWERED: one generic
# builder parses a ``tpuframe.parallel.pspec`` string, builds the
# declared (possibly ICI×DCN) mesh, and lets ``pspec.lower`` /
# ``pspec.lower_pp`` pick the step seams — zero1/wire-format/adasum ride
# as orthogonal modifiers, tp/ep thread the model sharding rules, sp
# partitions the sequence dim, pp drives the GPipe harness.  The only
# hand-wired builder left is the serving decode audit, which is a decode
# program (no train step, no parallelism spec to lower).
# --------------------------------------------------------------------------


def _spec_budget(spec, pb: int, n_devices: int, *, weight_update: str,
                 wire_format: str, padded: int | None, ab: int = 0,
                 seq_mode: str | None = None,
                 grad_reduce: str | None = None,
                 fusion_threshold: int | None = None,
                 hier: str | None = None,
                 wire_format_dcn: str | None = None,
                 n_inner: int = 1):
    """The declared CommBudget for a composed spec — the same per-kind
    ceilings the hand-wired family declared, picked by axis/modifier;
    the byte-exact pin lives in ``derived_budgets.json`` either way."""
    if spec.pp > 1:
        return budgets_lib.pp_budget(pb, ab, n_micro=2)
    if spec.ep > 1:
        return budgets_lib.ep_budget(pb, ab)
    if spec.tp > 1:
        return budgets_lib.tp_budget(pb, ab, num_layers=2)
    if spec.fsdp > 1:
        return budgets_lib.fsdp_budget(pb)
    if spec.sp > 1:
        if (seq_mode or "ring") == "ring":
            return budgets_lib.ring_sp_budget(pb, kv_bytes=2 * ab,
                                              sp_degree=spec.sp)
        return budgets_lib.ulysses_sp_budget(pb, ab)
    if grad_reduce == "adasum":
        return budgets_lib.adasum_budget(pb, n_devices)
    if hier == "hier":
        dcn_int8 = (wire_format_dcn or "fp") == "int8-block"
        if weight_update == "zero1":
            if dcn_int8:
                return budgets_lib.hier_zero1_int8_budget(padded, n_inner)
            return budgets_lib.hier_zero1_budget(padded, n_inner)
        if dcn_int8:
            return budgets_lib.hier_dp_int8_budget(pb, n_inner)
        return budgets_lib.hier_dp_budget(pb, n_inner)
    if weight_update == "zero1" and wire_format == "int8-block":
        return budgets_lib.zero1_int8_budget(padded, n_devices)
    if weight_update == "zero1":
        # Bucketed fusion keeps the exact pad-to-multiple wire bytes —
        # the zero1 ceilings hold unchanged, fused or not.
        return budgets_lib.zero1_budget(padded)
    if wire_format == "int8-block":
        return budgets_lib.dp_int8_budget(pb, n_devices)
    if fusion_threshold is not None:
        return budgets_lib.fused_dp_budget(pb)
    return budgets_lib.dp_budget(pb)


def _moe_pieces():
    """Tiny MoE TransformerLM + shapes-only state/batch for the ``ep``
    lowering: expert blocks every layer, aux loss threaded through the
    ``mutable=["aux_loss"]`` collection exactly as train.py does."""
    import jax
    import jax.numpy as jnp
    import optax

    from tpuframe.models import losses
    from tpuframe.models.transformer_lm import LMConfig, TransformerLM
    from tpuframe.parallel import step as step_lib

    cfg = LMConfig.tiny(vocab_size=64, hidden_size=32, num_layers=2,
                        num_heads=2, intermediate_size=64, max_seq=16,
                        moe_experts=4, moe_k=2, moe_every=1)
    model = TransformerLM(cfg)
    variables = jax.eval_shape(model.init, jax.random.key(0),
                               jax.ShapeDtypeStruct((1, 16), jnp.int32))
    tx = optax.adamw(1e-3)

    def loss_fn(params, model_state, b, rng):
        logits, sown = model.apply({"params": params}, b["input_ids"],
                                   train=True, rngs={"dropout": rng},
                                   mutable=["aux_loss"])
        loss = losses.softmax_cross_entropy(logits, b["labels"])
        leaves = jax.tree.leaves(sown)
        aux = sum(leaves) / max(len(leaves), 1)
        return loss + cfg.moe_aux_weight * aux, ({}, {"moe_aux": aux})

    state = jax.eval_shape(lambda p: step_lib.TrainState.create(p, tx),
                           variables["params"])
    ids = jax.ShapeDtypeStruct((8, 16), jnp.int32)
    example = (state, {"input_ids": ids, "labels": ids})
    pb = _tree_bytes(variables["params"])
    ab = 8 * 16 * 32 * 4
    return model, loss_fn, tx, example, pb, ab


def _pp_build(spec, mesh):
    """The ``pp`` lowering: ScanBlockLM with one block per stage, driven
    through :func:`tpuframe.parallel.pspec.lower_pp` (the GPipe
    harness).  Modifiers never reach here — the caller rejects them."""
    import jax
    import jax.numpy as jnp
    import optax

    from tpuframe.models.transformer_lm import LMConfig, ScanBlockLM
    from tpuframe.parallel import pspec
    from tpuframe.parallel import step as step_lib

    cfg = LMConfig.tiny(vocab_size=64, hidden_size=32,
                        num_layers=spec.pp, num_heads=2,
                        intermediate_size=64, max_seq=16)
    model = ScanBlockLM(cfg)
    tx = optax.adamw(1e-3)
    variables = jax.eval_shape(model.init, jax.random.key(0),
                               jax.ShapeDtypeStruct((1, 16), jnp.int32))
    n_micro = 2
    factory, _place_state, _place_batch = pspec.lower_pp(
        spec, mesh, model, tx, n_micro=n_micro)
    state = jax.eval_shape(lambda p: step_lib.TrainState.create(p, tx),
                           variables["params"])
    ids = jax.ShapeDtypeStruct((8, 16), jnp.int32)
    step = factory(state)
    pb = _tree_bytes(variables["params"])
    ab = 8 * 16 * 32 * 4
    return (step, (state, {"input_ids": ids, "labels": ids}),
            budgets_lib.pp_budget(pb, ab, n_micro=n_micro), pb,
            _meta(mesh))


def _build_from_spec(spec_text: str, n_devices: int, *,
                     weight_update: str = "replicated",
                     wire_format: str | None = None,
                     seq_mode: str | None = None,
                     grad_reduce: str | None = None,
                     fusion_threshold: int | None = None,
                     hier: str | None = None,
                     wire_format_dcn: str | None = None,
                     declared_overlapped: bool = False,
                     devices=None):
    """Generic spec-lowered builder: ``spec_text`` (the
    ``TPUFRAME_SPEC`` grammar) -> hierarchical mesh -> lowered step.
    A spec whose axis product cannot fit ``n_devices`` is an
    :class:`Unavailable` (a skip — the spec is for a different world
    size), never a violation.  ``devices`` overrides the device list
    (the planner passes compile-only topology devices); ``seq_mode``
    picks ring vs Ulysses attention for ``sp`` specs; ``grad_reduce``
    threads the adasum modifier; ``fusion_threshold`` threads the
    bucketed-fusion modifier (tpuframe.parallel.fusion's staged pass);
    ``hier``/``wire_format_dcn`` thread the two-level cross-slice
    lowering and its DCN-leg wire (tpuframe.parallel.hier), and
    ``declared_overlapped`` signs the overlap contract the
    exposed-comm detector then enforces live."""
    import dataclasses

    import jax

    from tpuframe.parallel import mesh as mesh_lib, pspec
    from tpuframe.parallel import step as step_lib

    spec = pspec.parse_spec(spec_text)
    try:
        spec.sizes(n_devices)
    except pspec.SpecError as e:
        raise Unavailable(str(e)) from e
    if devices is None:
        devices = jax.devices()[:n_devices]
    mesh = spec.make_mesh(devices=devices)
    wire = wire_format or "fp"
    if spec.pp > 1:
        if (weight_update != "replicated" or wire != "fp"
                or seq_mode or grad_reduce or fusion_threshold is not None):
            raise pspec.SpecError(
                f"spec '{spec.canonical()}': the GPipe lowering takes no "
                f"modifiers — zero1/wire/seq_mode/adasum/fusion do not "
                f"compose")
        return _pp_build(spec, mesh)
    if spec.ep > 1:
        _, loss_fn, tx, (state, batch), pb, ab = _moe_pieces()
    elif spec.sp > 1:
        _, loss_fn, tx, (state, batch), pb, ab = _lm_pieces(
            seq_mode=seq_mode or "ring")
    else:
        _, loss_fn, tx, (state, batch), pb, ab = _lm_pieces()
    padded = None
    if weight_update == "zero1":
        from tpuframe.parallel import zero1 as zero1_lib

        n = zero1_lib.world_size(mesh, mesh_lib.batch_axes(mesh))
        opt = jax.eval_shape(
            lambda p: zero1_lib.init_opt_state(tx, p, n), state.params)
        state = dataclasses.replace(state, opt_state=opt)
        padded = zero1_lib.padded_bytes(state.params, n)
    tp_rules = None
    if spec.tp > 1 or spec.ep > 1:
        from tpuframe.parallel import tp as tp_lib

        tp_rules = tp_lib.rules_for_model("transformer-lm")
    kwargs = pspec.lower(spec, mesh, state, weight_update=weight_update,
                         wire_format=wire, tp_rules=tp_rules,
                         grad_reduce=grad_reduce,
                         fusion_threshold=fusion_threshold,
                         hier=hier, wire_format_dcn=wire_format_dcn)
    step = step_lib.make_train_step(loss_fn, tx, mesh, donate=False,
                                    **kwargs)
    # In-slice world size for the two-level budgets: the batch-axis
    # product with the slice (DCN) axis divided out — the factor the
    # lowering's cross-slice leg shrinks by.
    sizes = dict(mesh.shape)
    n_slice = int(sizes.get(mesh_lib.SLICE_AXIS, 1))
    n_batch = 1
    for a in mesh_lib.batch_axes(mesh):
        n_batch *= int(sizes.get(a, 1))
    n_inner = max(1, n_batch // max(n_slice, 1))
    budget = _spec_budget(spec, pb, n_devices, weight_update=weight_update,
                          wire_format=wire, padded=padded, ab=ab,
                          seq_mode=seq_mode, grad_reduce=grad_reduce,
                          fusion_threshold=fusion_threshold,
                          hier=hier, wire_format_dcn=wire_format_dcn,
                          n_inner=n_inner)
    shardings = kwargs.get("state_shardings")
    dcn_int8 = (hier == "hier"
                and (wire_format_dcn or "fp") == "int8-block")
    return (step, (state, batch), budget, pb,
            _meta(mesh,
                  wire_format="int8-block"
                  if (wire == "int8-block" or dcn_int8) else "fp",
                  declared_leaves=(_declared_leaves(state, shardings)
                                   if shardings is not None else ()),
                  declared_overlapped=declared_overlapped))


def _spec_name(spec_text: str, *, weight_update: str = "replicated",
               wire_format: str | None = None,
               seq_mode: str | None = None,
               grad_reduce: str | None = None,
               fusion_threshold: int | None = None,
               hier: str | None = None,
               wire_format_dcn: str | None = None) -> str:
    """Canonical strategy name for a composed spec: the spec's canonical
    spelling under a ``spec:`` prefix plus any modifiers — stable, so an
    auto-derived budget can be pinned in ``derived_budgets.json``."""
    from tpuframe.parallel import pspec

    name = f"spec:{pspec.parse_spec(spec_text).canonical()}"
    if weight_update != "replicated":
        name += f"+{weight_update}"
    if wire_format:
        name += f"+{wire_format}"
    if hier:
        name += f"+{hier}"
    if wire_format_dcn and wire_format_dcn != "fp":
        name += "+dcn-int8"
    if seq_mode:
        name += f"+{seq_mode}"
    if grad_reduce:
        name += f"+{grad_reduce}"
    if fusion_threshold is not None:
        name += f"+fused{int(fusion_threshold)}"
    return name


def register_spec_strategy(spec_text: str, *,
                           weight_update: str = "replicated",
                           wire_format: str | None = None,
                           seq_mode: str | None = None,
                           grad_reduce: str | None = None,
                           fusion_threshold: int | None = None,
                           hier: str | None = None,
                           wire_format_dcn: str | None = None,
                           declared_overlapped: bool = False) -> str:
    """Register a composed parallelism spec as a dynamic analysis
    strategy.  The name is the spec's canonical spelling under a
    ``spec:`` prefix (plus any modifiers) — stable, so its auto-derived
    budget can be pinned in ``derived_budgets.json`` like any named
    strategy's.  This is the ONE seam through which strategies enter the
    registry (TF120 lints everything else), and the ONE module allowed
    to sign ``declared_overlapped=True`` (TF122 lints everything else) —
    a strategy cannot claim compute/communication overlap without going
    through the audited fusion registration below."""
    import functools

    name = _spec_name(spec_text, weight_update=weight_update,
                      wire_format=wire_format, seq_mode=seq_mode,
                      grad_reduce=grad_reduce,
                      fusion_threshold=fusion_threshold,
                      hier=hier, wire_format_dcn=wire_format_dcn)
    STRATEGIES[name] = functools.partial(
        _build_from_spec, spec_text, weight_update=weight_update,
        wire_format=wire_format, seq_mode=seq_mode,
        grad_reduce=grad_reduce, fusion_threshold=fusion_threshold,
        hier=hier, wire_format_dcn=wire_format_dcn,
        declared_overlapped=declared_overlapped)
    return name


_warned_legacy: set = set()


def _warn_legacy(fn_name: str, spec_text: str) -> None:
    """Warn-once deprecation for the retired hand-wired constructors
    (the ``TPUFRAME_BENCH_REMAT`` alias idiom)."""
    if fn_name in _warned_legacy:
        return
    _warned_legacy.add(fn_name)
    import warnings

    warnings.warn(
        f"strategies.{fn_name} is a deprecated hand-wired constructor; "
        f"the strategy is spec-lowered now — use the {spec_text!r} "
        f"parallelism spec (tpuframe.parallel.pspec)",
        DeprecationWarning, stacklevel=3)


def _build_dp(n_devices: int):
    _warn_legacy("_build_dp", "dp=*")
    return _build_from_spec("dp=*", n_devices)


def _build_zero1(n_devices: int):
    """Deprecated alias: plain DP with the ZeRO-1 weight-update modifier
    (``weight_update="zero1"`` on the ``dp=*`` spec) — the audit proves
    the collective swap (no all-reduce above the scalar floor;
    reduce-scatter + all-gather at exactly the pad-to-multiple total)."""
    _warn_legacy("_build_zero1", "dp=*")
    return _build_from_spec("dp=*", n_devices, weight_update="zero1")


def _build_dp_int8(n_devices: int):
    """Deprecated alias: plain DP over the int8-block wire
    (``wire_format="int8-block"`` on the ``dp=*`` spec) — grad
    all-reduce becomes a quantized all-to-all + all-gather pair carrying
    s8 payloads at ~4x fewer wire bytes."""
    _warn_legacy("_build_dp_int8", "dp=*")
    return _build_from_spec("dp=*", n_devices, wire_format="int8-block")


def _build_zero1_int8(n_devices: int):
    """Deprecated alias: ZeRO-1 over the int8-block wire — both
    modifiers composed on the ``dp=*`` spec."""
    _warn_legacy("_build_zero1_int8", "dp=*")
    return _build_from_spec("dp=*", n_devices, weight_update="zero1",
                            wire_format="int8-block")


def _build_fsdp(n_devices: int):
    """Deprecated alias: the dp×fsdp layout is spec-lowered now."""
    _warn_legacy("_build_fsdp", "dp=*,fsdp=2")
    return _build_from_spec("dp=*,fsdp=2", n_devices)


def _build_tp(n_devices: int):
    """Deprecated alias: tensor parallelism is spec-lowered now (the
    ``tp=`` axis threads ``tp.rules_for_model`` automatically)."""
    tp = 4 if n_devices % 4 == 0 else 2
    _warn_legacy("_build_tp", f"dp=*,tp={tp}")
    return _build_from_spec(f"dp=*,tp={tp}", n_devices)


def _build_ring_sp(n_devices: int, seq_mode: str = "ring"):
    """Deprecated alias: sequence parallelism is spec-lowered now (the
    ``sp=`` axis partitions the batch's sequence dim; ``seq_mode`` picks
    ring vs Ulysses attention)."""
    sp = 4 if n_devices % 4 == 0 else 2
    _warn_legacy("_build_ring_sp", f"dp=*,sp={sp}")
    return _build_from_spec(f"dp=*,sp={sp}", n_devices, seq_mode=seq_mode)


def _build_ulysses(n_devices: int):
    return _build_ring_sp(n_devices, seq_mode="ulysses")


def _build_pp(n_devices: int):
    """Deprecated alias: pipeline parallelism is spec-lowered now (the
    ``pp=`` axis drives the GPipe harness via ``pspec.lower_pp``)."""
    pipe = 4 if n_devices % 4 == 0 else 2
    _warn_legacy("_build_pp", f"dp=*,pp={pipe}")
    return _build_from_spec(f"dp=*,pp={pipe}", n_devices)


def _build_ep(n_devices: int):
    """Deprecated alias: expert parallelism is spec-lowered now (the
    ``ep=`` axis shards the MoE expert blocks via the model rules)."""
    _warn_legacy("_build_ep", "dp=*,ep=2")
    return _build_from_spec("dp=*,ep=2", n_devices)


def _build_serve_decode(n_devices: int):
    """Plain-DP serving decode: KV slots sharded over ``data``, params
    replicated, ONE decode step (query length 1) — the exact program
    serve/engine.py compiles, audited for a zero-collective HLO."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpuframe.models.transformer_lm import LMConfig, TransformerLM
    from tpuframe.parallel import mesh as mesh_lib
    from tpuframe.serve import engine as engine_lib
    from tpuframe.serve import kv_cache as kv

    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec(data=n_devices))
    cfg = LMConfig.tiny(vocab_size=64)
    spec = kv.spec_for_model(cfg, slots=n_devices, capacity=64)
    model = TransformerLM(cfg)
    decode_fn = engine_lib.make_decode_fn(model)

    variables = jax.eval_shape(model.init, jax.random.key(0),
                               jax.ShapeDtypeStruct((1, 8), jnp.int32))
    pb = _tree_bytes(variables["params"])

    rep = NamedSharding(mesh, P())
    row = NamedSharding(mesh, P("data"))
    sds = jax.ShapeDtypeStruct
    p_sds = jax.tree.map(lambda a: sds(a.shape, a.dtype, sharding=rep),
                         variables["params"])
    dtype = jnp.dtype(spec.dtype)
    cache_sds = tuple(
        (sds(spec.layer_shape(), dtype, sharding=row),
         sds(spec.layer_shape(), dtype, sharding=row))
        for _ in range(cfg.num_layers))
    example = (p_sds,
               sds((spec.slots, 1), jnp.int32, sharding=row),
               sds((spec.slots,), jnp.int32, sharding=row),
               cache_sds)
    return (jax.jit(decode_fn), example,
            budgets_lib.serve_decode_budget(pb), pb,
            _meta(mesh, declared_leaves=_leaves_from_sds(example)))


def _build_adasum(n_devices: int):
    """Deprecated alias: adasum is the ``grad_reduce`` modifier on the
    plain ``dp=*`` spec now."""
    _warn_legacy("_build_adasum", "dp=*")
    return _build_from_spec("dp=*", n_devices, grad_reduce="adasum")


#: MULTICHIP_r05.json strategy name -> builder.  Every training
#: strategy is spec-lowered (the partials below ARE the registration —
#: the old ``_build_*`` constructors survive only as warn-once
#: deprecated aliases).  The friendly names stay stable so the pinned
#: records in ``derived_budgets.json``/``derived_schedule.json`` keep
#: meaning the same programs.  ``spec:`` entries follow the
#: :func:`register_spec_strategy` naming convention; the composed
#: hierarchical entry is the PR 15 acceptance case — dp×fsdp inside
#: each slice, replicated over the DCN slice axis.  The serving decode
#: audit is the one non-spec entry (a decode program, not a train-step
#: parallelism).
STRATEGIES = {
    "dp": functools.partial(_build_from_spec, "dp=*"),
    "dp-int8": functools.partial(_build_from_spec, "dp=*",
                                 wire_format="int8-block"),
    "dp-zero1": functools.partial(_build_from_spec, "dp=*",
                                  weight_update="zero1"),
    "dp-zero1-int8": functools.partial(_build_from_spec, "dp=*",
                                       weight_update="zero1",
                                       wire_format="int8-block"),
    "spec:dp=2,fsdp=2;slices=2": functools.partial(
        _build_from_spec, "dp=2,fsdp=2;slices=2"),
    "resnet-fsdp": functools.partial(_build_from_spec, "dp=*,fsdp=2"),
    "lm-tensor-parallel": functools.partial(_build_from_spec, "dp=*,tp=4"),
    "lm-seq-parallel": functools.partial(_build_from_spec, "dp=*,sp=4",
                                         seq_mode="ring"),
    "lm-seq-ulysses": functools.partial(_build_from_spec, "dp=*,sp=4",
                                        seq_mode="ulysses"),
    "pipeline-parallel": functools.partial(_build_from_spec, "dp=*,pp=4"),
    "expert-parallel": functools.partial(_build_from_spec, "dp=*,ep=2"),
    "dp-adasum": functools.partial(_build_from_spec, "dp=*",
                                   grad_reduce="adasum"),
    "serve-dp-decode": _build_serve_decode,
}

#: Bucket threshold the fused registry variants pin — mirrors
#: ``fusion.REGISTRY_THRESHOLD`` (duplicated so this module stays
#: jax-free at import; tests/test_fusion.py asserts the two agree).
_FUSED_REGISTRY_THRESHOLD = 128 * 1024

#: The overlapped bucketed-fusion registrations (ISSUE 18): the staged
#: pass (fusion.staged_psum / the bucketed zero1 scatter-gather) signs
#: the ``declared_overlapped`` contract, flipping detect_exposed_comm
#: from report-only to a live gate for exactly these two programs.
#: These are the ONLY sanctioned ``declared_overlapped=True`` call
#: sites — TF122 fails the gate on any other (see source_lint).
DP_FUSED = register_spec_strategy(
    "dp=*", fusion_threshold=_FUSED_REGISTRY_THRESHOLD,
    declared_overlapped=True)
DP_ZERO1_FUSED = register_spec_strategy(
    "dp=*", weight_update="zero1",
    fusion_threshold=_FUSED_REGISTRY_THRESHOLD,
    declared_overlapped=True)

#: The hierarchical two-level collective family (ISSUE 20): flat/hier
#: twins on the pure-DP multi-slice spec so the auto-derived budget pins
#: document the DCN byte column dropping by n_inner (fp cross-slice leg)
#: and by ~4·n_inner (int8-block DCN leg) against the SAME spec, model
#: and world.  The zero1 composition is the acceptance carrier: flat
#: ZeRO-1 pays two full-size DCN collectives per step (rs in, ag out),
#: the two-level int8 shape two s8 shard-size ones.
_HIER_SPEC = "dp=*;slices=2"
HIER_FLAT = register_spec_strategy(_HIER_SPEC)
HIER_DP = register_spec_strategy(_HIER_SPEC, hier="hier")
HIER_DP_INT8 = register_spec_strategy(
    _HIER_SPEC, hier="hier", wire_format_dcn="int8-block")
HIER_ZERO1_FLAT = register_spec_strategy(
    _HIER_SPEC, weight_update="zero1")
HIER_ZERO1 = register_spec_strategy(
    _HIER_SPEC, weight_update="zero1", hier="hier")
HIER_ZERO1_INT8 = register_spec_strategy(
    _HIER_SPEC, weight_update="zero1", hier="hier",
    wire_format_dcn="int8-block")


def _overlap_compile_opts(meta) -> dict | None:
    """A strategy that signs ``declared_overlapped`` owns its bucketing:
    the staged fusion pass already packed the gradient wire, so XLA's
    all-reduce combiner is asked to keep its hands off via the generic
    DebugOptions field ("gpu" is historical naming — see
    parallel/tuning.py).  Backends that read the field (CPU XLA here)
    honor it; the v5e libtpu pin accepts-but-ignores it and re-merges
    the buckets into one end-of-step collective anyway (no ``xla_tpu_*``
    spelling exists: "No such compile option"), so on that backend the
    live gate (correctly) rules the declaration vacuously false —
    PERF.md §26 records the measurement.  Rides the compile request
    per-compile (the TF106-sanctioned path), never XLA_FLAGS."""
    if meta is None or not getattr(meta, "declared_overlapped", False):
        return None
    return {"xla_gpu_all_reduce_combine_threshold_bytes": 0}


def audit_spec(spec_text: str, *, n_devices: int,
               weight_update: str = "replicated",
               wire_format: str | None = None,
               seq_mode: str | None = None,
               grad_reduce: str | None = None,
               fusion_threshold: int | None = None,
               hier: str | None = None,
               wire_format_dcn: str | None = None,
               devices=None, name: str | None = None) -> StrategyAudit:
    """Audit an UNREGISTERED spec candidate — the ``tune plan`` seam.

    Same build/compile/budget-check pipeline as :func:`audit_strategy`,
    but over an ad-hoc spec string instead of a registry entry, and with
    an optional explicit device list so the planner can compile against
    ``pspec.topology_devices`` instead of the local backend.  The
    planner enumerating hundreds of candidates goes through here so it
    never hand-builds a :class:`StrategyMeta` (TF120's rule).  A
    ``fusion_threshold`` candidate runs the staged bucketed pass and is
    automatically declared overlapped — the same contract the registered
    fused variants sign."""
    label = name or _spec_name(spec_text, weight_update=weight_update,
                               wire_format=wire_format, seq_mode=seq_mode,
                               grad_reduce=grad_reduce,
                               fusion_threshold=fusion_threshold,
                               hier=hier, wire_format_dcn=wire_format_dcn)
    try:
        if devices is None:
            _require_devices(n_devices)
        step, example, budget, pb, meta = _build_from_spec(
            spec_text, n_devices, weight_update=weight_update,
            wire_format=wire_format, seq_mode=seq_mode,
            grad_reduce=grad_reduce, fusion_threshold=fusion_threshold,
            hier=hier, wire_format_dcn=wire_format_dcn,
            declared_overlapped=fusion_threshold is not None,
            devices=devices)
        report, compiled = hlo_audit.audit_jitted(
            step, *example, compiler_options=_overlap_compile_opts(meta))
    except Unavailable as e:
        return StrategyAudit(name=label, status="unavailable",
                             reason=str(e))
    except _CAPABILITY_ERRORS as e:
        return StrategyAudit(
            name=label, status="unavailable",
            reason=f"{type(e).__name__}: {e} (jax {_jax_version()} lacks "
                   f"an API this strategy's step code needs)")
    violations = budgets_lib.check_budget(report, budget)
    return StrategyAudit(
        name=label, status="ok" if not violations else "violation",
        violations=violations, report=report, budget=budget,
        param_bytes=pb, compiled=compiled, meta=meta)


def audit_strategy(name: str, n_devices: int = 8) -> StrategyAudit:
    """Build, AOT-compile and budget-check one strategy's step program."""
    if name not in STRATEGIES:
        raise ValueError(f"unknown strategy {name!r}; "
                         f"have {sorted(STRATEGIES)}")
    try:
        _require_devices(n_devices)
        step, example, budget, pb, meta = STRATEGIES[name](n_devices)
        report, compiled = hlo_audit.audit_jitted(
            step, *example, compiler_options=_overlap_compile_opts(meta))
    except Unavailable as e:
        return StrategyAudit(name=name, status="unavailable",
                             reason=str(e))
    except _CAPABILITY_ERRORS as e:
        return StrategyAudit(
            name=name, status="unavailable",
            reason=f"{type(e).__name__}: {e} (jax {_jax_version()} lacks "
                   f"an API this strategy's step code needs)")
    violations = budgets_lib.check_budget(report, budget)
    return StrategyAudit(
        name=name, status="ok" if not violations else "violation",
        violations=violations, report=report, budget=budget,
        param_bytes=pb, compiled=compiled, meta=meta)


def audit_all(n_devices: int = 8,
              names: tuple[str, ...] | None = None) -> list[StrategyAudit]:
    return [audit_strategy(n, n_devices)
            for n in (names or tuple(STRATEGIES))]


def _jax_version() -> str:
    import jax

    return jax.__version__
