"""Declared per-strategy communication budgets (Layer 1's policy half).

A :class:`CommBudget` is the *declared* communication structure of a
parallelism strategy: which collective kinds its step program is allowed
to contain and how many bytes each may move per step.  The mechanism
(``tpuframe.analysis.hlo_audit``) reports what the compiler actually
emitted; :func:`check_budget` compares the two.  A sharding-annotation
mistake that makes GSPMD materialize a full all-gather then fails CI
with the offending instruction's shape and replica groups, instead of
burning pod time (the round-5 failure mode this module institutionalizes).

Budgets are declared as *multipliers over program-derived sizes* (param
bytes, activation bytes), not absolute numbers, so the same declaration
covers the tiny CI-audit models and the real configs.  The multipliers
are deliberately generous (2-4x the textbook volume): the check exists
to catch the *class* error — a forbidden collective kind, or an
activation-sized transfer where a param-sized one was declared — not to
police 10% regressions (that is the perf rigs' job, PERF.md §7).

Declaring a budget for a new strategy (docs/DESIGN.md "analysis"):

    budget = CommBudget(
        name="my-strategy",
        allowed={"all-reduce": 2 * param_bytes,
                 "collective-permute": 4 * act_bytes},
        ignore_below=64 * 1024,   # scalar metrics / counters are free
    )

Every kind absent from ``allowed`` is forbidden outright (above the
``ignore_below`` floor) — new communication patterns must be declared,
never inherited silently.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from tpuframe.analysis.hlo_audit import COLLECTIVE_KINDS, CollectiveReport

# Ops smaller than this are metric scalars, step counters, degenerate
# single-element syncs — never the failure class this gate hunts.
DEFAULT_IGNORE_BELOW = 64 * 1024


@dataclass(frozen=True)
class CommBudget:
    """Declared per-step communication ceiling for one strategy."""

    name: str
    # kind -> max bytes per step (None = allowed, unlimited).  Kinds not
    # present are forbidden above ``ignore_below``.
    allowed: dict[str, int | None] = field(default_factory=dict)
    max_total_bytes: int | None = None
    ignore_below: int = DEFAULT_IGNORE_BELOW
    notes: str = ""

    def __post_init__(self):
        bad = set(self.allowed) - set(COLLECTIVE_KINDS)
        if bad:
            raise ValueError(f"unknown collective kind(s) {sorted(bad)}; "
                             f"expected {COLLECTIVE_KINDS}")


def check_budget(report: CollectiveReport, budget: CommBudget) -> list[str]:
    """Violation messages (empty = the program fits its declaration)."""
    violations: list[str] = []
    sig = report.filter(budget.ignore_below)
    by_kind = sig.bytes_by_kind()
    for kind, total in sorted(by_kind.items()):
        if kind not in budget.allowed:
            ops = [op for op in sig.ops if op.kind == kind]
            worst = max(ops, key=lambda op: op.bytes)
            violations.append(
                f"[{budget.name}] undeclared collective kind {kind!r}: "
                f"{len(ops)} op(s), {total / 1e6:.3f} MB "
                f"(largest: {worst})")
            continue
        cap = budget.allowed[kind]
        if cap is not None and total > cap:
            violations.append(
                f"[{budget.name}] {kind} budget exceeded: "
                f"{total / 1e6:.3f} MB > declared {cap / 1e6:.3f} MB")
    if (budget.max_total_bytes is not None
            and sig.total_bytes > budget.max_total_bytes):
        violations.append(
            f"[{budget.name}] total collective bytes exceeded: "
            f"{sig.total_bytes / 1e6:.3f} MB > declared "
            f"{budget.max_total_bytes / 1e6:.3f} MB")
    return violations


# ---------------------------------------------------------------------------
# Strategy declarations — one per parallelism strategy the framework
# trains with (the MULTICHIP_r*.json strategy set).  ``param_bytes`` is
# the f32 byte size of the model parameters (gradient wire dtype);
# ``act_bytes`` the byte size of one sharded activation tensor
# [local_batch, seq, hidden] in compute dtype.
# ---------------------------------------------------------------------------


def dp_budget(param_bytes: int, name: str = "dp") -> CommBudget:
    """Pure data parallelism (Horovod parity): ONE class of collective —
    gradient all-reduce ≲ param bytes (f32), plus metric scalars."""
    return CommBudget(
        name=name,
        allowed={"all-reduce": int(2.0 * param_bytes)},
        notes="grad all-reduce + BN-stat/metric reductions only",
    )


def fused_dp_budget(param_bytes: int,
                    name: str = "dp-fused") -> CommBudget:
    """Plain DP with the explicit bucketed-fusion pass
    (tpuframe.parallel.fusion's staged psum): the same single class of
    collective as :func:`dp_budget` — gradient all-reduce ≲ param bytes
    — but emitted as one op per ≤threshold-byte bucket instead of the
    combiner's grouping, so the floor drops to 1 KiB: EVERY bucket is a
    declared window the schedule records pin (the nonzero-interior
    contract), not just the ones over the 64 KiB scalar floor."""
    return CommBudget(
        name=name,
        allowed={"all-reduce": int(2.0 * param_bytes)},
        ignore_below=1024,
        notes="bucketed grad all-reduce (staged fusion pass) + metric "
              "scalars; every bucket counts above the 1 KiB floor",
    )


def zero1_budget(padded_param_bytes: int, name: str = "dp-zero1") -> CommBudget:
    """ZeRO-1 weight-update sharding (arXiv:2004.13336, the zero1 path):
    the gradient all-reduce is REPLACED by reduce-scatter (grads in — the
    operand is the full padded gradient, which is what crosses the wire)
    plus tiled all-gather (updated params out).  Unlike the other
    budgets' generous multipliers, the ceilings here are EXACT — the
    audit is the proof the collective swap happened, so the declared
    bytes are the pad-to-multiple layout's byte total and nothing more —
    and the floor drops to 1 KiB so even tiny per-leaf collectives count
    (scalar loss/metric/grad-norm reductions stay free).  Any all-reduce
    above that floor is the defect class itself."""
    return CommBudget(
        name=name,
        allowed={"reduce-scatter": int(padded_param_bytes),
                 "all-gather": int(padded_param_bytes)},
        ignore_below=1024,
        notes="grad reduce-scatter in + param all-gather out, exact "
              "pad-to-multiple bytes; all-reduce forbidden above the "
              "1 KiB scalar floor (arXiv:2004.13336 wire pattern)",
    )


def dp_int8_budget(param_bytes: int, n_devices: int = 8,
                   name: str = "dp-int8") -> CommBudget:
    """Plain DP over the int8-block wire (quantwire, arXiv:2506.17615
    style): the grad all-reduce is REPLACED by a quantized all-to-all
    (reduce-scatter phase) plus all-gather, both carrying s8 payloads
    with f32 per-block scales.  Each leg's ceiling is half the f32 param
    bytes — 2x headroom over the ~param_bytes/4 s8 payload + scale/pad
    overhead, and still 4x under :func:`dp_budget`'s 2.0x all-reduce
    ceiling, so the budget itself documents the wire-byte drop.  Leaves
    under the quantization floor (quantwire.MIN_QUANT_ELEMS) fall back
    to fp all-reduce; that residue plus metric reductions gets a small
    explicit allowance rather than a silent exemption, and the floor
    drops to 1 KiB so the audit actually sees the quantized ops (the
    tiny audit model's per-leaf collectives sit below the default
    floor)."""
    del n_devices  # wire bytes are per-device; degree cancels out
    leg = int(0.5 * param_bytes)
    return CommBudget(
        name=name,
        allowed={"all-to-all": leg, "all-gather": leg,
                 "all-reduce": int(0.25 * param_bytes)},
        ignore_below=1024,
        notes="quantized a2a+ag grad path (s8 payload + f32 block "
              "scales), fp all-reduce residue for sub-floor leaves",
    )


def zero1_int8_budget(padded_param_bytes: int, n_devices: int = 8,
                      name: str = "dp-zero1-int8") -> CommBudget:
    """ZeRO-1 over the int8-block wire: the grad reduce-scatter becomes
    a quantized all-to-all, and the param all-gather becomes a quantized
    DELTA all-gather (new_shard - old_shard on the wire; masters stay
    f32).  Each quantized leg is capped at half the padded f32 bytes
    (2x headroom over the s8 payload) versus :func:`zero1_budget`'s
    exact 1.0x per leg — the +9%-step-time all-gather PERF §18 charges
    ZeRO-1 for is the leg this shrinks.  Leaves whose padded size is
    under the quantization floor keep the fp reduce-scatter/all-gather
    pair; that residue is small per leaf (< 4 KiB) and gets an explicit
    quarter-size allowance on the reduce-scatter kind."""
    del n_devices
    leg = int(0.5 * padded_param_bytes)
    return CommBudget(
        name=name,
        allowed={"all-to-all": leg, "all-gather": leg,
                 "reduce-scatter": int(0.25 * padded_param_bytes)},
        ignore_below=1024,
        notes="quantized a2a grad-in + s8 delta all-gather param-out; "
              "fp reduce-scatter residue for sub-floor leaves; "
              "all-reduce still forbidden above the 1 KiB scalar floor",
    )


def hier_dp_budget(param_bytes: int, n_inner: int,
                   name: str = "dp-hier") -> CommBudget:
    """Plain DP under the two-level lowering (tpuframe.parallel.hier,
    arXiv:1909.09756 recipe): the flat grad all-reduce is REPLACED by
    in-slice reduce-scatter(mean) + in-slice all-gather (ICI, full
    bytes) around a cross-slice all-reduce of the 1/``n_inner`` shard —
    the ONLY collective that touches DCN, which is the byte drop this
    budget documents: its ceiling is ``param_bytes / n_inner`` plus a
    half-size fp allowance for sub-floor leaves (they keep the flat
    cross-slice mean — full bytes on DCN, but tiny).  The floor drops to
    1 KiB so the audit sees the shard-sized DCN leg on the tiny audit
    model."""
    return CommBudget(
        name=name,
        allowed={"reduce-scatter": int(1.5 * param_bytes),
                 "all-gather": int(1.5 * param_bytes),
                 "all-reduce": int((1 / n_inner + 0.5) * param_bytes)},
        ignore_below=1024,
        notes="two-level grad mean: in-slice rs+ag (ICI) around a "
              "1/n_inner cross-slice all-reduce (the sole DCN leg); "
              "sub-floor leaves keep the flat cross-slice mean",
    )


def hier_dp_int8_budget(param_bytes: int, n_inner: int,
                        name: str = "dp-hier-int8") -> CommBudget:
    """Plain DP, two-level lowering, int8-block DCN leg: the cross-slice
    mean of the 1/``n_inner`` shard rides the quantized wire (s8 payload
    + f32 block scales over all-to-all + all-gather) while the in-slice
    legs stay fp — the per-fabric composition PERF §20's "int8 loses at
    ICI speeds" verdict calls for.  The all-to-all ceiling is the
    documented DCN-byte crush: ~``param_bytes / (4 * n_inner)`` of s8
    payload with 4x headroom.  Shards under quantwire's size floor fall
    back to a fp cross-slice all-reduce; that residue gets the same
    explicit allowance as :func:`hier_dp_budget`'s."""
    return CommBudget(
        name=name,
        allowed={"reduce-scatter": int(1.5 * param_bytes),
                 "all-gather": int(1.75 * param_bytes),
                 "all-to-all": int(1.0 * param_bytes / n_inner),
                 "all-reduce": int(0.5 * param_bytes)},
        ignore_below=1024,
        notes="two-level grad mean with quantized DCN leg: in-slice "
              "rs+ag fp (ICI), cross-slice s8 a2a+ag on the 1/n_inner "
              "shard (DCN); fp all-reduce residue for sub-floor shards",
    )


def hier_zero1_budget(padded_param_bytes: int, n_inner: int,
                      name: str = "dp-zero1-hier") -> CommBudget:
    """ZeRO-1 under the two-level lowering: the grad reduce-scatter and
    the param all-gather each become a two-stage pair — in-slice over
    ICI at full bytes, cross-slice over DCN at 1/``n_inner`` of them.
    Like :func:`zero1_budget` the ceilings are EXACT, not generous: each
    kind totals ``padded * (1 + 1/n_inner)`` (the in-slice stage's full
    padded bytes plus the cross-slice stage's shard), so the audit
    proves both that the collective swap happened AND that only the
    shard-sized stage is left to cross DCN.  All-reduce stays forbidden
    above the 1 KiB scalar floor."""
    ceiling = int(padded_param_bytes * (1 + 1 / n_inner))
    return CommBudget(
        name=name,
        allowed={"reduce-scatter": ceiling, "all-gather": ceiling},
        ignore_below=1024,
        notes="two-stage rs(mean) in + two-stage ag out, exact "
              "padded*(1+1/n_inner) bytes per kind; only the shard-"
              "sized cross-slice stage rides DCN; all-reduce forbidden "
              "above the 1 KiB scalar floor",
    )


def hier_zero1_int8_budget(padded_param_bytes: int, n_inner: int,
                           name: str = "dp-zero1-hier-int8") -> CommBudget:
    """ZeRO-1, two-level lowering, int8-block DCN leg — the composed
    spec that carries the DCN-crush acceptance: flat ZeRO-1 pays TWO
    full-size DCN collectives per step (rs in, ag out) and this shape
    pays two s8 shard-size ones (quantized cross-slice a2a for the
    grad chunk, quantized cross-slice delta all-gather for the param
    chunk) — ~``1/(4*n_inner)`` of the bytes each way.  In-slice stages
    stay fp at exact bytes (the :func:`hier_zero1_budget` ceilings);
    leaves whose cross-slice chunk is under quantwire's floor keep the
    fp two-stage pair, so the rs/ag ceilings keep the full
    ``padded * (1 + 1/n_inner)`` allowance and the all-to-all ceiling
    prices the quantized grad leg alone."""
    ceiling = int(padded_param_bytes * (1 + 1 / n_inner))
    return CommBudget(
        name=name,
        allowed={"reduce-scatter": ceiling, "all-gather": ceiling,
                 "all-to-all": int(0.5 * padded_param_bytes / n_inner)},
        ignore_below=1024,
        notes="two-stage zero1 with s8 cross-slice legs: fp in-slice "
              "rs/ag + quantized a2a grad-in + quantized delta ag "
              "param-out on the 1/n_inner chunk; fp two-stage residue "
              "for sub-floor chunks",
    )


def serve_decode_budget(param_bytes: int = 0,
                        name: str = "serve-dp-decode") -> CommBudget:
    """Plain-DP serving decode: params replicated, KV slots sharded over
    data — NO collective has any business in the step.  Unlike training
    DP there is no gradient to sync; every byte of cross-replica traffic
    is the partitioner inventing communication a per-token latency
    budget cannot afford, so the allowed set is empty (``param_bytes``
    accepted for the uniform ``strategy_budget`` call shape; a
    zero-collective ceiling does not scale with it)."""
    del param_bytes
    return CommBudget(
        name=name,
        allowed={},
        notes="serving decode is replica-local by construction; any "
              "collective above the scalar floor is a partitioning bug",
    )


def fsdp_budget(param_bytes: int, name: str = "resnet-fsdp") -> CommBudget:
    """ZeRO/FSDP over data x fsdp: params all-gathered before use (fwd +
    bwd re-gather ⇒ ~2x param bytes), grads reduce-scattered (~1x) and
    cross-replica all-reduced over the data axis (~1x).  GSPMD may fold
    some of these into each other; ceilings are per-kind unions."""
    return CommBudget(
        name=name,
        allowed={
            "all-gather": int(3.0 * param_bytes),
            "reduce-scatter": int(2.0 * param_bytes),
            "all-reduce": int(3.0 * param_bytes),
        },
        notes="ZeRO-3 wire pattern (arXiv:2004.13336 weight-update "
              "sharding generalized)",
    )


def tp_budget(param_bytes: int, act_bytes: int, num_layers: int,
              name: str = "lm-tensor-parallel") -> CommBudget:
    """Megatron-style TP: per layer, activation-sized all-reduces (2 fwd
    + 2 bwd) over the model axis, plus the gradient sync over data.
    GSPMD sometimes chooses all-gather+dynamic-slice over an all-reduce
    pair, so activation-sized all-gathers are declared too."""
    act_traffic = int(8.0 * act_bytes * max(num_layers, 1))
    return CommBudget(
        name=name,
        allowed={
            "all-reduce": int(3.0 * param_bytes) + act_traffic,
            "all-gather": int(2.0 * param_bytes) + act_traffic,
            "reduce-scatter": int(2.0 * param_bytes) + act_traffic,
        },
        notes="activation all-reduces per layer + grad sync",
    )


def ring_sp_budget(param_bytes: int, kv_bytes: int, sp_degree: int,
                   name: str = "lm-seq-parallel") -> CommBudget:
    """Ring-attention SP: the KV pair rotates sp-1 hops per attention
    call, forward and backward (plus dq/dkv return traffic) — the only
    collective-permute user among the strategies.  Grad sync rides the
    usual all-reduce."""
    hops = max(sp_degree - 1, 1)
    return CommBudget(
        name=name,
        allowed={
            "collective-permute": int(8.0 * kv_bytes * hops),
            "all-reduce": int(3.0 * param_bytes),
            # shard_map boundary resharding of tiny carries
            "all-gather": int(1.0 * param_bytes),
        },
        notes="ppermute KV ring (fwd+bwd) + grad all-reduce",
    )


def ulysses_sp_budget(param_bytes: int, act_bytes: int,
                      name: str = "lm-seq-ulysses") -> CommBudget:
    """Ulysses SP: all_to_all head<->seq reshards (2 fwd + 2 bwd per
    attention, each moving the activation once) + grad all-reduce."""
    return CommBudget(
        name=name,
        allowed={
            "all-to-all": int(8.0 * act_bytes),
            "all-reduce": int(3.0 * param_bytes),
            "all-gather": int(1.0 * param_bytes),
        },
        notes="all_to_all head resharding + grad all-reduce",
    )


def pp_budget(param_bytes: int, act_bytes: int, n_micro: int,
              name: str = "pipeline-parallel") -> CommBudget:
    """GPipe PP: microbatch activations hop stage-to-stage via
    collective-permute (fwd + bwd per microbatch), block grads sync over
    data; the scan-stacked blocks may be all-gathered for the update."""
    return CommBudget(
        name=name,
        allowed={
            "collective-permute": int(8.0 * act_bytes * max(n_micro, 1)),
            "all-reduce": int(3.0 * param_bytes),
            "all-gather": int(3.0 * param_bytes),
            "reduce-scatter": int(2.0 * param_bytes),
        },
        notes="stage-boundary ppermute + grad sync",
    )


def ep_budget(param_bytes: int, act_bytes: int,
              name: str = "expert-parallel") -> CommBudget:
    """MoE EP: token dispatch/combine across the expert axis (all-to-all
    in the planned program; GSPMD's dense dispatch may lower to
    all-gather + masked compute at CI scale) + grad sync."""
    return CommBudget(
        name=name,
        allowed={
            "all-to-all": int(8.0 * act_bytes),
            "all-gather": int(3.0 * param_bytes) + int(8.0 * act_bytes),
            "reduce-scatter": int(2.0 * param_bytes),
            "all-reduce": int(3.0 * param_bytes) + int(8.0 * act_bytes),
        },
        notes="token dispatch/combine + grad sync",
    )


def adasum_budget(param_bytes: int, n_devices: int,
                  name: str = "dp-adasum") -> CommBudget:
    """DP with the Adasum ppermute XOR butterfly: log2(n) exchange rounds
    each moving the full gradient, instead of one all-reduce."""
    rounds = max((n_devices - 1).bit_length(), 1)
    return CommBudget(
        name=name,
        allowed={
            "collective-permute": int(3.0 * param_bytes * rounds),
            "all-reduce": int(2.0 * param_bytes),
        },
        notes="ppermute butterfly grad combine (hvd.Adasum parity)",
    )


def strategy_budget(strategy: str, **sizes) -> CommBudget:
    """Budget for a MULTICHIP strategy name from program-derived sizes."""
    builders = {
        "dp": dp_budget,
        "dp-int8": dp_int8_budget,
        "dp-zero1": zero1_budget,
        "dp-zero1-int8": zero1_int8_budget,
        "serve-dp-decode": serve_decode_budget,
        "resnet-fsdp": fsdp_budget,
        "lm-seq-parallel": ring_sp_budget,
        "lm-seq-ulysses": ulysses_sp_budget,
        "lm-tensor-parallel": tp_budget,
        "pipeline-parallel": pp_budget,
        "expert-parallel": ep_budget,
        "dp-adasum": adasum_budget,
    }
    if strategy not in builders:
        raise ValueError(f"no declared budget for strategy {strategy!r}; "
                         f"have {sorted(builders)}")
    return builders[strategy](**sizes)


# ---------------------------------------------------------------------------
# Known capability exclusions the budgets must cite instead of papering
# over (DESIGN.md invariant 2: no silent fallbacks at capability
# boundaries).  Each entry is checkable against the gate that causes it.
# ---------------------------------------------------------------------------

#: Shapes the fused conv+BN backward's VMEM gate excludes by design.
#: First entry: ResNet-50 layer4's downsample (K=1024 -> C=2048): the
#: resident weight block + f32 accumulator alone are K*C*6 B ≈ 12.6 MB,
#: over the 10 MB budget, so that pair keeps the plain-XLA composition
#: (numerics identical; see tpuframe/ops/fused_conv_bn.py and PERF.md
#: §11).  The audit cites this list so "fused BN covers the 1x1 convs"
#: claims stay honest about the one shape it does not.
KNOWN_VMEM_EXCLUSIONS: tuple[dict, ...] = (
    {
        "op": "fused_conv_bn",
        "site": "ResNet-50 layer4 downsample",
        "shape": {"h": 7, "w": 7, "n": 256, "k": 1024, "c": 2048},
        "reason": "K*C*6 = 12.58 MB resident weight+accumulator exceeds "
                  "the 10 MB VMEM budget; pair falls back to the "
                  "byte-identical XLA composition",
    },
)


def check_known_exclusions() -> list[str]:
    """Cross-check every KNOWN_VMEM_EXCLUSIONS entry against the actual
    gate: an entry whose shape became supported (or a gate change that
    silently widened an exclusion) must update this registry + PERF.md."""
    problems = []
    for entry in KNOWN_VMEM_EXCLUSIONS:
        if entry["op"] == "fused_conv_bn":
            from tpuframe.ops import fused_conv_bn

            s = entry["shape"]
            if fused_conv_bn.supported(s["h"], s["w"], s["n"], s["k"],
                                       s["c"]):
                problems.append(
                    f"{entry['site']}: registered as VMEM-excluded but "
                    f"fused_conv_bn.supported({s}) is now True — update "
                    f"KNOWN_VMEM_EXCLUSIONS and PERF.md §11")
    return problems
