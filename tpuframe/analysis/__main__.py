"""``python -m tpuframe.analysis`` — the offline CI gate.

Runs all three analysis layers against the shipped tree and exits
non-zero on any finding:

  1. source lint (TF101-TF106) over ``tpuframe/``;
  2. per-strategy collective budget audits — every strategy step program
     in :mod:`tpuframe.analysis.strategies` is AOT-compiled on a forced
     multi-device CPU backend and its collectives checked against the
     declared :class:`~tpuframe.analysis.budgets.CommBudget`;
  3. registry cross-checks — every
     :data:`~tpuframe.analysis.budgets.KNOWN_VMEM_EXCLUSIONS` entry must
     still be excluded by the gate it cites;
  4. tune self-check — the roofline hardware tables must keep
     reproducing PERF.md §2's recorded anchors, the shipped tuning DB
     (if any) must validate against the schema, and the tuner's own
     flag plumbing must pass TF106 (``tpuframe.tune.check``);
  5. obs self-check — ``python -m tpuframe.obs summarize --selfcheck``
     schema-validates the shipped sample event logs (docs/samples/), so
     an event-schema change that strands existing logs fails CI before
     it ships;
  6. mem self-check — the remat policy registry must apply every preset,
     ``save_named`` must parse (and reject unknown seams), and the
     model/step files must pass the TF108 registry-seam lint
     (``tpuframe.mem.check``);
  7. shardflow — the structural detectors of
     :mod:`tpuframe.analysis.shardflow` (redundant collective pairs,
     wire-dtype, accidental replication, replica-group consistency,
     exposed communication) run over the collective-flow graph of every
     compiled strategy; the auto-derived per-kind budgets are
     drift-checked against the checked-in ``derived_budgets.json``
     (regenerate with ``--emit-budgets``) and the schedule/liveness
     records against ``derived_schedule.json`` (regenerate with
     ``--emit-schedule``);
  8. pspec self-check — the declarative parallelism-spec grammar
     (:mod:`tpuframe.parallel.pspec`) fuzzes its pinned parse/format
     round-trip and rejection tables, and seeds a replica-group
     mismatch against the hierarchical ICI×DCN mesh that the detector
     MUST flag (plus a valid cross-slice twin whose bytes the ICI/DCN
     split must attribute to DCN) — the gate refuses to run blind;
  9. compare selfcheck — the jax-free golden compare pair under
     ``docs/samples/analysis_compare/`` must keep exercising the whole
     ``--compare`` contract (schema keys, rc codes, the schedule
     section), so a report-schema change that strands the differ fails
     CI before it ships;
  10. rollout self-check — the live-rollout controller
      (:mod:`tpuframe.serve.rollout`) replays its full state machine on
      a simulated fleet (drain→swap→readmit ordering, zero loss, zero
      compile misses, all replicas on the target version), runs the
      TF121 swap-seam lint over the tree, checks the rollout event
      registrations and the ``gate_compare`` rc contract, and seeds a
      poisoned canary that MUST auto-roll back naming the failing
      metric — the promotion gate refuses to run blind;
  11. plan self-check — the pinned ``tune plan`` report
     (``perf/results/plan_report_*``) must schema-validate, its ranking
     must re-derive from its own rows with every ranked candidate
     detector-clean, a seeded best/worst cost swap must flip the
     derived ranking (the gate refuses to rank blind), and the three
     pinned PERF verdicts (§18/§20/§23) must re-derive AND hold
     (``tpuframe.tune.plan.check``; version-skew skips itself like
     ``--emit-budgets``).
  12. fusion self-check — the bucketed-fusion pass
     (:mod:`tpuframe.parallel.fusion`) checks its env-knob parse, its
     bucket-census arithmetic (ordered partition, kind-homogeneous,
     byte-cap), seeds an all-exposed but ``declared_overlapped``
     program that ``detect_exposed_comm`` MUST fail (the live gate
     refuses to run blind), and on a multi-device backend pins the
     psum-linearity identity: per-leaf, packed, and staged reductions
     agree to 1e-6.
  13. trace self-check — the request-tracing plane
     (:mod:`tpuframe.obs.tracing`) cross-pins its span schema against
     ``obs/events.py``'s registry, runs the TF123 tracing-seam lint
     over the tree, round-trips a synthetic healthy trace (exactly one
     complete root, verifier-clean), seeds leaked-span / orphan-span /
     TTFT-mismatch positives the verifier MUST flag (the trace gate
     refuses to run blind), reconstructs the golden traced-fleet
     sample (``docs/samples/traced_fleet/``) clean with a resolvable
     p99 exemplar, and checks the SLO sentry's default specs and its
     rc contract (``tpuframe.obs.tracing.check``).
  14. hier self-check — the hierarchical two-level collective seam
     (:mod:`tpuframe.parallel.hier`) validates its mode registry and
     env parsing, pins a seeded flat/two-level HLO pair against the
     ICI/DCN byte split (the two-level lowering MUST move the
     cross-slice term down by n_inner), proves the two-level mean
     equals the flat mean to 1e-6 on a multi-device slice mesh, runs
     the TF124 cross-slice seam lint over the tree, and seeds a
     known-bad raw cross-slice collective the lint MUST flag (the
     seam gate refuses to run blind).

``--json PATH`` writes the whole gate outcome as a schema-pinned report;
``--compare A.json B.json`` diffs two such reports for structural
collective regressions (rc 1 regression / 0 clean / 2 no overlap — the
``obs compare`` contract) without touching jax at all; ``--selfcheck``
runs only legs 9 and 11 plus fusion's jax-free subset (version stamp
aside, no backend).

Strategies this interpreter cannot express (see
:class:`~tpuframe.analysis.strategies.Unavailable`) print as SKIP and do
not fail the gate.

The strategy audits need a multi-device jax backend, so the CLI
re-executes itself in a child process with a scrubbed CPU-only
environment (``JAX_PLATFORMS=cpu``, forced host device count, no TPU
plugin) — the same pattern as the repo's multichip dry run.  Pass
``--lint-only`` to skip the jax-dependent layers entirely (no re-exec,
no jax import).
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys

_CHILD_FLAG = "TPUFRAME_ANALYSIS_CHILD"


def _scrubbed_cpu_env(n_devices: int) -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # sitecustomize only registers the axon PJRT plugin when
    # PALLAS_AXON_POOL_IPS is non-empty.
    env["PALLAS_AXON_POOL_IPS"] = ""
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append(f"--xla_force_host_platform_device_count={n_devices}")
    env["XLA_FLAGS"] = " ".join(flags).strip()
    env["PYTHONUNBUFFERED"] = "1"
    env[_CHILD_FLAG] = "1"
    return env


def _parse(argv):
    ap = argparse.ArgumentParser(
        prog="python -m tpuframe.analysis",
        description="static SPMD/collective analysis (offline CI gate)")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/dirs to lint (default: the tpuframe "
                         "package directory)")
    ap.add_argument("--lint-only", action="store_true",
                    help="run only the AST source lint (no jax)")
    ap.add_argument("--strategy", action="append", default=None,
                    metavar="NAME",
                    help="audit only these strategies (repeatable)")
    ap.add_argument("--devices", type=int, default=8,
                    help="virtual CPU device count for the strategy "
                         "audits (default 8)")
    ap.add_argument("--json", metavar="PATH", default=None,
                    help="also write the gate outcome as a "
                         "machine-readable report (schema-pinned)")
    ap.add_argument("--emit-budgets", action="store_true",
                    help="regenerate tpuframe/analysis/"
                         "derived_budgets.json from the compiled "
                         "strategies (the drift check's declarations)")
    ap.add_argument("--emit-schedule", action="store_true",
                    help="regenerate tpuframe/analysis/"
                         "derived_schedule.json (per-strategy "
                         "liveness/overlap-window records) from the "
                         "compiled strategies")
    ap.add_argument("--selfcheck", action="store_true",
                    help="validate the golden --compare pair and the "
                         "pinned report schema (no jax), then exit")
    ap.add_argument("--compare", nargs=2, metavar=("A", "B"),
                    default=None,
                    help="diff two --json reports for structural "
                         "collective regressions (no jax; rc 1 "
                         "regression, 0 clean, 2 no overlap)")
    ap.add_argument("--bytes-tol", type=float, default=0.10,
                    help="relative per-kind byte tolerance for "
                         "--compare (default 0.10)")
    return ap.parse_args(argv)


def _default_lint_paths() -> list[str]:
    import tpuframe

    return [os.path.dirname(os.path.abspath(tpuframe.__file__))]


def _run_lint(paths) -> list:
    from tpuframe.analysis.source_lint import lint_paths

    findings = lint_paths(paths)
    for f in findings:
        print(f"LINT {f}")
    print(f"[analysis] source lint: {len(findings)} finding(s) over "
          f"{', '.join(map(str, paths))}")
    return findings


def _run_strategies(names, n_devices) -> tuple[int, list]:
    from tpuframe.analysis import strategies

    failures = 0
    audits = strategies.audit_all(n_devices, names)
    for audit in audits:
        print(f"[analysis] {audit}")
        if audit.status == "violation":
            failures += len(audit.violations) or 1
    return failures, audits


def _run_shardflow(audits, n_devices, *, emit: bool,
                   emit_schedule: bool) -> int:
    from tpuframe.analysis import shardflow

    if emit:
        shardflow.emit_derived(audits, n_devices=n_devices)
        print(f"[analysis] wrote {shardflow.DERIVED_BUDGETS_PATH}")
    if emit_schedule:
        shardflow.emit_schedule(audits, n_devices=n_devices)
        print(f"[analysis] wrote {shardflow.DERIVED_SCHEDULE_PATH}")
    problems = shardflow.check(audits, n_devices=n_devices)
    for p in problems:
        print(f"FLOW {p}")
    print(f"[analysis] shardflow: {len(problems)} problem(s) over "
          f"{sum(1 for a in audits if a.compiled is not None)} "
          f"compiled strategy program(s)")
    return len(problems)


def _run_compare(path_a, path_b, bytes_tol) -> int:
    import json

    from tpuframe.analysis import shardflow

    with open(path_a) as f:
        a = json.load(f)
    with open(path_b) as f:
        b = json.load(f)
    rc, lines = shardflow.compare_reports(a, b, bytes_tol=bytes_tol)
    for line in lines:
        print(line)
    return rc


def _write_json(path, audits, lint_findings, n_devices) -> None:
    import json

    from tpuframe.analysis import shardflow

    report = shardflow.build_report(audits, lint_findings=lint_findings,
                                    n_devices=n_devices)
    with open(path, "w") as f:
        json.dump(report, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"[analysis] wrote {path}")


def _run_tune_check() -> int:
    from tpuframe import tune

    problems = tune.check()
    for p in problems:
        print(f"TUNE {p}")
    print(f"[analysis] tune self-check: {len(problems)} problem(s)")
    return len(problems)


def _run_mem_check() -> int:
    from tpuframe import mem

    problems = mem.check()
    for p in problems:
        print(f"MEM {p}")
    print(f"[analysis] mem self-check: {len(problems)} problem(s)")
    return len(problems)


def _run_serve_check() -> int:
    from tpuframe import serve

    problems = serve.check()
    for p in problems:
        print(f"SERVE {p}")
    print(f"[analysis] serve self-check: {len(problems)} problem(s)")
    return len(problems)


def _run_zero1_check() -> int:
    from tpuframe.parallel import zero1

    problems = zero1.check()
    for p in problems:
        print(f"ZERO1 {p}")
    print(f"[analysis] zero1 self-check: {len(problems)} problem(s)")
    return len(problems)


def _run_fusion_check() -> int:
    from tpuframe.parallel import fusion

    problems = fusion.check()
    for p in problems:
        print(f"FUSION {p}")
    print(f"[analysis] fusion self-check: {len(problems)} problem(s)")
    return len(problems)


def _run_fusion_static() -> int:
    # Jax-free subset: env-knob parse, bucket-census arithmetic, the
    # seeded zero-overlap positive against the live exposed-comm gate.
    from tpuframe.parallel import fusion

    problems = fusion.check_static()
    for p in problems:
        print(f"FUSION {p}")
    print(f"[analysis] fusion static self-check: {len(problems)} "
          f"problem(s)")
    return len(problems)


def _run_elastic_check() -> int:
    from tpuframe import elastic

    problems = elastic.check()
    for p in problems:
        print(f"ELASTIC {p}")
    print(f"[analysis] elastic self-check: {len(problems)} problem(s)")
    return len(problems)


def _run_quantwire_check() -> int:
    from tpuframe.parallel import quantwire

    problems = quantwire.check()
    for p in problems:
        print(f"QUANTWIRE {p}")
    print(f"[analysis] quantwire self-check: {len(problems)} problem(s)")
    return len(problems)


def _run_hier_check() -> int:
    from tpuframe.parallel import hier

    problems = hier.check()
    for p in problems:
        print(f"HIER {p}")
    print(f"[analysis] hier self-check: {len(problems)} problem(s)")
    return len(problems)


def _run_pspec_check() -> int:
    from tpuframe.parallel import pspec

    problems = pspec.check()
    for p in problems:
        print(f"PSPEC {p}")
    print(f"[analysis] pspec self-check: {len(problems)} problem(s)")
    return len(problems)


def _run_plan_check() -> int:
    # Jax-light: validates the pinned planner report (schema pin,
    # re-derivable ranking, seeded ranking-drift positive, the three
    # pinned PERF verdicts) — jax is touched only for the version stamp.
    from tpuframe.tune import plan

    problems = plan.check()
    for p in problems:
        print(f"PLAN {p}")
    print(f"[analysis] plan self-check: {len(problems)} problem(s)")
    return len(problems)


def _run_router_check() -> int:
    from tpuframe.serve import router

    problems = router.check()
    for p in problems:
        print(f"ROUTER {p}")
    print(f"[analysis] router self-check: {len(problems)} problem(s)")
    return len(problems)


def _run_rollout_check() -> int:
    from tpuframe.serve import rollout

    problems = rollout.check()
    for p in problems:
        print(f"ROLLOUT {p}")
    print(f"[analysis] rollout self-check: {len(problems)} problem(s)")
    return len(problems)


def _run_trace_check() -> int:
    from tpuframe.obs import tracing

    problems = tracing.check()
    for p in problems:
        print(f"TRACE {p}")
    print(f"[analysis] trace self-check: {len(problems)} problem(s)")
    return len(problems)


def _run_obs_check() -> int:
    # Through the real CLI entry point, not an import — the gate then
    # also catches a broken ``python -m tpuframe.obs`` invocation.
    rc = subprocess.call([sys.executable, "-m", "tpuframe.obs",
                          "summarize", "--selfcheck"])
    if rc:
        print(f"[analysis] obs selfcheck FAILED (rc {rc})")
    return 1 if rc else 0


def _run_flow_selfcheck() -> int:
    # Jax-free: pure JSON over the checked-in golden compare pair.
    from tpuframe.analysis import shardflow

    problems = shardflow.selfcheck()
    for p in problems:
        print(f"SELFCHECK {p}")
    print(f"[analysis] compare selfcheck: {len(problems)} problem(s)")
    return len(problems)


def _run_registry_checks() -> int:
    from tpuframe.analysis.budgets import check_known_exclusions

    problems = check_known_exclusions()
    for p in problems:
        print(f"REGISTRY {p}")
    print(f"[analysis] known-exclusion registry: "
          f"{len(problems)} problem(s)")
    return len(problems)


def main(argv=None) -> int:
    args = _parse(argv if argv is not None else sys.argv[1:])
    lint_paths_arg = args.paths or _default_lint_paths()

    if args.compare:
        # Pure JSON diffing — no jax, no re-exec, usable anywhere.
        return _run_compare(args.compare[0], args.compare[1],
                            args.bytes_tol)

    if args.selfcheck:
        # Also jax-free: golden-pair + schema validation, plus the
        # planner-report pin (version-skew skips itself).
        return 1 if (_run_flow_selfcheck() + _run_plan_check()
                     + _run_fusion_static()) else 0

    if (args.emit_budgets or args.emit_schedule) and args.strategy:
        print("[analysis] --emit-budgets/--emit-schedule regenerate the "
              "whole declaration file and cannot be combined with "
              "--strategy")
        return 2

    if not args.lint_only and os.environ.get(_CHILD_FLAG) != "1":
        # Re-exec with a clean multi-device CPU backend; the child runs
        # this same main() with _CHILD_FLAG set.
        cmd = [sys.executable, "-m", "tpuframe.analysis",
               "--devices", str(args.devices)]
        for s in args.strategy or ():
            cmd += ["--strategy", s]
        if args.json:
            cmd += ["--json", args.json]
        if args.emit_budgets:
            cmd += ["--emit-budgets"]
        if args.emit_schedule:
            cmd += ["--emit-schedule"]
        cmd += args.paths or []
        return subprocess.call(cmd, env=_scrubbed_cpu_env(args.devices))

    lint_findings = _run_lint(lint_paths_arg)
    n_findings = len(lint_findings)
    if not args.lint_only:
        strat_failures, audits = _run_strategies(
            tuple(args.strategy) if args.strategy else None, args.devices)
        n_findings += strat_failures
        n_findings += _run_shardflow(audits, args.devices,
                                     emit=args.emit_budgets,
                                     emit_schedule=args.emit_schedule)
        n_findings += _run_flow_selfcheck()
        n_findings += _run_registry_checks()
        n_findings += _run_tune_check()
        n_findings += _run_mem_check()
        n_findings += _run_serve_check()
        n_findings += _run_router_check()
        n_findings += _run_rollout_check()
        n_findings += _run_zero1_check()
        n_findings += _run_fusion_check()
        n_findings += _run_elastic_check()
        n_findings += _run_quantwire_check()
        n_findings += _run_hier_check()
        n_findings += _run_pspec_check()
        n_findings += _run_plan_check()
        n_findings += _run_trace_check()
        n_findings += _run_obs_check()
        if args.json:
            _write_json(args.json, audits, lint_findings, args.devices)

    if n_findings:
        print(f"[analysis] FAIL: {n_findings} finding(s)")
        return 1
    print("[analysis] clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
