"""Layer 1: the HLO collective auditor.

Promoted from ``perf/_hlo_parse.py`` (which now re-exports from here) and
generalized from all-reduce-only to every collective XLA emits.  Pure
text parsing over compiled-HLO (post-GSPMD, the authoritative view — the
partitioner inserts collectives auto-SPMD programs don't show in their
StableHLO) with a StableHLO fallback for pre-compile lowerings
(shard_map programs carry their collectives explicitly there).

The byte accounting is a per-instruction wire-traffic proxy:

  * sync ops: bytes of the instruction's result (for reduce-scatter the
    result is the scattered shard — the full operand is what crosses the
    wire, so reduce-scatter uses the larger of operand/result when the
    operand types are visible, else the result);
  * ``-start`` ops (the latency-hiding scheduler's async form): the
    result tuple aliases the operand for equal-size kinds (all-reduce,
    collective-permute, all-to-all), so their shapes are halved;
    all-gather-start keeps the largest tuple element (the gathered
    output);
  * ``-done`` ops are skipped — their bytes were counted at the start.

This is a *budget-ceiling* model, not a cost model: it answers "did
GSPMD materialize a collective class/size the strategy never declared",
not "how many microseconds will the wire take".
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

# HLO op name -> canonical kind.  StableHLO spells these with
# underscores; both map to the dashed canonical form.
COLLECTIVE_KINDS = ("all-reduce", "all-gather", "reduce-scatter",
                    "all-to-all", "collective-permute")

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "i8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2, "i16": 2,
    "s32": 4, "u32": 4, "f32": 4, "i32": 4,
    "s64": 8, "u64": 8, "f64": 8, "i64": 8, "c64": 8,
    "c128": 16,
}

_DTYPE_RE = "|".join(sorted(_DTYPE_BYTES, key=len, reverse=True))

# `[ROOT] %name = (types) all-reduce(-start)?(operands), ...` — group(1)
# is the result-type text, group(3) the optional async suffix.  `-done`
# ops fail the `\(` right after the optional suffix and are skipped by
# design.  The ROOT prefix matters when a collective IS a computation's
# root (rare in full step programs, where the root is the result tuple,
# but routine in reduced/seeded modules) — the shardflow census
# cross-check caught this census blind spot.
_HLO_RE = re.compile(
    r"(?:ROOT )?%?[\w.-]+ = (.*?) ("
    + "|".join(COLLECTIVE_KINDS) + r")(-start)?\(")

# StableHLO / MHLO: `stablehlo.all_reduce`, `"stablehlo.all_gather"` ...
# result type parsed from the trailing `-> tensor<...>` (or the tensor
# list of a tuple result).
_STABLEHLO_RE = re.compile(
    r"\b(?:stablehlo|mhlo)\.(" +
    "|".join(k.replace("-", "_") for k in COLLECTIVE_KINDS) + r")\b")

_SHAPE_RE = re.compile(r"(" + _DTYPE_RE + r")\[([0-9,]*)\]")
_TENSOR_RE = re.compile(r"tensor<([0-9x]*?)x?(" + _DTYPE_RE + r")>")
_GROUPS_RE = re.compile(r"replica_groups=\{(\{[0-9,{} ]*\})\}")


def _shape_bytes(dtype: str, dims_txt: str) -> int:
    n = 1
    for d in dims_txt.split(","):
        if d:
            n *= int(d)
    return n * _DTYPE_BYTES[dtype]


@dataclass
class CollectiveOp:
    """One parsed collective instruction."""

    kind: str                      # canonical dashed kind
    bytes: int                     # wire-traffic proxy (see module doc)
    dtype_bytes: dict[str, int]    # per-dtype breakdown of ``bytes``
    shapes: list[str] = field(default_factory=list)  # raw result shapes
    replica_groups: str | None = None
    is_async: bool = False
    line: str = ""                 # the (stripped, truncated) source line

    def __str__(self):
        grp = f" groups={self.replica_groups}" if self.replica_groups else ""
        return (f"{self.kind}{'-start' if self.is_async else ''} "
                f"{self.bytes / 1e6:.3f} MB [{', '.join(self.shapes)}]{grp}")


@dataclass
class CollectiveReport:
    """All collectives of one program, with per-kind aggregates."""

    ops: list[CollectiveOp] = field(default_factory=list)

    @property
    def total_bytes(self) -> int:
        return sum(op.bytes for op in self.ops)

    def bytes_by_kind(self, min_bytes: int = 0) -> dict[str, int]:
        out: dict[str, int] = {}
        for op in self.ops:
            if op.bytes >= min_bytes:
                out[op.kind] = out.get(op.kind, 0) + op.bytes
        return out

    def count_by_kind(self, min_bytes: int = 0) -> dict[str, int]:
        out: dict[str, int] = {}
        for op in self.ops:
            if op.bytes >= min_bytes:
                out[op.kind] = out.get(op.kind, 0) + 1
        return out

    def filter(self, min_bytes: int) -> "CollectiveReport":
        return CollectiveReport(
            [op for op in self.ops if op.bytes >= min_bytes])

    def summary(self) -> str:
        if not self.ops:
            return "no collectives"
        parts = [f"{k}: {n} op(s), {b / 1e6:.3f} MB"
                 for (k, n), b in zip(self.count_by_kind().items(),
                                      self.bytes_by_kind().values())]
        return (f"{len(self.ops)} collective(s), "
                f"{self.total_bytes / 1e6:.3f} MB total — "
                + "; ".join(parts))


def parse_collectives(txt: str) -> CollectiveReport:
    """Parse every collective instruction out of HLO or StableHLO text."""
    ops: list[CollectiveOp] = []
    for raw in txt.splitlines():
        line = raw.strip()
        m = _HLO_RE.match(line)
        if m:
            ops.append(_parse_hlo_op(line, m))
            continue
        sm = _STABLEHLO_RE.search(line)
        if sm:
            op = _parse_stablehlo_op(line, sm)
            if op is not None:
                ops.append(op)
    return CollectiveReport(ops)


def _parse_hlo_op(line: str, m: re.Match) -> CollectiveOp:
    result_txt, kind, start = m.group(1), m.group(2), bool(m.group(3))
    shapes = _SHAPE_RE.findall(result_txt)
    dtype_bytes: dict[str, int] = {}
    per_shape = [(_shape_bytes(dt, dims), dt, dims) for dt, dims in shapes]
    if start and kind == "all-gather" and per_shape:
        # async tuple = (operand, gathered result): keep the output.
        per_shape = [max(per_shape)]
    factor = 0.5 if start and kind != "all-gather" else 1.0
    if kind == "reduce-scatter" and not start:
        # The full operand crosses the wire; prefer it when visible.
        operand_shapes = _SHAPE_RE.findall(line[m.end():])
        if operand_shapes:
            op_sz = [(_shape_bytes(dt, dims), dt, dims)
                     for dt, dims in operand_shapes]
            if sum(s for s, _, _ in op_sz) > sum(s for s, _, _ in per_shape):
                per_shape = op_sz
    for sz, dt, _dims in per_shape:
        dtype_bytes[dt] = dtype_bytes.get(dt, 0) + int(sz * factor)
    gm = _GROUPS_RE.search(line)
    return CollectiveOp(
        kind=kind,
        bytes=sum(dtype_bytes.values()),
        dtype_bytes=dtype_bytes,
        shapes=[f"{dt}[{dims}]" for _, dt, dims in per_shape],
        replica_groups=gm.group(1) if gm else None,
        is_async=start,
        line=line[:200],
    )


def _parse_stablehlo_op(line: str, m: re.Match) -> CollectiveOp | None:
    kind = m.group(1).replace("_", "-")
    # Result types come after `->`; fall back to every tensor type on the
    # line (over-counting is the safe direction for a ceiling check).
    arrow = line.rfind("->")
    tensors = _TENSOR_RE.findall(line[arrow:] if arrow >= 0 else line)
    if not tensors:
        return None
    dtype_bytes: dict[str, int] = {}
    shapes = []
    for dims_txt, dt in tensors:
        n = 1
        for d in dims_txt.split("x"):
            if d:
                n *= int(d)
        dtype_bytes[dt] = dtype_bytes.get(dt, 0) + n * _DTYPE_BYTES[dt]
        shapes.append(f"{dt}[{dims_txt.replace('x', ',')}]")
    gm = re.search(r"replica_groups\s*=\s*dense<([^>]*)>", line)
    return CollectiveOp(
        kind=kind,
        bytes=sum(dtype_bytes.values()),
        dtype_bytes=dtype_bytes,
        shapes=shapes,
        replica_groups=gm.group(1).strip()[:120] if gm else None,
        is_async=False,
        line=line[:200],
    )


def audit_compiled(compiled) -> CollectiveReport:
    """Collective report of an AOT-compiled executable (``jit(f).lower(
    ...).compile()``) — the post-GSPMD, authoritative program text."""
    return parse_collectives(compiled.as_text())


def audit_jitted(jitted, *example_args,
                 compiler_options: dict | None = None
                 ) -> tuple[CollectiveReport, object]:
    """Lower + backend-compile ``jitted`` on its example args (shapes
    only — ``jax.ShapeDtypeStruct`` leaves are fine) and audit the
    optimized HLO.  Returns ``(report, compiled)`` so callers can chain
    donation/memory checks on the same artifact.  ``compiler_options``
    ride the compile request (the TF106-sanctioned per-compile path —
    no XLA_FLAGS mutation)."""
    lowered = jitted.lower(*example_args)
    compiled = (lowered.compile(compiler_options=compiler_options)
                if compiler_options else lowered.compile())
    return audit_compiled(compiled), compiled


# ---------------------------------------------------------------------------
# Legacy surface (perf/_hlo_parse.py promotion): kept verbatim so the
# perf scripts and their recorded results keep meaning the same thing.
# ---------------------------------------------------------------------------


def allreduce_payload(txt: str):
    """Sum all-reduce payload bytes from optimized-HLO text.

    Returns ``({"bf16": bytes, "f32": bytes}, op_count)``.  Handles
    XLA's variadic tuple all-reduces; an ``all-reduce-start``'s result
    tuple aliases the operand (shapes appear twice — the form the
    latency-hiding scheduler emits), so those instructions are halved.
    """
    payload = {"bf16": 0.0, "f32": 0.0}
    ops = 0
    for op in parse_collectives(txt).ops:
        if op.kind != "all-reduce":
            continue
        for dt in ("bf16", "f32"):
            payload[dt] += op.dtype_bytes.get(dt, 0)
        ops += 1
    return payload, ops
