"""Layer 1.5: the typed collective-flow graph of a compiled program.

``hlo_audit`` answers *how many bytes* each collective class moves — a
flat census, enough for the budget ceilings.  This module answers the
*structural* questions the censuses cannot: which value feeds which
collective, whether two all-reduces sit on one def, whether a parameter
stayed at its full (replicated) shape under a sharding strategy.  It
parses the optimized HLO text (``compiled.as_text()``, post-GSPMD — the
authoritative program) into typed :class:`Node`/:class:`Computation`
objects with def-use edges, replica groups, shapes and dtypes, and the
detectors in :mod:`tpuframe.analysis.shardflow` run over the result.

Same contract as ``hlo_audit``: pure text parsing, stdlib only (perf
scripts import it through ``perf/_hlo_parse.py`` before their env-guard
re-exec, when initializing jax would pin the wrong backend).  The parser
is deliberately tolerant — an instruction it cannot classify still lands
in the graph as an opaque node with its def-use edges intact, so a new
XLA opcode degrades coverage, never correctness of the edges.

Byte accounting here is *result bytes* (what the instruction defines),
not the wire-traffic proxy — budget derivation stays on
``hlo_audit.parse_collectives`` so the derived budgets and the audit
ceilings are measured by the same ruler; the graph cross-checks the
census by collective *count*, where the two parsers must agree exactly.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

try:
    # When perf/_hlo_parse.py loads this module by file path (its
    # side-effect-free contract), hlo_audit is already registered under
    # this name and importing the tpuframe package (jax!) must not run.
    from _hlo_parse_impl import COLLECTIVE_KINDS, _DTYPE_BYTES
except ImportError:
    from tpuframe.analysis.hlo_audit import COLLECTIVE_KINDS, _DTYPE_BYTES

# `%comp_name (args...) -> result {` — ENTRY marks the top computation.
_COMP_RE = re.compile(
    r"^(ENTRY\s+)?%?([\w.$-]+)\s*\(.*\)\s*->\s*.+\{\s*$")

# `[ROOT] %name = <result-type> opcode(` — lazy result-type match stops
# at the first lowercase word directly followed by '(' (the opcode; type
# text never has that shape).
_INSTR_RE = re.compile(
    r"^\s*(ROOT\s+)?%?([\w.-]+)\s*=\s*(.+?)\s*([a-z][a-z0-9-]*)\(")

_SHAPE_RE = re.compile(
    r"(" + "|".join(sorted(_DTYPE_BYTES, key=len, reverse=True))
    + r")\[([0-9,]*)\]")

_OPERAND_RE = re.compile(r"%([\w.-]+)")
_CHANNEL_RE = re.compile(r"channel_id=(\d+)")
_GROUPS_RE = re.compile(r"replica_groups=\{((?:\{[0-9, ]*\},?)*)\}")
_GROUPS_IOTA_RE = re.compile(r"replica_groups=\[(\d+),(\d+)\]<=\[")
_PAIRS_RE = re.compile(r"source_target_pairs=\{((?:\{[0-9, ]*\},?)*)\}")
_CALLED_RE = re.compile(
    r"(?:to_apply|calls|body|condition|true_computation|"
    r"false_computation)=%?([\w.$-]+)")
_SHARDING_RE = re.compile(r"sharding=\{")

#: opcodes that forward their operand's value unchanged (or reshaped) —
#: def-use chains for the redundancy detectors look *through* these.
PASSTHROUGH_OPS = frozenset({
    "copy", "bitcast", "reshape", "transpose", "get-tuple-element",
    "optimization-barrier", "all-reduce-done", "all-gather-done",
    "reduce-scatter-done", "collective-permute-done", "all-to-all-done",
})

_COLLECTIVE_OPS = {}
for _k in COLLECTIVE_KINDS:
    _COLLECTIVE_OPS[_k] = _k
    _COLLECTIVE_OPS[_k + "-start"] = _k


def _span_paren(line: str, start: int) -> int:
    """Index just past the ')' matching the '(' at ``start``."""
    depth = 0
    for i in range(start, len(line)):
        c = line[i]
        if c == "(":
            depth += 1
        elif c == ")":
            depth -= 1
            if depth == 0:
                return i + 1
    return len(line)


def _parse_groups(txt: str) -> tuple[tuple[int, ...], ...]:
    groups = []
    for body in re.findall(r"\{([0-9, ]*)\}", txt):
        groups.append(tuple(int(x) for x in body.replace(" ", "").split(",")
                            if x))
    return tuple(g for g in groups if g)


@dataclass
class Node:
    """One HLO instruction: a def, its shape/dtype, and its uses."""

    name: str                       # instruction name, '%' stripped
    op: str                         # raw opcode ("all-reduce-start", "dot")
    kind: str | None                # canonical collective kind, else None
    is_root: bool = False
    is_async_start: bool = False
    shapes: tuple[tuple[str, tuple[int, ...]], ...] = ()  # (dtype, dims)
    operands: tuple[str, ...] = ()  # operand instruction names (in order)
    called: tuple[str, ...] = ()    # called computation names
    replica_groups: tuple[tuple[int, ...], ...] | None = None
    iota_groups: tuple[int, int] | None = None   # (count, size) iota form
    source_target_pairs: tuple[tuple[int, ...], ...] | None = None
    channel_id: int | None = None
    sharded: bool = False           # carries a sharding={...} annotation
    line_no: int = 0
    line: str = ""                  # stripped, truncated source line

    @property
    def result_bytes(self) -> int:
        total = 0
        for dt, dims in self.shapes:
            n = 1
            for d in dims:
                n *= d
            total += n * _DTYPE_BYTES[dt]
        return total

    @property
    def dtypes(self) -> frozenset:
        return frozenset(dt for dt, _ in self.shapes)

    def __str__(self):
        shp = ", ".join(f"{dt}[{','.join(map(str, dims))}]"
                        for dt, dims in self.shapes)
        return f"{self.op} %{self.name} = {shp}"


@dataclass
class Computation:
    """One HLO computation: an ordered def list plus the use index."""

    name: str
    is_entry: bool = False
    nodes: dict[str, Node] = field(default_factory=dict)
    root: str | None = None

    def users_of(self) -> dict[str, list[str]]:
        """operand name -> names of nodes that consume it (def-use)."""
        users: dict[str, list[str]] = {}
        for node in self.nodes.values():
            for op_name in node.operands:
                users.setdefault(op_name, []).append(node.name)
        return users

    def resolve_value(self, name: str) -> str:
        """Chase ``name`` back through pass-through ops to the def that
        actually produces the value (bounded by graph size — cycles are
        impossible in HLO SSA)."""
        seen = set()
        while name in self.nodes and name not in seen:
            seen.add(name)
            node = self.nodes[name]
            if node.op in PASSTHROUGH_OPS and node.operands:
                name = node.operands[0]
                continue
            break
        return name

    def parameters(self) -> list[Node]:
        return [n for n in self.nodes.values() if n.op == "parameter"]

    def collectives(self) -> list[Node]:
        return [n for n in self.nodes.values() if n.kind is not None]


@dataclass
class CollectiveGraph:
    """The whole module: computations by name, entry singled out."""

    computations: dict[str, Computation] = field(default_factory=dict)
    entry: str | None = None

    @property
    def entry_computation(self) -> Computation | None:
        return self.computations.get(self.entry) if self.entry else None

    def all_nodes(self):
        for comp in self.computations.values():
            yield from comp.nodes.values()

    def collectives(self) -> list[tuple[Computation, Node]]:
        """Every collective node, paired with its computation (collectives
        inside while/fusion bodies count — a scan-based pipeline keeps its
        ppermutes in the loop body computation)."""
        out = []
        for comp in self.computations.values():
            for node in comp.collectives():
                out.append((comp, node))
        return out

    def count_by_kind(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for _, node in self.collectives():
            out[node.kind] = out.get(node.kind, 0) + 1
        return out

    def summary(self) -> dict:
        """Graph-shape census (what the golden fixtures pin)."""
        return {
            "computations": len(self.computations),
            "nodes": sum(len(c.nodes) for c in self.computations.values()),
            "entry_parameters": len(
                self.entry_computation.parameters())
            if self.entry_computation else 0,
            "collectives_by_kind": dict(sorted(
                self.count_by_kind().items())),
        }


def _parse_instruction(line: str, line_no: int,
                       m: re.Match) -> Node:
    is_root, name, result_txt, op = (bool(m.group(1)), m.group(2),
                                     m.group(3), m.group(4))
    open_paren = m.end() - 1
    close = _span_paren(line, open_paren)
    args_txt = line[open_paren + 1:close - 1]
    attrs_txt = line[close:]
    shapes = tuple((dt, tuple(int(d) for d in dims.split(",") if d))
                   for dt, dims in _SHAPE_RE.findall(result_txt))
    gm = _GROUPS_RE.search(attrs_txt)
    im = _GROUPS_IOTA_RE.search(attrs_txt)
    pm = _PAIRS_RE.search(attrs_txt)
    cm = _CHANNEL_RE.search(attrs_txt)
    return Node(
        name=name,
        op=op,
        kind=_COLLECTIVE_OPS.get(op),
        is_root=is_root,
        is_async_start=op.endswith("-start"),
        shapes=shapes,
        operands=tuple(_OPERAND_RE.findall(args_txt)),
        called=tuple(_CALLED_RE.findall(attrs_txt)),
        replica_groups=_parse_groups(gm.group(1)) if gm else None,
        iota_groups=(int(im.group(1)), int(im.group(2))) if im else None,
        source_target_pairs=_parse_groups(pm.group(1)) if pm else None,
        channel_id=int(cm.group(1)) if cm else None,
        sharded=bool(_SHARDING_RE.search(attrs_txt)),
        line_no=line_no,
        line=line.strip()[:200],
    )


def parse_graph(txt: str) -> CollectiveGraph:
    """Parse optimized-HLO module text into a :class:`CollectiveGraph`."""
    graph = CollectiveGraph()
    current: Computation | None = None
    for line_no, raw in enumerate(txt.splitlines(), start=1):
        stripped = raw.strip()
        if current is None:
            cm = _COMP_RE.match(stripped)
            if cm:
                current = Computation(name=cm.group(2),
                                      is_entry=bool(cm.group(1)))
            continue
        if stripped == "}":
            graph.computations[current.name] = current
            if current.is_entry:
                graph.entry = current.name
            current = None
            continue
        im = _INSTR_RE.match(raw)
        if im:
            node = _parse_instruction(raw, line_no, im)
            current.nodes[node.name] = node
            if node.is_root:
                current.root = node.name
    # a torn tail (no closing brace) still lands in the graph
    if current is not None:
        graph.computations[current.name] = current
        if current.is_entry:
            graph.entry = current.name
    return graph


def graph_of_compiled(compiled) -> CollectiveGraph:
    """Graph of an AOT-compiled executable (``jit(f).lower(...).compile()``)."""
    return parse_graph(compiled.as_text())
