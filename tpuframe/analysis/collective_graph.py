"""Layer 1.5: the typed collective-flow graph of a compiled program.

``hlo_audit`` answers *how many bytes* each collective class moves — a
flat census, enough for the budget ceilings.  This module answers the
*structural* questions the censuses cannot: which value feeds which
collective, whether two all-reduces sit on one def, whether a parameter
stayed at its full (replicated) shape under a sharding strategy.  It
parses the optimized HLO text (``compiled.as_text()``, post-GSPMD — the
authoritative program) into typed :class:`Node`/:class:`Computation`
objects with def-use edges, replica groups, shapes and dtypes, and the
detectors in :mod:`tpuframe.analysis.shardflow` run over the result.

Same contract as ``hlo_audit``: pure text parsing, stdlib only (perf
scripts import it through ``perf/_hlo_parse.py`` before their env-guard
re-exec, when initializing jax would pin the wrong backend).  The parser
is deliberately tolerant — an instruction it cannot classify still lands
in the graph as an opaque node with its def-use edges intact, so a new
XLA opcode degrades coverage, never correctness of the edges.

Byte accounting here is *result bytes* (what the instruction defines),
not the wire-traffic proxy — budget derivation stays on
``hlo_audit.parse_collectives`` so the derived budgets and the audit
ceilings are measured by the same ruler; the graph cross-checks the
census by collective *count*, where the two parsers must agree exactly.

Analysis v3 adds the *schedule* view on top of the def-use view.  The
optimized HLO the strategies audit is post-scheduling text
(``is_scheduled=true`` in the module header), so a computation's
instruction order IS the linear schedule the backend will execute.  From
that order this module derives, per computation:

  * async collective ``-start``/``-done`` pairing
    (:func:`Computation.pair_async`), chased through intervening
    ``copy``/``bitcast``/``get-tuple-element`` chains — an unpaired
    start is a *parser* problem surfaced loudly, never skipped;
  * per-collective overlap windows (:func:`schedule_view`): the ops
    scheduled inside each start→done window (actually overlapped), and
    the set of compute ops *legally interleavable* with the collective —
    ops that are neither ancestors nor descendants of it in the def-use
    graph, i.e. what a fusion/overlap pass could move into the window;
  * a buffer-liveness peak-HBM estimate (:func:`liveness`): each
    value's buffer is live from its def to its last use in schedule
    order (the root escapes to the end), aliasing ops own no bytes, and
    donated entry parameters (``input_output_alias`` in the module
    header) are recognized so an UN-donated input whose full-shape
    update coexists with it — doubled residency — is flagged.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

try:
    # When perf/_hlo_parse.py loads this module by file path (its
    # side-effect-free contract), hlo_audit is already registered under
    # this name and importing the tpuframe package (jax!) must not run.
    from _hlo_parse_impl import COLLECTIVE_KINDS, _DTYPE_BYTES
except ImportError:
    from tpuframe.analysis.hlo_audit import COLLECTIVE_KINDS, _DTYPE_BYTES

# `%comp_name (args...) -> result {` — ENTRY marks the top computation.
_COMP_RE = re.compile(
    r"^(ENTRY\s+)?%?([\w.$-]+)\s*\(.*\)\s*->\s*.+\{\s*$")

# `[ROOT] %name = <result-type> opcode(` — lazy result-type match stops
# at the first lowercase word directly followed by '(' (the opcode; type
# text never has that shape).
_INSTR_RE = re.compile(
    r"^\s*(ROOT\s+)?%?([\w.-]+)\s*=\s*(.+?)\s*([a-z][a-z0-9-]*)\(")

_SHAPE_RE = re.compile(
    r"(" + "|".join(sorted(_DTYPE_BYTES, key=len, reverse=True))
    + r")\[([0-9,]*)\]")

_OPERAND_RE = re.compile(r"%([\w.-]+)")
_CHANNEL_RE = re.compile(r"channel_id=(\d+)")
_GROUPS_RE = re.compile(r"replica_groups=\{((?:\{[0-9, ]*\},?)*)\}")
_GROUPS_IOTA_RE = re.compile(
    r"replica_groups=\[(\d+),(\d+)\]<=\[([0-9,]+)\](?:T\(([0-9,]+)\))?")
_PAIRS_RE = re.compile(r"source_target_pairs=\{((?:\{[0-9, ]*\},?)*)\}")
_CALLED_RE = re.compile(
    r"(?:to_apply|calls|body|condition|true_computation|"
    r"false_computation)=%?([\w.$-]+)")
_SHARDING_RE = re.compile(r"sharding=\{")

#: opcodes that forward their operand's value unchanged (or reshaped) —
#: def-use chains for the redundancy detectors look *through* these.
PASSTHROUGH_OPS = frozenset({
    "copy", "bitcast", "reshape", "transpose", "get-tuple-element",
    "optimization-barrier", "all-reduce-done", "all-gather-done",
    "reduce-scatter-done", "collective-permute-done", "all-to-all-done",
})

#: ops the async ``-done`` chase looks through when pairing a done with
#: its ``-start`` — the compiler routinely threads the in-flight token
#: through copies/bitcasts/tuple plumbing between the two.
ASYNC_CHASE_OPS = frozenset({
    "copy", "bitcast", "get-tuple-element", "optimization-barrier",
})

#: opcodes whose result aliases (a slice of) an operand buffer — they
#: own zero bytes in the liveness model.  ``tuple`` is composite (its
#: components own their own buffers); the ``-done`` of an async
#: collective returns the buffer the ``-start`` already allocated.
LIVENESS_ALIAS_OPS = frozenset({
    "bitcast", "get-tuple-element", "tuple", "optimization-barrier",
    "all-reduce-done", "all-gather-done", "reduce-scatter-done",
    "collective-permute-done", "all-to-all-done",
})

#: opcodes that represent real device compute for the overlap windows —
#: what a scheduler can actually hide a collective behind.  Post-fusion
#: HLO packs nearly all element-wise work into ``fusion`` ops; ``while``
#: covers scan bodies, ``custom-call`` covers pallas kernels.
COMPUTE_OPS = frozenset({
    "dot", "convolution", "fusion", "custom-call", "while",
    "conditional", "reduce", "reduce-window", "scatter", "gather",
    "sort", "select-and-scatter", "triangular-solve", "cholesky",
})

_ALIAS_ENTRY_RE = re.compile(r"\(\s*(\d+)\s*,")

_COLLECTIVE_OPS = {}
for _k in COLLECTIVE_KINDS:
    _COLLECTIVE_OPS[_k] = _k
    _COLLECTIVE_OPS[_k + "-start"] = _k


def _span_paren(line: str, start: int) -> int:
    """Index just past the ')' matching the '(' at ``start``."""
    depth = 0
    for i in range(start, len(line)):
        c = line[i]
        if c == "(":
            depth += 1
        elif c == ")":
            depth -= 1
            if depth == 0:
                return i + 1
    return len(line)


def _parse_alias_params(line: str) -> frozenset:
    """Parameter numbers the module aliases to an output (donation), from
    the ``input_output_alias={ {0}: (0, {}, may-alias), ... }`` table in
    the HloModule header line.  Each entry's tuple leads with the
    parameter number; nesting is bounded so a brace scan finds the block
    end."""
    key = "input_output_alias="
    at = line.find(key)
    if at < 0:
        return frozenset()
    tail = line[at + len(key):]
    depth = 0
    end = 0
    for i, ch in enumerate(tail):
        if ch == "{":
            depth += 1
        elif ch == "}":
            depth -= 1
            if depth == 0:
                end = i
                break
    return frozenset(int(m.group(1))
                     for m in _ALIAS_ENTRY_RE.finditer(tail[:end + 1]))


def _parse_groups(txt: str) -> tuple[tuple[int, ...], ...]:
    groups = []
    for body in re.findall(r"\{([0-9, ]*)\}", txt):
        groups.append(tuple(int(x) for x in body.replace(" ", "").split(",")
                            if x))
    return tuple(g for g in groups if g)


def materialized_groups(node, n_devices: int
                        ) -> tuple[tuple[int, ...], ...] | None:
    """Explicit device groups for a collective node, whatever textual
    form its ``replica_groups`` took.

    Explicit groups pass through; the iota form
    ``[count,size]<=[dims]T(perm)`` materializes as
    ``transpose(reshape(arange(prod(dims)), dims), perm).flatten()``
    chunked into ``count`` rows of ``size`` (HLO's
    IotaReplicaGroupList semantics — the ``T(...)`` variant yields
    strided groups, so it cannot be skipped); absent/empty groups mean
    one group of all ``n_devices``.  Returns ``None`` when the iota
    spec is inconsistent — callers treat that as unattributable."""
    if node.replica_groups:
        return node.replica_groups
    if node.iota_groups is None:
        return (tuple(range(n_devices)),)
    count, size = node.iota_groups
    dims = node.iota_reshape or (count * size,)
    total = 1
    for d in dims:
        total *= d
    if total != count * size:
        return None
    perm = node.iota_transpose or tuple(range(len(dims)))
    if sorted(perm) != list(range(len(dims))):
        return None
    # Row-major strides of the reshape, read through the transpose.
    strides = [1] * len(dims)
    for i in range(len(dims) - 2, -1, -1):
        strides[i] = strides[i + 1] * dims[i + 1]
    t_dims = [dims[p] for p in perm]
    t_strides = [strides[p] for p in perm]
    flat: list[int] = []
    idx = [0] * len(t_dims)
    for _ in range(total):
        flat.append(sum(i * s for i, s in zip(idx, t_strides)))
        for ax in range(len(t_dims) - 1, -1, -1):
            idx[ax] += 1
            if idx[ax] < t_dims[ax]:
                break
            idx[ax] = 0
    return tuple(tuple(flat[g * size:(g + 1) * size])
                 for g in range(count))


@dataclass
class Node:
    """One HLO instruction: a def, its shape/dtype, and its uses."""

    name: str                       # instruction name, '%' stripped
    op: str                         # raw opcode ("all-reduce-start", "dot")
    kind: str | None                # canonical collective kind, else None
    is_root: bool = False
    is_async_start: bool = False
    shapes: tuple[tuple[str, tuple[int, ...]], ...] = ()  # (dtype, dims)
    operands: tuple[str, ...] = ()  # operand instruction names (in order)
    called: tuple[str, ...] = ()    # called computation names
    replica_groups: tuple[tuple[int, ...], ...] | None = None
    iota_groups: tuple[int, int] | None = None   # (count, size) iota form
    #: the iota form's reshape dims and transpose permutation
    #: (``[c,s]<=[d0,d1]T(1,0)``) — needed to materialize strided groups.
    iota_reshape: tuple[int, ...] | None = None
    iota_transpose: tuple[int, ...] | None = None
    source_target_pairs: tuple[tuple[int, ...], ...] | None = None
    channel_id: int | None = None
    sharded: bool = False           # carries a sharding={...} annotation
    param_number: int | None = None  # parameter(N) ordinal, else None
    line_no: int = 0
    line: str = ""                  # stripped, truncated source line

    @property
    def result_bytes(self) -> int:
        total = 0
        for dt, dims in self.shapes:
            n = 1
            for d in dims:
                n *= d
            total += n * _DTYPE_BYTES[dt]
        return total

    @property
    def dtypes(self) -> frozenset:
        return frozenset(dt for dt, _ in self.shapes)

    def __str__(self):
        shp = ", ".join(f"{dt}[{','.join(map(str, dims))}]"
                        for dt, dims in self.shapes)
        return f"{self.op} %{self.name} = {shp}"


@dataclass
class Computation:
    """One HLO computation: an ordered def list plus the use index."""

    name: str
    is_entry: bool = False
    nodes: dict[str, Node] = field(default_factory=dict)
    root: str | None = None

    def users_of(self) -> dict[str, list[str]]:
        """operand name -> names of nodes that consume it (def-use)."""
        users: dict[str, list[str]] = {}
        for node in self.nodes.values():
            for op_name in node.operands:
                users.setdefault(op_name, []).append(node.name)
        return users

    def resolve_value(self, name: str) -> str:
        """Chase ``name`` back through pass-through ops to the def that
        actually produces the value (bounded by graph size — cycles are
        impossible in HLO SSA)."""
        seen = set()
        while name in self.nodes and name not in seen:
            seen.add(name)
            node = self.nodes[name]
            if node.op in PASSTHROUGH_OPS and node.operands:
                name = node.operands[0]
                continue
            break
        return name

    def parameters(self) -> list[Node]:
        return [n for n in self.nodes.values() if n.op == "parameter"]

    def collectives(self) -> list[Node]:
        return [n for n in self.nodes.values() if n.kind is not None]

    def schedule_order(self) -> dict[str, int]:
        """name -> linear schedule position.  The parsed node order is
        the printed instruction order, which for post-scheduling HLO
        (``is_scheduled=true``) is the sequence the backend executes."""
        return {name: i for i, name in enumerate(self.nodes)}

    def pair_async(self) -> tuple[dict[str, str], list[str]]:
        """Pair every async collective ``-start`` with its ``-done``.

        The chase walks each ``-done``'s operand chain back through
        ``copy``/``bitcast``/``get-tuple-element`` (and optimization
        barriers) to the start that produced the in-flight token — the
        compiler routinely threads plumbing ops between the two, and
        pairing only direct operands silently drops those windows.
        Returns ``(start_name -> done_name, problems)``; an unpaired
        start (or a start claimed by two dones) is a problem string the
        census check fails loudly on, never a silent skip."""
        pairs: dict[str, str] = {}
        problems: list[str] = []
        starts = {n.name for n in self.nodes.values()
                  if n.kind is not None and n.is_async_start}
        for node in self.nodes.values():
            if not node.op.endswith("-done") or not node.operands:
                continue
            name = node.operands[0]
            seen: set = set()
            while name in self.nodes and name not in seen:
                seen.add(name)
                src = self.nodes[name]
                if src.name in starts:
                    break
                if src.op in ASYNC_CHASE_OPS and src.operands:
                    name = src.operands[0]
                    continue
                break
            if name in starts:
                if name in pairs:
                    problems.append(
                        f"async start %{name} in %{self.name} is consumed "
                        f"by two -done ops (%{pairs[name]}, %{node.name}) "
                        f"— the pairing chase is confused")
                else:
                    pairs[name] = node.name
        for sname in sorted(starts - set(pairs)):
            problems.append(
                f"unpaired async start %{sname} in %{self.name}: no "
                f"matching -done found through the copy/bitcast/"
                f"get-tuple-element chase — the schedule auditor would "
                f"run blind on this collective")
        return pairs, problems

    def dependency_cone(self, name: str, *, forward: bool,
                        users: dict[str, list[str]] | None = None) -> set:
        """Transitive descendants (``forward=True``) or ancestors of a
        node within this computation, excluding the node itself."""
        if users is None:
            users = self.users_of()
        cone: set = set()
        frontier = [name]
        while frontier:
            cur = frontier.pop()
            nxt = (users.get(cur, []) if forward
                   else list(self.nodes[cur].operands)
                   if cur in self.nodes else [])
            for other in nxt:
                if other not in cone and other in self.nodes:
                    cone.add(other)
                    frontier.append(other)
        return cone


@dataclass
class CollectiveGraph:
    """The whole module: computations by name, entry singled out."""

    computations: dict[str, Computation] = field(default_factory=dict)
    entry: str | None = None
    #: entry parameter numbers donated to an output
    #: (``input_output_alias`` in the HloModule header)
    aliased_params: frozenset = frozenset()

    @property
    def entry_computation(self) -> Computation | None:
        return self.computations.get(self.entry) if self.entry else None

    def all_nodes(self):
        for comp in self.computations.values():
            yield from comp.nodes.values()

    def collectives(self) -> list[tuple[Computation, Node]]:
        """Every collective node, paired with its computation (collectives
        inside while/fusion bodies count — a scan-based pipeline keeps its
        ppermutes in the loop body computation)."""
        out = []
        for comp in self.computations.values():
            for node in comp.collectives():
                out.append((comp, node))
        return out

    def count_by_kind(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for _, node in self.collectives():
            out[node.kind] = out.get(node.kind, 0) + 1
        return out

    def summary(self) -> dict:
        """Graph-shape census (what the golden fixtures pin)."""
        return {
            "computations": len(self.computations),
            "nodes": sum(len(c.nodes) for c in self.computations.values()),
            "entry_parameters": len(
                self.entry_computation.parameters())
            if self.entry_computation else 0,
            "collectives_by_kind": dict(sorted(
                self.count_by_kind().items())),
        }


@dataclass
class CollectiveWindow:
    """One collective's slot in a computation's linear schedule.

    ``start_pos``/``done_pos`` are schedule positions; for a sync
    collective they coincide (the op blocks — zero window).  ``exposed``
    means no compute op is scheduled inside the start→done window, i.e.
    every microsecond of that transfer is on the critical path.  The
    ``interleavable_*`` fields count compute ops that are neither
    ancestors nor descendants of the collective in the def-use graph —
    work a scheduling/fusion pass could legally move into the window."""

    name: str                     # the start (or sync) instruction name
    kind: str                     # canonical collective kind
    bytes: int                    # result bytes of the collective node
    is_async: bool
    start_pos: int
    done_pos: int
    done_name: str | None
    overlapped_compute: int       # compute ops actually in the window
    overlapped_bytes: int
    interleavable_compute: int    # compute ops legally movable into it
    interleavable_bytes: int
    exposed: bool

    @property
    def window_len(self) -> int:
        """Def-use distance start→done in schedule positions."""
        return self.done_pos - self.start_pos


@dataclass
class ScheduleView:
    """All collective windows of one computation, schedule-ordered."""

    computation: str
    n_positions: int
    windows: tuple[CollectiveWindow, ...] = ()
    problems: tuple[str, ...] = ()   # unpaired/double-paired async starts


def schedule_view(comp: Computation) -> ScheduleView:
    """Derive the per-collective overlap windows of ``comp``.

    Sync collectives (no ``-start``/``-done`` split) block the schedule
    by construction: zero window, exposed by definition.  Async starts
    get the ops scheduled strictly between start and done (the actually
    overlapped set) and the legally interleavable compute set via the
    dependency cones — both restricted to :data:`COMPUTE_OPS`, since
    hiding a transfer behind a ``bitcast`` hides nothing."""
    order = comp.schedule_order()
    users = comp.users_of()
    pairs, problems = comp.pair_async()
    node_at = list(comp.nodes.values())
    windows = []
    for node in comp.collectives():
        if node.is_async_start and node.name not in pairs:
            continue   # already surfaced as an unpaired-start problem
        start_pos = order[node.name]
        done_name = pairs.get(node.name)
        done_pos = order[done_name] if done_name else start_pos
        over_n = over_b = 0
        for pos in range(start_pos + 1, done_pos):
            inside = node_at[pos]
            if inside.op in COMPUTE_OPS:
                over_n += 1
                over_b += inside.result_bytes
        # Everything data-dependent on the start (the -done and its
        # consumers included) or feeding it cannot move into the window;
        # the rest of the computation's compute ops can.
        blocked = comp.dependency_cone(node.name, forward=True,
                                       users=users)
        blocked |= comp.dependency_cone(node.name, forward=False,
                                        users=users)
        blocked.add(node.name)
        inter_n = inter_b = 0
        for other in comp.nodes.values():
            if other.op in COMPUTE_OPS and other.name not in blocked:
                inter_n += 1
                inter_b += other.result_bytes
        windows.append(CollectiveWindow(
            name=node.name,
            kind=node.kind,
            bytes=node.result_bytes,
            is_async=node.is_async_start,
            start_pos=start_pos,
            done_pos=done_pos,
            done_name=done_name,
            overlapped_compute=over_n,
            overlapped_bytes=over_b,
            interleavable_compute=inter_n,
            interleavable_bytes=inter_b,
            exposed=(over_n == 0),
        ))
    windows.sort(key=lambda w: w.start_pos)
    return ScheduleView(computation=comp.name,
                        n_positions=len(comp.nodes),
                        windows=tuple(windows),
                        problems=tuple(problems))


@dataclass
class LivenessReport:
    """Schedule-order buffer-liveness estimate for one computation.

    A buffer is live from its def to its last use (through aliasing
    ops); the root's buffers escape to the caller and live to the end.
    ``peak_bytes`` is the sweep-line maximum of concurrently live bytes
    — a static *lower bound* on peak HBM (no padding, no workspace),
    but one whose drift tracks real residency changes.  ``undonated``
    lists large entry parameters that are not in the module's
    ``input_output_alias`` table yet shape-match a root output
    component: the classic donate-forgotten input whose full-shape
    update coexists with it, doubling residency."""

    computation: str
    peak_bytes: int
    peak_pos: int
    total_defined_bytes: int
    undonated: tuple[str, ...] = ()


def liveness(comp: Computation,
             aliased_params: frozenset = frozenset(),
             *, undonated_floor: int = 1 << 20) -> LivenessReport:
    """Sweep-line peak-live-bytes over ``comp``'s linear schedule."""
    order = comp.schedule_order()
    n = len(comp.nodes)

    # Buffer ownership: aliasing ops own zero bytes and forward liveness
    # to the buffers of the value(s) they view.  ``tuple`` fans out to
    # every component, so a use of the tuple keeps all of them alive.
    roots_cache: dict[str, tuple] = {}

    def roots(name: str) -> tuple:
        if name not in comp.nodes:
            return ()
        cached = roots_cache.get(name)
        if cached is not None:
            return cached
        roots_cache[name] = ()   # SSA has no cycles; guard regardless
        node = comp.nodes[name]
        if node.op in LIVENESS_ALIAS_OPS and node.operands:
            if node.op == "tuple":
                out: dict[str, None] = {}
                for op_name in node.operands:
                    for r in roots(op_name):
                        out[r] = None
                result = tuple(out)
            else:
                result = roots(node.operands[0])
        else:
            result = (name,)
        roots_cache[name] = result
        return result

    last_use: dict[str, int] = {}
    for node in comp.nodes.values():
        pos = order[node.name]
        for op_name in node.operands:
            for owner in roots(op_name):
                if last_use.get(owner, -1) < pos:
                    last_use[owner] = pos
    if comp.root is not None:
        for owner in roots(comp.root):
            last_use[owner] = n   # escapes to the caller
    # paired async starts stay live through their -done even when the
    # done is the only (chased) consumer recorded above
    pairs, _ = comp.pair_async()
    for start, done in pairs.items():
        for owner in roots(start):
            if last_use.get(owner, -1) < order[done]:
                last_use[owner] = order[done]

    events: dict[int, int] = {}
    total = 0
    for name, node in comp.nodes.items():
        if roots(name) != (name,):
            continue   # alias view — owns nothing
        nbytes = node.result_bytes
        if not nbytes:
            continue
        total += nbytes
        start = order[name]
        end = last_use.get(name, start)
        events[start] = events.get(start, 0) + nbytes
        events[end + 1] = events.get(end + 1, 0) - nbytes
    live = peak = 0
    peak_pos = 0
    for pos in sorted(events):
        live += events[pos]
        if live > peak:
            peak = live
            peak_pos = pos

    # Donation flag: large un-donated entry parameters whose exact
    # (dtype, dims) recurs in the root output — full-shape update and
    # original coexist at the peak.
    undonated: list[str] = []
    if comp.is_entry and comp.root is not None:
        out_shapes = set()
        root_node = comp.nodes.get(comp.root)
        if root_node is not None:
            sources = (root_node.operands if root_node.op == "tuple"
                       else (comp.root,))
            for src in sources:
                src_node = comp.nodes.get(src)
                if src_node is not None:
                    out_shapes.update(src_node.shapes)
        for param in comp.parameters():
            if (param.param_number is not None
                    and param.param_number not in aliased_params
                    and param.result_bytes >= undonated_floor
                    and any(s in out_shapes for s in param.shapes)):
                undonated.append(param.name)

    return LivenessReport(
        computation=comp.name,
        peak_bytes=peak,
        peak_pos=peak_pos,
        total_defined_bytes=total,
        undonated=tuple(sorted(undonated)),
    )


def _parse_instruction(line: str, line_no: int,
                       m: re.Match) -> Node:
    is_root, name, result_txt, op = (bool(m.group(1)), m.group(2),
                                     m.group(3), m.group(4))
    open_paren = m.end() - 1
    close = _span_paren(line, open_paren)
    args_txt = line[open_paren + 1:close - 1]
    attrs_txt = line[close:]
    shapes = tuple((dt, tuple(int(d) for d in dims.split(",") if d))
                   for dt, dims in _SHAPE_RE.findall(result_txt))
    gm = _GROUPS_RE.search(attrs_txt)
    im = _GROUPS_IOTA_RE.search(attrs_txt)
    pm = _PAIRS_RE.search(attrs_txt)
    cm = _CHANNEL_RE.search(attrs_txt)
    return Node(
        name=name,
        op=op,
        kind=_COLLECTIVE_OPS.get(op),
        is_root=is_root,
        is_async_start=op.endswith("-start"),
        shapes=shapes,
        operands=tuple(_OPERAND_RE.findall(args_txt)),
        called=tuple(_CALLED_RE.findall(attrs_txt)),
        replica_groups=_parse_groups(gm.group(1)) if gm else None,
        iota_groups=(int(im.group(1)), int(im.group(2))) if im else None,
        iota_reshape=(tuple(int(d) for d in im.group(3).split(","))
                      if im else None),
        iota_transpose=(tuple(int(d) for d in im.group(4).split(","))
                        if im and im.group(4) else None),
        source_target_pairs=_parse_groups(pm.group(1)) if pm else None,
        channel_id=int(cm.group(1)) if cm else None,
        sharded=bool(_SHARDING_RE.search(attrs_txt)),
        param_number=(int(args_txt) if op == "parameter"
                      and args_txt.strip().isdigit() else None),
        line_no=line_no,
        line=line.strip()[:200],
    )


def parse_graph(txt: str) -> CollectiveGraph:
    """Parse optimized-HLO module text into a :class:`CollectiveGraph`."""
    graph = CollectiveGraph()
    current: Computation | None = None
    for line_no, raw in enumerate(txt.splitlines(), start=1):
        stripped = raw.strip()
        if current is None:
            if stripped.startswith("HloModule"):
                graph.aliased_params |= _parse_alias_params(stripped)
                continue
            cm = _COMP_RE.match(stripped)
            if cm:
                current = Computation(name=cm.group(2),
                                      is_entry=bool(cm.group(1)))
            continue
        if stripped == "}":
            graph.computations[current.name] = current
            if current.is_entry:
                graph.entry = current.name
            current = None
            continue
        im = _INSTR_RE.match(raw)
        if im:
            node = _parse_instruction(raw, line_no, im)
            current.nodes[node.name] = node
            if node.is_root:
                current.root = node.name
    # a torn tail (no closing brace) still lands in the graph
    if current is not None:
        graph.computations[current.name] = current
        if current.is_entry:
            graph.entry = current.name
    return graph


def graph_of_compiled(compiled) -> CollectiveGraph:
    """Graph of an AOT-compiled executable (``jit(f).lower(...).compile()``)."""
    return parse_graph(compiled.as_text())
