"""ResNet-18 / ResNet-50 — configs 2, 3 and 5 (SURVEY.md §1, [B:8][B:9][B:11]).

The reference takes these from torchvision; this is a from-scratch flax
implementation of the same architectures (He et al. 2015, v1.5 downsampling
like torchvision: stride-2 on the 3x3 of a bottleneck, not the 1x1).

TPU-native choices:
  - NHWC layout (XLA:TPU's native conv layout; torchvision is NCHW).
  - ``dtype`` controls compute precision (bf16 for MXU throughput); params
    and BatchNorm statistics stay float32.
  - A CIFAR stem (3x3/stride-1, no maxpool) for config 2's ResNet-18/CIFAR-10
    and the standard 7x7/stride-2+maxpool ImageNet stem for ResNet-50.
  - BatchNorm running stats live in the ``batch_stats`` collection; the
    train step cross-replica-averages them (tpuframe.parallel.step), which
    replaces the reference's per-GPU local stats + rank-0 checkpointing.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from tpuframe import mem

ModuleDef = Callable[..., nn.Module]


class BasicBlock(nn.Module):
    """2x 3x3 — ResNet-18/34 block."""

    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), (self.strides, self.strides))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)  # zero-init last BN
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1),
                                 (self.strides, self.strides),
                                 name="downsample_conv")(residual)
            residual = self.norm(name="downsample_bn")(residual)
        return nn.relu(residual + y)


class Bottleneck(nn.Module):
    """1x1 → 3x3(stride) → 1x1(4x) — ResNet-50/101/152 block (v1.5).

    With ``fused`` set (the ``bn="fused"`` model option), every 1x1
    conv+BN pair — conv1, conv3 and the downsample, which carry the
    block's LARGE-channel tensors — goes through
    :class:`tpuframe.ops.fused_conv_bn.FusedConvBN`, whose pallas
    backward keeps the BN input-cotangent out of HBM (PERF.md §6.3: the
    backward's touch count is the byte lever).  The 3x3 stays on the XLA
    path.
    """

    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef
    fused: ModuleDef | None = None

    @nn.compact
    def __call__(self, x):
        residual = x
        if self.fused is not None:
            y = self.fused(self.filters)(x)
        else:
            y = self.conv(self.filters, (1, 1))(x)
            y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), (self.strides, self.strides))(y)
        y = self.norm()(y)
        y = nn.relu(y)
        if self.fused is not None:
            y = self.fused(self.filters * 4,
                           scale_init=nn.initializers.zeros)(y)
        else:
            y = self.conv(self.filters * 4, (1, 1))(y)
            y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            if self.fused is not None:
                residual = self.fused(self.filters * 4, strides=self.strides,
                                      name="downsample_fused")(residual)
            else:
                residual = self.conv(self.filters * 4, (1, 1),
                                     (self.strides, self.strides),
                                     name="downsample_conv")(residual)
                residual = self.norm(name="downsample_bn")(residual)
        return nn.relu(residual + y)


def space_to_depth(x: jax.Array, block: int = 2) -> jax.Array:
    """[B, H, W, C] → [B, H/b, W/b, b*b*C]; channel order (row-off, col-off,
    C) to match :func:`s2d_stem_kernel`'s weight layout."""
    b, h, w, c = x.shape
    x = x.reshape(b, h // block, block, w // block, block, c)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(
        b, h // block, w // block, block * block * c)


def s2d_stem_kernel(w7: jax.Array) -> jax.Array:
    """Rearrange a [7,7,C,O] stride-2 stem kernel into the equivalent
    [4,4,4C,O] kernel for the space-to-depth stem (pad to 8×8 at the end,
    split even/odd taps into the depth dim).  With flax SAME padding on
    224 input (pad (2,3)) the s2d conv needs padding ((1,2),(1,2)); the two
    formulations then compute bit-identical outputs (tests/test_models.py)."""
    c, o = w7.shape[2], w7.shape[3]
    w8 = jnp.pad(w7, ((0, 1), (0, 1), (0, 0), (0, 0)))
    #  [8,8,C,O] → [4,p=2,4,q=2,C,O] → [4,4,(p,q,C),O]
    w8 = w8.reshape(4, 2, 4, 2, c, o).transpose(0, 2, 1, 3, 4, 5)
    return w8.reshape(4, 4, 4 * c, o)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    width: int = 64
    cifar_stem: bool = False
    dtype: jnp.dtype = jnp.float32
    # "conv" = classic 7x7/stride-2; "space_to_depth" = the MXU-friendly
    # reformulation (4x4/stride-1 on 12-channel 112x112 input — a 3-channel
    # stride-2 conv wastes the systolic array's reduction dim; this is the
    # MLPerf-style recipe, exactly function-preserving per s2d_stem_kernel).
    stem: str = "conv"
    # Rematerialize each residual block in the backward pass: only block
    # boundaries are saved forward; intra-block activations are recomputed.
    # Module-level remat (mem.remat_module) — the pre-registry lever.
    # New code should prefer the loss-seam policies (tpuframe.mem / the
    # step factories' remat_policy=, searched via `python -m
    # tpuframe.tune sweep --remat`), which leave the param tree alone.
    remat: bool = False
    # "flax" = nn.BatchNorm; "folded" = FoldedBatchNorm, whose
    # activation-sized normalize math runs in the compute dtype instead of
    # f32 (the offline HLO census found 74% of activation-sized values in
    # f32 from the flax BN chain — PERF.md §7).  "fused" = the 1x1
    # conv+BN pairs in Bottleneck blocks use FusedConvBN's pallas
    # backward (ops/fused_conv_bn.py), removing the BN input-cotangent's
    # HBM write + two re-reads — the byte-floor lever (PERF.md §6.3);
    # Bottleneck-only.  NOTE: flax auto-naming keys modules by class
    # (BatchNorm_N vs FoldedBatchNorm_N vs FusedConvBN_N), so toggling
    # re-keys the param tree — pick per run, like `remat`.
    bn: str = "flax"

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype,
                       kernel_init=nn.initializers.variance_scaling(
                           2.0, "fan_out", "normal"))
        fused = None
        if self.bn == "folded":
            from tpuframe.models.folded_bn import FoldedBatchNorm

            norm = partial(FoldedBatchNorm, use_running_average=not train,
                           momentum=0.9, epsilon=1e-5, dtype=self.dtype,
                           param_dtype=jnp.float32)
        elif self.bn in ("flax", "fused"):
            norm = partial(nn.BatchNorm, use_running_average=not train,
                           momentum=0.9, epsilon=1e-5, dtype=self.dtype,
                           param_dtype=jnp.float32)
            if self.bn == "fused":
                if self.block_cls is not Bottleneck:
                    raise ValueError(
                        "bn='fused' targets the Bottleneck 1x1 convs; "
                        "BasicBlock models have no 1x1 compute convs")
                from tpuframe.ops.fused_conv_bn import FusedConvBN

                fused = partial(FusedConvBN,
                                use_running_average=not train,
                                momentum=0.9, epsilon=1e-5,
                                dtype=self.dtype, param_dtype=jnp.float32,
                                kernel_init=nn.initializers.
                                variance_scaling(2.0, "fan_out", "normal"))
        else:
            raise ValueError(f"unknown bn {self.bn!r}; "
                             f"expected 'flax', 'folded' or 'fused'")

        if self.stem not in ("conv", "space_to_depth"):
            raise ValueError(f"unknown stem {self.stem!r}; "
                             f"expected 'conv' or 'space_to_depth'")
        x = x.astype(self.dtype)
        if self.cifar_stem:
            x = conv(self.width, (3, 3), name="stem_conv")(x)
        elif self.stem == "space_to_depth":
            x = space_to_depth(x, 2)
            x = conv(self.width, (4, 4), padding=((1, 2), (1, 2)),
                     name="stem_conv")(x)
        else:
            x = conv(self.width, (7, 7), (2, 2), name="stem_conv")(x)
        x = norm(name="stem_bn")(x)
        x = nn.relu(x)
        if not self.cifar_stem:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        # Named checkpoint seams: identity unless a per_block/save_named
        # remat policy (tpuframe.mem) elects to save exactly these.
        x = mem.seam(x, "stem_out")

        block_cls = mem.remat_module(self.block_cls) if self.remat \
            else self.block_cls
        # Explicit names matching flax's auto-naming of the UNwrapped class:
        # nn.remat renames modules ("CheckpointBottleneck_0"), which would
        # silently re-key the param tree and orphan existing checkpoints
        # whenever remat is toggled.
        block_idx = 0
        for i, n_blocks in enumerate(self.stage_sizes):
            for j in range(n_blocks):
                strides = 2 if i > 0 and j == 0 else 1
                kw = {"fused": fused} if fused is not None else {}
                x = block_cls(self.width * 2 ** i, strides, conv, norm,
                              name=f"{self.block_cls.__name__}_{block_idx}",
                              **kw)(x)
                x = mem.seam(x, "block_out")
                block_idx += 1

        x = jnp.mean(x, axis=(1, 2))  # global average pool
        x = nn.Dense(self.num_classes, dtype=self.dtype,
                     kernel_init=nn.initializers.normal(0.01))(x)
        return x.astype(jnp.float32)


def ResNet18(num_classes: int = 10, *, cifar_stem: bool = True,
             dtype: jnp.dtype = jnp.float32, remat: bool = False,
             bn: str = "flax") -> ResNet:
    """Config 2 default: ResNet-18 with the CIFAR stem ([B:8])."""
    return ResNet(stage_sizes=(2, 2, 2, 2), block_cls=BasicBlock,
                  num_classes=num_classes, cifar_stem=cifar_stem, dtype=dtype,
                  remat=remat, bn=bn)


def ResNet50(num_classes: int = 1000, *, cifar_stem: bool = False,
             dtype: jnp.dtype = jnp.float32, stem: str = "conv",
             remat: bool = False, bn: str = "flax") -> ResNet:
    """Configs 3/5: ResNet-50 v1.5 for ImageNet ([B:9][B:11])."""
    return ResNet(stage_sizes=(3, 4, 6, 3), block_cls=Bottleneck,
                  num_classes=num_classes, cifar_stem=cifar_stem, dtype=dtype,
                  stem=stem, remat=remat, bn=bn)


def ResNet101(num_classes: int = 1000, *, cifar_stem: bool = False,
              dtype: jnp.dtype = jnp.float32, stem: str = "conv",
              remat: bool = False, bn: str = "flax") -> ResNet:
    """torchvision-parity depth variant (same v1.5 bottleneck family the
    reference pulls from torchvision; SURVEY.md §3a)."""
    return ResNet(stage_sizes=(3, 4, 23, 3), block_cls=Bottleneck,
                  num_classes=num_classes, cifar_stem=cifar_stem, dtype=dtype,
                  stem=stem, remat=remat, bn=bn)


def ResNet152(num_classes: int = 1000, *, cifar_stem: bool = False,
              dtype: jnp.dtype = jnp.float32, stem: str = "conv",
              remat: bool = False, bn: str = "flax") -> ResNet:
    """torchvision-parity depth variant (see ResNet101)."""
    return ResNet(stage_sizes=(3, 8, 36, 3), block_cls=Bottleneck,
                  num_classes=num_classes, cifar_stem=cifar_stem, dtype=dtype,
                  stem=stem, remat=remat, bn=bn)


def ResNet34(num_classes: int = 1000, *, cifar_stem: bool = False,
             dtype: jnp.dtype = jnp.float32, remat: bool = False,
             bn: str = "flax") -> ResNet:
    """torchvision-parity depth variant of the BasicBlock family."""
    return ResNet(stage_sizes=(3, 4, 6, 3), block_cls=BasicBlock,
                  num_classes=num_classes, cifar_stem=cifar_stem, dtype=dtype,
                  remat=remat, bn=bn)
