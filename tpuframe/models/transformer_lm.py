"""Decoder-only causal transformer LM — the long-context workload.

Beyond the reference's capability bar (its longest sequence is BERT-base
GLUE at 512 tokens — SURVEY.md §5.7): this model exists to exercise the
framework's first-class long-context path.  Architecture is the standard
modern decoder: pre-LN, RoPE, GELU MLP, untied LM head, bf16-compute capable.

Sequence parallelism is a *model config*, not a code fork: with
``seq_mode="ring"`` or ``"ulysses"`` the attention core runs the
sequence-parallel kernels from :mod:`tpuframe.ops.seq_parallel` over the
mesh's ``seq`` axis, and RoPE positions are offset by the device's global
chunk position (``lax.axis_index``).  Outside shard_map (or with the seq
axis unbound / size 1) the same model falls back to full attention — the
laptop-to-pod property the framework keeps everywhere.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from tpuframe import mem


@dataclass(frozen=True)
class LMConfig:
    vocab_size: int = 32000
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_seq: int = 8192
    dropout: float = 0.0
    rope_theta: float = 10000.0
    dtype: str = "float32"          # "bfloat16" for MXU throughput
    attn_impl: str | None = None    # None → TPUFRAME_ATTN_IMPL env / xla
    seq_axis: str = "seq"
    seq_mode: str = "none"          # none | ring | ulysses
    remat: bool = False             # jax.checkpoint each block (long-context)
    # Mixture of experts (expert parallelism over the ``expert`` mesh axis;
    # weights placed by tpuframe.parallel.tp rules). 0 experts = dense.
    moe_experts: int = 0
    moe_every: int = 2              # every Nth block swaps MLP for MoE
    moe_k: int = 2                  # experts per token
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01    # load-balance loss weight (harness adds)

    @property
    def jnp_dtype(self):
        return jnp.dtype(self.dtype)

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @classmethod
    def tiny(cls, **kw) -> "LMConfig":
        base = dict(vocab_size=512, hidden_size=64, num_layers=2,
                    num_heads=4, intermediate_size=128, max_seq=512)
        base.update(kw)
        return cls(**base)


def _seq_axis_bound(name: str) -> bool:
    try:
        lax.axis_size(name)
    except NameError:
        return False
    return True


def rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary position embedding. x: [B, S, N, D]; positions: [S] global,
    or [B, S] per-sequence (the decode path: each batch slot sits at its
    own absolute position in its own sequence)."""
    d = x.shape[-1]
    freqs = theta ** (-jnp.arange(0, d, 2, dtype=jnp.float32) / d)  # [D/2]
    angles = positions[..., None].astype(jnp.float32) * freqs  # [..., S, D/2]
    if angles.ndim == 2:
        angles = angles[None]  # shared positions -> broadcast batch dim
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = x[..., 0::2], x[..., 1::2]
    xf1, xf2 = x1.astype(jnp.float32), x2.astype(jnp.float32)
    out = jnp.stack([xf1 * cos - xf2 * sin, xf1 * sin + xf2 * cos], axis=-1)
    return out.reshape(x.shape).astype(x.dtype)


class CausalSelfAttention(nn.Module):
    cfg: LMConfig

    @nn.compact
    def __call__(self, x, positions, *, train: bool, kv_cache=None,
                 cache_length=None, decode: bool = False):
        from tpuframe.ops import attention as attn_ops
        from tpuframe.ops import seq_parallel

        c = self.cfg
        dense = lambda name: nn.DenseGeneral(  # noqa: E731
            (c.num_heads, c.head_dim), use_bias=False, dtype=c.jnp_dtype,
            name=name)
        q = rope(dense("query")(x), positions, c.rope_theta)
        k = rope(dense("key")(x), positions, c.rope_theta)
        v = dense("value")(x)

        if kv_cache is not None:
            # Serving path (tpuframe.serve): the cache stores post-RoPE
            # keys, so a wrapped ring slot keeps its original absolute
            # position and wraparound degrades to sliding-window
            # attention rather than silent position corruption.
            k_cache, v_cache = kv_cache
            cap = k_cache.shape[1]
            if decode:
                # Ring write: one new token per sequence at its own
                # write index (modulo capacity), then query-length-1
                # attention over the valid prefix.
                idx = (cache_length % cap).astype(jnp.int32)

                def _write(cache, vec, i):
                    return lax.dynamic_update_slice(cache, vec, (i, 0, 0))

                k_cache = jax.vmap(_write)(k_cache, k, idx)
                v_cache = jax.vmap(_write)(v_cache, v, idx)
                valid = jnp.minimum(cache_length + 1, cap)
                y = attn_ops.decode_attention(q, k_cache, v_cache,
                                              lengths=valid,
                                              impl=c.attn_impl)
            else:
                # Prefill: identical math to the training forward
                # (causal attention over the left-aligned prompt) plus
                # the cache write at [0:S] — golden-logits parity with
                # the training path is by construction, not by test
                # luck (the test still checks it).
                s = x.shape[1]
                if s > cap:
                    raise ValueError(f"prompt bucket {s} exceeds "
                                     f"KV-cache capacity {cap}")
                k_cache = lax.dynamic_update_slice(
                    k_cache, k.astype(k_cache.dtype), (0, 0, 0, 0))
                v_cache = lax.dynamic_update_slice(
                    v_cache, v.astype(v_cache.dtype), (0, 0, 0, 0))
                y = attn_ops.multihead_attention(q, k, v, causal=True,
                                                 impl=c.attn_impl)
            out = nn.DenseGeneral(c.hidden_size, axis=(-2, -1),
                                  use_bias=False, dtype=c.jnp_dtype,
                                  name="out")(y)
            return out, (k_cache, v_cache)

        mode = c.seq_mode
        if mode != "none" and not _seq_axis_bound(c.seq_axis):
            mode = "none"  # unmapped run of a seq-parallel config
        if mode == "ring":
            y = seq_parallel.ring_attention(q, k, v, axis=c.seq_axis,
                                            causal=True, impl=c.attn_impl)
        elif mode == "ulysses":
            y = seq_parallel.ulysses_attention(q, k, v, axis=c.seq_axis,
                                               causal=True, impl=c.attn_impl)
        elif mode == "none":
            y = attn_ops.multihead_attention(q, k, v, causal=True,
                                             impl=c.attn_impl)
        else:
            raise ValueError(f"unknown seq_mode {c.seq_mode!r}")
        return nn.DenseGeneral(c.hidden_size, axis=(-2, -1), use_bias=False,
                               dtype=c.jnp_dtype, name="out")(y)


class MoEMLP(nn.Module):
    """Top-k routed expert FFN (tpuframe.ops.moe). Dropped-token residual
    semantics: overflow tokens pass through with zero MLP contribution."""

    cfg: LMConfig

    @nn.compact
    def __call__(self, x):
        from tpuframe.ops import moe as moe_ops

        c = self.cfg
        b, s, h = x.shape
        e, inter = c.moe_experts, c.intermediate_size
        tokens = x.reshape(b * s, h)
        gate_logits = nn.Dense(e, use_bias=False, name="router")(
            tokens.astype(jnp.float32))
        cap = moe_ops.capacity_for(b * s, e, c.moe_k, c.moe_capacity_factor)
        dispatch, combine, aux = moe_ops.route_topk(gate_logits, k=c.moe_k,
                                                    capacity=cap)
        self.sow("aux_loss", "load_balance", aux)

        up = self.param("up_experts", nn.initializers.lecun_normal(),
                        (e, h, inter))
        down = self.param("down_experts", nn.initializers.lecun_normal(),
                          (e, inter, h))
        dtype = c.jnp_dtype
        expert_in = jnp.einsum("tec,th->ech", dispatch.astype(dtype),
                               tokens.astype(dtype))
        hmid = nn.gelu(jnp.einsum("ech,ehi->eci", expert_in,
                                  up.astype(dtype)))
        expert_out = jnp.einsum("eci,eih->ech", hmid, down.astype(dtype))
        y = jnp.einsum("tec,ech->th", combine.astype(dtype), expert_out)
        return y.reshape(b, s, h)


class Block(nn.Module):
    cfg: LMConfig
    train: bool = False  # attribute (not call arg) so nn.remat sees only arrays
    use_moe: bool = False

    @nn.compact
    def __call__(self, x, positions, *, kv_cache=None, cache_length=None,
                 decode: bool = False):
        c = self.cfg
        train = self.train
        h = nn.LayerNorm(use_bias=False, name="attn_ln")(x)
        new_cache = None
        if kv_cache is not None:
            h, new_cache = CausalSelfAttention(c, name="attn")(
                h, positions, train=train, kv_cache=kv_cache,
                cache_length=cache_length, decode=decode)
        else:
            h = CausalSelfAttention(c, name="attn")(h, positions,
                                                    train=train)
        h = nn.Dropout(c.dropout, deterministic=not train)(h)
        x = x + h
        h = nn.LayerNorm(use_bias=False, name="mlp_ln")(x)
        if self.use_moe:
            h = MoEMLP(c, name="moe")(h)
        else:
            h = nn.Dense(c.intermediate_size, use_bias=False,
                         dtype=c.jnp_dtype, name="up")(h)
            h = nn.gelu(h)
            h = nn.Dense(c.hidden_size, use_bias=False, dtype=c.jnp_dtype,
                         name="down")(h)
        h = nn.Dropout(c.dropout, deterministic=not train)(h)
        x = x + h
        if kv_cache is not None:
            return x, new_cache
        return x


class ScanBlockLM(nn.Module):
    """TransformerLM variant with the block stack as ONE ``nn.scan`` — the
    layer-stacked parameterization pipeline parallelism shards.

    Params: ``blocks`` holds every Block's weights stacked on a leading
    layer dim ``[L, ...]`` (also O(1) compile time in depth — the scan-over-
    layers idiom).  Three apply modes through the one compact method:

      * default: full forward — embed → scan(L blocks) → final_ln → head;
      * ``stage=True``: ONLY the block stack, with however many layers the
        passed ``blocks`` param slice carries (shard_map slices the leading
        dim over ``pipe``, so each stage runs its own L/S contiguous
        layers) — the ``stage_fn`` for tpuframe.parallel.pp.pipeline_apply;
      * ``embed_only=True`` / ``head_only=True``: the replicated ends,
        computed on every stage (cheap vs the blocks; keeps the SPMD
        program identical everywhere).

    MoE and sequence-parallel attention are not composed with this variant
    (``seq_mode="none"``, ``moe_experts=0`` enforced); use TransformerLM
    for those.
    """

    cfg: LMConfig = field(default_factory=LMConfig)

    @nn.compact
    def __call__(self, inputs, *, train: bool = False, stage: bool = False,
                 stage_layers: int | None = None,
                 embed_only: bool = False, head_only: bool = False,
                 hidden_only: bool = False):
        c = self.cfg
        if c.seq_mode != "none" or c.moe_experts > 0:
            raise ValueError("ScanBlockLM composes with pipeline parallelism"
                             " only; seq_mode must be 'none' and moe off")

        def block_stack(x, n_layers):
            positions = jnp.arange(x.shape[1])
            target = mem.remat_module(_ScanBlock) if c.remat \
                else _ScanBlock
            Scanned = nn.scan(
                target,
                variable_axes={"params": 0},
                split_rngs={"params": True, "dropout": True},
                length=n_layers,
            )
            (x, _), _ = Scanned(c, train, name="blocks")((x, positions), None)
            return x

        if stage:
            # inputs: hidden states [B, S, H]; the caller says how many of
            # the stacked layers its ``blocks`` param slice carries.
            if stage_layers is None:
                raise ValueError("stage=True requires stage_layers")
            return block_stack(inputs, stage_layers)
        if head_only:
            x = nn.LayerNorm(use_bias=False, name="final_ln")(inputs)
            if hidden_only:
                # normed hidden states for the chunked fused loss
                # (tpuframe.ops.fused_xent) — lm_head applied there.
                return x
            logits = nn.Dense(c.vocab_size, use_bias=False, name="lm_head")(x)
            return logits.astype(jnp.float32)

        x = nn.Embed(c.vocab_size, c.hidden_size, name="embed")(inputs)
        x = x.astype(c.jnp_dtype)
        if embed_only:
            return x
        x = block_stack(x, c.num_layers)
        x = nn.LayerNorm(use_bias=False, name="final_ln")(x)
        if hidden_only:
            # honor standalone hidden_only like TransformerLM does — the
            # harness's fused-xent loss path calls it without head_only
            # (transformer-lm-pp run on a non-pp mesh).
            return x
        logits = nn.Dense(c.vocab_size, use_bias=False, name="lm_head")(x)
        return logits.astype(jnp.float32)


class _ScanBlock(nn.Module):
    """``Block`` wrapped for ``nn.scan``: carry = (hidden, positions).
    Delegates to the one Block implementation so the dense architecture
    cannot drift between the looped and the scanned/pipelined variants."""

    cfg: LMConfig
    train: bool = False

    @nn.compact
    def __call__(self, carry, _):
        x, positions = carry
        y = Block(self.cfg, self.train, name="block")(x, positions)
        y = mem.seam(y, "block_out")
        return (y, positions), None


class TransformerLM(nn.Module):
    """input_ids [B, S_local] → logits [B, S_local, V] (f32)."""

    cfg: LMConfig = field(default_factory=LMConfig)

    @nn.compact
    def __call__(self, input_ids, *, train: bool = False,
                 hidden_only: bool = False, kv_cache=None,
                 cache_length=None, decode: bool = False):
        """``hidden_only=True`` returns the post-final-LayerNorm hidden
        states ``[B, S, H]`` instead of logits — the input the chunked
        fused cross-entropy (tpuframe.ops.fused_xent) consumes together
        with the ``lm_head`` kernel, so the ``[B, S, V]`` logits never
        materialize in HBM.  init() must run with the default full path so
        the lm_head parameters exist.

        Serving path (tpuframe.serve): ``kv_cache`` is a per-layer tuple
        of ``(k, v)`` pairs, each ``[B, capacity, N, D]``; ``cache_length``
        ``[B]`` counts tokens already cached.  ``decode=False`` prefills a
        left-aligned (padded) prompt — same math as the training forward —
        writing every layer's K/V; ``decode=True`` runs ONE new token per
        sequence through the query-length-1 attention entry
        (ops.attention.decode_attention) at its own ring write index.
        Returns ``(logits, new_kv_cache)``.  Sequence parallelism and MoE
        do not compose with the cache path (serving shards over batch)."""
        c = self.cfg
        s_local = input_ids.shape[-1]
        if kv_cache is not None:
            if c.seq_mode != "none" or c.moe_experts > 0:
                raise ValueError("the KV-cache path serves dense batch-"
                                 "parallel configs only; seq_mode must be"
                                 " 'none' and moe off")
            if len(kv_cache) != c.num_layers:
                raise ValueError(f"kv_cache has {len(kv_cache)} layers; "
                                 f"model has {c.num_layers}")
            if decode:
                if s_local != 1:
                    raise ValueError(f"decode wants one token per "
                                     f"sequence, got S={s_local}")
                positions = cache_length[:, None]  # [B, 1] absolute
            else:
                positions = jnp.arange(s_local)
            x = nn.Embed(c.vocab_size, c.hidden_size,
                         name="embed")(input_ids)
            x = x.astype(c.jnp_dtype)
            new_caches = []
            for i in range(c.num_layers):
                x, layer_cache = Block(c, False, False,
                                       name=f"block_{i}")(
                    x, positions, kv_cache=kv_cache[i],
                    cache_length=cache_length, decode=decode)
                new_caches.append(layer_cache)
            x = nn.LayerNorm(use_bias=False, name="final_ln")(x)
            logits = nn.Dense(c.vocab_size, use_bias=False,
                              name="lm_head")(x)
            return logits.astype(jnp.float32), tuple(new_caches)
        # Global positions: offset by this device's chunk index when the
        # sequence dimension is sharded over the seq axis.
        start = 0
        if c.seq_mode != "none" and _seq_axis_bound(c.seq_axis):
            start = lax.axis_index(c.seq_axis) * s_local
        positions = start + jnp.arange(s_local)

        x = nn.Embed(c.vocab_size, c.hidden_size, name="embed")(input_ids)
        x = x.astype(c.jnp_dtype)
        # Named checkpoint seams: identity unless a per_block/save_named
        # remat policy (tpuframe.mem) elects to save exactly these.
        x = mem.seam(x, "embed_out")
        block = mem.remat_module(Block) if c.remat else Block
        for i in range(c.num_layers):
            use_moe = c.moe_experts > 0 and (i + 1) % c.moe_every == 0
            x = block(c, train, use_moe, name=f"block_{i}")(x, positions)
            x = mem.seam(x, "block_out")
        x = nn.LayerNorm(use_bias=False, name="final_ln")(x)
        if hidden_only:
            return x
        logits = nn.Dense(c.vocab_size, use_bias=False, name="lm_head")(x)
        return logits.astype(jnp.float32)
