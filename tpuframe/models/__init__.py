"""Model zoo — the reference's workload models, rebuilt in flax.

Reference coverage (SURVEY.md §3a "Model defs", [B:7–10]):
  - MNIST ConvNet (custom nn.Module in the reference)  → ``convnet.ConvNet``
  - ResNet-18 / ResNet-50 (torchvision in the reference) → ``resnet``
  - BERT-base for GLUE (HF transformers in the reference) → ``bert``

All models are NHWC / bf16-compute-capable — the TPU-native layout/dtype
choices (MXU wants large bf16 matmuls; see task guidance + pallas_guide).
"""

from typing import Any, Callable

from tpuframe.models.convnet import ConvNet
from tpuframe.models.resnet import (ResNet, ResNet18, ResNet34,
                                    ResNet50, ResNet101, ResNet152)
from tpuframe.models.bert import BertConfig, BertForSequenceClassification
from tpuframe.models.transformer_lm import (LMConfig, ScanBlockLM,
                                             TransformerLM)

def _bert_base(dtype=None, **kwargs):
    """Registry adapter: flag-style kwargs → BertConfig (so get_model's
    uniform ``get_model(name, dtype=..., **kwargs)`` call shape works for
    BERT too)."""
    import numpy as np

    if dtype is not None:
        kwargs.setdefault("dtype", str(np.dtype(dtype)))
    return BertForSequenceClassification(BertConfig.base(**kwargs))


def _lm_adapter(cls):
    """Registry adapter shared by the LM variants: flag-style kwargs →
    LMConfig → the given module class."""

    def build(dtype=None, tiny=False, **kwargs):
        import numpy as np

        if dtype is not None:
            kwargs.setdefault("dtype", str(np.dtype(dtype)))
        cfg = LMConfig.tiny(**kwargs) if tiny else LMConfig(**kwargs)
        return cls(cfg)

    return build


# transformer-lm-pp: the pipeline-parallel variant (layer-stacked blocks;
# trained via tpuframe.parallel.pp_lm on a data x pipe mesh).
_transformer_lm = _lm_adapter(TransformerLM)
_transformer_lm_pp = _lm_adapter(ScanBlockLM)


_REGISTRY: dict[str, Callable[..., Any]] = {
    "convnet": ConvNet,
    "resnet18": ResNet18,
    "resnet34": ResNet34,
    "resnet50": ResNet50,
    "resnet101": ResNet101,
    "resnet152": ResNet152,
    "bert-base": _bert_base,
    "transformer-lm": _transformer_lm,
    "transformer-lm-pp": _transformer_lm_pp,
}


def get_model(name: str, **kwargs):
    """Construct a model by registry name (harness entry point)."""
    if name not in _REGISTRY:
        raise ValueError(f"unknown model {name!r}; have {sorted(_REGISTRY)}")
    return _REGISTRY[name](**kwargs)


__all__ = [
    "ConvNet",
    "LMConfig",
    "ScanBlockLM",
    "TransformerLM",
    "ResNet",
    "ResNet18",
    "ResNet34",
    "ResNet50",
    "ResNet101",
    "ResNet152",
    "BertConfig",
    "BertForSequenceClassification",
    "get_model",
]
