"""Folded BatchNorm — the byte-census-driven BN (PERF.md §6/§7).

The offline HLO census (`perf/exp_hlo_offline.py`, v5e AOT compile) showed
that with ``nn.BatchNorm(dtype=bfloat16)`` the normalize chain still runs
in float32: flax upcasts the ACTIVATION for ``(x - mean) * rsqrt(var+eps)
* gamma + beta``, so the compiled step is dominated by f32
activation-sized converts/multiplies/adds (74% of activation-sized HLO
values) — on a bandwidth-bound step (81% of the HBM roofline,
PERF.md §2) every f32 materialization costs 2x the bytes of bf16.

This module keeps every NUMERICALLY DELICATE quantity in f32 — the
mean/variance reductions (f32 accumulation via ``jnp.mean(..., dtype)``,
which XLA fuses into the reduce, no f32 activation materializes), the
running statistics, and the derivation of the per-channel affine — but
folds the normalization into exactly one activation-sized FMA in the
compute dtype:

    a = gamma * rsqrt(var + eps)        # f32, C-sized
    b = beta - mean * a                 # f32, C-sized
    y = x * a.astype(x.dtype) + b.astype(x.dtype)   # bf16, one pass

Difference vs ``nn.BatchNorm``: ``a``/``b`` are rounded to bf16 BEFORE
the activation math instead of after — one extra rounding of a per-channel
scalar, bounded by bf16 eps (~0.4%), with the activation-sized math
otherwise identical (parity pinned by tests/test_folded_bn.py; the f32
path agrees with ``nn.BatchNorm`` to 1e-5).

Interface parity with ``nn.BatchNorm``: same ``batch_stats`` collection
with ``mean``/``var`` entries and same param names (``scale``/``bias``).
(Flax auto-names modules by class — ``FoldedBatchNorm_N`` vs
``BatchNorm_N`` — so a whole-model checkpoint still re-keys when the BN
implementation is toggled; the per-module variable layout matches.)
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp


class FoldedBatchNorm(nn.Module):
    """Drop-in BatchNorm whose activation-sized math stays in ``dtype``.

    Supports the feature subset the model zoo uses: last-axis features,
    scale+bias on, zeros/ones initializers.
    """

    use_running_average: bool = False
    momentum: float = 0.9
    epsilon: float = 1e-5
    dtype: jnp.dtype | None = None
    param_dtype: jnp.dtype = jnp.float32
    scale_init: nn.initializers.Initializer = nn.initializers.ones
    bias_init: nn.initializers.Initializer = nn.initializers.zeros

    @nn.compact
    def __call__(self, x, use_running_average: bool | None = None):
        use_avg = nn.merge_param("use_running_average",
                                 self.use_running_average,
                                 use_running_average)
        features = x.shape[-1]
        scale = self.param("scale", self.scale_init, (features,),
                           self.param_dtype)
        bias = self.param("bias", self.bias_init, (features,),
                          self.param_dtype)
        ra_mean = self.variable("batch_stats", "mean",
                                lambda: jnp.zeros((features,), jnp.float32))
        ra_var = self.variable("batch_stats", "var",
                               lambda: jnp.ones((features,), jnp.float32))

        if use_avg:
            mean, var = ra_mean.value, ra_var.value
        else:
            # f32 ACCUMULATION without f32 materialization: the convert
            # and square feed straight into the reduces, so XLA fuses the
            # whole chain into one pass that reads the bf16 activation
            # once — only C-sized f32 lands in HBM.  The square must be
            # taken AFTER the f32 convert: squaring in bf16 first would
            # make E[x^2]-E[x]^2 catastrophically cancellative for
            # channels with |mean| >> std (bf16's ~2^-9 relative error on
            # x^2 swamps a small variance).
            axes = tuple(range(x.ndim - 1))
            xf = x.astype(jnp.float32)
            mean = jnp.mean(xf, axis=axes)
            mean2 = jnp.mean(jnp.square(xf), axis=axes)
            var = jnp.maximum(mean2 - jnp.square(mean), 0.0)
            if not self.is_initializing():
                m = self.momentum
                ra_mean.value = m * ra_mean.value + (1 - m) * mean
                ra_var.value = m * ra_var.value + (1 - m) * var

        a = scale.astype(jnp.float32) * jax.lax.rsqrt(var + self.epsilon)
        b = bias.astype(jnp.float32) - mean * a
        out_dtype = self.dtype or x.dtype
        return x.astype(out_dtype) * a.astype(out_dtype) + b.astype(out_dtype)
