"""BERT-base for GLUE fine-tuning — config 4 (SURVEY.md §1, [B:10]).

The reference uses HF ``transformers``' torch BERT; this is a from-scratch
flax implementation of the same architecture (Devlin et al. 2018: post-LN
encoder, learned position embeddings, GELU FFN, tanh pooler) so the whole
compute path is jit-compiled and pallas-swappable.

The config-4 workload exists to stress many-small-tensor gradient allreduce
(BERT-base has ~200 parameter tensors); in this framework that pressure lands
on XLA's all-reduce combiner rather than Horovod's fusion buffer — see
``tpuframe.parallel.tuning``.

The attention core routes through ``tpuframe.ops.attention`` so the pallas
flash-attention TPU kernel can replace the naive einsum without touching the
model definition.

``load_hf_weights`` imports a HuggingFace torch checkpoint (the reference's
starting point for fine-tuning) into this module's parameter tree.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import flax.linen as nn
import jax.numpy as jnp
import numpy as np


@dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position: int = 512
    type_vocab_size: int = 2
    dropout: float = 0.1
    layer_norm_eps: float = 1e-12
    num_classes: int = 2
    dtype: str = "float32"  # "bfloat16" for MXU throughput

    @property
    def jnp_dtype(self):
        return jnp.dtype(self.dtype)

    @classmethod
    def base(cls, **kw) -> "BertConfig":
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw) -> "BertConfig":
        """4-layer/128-wide config for tests (same graph shape, tiny sizes)."""
        base = dict(vocab_size=1024, hidden_size=128, num_layers=4,
                    num_heads=4, intermediate_size=256, max_position=128)
        base.update(kw)
        return cls(**base)


class BertEmbeddings(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, input_ids, token_type_ids, *, train: bool):
        c = self.cfg
        pos_ids = jnp.arange(input_ids.shape[-1])[None, :]
        x = (nn.Embed(c.vocab_size, c.hidden_size, name="word")(input_ids)
             + nn.Embed(c.max_position, c.hidden_size, name="position")(pos_ids)
             + nn.Embed(c.type_vocab_size, c.hidden_size, name="type")(token_type_ids))
        x = nn.LayerNorm(epsilon=c.layer_norm_eps, name="ln")(x)
        x = nn.Dropout(c.dropout, deterministic=not train)(x)
        return x.astype(c.jnp_dtype)


class BertSelfAttention(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x, attention_mask, *, train: bool):
        from tpuframe.ops import attention as attn_ops

        c = self.cfg
        head_dim = c.hidden_size // c.num_heads
        dense = lambda name: nn.DenseGeneral(  # noqa: E731
            (c.num_heads, head_dim), dtype=c.jnp_dtype, name=name)
        q = dense("query")(x)  # [B, S, H, D]
        k = dense("key")(x)
        v = dense("value")(x)
        y = attn_ops.multihead_attention(
            q, k, v, mask=attention_mask,
            dropout_rate=c.dropout if train else 0.0,
            dropout_rng=self.make_rng("dropout") if (train and c.dropout > 0) else None,
        )
        y = nn.DenseGeneral(c.hidden_size, axis=(-2, -1), dtype=c.jnp_dtype,
                            name="out")(y)
        return y


class BertLayer(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x, attention_mask, *, train: bool):
        c = self.cfg
        # Post-LN (original BERT): sublayer → dropout → add → LN.
        a = BertSelfAttention(c, name="attention")(x, attention_mask, train=train)
        a = nn.Dropout(c.dropout, deterministic=not train)(a)
        x = nn.LayerNorm(epsilon=c.layer_norm_eps, name="attention_ln")(x + a)

        h = nn.Dense(c.intermediate_size, dtype=c.jnp_dtype, name="intermediate")(x)
        h = nn.gelu(h, approximate=False)
        h = nn.Dense(c.hidden_size, dtype=c.jnp_dtype, name="output")(h)
        h = nn.Dropout(c.dropout, deterministic=not train)(h)
        x = nn.LayerNorm(epsilon=c.layer_norm_eps, name="output_ln")(x + h)
        return x


class BertEncoder(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x, attention_mask, *, train: bool):
        for i in range(self.cfg.num_layers):
            x = BertLayer(self.cfg, name=f"layer_{i}")(x, attention_mask,
                                                       train=train)
        return x


class BertForSequenceClassification(nn.Module):
    """Encoder + tanh pooler + classification head (the GLUE fine-tune model)."""

    cfg: BertConfig = field(default_factory=BertConfig)

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 *, train: bool = False):
        c = self.cfg
        if attention_mask is None:
            attention_mask = jnp.ones_like(input_ids)
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)

        x = BertEmbeddings(c, name="embeddings")(input_ids, token_type_ids,
                                                 train=train)
        x = BertEncoder(c, name="encoder")(x, attention_mask, train=train)
        pooled = nn.tanh(nn.Dense(c.hidden_size, dtype=c.jnp_dtype,
                                  name="pooler")(x[:, 0]))
        pooled = nn.Dropout(c.dropout, deterministic=not train)(pooled)
        logits = nn.Dense(c.num_classes, name="classifier")(pooled)
        return logits.astype(jnp.float32)


# ---------------------------------------------------------------------------
# HF torch checkpoint import (the reference fine-tunes from bert-base-uncased)
# ---------------------------------------------------------------------------

def load_hf_weights(params: dict, state_dict: dict, cfg: BertConfig) -> dict:
    """Map a HuggingFace ``bert-base-uncased`` torch ``state_dict`` onto this
    module's parameter tree.  Torch Linear weights are [out, in] and transpose
    to flax's [in, out]; attention projections reshape to [in, heads, head_dim].
    """
    import jax

    head_dim = cfg.hidden_size // cfg.num_heads
    H, N, D = cfg.hidden_size, cfg.num_heads, head_dim

    def t(name):
        return np.asarray(state_dict[name])

    out = jax.tree.map(lambda x: x, params)  # deep copy of structure
    emb = out["embeddings"]
    emb["word"]["embedding"] = t("bert.embeddings.word_embeddings.weight")
    emb["position"]["embedding"] = t("bert.embeddings.position_embeddings.weight")
    emb["type"]["embedding"] = t("bert.embeddings.token_type_embeddings.weight")
    emb["ln"]["scale"] = t("bert.embeddings.LayerNorm.weight")
    emb["ln"]["bias"] = t("bert.embeddings.LayerNorm.bias")

    for i in range(cfg.num_layers):
        src = f"bert.encoder.layer.{i}."
        dst = out["encoder"][f"layer_{i}"]
        att = dst["attention"]
        for proj, hf in (("query", "attention.self.query"),
                         ("key", "attention.self.key"),
                         ("value", "attention.self.value")):
            att[proj]["kernel"] = t(src + hf + ".weight").T.reshape(H, N, D)
            att[proj]["bias"] = t(src + hf + ".bias").reshape(N, D)
        att["out"]["kernel"] = t(src + "attention.output.dense.weight").T.reshape(N, D, H)
        att["out"]["bias"] = t(src + "attention.output.dense.bias")
        dst["attention_ln"]["scale"] = t(src + "attention.output.LayerNorm.weight")
        dst["attention_ln"]["bias"] = t(src + "attention.output.LayerNorm.bias")
        dst["intermediate"]["kernel"] = t(src + "intermediate.dense.weight").T
        dst["intermediate"]["bias"] = t(src + "intermediate.dense.bias")
        dst["output"]["kernel"] = t(src + "output.dense.weight").T
        dst["output"]["bias"] = t(src + "output.dense.bias")
        dst["output_ln"]["scale"] = t(src + "output.LayerNorm.weight")
        dst["output_ln"]["bias"] = t(src + "output.LayerNorm.bias")

    out["pooler"]["kernel"] = t("bert.pooler.dense.weight").T
    out["pooler"]["bias"] = t("bert.pooler.dense.bias")
    return out
