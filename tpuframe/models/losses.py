"""Loss/metric functions shared by the harness configs.

Reference parity: torch ``F.cross_entropy`` / ``F.nll_loss`` in ``train.py``
plus accuracy computed per rank and hvd.allreduce-averaged (SURVEY.md §4.5).
Here losses are plain functions used inside the compiled step; averaging
across replicas is the step builder's job.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax


def softmax_cross_entropy(logits: jax.Array, labels: jax.Array,
                          label_smoothing: float = 0.0) -> jax.Array:
    """Mean CE over the batch; integer labels. ImageNet configs use
    ``label_smoothing=0.1`` (standard ResNet-50 recipe)."""
    num_classes = logits.shape[-1]
    if label_smoothing > 0.0:
        on = 1.0 - label_smoothing
        off = label_smoothing / (num_classes - 1)
        soft = jax.nn.one_hot(labels, num_classes) * (on - off) + off
        loss = optax.softmax_cross_entropy(logits, soft)
    else:
        loss = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    return jnp.mean(loss)


def accuracy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    return jnp.mean((jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32))


def topk_accuracy(logits: jax.Array, labels: jax.Array, k: int = 5) -> jax.Array:
    topk = jax.lax.top_k(logits, k)[1]
    hit = jnp.any(topk == labels[:, None], axis=-1)
    return jnp.mean(hit.astype(jnp.float32))
