"""Loss/metric functions shared by the harness configs.

Reference parity: torch ``F.cross_entropy`` / ``F.nll_loss`` in ``train.py``
plus accuracy computed per rank and hvd.allreduce-averaged (SURVEY.md §4.5).
Here losses are plain functions used inside the compiled step; averaging
across replicas is the step builder's job.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax


def masked_mean(x: jax.Array, labels: jax.Array, ignore_index: int,
                reduce_axis=None) -> jax.Array:
    """Mean of ``x`` over positions whose label != ignore_index — THE one
    definition of the valid-token reduction (loss, accuracy, fused path).

    ``reduce_axis``: mesh axis name(s) to sum numerator AND denominator
    over before dividing.  Per-shard masked means pmean-ed uniformly are
    BIASED when shards hold unequal valid counts (padded docs: suffix
    padding makes seq shards systematically unequal; data shards unequal
    per draw) — the global sum-of-sums / sum-of-counts is exact.  Safe to
    pass always: unbound axes (unmapped jit / auto-SPMD) reduce globally
    already and psum_scalar no-ops."""
    from tpuframe.parallel import collectives

    valid = (labels != ignore_index).astype(jnp.float32)
    num = jnp.sum(x.astype(jnp.float32) * valid)
    den = jnp.sum(valid)
    if reduce_axis is not None:
        num = collectives.psum_scalar(num, reduce_axis)
        den = collectives.psum_scalar(den, reduce_axis)
    return num / jnp.maximum(den, 1.0)


def softmax_cross_entropy(logits: jax.Array, labels: jax.Array,
                          label_smoothing: float = 0.0,
                          ignore_index: int | None = None,
                          reduce_axis=None) -> jax.Array:
    """Mean CE over the batch; integer labels. ImageNet configs use
    ``label_smoothing=0.1`` (standard ResNet-50 recipe).

    ``ignore_index``: torch ``F.cross_entropy(ignore_index=...)`` parity —
    tokens with that label contribute neither loss nor gradient, and the
    mean divides by the VALID count (matching torch's 'mean' reduction);
    ``reduce_axis`` makes that count global across mesh shards (see
    masked_mean)."""
    num_classes = logits.shape[-1]
    safe_labels = labels
    if ignore_index is not None:
        safe_labels = jnp.where(labels == ignore_index, 0, labels)
    if label_smoothing > 0.0:
        on = 1.0 - label_smoothing
        off = label_smoothing / (num_classes - 1)
        soft = jax.nn.one_hot(safe_labels, num_classes) * (on - off) + off
        loss = optax.softmax_cross_entropy(logits, soft)
    else:
        loss = optax.softmax_cross_entropy_with_integer_labels(logits,
                                                               safe_labels)
    if ignore_index is None:
        return jnp.mean(loss)
    return masked_mean(loss, labels, ignore_index, reduce_axis)


def accuracy(logits: jax.Array, labels: jax.Array,
             ignore_index: int | None = None,
             reduce_axis=None) -> jax.Array:
    hit = (jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32)
    if ignore_index is None:
        return jnp.mean(hit)
    return masked_mean(hit, labels, ignore_index, reduce_axis)


def topk_accuracy(logits: jax.Array, labels: jax.Array, k: int = 5) -> jax.Array:
    topk = jax.lax.top_k(logits, k)[1]
    hit = jnp.any(topk == labels[:, None], axis=-1)
    return jnp.mean(hit.astype(jnp.float32))
