"""MNIST ConvNet — config 1's model (SURVEY.md §1 workload 1, [B:7]).

The reference uses a small custom ``nn.Module`` (torch MNIST-example style:
two convs → pool → dropout → two dense).  Same capacity here, flax.linen,
NHWC, optional bf16 compute (params stay f32; casts at the matmul boundary
is XLA's preferred mixed-precision shape on TPU).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class ConvNet(nn.Module):
    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32
    # None keeps the reference rates (0.25 conv / 0.5 dense).  Per-replica
    # dropout streams are decorrelated by axis index (parallel/step.py), so
    # masks are world-size dependent; proofs that need bit-for-bit loss
    # equivalence across a mesh resize set this to 0.0.
    dropout: float | None = None

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, train: bool = False) -> jnp.ndarray:
        # x: [B, 28, 28, 1] NHWC
        d1 = 0.25 if self.dropout is None else self.dropout
        d2 = 0.5 if self.dropout is None else self.dropout
        x = x.astype(self.dtype)
        x = nn.Conv(32, (3, 3), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Conv(64, (3, 3), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Dropout(d1, deterministic=not train)(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(128, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Dropout(d2, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)  # logits in f32 for a stable softmax
