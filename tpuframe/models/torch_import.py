"""torchvision ResNet checkpoint import (SURVEY.md §3a "Model defs").

The reference takes its ResNets straight from torchvision
(``torchvision.models.resnet50(pretrained=...)``), so a switching user
arrives with torch ``state_dict`` checkpoints.  This maps them onto the
flax trees of :mod:`tpuframe.models.resnet` — same spirit as
``bert.load_hf_weights`` for HF BERT.

Name mapping (torchvision → tpuframe):

    conv1.weight                  → params/stem_conv/kernel   (OIHW→HWIO)
    bn1.{weight,bias}             → params/stem_bn/{scale,bias}
    bn1.running_{mean,var}        → batch_stats/stem_bn/{mean,var}
    layer{L}.{i}.conv{j}.weight   → params/<Block>_{n}/Conv_{j-1}/kernel
    layer{L}.{i}.bn{j}.*          → .../<Block>_{n}/BatchNorm_{j-1}/*
    layer{L}.{i}.downsample.0/1.* → .../downsample_conv, downsample_bn
    fc.{weight,bias}              → params/Dense_0/{kernel,bias} (.T)

where ``n`` is the cumulative block index (flax auto-naming is flat
across stages) and ``<Block>`` is ``Bottleneck``/``BasicBlock``.

Dtype/layout transforms: conv ``[O, I, kH, kW] → [kH, kW, I, O]``; fc
``[out, in] → [in, out]``; everything cast to the destination leaf's
dtype.  ``num_batches_tracked`` buffers are ignored (tpuframe tracks no
step counter in BN).

Forward-parity caveat: torchvision's ImageNet preprocessing normalizes
with its mean/std on NCHW float tensors; tpuframe's pipelines are NHWC —
imported weights expect the SAME normalization values the torch model
was trained with (the imagenet builder's defaults match torchvision's).
"""

from __future__ import annotations

import re

import jax.numpy as jnp
import numpy as np


def _t(x):
    return np.asarray(x)  # torch tensors support __array__ (CPU)


def _block_prefix(variables) -> str:
    names = {k.split("/")[0] for k in _flat(variables["params"])}
    for cand in ("Bottleneck", "BasicBlock"):
        if any(n.startswith(cand + "_") for n in names):
            return cand
    raise ValueError("variables do not look like a tpuframe ResNet "
                     f"(top-level params: {sorted(names)[:8]}...)")


def _flat(tree, prefix=""):
    out = {}
    for k, v in tree.items():
        key = f"{prefix}/{k}" if prefix else k
        if isinstance(v, dict):
            out.update(_flat(v, key))
        else:
            out[key] = v
    return out


def _unflatten(flat):
    tree: dict = {}
    for key, v in flat.items():
        parts = key.split("/")
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = v
    return tree


def _stage_block_index(params_flat, block) -> dict[tuple[int, int], int]:
    """(layer, i) → cumulative flax block index, from the param tree's own
    block count per stage (channel widths identify the stage)."""
    n_blocks = len({k.split("/")[0] for k in params_flat
                    if k.startswith(block + "_")})
    # A new stage opens at block 0 and at every block carrying a
    # downsample conv (stage-opening blocks are exactly the shape-changing
    # ones; v1.5 Bottleneck layer1.0 downsamples too — channel expansion —
    # while BasicBlock layer1.0 doesn't, and both cases are covered by
    # the n == 0 clause).
    mapping = {}
    layer, i = 1, 0
    for n in range(n_blocks):
        has_ds = f"{block}_{n}/downsample_conv/kernel" in params_flat
        if n > 0 and has_ds:
            layer += 1
            i = 0
        mapping[(layer, i)] = n
        i += 1
    return mapping


def _reject_fused(params_flat) -> None:
    """Refuse ``bn='fused'`` trees up front instead of dying on a raw
    KeyError mid-import: FusedConvBN renames the Bottleneck 1x1 conv+BN
    pairs (FusedConvBN_N / downsample_fused), so the torchvision name map
    above does not apply to them."""
    fused = sorted({k.split("/")[0] for k in params_flat
                    if "FusedConvBN" in k or "downsample_fused" in k})
    if fused:
        raise ValueError(
            "load_torchvision_resnet does not support bn='fused' models "
            f"(found fused modules {fused[:4]}...): FusedConvBN folds the "
            "1x1 conv+BN pairs into one module with its own param names. "
            "Import into a bn='flax' model, then rebuild with bn='fused' — "
            "the two share identical per-layer weights (PERF.md §7.4b).")


def load_torchvision_resnet(variables: dict, state_dict: dict) -> dict:
    """Return a new ``{"params", "batch_stats"}`` tree with every leaf
    replaced from the torchvision ``state_dict``.  Raises KeyError on a
    missing source tensor and ValueError on a shape mismatch — silent
    partial imports are how wrong checkpoints sneak into runs."""
    block = _block_prefix(variables)
    params = _flat(variables["params"])
    stats = _flat(variables["batch_stats"])
    _reject_fused(params)
    idx = _stage_block_index(params, block)

    def conv(w):
        return _t(w).transpose(2, 3, 1, 0)  # OIHW → HWIO

    out_p, out_s = {}, {}

    def put_p(dst, src_name, transform=lambda x: _t(x)):
        if src_name not in state_dict:
            raise KeyError(f"state_dict missing {src_name!r} (for {dst})")
        v = transform(state_dict[src_name])
        ref = params[dst]
        if tuple(v.shape) != tuple(ref.shape):
            raise ValueError(f"{src_name} -> {dst}: shape {v.shape} != "
                             f"{tuple(ref.shape)}")
        out_p[dst] = jnp.asarray(v, ref.dtype)

    def put_s(dst, src_name):
        if src_name not in state_dict:
            raise KeyError(f"state_dict missing {src_name!r} (for {dst})")
        v = _t(state_dict[src_name])
        ref = stats[dst]
        if tuple(v.shape) != tuple(ref.shape):
            raise ValueError(f"{src_name} -> {dst}: shape {v.shape} != "
                             f"{tuple(ref.shape)}")
        out_s[dst] = jnp.asarray(v, ref.dtype)

    def bn(dst_mod, src_mod):
        put_p(f"{dst_mod}/scale", f"{src_mod}.weight")
        put_p(f"{dst_mod}/bias", f"{src_mod}.bias")
        put_s(f"{dst_mod}/mean", f"{src_mod}.running_mean")
        put_s(f"{dst_mod}/var", f"{src_mod}.running_var")

    put_p("stem_conv/kernel", "conv1.weight", conv)
    bn("stem_bn", "bn1")

    convs_per_block = 3 if block == "Bottleneck" else 2
    for (layer, i), n in sorted(idx.items()):
        tv = f"layer{layer}.{i}"
        fx = f"{block}_{n}"
        for j in range(1, convs_per_block + 1):
            put_p(f"{fx}/Conv_{j-1}/kernel", f"{tv}.conv{j}.weight", conv)
            bn(f"{fx}/BatchNorm_{j-1}", f"{tv}.bn{j}")
        if f"{fx}/downsample_conv/kernel" in params:
            put_p(f"{fx}/downsample_conv/kernel",
                  f"{tv}.downsample.0.weight", conv)
            bn(f"{fx}/downsample_bn", f"{tv}.downsample.1")

    put_p("Dense_0/kernel", "fc.weight", lambda w: _t(w).T)
    put_p("Dense_0/bias", "fc.bias")

    missing = set(params) - set(out_p)
    if missing:
        raise ValueError(f"import left params unset: {sorted(missing)[:6]}")
    missing_s = set(stats) - set(out_s)
    if missing_s:
        raise ValueError(f"import left stats unset: {sorted(missing_s)[:6]}")
    return {"params": _unflatten(out_p), "batch_stats": _unflatten(out_s)}


def export_torchvision_resnet(variables: dict) -> dict:
    """Inverse of :func:`load_torchvision_resnet` (numpy state_dict) —
    lets tpuframe-trained ResNets go BACK to torch eval stacks, and
    makes the import testable as a bijection without torchvision."""
    block = _block_prefix(variables)
    params = _flat(variables["params"])
    stats = _flat(variables["batch_stats"])
    idx = _stage_block_index(params, block)
    sd = {}

    def conv_back(w):
        return np.asarray(w).transpose(3, 2, 0, 1)  # HWIO → OIHW

    def bn_back(src_mod, dst_mod):
        sd[f"{dst_mod}.weight"] = np.asarray(params[f"{src_mod}/scale"])
        sd[f"{dst_mod}.bias"] = np.asarray(params[f"{src_mod}/bias"])
        sd[f"{dst_mod}.running_mean"] = np.asarray(stats[f"{src_mod}/mean"])
        sd[f"{dst_mod}.running_var"] = np.asarray(stats[f"{src_mod}/var"])

    sd["conv1.weight"] = conv_back(params["stem_conv/kernel"])
    bn_back("stem_bn", "bn1")
    convs_per_block = 3 if block == "Bottleneck" else 2
    for (layer, i), n in sorted(idx.items()):
        tv = f"layer{layer}.{i}"
        fx = f"{block}_{n}"
        for j in range(1, convs_per_block + 1):
            sd[f"{tv}.conv{j}.weight"] = conv_back(
                params[f"{fx}/Conv_{j-1}/kernel"])
            bn_back(f"{fx}/BatchNorm_{j-1}", f"{tv}.bn{j}")
        if f"{fx}/downsample_conv/kernel" in params:
            sd[f"{tv}.downsample.0.weight"] = conv_back(
                params[f"{fx}/downsample_conv/kernel"])
            bn_back(f"{fx}/downsample_bn", f"{tv}.downsample.1")
    sd["fc.weight"] = np.asarray(params["Dense_0/kernel"]).T
    sd["fc.bias"] = np.asarray(params["Dense_0/bias"])
    return sd
