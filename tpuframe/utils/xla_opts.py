"""``TPUFRAME_XLA_OPTS`` parsing, shared by bench.py, train.py and the
tune sweep.

Format: ``key=value,key=value`` (e.g.
``xla_tpu_enable_latency_hiding_scheduler=true``).  The resulting dict is
passed as ``jax.jit(..., compiler_options=...)`` — the options travel
inside the compile request, so they survive the relay's remote-compile
hop where env vars (XLA_FLAGS / LIBTPU_INIT_ARGS) either crash the local
flag parser or never reach the compiler, and they need no env mutation
at all (TF106).
"""

from __future__ import annotations

import os

ENV_VAR = "TPUFRAME_XLA_OPTS"


def parse(spec: str) -> dict:
    """'k=v,k=v' -> dict.  Raises ValueError listing every bad entry."""
    pairs = [kv.strip() for kv in spec.split(",") if kv.strip()]
    bad = [kv for kv in pairs
           if "=" not in kv or not kv.split("=", 1)[0].strip()
           or not kv.split("=", 1)[1].strip()]
    if bad:
        raise ValueError(f"{ENV_VAR} entries need key=value, got {bad!r}")
    return {k.strip(): v.strip() for k, v in
            (kv.split("=", 1) for kv in pairs)}


def from_env(var: str = ENV_VAR) -> dict | None:
    """The env var parsed, or None when unset/empty (so callers can fall
    through to the tuning DB: env override > measured > predicted >
    default)."""
    spec = os.environ.get(var, "")
    return parse(spec) if spec.strip() else None


def format_opts(opts: dict) -> str:
    """Inverse of :func:`parse` — the env-var spelling of an option set
    (used by tune records' env_overrides)."""
    return ",".join(f"{k}={v}" for k, v in sorted(opts.items()))
