"""Shared utilities: config dataclasses, optimizer/schedule builders."""

from tpuframe.utils.config import TrainConfig, WORKLOADS, get_config  # noqa: F401
from tpuframe.utils.optim import build_optimizer  # noqa: F401
