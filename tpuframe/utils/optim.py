"""Optimizer / LR-schedule builders.

Reference parity: torch SGD-momentum (+ LR scaled by ``hvd.size()``) for the
vision configs and AdamW with warmup for BERT, wrapped in
``hvd.DistributedOptimizer`` (SURVEY.md §3a).  Here the distributed wrapping
is unnecessary — gradient averaging lives in the compiled step — but the same
optax transformation chain is exposed so configs map 1:1.
"""

from __future__ import annotations

import optax

from tpuframe.utils.config import TrainConfig


def lr_schedule(cfg: TrainConfig, world_batch_scale: float) -> optax.Schedule:
    peak = cfg.base_lr * (world_batch_scale if cfg.scale_lr_by_batch else 1.0)
    decay_steps = max(cfg.total_steps - cfg.warmup_steps, 1)
    if cfg.schedule == "cosine":
        sched = optax.cosine_decay_schedule(peak, decay_steps)
    elif cfg.schedule == "linear":
        sched = optax.linear_schedule(peak, 0.0, decay_steps)
    elif cfg.schedule == "constant":
        sched = optax.constant_schedule(peak)
    else:
        raise ValueError(f"unknown schedule {cfg.schedule!r}")
    if cfg.warmup_steps > 0:
        warmup = optax.linear_schedule(0.0, peak, cfg.warmup_steps)
        return optax.join_schedules([warmup, sched], [cfg.warmup_steps])
    return sched


def _decay_mask(params) -> object:
    """No weight decay on biases/norm scales (standard recipe; matches the
    reference's torch param-group split)."""
    import jax

    def keep(path, _):
        name = str(path[-1].key) if hasattr(path[-1], "key") else ""
        return name not in ("bias", "scale", "b")

    return jax.tree_util.tree_map_with_path(keep, params)


def build_optimizer(cfg: TrainConfig, params=None) -> optax.GradientTransformation:
    """Chain: [clip] → optimizer(+wd) → schedule. LR linear-scaling rule:
    peak = base_lr * global_batch/256 (the hvd.size() scaling, SURVEY.md §3a)."""
    scale = cfg.global_batch / 256.0
    sched = lr_schedule(cfg, scale)
    parts: list[optax.GradientTransformation] = []
    if cfg.grad_clip_norm is not None:
        parts.append(optax.clip_by_global_norm(cfg.grad_clip_norm))
    if cfg.optimizer == "sgd":
        parts.append(optax.sgd(sched, momentum=cfg.momentum, nesterov=True))
        if cfg.weight_decay > 0.0:
            # torch SGD couples weight decay into the gradient; add_decayed_
            # weights before the update is the optax equivalent.
            parts.insert(-1, optax.add_decayed_weights(
                cfg.weight_decay,
                mask=_decay_mask(params) if params is not None else None))
    elif cfg.optimizer == "adamw":
        parts.append(optax.adamw(
            sched, weight_decay=cfg.weight_decay,
            mask=_decay_mask(params) if params is not None else None))
    elif cfg.optimizer == "lars":
        # Large-batch ResNet scaling (the You et al. recipe the
        # Horovod/MLPerf-era ImageNet runs used beyond ~8k global batch):
        # layerwise trust-ratio adaptation; biases/BN params excluded from
        # both adaptation and weight decay, as standard.
        mask = _decay_mask(params) if params is not None else True
        parts.append(optax.lars(
            sched, weight_decay=cfg.weight_decay,
            weight_decay_mask=mask, trust_ratio_mask=mask,
            momentum=cfg.momentum, nesterov=False))
    else:
        raise ValueError(f"unknown optimizer {cfg.optimizer!r}")
    return optax.chain(*parts)
