"""Workload configs — the five reference configurations (SURVEY.md §1, [B:6–12]).

The reference drives these via argparse flags + env vars (SURVEY.md §5.6);
here each workload is a frozen dataclass with CLI overrides applied on top
(``python -m tpuframe.train --config cifar10_resnet18 --set total_steps=100``).

Batch sizes / LRs follow the standard recipes the reference genre uses
(linear-LR scaling with world size — the ``scale LR by hvd.size()`` rule,
SURVEY.md §3a "Distributed glue").
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any

from tpuframe.parallel.mesh import MeshSpec


@dataclass(frozen=True)
class TrainConfig:
    name: str
    model: str                      # registry name (tpuframe.models)
    model_kwargs: dict[str, Any] = field(default_factory=dict)
    dataset: str = "mnist"          # mnist | cifar10 | imagenet | glue_sst2
    dataset_kwargs: dict[str, Any] = field(default_factory=dict)
    data_dir: str | None = None     # local dir or gs:// bucket path

    # distribution
    distributed: bool = True        # False → config-1 style unmapped jit
    mesh: MeshSpec = field(default_factory=MeshSpec)
    shard_seq: bool = False         # shard batch seq dim over the seq axis

    # optimization
    optimizer: str = "sgd"          # sgd | adamw | lars (large-batch)
    base_lr: float = 0.1            # per-256-examples; scaled by global batch
    scale_lr_by_batch: bool = True  # the hvd.size() linear-scaling rule
    warmup_steps: int = 0
    schedule: str = "cosine"        # cosine | linear | constant
    momentum: float = 0.9
    weight_decay: float = 0.0
    grad_clip_norm: float | None = None
    label_smoothing: float = 0.0

    # loop
    global_batch: int = 64
    total_steps: int = 200
    # Gradient accumulation (Horovod's backward_passes_per_step): microbatch
    # count per optimizer step; global_batch is split by this on-device.
    accum_steps: int = 1
    # Cross-replica gradient combine: "mean" (Horovod's averaged allreduce)
    # or "adasum" (op=hvd.Adasum — scale-insensitive adaptive summation;
    # pair it with scale_lr_by_batch=False, which is its purpose).
    grad_reduce: str = "mean"
    # GPipe microbatches per step when the mesh's pipe axis > 1
    # (model='transformer-lm-pp'; tpuframe.parallel.pp_lm).
    pp_microbatches: int = 4
    eval_every: int = 100
    eval_batches: int = 8
    log_every: int = 10
    seed: int = 42

    # On-device training augmentation (tpuframe/data/augment.py):
    # none | flip | pad_crop_flip (CIFAR recipe) | crop_flip (larger
    # stored images; crop size = the model input).  Train path only;
    # randomness rides the step rng (resume-exact).
    augment: str = "none"
    augment_crop: int | None = None

    # precision
    compute_dtype: str = "float32"  # bfloat16 on real TPU runs

    # LM loss path: chunked fused softmax-xent (tpuframe.ops.fused_xent) —
    # the [B,S,V] logits never materialize in HBM.  lm_text datasets only.
    fused_xent: bool = False

    # observability (SURVEY.md §5.5): TensorBoard event-file dir (gs:// ok)
    tb_dir: str | None = None

    # checkpoint (SURVEY.md §4.4)
    ckpt_dir: str | None = None
    ckpt_every: int = 500
    ckpt_keep: int = 3
    resume: bool = True
    # Background checkpoint writes: snapshot synchronously, serialize/upload
    # + COMMIT on a worker thread (no barrier — sidecar polling); the loop
    # never waits on storage.
    ckpt_async: bool = False
    # Keep the single best-by-eval-loss checkpoint under <ckpt_dir>/best/
    # (the reference genre's 'save best model' hook).
    track_best: bool = False

    def with_overrides(self, **kv) -> "TrainConfig":
        known = {f.name for f in dataclasses.fields(self)}
        bad = set(kv) - known
        if bad:
            raise ValueError(f"unknown config fields {sorted(bad)}")
        if "mesh" in kv and isinstance(kv["mesh"], dict):
            kv["mesh"] = MeshSpec(**kv["mesh"])
        # Dict-valued fields MERGE instead of replace: `--set
        # model_kwargs={"moe_experts": 4}` on a tiny config must not
        # silently rebuild the model at full default size by dropping the
        # config's own kwargs.  A None value DELETES that key, so
        # `--set 'model_kwargs={"seq_mode": None}'` removes a base-config
        # entry (the replace escape hatch).
        for field_name in ("model_kwargs", "dataset_kwargs"):
            if field_name in kv and isinstance(kv[field_name], dict):
                merged = dict(getattr(self, field_name))
                merged.update(kv[field_name])
                kv[field_name] = {k: v for k, v in merged.items()
                                  if v is not None}
        return dataclasses.replace(self, **kv)


def _mnist_single() -> TrainConfig:
    """Config 1 [B:7]: MNIST ConvNet, single process, no collectives."""
    return TrainConfig(
        name="mnist_single", model="convnet", dataset="mnist",
        distributed=False, optimizer="sgd", base_lr=0.02,
        scale_lr_by_batch=False, schedule="constant", global_batch=64,
        total_steps=400, eval_every=200,
    )


def _cifar10_resnet18() -> TrainConfig:
    """Config 2 [B:8]: ResNet-18 / CIFAR-10, data-parallel (reference: 2-process
    Horovod). Mesh defaults to all chips; 2-chip parity comes from running on 2."""
    return TrainConfig(
        name="cifar10_resnet18", model="resnet18",
        model_kwargs={"num_classes": 10, "cifar_stem": True},
        dataset="cifar10", dataset_kwargs={"keep_u8": True},
        optimizer="sgd", base_lr=0.1, warmup_steps=200,
        schedule="cosine", weight_decay=5e-4, global_batch=256,
        total_steps=2000, eval_every=500,
        augment="pad_crop_flip",   # the classic CIFAR train recipe
    )


def _imagenet_resnet50() -> TrainConfig:
    """Config 3 [B:9]: ResNet-50 / ImageNet, 8-chip DP with the GCS pipeline.
    Standard 90-epoch recipe scaled by batch; bf16 compute for the MXU."""
    return TrainConfig(
        name="imagenet_resnet50", model="resnet50",
        model_kwargs={"num_classes": 1000},
        dataset="imagenet", optimizer="sgd", base_lr=0.1, warmup_steps=1565,
        schedule="cosine", weight_decay=1e-4, label_smoothing=0.1,
        global_batch=2048, total_steps=56300, eval_every=2000,
        compute_dtype="bfloat16", ckpt_every=2000,
        augment="flip",   # storage is crop geometry; flip on device
    )


def _glue_bert() -> TrainConfig:
    """Config 4 [B:10]: BERT-base GLUE (SST-2) fine-tune — the many-small-grads
    allreduce stress test."""
    return TrainConfig(
        name="glue_bert", model="bert-base", dataset="glue_sst2",
        dataset_kwargs={"seq_len": 128}, optimizer="adamw", base_lr=2e-5,
        scale_lr_by_batch=False, warmup_steps=200, schedule="linear",
        weight_decay=0.01, grad_clip_norm=1.0, global_batch=32,
        total_steps=6000, eval_every=500, compute_dtype="bfloat16",
    )


def _glue_bert_mnli() -> TrainConfig:
    """Config 4 [B:10], second GLUE task: BERT-base MNLI fine-tune — the
    3-way sentence-PAIR format ([CLS] premise [SEP] hypothesis [SEP],
    segment ids 0/1), exercising the pair-encoding path SST-2 doesn't.
    Standard MNLI recipe: 3 epochs over 393k pairs at batch 32."""
    return _glue_bert().with_overrides(
        name="glue_bert_mnli", dataset="glue_mnli",
        model_kwargs={"num_classes": 3}, total_steps=36000, warmup_steps=1200,
    )


def _glue_bert_stsb() -> TrainConfig:
    """Config 4 [B:10], third GLUE shape: BERT-base STS-B — sentence-pair
    REGRESSION (similarity 0-5).  num_classes=1 ⇒ the harness trains with
    MSE on the single squeezed logit (HF's num_labels==1 convention).
    Standard recipe: ~4 epochs over 5.7k pairs at batch 32."""
    return _glue_bert().with_overrides(
        name="glue_bert_stsb", dataset="glue_stsb",
        model_kwargs={"num_classes": 1}, total_steps=720, warmup_steps=72,
    )


def _glue_bert_cola() -> TrainConfig:
    """Config 4 [B:10], fourth GLUE shape: CoLA — single-sentence binary
    with MATTHEWS CORRELATION eval (the skewed-class task where accuracy
    misleads).  Standard recipe: ~3 epochs over 8.5k sentences at 32."""
    return _glue_bert().with_overrides(
        name="glue_bert_cola", dataset="glue_cola", total_steps=800,
        warmup_steps=80,
    )


def _imagenet_resnet50_pod() -> TrainConfig:
    """Config 5 [B:11]: ResNet-50 / ImageNet on a multi-host pod (v4-32).
    Same recipe as config 3 at 4x the batch; launched via tpuframe.launch."""
    cfg = _imagenet_resnet50()
    return cfg.with_overrides(
        name="imagenet_resnet50_pod", global_batch=8192, warmup_steps=391,
        total_steps=14075,
    )


def _lm_long() -> TrainConfig:
    """Long-context causal LM with ring-attention sequence parallelism —
    beyond the reference's capability bar (SURVEY.md §5.7); seq/data mesh
    degrees come from --set mesh='{"data": N, "seq": M}'."""
    return TrainConfig(
        name="lm_long", model="transformer-lm",
        # attn_impl="pallas": ring stages run the flash kernel
        # (flash_mha_lse + logsumexp merge, round 5), cutting ring bytes
        # from >=2x to 1.33x of Ulysses+flash (PERF.md §11-§12).
        # Capacity (offline audit): dp1 x sp8 at 32k is 16.1 GB
        # resident/dev — still over v5e's 15.75 (fits v4's 32 GB), so
        # the data=2 default below is mandatory on v5e.  Unsupported
        # shapes auto-fall back to the xla stages.
        model_kwargs={"seq_mode": "ring", "attn_impl": "pallas",
                      "remat": True,
                      "max_seq": 32768, "vocab_size": 32000},
        dataset="lm_text", dataset_kwargs={"seq_len": 32768},
        # data=2 stays the default mesh: 4.7 GB/dev at dp2 x sp4 —
        # wide margin on both generations.
        shard_seq=True, mesh=MeshSpec(data=2, seq=-1),
        optimizer="adamw", base_lr=3e-4, scale_lr_by_batch=False,
        warmup_steps=200, schedule="cosine", weight_decay=0.1,
        grad_clip_norm=1.0, global_batch=8, total_steps=5000,
        eval_every=500, compute_dtype="bfloat16",
        # 32k tokens x 32k vocab: the dense-logits loss alone is 4 GB f32
        # per sequence — the chunked fused head keeps it out of HBM.
        fused_xent=True,
    )


def _lm_smoke() -> TrainConfig:
    """Tiny seq-parallel LM for tests/CI: 2-way data x 4-way seq on the
    8-device virtual mesh."""
    return TrainConfig(
        name="lm_smoke", model="transformer-lm",
        model_kwargs={"tiny": True, "seq_mode": "ring", "vocab_size": 64},
        dataset="lm_text",
        dataset_kwargs={"seq_len": 64, "vocab_size": 64, "synthetic_size": 64},
        shard_seq=True, mesh=MeshSpec(data=2, seq=4),
        optimizer="adamw", base_lr=3e-3, scale_lr_by_batch=False,
        schedule="constant", global_batch=8, total_steps=40,
        eval_every=20, eval_batches=2, log_every=10, ckpt_every=20,
    )


def _lm_pp_smoke() -> TrainConfig:
    """Tiny pipeline-parallel LM for tests/CI: 2-way data x 4-way pipe on
    the 8-device virtual mesh (ScanBlockLM, beyond-reference capability)."""
    return TrainConfig(
        name="lm_pp_smoke", model="transformer-lm-pp",
        model_kwargs={"tiny": True, "vocab_size": 64, "num_layers": 4},
        dataset="lm_text",
        dataset_kwargs={"seq_len": 64, "vocab_size": 64, "synthetic_size": 64},
        mesh=MeshSpec(data=2, pipe=4), pp_microbatches=2,
        optimizer="adamw", base_lr=3e-3, scale_lr_by_batch=False,
        schedule="constant", global_batch=8, total_steps=40,
        eval_every=20, eval_batches=2, log_every=10, ckpt_every=20,
    )


def _smoke() -> TrainConfig:
    """Tiny end-to-end config for tests/CI (not a reference workload)."""
    return TrainConfig(
        name="smoke", model="convnet", dataset="mnist",
        dataset_kwargs={"synthetic_size": 512}, optimizer="sgd", base_lr=0.02,
        scale_lr_by_batch=False, schedule="constant", global_batch=32,
        total_steps=30, eval_every=15, eval_batches=2, log_every=5,
        ckpt_every=10,
    )


WORKLOADS = {
    "mnist_single": _mnist_single,
    "cifar10_resnet18": _cifar10_resnet18,
    "imagenet_resnet50": _imagenet_resnet50,
    "glue_bert": _glue_bert,
    "glue_bert_mnli": _glue_bert_mnli,
    "glue_bert_stsb": _glue_bert_stsb,
    "glue_bert_cola": _glue_bert_cola,
    "imagenet_resnet50_pod": _imagenet_resnet50_pod,
    "lm_long": _lm_long,
    "lm_smoke": _lm_smoke,
    "lm_pp_smoke": _lm_pp_smoke,
    "smoke": _smoke,
}


def get_config(name: str) -> TrainConfig:
    if name not in WORKLOADS:
        raise ValueError(f"unknown config {name!r}; have {sorted(WORKLOADS)}")
    return WORKLOADS[name]()
