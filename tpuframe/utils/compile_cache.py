"""Shared persistent-compilation-cache startup helper.

The JAX persistent compile cache was wired only into bench.py; this moves
it into one helper used by ``train.py``, ``launch/launcher.py`` and
``bench.py`` — so PR 2's preemption relaunches and crash-loop restarts
stop recompiling every program from scratch.  Cache traffic is surfaced
as process-wide counters in ``obs.metrics``:

    compile_cache.hits    — programs served from the on-disk cache
    compile_cache.misses  — fresh compiles written to it

(train.py folds both into its final metrics next to the ``retry.*``
counters, so a warm restart is visible in the run log.)

Knobs:
    TPUFRAME_COMPILE_CACHE        cache dir; "" / "0" / "off" disables
                                  (default <repo>/.xla_cache — bench.py's
                                  long-standing location)
    TPUFRAME_COMPILE_CACHE_MIN_S  min compile seconds worth persisting
                                  (default 1.0, bench.py's value)
"""

from __future__ import annotations

import os

_ENV_DIR = "TPUFRAME_COMPILE_CACHE"
_ENV_MIN_S = "TPUFRAME_COMPILE_CACHE_MIN_S"
_OFF = ("", "0", "off", "none")

_LISTENER_INSTALLED = False
_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_MISS_EVENT = "/jax/compilation_cache/cache_misses"


def safe_for_key_outputs() -> bool:
    """Whether this jax can serve programs whose OUTPUTS are typed PRNG
    keys (e.g. the train step's ``TrainState.rng``) from the persistent
    cache.  jax 0.4.x hard-aborts (C++ CHECK in the key result handler)
    when such an executable is deserialized over a mesh — unprobeable at
    runtime, so gate on the same jax>=0.6 capability marker the analysis
    strategies use.  bench-style programs without key outputs are safe on
    every version and need no gate."""
    import jax

    return hasattr(jax, "typeof")


def outputs_cache_safe(out_avals) -> bool:
    """Whether a program with these output avals (a pytree from
    ``jax.eval_shape``) is persistent-cache safe on THIS jax.  On
    jax>=0.6 everything is; on older jax only programs whose outputs
    carry no extended dtype (typed PRNG keys) are — exactly the check
    the serving engine runs on its decode step, whose donated KV buffers
    make an executable-deserialization abort extra expensive."""
    if safe_for_key_outputs():
        return True
    import jax

    extended = getattr(jax.dtypes, "extended", None)
    for leaf in jax.tree_util.tree_leaves(out_avals):
        dtype = getattr(leaf, "dtype", None)
        if dtype is None:
            continue
        if extended is not None and jax.numpy.issubdtype(dtype, extended):
            return False
    return True


def reset_cache() -> bool:
    """Drop jax's latched in-process view of the persistent cache so the
    next compile re-initializes against the currently-configured dir.
    Needed by anything that re-points the cache mid-process (the serve
    loadgen cache-hit test, tune's AOT harness).  Returns False when the
    private hook is unavailable (then only early-set dirs engage)."""
    try:
        from jax._src import compilation_cache as _cc
        _cc.reset_cache()
        return True
    except Exception:  # noqa: BLE001 — private API
        return False


def disable() -> None:
    """Actively disarm the persistent cache for this process: clear the
    configured dir and drop the latched singleton so the next compile
    re-initializes cacheless.  Callers that merely *decline* to enable()
    are not safe — another in-process component (an LMEngine built by a
    colocated-serving test, say) may have enabled the cache already, and
    a cache hit on a keyed-output executable is a hard C++ abort on
    jax < 0.6."""
    import jax

    jax.config.update("jax_compilation_cache_dir", None)
    reset_cache()


def default_cache_dir() -> str:
    return os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), ".xla_cache")


def enable(cache_dir: str | None = None, *,
           min_compile_secs: float | None = None,
           min_entry_size_bytes: int | None = None) -> str | None:
    """Turn on the persistent compilation cache + hit/miss counters.

    Returns the cache dir, or None when disabled via env.  Call before
    the first compile; safe to call more than once (jax.config updates
    are idempotent, the monitoring listener installs once).  jax is
    imported lazily so stdlib-only callers (bench.py module level) can
    import this module freely.
    """
    env = os.environ.get(_ENV_DIR)
    if env is not None and env.strip().lower() in _OFF:
        return None
    cache_dir = cache_dir or env or default_cache_dir()

    import jax

    jax.config.update("jax_compilation_cache_dir", cache_dir)
    if min_compile_secs is None:
        min_compile_secs = float(os.environ.get(_ENV_MIN_S, "1.0"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      min_compile_secs)
    if min_entry_size_bytes is not None:
        jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                          min_entry_size_bytes)
    # If anything compiled before enable(), jax has already latched its
    # cache singleton as "no cache" and ignores the dir we just set —
    # reset so the next compile re-initializes against it.
    reset_cache()
    _install_listener()
    return cache_dir


def _install_listener() -> None:
    global _LISTENER_INSTALLED
    if _LISTENER_INSTALLED:
        return
    import jax

    from tpuframe.obs import metrics

    from tpuframe.obs import events as obs_events

    def _on_event(event: str, **kwargs) -> None:
        if event == _HIT_EVENT:
            metrics.bump("compile_cache.hits")
            obs_events.emit("compile", cached=True, source="persistent_cache")
        elif event == _MISS_EVENT:
            metrics.bump("compile_cache.misses")
            obs_events.emit("compile", cached=False,
                            source="persistent_cache")

    jax.monitoring.register_event_listener(_on_event)
    _LISTENER_INSTALLED = True
