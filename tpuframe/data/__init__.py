"""Input pipeline (L3) — TPU-native replacement for the reference's GCS data
loader + DistributedSampler sharding (SURVEY.md §3a).

Per-host dataset sharding (``num_shards=process_count, shard=process_index``)
replaces the reference's per-rank ``DistributedSampler``; batches land on
device pre-sharded over the mesh's batch axes via ``ShardedLoader``.
"""

from tpuframe.data.datasets import (  # noqa: F401
    ArrayDataset,
    cifar10,
    glue_sst2,
    imagenet,
    mnist,
)
from tpuframe.data.pipeline import ShardedLoader  # noqa: F401
from tpuframe.data import gcs  # noqa: F401
