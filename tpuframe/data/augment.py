"""On-device training augmentation (TrainConfig.augment).

The reference's input pipeline augments in the torch DataLoader workers
(RandomResizedCrop + RandomHorizontalFlip for ImageNet; pad-4 + random
crop + flip for CIFAR).  The TPU-native home for this work is INSIDE the
compiled train step: the ops are elementwise/slice-level (XLA fuses them
into the input read), they run on the uint8 batch BEFORE on-device
normalization (cheapest dtype), and the randomness rides the step rng —
per-step deterministic, so checkpoint-resume reproduces the exact batch
stream (tests/test_train_harness resume-exactness holds with
augmentation on).

Modes:
  * ``"flip"``          — per-image random horizontal flip (ImageNet
                          storage is already the crop geometry).
  * ``"pad_crop_flip"`` — zero-pad 4px, random crop back to the stored
                          size, then flip: the classic CIFAR recipe.
  * ``"crop_flip"``     — random crop to ``crop`` from larger stored
                          images (prepare_imagenet with a larger
                          --image-size), then flip.
  * ``"none"``          — identity.

Eval batches are never RANDOMLY augmented; the only eval-side entry
point is :func:`center_crop`, the deterministic geometry companion the
harness applies when ``crop_flip`` trains from larger stored images.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def random_flip(images: jax.Array, rng: jax.Array) -> jax.Array:
    """Per-image horizontal flip with p=0.5.  [B, H, W, C], any dtype."""
    flip = jax.random.bernoulli(rng, 0.5, (images.shape[0],))
    return jnp.where(flip[:, None, None, None], images[:, :, ::-1, :],
                     images)


def _random_crop(images: jax.Array, rng: jax.Array, crop_h: int,
                 crop_w: int) -> jax.Array:
    b, h, w, c = images.shape
    ry, rx = jax.random.split(rng)
    oy = jax.random.randint(ry, (b,), 0, h - crop_h + 1)
    ox = jax.random.randint(rx, (b,), 0, w - crop_w + 1)

    def one(img, y, x):
        return lax.dynamic_slice(img, (y, x, 0), (crop_h, crop_w, c))

    return jax.vmap(one)(images, oy, ox)


def apply(mode: str, images: jax.Array, rng: jax.Array,
          *, crop: int | None = None) -> jax.Array:
    """Dispatch on the config's ``augment`` mode (train path only)."""
    if mode == "none":
        return images
    r_crop, r_flip = jax.random.split(rng)
    if mode == "flip":
        return random_flip(images, r_flip)
    if mode == "pad_crop_flip":
        h, w = images.shape[1], images.shape[2]
        padded = jnp.pad(images, ((0, 0), (4, 4), (4, 4), (0, 0)))
        out = _random_crop(padded, r_crop, h, w)
        return random_flip(out, r_flip)
    if mode == "crop_flip":
        if crop is None:
            raise ValueError("crop_flip needs the model input size")
        if images.shape[1] < crop or images.shape[2] < crop:
            raise ValueError(
                f"crop_flip: stored images {images.shape[1:3]} smaller "
                f"than crop {crop} — prepare shards with a larger "
                f"--image-size")
        out = _random_crop(images, r_crop, crop, crop)
        return random_flip(out, r_flip)
    raise ValueError(f"unknown augment mode {mode!r}; expected none | flip "
                     f"| pad_crop_flip | crop_flip")


def center_crop(images: jax.Array, crop: int) -> jax.Array:
    """Deterministic eval-side companion of ``crop_flip``: when training
    random-crops from larger stored images, eval center-crops to the same
    geometry (the standard train/eval pairing)."""
    h, w = images.shape[1], images.shape[2]
    if h == crop and w == crop:
        return images
    if h < crop or w < crop:
        # Mirror apply()'s guard: a silent negative-offset slice would
        # return a tiny corner crop and eval would report garbage.
        raise ValueError(
            f"center_crop: stored images {images.shape[1:3]} smaller than "
            f"crop {crop} — prepare shards with a larger --image-size")
    oy, ox = (h - crop) // 2, (w - crop) // 2
    return images[:, oy:oy + crop, ox:ox + crop, :]
