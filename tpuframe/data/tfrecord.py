"""Dependency-free TFRecord + tf.train.Example codec.

Why: the ecosystem's ImageNet-on-GCS datasets overwhelmingly ship as
TFRecord shards of ``tf.Example`` protos (the format every TF/JAX input
pipeline in the genre reads), but this image carries no tensorflow.  The
wire formats are small and stable, so the framework implements them
directly:

  * TFRecord framing (per record):
        uint64  length        (little-endian)
        uint32  masked_crc32c(length bytes)
        bytes   data[length]
        uint32  masked_crc32c(data)
    with ``masked(c) = ((c >> 15 | c << 17) + 0xa282ead8) mod 2^32`` and
    crc32c the Castagnoli CRC — the SAME polynomial the checkpoint
    integrity path already implements natively
    (:func:`tpuframe.native.crc32c`).

  * ``tf.train.Example`` — three protobuf message levels (Example →
    Features → map<string, Feature>, Feature = oneof
    bytes_list/float_list/int64_list), decoded with a minimal
    wire-format reader (varint, length-delimited, fixed32/64; packed and
    unpacked repeated scalars).

Consumed by ``tpuframe.data.prepare_imagenet --src-tfrecords`` (offline
JPEG decode, per SURVEY.md §7 hard part 2 — training hosts stream dense
npy shards, never TFRecords); the encoder half exists for tests and for
exporting back into TF-ecosystem tooling.
"""

from __future__ import annotations

import struct
from typing import Iterable, Iterator

import numpy as np

from tpuframe import native

_MASK_DELTA = 0xA282EAD8


def _masked_crc(data: bytes) -> int:
    c = native.crc32c(data)
    return (((c >> 15) | (c << 17)) + _MASK_DELTA) & 0xFFFFFFFF


def iter_records(data: bytes, *, verify_crc: bool = True) -> Iterator[bytes]:
    """Yield record payloads from TFRecord-framed bytes.

    Raises ValueError on truncation or (with ``verify_crc``) a CRC
    mismatch — corrupt shards must fail loudly, not truncate silently.
    """
    pos, n = 0, len(data)
    while pos < n:
        if pos + 12 > n:
            raise ValueError(f"truncated TFRecord header at byte {pos}")
        (length,) = struct.unpack_from("<Q", data, pos)
        (len_crc,) = struct.unpack_from("<I", data, pos + 8)
        if verify_crc and _masked_crc(data[pos:pos + 8]) != len_crc:
            raise ValueError(f"TFRecord length CRC mismatch at byte {pos}")
        start = pos + 12
        end = start + length
        if end + 4 > n:
            raise ValueError(f"truncated TFRecord payload at byte {pos}")
        payload = data[start:end]
        (data_crc,) = struct.unpack_from("<I", data, end)
        if verify_crc and _masked_crc(payload) != data_crc:
            raise ValueError(f"TFRecord data CRC mismatch at byte {pos}")
        yield payload
        pos = end + 4


def write_records(records: Iterable[bytes]) -> bytes:
    out = bytearray()
    for rec in records:
        header = struct.pack("<Q", len(rec))
        out += header
        out += struct.pack("<I", _masked_crc(header))
        out += rec
        out += struct.pack("<I", _masked_crc(rec))
    return bytes(out)


# ---------------------------------------------------------------------------
# minimal protobuf wire reader/writer
# ---------------------------------------------------------------------------


def _read_varint(buf: bytes, pos: int) -> tuple[int, int]:
    result = shift = 0
    while True:
        if pos >= len(buf):
            raise ValueError("truncated varint")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 63:
            raise ValueError("varint too long")


def _write_varint(value: int) -> bytes:
    out = bytearray()
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _fields(buf: bytes) -> Iterator[tuple[int, int, bytes | int]]:
    """Yield (field_number, wire_type, value) — value is bytes for
    length-delimited fields, int for varint/fixed."""
    pos = 0
    while pos < len(buf):
        key, pos = _read_varint(buf, pos)
        field, wt = key >> 3, key & 7
        if wt == 0:                      # varint
            v, pos = _read_varint(buf, pos)
            yield field, wt, v
        elif wt == 2:                    # length-delimited
            ln, pos = _read_varint(buf, pos)
            if pos + ln > len(buf):
                raise ValueError("truncated length-delimited field")
            yield field, wt, buf[pos:pos + ln]
            pos += ln
        elif wt == 5:                    # fixed32
            (v,) = struct.unpack_from("<I", buf, pos)
            pos += 4
            yield field, wt, v
        elif wt == 1:                    # fixed64
            (v,) = struct.unpack_from("<Q", buf, pos)
            pos += 8
            yield field, wt, v
        else:
            raise ValueError(f"unsupported wire type {wt}")


def parse_example(data: bytes) -> dict[str, object]:
    """tf.train.Example bytes → {name: list[bytes] | np.ndarray}.

    bytes_list → list of bytes; float_list → float32 ndarray;
    int64_list → int64 ndarray.  Packed and unpacked repeated encodings
    both accepted (TF writers emit packed for numeric lists).
    """
    features: dict[str, object] = {}
    for f_ex, wt, v in _fields(data):
        if f_ex != 1 or wt != 2:
            continue                     # Example.features
        assert isinstance(v, bytes)
        for f_fs, wt2, entry in _fields(v):
            if f_fs != 1 or wt2 != 2:
                continue                 # Features.feature map entry
            assert isinstance(entry, bytes)
            name, feat = None, b""
            for f_e, _, ev in _fields(entry):
                if f_e == 1:
                    name = ev.decode("utf-8")   # type: ignore[union-attr]
                elif f_e == 2:
                    feat = ev
            if name is None:
                continue
            features[name] = _parse_feature(feat)  # type: ignore[arg-type]
    return features


def _parse_feature(feat: bytes):
    for f, wt, v in _fields(feat):
        if f == 1:                       # BytesList
            out_b = []
            assert isinstance(v, bytes)
            for ff, _, vv in _fields(v):
                if ff == 1:
                    out_b.append(vv)
            return out_b
        if f == 2:                       # FloatList
            vals: list[float] = []
            assert isinstance(v, bytes)
            for ff, wt2, vv in _fields(v):
                if ff != 1:
                    continue
                if wt2 == 2:             # packed
                    vals.extend(np.frombuffer(vv, "<f4").tolist())
                else:                    # unpacked fixed32
                    vals.append(struct.unpack("<f", struct.pack("<I", vv))[0])
            return np.asarray(vals, np.float32)
        if f == 3:                       # Int64List
            ivals: list[int] = []
            assert isinstance(v, bytes)
            for ff, wt2, vv in _fields(v):
                if ff != 1:
                    continue
                if wt2 == 2:             # packed varints
                    pos = 0
                    while pos < len(vv):
                        x, pos = _read_varint(vv, pos)
                        ivals.append(_to_signed64(x))
                else:
                    ivals.append(_to_signed64(vv))
            return np.asarray(ivals, np.int64)
    return []


def _to_signed64(x: int) -> int:
    return x - (1 << 64) if x >= (1 << 63) else x


def _ld(field: int, payload: bytes) -> bytes:
    return _write_varint((field << 3) | 2) + _write_varint(len(payload)) \
        + payload


def build_example(features: dict[str, object]) -> bytes:
    """Inverse of :func:`parse_example` (packed numeric encodings)."""
    entries = b""
    for name, value in features.items():
        if isinstance(value, (list, tuple)) and (
                not value or isinstance(value[0], (bytes, bytearray))):
            body = b"".join(_ld(1, bytes(b)) for b in value)
            feat = _ld(1, body)
        else:
            arr = np.asarray(value)
            if arr.dtype.kind == "f":
                packed = arr.astype("<f4").tobytes()
                feat = _ld(2, _ld(1, packed))
            elif arr.dtype.kind in "iu":
                packed = b"".join(
                    _write_varint(int(x) & 0xFFFFFFFFFFFFFFFF)
                    for x in arr.reshape(-1))
                feat = _ld(3, _ld(1, packed))
            else:
                raise TypeError(f"unsupported feature {name}: {arr.dtype}")
        entries += _ld(1, _ld(1, name.encode()) + _ld(2, feat))
    return _ld(1, entries)
