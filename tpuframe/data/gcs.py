"""GCS-or-local filesystem abstraction.

The reference streams training data from a GCS bucket and uploads checkpoints
to one (SURVEY.md §3a "GCS data loader", §4.4).  This module gives the rest of
the framework one path API that works on ``gs://bucket/key`` URIs when the
``google-cloud-storage`` client is importable and on plain local paths always —
so every pipeline and checkpoint codepath is testable in the zero-egress
sandbox with local directories standing in for buckets.

Every operation runs under a :class:`tpuframe.resilience.policy.RetryPolicy`
(exponential backoff + decorrelated jitter + deadline; transient-only
classification) and passes through a named fault-injection seam
(``gcs_read``/``gcs_write``/``gcs_list``/...; see
tpuframe.resilience.faults) so flaky-storage recovery is deterministically
testable.  Raw ``google.cloud.storage`` blob calls live ONLY in this
module — lint rule TF105 keeps un-retried client calls out of the rest of
the tree.
"""

from __future__ import annotations

import os
import shutil
from pathlib import Path

from tpuframe.resilience import faults
from tpuframe.resilience.policy import RetryPolicy

# One policy for all storage ops.  Env knobs exist for ops teams tuning a
# genuinely bad network day, not for code: code that needs different
# semantics should construct its own policy.
_POLICY = RetryPolicy(
    max_attempts=int(os.environ.get("TPUFRAME_IO_RETRIES", "5")),
    base_delay_s=float(os.environ.get("TPUFRAME_IO_RETRY_BASE_S", "0.05")),
    max_delay_s=float(os.environ.get("TPUFRAME_IO_RETRY_MAX_S", "5.0")),
    attempt_timeout_s=float(os.environ.get("TPUFRAME_IO_TIMEOUT_S", "60")),
    deadline_s=float(os.environ.get("TPUFRAME_IO_DEADLINE_S", "120")),
)


def is_gcs_path(path: str) -> bool:
    return str(path).startswith("gs://")


def _gcs_client():
    try:
        from google.cloud import storage  # type: ignore

        return storage.Client()
    except Exception as e:
        raise RuntimeError(
            "gs:// path used but no usable google-cloud-storage client "
            "(install it and set up application-default credentials on the "
            "TPU-VM, or use a local path): " + repr(e)
        ) from e


def _split(path: str) -> tuple[str, str]:
    rest = path[len("gs://"):]
    bucket, _, key = rest.partition("/")
    return bucket, key


def _timeout() -> float | None:
    return _POLICY.attempt_timeout_s


def read_bytes(path: str) -> bytes:
    return _POLICY.call(_read_bytes_once, path, op="gcs_read")


def _read_bytes_once(path: str) -> bytes:
    faults.fire("gcs_read")
    if is_gcs_path(path):
        bucket, key = _split(path)
        return (_gcs_client().bucket(bucket).blob(key)
                .download_as_bytes(timeout=_timeout()))
    return Path(path).read_bytes()


def write_bytes(path: str, data: bytes) -> None:
    # Degraded-storage seam, outside the retry wrapper: ``slow_gcs``
    # models a slow-but-healthy backend, so the delay must not eat the
    # attempt timeout or register as a retryable failure.
    faults.fire("slow_gcs")
    _POLICY.call(_write_bytes_once, path, data, op="gcs_write")


def _write_bytes_once(path: str, data: bytes) -> None:
    faults.fire("gcs_write")
    if is_gcs_path(path):
        bucket, key = _split(path)
        (_gcs_client().bucket(bucket).blob(key)
         .upload_from_string(data, timeout=_timeout()))
        return
    p = Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    tmp = p.with_name(p.name + ".tmp")
    tmp.write_bytes(data)
    os.replace(tmp, p)  # atomic on POSIX — no torn checkpoint files


def exists(path: str) -> bool:
    return _POLICY.call(_exists_once, path, op="gcs_stat")


def _exists_once(path: str) -> bool:
    faults.fire("gcs_stat")
    if is_gcs_path(path):
        bucket, key = _split(path)
        return _gcs_client().bucket(bucket).blob(key).exists(
            timeout=_timeout())
    return Path(path).exists()


def listdir(path: str) -> list[str]:
    """Immediate children (names, not full paths)."""
    return _POLICY.call(_listdir_once, path, op="gcs_list")


def _listdir_once(path: str) -> list[str]:
    faults.fire("gcs_list")
    if is_gcs_path(path):
        bucket, key = _split(path)
        prefix = key.rstrip("/") + "/" if key else ""
        it = _gcs_client().list_blobs(bucket, prefix=prefix, delimiter="/",
                                      timeout=_timeout())
        names = [os.path.basename(b.name) for b in it]
        names += [p.rstrip("/").split("/")[-1] for p in it.prefixes]
        return sorted(n for n in names if n)
    p = Path(path)
    return sorted(os.listdir(p)) if p.exists() else []


def makedirs(path: str) -> None:
    if not is_gcs_path(path):
        Path(path).mkdir(parents=True, exist_ok=True)


def mtime(path: str) -> float:
    """Last-modified time (unix seconds) of an object/file; 0.0 if absent.
    GCS timestamps are server-side, so cross-host comparisons are sound."""
    return _POLICY.call(_mtime_once, path, op="gcs_stat")


def _mtime_once(path: str) -> float:
    faults.fire("gcs_stat")
    if is_gcs_path(path):
        bucket, key = _split(path)
        blob = _gcs_client().bucket(bucket).get_blob(key,
                                                     timeout=_timeout())
        return blob.updated.timestamp() if blob and blob.updated else 0.0
    try:
        return os.path.getmtime(path)
    except FileNotFoundError:
        return 0.0


def delete(path: str) -> None:
    """Delete one object/file (no-op if absent)."""
    _POLICY.call(_delete_once, path, op="gcs_delete")


def _delete_once(path: str) -> None:
    faults.fire("gcs_delete")
    if is_gcs_path(path):
        bucket, key = _split(path)
        blob = _gcs_client().bucket(bucket).blob(key)
        if blob.exists(timeout=_timeout()):
            blob.delete(timeout=_timeout())
        return
    try:
        os.remove(path)
    except FileNotFoundError:
        pass


def delete_tree(path: str) -> None:
    _POLICY.call(_delete_tree_once, path, op="gcs_delete")


def _delete_tree_once(path: str) -> None:
    faults.fire("gcs_delete")
    if is_gcs_path(path):
        bucket, key = _split(path)
        client = _gcs_client()
        for blob in client.list_blobs(bucket, prefix=key.rstrip("/") + "/"):
            blob.delete(timeout=_timeout())
        return
    shutil.rmtree(path, ignore_errors=True)


def rename_tree(src: str, dst: str) -> None:
    """Rename a directory/prefix (the corrupt-checkpoint quarantine path:
    ``step_N`` → ``step_N.corrupt``).  Local rename is atomic; the GCS
    variant is per-object rename — a retried partial rename re-lists and
    finishes, which is all quarantine needs (restore ignores both the
    partially- and fully-renamed prefix, since COMMIT moves too)."""
    _POLICY.call(_rename_tree_once, src, dst, op="gcs_write")


def _rename_tree_once(src: str, dst: str) -> None:
    faults.fire("gcs_write")
    if is_gcs_path(src):
        bucket, key = _split(src)
        _, dst_key = _split(dst)
        client = _gcs_client()
        b = client.bucket(bucket)
        for blob in client.list_blobs(bucket, prefix=key.rstrip("/") + "/"):
            suffix = blob.name[len(key.rstrip("/")):]
            b.rename_blob(blob, dst_key.rstrip("/") + suffix,
                          timeout=_timeout())
        return
    os.replace(src, dst)


def join(*parts: str) -> str:
    if parts and is_gcs_path(parts[0]):
        return "/".join(p.strip("/") if i else p.rstrip("/")
                        for i, p in enumerate(parts))
    return os.path.join(*parts)
