"""GCS-or-local filesystem abstraction.

The reference streams training data from a GCS bucket and uploads checkpoints
to one (SURVEY.md §3a "GCS data loader", §4.4).  This module gives the rest of
the framework one path API that works on ``gs://bucket/key`` URIs when the
``google-cloud-storage`` client is importable and on plain local paths always —
so every pipeline and checkpoint codepath is testable in the zero-egress
sandbox with local directories standing in for buckets.
"""

from __future__ import annotations

import os
import shutil
from pathlib import Path


def is_gcs_path(path: str) -> bool:
    return str(path).startswith("gs://")


def _gcs_client():
    try:
        from google.cloud import storage  # type: ignore

        return storage.Client()
    except Exception as e:
        raise RuntimeError(
            "gs:// path used but no usable google-cloud-storage client "
            "(install it and set up application-default credentials on the "
            "TPU-VM, or use a local path): " + repr(e)
        ) from e


def _split(path: str) -> tuple[str, str]:
    rest = path[len("gs://"):]
    bucket, _, key = rest.partition("/")
    return bucket, key


def read_bytes(path: str) -> bytes:
    if is_gcs_path(path):
        bucket, key = _split(path)
        return _gcs_client().bucket(bucket).blob(key).download_as_bytes()
    return Path(path).read_bytes()


def write_bytes(path: str, data: bytes) -> None:
    if is_gcs_path(path):
        bucket, key = _split(path)
        _gcs_client().bucket(bucket).blob(key).upload_from_string(data)
        return
    p = Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    tmp = p.with_name(p.name + ".tmp")
    tmp.write_bytes(data)
    os.replace(tmp, p)  # atomic on POSIX — no torn checkpoint files


def exists(path: str) -> bool:
    if is_gcs_path(path):
        bucket, key = _split(path)
        return _gcs_client().bucket(bucket).blob(key).exists()
    return Path(path).exists()


def listdir(path: str) -> list[str]:
    """Immediate children (names, not full paths)."""
    if is_gcs_path(path):
        bucket, key = _split(path)
        prefix = key.rstrip("/") + "/" if key else ""
        it = _gcs_client().list_blobs(bucket, prefix=prefix, delimiter="/")
        names = [os.path.basename(b.name) for b in it]
        names += [p.rstrip("/").split("/")[-1] for p in it.prefixes]
        return sorted(n for n in names if n)
    p = Path(path)
    return sorted(os.listdir(p)) if p.exists() else []


def makedirs(path: str) -> None:
    if not is_gcs_path(path):
        Path(path).mkdir(parents=True, exist_ok=True)


def mtime(path: str) -> float:
    """Last-modified time (unix seconds) of an object/file; 0.0 if absent.
    GCS timestamps are server-side, so cross-host comparisons are sound."""
    if is_gcs_path(path):
        bucket, key = _split(path)
        blob = _gcs_client().bucket(bucket).get_blob(key)
        return blob.updated.timestamp() if blob and blob.updated else 0.0
    try:
        return os.path.getmtime(path)
    except OSError:
        return 0.0


def delete(path: str) -> None:
    """Delete one object/file (no-op if absent)."""
    if is_gcs_path(path):
        bucket, key = _split(path)
        blob = _gcs_client().bucket(bucket).blob(key)
        if blob.exists():
            blob.delete()
        return
    try:
        os.remove(path)
    except FileNotFoundError:
        pass


def delete_tree(path: str) -> None:
    if is_gcs_path(path):
        bucket, key = _split(path)
        client = _gcs_client()
        for blob in client.list_blobs(bucket, prefix=key.rstrip("/") + "/"):
            blob.delete()
        return
    shutil.rmtree(path, ignore_errors=True)


def join(*parts: str) -> str:
    if parts and is_gcs_path(parts[0]):
        return "/".join(p.strip("/") if i else p.rstrip("/")
                        for i, p in enumerate(parts))
    return os.path.join(*parts)
