"""WordPiece tokenizer — the real GLUE text path, no HF dependency.

Reference parity (SURVEY.md §3a "Model defs": BERT-base for GLUE via HF
transformers): the reference tokenizes SST-2 with BERT's WordPiece.  This is
a from-scratch implementation of the same algorithm — BERT "basic"
pre-tokenization (lowercase + accent strip for uncased vocabs, punctuation
splitting, CJK isolation) followed by greedy longest-match-first WordPiece
with ``##`` continuation pieces — driven by a standard ``vocab.txt`` (one
token per line, local path or ``gs://``).

Output matches ``transformers.BertTokenizer`` token-for-token on the same
vocab (asserted in ``tests/test_wordpiece.py``), so checkpoints/datasets are
interchangeable with the reference's pipeline.
"""

from __future__ import annotations

import unicodedata

import numpy as np

from tpuframe.data import gcs

_PAD, _UNK, _CLS, _SEP = "[PAD]", "[UNK]", "[CLS]", "[SEP]"


def _is_punctuation(ch: str) -> bool:
    cp = ord(ch)
    # ASCII ranges BERT treats as punctuation even where unicode doesn't
    # (e.g. ``$``, ``^``, backtick).
    if (33 <= cp <= 47 or 58 <= cp <= 64 or 91 <= cp <= 96
            or 123 <= cp <= 126):
        return True
    return unicodedata.category(ch).startswith("P")


def _is_cjk(cp: int) -> bool:
    return (0x4E00 <= cp <= 0x9FFF or 0x3400 <= cp <= 0x4DBF
            or 0x20000 <= cp <= 0x2A6DF or 0x2A700 <= cp <= 0x2B73F
            or 0x2B740 <= cp <= 0x2B81F or 0x2B820 <= cp <= 0x2CEAF
            or 0xF900 <= cp <= 0xFAFF or 0x2F800 <= cp <= 0x2FA1F)


def _is_control(ch: str) -> bool:
    if ch in ("\t", "\n", "\r"):
        return False
    return unicodedata.category(ch).startswith("C")


class WordPieceTokenizer:
    """Vocab-file-driven BERT tokenizer.

    ``vocab`` may be a path (local or gs://) to a ``vocab.txt`` or an
    already-built ``{token: id}`` dict.  ``lowercase=True`` matches the
    ``bert-base-uncased`` convention the reference's GLUE recipe uses.
    """

    def __init__(self, vocab: str | dict, *, lowercase: bool = True,
                 max_chars_per_word: int = 100):
        if isinstance(vocab, str):
            lines = gcs.read_bytes(vocab).decode("utf-8").split("\n")
            if lines and lines[-1] == "":
                lines.pop()
            self.vocab = {tok: i for i, tok in enumerate(lines)}
        else:
            self.vocab = dict(vocab)
        self.lowercase = lowercase
        self.max_chars_per_word = max_chars_per_word
        for tok in (_PAD, _UNK, _CLS, _SEP):
            if tok not in self.vocab:
                raise ValueError(f"vocab is missing required token {tok!r}")
        self.pad_id = self.vocab[_PAD]
        self.unk_id = self.vocab[_UNK]
        self.cls_id = self.vocab[_CLS]
        self.sep_id = self.vocab[_SEP]

    # -- basic tokenization (BERT's pre-split) ------------------------------

    def _clean(self, text: str) -> str:
        out = []
        for ch in text:
            cp = ord(ch)
            if cp == 0 or cp == 0xFFFD or _is_control(ch):
                continue
            if _is_cjk(cp):
                out.append(f" {ch} ")
            elif unicodedata.category(ch) == "Zs" or ch in ("\t", "\n", "\r"):
                out.append(" ")
            else:
                out.append(ch)
        return "".join(out)

    def _split_word(self, word: str) -> list[str]:
        if self.lowercase:
            word = word.lower()
            word = "".join(ch for ch in unicodedata.normalize("NFD", word)
                           if unicodedata.category(ch) != "Mn")
        pieces, current = [], []
        for ch in word:
            if _is_punctuation(ch):
                if current:
                    pieces.append("".join(current))
                    current = []
                pieces.append(ch)
            else:
                current.append(ch)
        if current:
            pieces.append("".join(current))
        return pieces

    def basic_tokenize(self, text: str) -> list[str]:
        tokens = []
        for word in self._clean(text).split():
            tokens.extend(self._split_word(word))
        return tokens

    # -- wordpiece ----------------------------------------------------------

    def wordpiece(self, token: str) -> list[str]:
        """Greedy longest-match-first subword split; [UNK] when stuck."""
        if len(token) > self.max_chars_per_word:
            return [_UNK]
        pieces = []
        start = 0
        while start < len(token):
            end = len(token)
            found = None
            while start < end:
                piece = token[start:end]
                if start > 0:
                    piece = "##" + piece
                if piece in self.vocab:
                    found = piece
                    break
                end -= 1
            if found is None:
                return [_UNK]
            pieces.append(found)
            start = end
        return pieces

    def tokenize(self, text: str) -> list[str]:
        out = []
        for tok in self.basic_tokenize(text):
            out.extend(self.wordpiece(tok))
        return out

    # -- model-ready encoding ----------------------------------------------

    def encode(self, text_a: str, text_b: str | None = None, *,
               max_len: int = 128) -> dict[str, np.ndarray]:
        """[CLS] a [SEP] (b [SEP]) with padding/truncation — the classic BERT
        sequence-classification encoding."""
        ids_a = [self.vocab[t] for t in self.tokenize(text_a)]
        ids_b = [self.vocab[t] for t in self.tokenize(text_b)] if text_b else []
        if ids_b:
            # pair truncation: trim the longer side first; on ties HF's
            # 'longest_first' removes from the SECOND sequence (its condition
            # is strictly len(a) > len(b)), so match that exactly.
            while len(ids_a) + len(ids_b) > max_len - 3:
                (ids_a if len(ids_a) > len(ids_b) else ids_b).pop()
            ids = [self.cls_id] + ids_a + [self.sep_id] + ids_b + [self.sep_id]
            types = [0] * (len(ids_a) + 2) + [1] * (len(ids_b) + 1)
        else:
            ids_a = ids_a[: max_len - 2]
            ids = [self.cls_id] + ids_a + [self.sep_id]
            types = [0] * len(ids)
        mask = [1] * len(ids)
        pad = max_len - len(ids)
        return {
            "input_ids": np.asarray(ids + [self.pad_id] * pad, np.int32),
            "attention_mask": np.asarray(mask + [0] * pad, np.int32),
            "token_type_ids": np.asarray(types + [0] * pad, np.int32),
        }

    def encode_batch(self, texts: list, *, max_len: int = 128) -> dict:
        """Batch encode; each item is a string or an (a, b) pair."""
        encs = [self.encode(*((t,) if isinstance(t, str) else tuple(t)),
                            max_len=max_len) for t in texts]
        if not encs:
            empty = np.zeros((0, max_len), np.int32)
            return {"input_ids": empty, "attention_mask": empty.copy(),
                    "token_type_ids": empty.copy()}
        return {k: np.stack([e[k] for e in encs]) for k in encs[0]}

    def __call__(self, texts, **kwargs):
        """HF-tokenizer-shaped call (padding/truncation implied) so this drops
        into ``datasets._tokenize``'s ``tokenizer`` slot."""
        max_len = kwargs.get("max_length", 128)
        return self.encode_batch(list(texts), max_len=max_len)
