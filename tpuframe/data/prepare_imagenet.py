"""Offline ImageNet preparation: class-folder JPEGs OR TFRecord shards →
per-host npy shards.

SURVEY.md §7 hard part 2: decoding JPEGs on the training hosts would
bottleneck the input pipeline at pod scale, so decode/resize happens offline
(once), and training hosts stream dense arrays.  Output layout consumed by
``tpuframe.data.datasets.imagenet``:

    <out>/images_00000.npy   # uint8 [N, S, S, 3]
    <out>/labels_00000.npy   # int32 [N]
    ...

Shard count should be a multiple of the training host count (the loader
assigns whole files to hosts).  ``--out gs://bucket/path`` writes straight
to GCS via tpuframe.data.gcs.

CLI:
    python -m tpuframe.data.prepare_imagenet \\
        --src /data/imagenet/train --out gs://bucket/imagenet/train \\
        --image-size 224 --shard-size 8192 --workers 16

    # from standard tf.Example TFRecord shards (image/encoded +
    # image/class/label — the TF-ecosystem ImageNet layout; read with the
    # built-in dependency-free codec, tpuframe.data.tfrecord):
    python -m tpuframe.data.prepare_imagenet \\
        --src-tfrecords gs://bucket/imagenet-tfrecords/train \\
        --out gs://bucket/imagenet/train
"""

from __future__ import annotations

import argparse
import io
import os
from concurrent.futures import ProcessPoolExecutor

import numpy as np

from tpuframe.data import gcs


def _require_pil():
    try:
        from PIL import Image  # noqa: F401

        return Image
    except ImportError as e:  # pragma: no cover - PIL present in this image
        raise RuntimeError(
            "prepare_imagenet needs Pillow for JPEG decode; install it or "
            "pre-decode to npy shards with your own tooling") from e


def list_examples(src: str) -> tuple[list[tuple[str, int]], list[str]]:
    """[(path, label)] over a class-folder tree; labels follow sorted wnids
    (the torchvision ImageFolder convention the reference relies on)."""
    classes = sorted(
        d for d in os.listdir(src) if os.path.isdir(os.path.join(src, d)))
    if not classes:
        raise ValueError(f"no class folders under {src}")
    examples = []
    for label, wnid in enumerate(classes):
        folder = os.path.join(src, wnid)
        for name in sorted(os.listdir(folder)):
            if name.lower().endswith((".jpeg", ".jpg", ".png")):
                examples.append((os.path.join(folder, name), label))
    return examples, classes


def decode_one(args: tuple[str, int, int]) -> np.ndarray:
    """Resize shorter side to 1.14*size, center-crop size×size, uint8 RGB
    (the standard ResNet eval geometry; training-time augmentation is the
    loader's job, not storage's)."""
    path, size, _label = args
    with open(path, "rb") as fh:
        return _decode_jpeg_bytes(fh.read(), size)


def _decode_jpeg_bytes(raw: bytes, size: int) -> np.ndarray:
    """The ONE decode geometry (both prep paths route here): resize
    shorter side to 1.14*size, center-crop size×size, uint8 RGB."""
    Image = _require_pil()
    with Image.open(io.BytesIO(raw)) as im:
        im = im.convert("RGB")
        w, h = im.size
        scale = (int(size * 1.14) + 1) / min(w, h)
        im = im.resize((max(size, round(w * scale)),
                        max(size, round(h * scale))), Image.BILINEAR)
        w, h = im.size
        lo_x, lo_y = (w - size) // 2, (h - size) // 2
        im = im.crop((lo_x, lo_y, lo_x + size, lo_y + size))
        return np.asarray(im, np.uint8)


def iter_tfrecord_examples(src: str, *, label_offset: int = 0):
    """Yield (jpeg_bytes, label) from every ``*.tfrecord*``-named (or
    extensionless ``train-00000-of-01024``-style) shard under ``src``.

    Feature names follow the standard TF ImageNet layout: ``image/encoded``
    (JPEG bytes) and ``image/class/label``.  CLASSIC Inception-era shards
    store 1-BASED labels (1..1000): pass ``--label-offset 1`` to map them
    onto the 0-based space the model head uses — a passed-through 1-based
    label space would silently mistrain (class 1000 one-hots to an
    all-zero row).  Labels are validated non-negative after the offset so
    a wrong guess fails loudly."""
    from tpuframe.data import tfrecord as tfr

    names = sorted(n for n in gcs.listdir(src)
                   if "tfrecord" in n or "-of-" in n)
    if not names:
        raise ValueError(f"no TFRecord shards under {src}")
    for name in names:
        data = gcs.read_bytes(gcs.join(src, name))
        for rec in tfr.iter_records(data):
            ex = tfr.parse_example(rec)
            enc = ex.get("image/encoded")
            lbl = ex.get("image/class/label")
            if not enc or lbl is None or len(lbl) == 0:
                raise ValueError(
                    f"{name}: record missing image/encoded or "
                    f"image/class/label (got {sorted(ex)})")
            label = int(np.asarray(lbl).reshape(-1)[0]) - label_offset
            if label < 0:
                raise ValueError(
                    f"{name}: label {label + label_offset} with "
                    f"--label-offset {label_offset} goes negative — wrong "
                    f"offset for this shard family?")
            yield enc[0], label


def prepare_tfrecords(src: str, out: str, *, image_size: int = 224,
                      shard_size: int = 8192, workers: int = 8,
                      label_offset: int = 0,
                      limit: int | None = None) -> int:
    """TFRecord shards → the npy layout ``datasets.imagenet`` consumes.
    Returns the number of shards written.  Decoding parallelizes over
    ``workers`` processes like the --src path (full ImageNet is 1.28M
    JPEGs; serial PIL would be ~an order of magnitude slower)."""
    gcs.makedirs(out)
    n_shards = 0
    buf_img: list[np.ndarray] = []
    buf_lbl: list[int] = []

    def flush():
        nonlocal n_shards
        if not buf_img:
            return
        img = np.stack(buf_img)
        lbl = np.asarray(buf_lbl, np.int32)
        for prefix, arr in (("images", img), ("labels", lbl)):
            b = io.BytesIO()
            np.save(b, arr)
            gcs.write_bytes(gcs.join(out, f"{prefix}_{n_shards:05d}.npy"),
                            b.getvalue())
        n_shards += 1
        buf_img.clear()
        buf_lbl.clear()

    examples = iter_tfrecord_examples(src, label_offset=label_offset)
    if limit:
        import itertools

        examples = itertools.islice(examples, limit)
    if workers > 1:
        import itertools

        # Chunked streaming: full ImageNet is ~150 GB of JPEG bytes —
        # decode one shard-sized chunk at a time, never the whole set.
        with ProcessPoolExecutor(max_workers=workers) as pool:
            while True:
                chunk = list(itertools.islice(examples, shard_size))
                if not chunk:
                    break
                jpegs = [j for j, _ in chunk]
                for (_, label), arr in zip(
                        chunk, pool.map(_decode_jpeg_bytes, jpegs,
                                        [image_size] * len(jpegs),
                                        chunksize=64)):
                    buf_img.append(arr)
                    buf_lbl.append(label)
                    if len(buf_img) >= shard_size:
                        flush()
    else:
        for jpeg, label in examples:
            buf_img.append(_decode_jpeg_bytes(jpeg, image_size))
            buf_lbl.append(label)
            if len(buf_img) >= shard_size:
                flush()
    flush()
    return n_shards


def prepare(src: str, out: str, *, image_size: int = 224,
            shard_size: int = 8192, workers: int = 8,
            limit: int | None = None) -> int:
    """Returns the number of shards written."""
    examples, classes = list_examples(src)
    if limit:
        examples = examples[:limit]
    gcs.makedirs(out)
    gcs.write_bytes(gcs.join(out, "classes.txt"),
                    "\n".join(classes).encode())

    n_shards = 0
    buf_img: list[np.ndarray] = []
    buf_lbl: list[int] = []

    def flush():
        nonlocal n_shards
        if not buf_img:
            return
        img = np.stack(buf_img)
        lbl = np.asarray(buf_lbl, np.int32)
        for prefix, arr in (("images", img), ("labels", lbl)):
            b = io.BytesIO()
            np.save(b, arr)
            gcs.write_bytes(gcs.join(out, f"{prefix}_{n_shards:05d}.npy"),
                            b.getvalue())
        n_shards += 1
        buf_img.clear()
        buf_lbl.clear()

    tasks = [(path, image_size, label) for path, label in examples]
    if workers > 1:
        with ProcessPoolExecutor(max_workers=workers) as pool:
            for (path, _s, label), arr in zip(
                    tasks, pool.map(decode_one, tasks, chunksize=64)):
                buf_img.append(arr)
                buf_lbl.append(label)
                if len(buf_img) >= shard_size:
                    flush()
    else:
        for t in tasks:
            buf_img.append(decode_one(t))
            buf_lbl.append(t[2])
            if len(buf_img) >= shard_size:
                flush()
    flush()
    return n_shards


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--src", help="class-folder JPEG tree")
    p.add_argument("--src-tfrecords",
                   help="dir of tf.Example TFRecord shards (alternative "
                        "to --src; image/encoded + image/class/label)")
    p.add_argument("--label-offset", type=int, default=0,
                   help="subtracted from TFRecord labels; classic "
                        "Inception-era ImageNet shards are 1-based: "
                        "pass 1")
    p.add_argument("--out", required=True, help="output dir (may be gs://)")
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--shard-size", type=int, default=8192)
    p.add_argument("--workers", type=int, default=8)
    p.add_argument("--limit", type=int, default=None)
    a = p.parse_args(argv)
    if bool(a.src) == bool(a.src_tfrecords):
        p.error("exactly one of --src / --src-tfrecords is required")
    if a.src_tfrecords:
        n = prepare_tfrecords(a.src_tfrecords, a.out,
                              image_size=a.image_size,
                              shard_size=a.shard_size, workers=a.workers,
                              label_offset=a.label_offset, limit=a.limit)
    else:
        n = prepare(a.src, a.out, image_size=a.image_size,
                    shard_size=a.shard_size, workers=a.workers,
                    limit=a.limit)
    print(f"wrote {n} shards to {a.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
