"""Host→device input pipeline with per-host sharding and prefetch.

Reference path (SURVEY.md §4.1): torch DataLoader worker processes feed
per-rank batches; each rank's DataLoader holds a DistributedSampler shard.
TPU-native path: each *host* process iterates its shard of the dataset and
device_puts batches pre-sharded over the mesh's batch axes, one step ahead of
compute (double buffering) so infeed overlaps the running step — the role
Horovod leaves to DataLoader prefetch + CUDA streams.

Batch assembly inside the prefetch thread uses the multi-threaded C++ row
gather from ``tpuframe.native`` (GIL-released; see ArrayDataset.__getitem__),
with numpy fancy-indexing as the fallback when the native library is
unavailable.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding

from tpuframe.data.datasets import ArrayDataset
from tpuframe.parallel import mesh as mesh_lib


class ShardedLoader:
    """Iterates epoch-shuffled, host-sharded, device-put batches.

    Parameters
    ----------
    dataset: the FULL (logical) dataset; every host passes the same one and
        takes its shard internally — keeps the call site identical from 1 host
        to N hosts (the reference's DistributedSampler ergonomics).
    global_batch: across all chips; each host feeds global/process_count rows.
    mesh: batches are placed with the mesh's batch-axis sharding; None → plain
        committed host→device transfer (single-device config 1).
    """

    def __init__(
        self,
        dataset: ArrayDataset,
        global_batch: int,
        mesh: Mesh | None = None,
        *,
        shuffle: bool = True,
        seed: int = 0,
        prefetch: int = 2,
        shard_by_host: bool = True,
        partition=None,
        cast_floats=None,
        cast_keys: tuple = ("image",),
    ):
        # The remainder partial batch is always dropped: compiled SPMD steps
        # need static shapes, and a ragged final batch would both recompile
        # and shard unevenly. (The reference's DistributedSampler pads or
        # drops similarly.)
        self.global_batch = global_batch
        self.mesh = mesh
        self.shuffle = shuffle
        self.seed = seed
        self.prefetch = prefetch

        n_proc = jax.process_count()
        if global_batch % n_proc:
            raise ValueError(
                f"global batch {global_batch} not divisible by {n_proc} hosts")
        self.host_batch = global_batch // n_proc
        # Builders that load one shard file per host mark the dataset
        # host_presharded; re-sharding it here would drop (N-1)/N of the data.
        shard_by_host = (shard_by_host
                         and not getattr(dataset, "host_presharded", False))
        if mesh is not None:
            dp = mesh_lib.data_parallel_size(mesh)
            if global_batch % dp:
                raise ValueError(
                    f"global batch {global_batch} not divisible by "
                    f"data-parallel size {dp} (mesh {dict(mesh.shape)})")
        self.dataset = (dataset.shard(n_proc, jax.process_index())
                        if shard_by_host and n_proc > 1 else dataset)
        if len(self.dataset) < self.host_batch:
            raise ValueError(
                f"host shard has {len(self.dataset)} examples < host batch "
                f"{self.host_batch}")
        # ``partition``: PartitionSpec override (seq-parallel configs shard
        # the sequence dim too); trimmed per-leaf to the array rank at
        # device_put so mixed-rank batches work.
        self._partition = partition
        self._sharding = (mesh_lib.batch_sharding(mesh)
                          if mesh is not None else None)
        # ``cast_floats``: cast the float MODEL-INPUT columns (``cast_keys``,
        # never targets/weights — those feed the loss in f32 and have no
        # compensating device cast) to this dtype on the HOST (in the
        # prefetch thread) before device_put.  The model's first op casts
        # inputs to its compute dtype anyway, so for bf16 configs
        # transferring f32 rows ships 2x the bytes only to round them on
        # arrival; host-casting halves infeed with bit-identical results.
        # Matters most when the device link is narrow (the remote-relay
        # bench chip; DCN-attached hosts).
        self._cast_floats = np.dtype(cast_floats) if cast_floats else None
        self._cast_keys = frozenset(cast_keys)

    def steps_per_epoch(self) -> int:
        return len(self.dataset) // self.host_batch

    def _epoch_order(self, epoch: int) -> np.ndarray:
        n = len(self.dataset)
        if not self.shuffle:
            return np.arange(n)
        # Same seed on every host + per-epoch fold-in: hosts draw disjoint
        # shards of one global permutation stream (reference:
        # DistributedSampler.set_epoch).
        rng = np.random.default_rng(np.random.SeedSequence([self.seed, epoch]))
        return rng.permutation(n)

    def epoch(self, epoch: int, *, skip: int = 0) -> Iterator[dict]:
        """Yield device-put batches for one epoch, assembled ``prefetch``
        steps ahead on a background thread (native gather + device_put run
        concurrently with the consumer's compute — the torch DataLoader
        worker role, SURVEY.md §4.1).  ``skip``: drop the first N batches
        without paying device transfer (resume seeking)."""
        order = self._epoch_order(epoch)
        starts = list(range(0, len(order) - self.host_batch + 1,
                            self.host_batch))[skip:]
        q: queue.Queue = queue.Queue(maxsize=max(self.prefetch, 1))
        stop = threading.Event()
        sentinel = object()

        def put(item) -> bool:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def worker():
            try:
                for lo in starts:
                    idx = order[lo:lo + self.host_batch]
                    if not put(self._to_device(self.dataset[idx])):
                        return  # consumer gone
                put(sentinel)
            except BaseException as e:  # noqa: BLE001 — surface to consumer
                put(e)

        t = threading.Thread(target=worker, daemon=True,
                             name="tpuframe-prefetch")
        t.start()
        try:
            while True:
                item = q.get()
                if item is sentinel:
                    break
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            stop.set()

    def from_step(self, step: int) -> Iterator[dict]:
        """Infinite stream positioned as if ``step`` batches were already
        consumed — exact-continuation resume (SURVEY.md §5.4 'exact-epoch
        continuation'): the restored run sees the same remaining data order
        as an uninterrupted run."""
        spe = self.steps_per_epoch()
        epoch, offset = divmod(step, spe)
        while True:
            yield from self.epoch(epoch, skip=offset)
            offset = 0
            epoch += 1

    def __iter__(self):
        """Infinite stream across epochs (step-based training loops)."""
        return self.from_step(0)

    def _to_device(self, batch: dict) -> dict:
        if self._cast_floats is not None:
            batch = {k: (v.astype(self._cast_floats)
                         if k in self._cast_keys
                         and np.issubdtype(v.dtype, np.floating) else v)
                     for k, v in batch.items()}
        if self._sharding is None:
            return jax.tree.map(jax.device_put, batch)
        # Host rows are this host's slice of the global batch; device_put with
        # a NamedSharding scatters rows to local devices and (multi-host)
        # assembles the logically-global array without gathering.
        def put(x):
            sharding = self._sharding
            if self._partition is not None:
                from jax.sharding import PartitionSpec as P
                sharding = NamedSharding(self.mesh,
                                         P(*self._partition[:x.ndim]))
            return _put_host_shard(x, sharding, self.global_batch)
        return jax.tree.map(put, batch)


def _put_host_shard(x: np.ndarray, sharding: NamedSharding, global_batch: int):
    if jax.process_count() == 1:
        return jax.device_put(x, sharding)
    global_shape = (global_batch, *x.shape[1:])
    return jax.make_array_from_process_local_data(sharding, x, global_shape)
