"""Dataset builders for the five reference workloads (SURVEY.md §1, [B:6–12]).

Each builder returns train/eval ``ArrayDataset``s.  Real on-disk formats are
read when a data directory is provided (MNIST idx files, CIFAR-10 python
pickles — the formats the reference's torchvision loaders consume); otherwise
deterministic synthetic data with the same shapes/dtypes is generated, so
every config runs end-to-end in the zero-egress sandbox and in CI.

Data may live under ``gs://`` paths (read via tpuframe.data.gcs), matching
the reference's GCS-bucket input pipeline [B:5].
"""

from __future__ import annotations

import gzip
import io
import pickle
import struct
import zlib
from dataclasses import dataclass

import numpy as np

from tpuframe.data import gcs


@dataclass
class ArrayDataset:
    """In-memory columnar dataset: dict of equal-length arrays.

    ``host_presharded`` (instance attribute, default False): set by builders
    whose on-disk layout is already one shard per host, so ShardedLoader
    skips its own host split."""

    columns: dict[str, np.ndarray]
    host_presharded: bool = False

    def __post_init__(self):
        lens = {k: len(v) for k, v in self.columns.items()}
        if len(set(lens.values())) > 1:
            raise ValueError(f"ragged columns: {lens}")

    def __len__(self) -> int:
        return len(next(iter(self.columns.values())))

    def __getitem__(self, idx) -> dict[str, np.ndarray]:
        if (isinstance(idx, np.ndarray) and idx.ndim == 1
                and idx.dtype != np.bool_):
            # (bool masks stay on the numpy fancy-indexing path below — the
            # native gather casts indices to int64 and would silently read
            # rows 0/1 instead of selecting masked rows.)
            # Batch assembly: multi-threaded native gather (tpuframe.native)
            # — the loader's per-step host work, off the GIL.
            from tpuframe import native

            return {k: native.gather_rows(v, idx)
                    for k, v in self.columns.items()}
        return {k: v[idx] for k, v in self.columns.items()}

    def shard(self, num_shards: int, index: int) -> "ArrayDataset":
        """Contiguous per-host shard (the reference's DistributedSampler
        ``num_replicas/rank`` split, SURVEY.md §3a)."""
        if not (0 <= index < num_shards):
            raise ValueError(f"shard index {index} out of range {num_shards}")
        n = len(self) // num_shards  # drop remainder: equal shards, SPMD-safe
        lo = index * n
        return ArrayDataset({k: v[lo:lo + n] for k, v in self.columns.items()})


# ---------------------------------------------------------------------------
# MNIST — config 1 [B:7]
# ---------------------------------------------------------------------------

def _read_idx(data: bytes) -> np.ndarray:
    magic, = struct.unpack(">I", data[:4])
    ndim = magic & 0xFF
    dims = struct.unpack(f">{ndim}I", data[4:4 + 4 * ndim])
    return np.frombuffer(data, np.uint8, offset=4 + 4 * ndim).reshape(dims)


def _maybe_gunzip(raw: bytes) -> bytes:
    return gzip.decompress(raw) if raw[:2] == b"\x1f\x8b" else raw


def mnist(data_dir: str | None = None, *, synthetic_size: int = 2048):
    """[B, 28, 28, 1] float32 in [0,1), int32 labels."""
    if data_dir is not None:
        def load(img_name, lbl_name):
            imgs = _read_idx(_maybe_gunzip(gcs.read_bytes(gcs.join(data_dir, img_name))))
            lbls = _read_idx(_maybe_gunzip(gcs.read_bytes(gcs.join(data_dir, lbl_name))))
            x = (imgs.astype(np.float32) / 255.0)[..., None]
            return ArrayDataset({"image": x, "label": lbls.astype(np.int32)})

        train = load("train-images-idx3-ubyte.gz", "train-labels-idx1-ubyte.gz")
        test = load("t10k-images-idx3-ubyte.gz", "t10k-labels-idx1-ubyte.gz")
        return train, test
    return (_synthetic_images(synthetic_size, (28, 28, 1), 10, seed=0),
            _synthetic_images(max(synthetic_size // 8, 64), (28, 28, 1), 10,
                              seed=1, template_seed=0))


# ---------------------------------------------------------------------------
# CIFAR-10 — config 2 [B:8]
# ---------------------------------------------------------------------------

CIFAR_MEAN = np.array([0.4914, 0.4822, 0.4465], np.float32)
CIFAR_STD = np.array([0.2470, 0.2435, 0.2616], np.float32)

IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)


def cifar10(data_dir: str | None = None, *, synthetic_size: int = 2048,
            keep_u8: bool = False):
    """[B, 32, 32, 3] float32 normalized (or uint8 raw with ``keep_u8`` —
    see :func:`imagenet`; the pickles are uint8 natively), int32 labels.
    Reads the python pickle batches of the standard
    ``cifar-10-batches-py`` layout."""
    if data_dir is not None:
        def load(names):
            xs, ys = [], []
            for name in names:
                d = pickle.loads(gcs.read_bytes(gcs.join(data_dir, name)),
                                 encoding="bytes")
                xs.append(np.asarray(d[b"data"], np.uint8))
                ys.append(np.asarray(d[b"labels"], np.int64))
            x = np.concatenate(xs).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
            if not keep_u8:
                x = (x.astype(np.float32) / 255.0 - CIFAR_MEAN) / CIFAR_STD
            return ArrayDataset({"image": np.ascontiguousarray(x),
                                 "label": np.concatenate(ys).astype(np.int32)})

        train = load([f"data_batch_{i}" for i in range(1, 6)])
        test = load(["test_batch"])
        return train, test
    train, test = (
        _synthetic_images(synthetic_size, (32, 32, 3), 10, seed=2),
        _synthetic_images(max(synthetic_size // 8, 64), (32, 32, 3), 10,
                          seed=3, template_seed=2))
    if keep_u8:
        for ds in (train, test):
            ds.columns["image"] = np.round(
                ds.columns["image"] * 255.0).astype(np.uint8)
    return train, test


# ---------------------------------------------------------------------------
# ImageNet — configs 3 & 5 [B:9][B:11]
# ---------------------------------------------------------------------------

def imagenet(data_dir: str | None = None, *, image_size: int = 224,
             synthetic_size: int = 512, keep_u8: bool = False,
             num_classes: int = 1000):
    """[B, S, S, 3] float32 (or uint8), int32 labels in [0, num_classes)
    (synthetic; real shards carry the full 1000-class labels).

    Real ImageNet arrives as per-host ``.npy`` shards (images_XXXXX.npy /
    labels_XXXXX.npy) prepared by ``tpuframe.data.prepare_imagenet`` —
    decoding JPEGs on the training hosts would bottleneck the input pipeline
    (SURVEY.md §7 hard part 2), so decode/resize happens offline.

    ``keep_u8``: keep images uint8 end-to-end on the host — 4x less host
    RAM than the f32 default (real ImageNet: ~150 GB vs ~600 GB per host
    group) and 1 byte/px over the host→device link (vs 2 for the bf16
    infeed cast); the harness normalizes ON DEVICE (train._maybe_normalize
    — XLA-fused on TPU, the native FFI kernel on CPU hosts).  Synthetic
    mode quantizes its f32 images to the same u8 representation.
    """
    if data_dir is not None:
        import jax

        names = sorted(n for n in gcs.listdir(data_dir)
                       if n.startswith("images_"))
        # Each host loads only its slice of the file list — the shard files
        # ARE the host shards; loading everything everywhere would cost
        # O(hosts x dataset) reads and OOM a TPU-VM host on real ImageNet.
        n_proc, proc = jax.process_count(), jax.process_index()
        if n_proc > 1:
            if len(names) % n_proc:
                raise ValueError(
                    f"{len(names)} imagenet shard files not divisible by "
                    f"{n_proc} hosts — re-shard with prepare_imagenet")
            names = names[proc::n_proc]
        xs = [np.load(io.BytesIO(gcs.read_bytes(gcs.join(data_dir, n))))
              for n in names]
        ys = [np.load(io.BytesIO(gcs.read_bytes(gcs.join(data_dir, n.replace("images_", "labels_")))))
              for n in names]
        x = np.concatenate(xs)
        y = np.concatenate(ys).astype(np.int32)
        if x.dtype == np.uint8 and not keep_u8:
            # prepare_imagenet stores uint8 (4x less IO); normalize here.
            x = ((x.astype(np.float32) / 255.0) - IMAGENET_MEAN) / IMAGENET_STD
        split = int(0.99 * len(x))
        train = ArrayDataset({"image": x[:split], "label": y[:split]})
        test = ArrayDataset({"image": x[split:], "label": y[split:]})
        # Tell ShardedLoader the per-host split already happened.
        train.host_presharded = n_proc > 1
        test.host_presharded = n_proc > 1
        return train, test
    # ``num_classes`` (synthetic only): scaled-down smoke configs shrink
    # the model head — the label range must shrink with it (the harness
    # rejects out-of-range labels at build time).
    train, test = (
        _synthetic_images(synthetic_size, (image_size, image_size, 3),
                          num_classes, seed=4),
        _synthetic_images(max(synthetic_size // 8, 64),
                          (image_size, image_size, 3), num_classes,
                          seed=5, template_seed=4))
    if keep_u8:
        for ds in (train, test):
            ds.columns["image"] = np.round(
                ds.columns["image"] * 255.0).astype(np.uint8)
    return train, test


# ---------------------------------------------------------------------------
# GLUE (SST-2) — config 4 [B:10]
# ---------------------------------------------------------------------------

def glue_sst2(data_dir: str | None = None, *, seq_len: int = 128,
              vocab_size: int = 30522, synthetic_size: int = 1024,
              tokenizer=None, vocab_file: str | None = None):
    """Tokenized sentence-classification batches: input_ids / attention_mask /
    token_type_ids int32 [B, S], label int32.

    With ``data_dir``: reads GLUE's SST-2 tsv files.  Tokenization, in
    preference order: a caller-supplied tokenizer (HF-compatible callable);
    the built-in WordPiece tokenizer (tpuframe.data.wordpiece) when
    ``vocab_file`` is given or ``<data_dir>/vocab.txt`` exists — the real
    SST-2 accuracy path, no HF needed; else a hash-based fallback (vocab-free,
    fine for allreduce-stress benchmarking only).
    """
    if data_dir is not None:
        tokenizer = _resolve_tokenizer(tokenizer, data_dir, vocab_file)

        def load(name):
            text = gcs.read_bytes(gcs.join(data_dir, name)).decode()
            lines = text.replace("\r\n", "\n").strip().split("\n")[1:]  # drop header; CRLF-safe
            sents, labels = [], []
            for line in lines:
                sent, _, lbl = line.rpartition("\t")
                sents.append(sent)
                labels.append(int(lbl))
            return _tokenize(sents, np.asarray(labels, np.int32), seq_len,
                             vocab_size, tokenizer)

        return load("train.tsv"), load("dev.tsv")
    return (_synthetic_tokens(synthetic_size, seq_len, vocab_size, seed=6),
            _synthetic_tokens(max(synthetic_size // 8, 64), seq_len, vocab_size, seed=7))


MNLI_LABELS = {"entailment": 0, "neutral": 1, "contradiction": 2}


def glue_mnli(data_dir: str | None = None, *, seq_len: int = 128,
              vocab_size: int = 30522, synthetic_size: int = 1024,
              tokenizer=None, vocab_file: str | None = None):
    """MNLI sentence-PAIR classification (3-way: entailment / neutral /
    contradiction) — the second GLUE task, exercising the ``[CLS] a [SEP]
    b [SEP]`` pair-encoding path (``token_type_ids`` 0/1 segments) that
    single-sentence SST-2 never touches.

    With ``data_dir``: reads MNLI's ``train.tsv`` / ``dev_matched.tsv``.
    MNLI tsv columns vary by split, so fields are located by HEADER NAME
    (``sentence1``, ``sentence2``, ``gold_label``); rows with a missing or
    ``-`` gold label (annotator disagreement) are dropped, matching the
    standard evaluation protocol.  Tokenizer resolution is identical to
    :func:`glue_sst2`.
    """
    if data_dir is not None:
        tokenizer = _resolve_tokenizer(tokenizer, data_dir, vocab_file)

        def parse_label(raw):  # '-' / unknown = no gold consensus: drop
            return MNLI_LABELS.get(raw.strip())

        def load(name):
            pairs, labels = _parse_pair_tsv(
                gcs.read_bytes(gcs.join(data_dir, name)).decode(),
                label_col="gold_label", parse_label=parse_label)
            return _tokenize(pairs, np.asarray(labels, np.int32), seq_len,
                             vocab_size, tokenizer)

        return load("train.tsv"), load("dev_matched.tsv")
    return (_synthetic_token_pairs(synthetic_size, seq_len, vocab_size,
                                   seed=8),
            _synthetic_token_pairs(max(synthetic_size // 8, 64), seq_len,
                                   vocab_size, seed=9))


def _parse_pair_tsv(text: str, *, label_col: str, parse_label):
    """Header-located GLUE pair-task tsv: returns ((a, b) pairs, labels).
    ``parse_label`` maps the raw label field to a value or None (drop row
    — '-' MNLI labels, unscored STS-B test rows).  CRLF-normalized
    (NOT splitlines(), which would also split on \\x0c / U+2028-class
    breaks that can legally appear inside a text field)."""
    lines = text.replace("\r\n", "\n").strip().split("\n")
    col = {c: i for i, c in enumerate(lines[0].split("\t"))}
    ia, ib, il = col["sentence1"], col["sentence2"], col[label_col]
    pairs, labels = [], []
    for line in lines[1:]:
        f = line.split("\t")
        if len(f) <= max(ia, ib, il):
            continue
        lbl = parse_label(f[il])
        if lbl is None:
            continue
        pairs.append((f[ia], f[ib]))
        labels.append(lbl)
    return pairs, labels


def glue_stsb(data_dir: str | None = None, *, seq_len: int = 128,
              vocab_size: int = 30522, synthetic_size: int = 1024,
              tokenizer=None, vocab_file: str | None = None):
    """STS-B sentence-pair REGRESSION (similarity score 0-5, float32
    label) — the GLUE task family's third shape: the harness trains it
    with MSE instead of cross-entropy (HF convention: num_classes=1 ⇒
    regression).  Float labels also exercise the loader's cast_keys
    contract: inputs may be host-cast to bf16, targets must stay f32.

    With ``data_dir``: reads ``train.tsv`` / ``dev.tsv`` with
    header-located ``sentence1``/``sentence2``/``score`` columns.
    """
    if data_dir is not None:
        tokenizer = _resolve_tokenizer(tokenizer, data_dir, vocab_file)

        def parse_label(raw):  # unscored (test-set shape) rows: drop
            try:
                return float(raw)
            except ValueError:
                return None

        def load(name):
            pairs, scores = _parse_pair_tsv(
                gcs.read_bytes(gcs.join(data_dir, name)).decode(),
                label_col="score", parse_label=parse_label)
            return _tokenize(pairs, np.asarray(scores, np.float32), seq_len,
                             vocab_size, tokenizer)

        return load("train.tsv"), load("dev.tsv")
    return (_synthetic_score_pairs(synthetic_size, seq_len, vocab_size,
                                   seed=10),
            _synthetic_score_pairs(max(synthetic_size // 8, 64), seq_len,
                                   vocab_size, seed=11))


def glue_cola(data_dir: str | None = None, *, seq_len: int = 128,
              vocab_size: int = 30522, synthetic_size: int = 1024,
              tokenizer=None, vocab_file: str | None = None):
    """CoLA (Corpus of Linguistic Acceptability) — single-sentence binary
    classification whose standard metric is MATTHEWS CORRELATION (the
    class balance is skewed ~70/30, so accuracy overstates; the harness
    derives MCC from aggregated confusion moments at eval, train.py).

    File format differs from every other GLUE task: ``train.tsv`` /
    ``dev.tsv`` have NO header and four columns
    ``source<TAB>label<TAB>star<TAB>sentence``.
    """
    if data_dir is not None:
        tokenizer = _resolve_tokenizer(tokenizer, data_dir, vocab_file)

        def load(name):
            text = gcs.read_bytes(gcs.join(data_dir, name)).decode()
            sents, labels = [], []
            for line in text.replace("\r\n", "\n").strip().split("\n"):
                cols = line.split("\t")
                if len(cols) < 4:
                    continue
                labels.append(int(cols[1]))
                sents.append(cols[3])
            return _tokenize(sents, np.asarray(labels, np.int32), seq_len,
                             vocab_size, tokenizer)

        return load("train.tsv"), load("dev.tsv")
    return (_synthetic_tokens(synthetic_size, seq_len, vocab_size, seed=12),
            _synthetic_tokens(max(synthetic_size // 8, 64), seq_len,
                              vocab_size, seed=13))


def _synthetic_score_pairs(n, seq_len, vocab_size, *, seed):
    """Pair-encoded batches with a LEARNABLE float score: the signal token
    (position 1) encodes one of 11 levels mapping to scores 0.0-5.0."""
    if vocab_size < 211:  # ids 200..210 must be real embedding rows
        raise ValueError(f"synthetic STS-B needs vocab_size >= 211 for the "
                         f"score signal tokens; got {vocab_size}")
    rng = np.random.default_rng(seed)
    level = rng.integers(0, 11, size=n)
    ds = _synthetic_token_pairs(n, seq_len, vocab_size, seed=seed)
    ds.columns["input_ids"][:, 1] = 200 + level
    ds.columns["label"] = (level / 2.0).astype(np.float32)
    return ds


def _resolve_tokenizer(tokenizer, data_dir, vocab_file):
    """glue_* shared tokenizer resolution: caller-supplied > WordPiece with
    a real vocab > None (hash fallback in _tokenize)."""
    if tokenizer is not None:
        return tokenizer
    vpath = vocab_file or gcs.join(data_dir, "vocab.txt")
    if gcs.exists(vpath):
        from tpuframe.data.wordpiece import WordPieceTokenizer

        return WordPieceTokenizer(vpath)
    if vocab_file is not None:
        # An explicit vocab path that doesn't exist is a config error —
        # silently hash-tokenizing would just show up as mysteriously bad
        # accuracy.
        raise FileNotFoundError(f"vocab_file not found: {vocab_file}")
    return None


def _tokenize(sents, labels, seq_len, vocab_size, tokenizer):
    """``sents``: strings, or (a, b) pair tuples for two-sentence tasks."""
    if tokenizer is not None:
        enc = tokenizer(sents, padding="max_length", truncation=True,
                        max_length=seq_len, return_tensors="np")
        return ArrayDataset({
            "input_ids": enc["input_ids"].astype(np.int32),
            "attention_mask": enc["attention_mask"].astype(np.int32),
            "token_type_ids": enc.get("token_type_ids",
                                      np.zeros_like(enc["input_ids"])).astype(np.int32),
            "label": labels,
        })
    # Hash-based whitespace tokenizer: deterministic (crc32, not Python's
    # salted hash — ids must agree across host processes and restarts),
    # vocab-free. Fine for pipeline/perf work; real GLUE scores need the
    # WordPiece tokenizer.
    ids = np.zeros((len(sents), seq_len), np.int32)
    mask = np.zeros((len(sents), seq_len), np.int32)
    types = np.zeros((len(sents), seq_len), np.int32)
    hashed = lambda w: 2 + (zlib.crc32(w.encode()) % (vocab_size - 4))  # noqa: E731
    for i, s in enumerate(sents):
        if isinstance(s, tuple):
            a, b = ([hashed(w) for w in part.split()] for part in s)
            while len(a) + len(b) > seq_len - 3:  # HF longest_first order
                (a if len(a) > len(b) else b).pop()
            toks = [101] + a + [102] + b + [102]
            types[i, len(a) + 2:len(toks)] = 1
        else:
            toks = [101] + [hashed(w) for w in s.split()][: seq_len - 2] + [102]
        ids[i, :len(toks)] = toks
        mask[i, :len(toks)] = 1
    return ArrayDataset({"input_ids": ids, "attention_mask": mask,
                         "token_type_ids": types, "label": labels})


def _synthetic_token_pairs(n, seq_len, vocab_size, *, seed):
    """Synthetic pair-encoded batches with 3 learnable classes: the signal
    token (position 1) carries the label, and segment B starts at a
    variable boundary so token_type_ids actually vary."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 3, size=n).astype(np.int32)
    ids = rng.integers(4, vocab_size, size=(n, seq_len)).astype(np.int32)
    ids[:, 0] = 101
    ids[:, 1] = 200 + labels
    lengths = rng.integers(seq_len // 2, seq_len + 1, size=n)
    bounds = rng.integers(2, np.maximum(lengths - 1, 3))
    pos = np.arange(seq_len)[None, :]
    mask = (pos < lengths[:, None]).astype(np.int32)
    types = ((pos >= bounds[:, None]) & (pos < lengths[:, None])).astype(
        np.int32)
    return ArrayDataset({"input_ids": ids, "attention_mask": mask,
                         "token_type_ids": types, "label": labels})


# ---------------------------------------------------------------------------
# Causal LM — long-context workload (beyond the reference's capability bar)
# ---------------------------------------------------------------------------

def lm_text(data_dir: str | None = None, *, seq_len: int = 2048,
            vocab_size: int = 32000, synthetic_size: int = 256,
            padded_docs: bool = False, pad_id: int = 0):
    """Next-token-prediction chunks: input_ids [N, S], labels [N, S] int32
    (labels pre-shifted on the host so the loss is positionwise — no
    cross-shard shift is needed when the sequence dim is sharded over the
    mesh's seq axis).

    With ``data_dir``: reads ``tokens.npy`` (a single int32 token stream,
    e.g. pre-tokenized wikitext) and chunks it; synthetic mode generates an
    order-2 structured stream so convergence tests are meaningful.

    ``padded_docs``: variable-length documents right-padded to ``seq_len``
    with ``pad_id``; padded label positions carry ``-100`` — torch's
    ``ignore_index`` convention, which the harness LM losses honor (zero
    loss AND zero gradient there, means over valid tokens only).  The
    fine-tuning data shape, vs the packed-stream pretraining shape.
    """
    if padded_docs:
        if data_dir is not None:
            raise ValueError("padded_docs is a synthetic-data mode; "
                             "pre-tokenized streams are packed, not padded")
        return (_synthetic_lm_docs(synthetic_size, seq_len, vocab_size,
                                   pad_id=pad_id, seed=8),
                _synthetic_lm_docs(max(synthetic_size // 8, 8), seq_len,
                                   vocab_size, pad_id=pad_id, seed=9))
    if data_dir is not None:
        stream = np.load(io.BytesIO(gcs.read_bytes(gcs.join(data_dir, "tokens.npy"))))
        stream = stream.astype(np.int32) % vocab_size
        n = (len(stream) - 1) // seq_len
        split = max(int(0.98 * n), 1)
        def chunk(lo, hi):
            ids = np.stack([stream[i*seq_len:(i+1)*seq_len] for i in range(lo, hi)])
            lbl = np.stack([stream[i*seq_len+1:(i+1)*seq_len+1] for i in range(lo, hi)])
            return ArrayDataset({"input_ids": ids, "labels": lbl})
        return chunk(0, split), chunk(split, n)
    return (_synthetic_lm(synthetic_size, seq_len, vocab_size, seed=8),
            _synthetic_lm(max(synthetic_size // 8, 8), seq_len, vocab_size, seed=9))


def _synthetic_lm_docs(n, seq_len, vocab_size, *, pad_id, seed):
    """Variable-length affine-recurrence documents, right-padded: lengths
    uniform in [seq_len//4, seq_len]; labels are the shifted next tokens
    inside the document and -100 (ignored) at/after the last real token."""
    rng = np.random.default_rng(seed)
    full = _synthetic_lm(n, seq_len, vocab_size, seed=seed)
    ids = np.array(full[:n]["input_ids"], copy=True)
    labels = np.array(full[:n]["labels"], copy=True)
    lengths = rng.integers(max(seq_len // 4, 2), seq_len + 1, size=n)
    for i, ln in enumerate(lengths):
        ids[i, ln:] = pad_id
        # position t predicts token t+1: the last valid prediction is at
        # index ln-2 (predicting the doc's final token); everything from
        # ln-1 on is padding context -> ignored.
        labels[i, ln - 1:] = -100
    return ArrayDataset({"input_ids": ids, "labels": labels})


def _synthetic_lm(n, seq_len, vocab_size, *, seed):
    """Deterministic affine-recurrence token stream: x_{t+1} =
    (a*x_t + b) mod V with occasional noise — next-token loss can fall well
    below log(V), so "loss decreases" tests measure learning, not chance."""
    rng = np.random.default_rng(seed)
    starts = rng.integers(0, vocab_size, size=n)
    a, b = 31, 17
    ids = np.empty((n, seq_len + 1), np.int64)
    ids[:, 0] = starts
    for t in range(seq_len):
        ids[:, t + 1] = (a * ids[:, t] + b) % vocab_size
    noise = rng.random((n, seq_len + 1)) < 0.05
    ids[noise] = rng.integers(0, vocab_size, size=int(noise.sum()))
    return ArrayDataset({"input_ids": ids[:, :-1].astype(np.int32),
                         "labels": ids[:, 1:].astype(np.int32)})


# ---------------------------------------------------------------------------
# Synthetic generators (deterministic; shapes/dtypes match the real data)
# ---------------------------------------------------------------------------

def _synthetic_images(n, shape, num_classes, *, seed, template_seed=None):
    # A fixed random spatial template per class (high per-pixel SNR) makes the
    # synthetic task quickly learnable, so convergence tests (loss decreasing,
    # accuracy rising) are meaningful, not vacuous.  Pixel statistics mimic
    # real normalized data (mean~0.5, std~0.3 like [0,1) images) — the LR
    # recipes assume that scale.  ``template_seed`` is shared between the
    # train and eval splits of one dataset (same classes, different examples)
    # so eval accuracy actually measures generalization.
    tmpl_rng = np.random.default_rng(seed if template_seed is None else template_seed)
    templates = tmpl_rng.normal(0.0, 1.0, size=(num_classes, *shape)).astype(np.float32)
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, num_classes, size=n).astype(np.int32)
    noise = rng.normal(0.0, 1.0, size=(n, *shape)).astype(np.float32)
    x = np.clip(0.5 + 0.25 * templates[labels] + 0.1 * noise, 0.0, 1.0)
    return ArrayDataset({"image": x.astype(np.float32), "label": labels})


def _synthetic_tokens(n, seq_len, vocab_size, *, seed):
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 2, size=n).astype(np.int32)
    ids = rng.integers(4, vocab_size, size=(n, seq_len)).astype(np.int32)
    # Learnable signal: first token id correlates with the label.
    ids[:, 0] = 101
    ids[:, 1] = 200 + labels
    lengths = rng.integers(seq_len // 2, seq_len + 1, size=n)
    mask = (np.arange(seq_len)[None, :] < lengths[:, None]).astype(np.int32)
    return ArrayDataset({"input_ids": ids, "attention_mask": mask,
                         "token_type_ids": np.zeros_like(ids), "label": labels})
