"""tpuframe — a TPU-native distributed training framework.

A from-scratch JAX/XLA rebuild of the capabilities of the reference repo
``onesamblack/distributed-torch-horovod-gcp`` (a PyTorch + Horovod + NCCL
data-parallel harness on GCP GPU VMs — see SURVEY.md §1).  The Horovod C++
collective runtime (background coordinator, tensor-fusion buffer, NCCL/MPI/Gloo
backends — SURVEY.md §3b) is replaced by XLA SPMD compilation: collectives are
emitted by the compiler inside a jitted step function and ride the TPU ICI
torus (intra-slice) / DCN (cross-slice).

Layering (SURVEY.md §2):
  - ``tpuframe.parallel`` — L0–L2: process bootstrap, device mesh, collective
    helpers, and a Horovod-compatible facade (``tpuframe.parallel.hvd``).
  - ``tpuframe.data``     — L3: host-sharded input pipeline, GCS-backed readers.
  - ``tpuframe.ckpt``     — L3: sharded checkpoint save/restore with resharding.
  - ``tpuframe.models``   — model zoo: MNIST ConvNet, ResNet-18/50, BERT-base.
  - ``tpuframe.train``    — L4: config-driven training harness (5 workloads).
  - ``tpuframe.launch``   — L5/L6: TPU-VM provisioning + SSH fan-out launcher.
  - ``tpuframe.obs``      — tracing, metrics, heartbeat/stall detection.
  - ``tpuframe.ops``      — pallas TPU kernels + native C++ host runtime.
  - ``tpuframe.resilience`` — I/O retry policies, the preemption contract
    (rc 14), structured fault injection (docs/DESIGN.md "Failure model").
"""

__version__ = "0.1.0"

from tpuframe.parallel import mesh as mesh  # noqa: F401
