"""Fused 1x1-conv + BatchNorm backward — the byte-floor pallas kernel.

Why this op exists (PERF.md §6.3/§7.4b): the ResNet-50 train step moves
143.5 GB/step on-chip (offline AOT census 149.0 GB, 4% apart), ~105 GB of
it in the backward pass, and the census showed the traffic is STRUCTURAL
— layouts are fine, folded-BN is a null, remat is negative.  The one
remaining lever is TOUCH COUNT: XLA's backward for a conv+BN pair
materializes the BN input-cotangent ``g`` (activation-sized) in HBM and
re-reads it twice (conv data-grad, conv weight-grad):

    XLA:   pass1 reads (x, dy)          -> BN sums
           pass2 reads (x, dy) writes g -> BN input grad
           dgrad reads (g)              -> da
           wgrad reads (g, a)           -> dW
           = 9 activation-sized touches

    here:  pass1 reads (x, dy)          -> BN sums  (XLA, fuses to one pass)
           pass2 reads (a, x, dy) writes da; g lives only in VMEM
           = 6 activation-sized touches

Every 1x1 conv in a ResNet-50 bottleneck (conv1, conv3, downsample — the
large-C tensors) is a matmul over ``(N*H*W, Cin) x (Cin, Cout)``, so
"conv backward" here is two MXU dots per tile: ``da = g @ W^T`` and
``dW += a^T @ g``, both fed by a ``g`` computed on the fly from the
folded per-channel BN-backward coefficients

    g = s*dy - u*x + c,   s = gamma*r,  u = gamma*r^2*c2,
                          c = gamma*r^2*c2*mu - gamma*r*c1,
    c1 = mean(dy), c2 = mean(dy * xhat), r = rsqrt(var+eps)

(the exact training-mode BN backward, differentiating through the batch
statistics).  Removing g's write + two reads is 3 activation-sized
touches per fused pair; summed over ResNet-50's 1x1 convs at batch 512
that is ~27 GB of the 149 GB census — verified offline by
``perf/exp_hlo_offline.py BN=fused`` (the AOT cost model counts a pallas
call as operands+outputs, which for this streaming kernel is the honest
count).

The 3x3 convs and the stem keep the XLA path: their g tensors are the
small-C minority of the bytes and an implicit-GEMM halo kernel is not
worth the risk for them (measured priority, not principle).

Forward is left to XLA (matmul + folded one-FMA normalize, same touch
count as flax BN); only training-mode backward uses the kernel.  Eval
mode is a plain affine fold, no custom anything.

Reference parity: the reference's ResNet comes from torchvision
(SURVEY.md §3a); its conv+BN backward is cuDNN's fused
``cudnnBatchNormalizationBackwardEx`` + conv grad kernels.  This is the
TPU-native equivalent of that fusion, not a translation of it.

CPU tests run the kernel under the pallas interpreter
(tests/test_fused_conv_bn.py): value + gradient parity vs the
unfused jnp composition, f32 tight / bf16 tolerance, stride-2, module
parity vs ``nn.Conv + nn.BatchNorm``, and golden-loss equivalence of the
full ResNet-50 step.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Row-block default: 256 rows x up to 2048 channels of bf16 activations
# keeps the worst ResNet-50 1x1 shape (~K=2048 or N=2048) near ~10 MB of
# VMEM including the f32 dW accumulator (see _pick_bm).
DEFAULT_BLOCK_M = 256
_VMEM_BUDGET = 10 * 1024 * 1024


def _auto_interpret() -> bool:
    return jax.default_backend() != "tpu"


def supported(m: int, k: int, n: int, block_m: int = DEFAULT_BLOCK_M) -> bool:
    """True when the backward kernel's static tiling fits (else callers keep
    the plain-XLA composition).  M must tile into whole row blocks; K/N are
    lane/sublane padded by Mosaic but bounded so W + the f32 dW accumulator
    stay within the VMEM budget."""
    bm = _pick_bm(m, k, n, block_m)
    return bm is not None


def _pick_bm(m: int, k: int, n: int, block_m: int) -> int | None:
    if k > 4096 or n > 4096 or k * n * 6 > _VMEM_BUDGET:  # W bf16 + acc f32
        return None
    bm = min(block_m, m)
    while bm >= 8:
        if bm % 8 == 0 and m % bm == 0 \
                and _vmem_est(bm, k, n) <= _VMEM_BUDGET:
            return bm
        bm //= 2
    return None


def _vmem_est(bm: int, k: int, n: int) -> int:
    # a + da tiles (bm,K) bf16; x + dy tiles (bm,N) bf16; g (bm,N) f32;
    # W (K,N) bf16; dW acc (K,N) f32; coef rows negligible.
    return 2 * (bm * k * 2) + 2 * (bm * n * 2) + bm * n * 4 \
        + k * n * 2 + k * n * 4


# ---------------------------------------------------------------------------
# backward pass 2: the fused kernel
# ---------------------------------------------------------------------------


def _bwd_kernel(a_ref, w_ref, x_ref, dy_ref, coef_ref,
                da_ref, dw_ref, dw_acc,
                *, n_m: int, precision=None):
    """Grid is (M/bm,), sequential.  coef rows: 0=s, 1=u, 2=c (f32).

    g = s*dy - u*x + c is computed in f32 in VMEM, used by both dots, and
    never written back; dW accumulates in f32 scratch across the row
    blocks and is emitted once at the last block.
    """
    mi = pl.program_id(0)

    @pl.when(mi == 0)
    def _init():
        dw_acc[...] = jnp.zeros_like(dw_acc)

    s = coef_ref[0, :][None, :]                       # [1, N] f32
    u = coef_ref[1, :][None, :]
    c = coef_ref[2, :][None, :]
    x = x_ref[...].astype(jnp.float32)                # [bm, N]
    dy = dy_ref[...].astype(jnp.float32)
    g = (s * dy - u * x + c).astype(w_ref.dtype)      # [bm, N] — VMEM only

    da_ref[...] = jax.lax.dot_general(                # g @ W^T   [bm, K]
        g, w_ref[...], (((1,), (1,)), ((), ())), precision=precision,
        preferred_element_type=jnp.float32).astype(da_ref.dtype)
    dw_acc[...] += jax.lax.dot_general(               # a^T @ g   [K, N]
        a_ref[...], g, (((0,), (0,)), ((), ())), precision=precision,
        preferred_element_type=jnp.float32)

    @pl.when(mi == n_m - 1)
    def _emit():
        dw_ref[...] = dw_acc[...]


def _sds(like: jax.Array, shape, dtype) -> jax.ShapeDtypeStruct:
    """Inherit varying-mesh-axes so the op composes with shard_map (same
    rationale as flash_attention._sds)."""
    return jax.ShapeDtypeStruct(shape, dtype, vma=jax.typeof(like).vma)


def _fused_bwd_matmuls(a2d, w_c, x, dy, coef, *, block_m, interpret,
                       precision=None):
    """da, dW for the 1x1 conv given the folded BN-backward coefficients."""
    m, k = a2d.shape
    n = x.shape[1]
    bm = _pick_bm(m, k, n, block_m)
    assert bm is not None, "caller must gate on supported()"
    n_m = m // bm

    da, dw = pl.pallas_call(
        functools.partial(_bwd_kernel, n_m=n_m, precision=precision),
        grid=(n_m,),
        in_specs=[
            pl.BlockSpec((bm, k), lambda i: (i, 0)),   # a
            pl.BlockSpec((k, n), lambda i: (0, 0)),    # W (resident)
            pl.BlockSpec((bm, n), lambda i: (i, 0)),   # x
            pl.BlockSpec((bm, n), lambda i: (i, 0)),   # dy
            pl.BlockSpec((3, n), lambda i: (0, 0)),    # coef rows
        ],
        out_specs=[
            pl.BlockSpec((bm, k), lambda i: (i, 0)),   # da
            pl.BlockSpec((k, n), lambda i: (0, 0)),    # dW (emitted last)
        ],
        out_shape=[
            _sds(a2d, (m, k), a2d.dtype),
            _sds(a2d, (k, n), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((k, n), jnp.float32)],
        # dW carries across row blocks: the single grid dim is sequential.
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(a2d, w_c, x, dy, coef)
    return da, dw


# ---------------------------------------------------------------------------
# the custom-vjp core: y, mean, var = conv1x1 + train-mode BN
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def conv1x1_bn_train(cfg: tuple, a2d: jax.Array, w: jax.Array,
                     gamma: jax.Array, beta: jax.Array):
    """``cfg = (eps, block_m, interpret)`` (hashable statics).

    a2d: [M, K] activations (rows = N*H*W), w: [K, N] f32 params,
    gamma/beta: [N] f32.  Returns (y [M,N] in a2d.dtype, mean [N] f32,
    var [N] f32 — biased, flax-style).  The mean/var outputs exist for
    the running-stats update and are NOT differentiated through
    (callers must stop_gradient them, as FusedConvBN does; their
    cotangents are ignored in the backward, matching flax's treatment
    of running statistics).
    """
    y, mean, var, _ = _fwd_math(cfg, a2d, w, gamma, beta)
    return y, mean, var


def _fwd_math(cfg, a2d, w, gamma, beta):
    eps, _, _ = cfg
    w_c = w.astype(a2d.dtype)
    # Conv-as-matmul with f32 MXU accumulation, stored in compute dtype —
    # the same contract as nn.Conv(dtype=bf16).
    x = jax.lax.dot_general(a2d, w_c, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32
                            ).astype(a2d.dtype)
    # f32 accumulation without f32 materialization (folded_bn.py rationale:
    # the convert feeds the reduce, only C-sized f32 lands).
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=0)
    var = jnp.maximum(jnp.mean(jnp.square(xf), axis=0) - jnp.square(mean),
                      0.0)
    r = jax.lax.rsqrt(var + eps)
    aa = gamma.astype(jnp.float32) * r
    bb = beta.astype(jnp.float32) - mean * aa
    y = x * aa.astype(x.dtype) + bb.astype(x.dtype)
    return y, mean, var, x


def _core_fwd(cfg, a2d, w, gamma, beta):
    y, mean, var, x = _fwd_math(cfg, a2d, w, gamma, beta)
    return (y, mean, var), (a2d, w, x, mean, var, gamma)


def _core_bwd(cfg, res, cots):
    eps, block_m, interpret = cfg
    a2d, w, x, mean, var, gamma = res
    dy, _dmean, _dvar = cots          # stats cotangents: see docstring
    m = a2d.shape[0]

    # Pass 1 (XLA): both BN reductions in one fused pass over (x, dy).
    r = jax.lax.rsqrt(var + eps)
    dyf = dy.astype(jnp.float32)
    xhat = (x.astype(jnp.float32) - mean) * r
    sum_dy = jnp.sum(dyf, axis=0)
    sum_dyxhat = jnp.sum(dyf * xhat, axis=0)
    dgamma = sum_dyxhat
    dbeta = sum_dy

    # Folded per-channel coefficients for g = s*dy - u*x + c.
    gf = gamma.astype(jnp.float32)
    c1 = sum_dy / m
    c2 = sum_dyxhat / m
    s = gf * r
    u = gf * r * r * c2
    c = u * mean - s * c1
    coef = jnp.stack([s, u, c])                     # [3, N] f32

    # Pass 2 (pallas): da + dW with g never materialized in HBM.
    da, dw = _fused_bwd_matmuls(a2d, w.astype(a2d.dtype), x, dy, coef,
                                block_m=block_m, interpret=interpret)
    # w is stored f32 and cast to compute dtype inside the fwd; the f32
    # accumulator already IS the gradient through that cast.
    return da, dw.astype(w.dtype), dgamma.astype(gamma.dtype), \
        dbeta.astype(gamma.dtype)


conv1x1_bn_train.defvjp(_core_fwd, _core_bwd)


def conv1x1_bn_reference(a2d, w, gamma, beta, *, eps):
    """The unfused jnp composition (matmul -> flax-semantics train BN) the
    kernel is parity-tested against; differentiable end to end by XLA."""
    w_c = w.astype(a2d.dtype)
    x = jax.lax.dot_general(a2d, w_c, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32
                            ).astype(a2d.dtype)
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=0)
    var = jnp.maximum(jnp.mean(jnp.square(xf), axis=0) - jnp.square(mean),
                      0.0)
    r = jax.lax.rsqrt(var + eps)
    aa = gamma.astype(jnp.float32) * r
    bb = beta.astype(jnp.float32) - mean * aa
    y = x * aa.astype(x.dtype) + bb.astype(x.dtype)
    return y, mean, var


# ---------------------------------------------------------------------------
# flax module: drop-in for a Conv(1x1, no bias) -> BatchNorm pair
# ---------------------------------------------------------------------------

import flax.linen as nn  # noqa: E402  (after-jax import, flax convention)


class FusedConvBN(nn.Module):
    """1x1 conv (no bias) + BatchNorm with the fused pallas backward.

    Parameter layout: ``kernel`` keeps nn.Conv's ``(1, 1, K, N)`` shape so
    torchvision-style weight ports map unchanged; ``scale``/``bias`` and
    the ``batch_stats`` ``mean``/``var`` entries match nn.BatchNorm, so
    the harness's cross-replica batch-stats averaging (parallel/step.py)
    applies unmodified.  (Flax auto-naming still re-keys module names vs
    the unfused pair — same caveat as the ``bn="folded"`` toggle.)

    Strides are handled OUTSIDE the fused core: a strided 1x1 conv is
    exactly a spatial slice followed by the dense matmul, and the slice's
    VJP (zero-scatter) stays with XLA.
    """

    features: int
    strides: int = 1
    use_running_average: bool = False
    momentum: float = 0.9
    epsilon: float = 1e-5
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    scale_init: nn.initializers.Initializer = nn.initializers.ones
    kernel_init: nn.initializers.Initializer = \
        nn.initializers.variance_scaling(2.0, "fan_out", "normal")
    block_m: int = DEFAULT_BLOCK_M
    interpret: bool | None = None     # None = auto (CPU -> interpreter)

    @nn.compact
    def __call__(self, x):
        k_in = x.shape[-1]
        kernel = self.param("kernel", self.kernel_init,
                            (1, 1, k_in, self.features), self.param_dtype)
        scale = self.param("scale", self.scale_init, (self.features,),
                           self.param_dtype)
        bias = self.param("bias", nn.initializers.zeros, (self.features,),
                          self.param_dtype)
        ra_mean = self.variable("batch_stats", "mean",
                                lambda: jnp.zeros((self.features,),
                                                  jnp.float32))
        ra_var = self.variable("batch_stats", "var",
                               lambda: jnp.ones((self.features,),
                                                jnp.float32))

        x = x.astype(self.dtype)
        if self.strides > 1:
            x = x[:, ::self.strides, ::self.strides, :]
        b, h, w_sp, _ = x.shape
        a2d = x.reshape(b * h * w_sp, k_in)
        w2d = kernel.reshape(k_in, self.features)

        if self.use_running_average:
            # Eval: affine fold with running stats — plain XLA.
            mean, var = ra_mean.value, ra_var.value
            xx = jax.lax.dot_general(a2d, w2d.astype(self.dtype),
                                     (((1,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32
                                     ).astype(self.dtype)
            r = jax.lax.rsqrt(var + self.epsilon)
            aa = scale.astype(jnp.float32) * r
            bb = bias.astype(jnp.float32) - mean * aa
            y2d = xx * aa.astype(self.dtype) + bb.astype(self.dtype)
        else:
            interpret = (_auto_interpret() if self.interpret is None
                         else self.interpret)
            if supported(a2d.shape[0], k_in, self.features, self.block_m) \
                    and not self.is_initializing():
                cfg = (float(self.epsilon), int(self.block_m),
                       bool(interpret))
                y2d, mean, var = conv1x1_bn_train(cfg, a2d, w2d, scale, bias)
            else:
                # Shape outside the kernel's tiling (or init pass): the
                # reference composition, identical numerics.
                y2d, mean, var = conv1x1_bn_reference(
                    a2d, w2d, scale, bias, eps=self.epsilon)
            if not self.is_initializing():
                mom = self.momentum
                ra_mean.value = mom * ra_mean.value + (1 - mom) * \
                    jax.lax.stop_gradient(mean)
                ra_var.value = mom * ra_var.value + (1 - mom) * \
                    jax.lax.stop_gradient(var)

        return y2d.reshape(b, h, w_sp, self.features)
