"""Fused 1x1-conv + BatchNorm backward — the byte-floor pallas kernel.

Why this op exists (PERF.md §6.3/§7.4b): the ResNet-50 train step moves
143.5 GB/step on-chip (offline AOT census 149.0 GB, 4% apart), ~105 GB
of it in the backward pass, and the census showed the traffic is
STRUCTURAL — layouts are fine, folded-BN is a null, remat is negative.
The one remaining lever is TOUCH COUNT: XLA's backward for a conv+BN
pair materializes the BN input-cotangent ``g`` (activation-sized) in HBM
and re-reads it twice (conv data-grad, conv weight-grad):

    XLA:   pass1 reads (x, dy)          -> BN sums
           pass2 reads (x, dy) writes g -> BN input grad
           dgrad reads (g)              -> da
           wgrad reads (g, a)           -> dW
           = 9 activation-sized touches

    here:  pass1 reads (x, dy)          -> BN sums  (XLA, fuses to one pass)
           pass2 reads (a, x, dy) writes da; g lives only in VMEM
           = 6 activation-sized touches

Every 1x1 conv in a ResNet-50 bottleneck (conv1, conv3, downsample — the
large-C tensors) is a matmul over ``(N*H*W, Cin) x (Cin, Cout)``, so
"conv backward" here is two MXU dots per tile fed by a ``g`` computed on
the fly from the folded per-channel BN-backward coefficients

    g = s*dy - u*x + c,   s = gamma*r,  u = gamma*r^2*c2,
                          c = gamma*r^2*c2*mu - gamma*r*c1,
    c1 = mean(dy), c2 = mean(dy * xhat), r = rsqrt(var+eps)

(the exact training-mode BN backward, differentiating through the batch
statistics).

LAYOUT CONTRACT (the round-5 lesson, measured): XLA:TPU lays ResNet
conv activations out as ``{3,0,2,1}`` — physically C on the 128 lanes,
N on the 8 sublanes, spatial dims major.  A naive ``reshape(N*H*W, C)``
before a pallas call demands a different physical order, and the
relayout copies it forces cost MORE than the fusion saves (measured
136.3 vs 81.4 GB at b=256 for the first cut of this kernel).  So:

  * the FORWARD is a plain ``lax.conv_general_dilated`` + folded BN —
    byte-identical ops to the unfused model, conv layouts end to end;
  * the BACKWARD kernel consumes ``[H*W, N, C]`` views, whose default
    (descending) layout is physically IDENTICAL to ``{3,0,2,1}`` on
    ``[N,H,W,C]`` — the transpose+reshape at the boundary is a bitcast,
    not a copy, and rows of the matmul are just a permutation of
    ``N*H*W`` (BN sums, dW and da are row-order-invariant).

Removing g's write + two reads is 3 activation-sized touches per fused
pair; verified offline by ``perf/exp_hlo_offline.py BN=fused`` (the AOT
cost model counts a pallas call as operands+outputs, which for this
streaming kernel is the honest count).

The 3x3 convs and the stem keep the XLA path: their g tensors are the
small-C minority of the bytes and an implicit-GEMM halo kernel is not
worth the risk for them (measured priority, not principle).

KNOWN EXCLUSION — ResNet-50 layer4 downsample: the VMEM gate in
``_pick_tiles`` keeps the resident weight block + f32 dW accumulator
under the 10 MB budget via ``k * c * 6 <= _VMEM_BUDGET``; the layer4
downsample 1x1 is K=1024 -> C=2048, i.e. 1024*2048*6 = 12.58 MB, so
``supported()`` returns False and that one pair falls back to the
plain-XLA composition (correct, just unfused).  Every other ResNet-50
1x1 fits.  Tracked as the first entry of
``tpuframe.analysis.budgets.KNOWN_VMEM_EXCLUSIONS`` — the analysis CI
gate cross-checks the registry against this gate so the exclusion list
cannot silently drift from the code (PERF.md §11).

Reference parity: the reference's ResNet comes from torchvision
(SURVEY.md §3a); its conv+BN backward is cuDNN's fused
``cudnnBatchNormalizationBackwardEx`` + conv grad kernels.  This is the
TPU-native equivalent of that fusion, not a translation of it.

CPU tests run the kernel under the pallas interpreter
(tests/test_fused_conv_bn.py): value + gradient parity vs the unfused
composition, f32 tight / bf16 tolerance, stride-2, module parity vs
``nn.Conv + nn.BatchNorm``, and golden-loss equivalence of the full
ResNet-50 step.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Row budget per grid step (spatial-tile x batch-tile rows): 2048 rows of
# up-to-2048-wide bf16 activations keeps the worst ResNet-50 1x1 shape
# near ~10 MB of VMEM including the f32 dW accumulator (see _pick_tiles).
DEFAULT_BLOCK_ROWS = 2048
_VMEM_BUDGET = 10 * 1024 * 1024


def _auto_interpret() -> bool:
    import os

    # TPUFRAME_PALLAS_INTERPRET overrides the backend check: the offline
    # AOT census compiles FOR a TPU topology FROM a CPU host, where the
    # backend heuristic would silently swap Mosaic kernels for
    # interpreter while-loops (perf/_common.ensure_cpu_backend sets 0).
    env = os.environ.get("TPUFRAME_PALLAS_INTERPRET")
    if env is not None:
        return env == "1"
    return jax.default_backend() != "tpu"


def supported(h: int, w: int, n: int, k: int, c: int,
              block_rows: int = DEFAULT_BLOCK_ROWS) -> bool:
    """True when the backward kernel's static tiling fits (else callers
    keep the plain-XLA composition).  ``h``/``w`` spatial dims (after any
    stride slicing), ``n`` = batch, ``k``/``c`` = in/out channels."""
    return _pick_tiles(h, w, n, k, c, block_rows) is not None


def _pick_tiles(h: int, w: int, n: int, k: int, c: int,
                block_rows: int) -> tuple[int] | None:
    """(tn,): batch-tile size.  Each grid step processes one spatial row
    of the [H, W, N, C] view — W*tn matmul rows — so tn shrinks (by
    halving, must divide N) until the row budget and VMEM fit."""
    if k > 4096 or c > 4096 or k * c * 6 > _VMEM_BUDGET:  # W bf16 + acc f32
        return None
    tn = n
    while tn > 1 and (w * tn > block_rows
                      or _vmem_est(w * tn, k, c) > _VMEM_BUDGET):
        tn //= 2
    if n % tn != 0 or w * tn > block_rows \
            or _vmem_est(w * tn, k, c) > _VMEM_BUDGET:
        return None
    return (tn,)


def _vmem_est(rows: int, k: int, c: int) -> int:
    # Mosaic DOUBLE-BUFFERS every grid-blocked operand/result (a, x, dy,
    # da — the 2x factor; the real v5e compiler OOM'd at 16 MB VMEM when
    # this estimate ignored that), plus the f32 g temp on the kernel
    # stack, the resident W block and the f32 dW accumulator scratch.
    dbuf = 2 * (2 * (rows * k * 2) + 2 * (rows * c * 2))
    return dbuf + rows * c * 4 + k * c * 2 + k * c * 4


# ---------------------------------------------------------------------------
# backward pass 2: the fused kernel
# ---------------------------------------------------------------------------


def _bwd_kernel(a_ref, w_ref, x_ref, dy_ref, coef_ref,
                da_ref, dw_ref, dw_acc,
                *, n_h: int, n_n: int, precision=None):
    """Grid is (H, N/tn), sequential (dW carries).  coef rows: 0=s, 1=u,
    2=c (f32).  Blocks are [1, W, tn, channels] — one spatial row of the
    [H, W, N, C] view per step; the collapse to [W*tn, channels] rows is
    a sublane-group stack, not a relayout.  g = s*dy - u*x + c is
    computed in f32 in VMEM, used by both dots, and never written back;
    dW accumulates in f32 scratch and is emitted once at the last step.
    """
    hi = pl.program_id(0)
    ni = pl.program_id(1)

    @pl.when(jnp.logical_and(hi == 0, ni == 0))
    def _init():
        dw_acc[...] = jnp.zeros_like(dw_acc)

    _, w_sp, tn, k = a_ref.shape
    c = x_ref.shape[-1]
    rows = w_sp * tn
    s = coef_ref[0, :][None, :]                       # [1, C] f32
    u = coef_ref[1, :][None, :]
    cc = coef_ref[2, :][None, :]
    a = a_ref[...].reshape(rows, k)
    x = x_ref[...].reshape(rows, c).astype(jnp.float32)
    dy = dy_ref[...].reshape(rows, c).astype(jnp.float32)
    g = (s * dy - u * x + cc).astype(w_ref.dtype)     # VMEM only

    da_ref[...] = jax.lax.dot_general(                # g @ W^T   [rows, K]
        g, w_ref[...], (((1,), (1,)), ((), ())), precision=precision,
        preferred_element_type=jnp.float32
    ).astype(da_ref.dtype).reshape(1, w_sp, tn, k)
    dw_acc[...] += jax.lax.dot_general(               # a^T @ g   [K, C]
        a, g, (((0,), (0,)), ((), ())), precision=precision,
        preferred_element_type=jnp.float32)

    @pl.when(jnp.logical_and(hi == n_h - 1, ni == n_n - 1))
    def _emit():
        dw_ref[...] = dw_acc[...]


def _sds(like: jax.Array, shape, dtype) -> jax.ShapeDtypeStruct:
    """Inherit varying-mesh-axes so the op composes with shard_map (same
    rationale as flash_attention._sds)."""
    return jax.ShapeDtypeStruct(shape, dtype, vma=jax.typeof(like).vma)


def _fused_bwd_matmuls(a4t, w_c, x4t, dy4t, coef, *, block_rows, interpret,
                       precision=None):
    """da4t, dW given [H, W, N, C]-view operands + folded coefficients."""
    h, w_sp, n, k = a4t.shape
    c = x4t.shape[-1]
    tiles = _pick_tiles(h, w_sp, n, k, c, block_rows)
    assert tiles is not None, "caller must gate on supported()"
    (tn,) = tiles
    n_n = n // tn

    da4t, dw = pl.pallas_call(
        functools.partial(_bwd_kernel, n_h=h, n_n=n_n,
                          precision=precision),
        grid=(h, n_n),
        in_specs=[
            pl.BlockSpec((1, w_sp, tn, k), lambda i, j: (i, 0, j, 0)),  # a
            pl.BlockSpec((k, c), lambda i, j: (0, 0)),                  # W
            pl.BlockSpec((1, w_sp, tn, c), lambda i, j: (i, 0, j, 0)),  # x
            pl.BlockSpec((1, w_sp, tn, c), lambda i, j: (i, 0, j, 0)),  # dy
            pl.BlockSpec((3, c), lambda i, j: (0, 0)),                  # coef
        ],
        out_specs=[
            pl.BlockSpec((1, w_sp, tn, k), lambda i, j: (i, 0, j, 0)),  # da
            pl.BlockSpec((k, c), lambda i, j: (0, 0)),           # dW (last)
        ],
        out_shape=[
            _sds(a4t, (h, w_sp, n, k), a4t.dtype),
            _sds(a4t, (k, c), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((k, c), jnp.float32)],
        # dW carries across every step: both grid dims are sequential.
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=interpret,
    )(a4t, w_c, x4t, dy4t, coef)
    return da4t, dw


def _to_hwnc(x4):
    """[N, H, W, C] -> [H, W, N, C].  The default (descending) layout on
    the result is minor-to-major (C, N, W, H) — physically IDENTICAL to
    the conv layout {3,0,2,1} on the input, so layout assignment folds
    this pure transpose into a bitcast (a transpose+reshape chain did
    NOT fold — measured 97.6 vs 81.4 GB baseline; this is the fix)."""
    return x4.transpose(1, 2, 0, 3)


def _from_hwnc(x4t):
    """[H, W, N, C] -> [N, H, W, C] (inverse, same bitcast argument)."""
    return x4t.transpose(2, 0, 1, 3)


# ---------------------------------------------------------------------------
# the custom-vjp core: y, mean, var = conv1x1 + train-mode BN (NHWC)
# ---------------------------------------------------------------------------


def _conv1x1(a4, w2, precision=None):
    """1x1 stride-1 conv via conv_general_dilated — the SAME op (same
    dtype contract: bf16 in/out, f32 MXU accumulation internally) the
    unfused flax model runs, so XLA's layout assignment sees nothing
    new.  No preferred_element_type: its f32 output would poison the
    VJP's conv dtypes, and flax.nn.Conv doesn't use it either."""
    return lax.conv_general_dilated(
        a4, w2[None, None], (1, 1), "VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        precision=precision)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def conv1x1_bn_train(cfg: tuple, a4: jax.Array, w: jax.Array,
                     gamma: jax.Array, beta: jax.Array):
    """``cfg = (eps, block_rows, interpret)`` (hashable statics).

    a4: [N, H, W, K] activations, w: [K, C] f32 params, gamma/beta: [C]
    f32.  Returns (y [N,H,W,C] in a4.dtype, mean [C] f32, var [C] f32 —
    biased, flax-style).  The mean/var outputs exist for the
    running-stats update and are NOT differentiated through (callers
    must stop_gradient them, as FusedConvBN does; their cotangents are
    ignored in the backward, matching flax's treatment of running
    statistics).
    """
    y, mean, var, _ = _fwd_math(cfg, a4, w, gamma, beta)
    return y, mean, var


def _fwd_math(cfg, a4, w, gamma, beta):
    eps, _, _ = cfg
    x = _conv1x1(a4, w.astype(a4.dtype))
    # f32 accumulation without f32 materialization (folded_bn.py
    # rationale: the convert feeds the reduce, only C-sized f32 lands).
    axes = (0, 1, 2)
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes)
    var = jnp.maximum(jnp.mean(jnp.square(xf), axis=axes)
                      - jnp.square(mean), 0.0)
    r = lax.rsqrt(var + eps)
    aa = gamma.astype(jnp.float32) * r
    bb = beta.astype(jnp.float32) - mean * aa
    y = x * aa.astype(x.dtype) + bb.astype(x.dtype)
    return y, mean, var, x


def _core_fwd(cfg, a4, w, gamma, beta):
    y, mean, var, x = _fwd_math(cfg, a4, w, gamma, beta)
    return (y, mean, var), (a4, w, x, mean, var, gamma)


def _core_bwd(cfg, res, cots):
    eps, block_rows, interpret = cfg
    a4, w, x, mean, var, gamma = res
    dy, _dmean, _dvar = cots          # stats cotangents: see docstring
    n, h, w_sp, c = x.shape
    m = n * h * w_sp

    # Pass 1 (XLA): both BN reductions in one fused pass over (x, dy),
    # native layout — reductions are layout-agnostic.
    r = lax.rsqrt(var + eps)
    dyf = dy.astype(jnp.float32)
    xhat = (x.astype(jnp.float32) - mean) * r
    sum_dy = jnp.sum(dyf, axis=(0, 1, 2))
    sum_dyxhat = jnp.sum(dyf * xhat, axis=(0, 1, 2))
    dgamma = sum_dyxhat
    dbeta = sum_dy

    # Folded per-channel coefficients for g = s*dy - u*x + c.
    gf = gamma.astype(jnp.float32)
    c1 = sum_dy / m
    c2 = sum_dyxhat / m
    s = gf * r
    u = gf * r * r * c2
    cc = u * mean - s * c1
    coef = jnp.stack([s, u, cc])                    # [3, C] f32

    # Pass 2 (pallas) on [H, W, N, C] views — bitcasts on the conv layout.
    da4t, dw = _fused_bwd_matmuls(
        _to_hwnc(a4), w.astype(a4.dtype), _to_hwnc(x), _to_hwnc(dy), coef,
        block_rows=block_rows, interpret=interpret)
    da4 = _from_hwnc(da4t)
    # w is stored f32 and cast to compute dtype inside the fwd; the f32
    # accumulator already IS the gradient through that cast.
    return da4, dw.astype(w.dtype), dgamma.astype(gamma.dtype), \
        dbeta.astype(gamma.dtype)


conv1x1_bn_train.defvjp(_core_fwd, _core_bwd)


def conv1x1_bn_reference(a4, w, gamma, beta, *, eps):
    """The unfused composition (1x1 conv -> flax-semantics train BN) the
    kernel is parity-tested against; differentiable end to end by XLA.
    Delegates to the SAME forward math as the custom_vjp (the module's
    fallback-path contract is bit-identical forward numerics)."""
    y, mean, var, _ = _fwd_math((eps, 0, False), a4, w, gamma, beta)
    return y, mean, var


# ---------------------------------------------------------------------------
# flax module: drop-in for a Conv(1x1, no bias) -> BatchNorm pair
# ---------------------------------------------------------------------------

import flax.linen as nn  # noqa: E402  (after-jax import, flax convention)


class FusedConvBN(nn.Module):
    """1x1 conv (no bias) + BatchNorm with the fused pallas backward.

    Parameter layout: ``kernel`` keeps nn.Conv's ``(1, 1, K, C)`` shape so
    torchvision-style weight ports map unchanged; ``scale``/``bias`` and
    the ``batch_stats`` ``mean``/``var`` entries match nn.BatchNorm, so
    the harness's cross-replica batch-stats averaging (parallel/step.py)
    applies unmodified.  (Flax auto-naming still re-keys module names vs
    the unfused pair — same caveat as the ``bn="folded"`` toggle.)

    Strides are handled OUTSIDE the fused core: a strided 1x1 conv is
    exactly a spatial slice followed by the stride-1 conv, and the
    slice's VJP (zero-scatter) stays with XLA.
    """

    features: int
    strides: int = 1
    use_running_average: bool = False
    momentum: float = 0.9
    epsilon: float = 1e-5
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    scale_init: nn.initializers.Initializer = nn.initializers.ones
    kernel_init: nn.initializers.Initializer = \
        nn.initializers.variance_scaling(2.0, "fan_out", "normal")
    block_rows: int = DEFAULT_BLOCK_ROWS
    interpret: bool | None = None     # None = auto (CPU -> interpreter)

    @nn.compact
    def __call__(self, x):
        k_in = x.shape[-1]
        kernel = self.param("kernel", self.kernel_init,
                            (1, 1, k_in, self.features), self.param_dtype)
        scale = self.param("scale", self.scale_init, (self.features,),
                           self.param_dtype)
        bias = self.param("bias", nn.initializers.zeros, (self.features,),
                          self.param_dtype)
        ra_mean = self.variable("batch_stats", "mean",
                                lambda: jnp.zeros((self.features,),
                                                  jnp.float32))
        ra_var = self.variable("batch_stats", "var",
                               lambda: jnp.ones((self.features,),
                                                jnp.float32))

        x = x.astype(self.dtype)
        if self.strides > 1:
            x = x[:, ::self.strides, ::self.strides, :]
        b, h, w_sp, _ = x.shape
        w2d = kernel.reshape(k_in, self.features)

        if self.use_running_average:
            # Eval: conv + affine fold with running stats — plain XLA.
            mean, var = ra_mean.value, ra_var.value
            xx = _conv1x1(x, w2d.astype(self.dtype))
            r = lax.rsqrt(var + self.epsilon)
            aa = scale.astype(jnp.float32) * r
            bb = bias.astype(jnp.float32) - mean * aa
            y = xx * aa.astype(self.dtype) + bb.astype(self.dtype)
        else:
            interpret = (_auto_interpret() if self.interpret is None
                         else self.interpret)
            if supported(h, w_sp, b, k_in, self.features,
                         self.block_rows) and not self.is_initializing():
                cfg = (float(self.epsilon), int(self.block_rows),
                       bool(interpret))
                y, mean, var = conv1x1_bn_train(cfg, x, w2d, scale, bias)
            else:
                # Shape outside the kernel's tiling (or init pass): the
                # reference composition, identical numerics.
                y, mean, var = conv1x1_bn_reference(
                    x, w2d, scale, bias, eps=self.epsilon)
            if not self.is_initializing():
                mom = self.momentum
                ra_mean.value = mom * ra_mean.value + (1 - mom) * \
                    jax.lax.stop_gradient(mean)
                ra_var.value = mom * ra_var.value + (1 - mom) * \
                    jax.lax.stop_gradient(var)

        return y
