"""Multi-head attention core with pluggable kernels.

The reference gets attention from HF transformers' torch BERT (cuDNN kernels
under the hood).  Here the op is a dispatch point:
  - ``xla``: einsum formulation — XLA fuses softmax into the matmuls well on
    TPU for BERT-scale sequence lengths (128–512, [B:10]).
  - ``pallas``: a flash-attention TPU kernel (tpuframe.ops.flash_attention),
    block-tiled for MXU/VMEM — the long-sequence path.

Selection: explicit ``impl=`` argument, else the ``TPUFRAME_ATTN_IMPL`` env
var, else ``xla``.
"""

from __future__ import annotations

import os
import warnings

import jax
import jax.numpy as jnp


def multihead_attention(
    q: jax.Array,  # [B, S, N, D]
    k: jax.Array,  # [B, S, N, D]
    v: jax.Array,  # [B, S, N, D]
    *,
    mask: jax.Array | None = None,  # [B, S] 1=keep or broadcastable [B,1,S,S]
    causal: bool = False,
    dropout_rate: float = 0.0,
    dropout_rng: jax.Array | None = None,
    impl: str | None = None,
) -> jax.Array:
    impl = impl or os.environ.get("TPUFRAME_ATTN_IMPL", "xla")
    if impl == "pallas":
        try:
            from tpuframe.ops import flash_attention
        except ImportError:
            warnings.warn("pallas flash attention unavailable; using xla impl")
            flash_attention = None
        if (flash_attention is not None and dropout_rate == 0.0
                and flash_attention.supported(q, k)
                and (mask is None or mask.ndim == 2)):
            return flash_attention.flash_mha(q, k, v, mask=mask, causal=causal)
        impl = "xla"  # dropout / unsupported shapes / missing kernel fall back
    if impl != "xla":
        raise ValueError(f"unknown attention impl {impl!r}")
    if causal:
        s_q, s_kv = q.shape[1], k.shape[1]
        tri = jnp.tril(jnp.ones((s_q, s_kv), bool))[None, None]
        if mask is not None:
            pad = mask[:, None, None, :] if mask.ndim == 2 else mask
            tri = jnp.logical_and(tri, pad.astype(bool))
        mask = tri
    return _xla_attention(q, k, v, mask=mask, dropout_rate=dropout_rate,
                          dropout_rng=dropout_rng)


def _xla_attention(q, k, v, *, mask, dropout_rate, dropout_rng):
    depth = q.shape[-1]
    scale = 1.0 / jnp.sqrt(depth).astype(q.dtype)
    # [B, N, S, S] scores; accumulate in f32 for softmax stability.
    scores = jnp.einsum("bqnd,bknd->bnqk", q * scale, k,
                        preferred_element_type=jnp.float32)
    if mask is not None:
        if mask.ndim == 2:  # [B, S] key padding mask
            mask = mask[:, None, None, :]
        scores = jnp.where(mask.astype(bool), scores, jnp.float32(-1e9))
    probs = jax.nn.softmax(scores, axis=-1)
    if dropout_rate > 0.0:
        if dropout_rng is None:
            raise ValueError("dropout_rate > 0 requires dropout_rng")
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_rate), 0.0)
    probs = probs.astype(v.dtype)
    return jnp.einsum("bnqk,bknd->bqnd", probs, v)
