"""Multi-head attention core with pluggable kernels.

The reference gets attention from HF transformers' torch BERT (cuDNN kernels
under the hood).  Here the op is a dispatch point:
  - ``xla``: einsum formulation — XLA fuses softmax into the matmuls well on
    TPU for BERT-scale sequence lengths (128–512, [B:10]).
  - ``pallas``: a flash-attention TPU kernel (tpuframe.ops.flash_attention),
    block-tiled for MXU/VMEM — the long-sequence path.

Selection: explicit ``impl=`` argument, else the ``TPUFRAME_ATTN_IMPL`` env
var, else ``xla``.
"""

from __future__ import annotations

import os
import warnings

import jax
import jax.numpy as jnp


def multihead_attention(
    q: jax.Array,  # [B, S, N, D]
    k: jax.Array,  # [B, S, N, D]
    v: jax.Array,  # [B, S, N, D]
    *,
    mask: jax.Array | None = None,  # [B, S] 1=keep or broadcastable [B,1,S,S]
    causal: bool = False,
    dropout_rate: float = 0.0,
    dropout_rng: jax.Array | None = None,
    impl: str | None = None,
) -> jax.Array:
    impl = impl or os.environ.get("TPUFRAME_ATTN_IMPL", "xla")
    if impl == "pallas":
        try:
            from tpuframe.ops import flash_attention
        except ImportError:
            warnings.warn("pallas flash attention unavailable; using xla impl")
            flash_attention = None
        if (flash_attention is not None and dropout_rate == 0.0
                and flash_attention.supported(q, k)
                and (mask is None or mask.ndim == 2)):
            return flash_attention.flash_mha(q, k, v, mask=mask, causal=causal)
        impl = "xla"  # dropout / unsupported shapes / missing kernel fall back
    if impl != "xla":
        raise ValueError(f"unknown attention impl {impl!r}")
    if causal:
        s_q, s_kv = q.shape[1], k.shape[1]
        tri = jnp.tril(jnp.ones((s_q, s_kv), bool))[None, None]
        if mask is not None:
            pad = mask[:, None, None, :] if mask.ndim == 2 else mask
            tri = jnp.logical_and(tri, pad.astype(bool))
        mask = tri
    return _xla_attention(q, k, v, mask=mask, dropout_rate=dropout_rate,
                          dropout_rng=dropout_rng)


def decode_attention(
    q: jax.Array,        # [B, 1, N, D] — the query-length-1 decode entry
    k_cache: jax.Array,  # [B, S_kv, N, D] — KV-cache keys (post-RoPE)
    v_cache: jax.Array,  # [B, S_kv, N, D]
    *,
    lengths: jax.Array,  # [B] int32 — valid cache entries per sequence
    impl: str | None = None,
) -> jax.Array:
    """Decode-mode attention: one new query token against the KV-cache.

    The serving counterpart of :func:`multihead_attention`
    (tpuframe.serve).  Causality is a *length mask*, not a triangle: the
    cache holds exactly the tokens the new position may attend, padded to
    the cache's bucketed capacity, so the mask is ``arange(S_kv) <
    lengths`` per sequence.  The flash kernel's advantage — keeping the
    S×S score matrix out of HBM — is moot at query length 1 (scores are
    [B, N, 1, S_kv], KV-cache-row-sized); the einsum formulation IS the
    memory-optimal decode program, and every HBM byte the step moves is
    cache+params, which the serve roofline (tune/roofline.decode_score)
    models directly.  ``impl`` is accepted for parity with the training
    entry: pallas falls back to xla here because ``flash_attention
    .supported`` rejects query length 1 (sublane-unaligned), by design.
    """
    if q.ndim != 4 or q.shape[1] != 1:
        raise ValueError(f"decode_attention wants q [B, 1, N, D]; "
                         f"got {q.shape}")
    s_kv = k_cache.shape[1]
    mask = (jnp.arange(s_kv)[None, :] < lengths[:, None]).astype(jnp.int32)
    return multihead_attention(q, k_cache, v_cache, mask=mask,
                               causal=False, impl=impl)


def _xla_attention(q, k, v, *, mask, dropout_rate, dropout_rng):
    depth = q.shape[-1]
    scale = 1.0 / jnp.sqrt(depth).astype(q.dtype)
    # [B, N, S, S] scores; accumulate in f32 for softmax stability.
    scores = jnp.einsum("bqnd,bknd->bnqk", q * scale, k,
                        preferred_element_type=jnp.float32)
    if mask is not None:
        if mask.ndim == 2:  # [B, S] key padding mask
            mask = mask[:, None, None, :]
        scores = jnp.where(mask.astype(bool), scores, jnp.float32(-1e9))
    probs = jax.nn.softmax(scores, axis=-1)
    if dropout_rate > 0.0:
        if dropout_rng is None:
            raise ValueError("dropout_rate > 0 requires dropout_rng")
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_rate), 0.0)
    probs = probs.astype(v.dtype)
    return jnp.einsum("bnqk,bknd->bqnd", probs, v)
