"""TPU compute kernels: reference (XLA-fused einsum) and pallas implementations,
plus the native C++ host runtime (tpuframe.ops.native).

Hot ops route through dispatch functions (e.g. ``attention.multihead_attention``)
so kernels can be swapped without touching model code.
"""
