"""Mixture-of-experts routing — top-k gating with capacity (Switch/GShard
formulation), built for expert parallelism over the ``expert`` mesh axis.

Not a reference capability (SURVEY.md §3c: no MoE workload); included
because expert parallelism is a first-class mesh axis in this framework.
The dispatch/combine are dense einsums over a one-hot token→(expert, slot)
tensor — static shapes, MXU-friendly, and under auto-SPMD with the expert
dim of the weights sharded over ``expert``, GSPMD lowers the dispatch
einsum to the same all-to-all a hand-written MoE runtime performs.

All routing math runs in float32 regardless of activation dtype.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def route_topk(gate_logits: jax.Array, *, k: int, capacity: int):
    """Top-k token→expert assignment with per-expert capacity.

    gate_logits: ``[T, E]`` (f32 recommended).
    Returns ``(dispatch [T, E, C] f32 0/1, combine [T, E, C] f32,
    aux_loss scalar)``.  Tokens overflowing an expert's capacity are
    dropped for that expert (their combine weights are 0 — the residual
    connection carries them, standard Switch behavior).
    """
    t, e = gate_logits.shape
    gates = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)  # [T, E]

    dispatch = jnp.zeros((t, e, capacity), jnp.float32)
    combine = jnp.zeros((t, e, capacity), jnp.float32)
    masked_gates = gates
    prior_count = jnp.zeros((e,), jnp.float32)   # slots used per expert
    chosen_masks = []
    chosen_weights = []

    for _ in range(k):
        choice = jnp.argmax(masked_gates, axis=-1)              # [T]
        mask = jax.nn.one_hot(choice, e, dtype=jnp.float32)     # [T, E]
        # position of each token in its chosen expert's queue
        pos_in_expert = (jnp.cumsum(mask, axis=0) - mask) + prior_count[None]
        keep = mask * (pos_in_expert < capacity)
        slot = jax.nn.one_hot((pos_in_expert * keep).astype(jnp.int32),
                              capacity, dtype=jnp.float32)      # [T, E, C]
        dispatch = dispatch + keep[..., None] * slot
        weight = jnp.sum(gates * keep, axis=-1, keepdims=True)  # [T, 1]
        combine = combine + (keep * weight)[..., None] * slot
        chosen_masks.append(mask)
        chosen_weights.append(weight)
        prior_count = prior_count + jnp.sum(keep, axis=0)
        masked_gates = masked_gates * (1.0 - mask)

    # Renormalize the k gate weights so kept tokens' weights sum to ~1.
    denom = sum(chosen_weights)
    denom = jnp.where(denom > 0, denom, 1.0)
    combine = combine / denom[..., None]

    # Load-balance aux loss (Switch): E * sum_e mean_gates_e * frac_routed_e,
    # computed on the FIRST choice (standard) before capacity dropping.
    me = jnp.mean(gates, axis=0)                   # [E]
    ce = jnp.mean(chosen_masks[0], axis=0)         # [E]
    aux = e * jnp.sum(me * ce)
    return dispatch, combine, aux


def capacity_for(tokens: int, num_experts: int, k: int,
                 capacity_factor: float) -> int:
    """Static per-expert capacity: ceil(k*T/E * factor), min 1, multiple of
    4 to keep the slot dim tile-friendly."""
    raw = int(tokens * k / num_experts * capacity_factor) + 1
    return max(4, (raw + 3) // 4 * 4)
