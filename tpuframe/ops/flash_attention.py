"""Flash attention as a Pallas TPU kernel — the framework's hot-op path.

The reference's attention ran inside HF torch BERT on cuDNN (SURVEY.md §3a
"Model defs"); its FLOPs lived in fused CUDA kernels.  The TPU-native
equivalent is a block-tiled online-softmax attention kernel that keeps the
S×S score matrix out of HBM entirely:

  * forward: for each query block, stream key/value blocks through VMEM,
    maintaining running max ``m``, normalizer ``l`` and an f32 accumulator —
    one HBM pass over K/V, scores never materialized.
  * backward: two kernels (dq-major and dkv-major), recomputing probabilities
    from the saved logsumexp instead of storing them — the standard
    flash-attention-2 residual scheme (O, logsumexp, delta=rowsum(dO·O)).

Block sizes default to 128 — the MXU tile edge — so every matmul in the loop
is a full systolic-array issue.  Accumulation is float32 regardless of input
dtype (bf16 inputs keep bf16 in HBM, f32 in VMEM).

Used through :func:`tpuframe.ops.attention.multihead_attention` with
``impl="pallas"`` (or ``TPUFRAME_ATTN_IMPL=pallas``); CPU tests run the same
kernel under the Pallas interpreter.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# 128 = the MXU tile edge.  Resolution order (tpuframe.tune):
# TPUFRAME_FA_BLOCK_Q/K env > tuning-DB measured > tuning-DB predicted >
# 128 — and the DB tiers only engage when the target TPU generation is
# known (TPUFRAME_TUNE_GEN / PALLAS_AXON_TPU_GEN), so plain CPU runs and
# the fast test tier always see 128/128.
from tpuframe.tune import db as _tune_db  # noqa: E402 — stdlib-only module

DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K = _tune_db.resolve_fa_blocks(128, 128)
NEG_INF = -1e30  # softmax mask fill; finite so (x - x) stays 0, not nan

_LANES = 128  # VMEM lane width: per-row stats are stored lane-broadcast

# jax < 0.5 names it TPUCompilerParams; same fields either way.
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or pltpu.TPUCompilerParams


def _auto_interpret() -> bool:
    # TPUFRAME_PALLAS_INTERPRET overrides the backend check: the offline
    # AOT census compiles FOR a TPU topology FROM a CPU host, where the
    # backend heuristic would silently swap Mosaic kernels for
    # interpreter while-loops (perf/_common.ensure_cpu_backend sets 0;
    # round-5 census correction).
    env = os.environ.get("TPUFRAME_PALLAS_INTERPRET")
    if env is not None:
        return env == "1"
    return jax.default_backend() != "tpu"


def _lse_lane_major() -> bool:
    """Generation-conditional lse/delta layout (PERF.md §12.2).

    The per-row residuals (logsumexp, delta) are logically [rows] vectors;
    as kernel operands they need a 2-D in-block shape.  Sublane-major
    ([bq, 1]) matches the running stats' natural orientation but pads the
    HBM array's trailing dim 1 → 128 lanes — a 128x residual blow-up that
    pushed lm_long's dp1×sp8 capacity-edge mesh back over v5e's HBM.
    Lane-major ([1, bq]) pads 1 → 8 sublanes instead (16x less), but the
    in-kernel [bq, 1] ↔ [1, bq] relayout lowers through tpu.dynamic_gather
    — "Sublane gather not supported by this TPU generation" on v4 (the
    offline v4 audit, PERF.md §12.1).  So: lane-major for every generation
    newer than v4, sublane-major for v4 and for unknown targets (CPU test
    runs keep the layout every generation can compile)."""
    gen = _tune_db.target_generation()
    return gen is not None and gen != "v4"


def _causal_dispatch(causal, qi, kv, block_q, block_k, compute):
    """Run ``compute(need_tri)`` for this block's causal region.

    Three regions by block position: strictly ABOVE the diagonal
    contributes nothing (skip entirely); STRADDLING it needs the
    per-element tri mask; strictly BELOW needs no tri at all — for long
    sequences most blocks are below, so skipping the iota/compare/select
    chain there removes real VPU work.  Non-causal: one unmasked call.
    """
    if not causal:
        compute(False)
        return
    first_row, last_row = qi * block_q, qi * block_q + (block_q - 1)
    first_col, last_col = kv * block_k, kv * block_k + (block_k - 1)

    @pl.when(first_row >= last_col)
    def _below():
        compute(False)

    @pl.when(jnp.logical_and(last_row >= first_col, first_row < last_col))
    def _straddle():
        compute(True)


def _sds(like: jax.Array, shape, dtype) -> jax.ShapeDtypeStruct:
    """out_shape that inherits ``like``'s varying-mesh-axes, so the kernel
    works unchanged inside ``shard_map`` (where jax requires outputs to
    declare their vma) and outside it (empty vma).  Legacy jax (< 0.5) has
    no vma typing — check_rep=False shard_map needs no declaration there."""
    if hasattr(jax, "typeof"):
        return jax.ShapeDtypeStruct(shape, dtype, vma=jax.typeof(like).vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def supported(q: jax.Array, k: jax.Array | None = None,
              block_q: int = DEFAULT_BLOCK_Q,
              block_k: int = DEFAULT_BLOCK_K) -> bool:
    """True when shapes fit the kernel's static tiling (else caller falls
    back to the XLA einsum path, tpuframe.ops.attention)."""
    if q.ndim != 4:
        return False
    _, s_q, _, d = q.shape
    s_kv = s_q if k is None else k.shape[1]
    bq, bk = min(block_q, s_q), min(block_k, s_kv)
    # seq dims must tile into whole blocks and stay sublane-aligned (mult of
    # 8); head dim beyond 256 would blow the per-block VMEM budget.
    return (d <= 256 and s_q % bq == 0 and s_kv % bk == 0
            and s_q % 8 == 0 and s_kv % 8 == 0)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(mask_ref, q_ref, k_ref, v_ref,  # inputs
                o_ref, lse_ref,                 # outputs
                acc_ref, m_ref, l_ref,          # scratch
                *, scale: float, causal: bool, block_q: int, block_k: int,
                n_kv: int, lane_lse: bool = False, precision=None):
    qi = pl.program_id(1)
    kv = pl.program_id(2)

    @pl.when(kv == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    def compute(need_tri):
        q = q_ref[0]                     # [bq, d]
        k = k_ref[0]                     # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), precision=precision,
            preferred_element_type=jnp.float32) * scale   # [bq, bk]

        keep = None                                       # [bq, bk] or None
        if mask_ref is not None:
            keep = jnp.broadcast_to(mask_ref[0, 0][None, :] != 0, s.shape)
        if need_tri:
            rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            tri = qi * block_q + rows >= kv * block_k + cols
            keep = tri if keep is None else jnp.logical_and(keep, tri)
        if keep is not None:
            s = jnp.where(keep, s, NEG_INF)

        m_prev = m_ref[:, :1]                             # [bq, 1]
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)        # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)                   # rescale factor
        p = jnp.exp(s - m_new)                            # [bq, bk]
        if keep is not None:
            # Explicit zeroing (not exp-underflow): a fully-masked row keeps
            # l == 0 and yields zero output + NEG_INF lse, and the backward
            # recompute below reproduces exactly p == 0 for it.
            p = jnp.where(keep, p, 0.0)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)

        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            precision=precision, preferred_element_type=jnp.float32)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    _causal_dispatch(causal, qi, kv, block_q, block_k, compute)

    @pl.when(kv == n_kv - 1)
    def _finalize():
        m = m_ref[:, :1]
        l = l_ref[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)   # fully-masked rows → zeros
        o_ref[0] = (acc_ref[...] / l_safe).astype(o_ref.dtype)
        # logsumexp residual for the backward pass.  Layout is generation-
        # conditional (_lse_lane_major): lane-major [1, bq] where the
        # sublane<->lane relayout compiles (v5e+ — 16x less HBM padding on
        # the residual array), sublane-major [bq, 1] on v4/unknown, where
        # Mosaic lowers the relayout as tpu.dynamic_gather — "Sublane
        # gather not supported by this TPU generation" (the offline v4
        # audit, PERF.md §12).
        lse = jnp.where(l == 0.0, NEG_INF, m + jnp.log(l_safe))
        lse_ref[0] = lse.reshape(1, block_q) if lane_lse else lse


def _flash_fwd(q, k, v, mask, *, scale, causal, block_q, block_k, interpret,
               precision=None):
    bn, s_q, d = q.shape
    s_kv = k.shape[1]
    bq, bk = min(block_q, s_q), min(block_k, s_kv)
    n_q, n_kv = s_q // bq, s_kv // bk
    grid = (bn, n_q, n_kv)

    in_specs = [
        pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),          # q
        pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),          # k
        pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),          # v
    ]
    args = [q, k, v]
    lane = _lse_lane_major()
    if mask is not None:
        n_heads = bn // mask.shape[0]
        in_specs.insert(0, pl.BlockSpec(
            (1, 1, bk), lambda b, i, j, h=n_heads: (b // h, 0, j)))
        args.insert(0, mask[:, None, :])
        kernel = functools.partial(
            _fwd_kernel, scale=scale, causal=causal,
            block_q=bq, block_k=bk, n_kv=n_kv, lane_lse=lane,
            precision=precision)
    else:
        kernel = functools.partial(
            _fwd_kernel, None, scale=scale, causal=causal,
            block_q=bq, block_k=bk, n_kv=n_kv, lane_lse=lane,
            precision=precision)

    lse_spec = (pl.BlockSpec((1, 1, bq), lambda b, i, j: (b, 0, i)) if lane
                else pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0)))
    lse_shape = (bn, 1, s_q) if lane else (bn, s_q, 1)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            lse_spec,
        ],
        out_shape=[
            _sds(q, (bn, s_q, d), q.dtype),
            _sds(q, lse_shape, jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, _LANES), jnp.float32),
        ],
        # batch and q-block dims carry no cross-iteration state (the
        # acc/m/l scratch carry lives on the kv dim only): declaring them
        # parallel lets Mosaic schedule/pipeline them freely.
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*args)
    return out, (lse[:, 0, :] if lane else lse[:, :, 0])


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _recompute_p(q_ref, k_ref, lse_ref, mask_ref, *, scale, need_tri,
                 qi, kv, block_q, block_k, lane_lse=False, precision=None):
    """Rebuild the probability block from saved logsumexp (f32)."""
    s = jax.lax.dot_general(
        q_ref[0], k_ref[0], (((1,), (1,)), ((), ())), precision=precision,
        preferred_element_type=jnp.float32) * scale
    keep = None
    if mask_ref is not None:
        keep = jnp.broadcast_to(mask_ref[0, 0][None, :] != 0, s.shape)
    if need_tri:
        rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        tri = qi * block_q + rows >= kv * block_k + cols
        keep = tri if keep is None else jnp.logical_and(keep, tri)
    lse = lse_ref[0]                           # [bq, 1] (or [1, bq] lane)
    if lane_lse:
        lse = lse.reshape(block_q, 1)
    p = jnp.exp(jnp.where(keep, s, NEG_INF) - lse) if keep is not None \
        else jnp.exp(s - lse)
    if keep is not None:
        p = jnp.where(keep, p, 0.0)                         # see fwd kernel
    return p                                                # [bq, bk]


def _bwd_dq_kernel(mask_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_acc, *, scale, causal, block_q, block_k, n_kv,
                   lane_lse=False, precision=None):
    qi = pl.program_id(1)
    kv = pl.program_id(2)

    @pl.when(kv == 0)
    def _():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    def compute(need_tri):
        p = _recompute_p(q_ref, k_ref, lse_ref, mask_ref, scale=scale,
                         need_tri=need_tri, qi=qi, kv=kv,
                         block_q=block_q, block_k=block_k,
                         lane_lse=lane_lse, precision=precision)
        dp = jax.lax.dot_general(                       # dO @ V^T  [bq, bk]
            do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
            precision=precision, preferred_element_type=jnp.float32)
        delta = (delta_ref[0].reshape(block_q, 1) if lane_lse
                 else delta_ref[0])
        ds = p * (dp - delta)                           # [bq, bk]
        dq_acc[...] += scale * jax.lax.dot_general(     # ds @ K    [bq, d]
            ds.astype(k_ref.dtype), k_ref[0], (((1,), (0,)), ((), ())),
            precision=precision, preferred_element_type=jnp.float32)

    _causal_dispatch(causal, qi, kv, block_q, block_k, compute)

    @pl.when(kv == n_kv - 1)
    def _():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(mask_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc,
                    *, scale, causal, block_q, block_k, n_q,
                    lane_lse=False, precision=None):
    kv = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    def compute(need_tri):
        p = _recompute_p(q_ref, k_ref, lse_ref, mask_ref, scale=scale,
                         need_tri=need_tri, qi=qi, kv=kv,
                         block_q=block_q, block_k=block_k,
                         lane_lse=lane_lse, precision=precision)
        dv_acc[...] += jax.lax.dot_general(             # P^T @ dO  [bk, d]
            p.astype(do_ref.dtype), do_ref[0], (((0,), (0,)), ((), ())),
            precision=precision, preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
            precision=precision, preferred_element_type=jnp.float32)
        delta = (delta_ref[0].reshape(block_q, 1) if lane_lse
                 else delta_ref[0])
        ds = p * (dp - delta)
        dk_acc[...] += scale * jax.lax.dot_general(     # ds^T @ Q  [bk, d]
            ds.astype(q_ref.dtype), q_ref[0], (((0,), (0,)), ((), ())),
            precision=precision, preferred_element_type=jnp.float32)

    _causal_dispatch(causal, qi, kv, block_q, block_k, compute)

    @pl.when(qi == n_q - 1)
    def _():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, mask, out, lse, do, *, scale, causal,
               block_q, block_k, interpret, precision=None, dlse=None):
    bn, s_q, d = q.shape
    s_kv = k.shape[1]
    bq, bk = min(block_q, s_q), min(block_k, s_kv)
    n_q, n_kv = s_q // bq, s_kv // bk

    # delta_i = rowsum(dO_i * O_i) — tiny elementwise reduce; let XLA fuse
    # it.  The residual arrays (delta, lse) take the generation-conditional
    # layout (_lse_lane_major): lane-major [bn, 1, s] where the relayout
    # compiles, sublane-major [bn, s, 1] on v4/unknown — same tradeoff as
    # the forward's lse store.
    lane = _lse_lane_major()
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)
    if dlse is not None:
        # lse-output cotangent (ring-attention stage merging): with
        # lse = logsumexp(s) an output, ∂lse/∂s_j = p_j adds dlse·p_j to
        # ds — i.e. ds = p·(dp - delta + dlse).  Folding it into delta
        # (delta_eff = delta - dlse) reuses both backward kernels
        # untouched.
        delta = delta - dlse.astype(jnp.float32)
    if lane:
        delta, lse3 = delta[:, None, :], lse[:, None, :]
    else:
        delta, lse3 = delta[:, :, None], lse[:, :, None]

    q_spec_qmajor = pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0))
    kv_spec_qmajor = pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0))
    row_spec_qmajor = (
        pl.BlockSpec((1, 1, bq), lambda b, i, j: (b, 0, i)) if lane
        else pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0)))

    common = [q, k, v, do, lse3, delta]

    def with_mask(kernel, index_map):
        if mask is None:
            return functools.partial(kernel, None), [], []
        n_heads = bn // mask.shape[0]
        spec = pl.BlockSpec((1, 1, bk), functools.partial(index_map, n_heads))
        return kernel, [spec], [mask[:, None, :]]

    # --- dq: grid (bn, q blocks, kv blocks) ---
    kernel, mspec, margs = with_mask(
        _bwd_dq_kernel, lambda h, b, i, j: (b // h, 0, j))
    dq = pl.pallas_call(
        functools.partial(kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk, n_kv=n_kv,
                          lane_lse=lane, precision=precision),
        grid=(bn, n_q, n_kv),
        in_specs=mspec + [q_spec_qmajor, kv_spec_qmajor, kv_spec_qmajor,
                          q_spec_qmajor, row_spec_qmajor, row_spec_qmajor],
        out_specs=pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
        out_shape=_sds(q, q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=_CompilerParams(      # dq carry: kv dim only
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*margs, *common)

    # --- dk/dv: grid (bn, kv blocks, q blocks) ---
    q_spec = pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, 0))
    kv_spec = pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0))
    row_spec = (pl.BlockSpec((1, 1, bq), lambda b, j, i: (b, 0, i)) if lane
                else pl.BlockSpec((1, bq, 1), lambda b, j, i: (b, i, 0)))
    kernel, mspec, margs = with_mask(
        _bwd_dkv_kernel, lambda h, b, j, i: (b // h, 0, j))
    dk, dv = pl.pallas_call(
        functools.partial(kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk, n_q=n_q,
                          lane_lse=lane, precision=precision),
        grid=(bn, n_kv, n_q),
        in_specs=mspec + [q_spec, kv_spec, kv_spec, q_spec, row_spec,
                          row_spec],
        out_specs=[pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
                   pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0))],
        out_shape=[_sds(q, k.shape, k.dtype),
                   _sds(q, v.shape, v.dtype)],
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        compiler_params=_CompilerParams(      # dk/dv carry: q dim only
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*margs, *common)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash(q, k, v, mask, causal, block_q, block_k, interpret, precision):
    out, _ = _flash_fwd(q, k, v, mask, scale=q.shape[-1] ** -0.5,
                        causal=causal, block_q=block_q, block_k=block_k,
                        interpret=interpret, precision=precision)
    return out


def _flash_vjp_fwd(q, k, v, mask, causal, block_q, block_k, interpret,
                   precision):
    out, lse = _flash_fwd(q, k, v, mask, scale=q.shape[-1] ** -0.5,
                          causal=causal, block_q=block_q, block_k=block_k,
                          interpret=interpret, precision=precision)
    return out, (q, k, v, mask, out, lse)


def _flash_vjp_bwd(causal, block_q, block_k, interpret, precision, res, do):
    q, k, v, mask, out, lse = res
    dq, dk, dv = _flash_bwd(q, k, v, mask, out, lse, do,
                            scale=q.shape[-1] ** -0.5, causal=causal,
                            block_q=block_q, block_k=block_k,
                            interpret=interpret, precision=precision)
    return dq, dk, dv, None


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash_lse(q, k, v, mask, causal, block_q, block_k, interpret, precision):
    return _flash_fwd(q, k, v, mask, scale=q.shape[-1] ** -0.5,
                      causal=causal, block_q=block_q, block_k=block_k,
                      interpret=interpret, precision=precision)


def _flash_lse_vjp_fwd(q, k, v, mask, causal, block_q, block_k, interpret,
                       precision):
    out, lse = _flash_fwd(q, k, v, mask, scale=q.shape[-1] ** -0.5,
                          causal=causal, block_q=block_q, block_k=block_k,
                          interpret=interpret, precision=precision)
    return (out, lse), (q, k, v, mask, out, lse)


def _flash_lse_vjp_bwd(causal, block_q, block_k, interpret, precision, res,
                       cots):
    q, k, v, mask, out, lse = res
    do, dlse = cots
    dq, dk, dv = _flash_bwd(q, k, v, mask, out, lse, do,
                            scale=q.shape[-1] ** -0.5, causal=causal,
                            block_q=block_q, block_k=block_k,
                            interpret=interpret, precision=precision,
                            dlse=dlse)
    return dq, dk, dv, None


_flash_lse.defvjp(_flash_lse_vjp_fwd, _flash_lse_vjp_bwd)


def flash_mha_lse(q: jax.Array, k: jax.Array, v: jax.Array, *,
                  mask: jax.Array | None = None, causal: bool = False,
                  block_q: int = DEFAULT_BLOCK_Q,
                  block_k: int = DEFAULT_BLOCK_K,
                  interpret: bool | None = None,
                  precision=None) -> tuple[jax.Array, jax.Array]:
    """:func:`flash_mha` that also returns the logsumexp rows.

    Returns ``(out [B, S, N, D], lse [B, N, S] f32)``.  The lse output is
    differentiable (its cotangent folds into the backward's delta), which
    is what lets ring attention merge per-stage flash results exactly:
    ``out = Σ_i exp(lse_i - LSE)·out_i`` with both factors carrying
    gradient.  Fully-masked rows report ``lse = NEG_INF`` and zero
    output, so they contribute nothing to a merge.
    """
    if interpret is None:
        interpret = _auto_interpret()
    if not supported(q, k, block_q, block_k):
        raise ValueError(
            f"flash_mha_lse: shapes q={q.shape} k={k.shape} do not tile "
            f"into block_q={block_q}, block_k={block_k} blocks")
    b, s_q, n, d = q.shape

    def fold(x):  # [B, S, N, D] → [B*N, S, D]
        return x.transpose(0, 2, 1, 3).reshape(b * n, x.shape[1], d)

    mask = None if mask is None else mask.astype(jnp.int32)
    out, lse = _flash_lse(fold(q), fold(k), fold(v), mask, causal,
                          block_q, block_k, interpret, precision)
    return (out.reshape(b, n, s_q, d).transpose(0, 2, 1, 3),
            lse.reshape(b, n, s_q))


def flash_mha(q: jax.Array, k: jax.Array, v: jax.Array, *,
              mask: jax.Array | None = None, causal: bool = False,
              block_q: int = DEFAULT_BLOCK_Q, block_k: int = DEFAULT_BLOCK_K,
              interpret: bool | None = None,
              precision=None) -> jax.Array:
    """Flash multi-head attention.

    Args:
      q, k, v: ``[batch, seq, heads, head_dim]`` (the attention.py layout).
      mask: optional ``[batch, seq_kv]`` key-padding mask, 1 = attend.
      causal: apply a causal (autoregressive) mask; above-diagonal key/value
        blocks are skipped entirely, halving the work.
      interpret: run under the Pallas interpreter (defaults to True off-TPU,
        which is how the CPU test suite executes this kernel).
      precision: forwarded to every dot inside the kernels (fwd, recompute,
        bwd).  None = backend default (bf16 MXU products for f32 inputs on
        TPU); lax.Precision.HIGHEST requests multi-pass f32 — whether
        Mosaic honors it on-chip is probed by perf/exp_precision_probe.py.

    Returns ``[batch, seq, heads, head_dim]`` attention output in q's dtype.
    """
    if interpret is None:
        interpret = _auto_interpret()
    if not supported(q, k, block_q, block_k):
        raise ValueError(
            f"flash_mha: shapes q={q.shape} k={k.shape} do not tile into "
            f"block_q={block_q}, block_k={block_k} blocks; use "
            f"tpuframe.ops.attention.multihead_attention for the fallback")
    b, s_q, n, d = q.shape
    s_kv = k.shape[1]

    def fold(x):  # [B, S, N, D] → [B*N, S, D]
        return x.transpose(0, 2, 1, 3).reshape(b * n, x.shape[1], d)

    mask = None if mask is None else mask.astype(jnp.int32)
    out = _flash(fold(q), fold(k), fold(v), mask, causal,
                 block_q, block_k, interpret, precision)
    return out.reshape(b, n, s_q, d).transpose(0, 2, 1, 3)
