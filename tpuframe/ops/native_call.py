"""In-graph native (C++) custom calls via the XLA FFI — SURVEY.md §3b's
native-component demonstrator, complementing the out-of-graph ctypes host
runtime (tpuframe.native).

``normalize_u8(x, mean, std)``: the canonical input transform
(``(x/255 - mean)/std``, torchvision ToTensor+Normalize semantics) as ONE
multithreaded C++ kernel running inside the compiled program.  CPU
backend only — on TPU the same math belongs to on-device XLA fusion
(custom C++ cannot run there; pallas is the TPU kernel path), so the
public entry transparently falls back to the identical jnp expression
whenever the FFI kernel is unavailable or the backend isn't CPU.  The
two paths agree to the 1-ulp class (pinned by test): the kernel
precomputes per-channel scale/shift so its rounding order differs from
the literal ``(x/255 - mean)/std`` in the last bits.
"""

from __future__ import annotations

import os
import threading

import jax
import jax.numpy as jnp

_TARGET = "tf_normalize_u8"
_LOCK = threading.Lock()
_STATE: dict = {}  # {"registered": bool}


def _ffi_available() -> bool:
    """Register the kernel once; False when the toolchain/headers/backend
    make the native path unavailable (callers fall back, never fail)."""
    with _LOCK:
        if "registered" in _STATE:
            return _STATE["registered"]
        ok = False
        if (jax.default_backend() == "cpu"
                and os.environ.get("TPUFRAME_NO_NATIVE") != "1"):
            try:
                import ctypes

                from tpuframe.native.build import build_ffi

                lib = ctypes.CDLL(build_ffi())
                jax.ffi.register_ffi_target(
                    _TARGET, jax.ffi.pycapsule(lib.TfNormalizeU8),
                    platform="cpu")
                _STATE["lib"] = lib  # keep the dlopen handle alive
                ok = True
            except Exception:  # noqa: BLE001 — capability, not a hard dep
                ok = False
        _STATE["registered"] = ok
        return ok


def _jnp_reference(x, mean, std):
    return (x.astype(jnp.float32) / 255.0 - mean) / std


def normalize_u8(x: jax.Array, mean, std) -> jax.Array:
    """``(x/255 - mean[c]) / std[c]`` for uint8 ``[..., C]`` images.

    Inside jit on the CPU backend this lowers to the C++ FFI kernel;
    everywhere else it is the equivalent jnp expression.
    """
    mean = jnp.asarray(mean, jnp.float32)
    std = jnp.asarray(std, jnp.float32)
    # Shape guards ordered so scalar mean/std (grayscale-style calls) fall
    # back instead of tripping on shape[-1] of a 0-d array.
    if (mean.ndim != 1 or std.shape != mean.shape or x.ndim < 1
            or x.dtype != jnp.uint8 or x.shape[-1] != mean.shape[0]):
        return _jnp_reference(x, mean, std)
    if not _ffi_available():
        return _jnp_reference(x, mean, std)
    call = jax.ffi.ffi_call(
        _TARGET, jax.ShapeDtypeStruct(x.shape, jnp.float32))
    return call(x, mean, std)
