"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference never scales the sequence dimension (its longest workload is
BERT-base GLUE, seq ≤ 512 — SURVEY.md §5.7); this framework makes
long-context training first-class.  Both strategies run *inside* a
``shard_map`` over the mesh's ``seq`` axis, with the sequence dimension of
activations sharded across chips:

  * **Ring attention** — K/V chunks rotate around the ``seq`` axis ring via
    ``lax.ppermute`` (ICI neighbor hops); each device accumulates its query
    chunk's attention over every K/V chunk with online-softmax merging, so
    the full S×S score matrix never exists on any chip and per-chip memory
    is O(S/n).  This is the classic blockwise/ring formulation; gradients
    flow through the rotation automatically (the transpose of ppermute is
    the reverse ring).

  * **Ulysses** — two ``all_to_all``s re-shard [B, S/n, N, D] → [B, S, N/n, D]
    so each device sees the whole sequence for a subset of heads, runs plain
    (or pallas flash) attention locally, then re-shards back.  Cheaper in
    collective volume for moderate S; requires heads % seq_size == 0.

Both are numerically identical to full attention over the gathered sequence
(tests/test_seq_parallel.py asserts this against the XLA reference on the
8-device virtual mesh).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30  # matches tpuframe.ops.flash_attention.NEG_INF


def _chunk_attn_whole(q, k, v, keep, scale):
    """Unnormalized blockwise attention in f32 (scores fully materialized).

    q: [B, Cq, N, D]; k/v: [B, Ck, N, D]; keep: [B, 1, Cq, Ck] bool or None.
    Returns (acc [B, Cq, N, D] f32, m [B, N, Cq] f32, l [B, N, Cq] f32).
    """
    s = jnp.einsum("bqnd,bknd->bnqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if keep is not None:
        s = jnp.where(keep, s, NEG_INF)
    m = jnp.max(s, axis=-1)                                   # [B, N, Cq]
    p = jnp.exp(s - m[..., None])
    if keep is not None:
        p = jnp.where(keep, p, 0.0)  # fully-masked rows stay exactly zero
    l = jnp.sum(p, axis=-1)                                   # [B, N, Cq]
    acc = jnp.einsum("bnqk,bknd->bqnd", p, v.astype(jnp.float32),
                     preferred_element_type=jnp.float32)
    return acc, m, l


def _chunk_attn(q, k, v, keep, scale, q_chunk=None):
    """``_chunk_attn_whole`` with a bounded score footprint.

    The whole-chunk scores are [B, N, Cq, Ck] f32 — at 32k over 4 devices
    that is 12 x 8192^2 x 4 B = 3.2 GB per ring stage, which OOMs the chip
    (found by the offline v5e AOT compile, PERF.md §9).  ``q_chunk`` caps
    the live score block at [B, N, q_chunk, Ck] by lax.map-ing over query
    sub-chunks: rows are independent given a fixed K/V chunk, so results
    concatenate exactly — no extra merging, bit-identical math.
    """
    b, cq, nh, d = q.shape
    if q_chunk is None or cq <= q_chunk:
        return _chunk_attn_whole(q, k, v, keep, scale)
    n_sub, tail = divmod(cq, q_chunk)
    head = n_sub * q_chunk
    # jax.checkpoint: without it, lax.map's transpose STACKS each
    # sub-chunk's softmax residuals ([n_sub, B, N, q_chunk, Ck] f32 — and
    # the enclosing ring scan stacks that again per stage), which is the
    # multi-GB saved-buffer class the chunking exists to eliminate.  With
    # it, the backward recomputes one sub-chunk's scores at a time.
    core = jax.checkpoint(
        lambda qi, kp: _chunk_attn_whole(qi, k, v, kp, scale))
    qs = q[:, :head].reshape(b, n_sub, q_chunk, nh, d).transpose(
        1, 0, 2, 3, 4)
    if keep is not None:
        ck = keep.shape[-1]
        ks = keep[:, :, :head].reshape(
            b, 1, n_sub, q_chunk, ck).transpose(2, 0, 1, 3, 4)
        acc, m, l = lax.map(lambda xs: core(xs[0], xs[1]), (qs, ks))
    else:
        acc, m, l = lax.map(lambda qi: core(qi, None), qs)
    acc = acc.transpose(1, 0, 2, 3, 4).reshape(b, head, nh, d)
    m = m.transpose(1, 2, 0, 3).reshape(b, nh, head)
    l = l.transpose(1, 2, 0, 3).reshape(b, nh, head)
    if tail:
        # Ragged remainder: rows are independent, so one extra sub-chunk
        # keeps the result exact without re-admitting whole-chunk scores.
        acc_t, m_t, l_t = core(
            q[:, head:], None if keep is None else keep[:, :, head:])
        acc = jnp.concatenate([acc, acc_t], axis=1)
        m = jnp.concatenate([m, m_t], axis=-1)
        l = jnp.concatenate([l, l_t], axis=-1)
    return acc, m, l


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                   axis: str = "seq",
                   mask: jax.Array | None = None,
                   causal: bool = False,
                   q_chunk: int | None = 1024,
                   impl: str | None = None) -> jax.Array:
    """Exact attention over a sequence sharded across the ``axis`` ring.

    Must be called inside ``shard_map`` with ``axis`` bound.  Per-device
    inputs are the local sequence chunk ``[B, S/n, N, D]`` (and ``mask``
    ``[B, S/n]``, 1 = attend, for the *local keys*).  Output is the local
    query chunk's attention over the FULL sequence, ``[B, S/n, N, D]``.

    Causal masking uses global positions: device ``i``'s queries occupy
    ``[i*C, (i+1)*C)`` of the gathered sequence.

    ``q_chunk`` bounds the per-stage score materialization (see
    ``_chunk_attn``); identical results, identical wire traffic — only
    the live f32 score block shrinks.  None disables.

    ``impl`` selects the per-stage attention kernel — explicit argument,
    else ``TPUFRAME_ATTN_IMPL``, else ``xla``:

      * ``"xla"`` — the chunked einsum stages below (always available).
      * ``"pallas"`` — each stage is the flash kernel
        (:func:`tpuframe.ops.flash_attention.flash_mha_lse`); stages
        merge via logsumexp weights instead of raw (m, l).  The
        capacity audit (PERF.md §9) found the XLA stages lower-bound
        ring at ≥2x Ulysses+flash bytes at 32k — and ring is the
        documented FALLBACK exactly when heads don't divide the sp
        degree, so the fallback path gets the kernel too.  Causal
        masking is a stage-level trichotomy (owner below / on / above
        the diagonal), so above-diagonal stages skip all compute and
        the diagonal stage reuses the kernel's own block-skipping tri
        mask.  Unsupported shapes fall back to ``xla`` (same contract
        as tpuframe.ops.attention).
    """
    import os

    impl = impl or os.environ.get("TPUFRAME_ATTN_IMPL", "xla")
    if impl == "pallas":
        from tpuframe.ops import flash_attention as fa

        # Interpreter guard: the pallas HLO interpreter's internal
        # slicing trips shard_map's vma check (see the CPU tests'
        # check_vma=False concession), so a config that requests pallas
        # ring stages quietly keeps the numerically-identical XLA stages
        # when the kernel would interpret (CPU harness runs, dryrun) —
        # real-TPU and offline-AOT contexts lower Mosaic and take the
        # flash path.  TPUFRAME_RING_FLASH_INTERPRET=1 forces the flash
        # stages under the interpreter (the kernel tests do, with
        # check_vma=False shard_maps).
        interpreting = fa._auto_interpret()
        forced = os.environ.get("TPUFRAME_RING_FLASH_INTERPRET") == "1"
        if fa.supported(q, k) and (mask is None or mask.ndim == 2) \
                and (not interpreting or forced):
            return _ring_flash(q, k, v, axis=axis, mask=mask, causal=causal)
        impl = "xla"
    elif impl != "xla":
        raise ValueError(f"unknown ring attention impl {impl!r}")

    n = lax.axis_size(axis)
    my = lax.axis_index(axis)
    b, c, heads, d = q.shape
    scale = d ** -0.5
    perm = [(i, (i + 1) % n) for i in range(n)]  # rotate kv chunks rightward

    def make_keep(kv_owner, kv_mask):
        keep = None
        if kv_mask is not None:
            keep = (kv_mask != 0)[:, None, None, :]           # [B,1,1,Ck]
            keep = jnp.broadcast_to(keep, (b, 1, c, c))
        if causal:
            q_pos = my * c + jnp.arange(c)[:, None]           # [Cq, 1]
            kv_pos = kv_owner * c + jnp.arange(c)[None, :]    # [1, Ck]
            tri = (q_pos >= kv_pos)[None, None]               # [1,1,Cq,Ck]
            tri = jnp.broadcast_to(tri, (b, 1, c, c))
            keep = tri if keep is None else jnp.logical_and(keep, tri)
        return keep

    def step(carry, i):
        acc, m, l, kv_k, kv_v, kv_mask = carry
        kv_owner = (my - i) % n  # whose chunk we hold after i rotations
        # checkpoint: the ring scan's transpose must save only the small
        # per-stage inputs (kv chunk, [B,Ck] mask, scalar owner), not the
        # stage's score-sized softmax residuals stacked n times — the keep
        # mask ([B,1,Cq,Ck]) is built INSIDE so it is recomputed too.
        def stage(qq, kk, vv, owner, kmask):
            return _chunk_attn(qq, kk, vv, make_keep(owner, kmask), scale,
                               q_chunk=q_chunk)

        acc_c, m_c, l_c = jax.checkpoint(stage)(q, kv_k, kv_v, kv_owner,
                                                kv_mask)
        m_new = jnp.maximum(m, m_c)
        a1 = jnp.exp(m - m_new)
        a2 = jnp.exp(m_c - m_new)
        # [B, N, Cq] stats scale the [B, Cq, N, D] accumulator.
        t = lambda x: x.transpose(0, 2, 1)[..., None]  # noqa: E731
        acc = acc * t(a1) + acc_c * t(a2)
        l = l * a1 + l_c * a2
        m = m_new
        kv_k = lax.ppermute(kv_k, axis, perm)
        kv_v = lax.ppermute(kv_v, axis, perm)
        if kv_mask is not None:
            kv_mask = lax.ppermute(kv_mask, axis, perm)
        return (acc, m, l, kv_k, kv_v, kv_mask), None

    # Fresh accumulators are unvarying; mark them varying over the same mesh
    # axes as q so the scan carry type is stable under shard_map's vma checks.
    vary = lambda x: lax.pcast(  # noqa: E731
        x, tuple(jax.typeof(q).vma), to="varying")
    init = (
        vary(jnp.zeros((b, c, heads, d), jnp.float32)),
        vary(jnp.full((b, heads, c), NEG_INF, jnp.float32)),
        vary(jnp.zeros((b, heads, c), jnp.float32)),
        k, v, mask,
    )
    (acc, m, l, *_), _ = lax.scan(step, init, jnp.arange(n))
    l = l.transpose(0, 2, 1)[..., None]                       # [B, Cq, N, 1]
    return (acc / jnp.where(l == 0.0, 1.0, l)).astype(q.dtype)


def _ring_flash(q, k, v, *, axis, mask, causal):
    """Ring attention with flash-kernel stages (see ring_attention docs).

    Each stage returns the kernel's normalized output plus its logsumexp
    rows; stages merge exactly via

        LSE' = logaddexp(LSE, lse_i)
        out' = out·exp(LSE - LSE') + out_i·exp(lse_i - LSE')

    which equals the (acc, m, l) online-softmax merge of the XLA path.
    Both merge factors carry gradient: flash_mha_lse's backward folds the
    lse cotangent into its delta rows, so XLA autodiff of this merge +
    the per-stage custom_vjp is the exact ring backward.  Stages sit
    under jax.checkpoint like the XLA path — the scan saves only rotated
    kv chunks, never per-stage kernel residuals.
    """
    from tpuframe.ops import flash_attention as fa

    n = lax.axis_size(axis)
    my = lax.axis_index(axis)
    b, c, heads, d = q.shape
    perm = [(i, (i + 1) % n) for i in range(n)]

    vary = lambda x: lax.pcast(  # noqa: E731
        x, tuple(jax.typeof(q).vma), to="varying")

    def stage(qq, kk, vv, owner, kmask):
        def run(causal_flag):
            def f(_):
                return fa.flash_mha_lse(qq, kk, vv, mask=kmask,
                                        causal=causal_flag)
            return f

        if not causal:
            return run(False)(None)

        def above(_):
            # Strictly above the diagonal: nothing attends — no kernel
            # launch, zero contribution, zero gradient to this kv chunk.
            return (vary(jnp.zeros((b, c, heads, d), qq.dtype)),
                    vary(jnp.full((b, heads, c), NEG_INF, jnp.float32)))

        idx = jnp.where(owner < my, 0, jnp.where(owner == my, 1, 2))
        return lax.switch(idx, [run(False), run(True), above], None)

    def step(carry, i):
        out_acc, lse_acc, kv_k, kv_v, kv_mask = carry
        owner = (my - i) % n
        o_i, lse_i = jax.checkpoint(stage)(q, kv_k, kv_v, owner, kv_mask)
        lse_new = jnp.logaddexp(lse_acc, lse_i)            # [B, N, C]
        w1 = jnp.exp(lse_acc - lse_new)
        w2 = jnp.exp(lse_i - lse_new)
        t = lambda x: x.transpose(0, 2, 1)[..., None]  # noqa: E731
        out_acc = out_acc * t(w1) + o_i.astype(jnp.float32) * t(w2)
        kv_k = lax.ppermute(kv_k, axis, perm)
        kv_v = lax.ppermute(kv_v, axis, perm)
        if kv_mask is not None:
            kv_mask = lax.ppermute(kv_mask, axis, perm)
        return (out_acc, lse_new, kv_k, kv_v, kv_mask), None

    init = (
        vary(jnp.zeros((b, c, heads, d), jnp.float32)),
        vary(jnp.full((b, heads, c), NEG_INF, jnp.float32)),
        k, v, mask,
    )
    (out, _lse, *_), _ = lax.scan(step, init, jnp.arange(n))
    return out.astype(q.dtype)


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                      axis: str = "seq",
                      mask: jax.Array | None = None,
                      causal: bool = False,
                      impl: str | None = None) -> jax.Array:
    """All-to-all (DeepSpeed-Ulysses-style) sequence-parallel attention.

    Re-shards seq→heads so each device runs full-sequence attention on
    ``heads/n`` heads — the inner attention is the regular dispatch
    (``tpuframe.ops.attention``), so the pallas flash kernel applies.
    Requires ``heads % axis_size == 0``.
    """
    from tpuframe.ops import attention as attn_ops

    n = lax.axis_size(axis)
    b, c, heads, d = q.shape
    if heads % n != 0:
        raise ValueError(f"ulysses needs heads ({heads}) % seq axis ({n}) == 0")

    def to_heads(x):  # [B, S/n, N, D] → [B, S, N/n, D]
        return lax.all_to_all(x, axis, split_axis=2, concat_axis=1, tiled=True)

    def to_seq(x):    # [B, S, N/n, D] → [B, S/n, N, D]
        return lax.all_to_all(x, axis, split_axis=1, concat_axis=2, tiled=True)

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
    full_mask = None
    if mask is not None:
        full_mask = lax.all_gather(mask, axis, axis=1, tiled=True)  # [B, S]
    out = attn_ops.multihead_attention(qh, kh, vh, mask=full_mask,
                                        causal=causal, impl=impl)
    return to_seq(out)
