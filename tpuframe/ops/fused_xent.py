"""Chunked, fused softmax cross-entropy over a large vocabulary.

The reference computed LM/classifier losses the eager-torch way: materialize
``logits = h @ W`` ``[T, V]``, then softmax+gather (SURVEY.md §3a model
rows).  On TPU that is an HBM-traffic problem, not a FLOP problem: at
B*S = 16k tokens and V = 32k, the logits tensor is 1 GB in bf16 (plus f32
softmax intermediates, plus the same again in backward), all of it
round-tripping HBM on a step that is already bandwidth-bound.

This op computes the exact same loss with the logits never resident in HBM:
a ``lax.scan`` over vocab chunks keeps running (max, sumexp, target-logit)
statistics — the online-logsumexp recurrence flash attention uses along the
key axis, applied to the vocab axis — and the backward pass recomputes each
chunk's logits from the saved logsumexp instead of storing probabilities
(custom VJP).  Peak extra memory is one ``[T, chunk]`` block; matmuls stay
MXU-shaped ([T, H] x [H, chunk]).

No approximation: forward losses match the naive path to accumulation
rounding, gradients are the analytic ``(softmax - onehot)`` pulled through
the same chunking.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

DEFAULT_CHUNK = 8192
NEG_INF = -1e30


def _vary_like(x: jax.Array, ref: jax.Array) -> jax.Array:
    """Match ``x``'s varying-mesh-axes to ``ref``'s so scan carries agree
    inside ``shard_map`` (fresh zeros are unvarying; body outputs derived
    from the sharded hidden states are varying)."""
    want = getattr(jax.typeof(ref), "vma", frozenset())
    have = getattr(jax.typeof(x), "vma", frozenset())
    missing = tuple(want - have)
    return lax.pcast(x, missing, to="varying") if missing else x


def _pad_vocab(w: jax.Array, chunk: int) -> tuple[jax.Array, int]:
    v = w.shape[1]
    vp = ((v + chunk - 1) // chunk) * chunk
    if vp != v:
        w = jnp.pad(w, ((0, 0), (0, vp - v)))
    return w, vp


def _chunk_logits(h, w, c_idx, chunk, v):
    """f32 ``[T, chunk]`` logits for one vocab chunk; padded columns and
    (by the caller's mask) out-of-range labels read as NEG_INF."""
    wc = lax.dynamic_slice(w, (0, c_idx * chunk), (w.shape[0], chunk))
    s = lax.dot_general(h, wc.astype(h.dtype), (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32)
    cols = c_idx * chunk + lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(cols < v, s, NEG_INF)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _fused(h, w, labels, chunk):
    (loss, arg), _ = _fused_fwd(h, w, labels, chunk)
    return loss, arg


def _fused_fwd(h, w, labels, chunk):
    t = h.shape[0]
    v = w.shape[1]
    wp, vp = _pad_vocab(w, chunk)
    n = vp // chunk

    def body(carry, c_idx):
        m, l, tgt, arg = carry
        s = _chunk_logits(h, wp, c_idx, chunk, v)
        m_c = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m, m_c)
        l = l * jnp.exp(m - m_new) + jnp.sum(jnp.exp(s - m_new[:, None]),
                                             axis=-1)
        # argmax rides along for free (the per-chunk max is already here):
        # the metrics companion costs no extra vocab sweep.
        a_c = c_idx * chunk + jnp.argmax(s, axis=-1).astype(jnp.int32)
        arg = jnp.where(m_c > m, a_c, arg)
        loc = labels - c_idx * chunk
        in_c = (loc >= 0) & (loc < chunk)
        picked = jnp.take_along_axis(
            s, jnp.clip(loc, 0, chunk - 1)[:, None], axis=-1)[:, 0]
        tgt = tgt + jnp.where(in_c, picked, 0.0)
        return (m_new, l, tgt, arg), None

    init = tuple(_vary_like(a, h) for a in (
        jnp.full((t,), NEG_INF, jnp.float32),
        jnp.zeros((t,), jnp.float32),
        jnp.zeros((t,), jnp.float32),
        jnp.zeros((t,), jnp.int32)))
    (m, l, tgt, arg), _ = lax.scan(body, init, jnp.arange(n))
    lse = m + jnp.log(l)
    # labels < 0 mark ignored tokens (ignore_index is remapped to -1 by the
    # public wrappers): zero loss here, zero gradient in _fused_bwd.
    loss = jnp.where(labels >= 0, lse - tgt, 0.0)
    return (loss, arg), (h, w, labels, lse)


def _fused_bwd(chunk, res, g):
    h, w, labels, lse = res
    g = g[0]  # (loss cotangent, argmax cotangent): argmax is int, no grad
    v = w.shape[1]
    wp, vp = _pad_vocab(w, chunk)
    n = vp // chunk

    def body(dh, c_idx):
        wc = lax.dynamic_slice(wp, (0, c_idx * chunk), (w.shape[0], chunk))
        s = _chunk_logits(h, wp, c_idx, chunk, v)
        p = jnp.exp(s - lse[:, None])                       # [T, C] f32
        loc = labels - c_idx * chunk
        cols = lax.broadcasted_iota(jnp.int32, p.shape, 1)
        onehot = (cols == loc[:, None]) & (loc >= 0)[:, None]
        gvec = jnp.where(labels >= 0, g, 0.0)  # ignored tokens: no gradient
        gmat = ((p - onehot.astype(jnp.float32)) * gvec[:, None]).astype(h.dtype)
        dh = dh + lax.dot_general(
            gmat, wc.astype(h.dtype), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        dwc = lax.dot_general(h, gmat, (((0,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)
        return dh, dwc

    dh, dwc_stack = lax.scan(
        body, _vary_like(jnp.zeros(h.shape, jnp.float32), h), jnp.arange(n))
    # dwc_stack: [n_chunks, H, chunk] -> [H, Vp] -> drop padding columns.
    dw = dwc_stack.transpose(1, 0, 2).reshape(w.shape[0], vp)[:, :v]
    # custom_vjp bypasses shard_map's automatic transpose-psum for an
    # unvarying (replicated) w used in a varying computation: reduce dw
    # over the axes w lacks relative to h so its cotangent matches w's
    # replication (total gradient = sum of per-shard token sums).  No-op
    # outside shard_map and in the explicit pcast-varying-params mode.
    missing = tuple(getattr(jax.typeof(h), "vma", frozenset())
                    - getattr(jax.typeof(w), "vma", frozenset()))
    if missing:
        dw = lax.psum(dw, missing)
    return dh.astype(h.dtype), dw.astype(w.dtype), None


_fused.defvjp(_fused_fwd, _fused_bwd)


def fused_softmax_xent(hidden: jax.Array, w: jax.Array, labels: jax.Array,
                       *, chunk: int = DEFAULT_CHUNK,
                       ignore_index: int | None = None) -> jax.Array:
    """Per-token cross-entropy of ``softmax(hidden @ w)`` vs ``labels``.

    Args:
      hidden: ``[..., H]`` final hidden states (any float dtype; matmuls run
        in that dtype with f32 accumulation).
      w: ``[H, V]`` output-projection kernel (the LM head).
      labels: ``[...]`` int targets in ``[0, V)``.
      chunk: vocab tile width; V is internally padded up to a multiple.
      ignore_index: torch ``F.cross_entropy(ignore_index=...)`` parity —
        tokens with that label get zero loss AND zero gradient.  Their
        per-token entries are 0; for torch's 'mean' reduction divide the
        sum by the valid count (``(labels != ignore_index).sum()``).

    Returns per-token losses with ``labels``' shape, float32.
    """
    loss, _ = fused_softmax_xent_and_argmax(hidden, w, labels, chunk=chunk,
                                            ignore_index=ignore_index)
    return loss


def fused_softmax_xent_and_argmax(
        hidden: jax.Array, w: jax.Array, labels: jax.Array,
        *, chunk: int = DEFAULT_CHUNK,
        ignore_index: int | None = None) -> tuple[jax.Array, jax.Array]:
    """Like :func:`fused_softmax_xent` but also returns the per-token
    argmax prediction — computed inside the same vocab sweep (the per-chunk
    max already exists for the online logsumexp), so token accuracy costs
    no extra pass."""
    lead = hidden.shape[:-1]
    hid = hidden.reshape(-1, hidden.shape[-1])
    lab = labels.reshape(-1).astype(jnp.int32)
    if ignore_index is not None:
        # the kernel's internal ignore convention is negative labels
        lab = jnp.where(lab == ignore_index, -1, lab)
    if hid.shape[0] != lab.shape[0]:
        raise ValueError(f"hidden {hidden.shape} / labels {labels.shape} "
                         f"token counts differ")
    loss, arg = _fused(hid, w, lab, int(chunk))
    return loss.reshape(lead), arg.reshape(lead)


def mean_xent_and_accuracy(hidden: jax.Array, w: jax.Array,
                           labels: jax.Array, *,
                           chunk: int = DEFAULT_CHUNK,
                           ignore_index: int | None = None,
                           reduce_axis=None) -> tuple[jax.Array, jax.Array]:
    """(mean loss, token accuracy) through the fused head — the one shared
    definition the harness loss/metric fns and the pipeline step all call,
    so train and eval math cannot drift.  With ``ignore_index`` both the
    loss mean and the accuracy divide by the valid-token count, globally
    across ``reduce_axis`` mesh shards (losses.masked_mean: per-shard
    means pmean-ed uniformly are biased under unequal padding)."""
    per_tok, pred = fused_softmax_xent_and_argmax(
        hidden, w, labels, chunk=chunk, ignore_index=ignore_index)
    hit = (pred == labels).astype(jnp.float32)
    if ignore_index is None:
        return jnp.mean(per_tok), jnp.mean(hit)
    from tpuframe.models.losses import masked_mean  # lazy: no import cycle

    return (masked_mean(per_tok, labels, ignore_index, reduce_axis),
            masked_mean(hit, labels, ignore_index, reduce_axis))


def chunked_argmax(hidden: jax.Array, w: jax.Array,
                   *, chunk: int = DEFAULT_CHUNK) -> jax.Array:
    """argmax of ``hidden @ w`` without materializing the logits — the
    metrics companion to :func:`fused_softmax_xent` (token accuracy)."""
    lead = hidden.shape[:-1]
    hid = hidden.reshape(-1, hidden.shape[-1])
    v = w.shape[1]
    wp, vp = _pad_vocab(w, chunk)
    n = vp // chunk

    def body(carry, c_idx):
        best, arg = carry
        s = _chunk_logits(hid, wp, c_idx, chunk, v)
        m = jnp.max(s, axis=-1)
        a = c_idx * chunk + jnp.argmax(s, axis=-1).astype(jnp.int32)
        take = m > best
        return (jnp.where(take, m, best), jnp.where(take, a, arg)), None

    init = tuple(_vary_like(a, hid) for a in (
        jnp.full((hid.shape[0],), NEG_INF, jnp.float32),
        jnp.zeros((hid.shape[0],), jnp.int32)))
    (_, arg), _ = lax.scan(body, init, jnp.arange(n))
    return arg.reshape(lead)
