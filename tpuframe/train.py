"""Training harness (L4) — the reference's ``train.py``, TPU-native.

Reference flow (SURVEY.md §4.1): init Horovod → pin GPU → build model/data/
optimizer → broadcast params → epoch loop with async allreduce hooks.
Here: bootstrap → mesh → compiled SPMD step → host loop that only feeds
sharded batches, logs, evals and checkpoints.

CLI:
    python -m tpuframe.train --config cifar10_resnet18 \
        [--set total_steps=100 --set global_batch=64] [--data-dir PATH] \
        [--ckpt-dir PATH]

Every workload config ([B:6–12]) runs through this one entry point, from
single-process MNIST to the multi-host pod launch (tpuframe.launch execs this
module on every worker).
"""

from __future__ import annotations

import argparse
import ast
import hashlib
import itertools
import os
import sys
import time
from dataclasses import dataclass
from typing import Any, Iterator

import jax
import jax.numpy as jnp
import numpy as np

from tpuframe import ckpt as ckpt_lib
from tpuframe import models
from tpuframe.data import ShardedLoader, datasets
from tpuframe.models import losses
from tpuframe.obs import (Heartbeat, MetricLogger, RateMeter, StepTimeline,
                          parse_trace_steps, profile_trace,
                          start_profiler_server)
from tpuframe.obs import devmem as devmem_lib
from tpuframe.obs import events as events_lib
from tpuframe.obs import exporter as exporter_lib
from tpuframe.obs import flight as flight_lib
from tpuframe.obs import goodput as goodput_lib
from tpuframe.obs import metrics as obs_metrics
from tpuframe.parallel import bootstrap
from tpuframe.resilience import faults as faults_lib
from tpuframe.resilience.preempt import RC_PREEMPTED, PreemptionGuard
from tpuframe.parallel import mesh as mesh_lib
from tpuframe.parallel import step as step_lib
from tpuframe.utils import build_optimizer, get_config
from tpuframe.utils.config import TrainConfig


def build_datasets(cfg: TrainConfig):
    builder = {
        "mnist": datasets.mnist,
        "cifar10": datasets.cifar10,
        "imagenet": datasets.imagenet,
        "glue_sst2": datasets.glue_sst2,
        "glue_mnli": datasets.glue_mnli,
        "glue_stsb": datasets.glue_stsb,
        "glue_cola": datasets.glue_cola,
        "lm_text": datasets.lm_text,
    }[cfg.dataset]
    return builder(cfg.data_dir, **cfg.dataset_kwargs)


def _is_text_task(cfg: TrainConfig) -> bool:
    return cfg.dataset in ("glue_sst2", "glue_mnli", "glue_stsb",
                           "glue_cola")


def _maybe_normalize(cfg: TrainConfig, x):
    """On-device normalization for uint8 image batches (datasets built
    with ``keep_u8=True``: 1 byte/px over the host→device link, 4x less
    host RAM).  XLA fuses this into the first conv's input read on TPU;
    on CPU hosts it lowers to the native FFI kernel
    (tpuframe.ops.native_call).  Float batches pass through — they were
    normalized on the host at build time."""
    if x.dtype != jnp.uint8:
        return x
    from tpuframe.ops.native_call import normalize_u8

    if cfg.data_dir is None:
        # Synthetic u8 is quantized [0,1]-scale data: de-quantize only, so
        # the u8 and f32 synthetic paths feed the same distribution.
        mean, std = np.float32(0.0), np.float32(1.0)
    else:
        # Real data: the same per-dataset constants the f32 builder branch
        # applies on the host.
        mean, std = {
            "imagenet": (datasets.IMAGENET_MEAN, datasets.IMAGENET_STD),
            "cifar10": (datasets.CIFAR_MEAN, datasets.CIFAR_STD),
        }.get(cfg.dataset, (np.float32(0.0), np.float32(1.0)))
    mean = np.broadcast_to(np.asarray(mean, np.float32), (x.shape[-1],))
    std = np.broadcast_to(np.asarray(std, np.float32), (x.shape[-1],))
    return normalize_u8(x, mean, std)


def _is_regression_task(cfg: TrainConfig) -> bool:
    # HF convention, enforced as stated: num_labels == 1 ⇒ regression
    # (STS-B) — MSE on the squeezed single logit, no accuracy metric.
    return cfg.model_kwargs.get("num_classes") == 1


def _is_lm_task(cfg: TrainConfig) -> bool:
    return cfg.dataset == "lm_text"


def _cfg_batch_axes(cfg: TrainConfig) -> tuple:
    """The config's data-parallel mesh axes — slice-aware: a multi-slice
    MeshSpec replicates data over the DCN ``slice`` axis too, so batch
    partitions and loss means must range over it (the mesh-aware
    ``mesh_lib.batch_axes`` twin, derivable before the mesh exists)."""
    if getattr(cfg.mesh, "slices", 1) > 1:
        return (mesh_lib.SLICE_AXIS, *mesh_lib.BATCH_AXES)
    return mesh_lib.BATCH_AXES


def _batch_layout(cfg: TrainConfig):
    """(loader partition, step batch_partition, reduce axes) for the config.
    Sequence-parallel configs shard the batch's seq dim and extend the loss
    mean over the seq axis; everything else uses the pure batch layout."""
    from jax.sharding import PartitionSpec as P
    if cfg.shard_seq:
        axes = _cfg_batch_axes(cfg)
        part = P(axes, "seq")
        return part, part, (*axes, "seq")
    return None, None, None


@dataclass
class Harness:
    """Everything the loop needs, built once from a config."""

    cfg: TrainConfig
    mesh: Any
    model: Any
    state: step_lib.TrainState
    train_step: Any
    eval_step: Any
    train_loader: ShardedLoader
    eval_loader: ShardedLoader
    manager: ckpt_lib.CheckpointManager | None
    start_step: int
    # (policy name, resolution source) from tpuframe.mem.resolve —
    # ("none", "default") when nothing elected a remat policy.
    remat_policy: tuple = ("none", "default")
    # (mode, resolution source) from tpuframe.parallel.zero1.resolve —
    # ("replicated", "default") when nothing elected weight-update sharding.
    weight_update: tuple = ("replicated", "default")
    # (format, resolution source) from tpuframe.parallel.quantwire.resolve
    # — ("fp", "default") when nothing elected a quantized wire.
    wire_format: tuple = ("fp", "default")
    # (format, resolution source) for the cross-slice DCN leg from
    # tpuframe.parallel.quantwire.resolve_legs — ("fp", "default") when
    # nothing elected a quantized DCN wire (needs hier="hier").
    wire_format_dcn: tuple = ("fp", "default")
    # (mode, resolution source) from tpuframe.parallel.hier.resolve —
    # ("flat", "default") when nothing elected two-level collectives.
    hier: tuple = ("flat", "default")
    # (bucket threshold bytes, resolution source) from
    # tpuframe.parallel.fusion.resolve — (None, "default") when nothing
    # elected bucketed gradient fusion (per-leaf collectives).
    fusion_threshold: tuple = (None, "default")
    # (canonical spec string, resolution source) from
    # tpuframe.parallel.pspec.resolve — (None, "default") when the mesh
    # came from the config rather than a TPUFRAME_SPEC declaration.
    pspec: tuple = (None, "default")
    # Full provenance of an elastic n→n′ resize detected at build time
    # (committed checkpoint world ≠ current world), or None.  Emitted as
    # the typed ``elastic_resize`` run event.
    elastic_resize: dict | None = None


def _resolved_fusion(cfg: TrainConfig) -> tuple:
    """The step program's gradient-fusion bucket threshold with its
    provenance: TPUFRAME_FUSION_THRESHOLD env > the tuning DB's
    generation-gated ``fusion_threshold`` sweep winner > None
    (per-leaf).  One shared resolution for :func:`build_harness` and
    :func:`_lm_reduce_axis`, so the explicit-fusion step mode and its
    local-loss requirement cannot disagree about whether fusion is on."""
    from tpuframe.parallel import fusion as fusion_lib
    from tpuframe.parallel import quantwire

    model_tag = cfg.model.replace("-", "_")
    program = f"train_{model_tag}_b{cfg.global_batch}"
    threshold, source = fusion_lib.resolve(program=program,
                                           family="fusion_threshold")
    if threshold is not None and source != "env":
        (wf, wf_src), _ = quantwire.resolve_legs(
            program=program, family=f"wire_format_{model_tag}")
        if wf != "fp" and wf_src == "env":
            # An explicit env-elected quantized wire owns the gradient
            # path; the advisory DB-elected bucket threshold yields.
            threshold, source = None, "default"
    return threshold, source


def build_harness(cfg: TrainConfig) -> Harness:
    bootstrap.initialize()
    # Declarative parallelism spec: a TPUFRAME_SPEC declaration
    # ("dp=4,fsdp=2;slices=2") wins over the config's mesh — one string
    # names the whole hierarchical ICI×DCN layout, and the MeshSpec it
    # lowers to flows through every seam below (world resolution,
    # sharded-state detection, batch axes) unchanged.
    from tpuframe.parallel import pspec as pspec_lib

    spec, spec_source = pspec_lib.resolve()
    if spec is None:
        # Planner fallback: a `tune plan` winner (tune_db.json, family
        # plan_spec) supplies the spec when neither an argument nor
        # TPUFRAME_SPEC declared one — env > DB > default, the same
        # precedence every other tuned knob resolves under.  Gated on a
        # known target generation, so plain CPU test runs stay on the
        # config's mesh.
        from tpuframe.tune import db as tune_db

        planned = tune_db.resolve_spec("train_lm_tiny")
        if planned is not None:
            try:
                spec, spec_source = pspec_lib.parse_spec(planned), "plan"
            except pspec_lib.SpecError as e:
                raise pspec_lib.SpecError(
                    f"tune_db.json plan_spec winner {planned!r} does not "
                    f"parse: {e} — re-run `python -m tpuframe.tune plan` "
                    f"or set TPUFRAME_SPEC to override") from e
    if spec is not None:
        cfg = cfg.with_overrides(mesh=spec.mesh_spec())
        if bootstrap.is_primary():
            print(f"[tpuframe] parallelism spec '{spec.canonical()}' "
                  f"({spec_source}) -> mesh {cfg.mesh}", flush=True)
    # World resolution goes through the elastic resolver — the single
    # source of truth train.py and bench.py share, read at call time so a
    # relaunch at a new world size can never see a stale capture.
    from tpuframe import elastic as elastic_lib

    world = elastic_lib.current_world(cfg.mesh, distributed=cfg.distributed)
    mesh = world.mesh
    # Elastic resize detection: resuming onto a different world size than
    # the latest committed checkpoint was written at.  The declared
    # policy (TPUFRAME_ELASTIC_RESCALE: hold/linear/sqrt) rescales global
    # batch + LR HERE, before loaders and optimizer are built, so the
    # whole harness sees the post-resize config; restore then reshards
    # the ZeRO-1 state n→n′ from shapes alone (ckpt/checkpoint.py).
    elastic_resize = None
    if cfg.ckpt_dir is not None and cfg.resume:
        prev = ckpt_lib.committed_world(cfg.ckpt_dir)
        if prev and int(prev.get("devices", 0)) not in (0, world.n_devices):
            n_from = int(prev["devices"])
            policy, policy_src = elastic_lib.resolve_rescale()
            new_batch, new_lr = elastic_lib.rescale(
                cfg.global_batch, cfg.base_lr, n_from, world.n_devices,
                policy)
            elastic_resize = {
                "n_from": n_from,
                "n_to": world.n_devices,
                "processes_from": int(prev.get("processes", 0)) or None,
                "at_step": int(prev.get("step", 0)),
                "policy": policy,
                "policy_source": policy_src,
                "global_batch_from": cfg.global_batch,
                "global_batch_to": new_batch,
                "base_lr_from": cfg.base_lr,
                "base_lr_to": new_lr,
            }
            if (new_batch, new_lr) != (cfg.global_batch, cfg.base_lr):
                cfg = cfg.with_overrides(global_batch=new_batch,
                                         base_lr=new_lr)
            if bootstrap.is_primary():
                print(f"[tpuframe] elastic resize: {n_from}→"
                      f"{world.n_devices} devices at committed step "
                      f"{elastic_resize['at_step']} (policy={policy}, "
                      f"batch {elastic_resize['global_batch_from']}→"
                      f"{new_batch}, lr "
                      f"{elastic_resize['base_lr_from']:g}→{new_lr:g})",
                      flush=True)
    # Sharded-state (auto-SPMD) mode: ZeRO/FSDP over the fsdp axis and/or
    # Megatron-style TP over the model axis — both are placement decisions
    # living on the Auto-typed mesh twin (tpuframe.parallel.fsdp.auto_mesh).
    use_sharded_state = mesh is not None and (
        mesh.shape["fsdp"] > 1 or mesh.shape["model"] > 1
        or mesh.shape["expert"] > 1)
    data_mesh = mesh
    if use_sharded_state:
        from tpuframe.parallel import fsdp as fsdp_lib

        data_mesh = fsdp_lib.auto_mesh(mesh)

    dtype = jnp.dtype(cfg.compute_dtype)
    model = models.get_model(cfg.model, dtype=dtype, **cfg.model_kwargs)

    train_ds, eval_ds = build_datasets(cfg)
    # Labels out of the head's range don't crash — one_hot silently yields
    # all-zero rows, training "runs" with a nonsense loss and eval goes
    # NaN.  Catch the config error (e.g. num_classes=10 on the 1000-class
    # synthetic imagenet) at build time with a message instead.
    n_cls = cfg.model_kwargs.get("num_classes")
    if (n_cls is not None and n_cls > 1 and not _is_lm_task(cfg)):
        for split_name, ds in (("train", train_ds), ("eval", eval_ds)):
            labels = ds.columns.get("label")
            if labels is not None and np.issubdtype(labels.dtype,
                                                    np.integer) and len(labels):
                hi = int(labels.max())
                if hi >= n_cls:
                    raise ValueError(
                        f"{split_name} labels reach {hi} but the model head "
                        f"has num_classes={n_cls} — label range and head "
                        f"size must match (check model_kwargs/dataset)")
    loader_part, step_part, reduce_axes = _batch_layout(cfg)
    # Float inputs are host-cast to the compute dtype before transfer (the
    # model's first op would cast them on device anyway; bf16 halves
    # infeed bytes — same rounding, same losses).
    cast = dtype if dtype != jnp.float32 else None
    train_loader = ShardedLoader(train_ds, cfg.global_batch, data_mesh,
                                 seed=cfg.seed, partition=loader_part,
                                 cast_floats=cast)
    eval_loader = ShardedLoader(eval_ds, cfg.global_batch, data_mesh,
                                shuffle=False, partition=loader_part,
                                cast_floats=cast)

    sample = train_ds[:2]
    rng = jax.random.key(cfg.seed)
    if _is_text_task(cfg) or _is_lm_task(cfg):
        variables = model.init(rng, jnp.asarray(sample["input_ids"]))
    else:
        variables = model.init(
            rng, _maybe_normalize(cfg, jnp.asarray(sample["image"])))
    params = variables["params"]
    model_state = {k: v for k, v in variables.items() if k != "params"}

    use_pp = mesh is not None and mesh.shape["pipe"] > 1
    if use_pp and cfg.grad_clip_norm is not None:
        # optax's clip computes the norm from local leaf values — a
        # per-STAGE statistic under the pipe-sharded layout; build the
        # chain with the vma-aware cross-stage clip instead (once — pp
        # models sit near the memory limit, no throwaway Adam trees).
        import optax

        from tpuframe.parallel.pp_lm import pp_clip_by_global_norm

        tx = optax.chain(
            pp_clip_by_global_norm(cfg.grad_clip_norm),
            build_optimizer(cfg.with_overrides(grad_clip_norm=None),
                            params))
    else:
        tx = build_optimizer(cfg, params)
    state = step_lib.TrainState.create(params, tx, model_state=model_state,
                                       rng=jax.random.key(cfg.seed + 1))

    # Rematerialization policy: TPUFRAME_REMAT_POLICY env (or the legacy
    # TPUFRAME_BENCH_REMAT alias) wins, else the tuning DB's offline remat
    # sweep winner (generation-gated, like the XLA opts above), else none.
    from tpuframe import mem

    model_tag = cfg.model.replace("-", "_")
    remat_policy, remat_source = mem.resolve(
        program=f"train_{model_tag}_b{cfg.global_batch}",
        family=f"remat_{model_tag}")
    step_policy = None if remat_policy == "none" else remat_policy

    # Weight-update sharding (ZeRO-1): TPUFRAME_WEIGHT_UPDATE env wins,
    # else the tuning DB's offline weight_update_* sweep winner
    # (generation-gated), else replicated.  zero1 is the plain-DP
    # shard_map path only — on configs it cannot serve (pp, auto-SPMD
    # sharded state, no mesh, adasum) a DB-elected mode falls back
    # silently (a stale DB row must never break a run) while an explicit
    # env ask gets make_train_step's specific error.
    from tpuframe.parallel import zero1 as zero1_lib

    weight_update, wu_source = zero1_lib.resolve(
        program=f"train_{model_tag}_b{cfg.global_batch}",
        family=f"weight_update_{model_tag}")
    if (weight_update == "zero1" and wu_source != "env"
            and (use_pp or use_sharded_state or mesh is None
                 or cfg.grad_reduce == "adasum")):
        weight_update, wu_source = "replicated", "default"

    # Gradient-path wire format (int8-block quantized collectives): same
    # resolution shape — TPUFRAME_WIRE_FORMAT env wins, else the DB's
    # offline wire_format_* sweep winner (generation-gated), else full
    # precision.  Same fallback discipline too: on configs the quantized
    # wire cannot serve (pp, auto-SPMD sharded state, no mesh, adasum) a
    # DB-elected format falls back silently while an explicit env ask
    # gets make_train_step's specific error.
    from tpuframe.parallel import quantwire

    (wire_format, wf_source), (wire_format_dcn, wfd_source) = \
        quantwire.resolve_legs(
            program=f"train_{model_tag}_b{cfg.global_batch}",
            family=f"wire_format_{model_tag}",
            family_dcn="hier_collectives")
    if (wire_format != "fp" and wf_source != "env"
            and (use_pp or use_sharded_state or mesh is None
                 or cfg.grad_reduce == "adasum")):
        wire_format, wf_source = "fp", "default"

    # Hierarchical two-level collectives: TPUFRAME_HIER env wins, else
    # the DB's offline hier_collectives sweep winner (generation-gated),
    # else flat.  Same fallback discipline: on configs the two-level
    # lowering cannot serve (pp, auto-SPMD sharded state, no mesh,
    # adasum, a program-wide quantized wire, sequence sharding) a
    # DB-elected mode demotes silently while an explicit env ask gets
    # make_train_step's specific error.  The DCN-leg wire format rides
    # the lowering: without hier it demotes to fp the same way.
    from tpuframe.parallel import hier as hier_lib

    hier_mode, hier_source = hier_lib.resolve(
        program=f"train_{model_tag}_b{cfg.global_batch}",
        family=hier_lib.DB_FAMILY)
    if (hier_mode != "flat" and hier_source != "env"
            and (use_pp or use_sharded_state or mesh is None
                 or cfg.grad_reduce == "adasum" or wire_format != "fp"
                 or cfg.shard_seq)):
        hier_mode, hier_source = "flat", "default"
    if (wire_format_dcn != "fp" and wfd_source != "env"
            and hier_mode != "hier"):
        wire_format_dcn, wfd_source = "fp", "default"

    # GPipe pp takes no gradient-fusion modifier; the knob resolves (and
    # can be DB-elected) only on the shard_map branch below.
    fusion_threshold, ft_source = None, "default"

    if use_pp:
        # Pipeline parallelism: ScanBlockLM blocks + opt state sharded over
        # the pipe axis, GPipe microbatching (tpuframe.parallel.pp_lm).
        if cfg.model != "transformer-lm-pp":
            raise ValueError(
                f"mesh pipe={mesh.shape['pipe']} needs model="
                f"'transformer-lm-pp' (layer-stacked blocks); got "
                f"{cfg.model!r}")
        if use_sharded_state:
            raise ValueError("pipe parallelism does not compose with "
                             "fsdp/model/expert sharded-state axes yet")
        if cfg.accum_steps != 1:
            raise ValueError("pipe parallelism has its own microbatching "
                             "(pp_microbatches); accum_steps must be 1")
        if cfg.grad_reduce != "mean":
            raise ValueError("pipe parallelism supports grad_reduce='mean' "
                             "only (the pp step has its own cross-stage "
                             "reduction)")
        if cfg.shard_seq:
            raise ValueError("pipe parallelism does not compose with "
                             "shard_seq sequence parallelism yet")
        if weight_update == "zero1":
            raise ValueError("TPUFRAME_WEIGHT_UPDATE=zero1 is the plain-DP "
                             "shard_map path; the pipeline step owns its "
                             "own stage-sharded update")
        if wire_format != "fp":
            raise ValueError("TPUFRAME_WIRE_FORMAT=int8-block is the "
                             "plain-DP shard_map path; the pipeline step "
                             "owns its own cross-stage communication")
        if hier_mode != "flat":
            raise ValueError("TPUFRAME_HIER=hier is the plain-DP "
                             "shard_map path; the pipeline step owns its "
                             "own cross-stage communication")
        from tpuframe.parallel import pp_lm

        factory, place_state, _ = pp_lm.make_pp_lm_step(
            model, tx, mesh, n_micro=cfg.pp_microbatches,
            fused_xent=cfg.fused_xent, remat_policy=step_policy)
        state = place_state(state)
        train_step = factory(state)
        eval_step = pp_lm.make_pp_lm_eval(
            model, mesh, n_micro=cfg.pp_microbatches,
            fused_xent=cfg.fused_xent)(state)
    else:
        state_shardings = None
        if use_sharded_state:
            from tpuframe.parallel import fsdp as fsdp_lib

            tp_rules = None
            if mesh.shape["model"] > 1 or mesh.shape["expert"] > 1:
                from tpuframe.parallel import tp as tp_lib

                tp_rules = tp_lib.rules_for_model(cfg.model)
            state_shardings = fsdp_lib.state_shardings(state, mesh,
                                                       tp_rules=tp_rules)
            state = jax.tree.map(mesh_lib.host_device_put, state,
                                 state_shardings)
        elif mesh is not None:
            if weight_update == "zero1":
                # Optimizer state born sharded in zero1's flat padded
                # layout — never materialized replicated on any chip.
                state = zero1_lib.make_state(
                    params, tx, mesh, model_state=model_state,
                    rng=jax.random.key(cfg.seed + 1))
            else:
                state = step_lib.replicate_state(state, mesh)

        loss_fn = make_loss_fn(cfg, model)
        from tpuframe.tune import db as tune_db
        from tpuframe.utils import xla_opts as xla_opts_lib

        # Per-compile compiler options: TPUFRAME_XLA_OPTS env wins, else
        # the offline tuning DB (tpuframe.tune; only engages when the
        # target TPU generation is known).  This is how queue-6's
        # scheduler-flag A/Bs run through the real training loop.
        xla_opts = xla_opts_lib.from_env()
        if xla_opts is None:
            xla_opts = tune_db.resolve_xla_opts(cfg.name,
                                                family="train_step")
        # Gradient-fusion bucket threshold: same resolution shape as the
        # other knobs — TPUFRAME_FUSION_THRESHOLD env wins, else the
        # DB's generation-gated fusion_threshold sweep winner, else
        # per-leaf (the helper also yields a DB-elected threshold to an
        # env-elected quantized wire).  A DB-elected threshold serves
        # the shard_map gradient path only: where the step ignores the
        # knob (unmapped jit, auto-SPMD sharded state) it demotes
        # silently.
        fusion_threshold, ft_source = _resolved_fusion(cfg)
        if (fusion_threshold is not None and ft_source != "env"
                and (mesh is None or use_sharded_state)):
            fusion_threshold, ft_source = None, "default"
        if (wire_format != "fp" and wf_source != "env"
                and (fusion_threshold or cfg.grad_reduce == "adasum")):
            # Explicit-fusion mode reduces bucket-by-bucket inside the
            # step; the quantized wire only serves the implicit/zero1
            # paths.  A DB-elected format demotes silently here too.
            wire_format, wf_source = "fp", "default"
        if (wire_format_dcn != "fp" and wfd_source != "env"
                and fusion_threshold):
            # The quantized DCN leg rides the per-leaf hier lowering;
            # bucketed fusion concatenates leaves past the block
            # heuristics, so a DB-elected DCN format demotes silently.
            wire_format_dcn, wfd_source = "fp", "default"
        train_step = step_lib.make_train_step(
            loss_fn, tx, mesh, batch_partition=step_part,
            reduce_axes=reduce_axes, state_shardings=state_shardings,
            fusion_threshold=fusion_threshold,
            accum_steps=cfg.accum_steps,
            grad_reduce=cfg.grad_reduce,
            compiler_options=xla_opts,
            remat_policy=step_policy,
            weight_update=weight_update,
            wire_format=wire_format,
            hier=hier_mode,
            wire_format_dcn=wire_format_dcn)
        eval_step = step_lib.make_eval_step(
            make_metric_fn(cfg, model), mesh, batch_partition=step_part,
            reduce_axes=reduce_axes, state_shardings=state_shardings)

    manager = None
    start_step = 0
    if cfg.track_best and cfg.ckpt_dir is None:
        raise ValueError("track_best=True needs ckpt_dir (the best/ "
                         "checkpoint lives under it)")
    if cfg.ckpt_dir is not None:
        # TPUFRAME_ASYNC_CKPT overrides the config knob when set — the
        # ops-side switch for flipping a fleet to async saves (or back)
        # without touching run configs.
        async_env = os.environ.get("TPUFRAME_ASYNC_CKPT", "")
        ckpt_async = (async_env not in ("0", "false", "")
                      if async_env else cfg.ckpt_async)
        manager = ckpt_lib.CheckpointManager(
            cfg.ckpt_dir, every_steps=cfg.ckpt_every, keep=cfg.ckpt_keep,
            async_write=ckpt_async)
        if cfg.resume:
            resumed = manager.restore_latest(mesh=mesh, target=state)
            if resumed is not None:
                start_step, state = resumed
                if bootstrap.is_primary():
                    print(f"[tpuframe] resumed from step {start_step}",
                          flush=True)

    return Harness(cfg=cfg, mesh=mesh, model=model, state=state,
                   train_step=train_step, eval_step=eval_step,
                   train_loader=train_loader, eval_loader=eval_loader,
                   manager=manager, start_step=start_step,
                   remat_policy=(remat_policy, remat_source),
                   weight_update=(weight_update, wu_source),
                   wire_format=(wire_format, wf_source),
                   wire_format_dcn=(wire_format_dcn, wfd_source),
                   hier=(hier_mode, hier_source),
                   fusion_threshold=(fusion_threshold, ft_source),
                   pspec=(spec.canonical() if spec is not None else None,
                          spec_source),
                   elastic_resize=elastic_resize)


def _lm_reduce_axis(cfg: TrainConfig, *, for_grad: bool):
    """Mesh axes for the GLOBAL valid-token mean (losses.masked_mean):
    per-shard masked means pmean-ed uniformly are biased when shards hold
    unequal valid counts (padded_docs).  The explicit-fusion and
    grad-accumulation step modes differentiate a LOCAL loss and reduce
    gradients themselves — a psum inside the loss would mis-scale them —
    so the gradient-side global mean only applies in the default implicit
    mode, and the biased combination is refused outright."""
    axes = ((*_cfg_batch_axes(cfg), "seq") if cfg.shard_seq
            else _cfg_batch_axes(cfg))
    if not for_grad:
        return axes  # eval metrics have no explicit-reduction mode
    # The local-loss requirement only exists where make_train_step actually
    # takes the explicit path: shard_map mode (distributed, no sharded-state
    # axes).  Unmapped jit and auto-SPMD ignore the fusion knob and reduce
    # globally by construction; a psum with unbound axes is a no-op there.
    sharded_state = (cfg.mesh.fsdp > 1 or cfg.mesh.model > 1
                     or cfg.mesh.expert > 1)
    shard_map_mode = cfg.distributed and not sharded_state
    explicit = shard_map_mode and (_resolved_fusion(cfg)[0] is not None
                                   or cfg.accum_steps > 1
                                   or cfg.grad_reduce == "adasum")
    if not explicit:
        return axes
    if bool(cfg.dataset_kwargs.get("padded_docs")):
        raise ValueError(
            "padded_docs with TPUFRAME_FUSION_THRESHOLD, accum_steps>1 or "
            "grad_reduce='adasum' in shard_map mode: these paths need a "
            "local loss, and a per-shard valid-token mean would be biased "
            "by unequal padding across shards")
    return None  # local loss; no -100 labels, so per-shard mean is exact


def make_loss_fn(cfg: TrainConfig, model) -> step_lib.LossFn:
    if _is_lm_task(cfg):
        aux_w = float(cfg.model_kwargs.get("moe_aux_weight", 0.01))
        raxis = _lm_reduce_axis(cfg, for_grad=True)

        def loss_fn(params, model_state, batch, rng):
            if cfg.fused_xent:
                # Chunked fused head+loss: [B,S,V] logits never hit HBM
                # (tpuframe.ops.fused_xent); the argmax for token accuracy
                # rides in the same vocab sweep.
                from tpuframe.ops import fused_xent as fx

                hidden, sown = model.apply(
                    {"params": params, **model_state}, batch["input_ids"],
                    train=True, rngs={"dropout": rng},
                    mutable=["aux_loss"], hidden_only=True)
                loss, acc = fx.mean_xent_and_accuracy(
                    hidden, params["lm_head"]["kernel"], batch["labels"],
                    ignore_index=-100, reduce_axis=raxis)
                metrics = {"accuracy": acc}
            else:
                logits, sown = model.apply({"params": params, **model_state},
                                           batch["input_ids"], train=True,
                                           rngs={"dropout": rng},
                                           mutable=["aux_loss"])
                # ignore_index=-100: the torch/HF convention — padded
                # label positions (datasets.lm_text padded_docs) carry -100
                # and contribute neither loss nor gradient; a no-op for
                # packed streams with no negative labels.
                loss = losses.softmax_cross_entropy(logits, batch["labels"],
                                                    ignore_index=-100,
                                                    reduce_axis=raxis)
                metrics = {"accuracy": losses.accuracy(logits,
                                                       batch["labels"],
                                                       ignore_index=-100,
                                                       reduce_axis=raxis)}
            aux_leaves = jax.tree.leaves(sown)
            if aux_leaves:  # MoE load-balance penalty (tpuframe.ops.moe)
                aux = sum(aux_leaves) / len(aux_leaves)
                loss = loss + aux_w * aux
                metrics["moe_aux"] = aux
            return loss, (model_state, metrics)

        return loss_fn

    if _is_text_task(cfg):
        regression = _is_regression_task(cfg)

        def loss_fn(params, model_state, batch, rng):
            logits = model.apply(
                {"params": params, **model_state}, batch["input_ids"],
                batch["attention_mask"], batch["token_type_ids"], train=True,
                rngs={"dropout": rng})
            if regression:
                pred = logits[..., 0]
                loss = jnp.mean((pred - batch["label"]) ** 2)
                return loss, (model_state, {"mse": loss})
            loss = losses.softmax_cross_entropy(logits, batch["label"])
            return loss, (model_state,
                          {"accuracy": losses.accuracy(logits, batch["label"])})

        return loss_fn

    def loss_fn(params, model_state, batch, rng):
        images = batch["image"]
        if cfg.augment != "none":
            from tpuframe.data import augment as augment_lib

            aug_rng, rng = jax.random.split(rng)
            images = augment_lib.apply(cfg.augment, images, aug_rng,
                                       crop=cfg.augment_crop)
        outputs = model.apply(
            {"params": params, **model_state},
            _maybe_normalize(cfg, images), train=True,
            rngs={"dropout": rng},
            mutable=list(model_state) if model_state else False)
        if model_state:
            logits, mutated = outputs
            model_state = dict(mutated)
        else:
            logits = outputs
        loss = losses.softmax_cross_entropy(logits, batch["label"],
                                            cfg.label_smoothing)
        return loss, (model_state,
                      {"accuracy": losses.accuracy(logits, batch["label"])})

    return loss_fn


def make_metric_fn(cfg: TrainConfig, model):
    if _is_lm_task(cfg):
        if cfg.fused_xent:
            # Eval must honor the fused path too: lm_long's eval logits
            # would be ~4 GB f32 per 32k-token sequence otherwise.
            from tpuframe.ops import fused_xent as fx

            raxis = _lm_reduce_axis(cfg, for_grad=False)

            def metric_fn(params, model_state, batch):
                hidden = model.apply({"params": params, **model_state},
                                     batch["input_ids"], hidden_only=True)
                loss, acc = fx.mean_xent_and_accuracy(
                    hidden, params["lm_head"]["kernel"], batch["labels"],
                    ignore_index=-100, reduce_axis=raxis)
                return {"loss": loss, "perplexity": jnp.exp(loss),
                        "accuracy": acc}

            return metric_fn

        raxis = _lm_reduce_axis(cfg, for_grad=False)

        def metric_fn(params, model_state, batch):
            logits = model.apply({"params": params, **model_state},
                                 batch["input_ids"])
            loss = losses.softmax_cross_entropy(logits, batch["labels"],
                                                ignore_index=-100,
                                                reduce_axis=raxis)
            return {"loss": loss, "perplexity": jnp.exp(loss),
                    "accuracy": losses.accuracy(logits, batch["labels"],
                                                ignore_index=-100,
                                                reduce_axis=raxis)}

        return metric_fn

    if _is_text_task(cfg):
        regression = _is_regression_task(cfg)

        def metric_fn(params, model_state, batch):
            logits = model.apply({"params": params, **model_state},
                                 batch["input_ids"], batch["attention_mask"],
                                 batch["token_type_ids"])
            if regression:
                pred = logits[..., 0]
                y = batch["label"]
                mse = jnp.mean((pred - y) ** 2)
                # First/second moments as per-batch MEANS: evaluate()'s
                # averaging over equal-size batches then reproduces the
                # whole-set moments exactly, from which _finalize_eval
                # derives the task's standard Pearson r without a second
                # pass or per-example host traffic.
                return {"loss": mse, "mse": mse,
                        "_m_pred": jnp.mean(pred), "_m_y": jnp.mean(y),
                        "_m_pred2": jnp.mean(pred ** 2),
                        "_m_y2": jnp.mean(y ** 2),
                        "_m_py": jnp.mean(pred * y)}
            out = {"accuracy": losses.accuracy(logits, batch["label"]),
                   "loss": losses.softmax_cross_entropy(logits,
                                                        batch["label"])}
            if cfg.dataset == "glue_cola":
                # Confusion-rate moments: equal-size eval batches mean
                # evaluate()'s averaging reproduces whole-set rates, from
                # which _finalize_eval derives the task's standard
                # Matthews correlation (scale cancels in MCC).
                pred = jnp.argmax(logits, -1)
                y = batch["label"]
                out.update(
                    _m_tp=jnp.mean((pred == 1) & (y == 1)),
                    _m_fp=jnp.mean((pred == 1) & (y == 0)),
                    _m_tn=jnp.mean((pred == 0) & (y == 0)),
                    _m_fn=jnp.mean((pred == 0) & (y == 1)))
            return out

        return metric_fn

    def metric_fn(params, model_state, batch):
        images = batch["image"]
        if cfg.augment == "crop_flip" and cfg.augment_crop:
            from tpuframe.data import augment as augment_lib

            # train random-crops from larger stored images; eval pairs it
            # with the deterministic center crop at the same geometry.
            images = augment_lib.center_crop(images, cfg.augment_crop)
        logits = model.apply({"params": params, **model_state},
                             _maybe_normalize(cfg, images))
        out = {"accuracy": losses.accuracy(logits, batch["label"]),
               "loss": losses.softmax_cross_entropy(logits, batch["label"])}
        if batch["label"].shape and cfg.dataset == "imagenet":
            out["top5"] = losses.topk_accuracy(logits, batch["label"], 5)
        return out

    return metric_fn


def evaluate(h: Harness, max_batches: int) -> dict:
    # Accumulate on device: per-batch metric dicts are summed as device
    # arrays (async dispatch, no host sync), and the ONE device_get at the
    # end fetches the whole pass — the reference's eval loop does one small
    # allreduce per metric per batch and a host read each time (SURVEY.md
    # §4.5); here host↔device traffic is a single transfer per eval.
    agg: dict | None = None
    n = 0
    for i, batch in enumerate(h.eval_loader.epoch(0)):
        if i >= max_batches:
            break
        m = h.eval_step(h.state, batch)
        agg = m if agg is None else jax.tree.map(jnp.add, agg, m)
        n += 1
        if n % 8 == 0:
            # Bound device-memory run-ahead: without a sync the loader can
            # device_put batches faster than eval consumes them and in-flight
            # buffers pile up in HBM.  block_until_ready is a sync, not a
            # transfer — the one-device_get-per-eval contract holds.
            jax.block_until_ready(agg)
    if agg is None:
        return {}
    return _finalize_eval({k: float(v) / n
                           for k, v in jax.device_get(agg).items()})


def _finalize_eval(avg: dict) -> dict:
    """Derive set-level metrics from aggregated moments (keys starting
    with ``_m_``), which are internal and dropped from the report."""
    if "_m_tp" in avg:
        tp, fp = avg["_m_tp"], avg["_m_fp"]
        tn, fn = avg["_m_tn"], avg["_m_fn"]
        denom = ((tp + fp) * (tp + fn) * (tn + fp) * (tn + fn)) ** 0.5
        if denom > 0:
            avg["mcc"] = (tp * tn - fp * fn) / denom
    if "_m_py" in avg:
        var_p = avg["_m_pred2"] - avg["_m_pred"] ** 2
        var_y = avg["_m_y2"] - avg["_m_y"] ** 2
        cov = avg["_m_py"] - avg["_m_pred"] * avg["_m_y"]
        if var_p > 0 and var_y > 0:
            avg["pearson"] = cov / (var_p * var_y) ** 0.5
    return {k: v for k, v in avg.items() if not k.startswith("_m_")}


def _tune_db_fingerprint() -> str | None:
    """sha256 prefix of the tuning-DB file feeding this run's XLA opts
    (None when no DB exists) — the run_start manifest field that ties a
    run record to the exact tuned-flag state it trained under."""
    try:
        from tpuframe.tune import db as tune_db

        with open(tune_db.default_db_path(), "rb") as f:
            return hashlib.sha256(f.read()).hexdigest()[:16]
    except Exception:  # noqa: BLE001 — no DB / unreadable: not a run error
        return None


def _step_costs(train_step, state, batch):
    """Whole-program (flops, bytes accessed) of one train step from the
    *lowered* module's cost analysis — tracing only, no compile
    (Lowered.cost_analysis works pre-compile on this jax).  Returns
    (flops, bytes, "cost_analysis") or (None, None, None) when the path is
    unavailable (pp factory steps, older jax) — callers fall back to the
    analytic 6·N·D flops estimate (bytes has no analytic fallback: the
    HBM-utilization row simply doesn't print without a cost model)."""
    try:
        ca = train_step.lower(state, batch).cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        flops = float(ca.get("flops", 0.0) or 0.0)
        nbytes = float(ca.get("bytes accessed", 0.0) or 0.0)
        if flops > 0:
            return flops, (nbytes if nbytes > 0 else None), "cost_analysis"
    except Exception:  # noqa: BLE001 — cost model optional by design
        pass
    return None, None, None


def train(cfg: TrainConfig, *, trace_dir: str | None = None,
          log_file: str | None = None) -> dict:
    """Run the workload; returns final metrics (the driver/test surface).

    Thin shell around the real loop: any escaping exception first dumps
    the flight recorder's ring (``obs/flight.py``) so the postmortem has
    the last-N events even when the JSONL log's tail was torn."""
    try:
        return _train_impl(cfg, trace_dir=trace_dir, log_file=log_file)
    except SystemExit:
        raise  # clean exits (preemption rc 14) are not crashes
    except BaseException:
        flight_lib.dump("exception")
        raise


def _train_impl(cfg: TrainConfig, *, trace_dir: str | None = None,
                log_file: str | None = None) -> dict:
    # Preemption contract (resilience/preempt.py): installed before the
    # harness so a SIGTERM during compile/restore is already caught; the
    # loop below checkpoints at the next step boundary and exits rc 14.
    guard = PreemptionGuard().install()
    # Structured run-event log (obs/events.py): env-gated — opened before
    # build_harness so restore-time ckpt_restore events land in the file.
    # The goodput meter starts here too: everything before the first step
    # (harness build, data, restore, compile-cache setup) is "init".
    events_lib.init()
    # Flight recorder tees every emitted record into a bounded ring so a
    # crash/preemption/stall dump carries the last-N events even when the
    # JSONL tail was torn (installed right after init so the ring sees
    # restore-time events too).
    flight_lib.install()
    meter = goodput_lib.GoodputMeter()
    # On-demand profiling endpoint (TensorBoard "capture profile"): env-
    # gated, best-effort — a busy port must not kill training.
    profiler_port = os.environ.get("TPUFRAME_PROFILER_PORT", "").strip()
    if profiler_port:
        try:
            start_profiler_server(int(profiler_port))
        except ValueError:
            pass
    # Persistent compilation cache (utils/compile_cache): a relaunch or
    # crash-loop restart of the same program compiles from the on-disk
    # cache instead of from scratch — hit/miss counters land in the final
    # metrics below next to the retry.* counters.  Gated: the train step
    # returns typed PRNG keys (state.rng), which jax 0.4.x cannot serve
    # from the cache without a hard C++ abort.
    from tpuframe.utils import compile_cache

    if compile_cache.safe_for_key_outputs():
        compile_cache.enable()
    else:
        # Disarm, don't just decline: an in-process LMEngine (colocated
        # serving, the swap-seam tests) enables the cache for its own
        # key-free programs, and a cache hit on the train step's keyed
        # outputs would abort.
        compile_cache.disable()
        print("[tpuframe] compile cache: disabled (this jax aborts on "
              "cached executables with typed-PRNG-key outputs)",
              file=sys.stderr)
    # Re-parse TPUFRAME_FAULTS per run: in-process callers (tests) invoke
    # train() repeatedly under different envs, and restore-time gcs reads
    # inside build_harness already pass through the seams.
    faults_lib.reset_from_env()
    h = build_harness(cfg)
    # An elastic resize may have rescaled global_batch/base_lr inside
    # build_harness — everything below reads the config the harness was
    # actually built with.
    cfg = h.cfg
    # In distributed mode build_harness ran jax.distributed.initialize,
    # whose preemption notifier steals SIGTERM (it only logs the signal);
    # take it back so rc-14 preemption works under the supervisor too.
    guard.reassert()
    logger = MetricLogger(
        log_file, tb_dir=cfg.tb_dir or os.environ.get("TPUFRAME_TB_DIR"))
    rate = RateMeter()
    timeline = StepTimeline.from_env()  # HOROVOD_TIMELINE parity (§5.1)

    # Collective-timeout surfacing (SURVEY.md §5.3): a hung step — peer host
    # dead mid-collective, wedged infeed, dead coordinator — becomes a clean
    # nonzero exit instead of an indefinite hang, so the slice launcher can
    # restart the job and it auto-resumes from the last committed checkpoint.
    # The watchdog arms after the first completed step (compile is unbounded).
    stall_timeout = float(os.environ.get("TPUFRAME_STALL_TIMEOUT_S", "300"))
    stall_poll = float(os.environ.get("TPUFRAME_STALL_POLL_S", "5"))
    stall_abort = os.environ.get("TPUFRAME_STALL_ABORT", "1") == "1"

    # Mutable run facts the event-emitting closures need (filled in once
    # the harness/flops model is known; read from the watchdog thread).
    run_info: dict = {"flops": None, "flops_source": None, "bytes": None,
                      "generation": goodput_lib.DEFAULT_GENERATION,
                      "devmem": None, "step": h.start_step}

    def _emit_run_end(final_step: int) -> None:
        """Close the books: goodput buckets, both MFU flavors, peak HBM
        and the full counter table, in one run_end record."""
        if not events_lib.enabled():
            return
        summary = meter.summary()
        extra: dict = {}
        flops = run_info["flops"]
        prod_steps = summary["productive_steps"]
        prod_s = summary["buckets"]["productive"]
        if flops and prod_steps and prod_s > 0:
            extra["mfu_productive"] = round(goodput_lib.mfu(
                flops, prod_s / prod_steps,
                generation=run_info["generation"],
                n_devices=jax.device_count()), 6)
            if summary["wall_s"] > 0:
                extra["mfu_goodput"] = round(goodput_lib.mfu(
                    flops * prod_steps, summary["wall_s"],
                    generation=run_info["generation"],
                    n_devices=jax.device_count()), 6)
        if run_info["bytes"] and prod_steps and prod_s > 0:
            extra["hbm_util_productive"] = round(goodput_lib.hbm_util(
                run_info["bytes"], prod_s / prod_steps,
                generation=run_info["generation"],
                n_devices=jax.device_count()), 6)
        if run_info["devmem"] is not None:
            extra.update(run_info["devmem"].peak_summary())
        events_lib.emit("run_end", final_step=final_step,
                        wall_s=summary["wall_s"], goodput=summary,
                        counters=obs_metrics.counters(), **extra)

    def _on_stall(idle: float) -> None:
        if not stall_abort:
            return
        import sys

        print(f"[tpuframe] STALL: no step completed in {idle:.0f}s — "
              f"aborting for clean restart + checkpoint resume (exit 13)",
              file=sys.stderr, flush=True)
        try:
            # The heartbeat already emitted the structured stall event;
            # here the dying attempt commits its own books so summarize
            # works from the recorded run_end instead of reconstructing.
            # Capped at the unattributed remainder: the idle window can
            # overlap a step that completed without beating, and the
            # buckets must never sum past wall.
            meter.charge("stall", min(idle, meter.unaccounted_s()))
            _emit_run_end(run_info["step"])
            flight_lib.dump("stall_abort")
            events_lib.close()
            logger.close()
            if timeline is not None:
                timeline.instant("stall_abort", idle_s=idle)
                timeline.close()
            exporter_lib.stop()  # final textfile flush rides on stop()
        finally:
            os._exit(13)

    heartbeat = Heartbeat(timeout_s=stall_timeout, poll_s=stall_poll,
                          on_stall=_on_stall,
                          arm_after_first_beat=True).start()

    # Live telemetry plane (obs/exporter.py): /metrics + /healthz, env-
    # gated.  The health probe is the heartbeat watchdog — a run that
    # stops completing steps reads 503 before the stall-abort kills it.
    exporter = exporter_lib.start_from_env(
        health=lambda: not heartbeat.stalled)
    if exporter is not None:
        def _goodput_samples():
            s = meter.summary()
            out = [("tpuframe_goodput_bucket_seconds", {"bucket": k}, v)
                   for k, v in s["buckets"].items()]
            out.append(("tpuframe_wall_seconds", {}, s["wall_s"]))
            out.append(("tpuframe_steps_completed", {}, s["steps"]))
            return out

        def _devmem_samples():
            sampler = run_info["devmem"]
            if sampler is None:
                return []
            peaks = sampler.peak_summary()
            out = []
            if peaks.get("peak_hbm_bytes") is not None:
                out.append(("tpuframe_hbm_peak_bytes", {},
                            peaks["peak_hbm_bytes"]))
            for did, b in (peaks.get("per_device") or {}).items():
                out.append(("tpuframe_hbm_device_peak_bytes",
                            {"device": did}, b))
            return out

        exporter.add_collector(_goodput_samples)
        exporter.add_collector(_devmem_samples)
    examples_per_step = cfg.global_batch

    if bootstrap.is_primary():
        n_params = sum(int(np.prod(p.shape))
                       for p in jax.tree.leaves(h.state.params))
        print(f"[tpuframe] {cfg.name}: model={cfg.model} "
              f"params={n_params/1e6:.2f}M devices={jax.device_count()} "
              f"global_batch={cfg.global_batch} steps={cfg.total_steps}",
              flush=True)

    # Structured fault injection (resilience/faults.py): TPUFRAME_FAULTS
    # arms named seams (the removed TPUFRAME_FAULT_STEP/_ONCE aliases
    # raise at registry build with the spelling to use).  once=1 faults
    # are dropped on a resumed run so relaunch/resume tests survive the
    # step that killed them.  HANG_STEP/HANG_RANK stay env-level: the
    # rank gate below needs jax.process_index().
    faults_lib.set_resumed(h.start_step > 0)
    hang_step = int(os.environ.get("TPUFRAME_HANG_STEP", "0") or "0")
    hang_rank = int(os.environ.get("TPUFRAME_HANG_RANK", "-1") or "-1")
    if hang_rank >= 0 and jax.process_index() != hang_rank:
        hang_step = 0

    state = h.state
    step = h.start_step
    final_train_metrics: dict = {}
    data_iter: Iterator = h.train_loader.from_step(step)

    if os.environ.get("TPUFRAME_CHECK_SPMD") == "1":
        # Debug mode (SURVEY.md §5.2): every host verifies it built the same
        # config AND the same lowered step program before any collective runs
        # — the host-dependent-trace divergence class.
        from tpuframe.obs import spmd_check

        spmd_check.assert_uniform_across_hosts("config", repr(cfg))
        if step < cfg.total_steps:
            first = next(data_iter)
            spmd_check.check_step_program(h.train_step, "train_step",
                                          state, first)
            data_iter = itertools.chain([first], data_iter)

    if events_lib.enabled():
        # Run manifest + flops model.  The flops count comes from tracing
        # the step once (no compile); the analytic 6·N·D estimate is the
        # fallback — either way run_start records a nonzero flops_per_step
        # so MFU is recomputable offline even from a crashed log.
        from tpuframe.tune import db as tune_db

        n_params = sum(int(np.prod(p.shape))
                       for p in jax.tree.leaves(h.state.params))
        run_info["generation"] = (tune_db.target_generation()
                                  or goodput_lib.DEFAULT_GENERATION)
        if step < cfg.total_steps:
            first = next(data_iter)
            flops, nbytes, src = _step_costs(h.train_step, state, first)
            data_iter = itertools.chain([first], data_iter)
        else:
            flops, nbytes, src = None, None, None
        if not flops:
            flops = goodput_lib.flops_fallback(n_params, examples_per_step)
            src = "analytic_6nd"
        run_info["flops"], run_info["flops_source"] = flops, src
        run_info["bytes"] = nbytes
        events_lib.emit(
            "run_start", config=cfg.name,
            config_hash=hashlib.sha256(repr(cfg).encode()).hexdigest()[:16],
            jax_version=jax.__version__,
            devices=jax.device_count(), processes=jax.process_count(),
            mesh=dict(h.mesh.shape) if h.mesh is not None else None,
            tune_db=_tune_db_fingerprint(),
            xla_opts=os.environ.get("TPUFRAME_XLA_OPTS") or None,
            start_step=h.start_step, total_steps=cfg.total_steps,
            global_batch=cfg.global_batch, n_params=n_params,
            generation=run_info["generation"],
            flops_per_step=flops, flops_source=src,
            bytes_per_step=nbytes)
        # The chosen remat policy as its own typed record: joinable with
        # the tuning DB (same policy names) and visible in summarize even
        # when the run dies before run_end.
        events_lib.emit("remat_policy", policy=h.remat_policy[0],
                        source=h.remat_policy[1],
                        predicted_bytes_per_step=nbytes)
        # Weight-update sharding provenance, same contract: which mode the
        # run actually compiled with and who elected it (env / tune_db /
        # default) — the analyzer joins this with devmem's HBM samples to
        # attribute optimizer-state residency deltas.
        from tpuframe.parallel import zero1 as zero1_lib

        events_lib.emit(
            "weight_update", mode=h.weight_update[0],
            source=h.weight_update[1],
            n_shards=(zero1_lib.world_size(h.mesh)
                      if h.mesh is not None else 1))
        # Wire-format provenance, same contract: which gradient-path
        # wire the run actually compiled with and who elected it — the
        # analyzer joins this with the roofline's comm model to check
        # the predicted byte drop landed.
        # Both fabric legs ride the one record: ``format``/``source`` is
        # the in-slice ICI leg (the historical single-fabric field pair),
        # ``format_dcn``/``source_dcn`` the cross-slice DCN leg, and
        # ``hier``/``hier_source`` says whether the two-level lowering
        # that separates the legs was actually compiled in.
        events_lib.emit("wire_format", format=h.wire_format[0],
                        source=h.wire_format[1],
                        format_dcn=h.wire_format_dcn[0],
                        source_dcn=h.wire_format_dcn[1],
                        hier=h.hier[0], hier_source=h.hier[1])
        # Gradient-fusion provenance, same contract: which bucket
        # threshold the step actually compiled with (None = per-leaf)
        # and who elected it — the analyzer joins this with the
        # schedule plane's interior-window records to attribute
        # overlap-score deltas to the knob that moved them.
        events_lib.emit("fusion_threshold", threshold=h.fusion_threshold[0],
                        source=h.fusion_threshold[1])
        # Parallelism-spec provenance: which declarative spec (if any)
        # the run's mesh was lowered from and who elected it — joins
        # the run manifest's mesh dict to the TPUFRAME_SPEC grammar, so
        # the analyzer can tie ICI/DCN comm attribution back to the
        # declared hierarchical layout.
        if h.pspec[0] is not None:
            events_lib.emit("pspec", spec=h.pspec[0], source=h.pspec[1])
        # Elastic resize provenance: the world changed across the attempt
        # boundary.  n_from/n_to, the declared rescale policy and the
        # exact batch/LR transition, as one typed record — the obs
        # stitcher joins this with the per-attempt step high-water marks
        # to prove the ≤1-lost-step invariant across the resize.
        if h.elastic_resize is not None:
            events_lib.emit("elastic_resize", **h.elastic_resize)
        run_info["devmem"] = devmem_lib.DevmemSampler(
            interval_s=float(os.environ.get("TPUFRAME_DEVMEM_INTERVAL_S",
                                            "30"))).start()
        meter.charge("init", meter.wall_s())
    # Profiler trace window.  ``TPUFRAME_TRACE_STEPS="<start>:<count>"``
    # (absolute step indices) captures a jax.profiler trace of exactly
    # those steps; the legacy ``--trace-dir``-only invocation keeps its
    # historical window (start_step+5, 3 steps).  The window is announced
    # as typed trace_start/trace_end events carrying the artifact path,
    # so the offline analyzer can join profile artifacts to the steps
    # they cover.
    trace_window = parse_trace_steps(os.environ.get("TPUFRAME_TRACE_STEPS"))
    if trace_window is None and trace_dir is not None:
        trace_window = (h.start_step + 5, 3)
    events_dir = os.environ.get(events_lib.ENV_DIR, "").strip()
    trace_path = trace_dir or (os.path.join(events_dir, "trace")
                               if events_dir else "trace")

    def _trace_end(at_step: int) -> None:
        nonlocal t_trace
        if t_trace is None:
            return
        try:
            t_trace.__exit__(None, None, None)
        except Exception:  # noqa: BLE001 — profiling must not kill the run
            pass
        t_trace = None
        events_lib.emit("trace_end", step=at_step, path=trace_path)

    t_trace = None
    while step < cfg.total_steps:
        if (trace_window is not None and t_trace is None
                and step == trace_window[0]):
            try:
                ctx = profile_trace(trace_path)
                ctx.__enter__()
            except Exception:  # noqa: BLE001 — profiler unavailable: the
                trace_window = None  # run goes on untraced
            else:
                t_trace = ctx
                events_lib.emit("trace_start", step=step, path=trace_path)
        if (t_trace is not None
                and step >= trace_window[0] + trace_window[1]):
            _trace_end(step)
            trace_window = None  # one window per run

        t_step0 = time.perf_counter()
        if timeline is not None:
            with timeline.phase("data_wait", step=step):
                batch = next(data_iter)
            t_compute0 = time.perf_counter()
            with timeline.phase("train_step", step=step):
                state, metrics = h.train_step(state, batch)
        else:
            batch = next(data_iter)
            t_compute0 = time.perf_counter()
            state, metrics = h.train_step(state, batch)
        step += 1
        t_end = time.perf_counter()
        # Input wait is its own goodput bucket (arXiv:1909.09756's input
        # stall), NOT part of step time: a loader that can't keep up must
        # show as `input`, never masquerade as slow compute.
        input_wait_s = t_compute0 - t_step0
        step_s = t_end - t_compute0
        first_step = meter.first_step_s is None
        meter.charge("input", input_wait_s)
        meter.step(step_s)
        run_info["step"] = step
        is_log_step = step % cfg.log_every == 0 or step == cfg.total_steps
        fetched = None
        if events_lib.enabled():
            # Step event BEFORE the fault seam fires: a crash fault must
            # not erase the record of the step that preceded it.  Loss
            # rides along only on log steps — those device_get anyway, so
            # the event costs no extra host↔device sync.
            extra: dict = {}
            if is_log_step:
                fetched = jax.device_get(metrics)
                if "loss" in fetched:
                    extra["loss"] = float(fetched["loss"])
            events_lib.emit("step", step=step,
                            wall_ms=round(step_s * 1e3, 3),
                            input_wait_ms=round(input_wait_s * 1e3, 3),
                            examples=examples_per_step, **extra)
            if first_step:
                events_lib.emit("compile", step=step,
                                wall_ms=round(step_s * 1e3, 3),
                                source="first_step")
        faults_lib.set_step(step)
        faults_lib.fire("host")  # crash/signal faults, once per step
        if hang_step and step == hang_step:
            print(f"[tpuframe] FAULT INJECTION: hanging at step {step}",
                  flush=True)
            time.sleep(10 ** 6)
        rate.update(examples_per_step)
        heartbeat.beat(step)

        if is_log_step:
            metrics = fetched if fetched is not None \
                else jax.device_get(metrics)
            final_train_metrics = {k: float(v) for k, v in metrics.items()}
            r = rate.rate()
            if r is not None:
                final_train_metrics["examples_per_sec"] = r
                final_train_metrics["examples_per_sec_per_chip"] = rate.per_chip()
            # Retry-loop activity (resilience/policy.py) — empty unless the
            # storage layer actually retried, so clean runs log nothing new.
            final_train_metrics.update(obs_metrics.counters("retry."))
            final_train_metrics.update(
                obs_metrics.counters("compile_cache."))
            logger.log(step, final_train_metrics)
            if exporter is not None:
                exporter.set_gauge("tpuframe_step", step)
                exporter.set_gauge("tpuframe_step_time_ms", step_s * 1e3)
                exporter.set_gauge("tpuframe_input_wait_ms",
                                   input_wait_s * 1e3)
                if r is not None:
                    exporter.set_gauge("tpuframe_examples_per_sec", r)
                exporter.flush()  # keep the textfile fallback current

        if step % cfg.eval_every == 0 or step == cfg.total_steps:
            h.state = state
            t_eval0 = time.perf_counter()
            with rate.paused():  # eval time isn't training throughput
                if timeline is not None:
                    with timeline.phase("eval", step=step):
                        eval_metrics = evaluate(h, cfg.eval_batches)
                else:
                    eval_metrics = evaluate(h, cfg.eval_batches)
            meter.charge("eval", time.perf_counter() - t_eval0)
            logger.log(step, eval_metrics, prefix="eval")
            final_train_metrics.update(
                {f"eval_{k}": v for k, v in eval_metrics.items()})
            if (cfg.track_best and h.manager is not None
                    and "loss" in eval_metrics):
                if h.manager.save_best(step, state,
                                       float(eval_metrics["loss"])):
                    if bootstrap.is_primary():
                        print(f"[tpuframe] new best eval loss "
                              f"{eval_metrics['loss']:.4f} at step {step}",
                              flush=True)
            heartbeat.beat(step)  # eval (incl. its first compile) is progress

        if h.manager is not None:
            will_save = h.manager.should_save(step)
            t_ckpt0 = time.perf_counter()
            with rate.paused():
                if timeline is not None and will_save:
                    with timeline.phase("checkpoint", step=step):
                        h.manager.maybe_save(step, state)
                else:
                    h.manager.maybe_save(step, state)
                heartbeat.beat(step)  # a long blocking save is progress too
            if will_save:
                meter.charge("ckpt", time.perf_counter() - t_ckpt0)

        if guard.requested:
            # Preemption contract: commit a final checkpoint at this step
            # boundary and exit rc 14 so the supervisor resumes (no crash
            # charged, no backoff) instead of losing up to ckpt_every steps.
            if h.manager is not None:
                t_ckpt0 = time.perf_counter()
                if not h.manager.should_save(step):  # else just saved above
                    h.manager.save(step, state)
                # Deadline-bounded drain, not an open-ended join: the
                # SIGTERM grace window is finite, and flush() guarantees
                # every pending save is committed or quarantined before
                # rc 14 tells the supervisor "resume me" — never
                # acknowledged-but-unwritten.
                flushed = h.manager.flush(deadline_s=float(os.environ.get(
                    "TPUFRAME_FLUSH_DEADLINE_S", "60")))
                if not flushed and bootstrap.is_primary():
                    print("[tpuframe] flush deadline expired — in-flight "
                          "save quarantined; resume uses the previous "
                          "committed step", flush=True)
                meter.charge("ckpt", time.perf_counter() - t_ckpt0)
            heartbeat.stop()
            _trace_end(step)
            if timeline is not None:
                timeline.instant("preempted", step=step)
                timeline.close()
            if run_info["devmem"] is not None:
                run_info["devmem"].stop()
            _emit_run_end(step)
            events_lib.close()
            logger.close()
            exporter_lib.stop()
            guard.uninstall()
            if bootstrap.is_primary():
                print(f"[tpuframe] preempted ({guard.signal_name}): "
                      f"checkpoint committed at step {step}; exiting rc "
                      f"{RC_PREEMPTED} for supervisor resume", flush=True)
            raise SystemExit(RC_PREEMPTED)

    _trace_end(step)
    t_ckpt0 = time.perf_counter()
    if h.manager is not None and step % cfg.ckpt_every != 0:
        h.manager.save(step, state)  # final state always durable
    if h.manager is not None:
        h.manager.wait_pending()  # async saves must commit before exit
        meter.charge("ckpt", time.perf_counter() - t_ckpt0)
    heartbeat.stop()
    if timeline is not None:
        timeline.close()
        if bootstrap.is_primary():
            print(f"[tpuframe] step timeline written to {timeline.path}",
                  flush=True)
    logger.close()
    if run_info["devmem"] is not None:
        run_info["devmem"].stop()
    _emit_run_end(step)
    events_lib.close()
    flight_lib.uninstall()
    # Exporter goes down last: the final scrape (and the textfile flush
    # inside stop()) reflects the completed run's books.
    exporter_lib.stop()
    guard.uninstall()
    final_train_metrics["step"] = step
    final_train_metrics.update(obs_metrics.counters("retry."))
    final_train_metrics.update(obs_metrics.counters("compile_cache."))
    return final_train_metrics


def _parse_set(values: list[str]) -> dict:
    out: dict = {}
    for item in values:
        key, _, raw = item.partition("=")
        if not raw:
            raise ValueError(f"--set needs key=value, got {item!r}")
        try:
            out[key] = ast.literal_eval(raw)
        except (ValueError, SyntaxError):
            out[key] = raw
    return out


def main(argv: list[str] | None = None) -> dict:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--config", required=True,
                   help="workload name (see tpuframe.utils.config.WORKLOADS)")
    p.add_argument("--set", action="append", default=[], metavar="KEY=VALUE",
                   help="override any TrainConfig field")
    p.add_argument("--data-dir", default=None)
    p.add_argument("--ckpt-dir", default=None)
    p.add_argument("--log-file", default=None)
    p.add_argument("--trace-dir", default=None,
                   help="capture an XLA profiler trace of a few steps")
    p.add_argument("--events-dir", default=None,
                   help="write structured run events "
                        "(events.<host>.jsonl; same as TPUFRAME_EVENTS_DIR)")
    args = p.parse_args(argv)
    if args.events_dir:
        # Via the env so every layer (ckpt, resilience, compile_cache,
        # supervisor-relaunched children) sees the same switch.
        os.environ[events_lib.ENV_DIR] = args.events_dir

    cfg = get_config(args.config)
    overrides = _parse_set(args.set)
    if args.data_dir:
        overrides["data_dir"] = args.data_dir
    if args.ckpt_dir:
        overrides["ckpt_dir"] = args.ckpt_dir
    cfg = cfg.with_overrides(**overrides)
    t0 = time.time()
    metrics = train(cfg, trace_dir=args.trace_dir, log_file=args.log_file)
    if bootstrap.is_primary():
        print(f"[tpuframe] done in {time.time() - t0:.1f}s: "
              f"{ {k: round(v, 5) if isinstance(v, float) else v for k, v in metrics.items()} }",
              flush=True)
    return metrics


if __name__ == "__main__":
    main()
