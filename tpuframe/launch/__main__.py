from tpuframe.launch.launcher import main

raise SystemExit(main())
