"""L5/L6: slice provisioning + SPMD launch (SURVEY.md §2, §4.2).

Reference: gcloud GPU-fleet scripts + ``horovodrun`` [B:5]; here: TPU-VM
slice lifecycle (provision), SSH fan-out of one SPMD binary per host
(SliceLauncher), and a local multi-process fake cluster for CI
(LocalCluster)."""

from tpuframe.launch.provision import SliceConfig, emit_scripts
from tpuframe.launch.launcher import LocalCluster, SliceLauncher, main

__all__ = ["SliceConfig", "emit_scripts", "LocalCluster", "SliceLauncher",
           "main"]
