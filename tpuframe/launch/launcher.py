"""Launchers — L5 of the layer map: the ``horovodrun`` replacement.

Reference launch path (SURVEY.md §4.2): ``horovodrun -np 32 -H a:8,... python
train.py`` → mpirun/ssh spawns one process per GPU.  TPU-native SPMD launch
is simpler and different in shape: ONE process per *host*, each seeing the
host's chips, every host running the SAME binary; rendezvous happens through
``jax.distributed.initialize`` (GRPC coordinator), not MPI.

Two launchers:

  * :class:`SliceLauncher` — production: fans the command out to every
    TPU-VM worker over ``gcloud ... ssh --worker=all`` (built by
    tpuframe.launch.provision); each worker autodetects its process id from
    the TPU metadata (``TPUFRAME_MULTIHOST=1``).

  * :class:`LocalCluster` — the CI stand-in (SURVEY.md §7 "fake cluster"):
    spawns N *local* processes, each a separate jax runtime with K forced
    host CPU devices, wired together with TPUFRAME_COORDINATOR/_PROCESS_ID
    env vars consumed by tpuframe.parallel.bootstrap.  Multi-host semantics
    (process_count > 1, cross-host collectives, per-host data sharding) are
    exercised for real, with zero TPUs.
"""

from __future__ import annotations

import random
import re
import os
import socket
import subprocess
import sys
import time
from dataclasses import dataclass, field

from tpuframe import elastic
from tpuframe.launch.provision import SliceConfig
from tpuframe.obs import exporter as exporter_lib
from tpuframe.resilience.preempt import RC_PREEMPTED
from tpuframe.utils import compile_cache


def _free_port() -> int:
    # Local ephemeral-port probe (bind on loopback, never fleet traffic)
    # — no retry/backoff semantics to bypass.
    with socket.socket() as s:  # tf-lint: ok[TF118]
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@dataclass
class CompletedProcess:
    process_id: int
    returncode: int
    stdout: str
    stderr: str


@dataclass
class LocalCluster:
    """Spawn ``num_processes`` local SPMD processes (CPU backend).

    ``devices_per_process`` forced host devices each → a virtual
    ``num_processes × devices_per_process``-chip cluster.
    """

    num_processes: int = 2
    devices_per_process: int = 4
    timeout: float = 600.0
    extra_env: dict[str, str] = field(default_factory=dict)

    def launch(self, argv: list[str]) -> list[CompletedProcess]:
        """Run ``argv`` (e.g. ``[sys.executable, "-m", "tpuframe.train", ...]``)
        once per process; block until all exit.  Raises ``RuntimeError`` if
        any process fails — with every rank's tail, since SPMD failures often
        only explain themselves on one rank."""
        port = _free_port()
        procs = []
        for pid in range(self.num_processes):
            env = dict(os.environ)
            env.update({
                # kill any sandbox TPU plugin; force the CPU fake cluster
                "PALLAS_AXON_POOL_IPS": "",
                "JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": (env.get("XLA_FLAGS", "") +
                              f" --xla_force_host_platform_device_count="
                              f"{self.devices_per_process}"),
                "TPUFRAME_COORDINATOR": f"127.0.0.1:{port}",
                "TPUFRAME_NUM_PROCESSES": str(self.num_processes),
                "TPUFRAME_PROCESS_ID": str(pid),
            })
            # Pin all ranks (and any relaunch of this cluster) to one
            # persistent compilation cache so warm restarts skip the
            # recompile (utils/compile_cache; train() enables it from
            # this env var).  An operator's explicit setting wins.
            env.setdefault("TPUFRAME_COMPILE_CACHE",
                           compile_cache.default_cache_dir())
            env.update(self.extra_env)
            procs.append(subprocess.Popen(
                argv, env=env, stdout=subprocess.PIPE,
                stderr=subprocess.PIPE, text=True))

        results = []
        for pid, p in enumerate(procs):
            try:
                out, err = p.communicate(timeout=self.timeout)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                raise RuntimeError(
                    f"local cluster rank {pid} timed out after {self.timeout}s")
            results.append(CompletedProcess(pid, p.returncode, out, err))

        failures = [r for r in results if r.returncode != 0]
        if failures:
            detail = "\n".join(
                f"--- rank {r.process_id} (exit {r.returncode}) ---\n"
                f"{r.stderr[-2000:]}" for r in failures)
            raise RuntimeError(f"local cluster failed:\n{detail}")
        return results


@dataclass
class SliceLauncher:
    """Fan a command out to every worker of a TPU-VM slice.

    ``dry_run=True`` returns the argv lists instead of executing — the
    testable surface in environments without gcloud credentials."""

    slice_cfg: SliceConfig
    dry_run: bool = False

    def launch(self, command: str, env: dict[str, str] | None = None):
        full_env = {"TPUFRAME_MULTIHOST": "1", **(env or {})}
        cmd = self.slice_cfg.ssh_cmd(command, worker="all", env=full_env)
        if self.dry_run:
            return cmd
        return subprocess.run(cmd, check=True)


def run_with_relaunch(run_once, relaunches: int, *, log=print,
                      progress=None, backoff_base_s: float | None = None,
                      backoff_max_s: float | None = None,
                      max_stalled: int | None = None,
                      sleep=time.sleep, rng: random.Random | None = None
                      ) -> int:
    """Supervise a job through slice-restart recovery (SURVEY.md §5.3).

    The failure model: jobs that stall or lose a host exit nonzero (the
    harness's stall watchdog exits 13 precisely so a supervisor restarts
    it), and the restarted job auto-resumes from the latest committed
    checkpoint — the TPU-native replacement for hvd.elastic's in-place
    re-rendezvous.  ``run_once() -> int`` is re-invoked until it returns 0
    or ``relaunches`` restarts are spent.

    Hardened semantics (docs/DESIGN.md "Failure model & resilience"):

      * rc 14 (:data:`RC_PREEMPTED`) is *cooperative*: the job already
        committed a final checkpoint, so it relaunches immediately —
        no backoff and no charge against the relaunch budget.
      * Crashes back off exponentially with jitter before each relaunch
        (base ``TPUFRAME_RELAUNCH_BACKOFF_S`` [1s], doubling to
        ``backoff_max_s`` [60s]) so a hard-down dependency is not hammered.
      * Crash-loop detection: when ``progress() -> int|None`` (typically
        ``latest_step`` on the job's checkpoint dir) shows no advance
        across ``max_stalled`` (``TPUFRAME_RELAUNCH_MAX_STALLED`` [3])
        consecutive relaunches, the supervisor gives up early — a job
        dying at the same step every time will not burn a day of budget.
      * Any checkpoint progress *refreshes* the budget: attempts, the
        stall counter and the backoff all reset, so a long job that fails
        occasionally-but-productively can keep going indefinitely.
    """
    if backoff_base_s is None:
        backoff_base_s = float(
            os.environ.get("TPUFRAME_RELAUNCH_BACKOFF_S", "1.0"))
    if backoff_max_s is None:
        backoff_max_s = 60.0
    if max_stalled is None:
        max_stalled = int(
            os.environ.get("TPUFRAME_RELAUNCH_MAX_STALLED", "3"))
    rng = rng or random.Random()
    attempt = 0
    stalled = 0
    delay = backoff_base_s
    last_progress = progress() if progress is not None else None
    # Attempt stitching for the structured event log (obs/events.py): every
    # (re)launch — cooperative rc-14 resumes included — gets the next serial
    # so one events.<host>.jsonl reconstructs the full supervised lifecycle.
    # Env contract, not an import: run_once children inherit os.environ.
    attempt_serial = int(os.environ.get("TPUFRAME_ATTEMPT", "0") or "0")
    # Supervisor's own telemetry (obs/exporter.py): bound one port above
    # the child's (``port_offset=1``) so both can serve on one host.
    # Relaunch accounting is exactly what a pager wants from a supervisor:
    # attempts spent, last exit code, crash-loop stall count.
    exporter = exporter_lib.start_from_env(port_offset=1)

    def _export(rc=None):
        if exporter is None:
            return
        exporter.set_gauge("tpuframe_supervisor_attempts", attempt)
        exporter.set_gauge("tpuframe_supervisor_attempt_serial",
                           attempt_serial)
        exporter.set_gauge("tpuframe_supervisor_stalled_relaunches",
                           stalled)
        if rc is not None:
            exporter.set_gauge("tpuframe_supervisor_last_rc", rc)
        exporter.flush()

    while True:
        os.environ["TPUFRAME_ATTEMPT"] = str(attempt_serial)
        attempt_serial += 1
        _export()
        rc = run_once()
        _export(rc)
        if rc == 0:
            return rc
        if rc == RC_PREEMPTED:
            log(f"[tpuframe.launch] job preempted (rc={rc}); relaunching "
                f"immediately (checkpoint committed, budget untouched)")
            continue
        if progress is not None:
            now = progress()
            if now is not None and (last_progress is None
                                    or now > last_progress):
                if attempt or stalled:
                    log(f"[tpuframe.launch] checkpoint progress "
                        f"(latest step {now}) — relaunch budget refreshed")
                last_progress = now
                attempt = 0
                stalled = 0
                delay = backoff_base_s
            else:
                stalled += 1
                if stalled > max_stalled:
                    log(f"[tpuframe.launch] crash loop: no checkpoint "
                        f"progress across {stalled} relaunches — giving up; "
                        f"last rc={rc}")
                    return rc
        if attempt >= relaunches:
            if relaunches > 0:
                log(f"[tpuframe.launch] giving up after {attempt} "
                    f"relaunch(es); last rc={rc}")
            return rc
        attempt += 1
        log(f"[tpuframe.launch] job exited rc={rc}; relaunch "
            f"{attempt}/{relaunches} in {delay:.1f}s "
            f"(resume from latest checkpoint)")
        sleep(delay * rng.uniform(0.5, 1.0))
        delay = min(backoff_max_s, delay * 2.0)


def _progress_probe(cmd: list[str], *, log=print):
    """A ``progress()`` callable for :func:`run_with_relaunch`, watching the
    job's checkpoint directory when one is discoverable from its argv
    (``--ckpt-dir X`` or ``--ckpt-dir=X``).  None when there isn't one —
    crash-loop detection simply stays off.

    Elastic tolerance: under a ``TPUFRAME_ELASTIC`` schedule consecutive
    attempts run at DIFFERENT world sizes, so the directory accumulates
    committed checkpoints written at several n.  Progress is measured in
    steps, which are world-size invariant — a commit from any n counts,
    and a manifest whose ``world`` metadata is absent (pre-elastic),
    foreign, or unreadable must never zero the budget refresh.  The world
    peek below is therefore strictly best-effort visibility: it logs the
    n→n′ transition supervisor-side and feeds nothing into the progress
    value."""
    ckpt_dir = None
    for i, arg in enumerate(cmd):
        if arg == "--ckpt-dir" and i + 1 < len(cmd):
            ckpt_dir = cmd[i + 1]
        elif arg.startswith("--ckpt-dir="):
            ckpt_dir = arg.split("=", 1)[1]
    if not ckpt_dir:
        return None
    seen_world: list[int] = []

    def probe():
        from tpuframe.ckpt.checkpoint import (committed_world,
                                              in_flight_step, latest_step)

        try:
            # In-flight saves count: a job preempted mid-upload advanced
            # past its last COMMIT, and the relaunch will either finish
            # the commit or retrain those few steps — either way it is
            # not a crash loop, and the budget must not be charged as
            # one.
            marks = [s for s in (latest_step(ckpt_dir),
                                 in_flight_step(ckpt_dir))
                     if s is not None]
            world = committed_world(ckpt_dir)
            devices = int(world["devices"]) if world else 0
            if devices > 0:
                if seen_world and seen_world[-1] != devices:
                    log(f"[tpuframe.launch] checkpoint world resized "
                        f"{seen_world[-1]}→{devices} devices (committed "
                        f"step {world.get('step')}) — progress accounting "
                        f"unaffected, steps are world-size invariant")
                if not seen_world or seen_world[-1] != devices:
                    seen_world.append(devices)
            return max(marks) if marks else None
        except Exception:  # noqa: BLE001 — a flaky probe must not kill the
            # supervisor; "unknown" just means no budget refresh this round.
            return None

    return probe


def main(argv: list[str] | None = None) -> int:
    """CLI::

        # fake cluster (CI): 2 hosts x 4 devices running the smoke config
        python -m tpuframe.launch local --nprocs 2 --devices 4 -- \\
            python -m tpuframe.train --config smoke

        # real slice: provision scripts + SPMD fan-out
        python -m tpuframe.launch provision --name pod --accelerator v4-32 \\
            --out launch_scripts/
        python -m tpuframe.launch slice --name pod --accelerator v4-32 -- \\
            python -m tpuframe.train --config imagenet_resnet50_pod
    """
    import argparse

    p = argparse.ArgumentParser(prog="tpuframe.launch", description=main.__doc__)
    sub = p.add_subparsers(dest="mode", required=True)

    lp = sub.add_parser("local", help="spawn a local multi-process fake cluster")
    lp.add_argument("--nprocs", type=int, default=2)
    lp.add_argument("--devices", type=int, default=4,
                    help="forced host devices per process")
    lp.add_argument("--relaunch", type=int, default=0, metavar="N",
                    help="restart a failed job up to N times (auto-resume)")
    lp.add_argument("cmd", nargs=argparse.REMAINDER)

    pp = sub.add_parser("provision", help="emit gcloud provisioning scripts")
    pp.add_argument("--name", required=True)
    pp.add_argument("--zone", default="us-central2-b")
    pp.add_argument("--accelerator", default="v4-32")
    pp.add_argument("--out", default="launch_scripts")

    sp = sub.add_parser("slice", help="run a command on every slice worker")
    sp.add_argument("--name", required=True)
    sp.add_argument("--zone", default="us-central2-b")
    sp.add_argument("--accelerator", default="v4-32")
    sp.add_argument("--dry-run", action="store_true")
    sp.add_argument("--relaunch", type=int, default=0, metavar="N",
                    help="restart a failed job up to N times (auto-resume)")
    sp.add_argument("cmd", nargs=argparse.REMAINDER)

    args = p.parse_args(argv)

    if args.mode == "local":
        cmd = [c for c in args.cmd if c != "--"]
        schedule = elastic.schedule_from_env()

        def run_once() -> int:
            # Elastic membership plan: each supervisor attempt may run at
            # a different TOTAL device count (TPUFRAME_ELASTIC="8,4,8" —
            # shrink after the first membership change, grow back after
            # the second).  The cluster is rebuilt per attempt, so the
            # relaunch IS the re-rendezvous; restore reshards the state.
            devices = args.devices
            if schedule:
                attempt = int(os.environ.get("TPUFRAME_ATTEMPT", "0")
                              or "0")
                n_total = elastic.world_for_attempt(attempt, schedule)
                if n_total % args.nprocs:
                    print(f"[tpuframe.launch] TPUFRAME_ELASTIC leg "
                          f"{n_total} is not divisible by --nprocs "
                          f"{args.nprocs}")
                    return 2
                devices = n_total // args.nprocs
                print(f"[tpuframe.launch] elastic attempt {attempt}: "
                      f"world {n_total} devices ({args.nprocs} proc × "
                      f"{devices} dev)")
            try:
                results = LocalCluster(args.nprocs, devices).launch(cmd)
            except RuntimeError as e:
                print(f"[tpuframe.launch] {e}")
                # preserve the failure model's exit codes (13 = stall
                # abort, 42-class = crash injection): surface the first
                # failing rank's rc rather than flattening to 1.
                m = re.search(r"exit (\d+)", str(e))
                return int(m.group(1)) if m else 1
            for r in results:
                prefix = f"[rank {r.process_id}] "
                for line in r.stdout.strip().splitlines():
                    print(prefix + line)
            return 0

        return run_with_relaunch(run_once, args.relaunch,
                                 progress=_progress_probe(cmd))

    cfg = SliceConfig(name=args.name, zone=args.zone,
                      accelerator=args.accelerator)
    if args.mode == "provision":
        from tpuframe.launch.provision import emit_scripts

        paths = emit_scripts(cfg, args.out)
        for name, path in paths.items():
            print(f"wrote {path}")
        return 0

    cmd = " ".join(c for c in args.cmd if c != "--")
    launcher = SliceLauncher(cfg, dry_run=args.dry_run)
    if args.dry_run:
        print(" ".join(launcher.launch(cmd)))
        return 0

    def run_once() -> int:
        try:
            launcher.launch(cmd)
        except subprocess.CalledProcessError as e:
            return e.returncode or 1
        return 0

    return run_with_relaunch(run_once, args.relaunch,
                             progress=_progress_probe(args.cmd))


if __name__ == "__main__":
    raise SystemExit(main())
