"""Donation/aliasing audit — verify the compiled step actually donates.

``make_train_step(donate=True)`` marks the TrainState argument donated,
which is what keeps params + optimizer state single-buffered through the
update (the difference between fitting and OOMing near the HBM limit,
and an HBM-traffic term of its own: an un-aliased update writes fresh
buffers).  But donation is a *request* — XLA drops it silently when
dtypes/layouts mismatch or a result doesn't line up with an input, and
jax only surfaces a warning buried in the log.  This audit parses the
compiled module's ``input_output_alias`` table so tests and the tune
sweep can assert the aliasing actually happened.

HLO text carries the table in the module header::

    HloModule jit__grad_step, input_output_alias={ {0}: (0, {0},
        may-alias), {1}: (0, {1}, may-alias), ... }

one ``{output index}: (param number, {param index}, kind)`` entry per
aliased buffer.
"""

from __future__ import annotations

import re

# one alias entry: "{1,2}: (0, {3}, may-alias)"
_ENTRY_RE = re.compile(
    r"\{[\d,\s]*\}:\s*\((\d+),\s*\{[\d,\s]*\},\s*(may-alias|must-alias)\)")


def _alias_block(hlo_text: str) -> str:
    """The ``input_output_alias={...}`` block (brace-matched — entries
    contain nested braces), or '' when the module has no aliases."""
    key = "input_output_alias={"
    start = hlo_text.find(key)
    if start < 0:
        return ""
    i = start + len(key)
    depth = 1
    while i < len(hlo_text) and depth:
        if hlo_text[i] == "{":
            depth += 1
        elif hlo_text[i] == "}":
            depth -= 1
        i += 1
    return hlo_text[start + len(key):i - 1]


def donation_report(compiled) -> dict:
    """Parse a compiled executable's aliasing table.

    Returns ``{"n_aliased", "aliased_params" (sorted arg numbers that
    donate at least one buffer), "donated" (any alias at all)}``.
    Accepts anything with ``as_text()`` (jax AOT compiled objects).
    """
    text = compiled.as_text() if hasattr(compiled, "as_text") else \
        str(compiled)
    entries = _ENTRY_RE.findall(_alias_block(text))
    return {
        "n_aliased": len(entries),
        "aliased_params": sorted({int(argnum) for argnum, _ in entries}),
        "donated": bool(entries),
    }


def audit_step_donation(compiled, state=None) -> list:
    """Problem strings for a compiled *train step* (arg 0 = TrainState).

    With ``state`` (the concrete/abstract TrainState) the check is
    strict: every params + opt_state leaf must be covered by an alias —
    the optimizer update buffers are exactly what donation exists for.
    Without it, any empty table is flagged.
    """
    report = donation_report(compiled)
    if not report["donated"]:
        return ["no input_output_alias entries — the step's donate=True "
                "request was dropped (or the step was built with "
                "donate=False); params + optimizer state are "
                "double-buffered through the update"]
    problems = []
    if 0 not in report["aliased_params"]:
        problems.append(
            f"aliases exist but none donate from arg 0 (the TrainState): "
            f"aliased args {report['aliased_params']}")
    if state is not None:
        import jax

        n_update_leaves = len(jax.tree.leaves(state.params)) + \
            len(jax.tree.leaves(state.opt_state))
        if report["n_aliased"] < n_update_leaves:
            problems.append(
                f"only {report['n_aliased']} buffers aliased but the "
                f"update touches {n_update_leaves} params+opt_state "
                f"leaves — donation partially dropped")
    return problems
