"""The rematerialization policy registry — activation memory/traffic as a
named, searchable dimension.

PERF.md §2 proved the ResNet-50 step is bandwidth-bound (81% of the v5e
HBM roofline, MXU ≤29% busy) and §6 attributed the bytes: ~105 of
143.5 GB is backward-pass touch count — saved-activation re-reads plus dy
double-reads.  What forward activations are *saved* for the backward is
therefore a first-order performance lever, and until this module it lived
in two ad-hoc places: the models' ``remat=`` flag and the
``TPUFRAME_BENCH_REMAT`` bench knob.

This module makes the decision a **named policy** applied uniformly at
the loss-function seam (``parallel/step.py``/``parallel/pp_lm.py`` wrap
the loss in ``jax.checkpoint`` with the policy's saveable predicate):

  ============== ======================================================
  ``none``       no checkpoint region at all — XLA saves whatever the
                 autodiff residual rule produces (the historical
                 default; §6's 143.5 GB at b512)
  ``everything`` a checkpoint region that saves every intermediate —
                 semantically ``none`` but through the remat machinery
                 (the A/B control for the wrapper itself)
  ``dots``       save only matmul/conv outputs
                 (``jax.checkpoint_policies.checkpoint_dots``); the
                 elementwise BN/relu chains — 74% of activation-sized
                 f32 values in the §7 census — are recomputed, and they
                 fuse into their consumers so the recompute adds no HBM
                 traffic
  ``dots_no_batch``  ``dots_with_no_batch_dims_saveable``: save only
                 batch-free dot outputs; on a conv net everything
                 carries batch dims, so this approaches ``full``
  ``per_block``  save only the named block seams the models annotate
                 (``save_only_these_names`` over ``SEAM_NAMES``);
                 intra-block activations are recomputed from the seams
  ``full``       save nothing (``nothing_saveable``) — maximum
                 recompute, minimum residency
  ``save_named(a,b,...)``  parametric: save exactly the listed seam
                 names — the search's fine-grained axis
  ============== ======================================================

Models annotate their seams with :func:`seam` (a thin
``jax.ad_checkpoint.checkpoint_name`` wrapper so every name is validated
against ``SEAM_NAMES``) — a no-op identity unless a ``save_named``-class
policy is active.  Model-level ``nn.remat`` goes through
:func:`remat_module` so the TF108 lint can pin every remat decision to
this registry.

Which policy actually wins is an *empirical*, generation- and
batch-dependent question — §7 measured naive per-block flax remat at
+18% bytes (recomputed intra-block convs land in HBM again), while
``dots`` removes the fusable elementwise residuals for free.  That is
exactly what ``python -m tpuframe.tune sweep --remat`` measures offline
(AOT ``cost_analysis()`` bytes on a compile-only topology) and persists
to the tuning DB; resolution precedence is the tuning subsystem's:

    TPUFRAME_REMAT_POLICY  >  legacy TPUFRAME_BENCH_REMAT alias
                           >  tuning DB (generation-gated)  >  default
"""

from __future__ import annotations

import os
import re

SEAM_NAMES = ("stem_out", "embed_out", "block_out")

ENV_POLICY = "TPUFRAME_REMAT_POLICY"
# PR-2-style deprecated alias: the old bench knob. "1" maps to per_block
# (what the knob toggled); anything else is ignored.
ENV_LEGACY = "TPUFRAME_BENCH_REMAT"

_PRESETS = ("none", "everything", "dots", "dots_no_batch", "per_block",
            "full")

_SAVE_NAMED_RE = re.compile(r"^save_named\(\s*([\w\s,]*?)\s*\)$")

_warned_legacy = False


def available_policies() -> tuple:
    """The preset names (``save_named(...)`` is parametric on top)."""
    return _PRESETS


def parse_save_named(name: str) -> tuple | None:
    """``save_named(a, b)`` → ``("a", "b")``; None when not that shape.
    Raises on unknown seam names — a typo'd name silently saving nothing
    would be the worst failure mode."""
    m = _SAVE_NAMED_RE.match(name.strip())
    if m is None:
        return None
    names = tuple(n for n in re.split(r"[,\s]+", m.group(1)) if n)
    if not names:
        raise ValueError("save_named() needs at least one seam name; "
                         f"known seams: {SEAM_NAMES}")
    unknown = [n for n in names if n not in SEAM_NAMES]
    if unknown:
        raise ValueError(f"save_named: unknown seam name(s) {unknown}; "
                         f"models annotate {SEAM_NAMES}")
    return names


def validate_policy(name: str) -> str:
    """Normalize + validate a policy name; raises ValueError on junk."""
    name = (name or "none").strip()
    if name in _PRESETS:
        return name
    if parse_save_named(name) is not None:
        return name
    raise ValueError(f"unknown remat policy {name!r}; presets: "
                     f"{_PRESETS} or save_named(<seam,...>) over "
                     f"{SEAM_NAMES}")


def _jax_policy(name: str):
    """The ``jax.checkpoint`` saveable predicate for ``name`` (None for
    ``none`` — no checkpoint region is applied at all)."""
    import jax

    cp = jax.checkpoint_policies
    if name == "none":
        return None
    if name == "everything":
        return cp.everything_saveable
    if name == "full":
        return cp.nothing_saveable
    if name == "dots":
        return cp.checkpoint_dots
    if name == "dots_no_batch":
        return cp.dots_with_no_batch_dims_saveable
    if name == "per_block":
        return cp.save_only_these_names(*SEAM_NAMES)
    names = parse_save_named(name)
    if names is not None:
        return cp.save_only_these_names(*names)
    raise ValueError(f"unknown remat policy {name!r}")


def wrap(fn, policy: str | None):
    """Apply ``policy`` to a differentiated function (the loss) — the ONE
    place a ``jax.checkpoint`` enters step construction (TF108 pins every
    other call site to this module).  ``None``/``"none"`` returns ``fn``
    unchanged: no checkpoint region, the historical behavior."""
    import jax

    name = validate_policy(policy) if policy else "none"
    if name == "none":
        return fn
    return jax.checkpoint(fn, policy=_jax_policy(name))


def seam(x, name: str):
    """Annotate a block-boundary activation so ``per_block``/
    ``save_named`` policies can elect to save it.  Identity (a ``name``
    primitive) when no checkpoint region or policy references it."""
    if name not in SEAM_NAMES:
        raise ValueError(f"unknown seam name {name!r}; add it to "
                         f"mem.policy.SEAM_NAMES first")
    from jax.ad_checkpoint import checkpoint_name

    return checkpoint_name(x, name)


def remat_module(module_cls, **kwargs):
    """``flax.linen.remat`` through the registry seam.  Model code calls
    this instead of ``nn.remat`` directly so TF108 can lint that every
    remat decision is visible to the policy layer (same seam rule as
    TF105's GCS check)."""
    import flax.linen as nn

    return nn.remat(module_cls, **kwargs)


# ---------------------------------------------------------------------------
# Resolution: env (incl. the deprecated alias) > tuning DB > default.
# ---------------------------------------------------------------------------

def policy_from_env(env=os.environ) -> str | None:
    """The explicit env override, or None.  Folds the legacy
    ``TPUFRAME_BENCH_REMAT=1`` knob in as a deprecated alias for
    ``per_block`` (warn once — the faults.py legacy-knob pattern);
    ``TPUFRAME_REMAT_POLICY`` wins when both are set."""
    global _warned_legacy
    explicit = env.get(ENV_POLICY, "").strip()
    if explicit:
        return validate_policy(explicit)
    if env.get(ENV_LEGACY, "").strip() == "1":
        if not _warned_legacy:
            print(f"[tpuframe] {ENV_LEGACY} is deprecated — use "
                  f"{ENV_POLICY}=per_block", flush=True)
            _warned_legacy = True
        return "per_block"
    return None


def resolve(program: str | None = None, family: str | None = None,
            default: str = "none") -> tuple:
    """``(policy, source)`` for a step program: env override (explicit or
    legacy alias) > tuning-DB winner (generation-gated, fingerprint-free
    family lookup like resolve_xla_opts) > ``default``.  ``source`` is
    one of ``env``/``env_legacy``/``tune_db``/``default`` — emitted in
    the run event so a run's policy provenance is always on record."""
    env_val = policy_from_env()
    if env_val is not None:
        explicit = os.environ.get(ENV_POLICY, "").strip()
        return env_val, ("env" if explicit else "env_legacy")
    if program or family:
        from tpuframe.tune import db as tune_db

        db_val = tune_db.resolve_remat_policy(program or "", family=family)
        if db_val is not None:
            return validate_policy(db_val), "tune_db"
    return validate_policy(default), "default"
