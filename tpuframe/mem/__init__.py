"""tpuframe.mem — structured rematerialization & HBM-traffic policy.

The §6 byte attribution showed the ResNet-50 step's 143.5 GB is mostly
backward-pass touch count; *what gets saved for the backward* is the
lever.  This package turns that decision into a named, searchable policy:

  - :mod:`tpuframe.mem.policy` — the policy registry (``none`` / ``full``
    / ``per_block`` / ``dots`` presets / ``save_named(...)``), the model
    seam annotations (``seam``/``remat_module``), and the
    env-alias-DB resolution chain;
  - :mod:`tpuframe.mem.audit` — the donation/aliasing audit over compiled
    steps (``input_output_alias`` parsing);
  - the offline search lives in ``tpuframe.tune`` (``python -m
    tpuframe.tune sweep --remat``) and persists winners to the tuning DB.

``check()`` is the analysis-gate hook: registry self-validation plus a
TF108 self-lint of the model/step files that must route every remat
through this package.
"""

from __future__ import annotations

import os

from tpuframe.mem.audit import audit_step_donation, donation_report
from tpuframe.mem.policy import (ENV_LEGACY, ENV_POLICY, SEAM_NAMES,
                                 available_policies, parse_save_named,
                                 policy_from_env, remat_module, resolve,
                                 seam, validate_policy, wrap)

__all__ = [
    "ENV_LEGACY", "ENV_POLICY", "SEAM_NAMES", "audit_step_donation",
    "available_policies", "check", "donation_report", "parse_save_named",
    "policy_from_env", "remat_module", "resolve", "seam",
    "validate_policy", "wrap",
]

# The files whose remat decisions must route through this registry —
# TF108's scope, self-linted here so the analysis gate fails closed if a
# bare jax.checkpoint/nn.remat sneaks back into model/step code.
_TF108_SELF_LINT = (
    os.path.join("models", "resnet.py"),
    os.path.join("models", "transformer_lm.py"),
    os.path.join("parallel", "step.py"),
    os.path.join("parallel", "pp_lm.py"),
)


def check() -> list:
    """Self-check for the ``python -m tpuframe.analysis`` CI gate.
    Returns problem strings; [] means healthy."""
    problems = []
    # 1. every preset resolves to a policy and wraps a function
    for name in available_policies():
        try:
            wrap(lambda x: x, name)
        except Exception as e:  # noqa: BLE001 — report, don't crash CI
            problems.append(f"policy {name!r} failed to apply: "
                            f"{type(e).__name__}: {e}")
    # 2. save_named parses and rejects unknown seams
    try:
        got = parse_save_named("save_named(block_out, stem_out)")
        if got != ("block_out", "stem_out"):
            problems.append(f"save_named parse drift: {got!r}")
    except Exception as e:  # noqa: BLE001
        problems.append(f"save_named parse failed: {e}")
    try:
        parse_save_named("save_named(not_a_seam)")
        problems.append("save_named accepted an unknown seam name")
    except ValueError:
        pass
    # 3. TF108 self-lint: model/step files keep using the registry
    from tpuframe.analysis.source_lint import lint_paths

    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    paths = [os.path.join(pkg_root, p) for p in _TF108_SELF_LINT]
    for f in lint_paths([p for p in paths if os.path.exists(p)]):
        if f.rule == "TF108":
            problems.append(f"self-lint: {f}")
    return problems
