"""Checkpoint-to-bucket save/restore (L3) — SURVEY.md §4.4.

Reference flow: rank 0 ``torch.save``s to local disk, uploads to GCS, and on
resume broadcasts restored state to all ranks.  TPU-native flow implemented
here: every host writes exactly the array shards it owns straight to the
(bucket) path in parallel — no rank-0 bottleneck — and restore reassembles
with *resharding*, so an 8-chip checkpoint restores onto 32 chips and back
(SURVEY.md §7 hard part 3).
"""

from tpuframe.ckpt.checkpoint import (  # noqa: F401
    CheckpointManager,
    committed_world,
    latest_step,
    restore,
    save,
)
