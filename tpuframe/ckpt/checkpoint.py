"""Sharded, reshardable checkpointing with integrity checks.

Layout of one checkpoint (all paths may be ``gs://`` URIs):

    <dir>/step_00000100/
        manifest.json           # treedef, per-leaf shape/dtype/partition-spec,
                                # shard table, CRC32 per file, framework version
        <leaf>.shard_<i>.npy    # raw shard bytes (np.save format)
        COMMIT                  # written last; a checkpoint without it is torn

Save: each host serializes only the addressable shards it owns (one writer
per distinct shard — the process holding the shard's first replica), so pod
saves parallelize across hosts with no cross-host traffic (reference contrast:
rank-0 torch.save + upload, SURVEY.md §4.4).

Restore: shards are read and placed per-device for the *target* sharding.
The source mesh size does not need to match — restoring an 8-chip checkpoint
onto a 32-chip mesh reassembles from the shard table (SURVEY.md §7 hard
part 3: "restore 8-chip ckpt on 32 chips").

Integrity: CRC32C (Castagnoli — the polynomial GCS object checksums use) of
every shard file is recorded in the manifest and verified on restore; the
checksum runs in C++ (tpuframe.native) with a pure-Python fallback.
"""

from __future__ import annotations

import io
import json
import os
import re
import threading
import time
from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpuframe.data import gcs
from tpuframe.obs import events as obs_events
from tpuframe.resilience import faults

PyTree = Any

_STEP_RE = re.compile(r"^step_(\d{8})$")
_MANIFEST = "manifest.json"
_COMMIT = "COMMIT"


def _crc32(data: bytes, algo: str = "crc32c") -> int:
    if algo == "crc32":  # honored if a manifest ever records zlib crc32
        import zlib

        return zlib.crc32(data)
    if algo != "crc32c":
        raise ValueError(f"unknown checkpoint checksum algorithm {algo!r}")
    from tpuframe import native

    return native.crc32c(data)


def _flatten_with_paths(tree: PyTree):
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    names = ["/".join(_path_str(k) for k in path) for path, _ in flat]
    leaves = [leaf for _, leaf in flat]
    return names, leaves, treedef


def _path_str(key) -> str:
    if hasattr(key, "key"):
        return str(key.key)
    if hasattr(key, "idx"):
        return str(key.idx)
    if hasattr(key, "name"):
        return str(key.name)
    return str(key)


def _spec_of(leaf) -> list:
    sharding = getattr(leaf, "sharding", None)
    if isinstance(sharding, NamedSharding):
        out = []
        for entry in sharding.spec:
            if entry is None:
                out.append(None)
            elif isinstance(entry, (tuple, list)):
                out.append(list(entry))
            else:
                out.append([entry])
        return out
    return []


def _prepare_save(directory: str, step: int, tree: PyTree, *, sink=None):
    """Synchronous part of a save.

    ``sink=None`` (async mode): device->host SNAPSHOTS (forced copies) of
    every owned shard are accumulated and returned — after this returns the
    live tree may keep training.  ``sink`` given (sync mode): each leaf's
    owned shards are passed to ``sink(owned)`` immediately and NOT
    accumulated, keeping peak memory at one leaf (the pre-async streaming
    behavior; no copies needed since the caller blocks until written).

    Stale artifacts from a prior save of this SAME step (torn save, or a
    rerun over an old ckpt_dir) are cleaned here, synchronously: this
    host's CRC sidecar, and COMMIT+manifest — otherwise wait_pending/
    restore could see the directory as committed mid-rewrite.  The async
    finalizer additionally requires sidecar mtimes newer than this
    attempt (see _finalize), so a lagging host's stale sidecar cannot be
    trusted even before its cleanup runs."""
    path = gcs.join(directory, f"step_{step:08d}")
    gcs.makedirs(path)
    for stale in (gcs.join(path, f"crc_{jax.process_index()}.json"),
                  gcs.join(path, _COMMIT), gcs.join(path, _MANIFEST)):
        if gcs.exists(stale):
            gcs.delete(stale)
    names, leaves, treedef = _flatten_with_paths(tree)

    del treedef  # structure is recorded as the ordered leaf-name list; restore
    # rebuilds via the caller's target tree (exact classes) or a nested dict.
    manifest: dict = {
        "version": 1,
        "step": step,
        "leaf_order": names,
        "leaves": {},
        "crc": {},
        # Algorithm versioning: absent == legacy zlib crc32; restore verifies
        # with whatever the writer recorded.
        "crc_algo": "crc32c",
        # Elastic provenance: the world this checkpoint was written at.
        # restore() reshards n→n′ from shapes alone; train.py peeks it
        # (committed_world) to pick the batch/LR rescale and stamp the
        # elastic_resize event's n_from.  Absent in pre-elastic manifests.
        "world": {"processes": jax.process_count(),
                  "devices": jax.device_count()},
    }
    owned_files: list[tuple[str, np.ndarray]] = []
    for name, leaf in zip(names, leaves):
        arr = leaf if isinstance(leaf, jax.Array) else jnp_asarray(leaf)
        prng_impl = None
        if jax.dtypes.issubdtype(arr.dtype, jax.dtypes.prng_key):
            prng_impl = str(jax.random.key_impl(arr))
            arr = jax.random.key_data(arr)
        # Every host computes the same global shard table; each host writes
        # only the files whose shard it owns (lowest-device-id replica).
        table, owned = _shard_table(arr, _sanitize(name), copy=sink is None)
        entry = {
            "shape": list(arr.shape),
            "dtype": _dtype_str(arr),
            "spec": _spec_of(arr),
            "shards": table,
        }
        if prng_impl is not None:
            entry["prng_impl"] = prng_impl
        if sink is not None:
            sink(owned)
        else:
            owned_files.extend(owned)
        manifest["leaves"][name] = entry
    return path, manifest, owned_files


def _write_files(path: str, owned_files) -> dict:
    """Serialize + write shard files; returns fname->crc."""
    crc_local: dict[str, int] = {}
    for fname, data in owned_files:
        buf = io.BytesIO()
        np.save(buf, data)
        raw = buf.getvalue()
        # The ckpt_shard fault seam mangles the bytes actually written while
        # the CRC is computed over the CLEAN bytes — modeling storage-side
        # corruption, which restore must catch via the CRC mismatch.
        gcs.write_bytes(gcs.join(path, fname), faults.mangle("ckpt_shard", raw))
        crc_local[fname] = _crc32(raw)
    return crc_local


def _write_sidecar(path: str, crc_local: dict) -> None:
    """The per-host CRC sidecar — each host's LAST artifact; its existence
    (with a fresh mtime) means this host's files are durably written."""
    gcs.write_bytes(gcs.join(path, f"crc_{jax.process_index()}.json"),
                    json.dumps(crc_local).encode())


def _write_owned(path: str, owned_files) -> dict:
    """Files + sidecar in one call (the async worker's whole write)."""
    crc_local = _write_files(path, owned_files)
    # Hard-kill seam between the shard files and the sidecar: the worst
    # moment for an upload to die — bytes are on storage but nothing
    # acknowledges them.  The chaos harness proves resume never trusts
    # this state (no sidecar -> no COMMIT -> torn, invisible to resume).
    faults.fire("crash_during_upload")
    _write_sidecar(path, crc_local)
    return crc_local


def _finalize(path: str, manifest: dict, *, poll: bool,
              min_mtime: float = 0.0, timeout_s: float = 600.0) -> None:
    """Process 0 merges every host's CRC sidecar and writes manifest+COMMIT.

    ``poll=False``: callers already synchronized (the sync save's barrier).
    ``poll=True``: wait for the sidecar files to appear instead — the async
    path runs off the main thread, where a collective barrier could
    interleave with the training loop's collectives (the exact ordering
    hazard the packed-broadcast restore exists to avoid).  Sidecar files
    are each host's last write, so their presence == that host finished.
    On timeout the checkpoint is left torn (no COMMIT) — exactly what the
    restore-side torn protection already handles."""
    if jax.process_index() != 0:
        return
    deadline = time.time() + timeout_s
    crc: dict[str, int] = {}
    for i in range(jax.process_count()):
        sidecar = gcs.join(path, f"crc_{i}.json")
        while poll and not (gcs.exists(sidecar)
                            and gcs.mtime(sidecar) >= min_mtime):
            # Freshness gate: a STALE sidecar (torn prior save of the same
            # step) must not be trusted just because it exists — a lagging
            # host may not have cleaned it yet.  Storage-side mtimes are
            # host-skew-free on GCS; 60s covers local-FS clock fuzz, and
            # genuinely stale artifacts are minutes-to-hours old (a crash +
            # restart + retrain separates attempts).
            if time.time() > deadline:
                print(f"[ckpt] finalize timeout: host {i} sidecar missing "
                      f"or stale; leaving {path} uncommitted", flush=True)
                return
            time.sleep(0.2)
        crc.update(json.loads(gcs.read_bytes(sidecar)))
    manifest["crc"] = crc
    gcs.write_bytes(gcs.join(path, _MANIFEST),
                    json.dumps(manifest, indent=1).encode())
    gcs.write_bytes(gcs.join(path, _COMMIT), b"ok")


def save(directory: str, step: int, tree: PyTree) -> str:
    """Write one checkpoint; returns its path. Collective: every process must
    call it (each writes the shards it owns).  Streams leaf by leaf — peak
    extra host memory is one leaf's shards, not the whole checkpoint."""
    crc_local: dict[str, int] = {}
    path_holder: list[str] = []

    def sink(owned):
        crc_local.update(_write_files(path_holder[0], owned))

    path = gcs.join(directory, f"step_{step:08d}")
    path_holder.append(path)
    path, manifest, _ = _prepare_save(directory, step, tree, sink=sink)
    _write_sidecar(path, crc_local)
    _barrier()
    _finalize(path, manifest, poll=False)
    return path


def jnp_asarray(x):
    import jax.numpy as jnp

    return jnp.asarray(x)


def _dtype_str(arr) -> str:
    return str(np.dtype(arr.dtype))


def _sanitize(name: str) -> str:
    return name.replace("/", ".")


def _shard_table(arr, base: str, *, copy: bool = True):
    """(manifest shard table, [(fname, np data) this process writes]).

    The table is identical on every host (deterministic ordering by index);
    ownership = the shard replica on the lowest device id, so exactly one
    host writes each file.
    """
    if not isinstance(arr, jax.Array) or not hasattr(arr, "global_shards"):
        fname = f"{base}.shard_0.npy"
        if jax.process_index() != 0:
            return [{"id": 0, "index": None, "file": fname}], []
        # copy (async snapshots only): np.asarray may ALIAS an XLA buffer
        # on the CPU backend, and async saves must survive the live tree
        # being donated/updated before the background write runs.
        data = np.array(arr, copy=True) if copy else np.asarray(arr)
        return ([{"id": 0, "index": None, "file": fname}], [(fname, data)])
    by_index: dict = {}
    for shard in arr.global_shards:
        key = _index_key(shard.index, arr.shape)
        owner = by_index.get(key)
        if owner is None or shard.device.id < owner.device.id:
            by_index[key] = shard
    table, owned = [], []
    for shard_id, (key, shard) in enumerate(sorted(by_index.items())):
        fname = f"{base}.shard_{shard_id}.npy"
        table.append({"id": shard_id, "index": key, "file": fname})
        if shard.device.process_index == jax.process_index():
            local = next(s for s in arr.addressable_shards
                         if _index_key(s.index, arr.shape) == key)
            owned.append((fname, np.array(local.data, copy=True) if copy
                          else np.asarray(local.data)))
    return table, owned


def _entry_spec(entry: dict) -> P:
    """The PartitionSpec a manifest leaf entry was saved with (see
    _spec_of); used both for broadcast-eligibility and for placement, so
    the two can't diverge."""
    if not entry["spec"]:
        return P()
    return P(*[tuple(e) if e else None for e in entry["spec"]])


def _index_key(index, shape) -> tuple:
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else sl.start
        stop = dim if sl.stop is None else sl.stop
        out.append((int(start), int(stop)))
    return tuple(out)


def restore(directory: str, step: int, *, mesh: Mesh | None = None,
            target: PyTree | None = None, verify_crc: bool = True) -> PyTree:
    """Load a checkpoint, placing leaves per ``target``'s shardings (or
    replicated on ``mesh``; or as host numpy when both are None)."""
    path = gcs.join(directory, f"step_{step:08d}")
    if not gcs.exists(gcs.join(path, _COMMIT)):
        raise FileNotFoundError(f"no committed checkpoint at {path}")
    manifest = json.loads(gcs.read_bytes(gcs.join(path, _MANIFEST)))
    saved_names = manifest["leaf_order"]
    # Default for manifests without the key: crc32c — every committed version
    # of this writer used crc32c; the explicit key exists so a future
    # algorithm change can't silently mis-verify old checkpoints.
    crc_algo = manifest.get("crc_algo", "crc32c")

    def _placed(name: str, tgt) -> Any:
        entry = manifest["leaves"][name]
        tgt_sharding = getattr(tgt, "sharding", None)
        tgt_shape = getattr(tgt, "shape", None)
        if (tgt is not None and tgt_shape is not None
                and "prng_impl" not in entry
                and tuple(entry["shape"]) != tuple(tgt_shape)):
            # Elastic n→n′ reshard: a ZeRO-1 flat opt-state vector whose
            # pad-to-multiple length changed with the world size.  The map
            # is truncate-or-zero-pad and provably exact (the pad region
            # is zero forever — see tpuframe/elastic/resharding.py), so no
            # layout metadata is consulted: fully reassemble (CRC-verified
            # — a torn shard still raises into restore_latest's
            # quarantine, never a half-reshard), remap, place per target.
            from tpuframe.elastic import resharding
            from tpuframe.parallel.mesh import host_device_put

            if (len(entry["shape"]) == 1 and len(tgt_shape) == 1
                    and name.split("/", 1)[0] == "opt_state"):
                arr = _assemble(path, entry, manifest["crc"], verify_crc,
                                crc_algo)
                arr = arr.astype(np.dtype(entry["dtype"]), copy=False)
                arr = resharding.reshard_flat(arr, int(tgt_shape[0]))
                if tgt_sharding is not None:
                    return host_device_put(arr, tgt_sharding)
                return arr
            raise ValueError(
                f"checkpoint leaf {name!r} shape {tuple(entry['shape'])} "
                f"does not match target shape {tuple(tgt_shape)} and is "
                f"not a flat ZeRO-1 opt-state vector — no resharding map "
                f"applies")
        if (tgt_sharding is not None and "prng_impl" not in entry
                and not tgt_sharding.is_fully_replicated
                and isinstance(tgt_sharding, NamedSharding)):
            # Sharded target: read only the shard files overlapping each
            # locally-addressable device slice, never the full array.
            shape = tuple(entry["shape"])
            cache: dict = {}
            idx_map = tgt_sharding.addressable_devices_indices_map(shape)
            pieces = [
                jax.device_put(
                    _assemble_region(path, entry, idx, manifest["crc"],
                                     verify_crc, cache, crc_algo),
                    device)
                for device, idx in idx_map.items()
            ]
            return jax.make_array_from_single_device_arrays(
                shape, tgt_sharding, pieces)
        # Multi-host-safe placement: device_put rejects shardings spanning
        # non-addressable devices (the restore-on-a-different-host-count
        # path), so all global placement goes through host_device_put.
        from tpuframe.parallel.mesh import host_device_put

        def _broadcast_restore(sharding):
            # Payload already arrived via the ONE packed broadcast (see
            # _receive_broadcast_batch); placement here is local-only.
            a = _bcast_payload[name]
            data = host_device_put(a, sharding)
            if "prng_impl" in entry:
                return jax.random.wrap_key_data(data, impl=entry["prng_impl"])
            return data

        if tgt_sharding is not None and name in _bcast_payload:
            return _broadcast_restore(tgt_sharding)
        if tgt_sharding is None and name in _bcast_payload:
            return _broadcast_restore(NamedSharding(mesh,
                                                     _entry_spec(entry)))

        arr = _assemble(path, entry, manifest["crc"], verify_crc, crc_algo)
        arr = arr.astype(np.dtype(entry["dtype"]), copy=False)
        if "prng_impl" in entry:
            key = jax.random.wrap_key_data(jnp_asarray(arr),
                                           impl=entry["prng_impl"])
            if tgt_sharding is not None:
                key = host_device_put(key, tgt_sharding)
            return key
        if tgt_sharding is not None:
            # Replicated target: full assemble + global placement.
            return host_device_put(arr, tgt_sharding)
        if mesh is not None:
            return host_device_put(arr, NamedSharding(mesh,
                                                      _entry_spec(entry)))
        return arr

    def _use_broadcast(sharding) -> bool:
        # Fully-replicated leaves on a multi-host run: only the primary
        # touches storage; bytes fan out over the interconnect — kills the
        # O(hosts × ckpt bytes) storage read amplification of everyone
        # re-assembling.  CRC is verified by the one process that reads.
        return (jax.process_count() > 1
                and isinstance(sharding, NamedSharding)
                and sharding.is_fully_replicated
                and os.environ.get("TPUFRAME_RESTORE_BROADCAST", "1") == "1"
                and {d.id for d in sharding.mesh.devices.flat}
                == {d.id for d in jax.devices()})

    def _receive_broadcast_batch(plan) -> dict:
        """All primary-read leaves shipped in ONE packed collective.

        Per-leaf broadcasts deadlock: the primary blocks on storage reads
        while the placeholder ranks race ahead dispatching later leaves'
        broadcast programs, and those programs' out-of-band Gloo/communicator
        setup interleaves with in-flight collectives — the exact
        collective-ordering hazard Horovod's background coordinator existed
        to serialize away (SURVEY.md §3b).  One program + one collective has
        no ordering to get wrong, and is faster (one fabric round instead of
        hundreds).  Transient cost: the packed replicated-leaf bytes
        materialize once per host."""
        eligible, shard_mesh = [], None
        for name, tgt in plan:
            entry = manifest["leaves"][name]
            s = getattr(tgt, "sharding", None)
            if s is None:
                if mesh is None:
                    continue
                s = NamedSharding(mesh, _entry_spec(entry))
            if _use_broadcast(s):
                eligible.append((name, entry))
                shard_mesh = s.mesh
        if not eligible:
            return {}
        from tpuframe.parallel import collectives

        sizes = []
        for name, entry in eligible:
            n = int(np.prod(tuple(entry["shape"]), dtype=np.int64)) \
                if entry["shape"] else 1
            sizes.append(n * np.dtype(entry["dtype"]).itemsize)
        total = int(sum(sizes))
        if jax.process_index() == 0:
            parts = []
            for name, entry in eligible:
                a = _assemble(path, entry, manifest["crc"], verify_crc,
                              crc_algo)
                a = np.ascontiguousarray(
                    a.astype(np.dtype(entry["dtype"]), copy=False))
                # reshape(-1) before view: 0-d leaves (step counters) reject
                # itemsize-changing views.
                parts.append(a.reshape(-1).view(np.uint8))
            buf = np.concatenate(parts)
            assert buf.nbytes == total, (buf.nbytes, total)
        else:  # placeholder; payload arrives over the fabric
            buf = np.zeros(total, np.uint8)
        got = collectives.primary_device_put(
            buf, NamedSharding(shard_mesh, P()))
        # jnp.sum promotes uint8 — bring the bytes back to uint8 (values are
        # preserved: exactly one row of the broadcast sum is nonzero).
        host = np.asarray(got.addressable_shards[0].data).astype(np.uint8)
        payload, off = {}, 0
        for (name, entry), nb in zip(eligible, sizes):
            payload[name] = host[off:off + nb].view(
                np.dtype(entry["dtype"])).reshape(tuple(entry["shape"]))
            off += nb
        return payload

    if target is not None:
        # Exact structure (incl. registered dataclasses like TrainState)
        # comes from the caller's abstract/concrete target tree.
        tgt_names, tgt_leaves, treedef = _flatten_with_paths(target)
        if set(tgt_names) != set(saved_names):
            missing = set(tgt_names) - set(saved_names)
            extra = set(saved_names) - set(tgt_names)
            raise ValueError(
                f"checkpoint/target structure mismatch; missing={sorted(missing)} "
                f"extra={sorted(extra)}")
        _bcast_payload = _receive_broadcast_batch(zip(tgt_names, tgt_leaves))
        leaves = [_placed(name, tgt) for name, tgt in zip(tgt_names,
                                                          tgt_leaves)]
        return jax.tree_util.tree_unflatten(treedef, leaves)

    # No target: rebuild a nested dict from the saved leaf paths.
    _bcast_payload = _receive_broadcast_batch(
        [(name, None) for name in saved_names])
    out: dict = {}
    for name in saved_names:
        node = out
        parts = name.split("/")
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = _placed(name, None)
    return out


def _assemble(path: str, entry: dict, crcs: dict, verify_crc: bool,
              algo: str = "crc32c") -> np.ndarray:
    shape = tuple(entry["shape"])
    dtype = np.dtype(entry["dtype"])
    shards = entry["shards"] if entry["shards"] else []
    if not shards:
        raise FileNotFoundError(f"manifest entry has no shard files: {entry}")
    first = _load_shard(path, shards[0]["file"], crcs, verify_crc, dtype,
                        algo)
    if shards[0]["index"] is None or first.shape == shape:
        return first
    out = np.empty(shape, dtype)
    for sh in shards:
        data = _load_shard(path, sh["file"], crcs, verify_crc, dtype, algo)
        slices = tuple(slice(lo, hi) for lo, hi in sh["index"])
        out[slices] = data
    return out


def _assemble_region(path: str, entry: dict, region: tuple[slice, ...],
                     crcs: dict, verify_crc: bool,
                     file_cache: dict, algo: str = "crc32c") -> np.ndarray:
    """Materialize only ``region`` of a saved leaf, reading just the shard
    files that overlap it — the per-device restore path that avoids every
    host reading the whole checkpoint (SURVEY.md §4.4's no-rank-0-bottleneck
    goal applied to restore)."""
    shape = tuple(entry["shape"])
    dtype = np.dtype(entry["dtype"])
    bounds = [(0 if s.start is None else s.start,
               dim if s.stop is None else s.stop)
              for s, dim in zip(region, shape)]
    out = np.empty([hi - lo for lo, hi in bounds], dtype)
    for sh in entry["shards"]:
        idx = sh["index"] or [(0, d) for d in shape]
        overlap = [(max(lo, slo), min(hi, shi))
                   for (lo, hi), (slo, shi) in zip(bounds, idx)]
        if any(lo >= hi for lo, hi in overlap):
            continue
        if sh["file"] not in file_cache:
            file_cache[sh["file"]] = _load_shard(path, sh["file"], crcs,
                                                 verify_crc, dtype, algo)
        data = file_cache[sh["file"]]
        src = tuple(slice(lo - slo, hi - slo)
                    for (lo, hi), (slo, _) in zip(overlap, idx))
        dst = tuple(slice(lo - blo, hi - blo)
                    for (lo, hi), (blo, _) in zip(overlap, bounds))
        out[dst] = data[src]
    return out


def _load_shard(path: str, fname: str, crcs: dict, verify_crc: bool,
                dtype: np.dtype | None = None,
                algo: str = "crc32c") -> np.ndarray:
    raw = gcs.read_bytes(gcs.join(path, fname))
    if verify_crc and fname in crcs and _crc32(raw, algo) != crcs[fname]:
        raise IOError(f"CRC mismatch in checkpoint shard {fname} — corrupt file")
    arr = np.load(io.BytesIO(raw), allow_pickle=False)
    if arr.dtype.kind == "V" and dtype is not None:
        # numpy round-trips ml_dtypes (bfloat16 etc.) as raw void records;
        # reinterpret with the dtype recorded in the manifest.
        arr = arr.view(dtype)
    return arr


def _barrier() -> None:
    """Cross-host sync so COMMIT is written only after every host's shards."""
    from tpuframe.parallel import bootstrap

    bootstrap.host_barrier("tpuframe_ckpt_commit")


def _committed_steps(directory: str) -> list[int]:
    """Committed checkpoint steps, ascending.  Quarantined ``.corrupt``
    dirs don't match ``_STEP_RE`` and so are invisible here by design."""
    steps = []
    for name in gcs.listdir(directory):
        m = _STEP_RE.match(name)
        if m and gcs.exists(gcs.join(directory, name, _COMMIT)):
            steps.append(int(m.group(1)))
    return sorted(steps)


def latest_step(directory: str) -> int | None:
    steps = _committed_steps(directory)
    return steps[-1] if steps else None


def committed_world(directory: str) -> dict | None:
    """World metadata of the NEWEST committed checkpoint —
    ``{"step", "processes", "devices"}`` — or None (no checkpoint,
    pre-elastic manifest without the ``world`` key, or unreadable
    manifest).  A peek, not a restore: best-effort and read-only, it
    never quarantines — the elastic resize decision must not mutate the
    checkpoint directory before restore_latest gets its turn."""
    try:
        steps = _committed_steps(directory)
        if not steps:
            return None
        manifest = json.loads(gcs.read_bytes(
            gcs.join(directory, f"step_{steps[-1]:08d}", _MANIFEST)))
        world = manifest.get("world")
        if isinstance(world, dict) and "devices" in world:
            return {"step": steps[-1], **world}
    except (OSError, EOFError, KeyError, ValueError):
        return None
    return None


def in_flight_step(directory: str) -> int | None:
    """Highest ``step_N`` directory WITHOUT a COMMIT — evidence of an
    async save still uploading (or killed mid-upload).  The supervisor's
    progress probe treats this as progress past the last committed step:
    a job preempted with a snapshot in flight did advance, and charging
    its relaunch budget for the commit it never got to finish would turn
    every slow-storage preemption into a spurious crash-loop verdict.
    Quarantined ``.corrupt`` dirs don't match the pattern and never
    count."""
    steps = []
    try:
        names = gcs.listdir(directory)
    except OSError:
        return None
    for name in names:
        m = _STEP_RE.match(name)
        if m and not gcs.exists(gcs.join(directory, name, _COMMIT)):
            steps.append(int(m.group(1)))
    return max(steps) if steps else None


def quarantine_step(directory: str, step: int) -> str:
    """Rename ``step_N`` to ``step_N.corrupt`` so resume skips it forever
    while the evidence survives for post-mortem.  Process 0 only — a pod
    of hosts discovering the same bad checkpoint must not race the rename
    (losers would see FileNotFoundError on a directory already moved)."""
    src = gcs.join(directory, f"step_{step:08d}")
    dst = src + ".corrupt"
    if jax.process_index() == 0:
        gcs.rename_tree(src, dst)
    return dst


class CheckpointManager:
    """Periodic save + retention + resume-latest (reference parity: the
    checkpoint hooks + resume-from-bucket path, SURVEY.md §3a/§4.4).

    ``async_write=True``: ``save()`` snapshots device state synchronously
    (device->host copies of this host's owned shards) and returns; file
    serialization, upload, and the COMMIT land on a single background
    worker thread, so the train loop never waits on storage.  Cross-host
    finalization uses sidecar-file polling instead of a collective barrier
    — background threads must never issue collectives (ordering hazard vs
    the main loop's compiled steps).  One worker == saves stay ordered;
    call ``wait_pending()`` before reading the latest checkpoint back or
    exiting."""

    def __init__(self, directory: str, *, every_steps: int = 1000,
                 keep: int = 3, async_write: bool = False):
        self.directory = directory
        self.every_steps = every_steps
        self.keep = keep
        self.async_write = async_write
        # (worker, step, path) per in-flight async save — flush() needs
        # the step/path to quarantine a deadline-stranded upload.
        # _mutex guards _pending/_errors/_last_path: the worker thread
        # appends errors while the train loop prunes/waits, so every
        # mutation holds it (TF114) — and NO join() ever runs under it
        # (the worker takes it to report an error; joining while holding
        # it would deadlock).
        self._mutex = threading.Lock()
        self._pending: list[tuple[threading.Thread, int, str]] = []
        self._errors: list[str] = []
        self._last_path: str | None = None
        gcs.makedirs(directory)

    def should_save(self, step: int) -> bool:
        return step > 0 and step % self.every_steps == 0

    def save(self, step: int, tree: PyTree) -> str:
        if not self.async_write:
            t0 = time.perf_counter()
            path = save(self.directory, step, tree)
            self._gc()
            ms = round((time.perf_counter() - t0) * 1e3, 3)
            # block_ms == ms for sync saves: the whole write sits on the
            # step path (what the blocked_ckpt anomaly detector reads).
            obs_events.emit("ckpt_save", step=step, ms=ms, block_ms=ms,
                            async_write=False)
            return path
        prep_t0 = time.time()
        path, manifest, owned_files = _prepare_save(self.directory, step,
                                                    tree)
        # Backpressure: each queued save holds a full host-RAM snapshot.
        # Cap the backlog at 2 (one writing + one queued) — beyond that,
        # block briefly on the oldest instead of accumulating snapshots
        # until the host OOMs; and prune finished workers (only the newest
        # is needed for ordering).  Prune/read under the mutex, join
        # outside it.
        while True:
            with self._mutex:
                self._pending = [p for p in self._pending
                                 if p[0].is_alive()]
                oldest = (self._pending[0][0]
                          if len(self._pending) >= 2 else None)
                prev = self._pending[-1][0] if self._pending else None
            if oldest is None:
                break
            oldest.join()
        # What the step path actually waited for: the snapshot plus any
        # backpressure join above.  Captured here so the worker can stamp
        # it on the ckpt_save event next to the full span.
        block_ms = round((time.time() - prep_t0) * 1e3, 3)

        def work():
            try:
                if prev is not None:
                    prev.join()  # saves commit in order
                _write_owned(path, owned_files)
                _finalize(path, manifest, poll=True,
                          min_mtime=prep_t0 - 60.0)
                self._gc()
                # ms spans snapshot through commit; the train loop only
                # blocked for block_ms (the snapshot slice).
                obs_events.emit("ckpt_save", step=step,
                                ms=round((time.time() - prep_t0) * 1e3, 3),
                                block_ms=block_ms,
                                async_write=True)
            except Exception as e:  # noqa: BLE001 — surfaced by wait_pending
                with self._mutex:
                    self._errors.append(f"save step {step}: "
                                        f"{type(e).__name__}: {e}")

        t = threading.Thread(target=work, name=f"ckpt-save-{step}",
                             daemon=True)
        with self._mutex:
            self._pending.append((t, step, path))
            self._last_path = path
        t.start()
        # Preemption-while-uploading seam: SIGTERM lands the instant a
        # snapshot is in flight — the exact window flush() exists for.
        faults.fire("sigterm_pending_upload")
        return path

    def save_best(self, step: int, tree: PyTree, metric: float,
                  *, mode: str = "min") -> bool:
        """Keep the single best-by-eval-metric checkpoint under ``best/``
        (the reference genre's 'save best model' hook).  Returns True when
        ``metric`` beat the stored record and the state was saved.  Always
        a synchronous save: best saves are rare (eval cadence) and racing
        an in-flight periodic async save of the same step is not worth it.
        Collective: every process must call it with the same metric."""
        if mode not in ("min", "max"):
            raise ValueError(f"mode must be 'min' or 'max', got {mode!r}")
        best_dir = gcs.join(self.directory, "best")
        record_path = gcs.join(best_dir, "metric.json")
        prev = None
        if gcs.exists(record_path):
            record = json.loads(gcs.read_bytes(record_path))
            if record.get("mode", mode) != mode:
                raise ValueError(
                    f"save_best mode {mode!r} contradicts the stored best "
                    f"record's mode {record['mode']!r} in {best_dir} — "
                    f"comparing a new metric against an opposite-ordered "
                    f"record would silently corrupt best tracking")
            prev = record["metric"]
        better = (prev is None or
                  (metric < prev if mode == "min" else metric > prev))
        if not better:
            return False
        # Order matters for crash safety: save the NEW best first (COMMIT-
        # atomic), then update the record, then delete the stale dir —
        # deleting first would leave a window where a preemption loses the
        # old best while the record still blocks any future save_best.
        save(best_dir, step, tree)
        if jax.process_index() == 0:
            gcs.write_bytes(record_path, json.dumps(
                {"metric": float(metric), "step": step,
                 "mode": mode}).encode())
            new_name = f"step_{step:08d}"
            for m in (_STEP_RE.match(n) for n in gcs.listdir(best_dir)):
                if m and m.group(0) != new_name:
                    gcs.delete_tree(gcs.join(best_dir, m.group(0)))
        return True

    def restore_best(self, *, mesh: Mesh | None = None,
                     target: PyTree | None = None):
        """(step, tree) of the best-metric checkpoint, or None.

        The step comes from the RECORD, not from the newest committed dir:
        save_best's crash window can leave the beaten checkpoint alongside
        the new one, and the beaten one may carry the higher step."""
        best_dir = gcs.join(self.directory, "best")
        record_path = gcs.join(best_dir, "metric.json")
        if gcs.exists(record_path):
            step = int(json.loads(gcs.read_bytes(record_path))["step"])
            if not gcs.exists(gcs.join(best_dir, f"step_{step:08d}",
                                       _COMMIT)):
                # record written but its save lost (shouldn't happen given
                # save-before-record ordering; be defensive): fall back
                step = latest_step(best_dir)
        else:
            step = latest_step(best_dir)
        if step is None:
            return None
        return step, restore(best_dir, step, mesh=mesh, target=target)

    def wait_pending(self, *, commit_timeout_s: float = 600.0) -> None:
        """Block until every async save has committed (no-op when sync).

        Joining the local worker only proves THIS host's writes are done;
        the COMMIT marker comes from process 0's worker, so every other
        host additionally polls for it — after this returns, the newest
        checkpoint is durably visible to all hosts (or the timeout left it
        torn, which restore already tolerates)."""
        with self._mutex:
            pending = list(self._pending)
        for t, _, _ in pending:
            t.join()
        with self._mutex:
            self._pending = [p for p in self._pending if p not in pending]
            errs = "; ".join(self._errors)
            self._errors = []
            last = self._last_path
        if errs:
            raise RuntimeError(f"async checkpoint save(s) failed: {errs}")
        if last is None or jax.process_index() == 0:
            return
        deadline = time.time() + commit_timeout_s
        while not gcs.exists(gcs.join(last, _COMMIT)):
            if time.time() > deadline:
                print(f"[ckpt] wait_pending: no COMMIT at {last} after "
                      f"{commit_timeout_s}s", flush=True)
                return
            time.sleep(0.2)

    def flush(self, deadline_s: float = 60.0) -> bool:
        """Deadline-bounded drain of pending async saves — the preemption
        exit gate (train.py calls this before raising rc 14, inside the
        SIGTERM grace window).

        Commit-or-quarantine: every pending save either commits within
        the deadline (returns True) or its uncommitted ``step_N`` dir is
        quarantined to ``step_N.corrupt`` (returns False) — the directory
        is never left in a state a later resume, GC pass, or progress
        probe could mistake for durable.  Worker errors are printed, not
        raised: the caller is exiting on a grace timer, and the
        quarantine below already neutralizes whatever the failed save
        left behind.  Sync managers have nothing in flight and return
        True immediately."""
        deadline = time.time() + deadline_s
        with self._mutex:
            pending = list(self._pending)
        for t, _, _ in pending:
            t.join(max(0.0, deadline - time.time()))
        with self._mutex:
            self._pending = [p for p in self._pending if p not in pending]
            errs = "; ".join(self._errors)
            self._errors = []
        if errs:
            print(f"[ckpt] flush: async save error(s): {errs}", flush=True)
        all_committed = True
        for t, step, path in pending:
            committed = gcs.exists(gcs.join(path, _COMMIT))
            # Non-primary hosts: the COMMIT comes from process 0's
            # finalizer, possibly after the local worker finished — poll
            # out the remaining deadline for it.  (A still-alive local
            # worker means this host's sidecar isn't written, so process 0
            # cannot commit yet; no point polling.)
            while (not committed and not t.is_alive()
                   and jax.process_index() != 0
                   and time.time() <= deadline):
                time.sleep(0.1)
                committed = gcs.exists(gcs.join(path, _COMMIT))
            if committed:
                continue
            all_committed = False
            quarantine_step(self.directory, step)  # process 0 renames
            print(f"[ckpt] flush: step {step} uncommitted at deadline "
                  f"({deadline_s:.1f}s) — quarantined, resume will use "
                  f"the previous committed step", flush=True)
        return all_committed

    def maybe_save(self, step: int, tree: PyTree) -> str | None:
        return self.save(step, tree) if self.should_save(step) else None

    def restore_latest(self, *, mesh: Mesh | None = None,
                       target: PyTree | None = None):
        """(step, tree) of the newest *readable* committed checkpoint, or
        None — the automatic resume path for slice-restart recovery
        (SURVEY.md §5.3).

        Hardened: a committed-but-unreadable latest checkpoint (CRC
        mismatch, torn/garbled manifest, vanished shard) is quarantined to
        ``step_N.corrupt`` and resume walks back to the previous committed
        step with a loud warning, instead of bricking the job on an error
        the operator can do nothing about mid-run.  Structure mismatches
        (ValueError from a target/treedef disagreement) still raise: that
        is a config error, and silently walking past it would resume every
        misconfigured job from step 0."""
        tried: set[int] = set()
        while True:
            steps = [s for s in _committed_steps(self.directory)
                     if s not in tried]
            if not steps:
                return None
            step = steps[-1]
            tried.add(step)
            try:
                t0 = time.perf_counter()
                out = step, restore(self.directory, step, mesh=mesh,
                                    target=target)
                # Times host-side I/O (restore reads + deserializes on
                # host), not async device dispatch.
                ms = (time.perf_counter() - t0) * 1e3  # tf-lint: ok[TF103]
                obs_events.emit("ckpt_restore", step=step,
                                ms=round(ms, 3))
                return out
            except (OSError, EOFError, KeyError,
                    json.JSONDecodeError) as e:
                quarantined = quarantine_step(self.directory, step)
                print(f"[ckpt] WARNING: checkpoint step {step} is "
                      f"unreadable ({type(e).__name__}: {e}) — quarantined "
                      f"to {quarantined}; walking back to the previous "
                      f"committed step", flush=True)

    def _gc(self) -> None:
        if jax.process_index() != 0:
            return
        # Committed checkpoints only: an uncommitted dir may be an IN-FLIGHT
        # async save (another host mid-write) — deleting it would corrupt a
        # checkpoint about to gain its COMMIT.  Torn crash leftovers are
        # therefore never GC'd here; they are bounded by crash count,
        # ignored by resume, and overwritten if the job retrains to the
        # same step.
        steps = sorted(
            int(m.group(1))
            for m in (_STEP_RE.match(n) for n in gcs.listdir(self.directory))
            if m and gcs.exists(gcs.join(self.directory, m.group(0),
                                         _COMMIT)))
        for old in steps[:-self.keep] if self.keep > 0 else []:
            gcs.delete_tree(gcs.join(self.directory, f"step_{old:08d}"))
