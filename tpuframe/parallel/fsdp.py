"""FSDP / ZeRO-style parameter + optimizer-state sharding over ``fsdp``.

The reference is pure replicated-parameter data parallelism (SURVEY.md §3c);
its optimizer state is replicated on every GPU.  On TPU the idiomatic
memory-scaling upgrade is sharding parameters and optimizer state across a
mesh axis and letting XLA's SPMD partitioner insert the all-gathers (before
use) and reduce-scatters (of gradients) — cross-replica weight-update
sharding (PAPERS.md:5) generalized to ZeRO-3.  No runtime machinery: the
sharding is a *placement decision* expressed as ``NamedSharding``s on the
``TrainState`` pytree, consumed by the auto-SPMD (``mode="jit"``) train step.

Rule: each array leaf shards its largest dimension divisible by the fsdp
axis size; indivisible or tiny leaves stay replicated.  The same rule
applied to the optimizer state (whose momentum/variance leaves mirror the
param shapes) yields consistent placement for the whole update.
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax.sharding import AxisType
except ImportError:  # older jax: no sharding-in-types; all axes are Auto
    AxisType = None

PyTree = Any

MIN_SHARD_ELEMENTS = 1024  # below this, sharding overhead beats the savings


def auto_mesh(mesh: Mesh) -> Mesh:
    """An Auto-axis-typed twin of ``mesh``.

    ``jax.make_mesh`` yields Explicit axes (sharding-in-types), under which
    auto-SPMD propagation refuses ambiguous ops (e.g. embedding gathers from
    an fsdp-sharded table).  The FSDP path wants classic GSPMD propagation,
    so its shardings are built on an Auto twin of the same device layout."""
    if AxisType is None or not hasattr(mesh, "axis_types"):
        return mesh  # pre-AxisType jax: every mesh already propagates Auto
    if all(t == AxisType.Auto for t in mesh.axis_types):
        return mesh
    # Axis-type-only rewrap of an existing seam-built mesh: devices and
    # axis names pass through unchanged.
    return Mesh(mesh.devices, mesh.axis_names,  # tf-lint: ok[TF119]
                axis_types=(AxisType.Auto,) * len(mesh.axis_names))


def choose_spec(shape: tuple[int, ...], fsdp_size: int,
                axis: str = "fsdp") -> P:
    """Shard the largest divisible dim of ``shape`` over ``axis``."""
    if fsdp_size <= 1 or int(np.prod(shape or (1,))) < MIN_SHARD_ELEMENTS:
        return P()
    dims = sorted(range(len(shape)), key=lambda i: shape[i], reverse=True)
    for i in dims:
        if shape[i] % fsdp_size == 0:
            spec = [None] * len(shape)
            spec[i] = axis
            return P(*spec)
    return P()


def state_shardings(state: PyTree, mesh: Mesh, axis: str = "fsdp",
                    *, tp_rules=None, tp_axis: str = "model") -> PyTree:
    """NamedSharding tree for a TrainState (or any pytree of arrays).

    With ``tp_rules`` (tpuframe.parallel.tp) the tensor-parallel spec is
    applied first by parameter path; the ``fsdp`` axis then shards the
    largest *still-unsharded* divisible dim of each leaf — composing
    ZeRO × TP from placement alone.
    """
    size = mesh.shape[axis]
    axis_sizes = dict(mesh.shape) if tp_rules else None
    amesh = auto_mesh(mesh)
    flat, treedef = jax.tree_util.tree_flatten_with_path(state)

    def path_str(path) -> str:
        parts = []
        for k in path:
            for attr in ("key", "name", "idx"):
                if hasattr(k, attr):
                    parts.append(str(getattr(k, attr)))
                    break
            else:
                parts.append(str(k))
        return "/".join(parts)

    out = []
    for path, x in flat:
        shape = tuple(getattr(x, "shape", ()))
        base = None
        if axis_sizes is not None:
            from tpuframe.parallel import tp as tp_lib

            base = tp_lib.match_spec(path_str(path), shape, axis_sizes,
                                     tp_rules)
        spec = _add_fsdp(shape, base, size, axis)
        out.append(NamedSharding(amesh, spec))
    return jax.tree_util.tree_unflatten(treedef, out)


def _add_fsdp(shape: tuple[int, ...], base: P | None, fsdp_size: int,
              axis: str) -> P:
    """Overlay the fsdp axis on the largest unsharded divisible dim."""
    entries = list(base) + [None] * (len(shape) - len(base)) if base else         [None] * len(shape)
    if fsdp_size <= 1 or int(np.prod(shape or (1,))) < MIN_SHARD_ELEMENTS:
        return P(*entries) if base else P()
    dims = sorted(range(len(shape)), key=lambda i: shape[i], reverse=True)
    for i in dims:
        if entries[i] is None and shape[i] % fsdp_size == 0:
            entries[i] = axis
            return P(*entries)
    return P(*entries) if base else P()


def shard_state(state: PyTree, mesh: Mesh, axis: str = "fsdp") -> PyTree:
    """Place a (host or replicated) TrainState with fsdp shardings."""
    from tpuframe.parallel import mesh as mesh_lib

    shardings = state_shardings(state, mesh, axis)
    return jax.tree.map(mesh_lib.host_device_put, state, shardings)


def param_fraction_sharded(state: PyTree, axis: str = "fsdp") -> float:
    """Diagnostics: fraction of state elements whose placement splits ``axis``
    (used by tests and the harness banner)."""
    total, sharded = 0, 0
    for leaf in jax.tree.leaves(state):
        n = int(np.prod(leaf.shape)) if leaf.shape else 1
        total += n
        sharding = getattr(leaf, "sharding", None)
        spec = getattr(sharding, "spec", None)
        if spec is not None and any(
                (ax == axis or (isinstance(ax, tuple) and axis in ax))
                for ax in spec if ax is not None):
            sharded += n
    return sharded / max(total, 1)
