"""Device-mesh construction — the TPU-native "communicator".

The reference's communicator is implicit: Horovod ranks 0..N-1 joined in one
NCCL/MPI world (SURVEY.md §2 L0–L1).  On TPU the analogous object is a
``jax.sharding.Mesh``: a named, possibly multi-dimensional view of the chips.
The reference is pure data-parallel (SURVEY.md §3c), so the default mesh is
1-D over a ``data`` axis; we still carry optional ``model`` / ``seq`` /
``pipe`` / ``expert`` axes (size 1 by default) so shardings composed against
this mesh do not need rewriting when a workload later turns those on — the
design requirement in SURVEY.md §5.7 that the mesh not preclude extra axes.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Canonical axis order. Data-parallel outermost so its collectives ride the
# slowest-varying physical dimension (and DCN when a mesh spans slices);
# model/seq innermost so their heavier collectives stay on nearest-neighbor ICI.
AXES = ("data", "fsdp", "pipe", "seq", "expert", "model")

# Multi-slice meshes carry one extra DCN axis *outside* every ICI axis: the
# slice axis must be the slowest-varying dimension so that only collectives
# which genuinely span slices ride the (much slower) data-center network.
SLICE_AXIS = "slice"

# The axes over which a global batch is partitioned. Batch-like arrays shard
# over all of these; fsdp contributes to the data-parallel world size.
BATCH_AXES = ("data", "fsdp")


@dataclass(frozen=True)
class MeshSpec:
    """Logical parallelism degrees. -1 on ``data`` means "all remaining chips".

    ``slices > 1`` declares a hierarchical ICI×DCN topology: the ICI axes
    describe one slice, and a ``slice`` axis of that size is prepended
    outermost.  ``slices == 1`` (the default) produces the exact same mesh
    as before the axis existed — single-slice programs see zero drift.
    """

    data: int = -1
    fsdp: int = 1
    pipe: int = 1
    seq: int = 1
    expert: int = 1
    model: int = 1
    slices: int = 1

    def axis_names(self) -> tuple[str, ...]:
        return (SLICE_AXIS, *AXES) if self.slices > 1 else AXES

    def sizes(self, n_devices: int) -> dict[str, int]:
        if self.slices < 1:
            raise ValueError(f"slices must be >= 1, got {self.slices}")
        sizes = {
            "data": self.data,
            "fsdp": self.fsdp,
            "pipe": self.pipe,
            "seq": self.seq,
            "expert": self.expert,
            "model": self.model,
        }
        if self.slices > 1:
            sizes = {SLICE_AXIS: self.slices, **sizes}
        fixed = int(np.prod([v for v in sizes.values() if v != -1]))
        n_wild = sum(1 for v in sizes.values() if v == -1)
        if n_wild > 1:
            raise ValueError("at most one mesh axis may be -1")
        if n_wild == 1:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {fixed}"
                )
            wild = n_devices // fixed
            sizes = {k: (wild if v == -1 else v) for k, v in sizes.items()}
        total = int(np.prod(list(sizes.values())))
        if total != n_devices:
            raise ValueError(
                f"mesh {sizes} covers {total} devices but {n_devices} are present"
            )
        return sizes


def make_mesh(
    spec: MeshSpec | None = None,
    *,
    devices: list[jax.Device] | None = None,
) -> Mesh:
    """Build the framework's device mesh.

    Defaults to a pure data-parallel mesh over every visible chip — the
    reference's (only) topology, SURVEY.md §3c.  ``jax.make_mesh`` internally
    reorders devices to match the physical ICI torus when running on real TPU
    slices, so collectives over the trailing axes map to neighbor links.
    """
    spec = spec or MeshSpec()
    all_devices = jax.devices()
    devices = devices if devices is not None else all_devices
    sizes = spec.sizes(len(devices))
    axes = spec.axis_names()
    shape = tuple(sizes[a] for a in axes)
    if [d.id for d in devices] == [d.id for d in all_devices]:
        # Full-device meshes go through jax.make_mesh, which reorders devices
        # to match the physical ICI torus on real TPU slices.
        return jax.make_mesh(shape, axes, devices=devices)
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, axes)


def best_effort_mesh(max_devices: int | None = None) -> Mesh:
    """Data-parallel mesh over up to ``max_devices`` chips (for tests/bench)."""
    devices = jax.devices()
    if max_devices is not None:
        devices = devices[:max_devices]
    return make_mesh(MeshSpec(data=len(devices)), devices=devices)


def batch_axes(mesh: Mesh | None = None) -> tuple[str, ...]:
    """The axes a global batch shards over, for this mesh's topology.

    On a hierarchical mesh the slice axis is batch-like too — each slice
    works on its own shard of the batch and only gradients cross DCN — so
    it joins ``data``/``fsdp`` (outermost, matching mesh axis order).
    """
    if mesh is not None and SLICE_AXIS in mesh.shape:
        return (SLICE_AXIS, *BATCH_AXES)
    return BATCH_AXES


def data_parallel_size(mesh: Mesh) -> int:
    return int(np.prod([mesh.shape[a] for a in batch_axes(mesh)]))


def batch_spec(extra: tuple = (), *, mesh: Mesh | None = None) -> P:
    """PartitionSpec for batch-major arrays: leading dim over the batch axes."""
    return P(batch_axes(mesh), *extra)


def replicated_spec() -> P:
    return P()


def batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, batch_spec(mesh=mesh))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, replicated_spec())


def host_device_put(x, sharding: NamedSharding):
    """Multi-host-safe placement of host data.

    ``jax.device_put`` rejects shardings spanning non-addressable devices;
    on multi-host meshes each process contributes its shard via
    ``make_array_from_callback``.  Handles PRNG-key (extended-dtype) leaves,
    which numpy cannot represent directly."""
    if jax.process_count() == 1 or sharding.is_fully_addressable:
        return jax.device_put(x, sharding)
    if hasattr(x, "dtype") and jax.dtypes.issubdtype(x.dtype, jax.dtypes.extended):
        data = host_device_put(jax.random.key_data(x), sharding)
        return jax.random.wrap_key_data(data, impl=jax.random.key_impl(x))
    arr = np.asarray(x)
    return jax.make_array_from_callback(
        arr.shape, sharding, lambda idx: arr[idx])


def local_batch_size(mesh: Mesh, global_batch: int) -> int:
    """Per-host batch share (reference: DistributedSampler num_replicas/rank
    partitioning, SURVEY.md §3a 'GCS data loader')."""
    dp = data_parallel_size(mesh)
    if global_batch % dp != 0:
        raise ValueError(f"global batch {global_batch} not divisible by dp={dp}")
    # Each host feeds its local devices; global batch / process_count rows.
    n_proc = max(1, jax.process_count())
    if global_batch % n_proc != 0:
        raise ValueError(f"global batch {global_batch} not divisible by hosts={n_proc}")
    return global_batch // n_proc
