"""Collective-fusion tuning — HOROVOD_FUSION_THRESHOLD parity (SURVEY.md §3b).

Horovod packs small gradient tensors into a fusion buffer (default 64 MB)
before each NCCL allreduce; the knob matters most for many-small-tensor
models (BERT-base, ~200 tensors — config 4's stress axis [B:10]).  Under XLA
the same role is played by the all-reduce combiner pass, which merges small
AllReduce HLOs up to a byte threshold.  This module maps the Horovod-style
env knob onto the XLA flags:

    TPUFRAME_FUSION_THRESHOLD=67108864   # bytes, like HOROVOD_FUSION_THRESHOLD

XLA flags only take effect before backend initialization, so the harness
calls :func:`apply_from_env` at import/startup (tpuframe.parallel.bootstrap);
afterwards the combiner threshold is compiled into every program.
"""

from __future__ import annotations

import logging
import os

logger = logging.getLogger(__name__)

ENV_KNOB = "TPUFRAME_FUSION_THRESHOLD"

# The combiner passes read DebugOptions.xla_gpu_all_reduce_combine_threshold
# _bytes ("gpu" is historical naming — it is the generic DebugOptions field,
# and XLA's flag parser aborts on unknown flags, so only real fields can be
# set).  On TPU slices, additional libtpu-private combiner knobs travel via
# LIBTPU_INIT_ARGS, which the launcher propagates (SURVEY.md §5.6).
_FLAG_TEMPLATES = (
    "--xla_gpu_all_reduce_combine_threshold_bytes={n}",
)

_APPLIED: dict = {"threshold": None}


def fusion_flags(threshold_bytes: int) -> list[str]:
    return [t.format(n=int(threshold_bytes)) for t in _FLAG_TEMPLATES]


def apply(threshold_bytes: int) -> bool:
    """Prepend the combiner flags to XLA_FLAGS. Returns False (with a
    warning) if the backend already initialized — too late to take effect."""
    import jax

    live = jax._src.xla_bridge._backends  # noqa: SLF001 — init probe only
    if live:
        logger.warning(
            "%s=%d requested after backend init — combiner flags ignored; "
            "set the env before importing jax workloads", ENV_KNOB,
            threshold_bytes)
        return False
    existing = os.environ.get("XLA_FLAGS", "")
    flags = [f for f in fusion_flags(threshold_bytes) if f not in existing]
    os.environ["XLA_FLAGS"] = (existing + " " + " ".join(flags)).strip()
    _APPLIED["threshold"] = int(threshold_bytes)
    return True


def apply_from_env() -> int | None:
    """Honor TPUFRAME_FUSION_THRESHOLD if set; returns the applied value."""
    raw = os.environ.get(ENV_KNOB)
    if not raw:
        return None
    threshold = int(raw)
    apply(threshold)
    return threshold


def current() -> int | None:
    return _APPLIED["threshold"]


def step_threshold() -> int | None:
    """The threshold the *train step* should use for explicit program-level
    fusion buffers (tpuframe.parallel.fusion) — read directly from the env so
    it works even after backend init (unlike the XLA-flag path above, which
    is best-effort and backend-dependent).  None → knob unset → leave
    gradient reduction to the autodiff transpose + XLA combiner."""
    raw = os.environ.get(ENV_KNOB)
    return int(raw) if raw else None
