"""Compiled SPMD train/eval steps — the TPU-native hot loop.

Reference hot loop (SURVEY.md §4.1): forward → backward with per-grad hooks
enqueueing async NCCL allreduces into Horovod's C++ op queue → fusion →
``opt.step()`` waits on handles.  On TPU the whole step is ONE XLA program:
grads are ``pmean``-ed inside the traced function, and the compiler does the
ordering, fusion (all-reduce combining) and compute/communication overlap
that Horovod's runtime did by hand.  The only per-step host work left is
feeding the next sharded batch (``tpuframe.data``) and reading back metrics —
exactly the mapping called out in SURVEY.md §2 (L1 row).

Two step-construction modes:
  - ``shard_map`` (default): explicit per-shard code + explicit ``pmean`` —
    the closest analog of Horovod's explicit allreduce, with no surprises.
  - ``jit`` (auto-SPMD): sharding propagation inserts the collectives; same
    semantics, exercised in tests to cross-check the explicit path.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpuframe.parallel import mesh as mesh_lib

_LEGACY_SHARD_MAP = not hasattr(jax, "shard_map")
if not _LEGACY_SHARD_MAP:
    _shard_map = jax.shard_map
else:  # older jax: jax.experimental.shard_map, no vma types
    from jax.experimental.shard_map import shard_map as _shard_map_legacy

    def _shard_map(f, *, mesh, in_specs, out_specs):
        # The legacy static replication checker cannot infer through the
        # step body (no vma types), so it is disabled — which ALSO
        # disables the psum-transpose rewrite that the pmean-of-loss
        # gradient path relies on.  _grad_step compensates by taking
        # local gradients and reducing them explicitly when
        # _LEGACY_SHARD_MAP is set (verified against the single-device
        # step; see tests/test_analysis.py).
        return _shard_map_legacy(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_rep=False)

PyTree = Any

# loss_fn(params, model_state, batch, rng) -> (loss, (new_model_state, metrics))
LossFn = Callable[[PyTree, PyTree, PyTree, jax.Array], tuple[jax.Array, tuple[PyTree, dict]]]


@jax.tree_util.register_dataclass
@dataclass
class TrainState:
    """Replicated training state. ``model_state`` carries mutable collections
    (BatchNorm statistics for the ResNets); empty dict for stateless models."""

    step: jax.Array
    params: PyTree
    opt_state: PyTree
    model_state: PyTree
    rng: jax.Array

    @classmethod
    def create(cls, params: PyTree, tx: optax.GradientTransformation,
               model_state: PyTree | None = None, rng: jax.Array | None = None):
        return cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=tx.init(params),
            model_state={} if model_state is None else model_state,
            rng=jax.random.key(0) if rng is None else rng,
        )


def _grad_step(loss_fn: LossFn, tx: optax.GradientTransformation,
               axes: tuple[str, ...] | None,
               fusion_threshold: int | None,
               accum_steps: int,
               grad_reduce: str,
               weight_update: str,
               wire_format: str,
               hier: str,
               wire_format_dcn: str,
               state: TrainState, batch: PyTree):
    """Shared body for both modes. ``axes`` bound ⇒ explicit collectives."""
    step_rng = jax.random.fold_in(state.rng, state.step)
    if axes:
        # Decorrelate per-replica dropout while keeping params in lockstep.
        for ax in axes:
            step_rng = jax.random.fold_in(step_rng, lax.axis_index(ax))

    if accum_steps > 1:
        return _accum_grad_step(loss_fn, tx, axes, fusion_threshold,
                                accum_steps, grad_reduce, weight_update,
                                wire_format, hier, wire_format_dcn,
                                state, batch, step_rng)

    # The reference's raison d'être: synchronous gradient averaging.
    # Horovod: per-tensor async NCCL ring-allreduce with fusion buffer.
    # Here: the *global* (pmean-ed) loss is what gets differentiated, so the
    # autodiff transpose emits the cross-replica reduction of the gradients
    # (params are replicated/unvarying, so d(pmean ℓ)/dθ = psum(∂ℓᵢ/∂θ)/N —
    # exactly Horovod's averaged allreduce).  XLA's all-reduce combiner fuses
    # the per-leaf reductions and the scheduler overlaps them with remaining
    # backward compute (SURVEY.md §3b).
    #
    # ``fusion_threshold`` set (TPUFRAME_FUSION_THRESHOLD) selects the
    # explicit Horovod-parity path instead: params are pcast to per-replica
    # ("varying") so the backward produces LOCAL gradients with NO implicit
    # reduction (the transpose of replicated params would otherwise insert
    # its own psum), and the framework's fusion buffers
    # (tpuframe.parallel.fusion) perform the only cross-replica averaging —
    # one psum per ≤threshold-byte bucket, 0 → one per leaf.  Same math
    # (psum is linear); observable in the compiled HLO's all-reduce count.
    # ``grad_reduce="adasum"`` also needs LOCAL per-replica grads — the
    # adaptive combine is computed from them, so the implicit
    # pmean-of-loss transpose (which pre-averages) cannot be used.
    explicit = bool(axes) and (fusion_threshold is not None
                               or grad_reduce == "adasum")
    # ZeRO-1 weight-update sharding consumes LOCAL grads too: the sharded
    # update's reduce-scatter IS the step's gradient reduction, so the
    # implicit pmean-of-loss transpose (which would all-reduce) must not
    # run.  On new jax the params are pcast varying like the explicit
    # path; on legacy shard_map local grads come free (below).
    zero1 = bool(axes) and weight_update == "zero1"
    # A quantized wire on the plain-DP path ALSO needs LOCAL grads: the
    # per-replica gradients are what gets block-quantized before the
    # exchange (tpuframe.parallel.quantwire), so the implicit
    # pmean-of-loss transpose (which would pre-reduce in f32) must not
    # run.  The zero1 tail already takes local grads; its wire choice
    # lives inside sharded_update.
    wire_local = bool(axes) and wire_format != "fp" and not zero1
    # The two-level (hierarchical) lowering restructures the gradient
    # mean itself — rs over ICI → cross-slice mean over DCN → ag back
    # (tpuframe.parallel.hier) — so it consumes LOCAL grads like every
    # other explicit wire pattern.  The zero1 tail runs its own
    # two-stage scatter/gather and already takes local grads.
    hier_local = bool(axes) and hier == "hier" and not zero1
    # Legacy shard_map (check_rep=False) has no psum-transpose rewrite:
    # differentiating the pmean-ed loss there yields LOCAL grads with no
    # implicit reduction, so the reduction must be explicit.
    legacy_local = bool(axes) and _LEGACY_SHARD_MAP and not explicit
    diff_params = state.params
    if (explicit or zero1 or wire_local or hier_local) \
            and not _LEGACY_SHARD_MAP:
        # Legacy shard_map needs no pcast (and has none): check_rep=False
        # already differentiates to LOCAL grads with no implicit psum.
        diff_params = jax.tree.map(
            lambda p: lax.pcast(p, axes, to="varying"), state.params)

    def global_loss(params, model_state, batch, rng):
        loss, aux = loss_fn(params, model_state, batch, rng)
        if (axes and not explicit and not legacy_local and not zero1
                and not wire_local and not hier_local):
            loss = lax.pmean(loss, axes)
        return loss, aux

    (loss, (model_state, metrics)), grads = jax.value_and_grad(
        global_loss, has_aux=True)(diff_params, state.model_state, batch, step_rng)

    return _reduce_and_apply(tx, axes, fusion_threshold, grad_reduce,
                             weight_update, wire_format, hier,
                             wire_format_dcn, state,
                             grads, loss, metrics, model_state,
                             reduce_grads=(explicit or legacy_local or zero1
                                           or wire_local or hier_local))


def _reduce_and_apply(tx, axes, fusion_threshold, grad_reduce, weight_update,
                      wire_format, hier, wire_format_dcn, state, grads,
                      loss, metrics, model_state, *, reduce_grads: bool):
    """Shared step tail: cross-replica reductions + optimizer update.

    ``reduce_grads``: True when ``grads``/``loss`` are still per-replica
    (explicit-fusion, adasum, zero1, quantized-wire and accumulation
    paths); False when the pmean-of-loss transpose already reduced them
    (the implicit default)."""
    if weight_update == "zero1" and axes:
        # ZeRO-1 tail: NO gradient all-reduce — the grads stay local and
        # zero1.sharded_update's reduce-scatter performs the one and only
        # gradient-sized reduction.  Scalars (loss/metrics) and BN stats
        # still pmean (all under the audit's scalar floor).
        # ``fusion_threshold`` buckets that reduce-scatter (and the param
        # all-gather out) — same padded bytes, n_buckets collectives
        # instead of n_leaves, issued before any shard is consumed.
        from tpuframe.parallel import zero1 as zero1_lib

        if reduce_grads:
            loss = lax.pmean(loss, axes)
        metrics = jax.tree.map(lambda m: lax.pmean(m, axes), metrics)
        model_state = jax.tree.map(lambda s: lax.pmean(s, axes), model_state)
        grads = jax.tree.map(lambda g, p: g.astype(p.dtype), grads,
                             state.params)
        params, opt_state, grad_norm = zero1_lib.sharded_update(
            tx, axes, state.params, state.opt_state, grads,
            wire_format=wire_format, fusion_threshold=fusion_threshold,
            hier=(hier == "hier"), wire_format_dcn=wire_format_dcn)
        metrics = dict(metrics)
        metrics["loss"] = loss
        metrics["grad_norm"] = grad_norm
        return TrainState(step=state.step + 1, params=params,
                          opt_state=opt_state, model_state=model_state,
                          rng=state.rng), metrics
    if reduce_grads and axes:
        if grad_reduce == "adasum":
            from tpuframe.parallel import collectives

            grads = collectives.adasum(grads, axes)
        elif hier == "hier":
            # Two-level cross-slice mean (tpuframe.parallel.hier): full
            # bytes stay on ICI, only the 1/n_inner shard crosses DCN —
            # in wire_format_dcn.  fusion_threshold buckets the
            # lowerings (fp DCN leg only; validated at build time).
            from tpuframe.parallel import hier as hier_lib

            if fusion_threshold is not None:
                grads = hier_lib.fused_hier_mean(
                    grads, axes, threshold_bytes=fusion_threshold,
                    wire_format_dcn=wire_format_dcn)
            else:
                grads = hier_lib.hier_mean(
                    grads, axes, wire_format_dcn=wire_format_dcn)
        elif fusion_threshold is not None:
            from tpuframe.parallel import fusion

            grads = fusion.staged_pmean(grads, axes,
                                        threshold_bytes=fusion_threshold)
        elif wire_format == "int8-block":
            from tpuframe.parallel import quantwire

            grads = quantwire.all_reduce_mean(grads, axes)
        else:
            grads = jax.tree.map(lambda g: lax.pmean(g, axes), grads)
        loss = lax.pmean(loss, axes)
    if axes:
        metrics = jax.tree.map(lambda m: lax.pmean(m, axes), metrics)
        # BatchNorm running stats: cross-replica averaged so the replicated
        # state stays single-valued (reference kept per-GPU local stats and
        # checkpointed rank 0's — averaging is the SPMD-correct equivalent).
        model_state = jax.tree.map(lambda s: lax.pmean(s, axes), model_state)

    # No-op for same-dtype grads; the accumulation path accumulates in f32
    # and casts back to the param dtype here.
    grads = jax.tree.map(lambda g, p: g.astype(p.dtype), grads, state.params)
    updates, opt_state = tx.update(grads, state.opt_state, state.params)
    params = optax.apply_updates(state.params, updates)
    metrics = dict(metrics)
    metrics["loss"] = loss
    metrics["grad_norm"] = optax.global_norm(grads)
    new_state = TrainState(step=state.step + 1, params=params,
                           opt_state=opt_state, model_state=model_state,
                           rng=state.rng)
    return new_state, metrics


def _accum_grad_step(loss_fn, tx, axes, fusion_threshold, accum_steps,
                     grad_reduce, weight_update, wire_format, hier,
                     wire_format_dcn, state, batch, step_rng):
    """Gradient accumulation — Horovod's ``backward_passes_per_step``
    (DistributedOptimizer option; the reference's recipe for batches that
    exceed device memory).  The local batch is split into ``accum_steps``
    microbatches, a ``lax.scan`` runs fwd+bwd per microbatch accumulating
    f32 gradients and threading mutable model state (BN stats update
    sequentially, matching N torch backward passes), and ONE optimizer
    update + ONE cross-replica reduction happens at the end — collectives
    per step stay constant as accum grows, exactly Horovod's semantics."""
    for leaf in jax.tree.leaves(batch):
        if leaf.shape[0] % accum_steps:
            raise ValueError(
                f"accum_steps={accum_steps} does not divide the per-device "
                f"batch {leaf.shape[0]} (leaf shape {leaf.shape}); choose a "
                f"global batch divisible by devices x accum_steps")
    micro = jax.tree.map(
        lambda a: a.reshape(accum_steps, a.shape[0] // accum_steps,
                            *a.shape[1:]), batch)

    # Differentiate w.r.t. per-replica ("varying") copies of the params:
    # grads then stay LOCAL through the whole scan — zero collectives per
    # microbatch — and the single reduction below is the step's only one
    # (Horovod's backward_passes_per_step wire behavior).  Grads of
    # replicated params would instead be psum'd inside every scan
    # iteration by the autodiff transpose.
    def vary(t):
        if not axes:
            return t
        return jax.tree.map(
            lambda a: a if all(x in jax.typeof(a).vma for x in axes)
            else lax.pcast(a, tuple(x for x in axes
                                    if x not in jax.typeof(a).vma),
                           to="varying"), t)

    diff_params = vary(state.params)

    def one_micro(carry, xs):
        mb_i, i = xs
        model_state, g_acc, loss_acc, metrics_acc = carry
        rng_i = jax.random.fold_in(step_rng, i)
        (loss, (model_state, metrics)), g = jax.value_and_grad(
            loss_fn, has_aux=True)(diff_params, model_state, mb_i, rng_i)
        g_acc = jax.tree.map(
            lambda a, b: a + b.astype(jnp.float32), g_acc, g)
        metrics_acc = jax.tree.map(jnp.add, metrics_acc,
                                   jax.tree.map(jnp.asarray, dict(metrics)))
        return (model_state, g_acc, loss_acc + loss, metrics_acc), None

    zeros_like_f32 = vary(jax.tree.map(
        lambda p: jnp.zeros(p.shape, jnp.float32), state.params))
    mb0 = jax.tree.map(lambda a: a[0], micro)
    _, (_, metrics0) = jax.eval_shape(
        lambda: loss_fn(state.params, state.model_state, mb0, step_rng))
    metrics_zero = vary(jax.tree.map(
        lambda s: jnp.zeros(s.shape, s.dtype), dict(metrics0)))
    (model_state, grads, loss, metrics), _ = lax.scan(
        one_micro,
        (vary(state.model_state), zeros_like_f32,
         vary(jnp.zeros((), jnp.float32)), metrics_zero),
        (micro, jnp.arange(accum_steps)))
    grads = jax.tree.map(lambda g: g / accum_steps, grads)
    loss = loss / accum_steps
    metrics = jax.tree.map(lambda m: m / accum_steps, metrics)

    return _reduce_and_apply(tx, axes, fusion_threshold, grad_reduce,
                             weight_update, wire_format, hier,
                             wire_format_dcn, state,
                             grads, loss, metrics, model_state,
                             reduce_grads=True)


def make_train_step(
    loss_fn: LossFn,
    tx: optax.GradientTransformation,
    mesh: Mesh | None = None,
    *,
    mode: str = "shard_map",
    donate: bool = True,
    batch_partition: P | None = None,
    reduce_axes: tuple[str, ...] | None = None,
    state_shardings: PyTree | None = None,
    fusion_threshold: int | None = None,
    accum_steps: int = 1,
    grad_reduce: str = "mean",
    compiler_options: dict | None = None,
    remat_policy: str | None = None,
    weight_update: str = "replicated",
    wire_format: str = "fp",
    hier: str = "flat",
    wire_format_dcn: str = "fp",
):
    """Build the compiled train step.

    ``grad_reduce``: ``"mean"`` (default — Horovod's averaged allreduce) or
    ``"adasum"`` (adaptive summation, Horovod's ``op=hvd.Adasum``): local
    per-replica gradients are combined with the scale-insensitive ppermute
    butterfly (tpuframe.parallel.collectives.adasum) instead of averaged.
    With adasum, keep ``scale_lr_by_batch`` off — removing the LR-by-size
    rule is the op's purpose.  shard_map mode only; composes with
    ``accum_steps`` (local f32 accumulation, one adasum at the end) but not
    with ``fusion_threshold`` (the butterfly is its own wire pattern).

    ``fusion_threshold``: byte size of the explicit gradient-fusion buffers
    (HOROVOD_FUSION_THRESHOLD parity, tpuframe.parallel.fusion); ``None``
    (default) leaves gradient reduction to the autodiff transpose + XLA's
    combiner.  Only meaningful in ``shard_map`` mode — auto-SPMD programs
    have no explicit collectives to pack.

    ``accum_steps``: gradient accumulation (Horovod's
    ``backward_passes_per_step``): the per-device batch is split into this
    many microbatches scanned sequentially, f32 grad accumulation, one
    optimizer update and one cross-replica reduction per step.  NOTE the
    batching direction differs from Horovod: Horovod aggregates N loader
    batches (effective batch grows Nx); here the configured batch is SPLIT
    (effective batch unchanged, per-pass memory shrinks Nx) — to port a
    Horovod recipe, multiply global_batch by N as well.

    ``batch_partition``/``reduce_axes``: sequence-parallel configs pass
    ``P(('data','fsdp'), 'seq')`` and ``('data','fsdp','seq')`` so batches
    shard along their sequence dim and the loss mean spans the seq axis.
    A non-default ``batch_partition`` applies to every batch leaf, so all
    leaves must share the partitioned ranks.

    ``state_shardings``: a NamedSharding tree over the TrainState (see
    tpuframe.parallel.fsdp) — selects the auto-SPMD ``jit`` mode with
    parameters/optimizer state sharded; XLA inserts the all-gathers and
    reduce-scatters of ZeRO-style training.

    ``mesh=None`` → single-device jit (config 1, SURVEY.md §7 step 1): same
    body, no collectives — the property the reference gets from Horovod's
    size()==1 no-op mode.

    ``remat_policy``: a :mod:`tpuframe.mem` policy name (``none`` /
    ``full`` / ``per_block`` / ``dots`` / ``save_named(...)``) applied to
    ``loss_fn`` before differentiation — selects which forward
    activations are saved for the backward (the §6 HBM-traffic lever).
    ``None``/``"none"`` leaves the loss unwrapped.  Resolution (env >
    tuning DB > default) is the caller's job via ``mem.resolve``.

    ``weight_update``: ``"replicated"`` (default — every chip holds the
    full optimizer state and applies the full update) or ``"zero1"``
    (:mod:`tpuframe.parallel.zero1`, arXiv:2004.13336): the gradient
    all-reduce is replaced by reduce-scatter → 1/n-shard optimizer
    update → tiled all-gather, and the optimizer state lives sharded
    (build it with ``zero1.make_state``; ``TrainState.create``'s
    replicated layout is rejected at trace time).  shard_map mode with a
    mesh only; element-wise optimizers only; composes with
    ``fusion_threshold`` (the sharded update's reduce-scatter/all-gather
    go bucketed — same padded bytes, fewer collectives, issued before
    any shard is consumed) but not with ``adasum`` (an all-gradient wire
    pattern the sharded update replaces) or ``state_shardings``
    (auto-SPMD ZeRO-3 already shards the update).  Resolution (env
    ``TPUFRAME_WEIGHT_UPDATE`` > tuning DB > default) is the caller's job
    via ``zero1.resolve``.

    ``wire_format``: ``"fp"`` (default — gradient-path collectives move
    full-precision payloads) or ``"int8-block"``
    (:mod:`tpuframe.parallel.quantwire`, arXiv:2506.17615): per-replica
    gradients are block-quantized (s8 payload + per-256-element f32
    scales, ~4x fewer wire bytes) before the cross-replica exchange; on
    the zero1 path both the gradient reduce-scatter and the param-delta
    all-gather take the quantized wire.  shard_map mode with a mesh only
    (auto-SPMD inserts its own collectives; ``mesh=None`` has no wire,
    so the format is ignored — the world-of-1 no-op contract); does not
    compose with ``fusion_threshold``/``adasum`` (each is its own wire
    pattern).  Resolution (env ``TPUFRAME_WIRE_FORMAT`` > tuning DB >
    default) is the caller's job via ``quantwire.resolve``.

    ``hier``: ``"flat"`` (default — cross-replica means are single
    collectives whose groups may span slices) or ``"hier"``
    (:mod:`tpuframe.parallel.hier`, arXiv:1909.09756): the gradient mean
    lowers as in-slice reduce-scatter over ICI → cross-slice mean of the
    1/n_inner shard over DCN → in-slice all-gather back, so only
    1/n_inner of the gradient bytes touch the ~32x-slower fabric.  On a
    single-slice mesh the lowering degenerates to flat.  shard_map mode
    with a mesh only; composes with ``accum_steps``, ``weight_update=
    'zero1'`` (the sharded update's scatter/gather go two-stage) and
    ``fusion_threshold`` (bucketed lowerings, fp DCN leg only), but not
    with ``adasum`` (its butterfly is its own wire pattern) or the
    program-wide ``wire_format='int8-block'`` — PERF §20's verdict is
    that int8 loses at ICI speeds; quantize the slow leg instead via
    ``wire_format_dcn``.  Resolution (env ``TPUFRAME_HIER`` > tuning DB
    > default) is the caller's job via ``hier.resolve``.

    ``wire_format_dcn``: wire format of the cross-slice (DCN) leg of the
    two-level lowering — ``"fp"`` (default) or ``"int8-block"`` (the
    quantwire path riding the slow fabric alone, ~4x fewer DCN bytes on
    top of hier's 1/n_inner).  Needs ``hier='hier'``; flat programs have
    a single fabric-blind wire (use ``wire_format``).  Resolution (env
    ``TPUFRAME_WIRE_FORMAT_DCN`` > tuning DB > fp) is the caller's job
    via ``quantwire.resolve_legs``.
    """
    from tpuframe.parallel import hier as hier_lib
    from tpuframe.parallel import quantwire

    wire_format = quantwire.validate_format(wire_format)
    hier = hier_lib.validate_mode(hier)
    wire_format_dcn = quantwire.validate_format(wire_format_dcn)
    if hier == "hier":
        if state_shardings is not None or mode != "shard_map":
            raise ValueError("hier='hier' needs shard_map mode — auto-SPMD "
                             "programs have no explicit collectives to "
                             "restructure")
        if grad_reduce == "adasum":
            raise ValueError("hier='hier' does not compose with adasum — "
                             "the butterfly is its own wire pattern")
        if wire_format != "fp":
            raise ValueError(f"hier='hier' does not compose with the "
                             f"program-wide wire_format={wire_format!r}: "
                             f"int8 on the ICI legs loses (PERF §20) — "
                             f"quantize only the DCN leg via "
                             f"wire_format_dcn")
    if wire_format_dcn != "fp":
        if hier != "hier":
            raise ValueError(f"wire_format_dcn={wire_format_dcn!r} is the "
                             f"DCN leg of the two-level lowering and needs "
                             f"hier='hier'; a flat program has one "
                             f"fabric-blind wire (wire_format)")
        if fusion_threshold is not None:
            raise ValueError(f"wire_format_dcn={wire_format_dcn!r} does not "
                             f"compose with fusion_threshold — the fusion "
                             f"buffers pack full-precision payloads")
    if wire_format != "fp":
        if state_shardings is not None or mode != "shard_map":
            raise ValueError(f"wire_format={wire_format!r} needs shard_map "
                             f"mode — auto-SPMD programs have no explicit "
                             f"collectives to quantize")
        if grad_reduce == "adasum":
            raise ValueError(f"wire_format={wire_format!r} does not compose "
                             f"with adasum — the butterfly is its own wire "
                             f"pattern")
        if fusion_threshold is not None:
            raise ValueError(f"wire_format={wire_format!r} does not compose "
                             f"with fusion_threshold — the fusion buffers "
                             f"pack full-precision payloads")
    weight_update = (weight_update or "replicated").strip().lower()
    if weight_update not in ("replicated", "zero1"):
        raise ValueError(f"unknown weight_update {weight_update!r}; "
                         f"expected 'replicated' or 'zero1'")
    if weight_update == "zero1":
        if mesh is None:
            raise ValueError("weight_update='zero1' needs a mesh — a world "
                             "of 1 has nothing to shard the update over")
        if state_shardings is not None:
            raise ValueError("weight_update='zero1' is the shard_map DP "
                             "path; state_shardings (auto-SPMD ZeRO-3) "
                             "already shards the update")
        if grad_reduce == "adasum":
            raise ValueError("weight_update='zero1' does not compose with "
                             "adasum — the butterfly needs full gradients "
                             "on every replica")
        if mode != "shard_map":
            raise ValueError("weight_update='zero1' needs shard_map mode")
    if remat_policy:
        from tpuframe.mem import policy as mem_policy

        loss_fn = mem_policy.wrap(loss_fn, remat_policy)
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
    if grad_reduce not in ("mean", "adasum"):
        raise ValueError(f"grad_reduce must be 'mean' or 'adasum', "
                         f"got {grad_reduce!r}")
    if grad_reduce == "adasum" and fusion_threshold is not None:
        raise ValueError("grad_reduce='adasum' does not compose with "
                         "fusion_threshold — the butterfly is its own wire "
                         "pattern")
    if mesh is None:
        # World of 1: adasum degrades to identity like every collective,
        # and there is no wire (or fabric split) for a format to shrink.
        body = functools.partial(_grad_step, loss_fn, tx, None, None,
                                 accum_steps, "mean", "replicated", "fp",
                                 "flat", "fp")
        return jax.jit(body, donate_argnums=(0,) if donate else (),
                       compiler_options=compiler_options)

    # Reduce over every batch-like axis, including size-1 ones: a size-1 pmean
    # is free after compilation but tells shard_map's replication checker the
    # outputs are single-valued across those axes.  Sequence-parallel configs
    # extend both: the batch is additionally sharded along its seq dim and the
    # loss mean spans the seq axis too.
    axes = reduce_axes if reduce_axes is not None else mesh_lib.batch_axes(mesh)
    repl = NamedSharding(mesh, P())
    batch_part = (batch_partition if batch_partition is not None
                  else mesh_lib.batch_spec(mesh=mesh))
    batch_sh = NamedSharding(mesh, batch_part)

    if state_shardings is not None:
        mode = "jit"  # sharded state is an auto-SPMD placement decision
        # All shardings must live on one mesh; the fsdp tree is built on an
        # Auto-typed twin (see tpuframe.parallel.fsdp.auto_mesh).
        any_leaf = jax.tree.leaves(state_shardings)[0]
        repl = NamedSharding(any_leaf.mesh, P())
        batch_sh = NamedSharding(any_leaf.mesh, batch_part)
    if mode == "jit":
        if grad_reduce != "mean":
            raise ValueError("grad_reduce='adasum' needs shard_map mode — "
                             "auto-SPMD has no per-replica grads to combine")
        # Auto-SPMD: annotate shardings, let the partitioner insert collectives.
        body = functools.partial(_grad_step, loss_fn, tx, None, None,
                                 accum_steps, "mean", "replicated", "fp",
                                 "flat", "fp")
        state_sh = repl if state_shardings is None else state_shardings
        return jax.jit(
            body,
            in_shardings=(state_sh, batch_sh),
            out_shardings=(state_sh, repl),
            donate_argnums=(0,) if donate else (),
            compiler_options=compiler_options,
        )

    if mode != "shard_map":
        raise ValueError(f"unknown step mode {mode!r}")

    body = functools.partial(_grad_step, loss_fn, tx, axes, fusion_threshold,
                             accum_steps, grad_reduce, weight_update,
                             wire_format, hier, wire_format_dcn)
    if weight_update == "zero1":
        from tpuframe.parallel import zero1 as zero1_lib

        n_shards = zero1_lib.world_size(mesh, axes)

        def zero1_stepper(state, batch):
            # The opt_state tree shape is the optimizer's business
            # (tx.init), only known from the traced state — so the
            # per-leaf spec tree (moment vectors sharded on dim 0,
            # everything else replicated) is built here inside the jit
            # trace.  shard_map composes under jit, and ``.lower()``
            # still works for the AOT sweeps/audits.
            zero1_lib.check_state_layout(state, n_shards)
            specs = zero1_lib.state_partition_specs(state, axes)
            mapped = _shard_map(body, mesh=mesh,
                                in_specs=(specs, batch_part),
                                out_specs=(specs, P()))
            return mapped(state, batch)

        return jax.jit(zero1_stepper,
                       donate_argnums=(0,) if donate else (),
                       compiler_options=compiler_options)
    mapped = _shard_map(
        body, mesh=mesh,
        in_specs=(P(), batch_part),
        out_specs=(P(), P()),
    )
    return jax.jit(mapped, donate_argnums=(0,) if donate else (),
                   compiler_options=compiler_options)


def make_eval_step(
    metric_fn: Callable[[PyTree, PyTree, PyTree], dict],
    mesh: Mesh | None = None,
    *,
    batch_partition: P | None = None,
    reduce_axes: tuple[str, ...] | None = None,
    state_shardings: PyTree | None = None,
):
    """Forward-only step with cross-replica metric averaging.

    Reference parity: eval loop + one small ``hvd.allreduce`` per metric
    (SURVEY.md §4.5).  ``metric_fn(params, model_state, batch) -> dict`` must
    return *mean-able* values (sums should be divided locally; weights equal).
    """
    if mesh is None:
        return jax.jit(lambda s, b: metric_fn(s.params, s.model_state, b))

    axes = reduce_axes if reduce_axes is not None else mesh_lib.batch_axes(mesh)
    batch_part = (batch_partition if batch_partition is not None
                  else mesh_lib.batch_spec(mesh=mesh))

    if state_shardings is not None:
        # Auto-SPMD eval against fsdp-sharded state (shard_map would demand a
        # replicated state); means over the sharded batch become global
        # reductions via sharding propagation.
        amesh = jax.tree.leaves(state_shardings)[0].mesh
        return jax.jit(
            lambda s, b: metric_fn(s.params, s.model_state, b),
            in_shardings=(state_shardings, NamedSharding(amesh, batch_part)),
            out_shardings=NamedSharding(amesh, P()),
        )

    def body(state: TrainState, batch: PyTree) -> dict:
        metrics = metric_fn(state.params, state.model_state, batch)
        return jax.tree.map(lambda m: lax.pmean(m, axes), metrics)

    mapped = _shard_map(
        body, mesh=mesh,
        in_specs=(P(), batch_part),
        out_specs=P(),
    )
    return jax.jit(mapped)


def replicate_state(state: TrainState, mesh: Mesh) -> TrainState:
    """Place state replicated on the mesh (reference parity with the rank-0
    ``broadcast_parameters`` at startup, SURVEY.md §4.1 — under SPMD this is a
    device_put with a replicated sharding, no network broadcast needed)."""
    repl = mesh_lib.replicated_sharding(mesh)
    return jax.tree.map(lambda t: mesh_lib.host_device_put(t, repl), state)
