"""Block-quantized int8 wire formats for the gradient-path collectives.

*EQuARX: Efficient Quantized AllReduce in XLA* (arXiv:2506.17615,
PAPERS.md) shows a block-quantized int8 all-reduce cuts wire bytes ~4x
with bounded accuracy cost — the lineage optimization for this repo's
Horovod-parity DP strategies.  XLA owns the ring's internals, so
EQuARX's per-hop requantization is not reachable from program level;
the reachable sound formulation decomposes the all-reduce into the two
phases whose payload dtype IS program-visible:

  reduce-scatter(mean)  →  all-to-all of (s8 payload, f32 block scales)
                           + local dequantize/sum/divide
  all-gather            →  all_gather_invariant of (s8 payload, scales)
                           + local dequantize
  all-reduce(mean)      =  the two composed

Quantization is symmetric per-block (``DEFAULT_BLOCK`` elements share
one f32 max-abs/127 scale, ~1.6% scale overhead at 256), accumulation
is f32 and local, so there is no integer-overflow ceiling on the world
size — the s8 payload only ever crosses the wire, never a psum.  Error
per element is one quantization step per phase: |err| <= blockmax/254
for each of the scatter and gather stages (pinned by tests).

This module is a *wire format*, not a call-site choice: ``make_train_step``
and the ZeRO-1 seam resolve the wire per strategy via :func:`resolve`
(env ``TPUFRAME_WIRE_FORMAT`` > generation-gated tune DB > full
precision) and emit the decision as a typed ``wire_format`` obs event.
The format is registered with ``shardflow.register_wire_format`` so the
f32-under-bf16 wire detector knows s8 payloads are intentional, and a
TF115 lint rule keeps raw ``lax.p*`` collectives in ``parallel/step.py``
/ ``parallel/zero1.py`` from bypassing this seam.
"""

from __future__ import annotations

import os
from typing import Any, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from tpuframe.parallel import collectives

AxisName = str | Sequence[str]
PyTree = Any

FORMATS = ("fp", "int8-block")
ENV_VAR = "TPUFRAME_WIRE_FORMAT"
#: the DCN leg of the two-level lowering (tpuframe.parallel.hier) gets
#: its own wire — the fabric is ~32x slower, so PERF §20's "int8 loses
#: at ICI speeds" verdict inverts there.
ENV_VAR_DCN = "TPUFRAME_WIRE_FORMAT_DCN"

# Elements per shared f32 scale: 4/256 = 1.6% wire overhead, small
# enough that the budget ratio tests treat it as the documented slack.
DEFAULT_BLOCK = 256
# Leaves smaller than this stay full precision: a 4x cut on a sub-KiB
# bias is noise on the wire but doubles its collective count (payload +
# scales), and the derived-budget floors are sized to ignore fp strays.
MIN_QUANT_ELEMS = 1024
_QMAX = 127.0

# Pre-vma jax (< 0.6, legacy shard_map with check_rep=False) tracks no
# replication state: every leaf inside the map is local, so treat all
# bound axes as varying and skip the pcast/clear bookkeeping entirely.
_HAS_VMA = hasattr(jax, "typeof") and hasattr(lax, "pcast")


# ---------------------------------------------------------------------------
# Format selection: env > tuning DB > default (zero1.resolve's chain).
# ---------------------------------------------------------------------------


def validate_format(fmt: str) -> str:
    fmt = (fmt or "fp").strip().lower()
    if fmt not in FORMATS:
        raise ValueError(f"unknown wire format {fmt!r}; "
                         f"expected one of {FORMATS} ({ENV_VAR})")
    return fmt


def format_from_env(env=os.environ) -> str | None:
    """The explicit ``TPUFRAME_WIRE_FORMAT`` override, or None."""
    raw = env.get(ENV_VAR, "").strip()
    return validate_format(raw) if raw else None


def format_from_env_dcn(env=os.environ) -> str | None:
    """The explicit ``TPUFRAME_WIRE_FORMAT_DCN`` override, or None."""
    raw = env.get(ENV_VAR_DCN, "").strip()
    return validate_format(raw) if raw else None


def resolve_legs(program: str | None = None, family: str | None = None,
                 family_dcn: str | None = None,
                 default: str = "fp", default_dcn: str = "fp",
                 ) -> tuple[tuple, tuple]:
    """Per-fabric wire resolution: ``((ici_format, ici_source),
    (dcn_format, dcn_source))`` for a step program.

    Each leg resolves independently with the standard precedence — env
    override (``TPUFRAME_WIRE_FORMAT`` / ``TPUFRAME_WIRE_FORMAT_DCN``) >
    generation-gated tuning-DB winner (family ``wire_format_*`` from
    ``tune sweep --wire`` for ICI; family ``hier_collectives`` from
    ``tune sweep --hier`` for DCN) > default.  The ICI leg is the wire
    every gradient-path collective takes on a flat program; the DCN leg
    only exists under the two-level lowering
    (:mod:`tpuframe.parallel.hier`), where it rides the cross-slice
    exchange alone.  Both legs + sources are emitted in the typed
    ``wire_format`` run event."""
    env_val = format_from_env()
    if env_val is not None:
        ici = (env_val, "env")
    else:
        ici = None
        if program or family:
            from tpuframe.tune import db as tune_db

            db_val = tune_db.resolve_wire_format(program or "",
                                                 family=family)
            if db_val is not None:
                try:
                    ici = (validate_format(str(db_val)), "tune_db")
                except ValueError:
                    pass  # a stale DB row must never break a run
        if ici is None:
            ici = (validate_format(default), "default")
    env_dcn = format_from_env_dcn()
    if env_dcn is not None:
        dcn = (env_dcn, "env")
    else:
        dcn = None
        if program or family_dcn:
            from tpuframe.tune import db as tune_db

            db_val = tune_db.resolve_wire_format_dcn(program or "",
                                                     family=family_dcn)
            if db_val is not None:
                try:
                    dcn = (validate_format(str(db_val)), "tune_db")
                except ValueError:
                    pass  # a stale DB row must never break a run
        if dcn is None:
            dcn = (validate_format(default_dcn), "default")
    return ici, dcn


_WARNED_SINGLE_RESOLVE = False


def resolve(program: str | None = None, family: str | None = None,
            default: str = "fp") -> tuple:
    """Deprecated single-format spelling of :func:`resolve_legs` — the
    wire is per-fabric now; this returns the ICI leg only (and is blind
    to ``TPUFRAME_WIRE_FORMAT_DCN``).  Warns once per process."""
    global _WARNED_SINGLE_RESOLVE
    if not _WARNED_SINGLE_RESOLVE:
        _WARNED_SINGLE_RESOLVE = True
        import warnings

        warnings.warn(
            "quantwire.resolve() resolves one program-wide wire format; "
            "the wire is per-fabric now — use quantwire.resolve_legs() "
            "for the (ICI, DCN) pair", DeprecationWarning, stacklevel=2)
    return resolve_legs(program, family=family, default=default)[0]


# ---------------------------------------------------------------------------
# Block quantize / dequantize (local, f32 <-> s8 + f32 scales).
# ---------------------------------------------------------------------------


def quantize_blocks(flat: jax.Array, block: int = DEFAULT_BLOCK):
    """Symmetric per-block s8 quantization of a flat f32 array whose size
    is a multiple of ``block``: returns ``(q s8 [m/block, block],
    scales f32 [m/block])`` with ``scale = max|row|/127`` (an all-zero
    block keeps scale 0 and dequantizes to exact zeros)."""
    rows = flat.reshape(-1, block)
    scales = jnp.max(jnp.abs(rows), axis=1) / _QMAX
    safe = jnp.where(scales == 0.0, 1.0, scales)
    q = jnp.clip(jnp.round(rows / safe[:, None]), -_QMAX, _QMAX)
    return q.astype(jnp.int8), scales.astype(jnp.float32)


def dequantize_blocks(q: jax.Array, scales: jax.Array) -> jax.Array:
    """Inverse of :func:`quantize_blocks` (same ``[rows, block]`` shape)."""
    return q.astype(jnp.float32) * scales[..., None]


def _pad_to(flat: jax.Array, multiple: int) -> jax.Array:
    pad = (-flat.size) % multiple
    return jnp.pad(flat, (0, pad)) if pad else flat


def _axis_prod(names: tuple[str, ...]) -> int:
    n = 1
    for a in names:
        n *= lax.axis_size(a)
    return n


def _require_flat(x: jax.Array, who: str) -> None:
    if x.ndim != 1:
        raise ValueError(f"{who} takes a flat 1-D operand (the zero1 "
                         f"pad-to-multiple layout), got shape "
                         f"{tuple(x.shape)}; reshape(-1) first")


# ---------------------------------------------------------------------------
# The three quantized collectives.
# ---------------------------------------------------------------------------


def _rs_mean_flat(flat: jax.Array, axes: tuple[str, ...], n: int,
                  block: int) -> jax.Array:
    """Quantized reduce-scatter(mean) of an f32 ``(n*c,)`` operand over
    ``axes`` (member count ``n``): returns this replica's ``(c,)`` mean
    shard in f32.  Chunk ownership matches ``lax.psum_scatter(tiled=True)``
    — contiguous chunk *i* to linearized member *i* — so zero1's
    dynamic-slice/regather index math is unchanged by the wire swap."""
    c = flat.size // n
    rows = flat.reshape(n, c)
    nb = -(-c // block)
    if nb * block != c:
        rows = jnp.pad(rows, ((0, 0), (0, nb * block - c)))
    q, scales = quantize_blocks(rows.reshape(-1), block)
    q = q.reshape(n, nb, block)
    scales = scales.reshape(n, nb)
    # The exchange: member i keeps row i of every source — each source's
    # scales travel with its payload, so dequantization is per-source.
    q = lax.all_to_all(q, axes, split_axis=0, concat_axis=0, tiled=True)
    scales = lax.all_to_all(scales, axes, split_axis=0, concat_axis=0,
                            tiled=True)
    total = jnp.sum(dequantize_blocks(q, scales), axis=0)  # f32 accumulate
    return total.reshape(-1)[:c] / n


def _gather_flat(shard: jax.Array, axes: tuple[str, ...],
                 block: int) -> jax.Array:
    """Quantized tiled all-gather of an f32 ``(c,)`` shard over ``axes``:
    returns the replication-invariant ``(n*c,)`` full vector in f32
    (per-source block padding stripped after the gather)."""
    c = shard.size
    nb = -(-c // block)
    q, scales = quantize_blocks(_pad_to(shard, block), block)
    gq = collectives.allgather_invariant(q, axes, gather_axis=0)
    gs = collectives.allgather_invariant(scales, axes, gather_axis=0)
    n = gq.shape[0] // nb
    full = dequantize_blocks(gq, gs).reshape(n, nb * block)
    return full[:, :c].reshape(-1)


def reduce_scatter_mean(x: jax.Array, axis: AxisName = "data", *,
                        block: int = DEFAULT_BLOCK) -> jax.Array:
    """Block-quantized twin of ``collectives.reduce_scatter(average=True)``
    on a flat operand: s8 payload + f32 scales over all-to-all, f32
    accumulation locally (no integer psum, so no world-size overflow
    ceiling).  Same divisibility contract and chunk ownership as
    psum_scatter; result dtype matches the input.  Unmapped or world of
    1: the full-precision path (nothing on the wire to shrink)."""
    bound = collectives._bound_axes(axis)
    if not bound:
        return x
    _require_flat(x, "quantwire.reduce_scatter_mean")
    n = _axis_prod(bound)
    if x.size % n:
        raise ValueError(
            f"quantwire.reduce_scatter_mean: size {x.size} is not "
            f"divisible by the {n}-member axis {bound}; pad to a "
            f"multiple of {n} first (zero1's pad-to-multiple layout)")
    if n == 1:
        return collectives.reduce_scatter(x, bound, average=True)
    flat = x.astype(jnp.float32)
    if _HAS_VMA:
        flat = collectives._vary_over(flat, collectives._sized_axes(bound))
    return _rs_mean_flat(flat, bound, n, block).astype(x.dtype)


def all_gather(x: jax.Array, axis: AxisName = "data", *,
               block: int = DEFAULT_BLOCK) -> jax.Array:
    """Block-quantized twin of the tiled invariant all-gather on a flat
    shard: every replica reconstructs the identical (invariant) full
    vector from s8 payloads + scales.  Result dtype matches the input.
    Unmapped or world of 1: plain invariant gather."""
    bound = collectives._bound_axes(axis)
    if not bound:
        return x
    _require_flat(x, "quantwire.all_gather")
    if _axis_prod(bound) == 1:
        return collectives.allgather_invariant(x, bound)
    return _gather_flat(x.astype(jnp.float32), bound, block).astype(x.dtype)


def all_reduce_mean(tree: PyTree, axis: AxisName = "data", *,
                    block: int = DEFAULT_BLOCK,
                    min_elems: int = MIN_QUANT_ELEMS) -> PyTree:
    """Block-quantized cross-replica gradient mean — the ``int8-block``
    wire for the plain-DP grad all-reduce, composed from the scatter and
    gather phases above (each phase moves ~1/4 the f32 bytes).

    Keeps ``average_gradients``' vma contract: varying leaves take the
    quantized reduce, bound-but-unvarying (presummed) leaves are divided
    by their axis size, size-1 axes are cleared so results come back
    invariant over ALL bound axes.  Leaves under ``min_elems`` (and any
    world-of-1 reduction) stay full precision via ``lax.pmean``.  Error
    per element: one quantization step per phase, <= 2·blockmax/254.
    """
    names = collectives._bound_axes(axis)
    if not names:
        return tree

    def _qmean(g):
        vma = jax.typeof(g).vma if _HAS_VMA else frozenset(names)
        varying = tuple(a for a in names if a in vma)
        size_presummed = _axis_prod(tuple(a for a in names if a not in vma))
        if not varying:
            return g / size_presummed if size_presummed > 1 else g
        sized = collectives._sized_axes(varying)
        n = _axis_prod(sized)
        if n == 1 or g.size < max(min_elems, 1):
            out = lax.pmean(g, varying)
        else:
            flat = _pad_to(g.astype(jnp.float32).reshape(-1), n)
            if _HAS_VMA:
                flat = collectives._vary_over(flat, sized)
            shard = _rs_mean_flat(flat, sized, n, block)
            full = _gather_flat(shard, sized, block)
            out = full[:g.size].reshape(g.shape)
            if _HAS_VMA:
                out = collectives._clear_unit_axes(out, names)
        if size_presummed > 1:
            out = out / size_presummed
        return out.astype(g.dtype)

    return jax.tree.map(_qmean, tree)


# ---------------------------------------------------------------------------
# Analysis-gate self-check.
# ---------------------------------------------------------------------------

# Files whose gradient-path collectives must route through this wire
# seam — TF115's scope, self-linted so the gate fails closed if a raw
# lax.psum/all_gather/psum_scatter/ppermute sneaks past the resolved
# format (the dual of zero1's TF110 optimizer-seam self-lint).
_TF115_SELF_LINT = (
    os.path.join("parallel", "step.py"),
    os.path.join("parallel", "zero1.py"),
)


def check() -> list:
    """Self-check for the ``python -m tpuframe.analysis`` CI gate.
    Returns problem strings; [] means healthy."""
    problems: list[str] = []
    # 1. the format registry and env parsing agree
    for f in FORMATS:
        try:
            validate_format(f)
        except Exception as e:  # noqa: BLE001 — report, don't crash CI
            problems.append(f"format {f!r} failed validation: {e}")
    try:
        format_from_env()
    except ValueError as e:
        problems.append(f"{ENV_VAR} is set to an invalid format: {e}")
    # 2. quantize/dequantize round-trip honors the per-block error bound
    x = jnp.linspace(-3.0, 3.0, 2 * DEFAULT_BLOCK, dtype=jnp.float32)
    q, s = quantize_blocks(x, DEFAULT_BLOCK)
    err = float(jnp.max(jnp.abs(dequantize_blocks(q, s).reshape(-1) - x)))
    bound = float(jnp.max(jnp.abs(x))) / (2 * _QMAX) * 1.001
    if err > bound:
        problems.append(f"round-trip error {err:.3e} exceeds the "
                        f"blockmax/254 bound {bound:.3e}")
    # 3. the wire format is declared to the shardflow dtype detector
    from tpuframe.analysis import shardflow

    if "int8-block" not in shardflow.registered_wire_formats():
        problems.append("'int8-block' is not registered with "
                        "shardflow.register_wire_format — an s8 payload "
                        "under a float wire would read as undeclared")
    # 4. TF115 self-lint: gradient-path collectives stay at the seam
    from tpuframe.analysis.source_lint import lint_paths

    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    paths = [os.path.join(pkg_root, p) for p in _TF115_SELF_LINT]
    for f in lint_paths([p for p in paths if os.path.exists(p)]):
        if f.rule == "TF115":
            problems.append(f"self-lint: {f}")
    return problems
