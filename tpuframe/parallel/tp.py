"""Tensor parallelism over the ``model`` mesh axis — Megatron-style, the
XLA way.

Absent from the reference (SURVEY.md §3c: DP only); implemented here because
the mesh reserves the axis and large models need it.  There is no runtime
machinery and no model-code fork: TP is a set of *parameter placement rules*
(path-pattern → PartitionSpec) consumed by the auto-SPMD step — GSPMD then
inserts the activation all-reduces that Megatron wires by hand:

  * attention q/k/v projections: heads dim over ``model`` (column-parallel)
  * attention output projection: heads dim over ``model`` (row-parallel —
    its products are partial sums; GSPMD emits the all-reduce)
  * MLP up: intermediate dim over ``model``; MLP down: the same dim
    (row-parallel)
  * embedding + LM head: hidden/vocab dim over ``model``

Rules compose with FSDP: tpuframe.parallel.fsdp adds the ``fsdp`` axis on
the largest still-unsharded divisible dim of every leaf, so a
``data × fsdp × model`` mesh gives ZeRO-sharded, tensor-parallel training
from placement alone.
"""

from __future__ import annotations

import re

from jax.sharding import PartitionSpec as P

# (path regex, spec). First match wins; paths are "/"-joined flax param
# paths, e.g. "block_3/attn/query/kernel" — optimizer-state leaves carry the
# same tail (".../mu/block_3/attn/query/kernel"), so the rules cover them.
TRANSFORMER_LM_RULES: tuple[tuple[str, P], ...] = (
    (r"attn/(query|key|value)/kernel$", P(None, "model", None)),
    (r"attn/out/kernel$", P("model", None, None)),
    (r"up/kernel$", P(None, "model")),
    (r"down/kernel$", P("model", None)),
    (r"lm_head/kernel$", P(None, "model")),
    (r"embed/embedding$", P(None, "model")),
    # MoE experts: leading expert dim over the expert axis; the expert's
    # intermediate dim additionally over model (TP inside each expert).
    (r"moe/up_experts$", P("expert", None, "model")),
    (r"moe/down_experts$", P("expert", "model", None)),
    (r"moe/router/kernel$", P()),
)

BERT_RULES: tuple[tuple[str, P], ...] = (
    (r"attention/(query|key|value)/kernel$", P(None, "model", None)),
    (r"attention/(query|key|value)/bias$", P("model", None)),
    (r"attention/out/kernel$", P("model", None, None)),
    (r"intermediate/kernel$", P(None, "model")),
    (r"intermediate/bias$", P("model")),
    (r"output/kernel$", P("model", None)),
    (r"embeddings/word/embedding$", P(None, "model")),
)

RULES_BY_MODEL: dict[str, tuple[tuple[str, P], ...]] = {
    "transformer-lm": TRANSFORMER_LM_RULES,
    "bert-base": BERT_RULES,
}


def rules_for_model(name: str) -> tuple[tuple[str, P], ...]:
    if name not in RULES_BY_MODEL:
        raise ValueError(
            f"no tensor-parallel rules for model {name!r}; "
            f"have {sorted(RULES_BY_MODEL)} — add rules to tpuframe.parallel.tp")
    return RULES_BY_MODEL[name]


def match_spec(path: str, shape: tuple[int, ...],
               axis_sizes: dict[str, int] | int,
               rules: tuple[tuple[str, P], ...]) -> P | None:
    """The placement spec for a param path, or None when no rule
    applies or the named mesh axes don't divide the dims (replicate
    rather than crash).  ``axis_sizes``: mesh axis → size (an int means
    "every named axis has this size" — legacy TP-only call shape)."""
    for pattern, spec in rules:
        if re.search(pattern, path):
            if len(spec) > len(shape):
                return None
            for dim, entry in zip(shape, spec):
                if entry is None:
                    continue
                axes = entry if isinstance(entry, tuple) else (entry,)
                size = 1
                for ax in axes:
                    size *= (axis_sizes if isinstance(axis_sizes, int)
                             else axis_sizes.get(ax, 1))
                if size > 1 and dim % size != 0:
                    return None
            return spec
    return None
