"""Explicit gradient-fusion buffers — the guaranteed HOROVOD_FUSION_THRESHOLD
mechanism (SURVEY.md §3b, tensor-fusion-buffer row).

Horovod packs many small gradient tensors into one 64–128 MB buffer per
cycle so each NCCL ring pays its latency once (key for the BERT workload's
~200 small tensors, SURVEY.md §1 config 4 [B:10]).  Under XLA the same role
is normally played by the compiler's all-reduce combiner, but that pass is
backend-internal: the GPU pipeline honors the DebugOptions threshold
(tpuframe.parallel.tuning maps the env knob onto it), the CPU pipeline does
not run it at all, and libtpu's combiner is tuned by private flags.  This
module therefore implements the fusion buffer *in the program itself*, where
it is visible, testable and backend-independent:

  grads are flattened leaf-by-leaf in deterministic tree order, greedily
  packed into same-dtype buckets of up to ``threshold_bytes``, each bucket
  concatenated into one 1-D buffer, ONE ``lax.psum`` issued per bucket, and
  the results split/reshaped back.

Two emission orders share that bucketing:

:func:`fused_psum` — the synchronous reference: pack → reduce → unpack one
  bucket at a time, in tree order.  Simple, and the identity the staged
  pass is tested against.

:func:`staged_psum` — the overlapped pass (the ``declared_overlapped``
  contract signer).  Every bucket's reduction is ISSUED before any bucket
  is consumed, and an ``optimization_barrier`` chain pins the program
  order so bucket k+1's packing + reduction sit between bucket k's
  reduction and its unpack.  On a backend that lowers collectives to
  async ``all-reduce-start``/``-done`` pairs, each completion window
  therefore contains the later buckets' collectives and packing compute
  — real windows for ``collective_graph.pair_async`` to see.  jax exposes
  no portable async psum form (probed via ``_HAS_ASYNC_PSUM``; no current
  release has one), so the start/done *split itself* is delegated to the
  backend scheduler: CPU XLA emits every all-reduce synchronous (PERF
  §21/§26 record this honestly), while async-capable pipelines get a
  program whose windows are provably non-empty.

``threshold_bytes <= 0`` disables packing (one collective per leaf — the
HOROVOD_FUSION_THRESHOLD=0 semantics).  The compiled-HLO effect is directly
assertable: the all-reduce op count drops from n_leaves to n_buckets
(tests/test_fusion.py).  Semantics are unchanged — psum is linear, so
psum(concat(gs)) == concat(psum(g) for g in gs) — which the golden-loss test
asserts against the implicit pmean-of-loss path.

The bucket-size knob resolves through the standard chain
(:func:`resolve`, mirroring ``zero1.resolve``/``quantwire.resolve``):
``TPUFRAME_FUSION_THRESHOLD`` env > generation-gated ``tune_db.json``
winner (family ``fusion_threshold``, persisted by
``python -m tpuframe.tune sweep --fusion``) > default (off).
"""

from __future__ import annotations

import os
from typing import Any, Sequence

import jax
import jax.numpy as jnp
from jax import lax

PyTree = Any

ENV_VAR = "TPUFRAME_FUSION_THRESHOLD"

#: Bucket size the fused registry strategies pin (128 KiB): large enough
#: that the tiny audit models pack several leaves per bucket, small enough
#: that they emit MULTIPLE buckets — so every completion window has later
#: buckets' work legally interleavable (the nonzero-interior-window
#: property the schedule records pin).  Production thresholds come from
#: the sweep; Horovod's default is 64 MiB.
REGISTRY_THRESHOLD = 128 * 1024

# jax >= 0.6 vma machinery (PR 7 compat shim idiom): ``jax.typeof`` carries
# the varying-manual-axes set concat compatibility must respect.  The floor
# jax (0.4.37) has neither typeof nor pcast — bucketing keys on dtype alone
# there (legacy shard_map's check_rep=False tracks no vma anyway).
_HAS_VMA = hasattr(jax, "typeof") and hasattr(lax, "pcast")

# No jax release exposes an async psum (start/done split at the lax level);
# probed so the staged pass picks it up the release it appears instead of
# silently staying synchronous.
_HAS_ASYNC_PSUM = hasattr(lax, "psum_start") and hasattr(lax, "psum_done")

_HAS_BARRIER = hasattr(lax, "optimization_barrier")


def _leaf_kind(leaf) -> tuple:
    """Bucket compatibility key: dtype + vma (concat needs both to match)."""
    if _HAS_VMA:
        ty = jax.typeof(leaf)
        return (ty.dtype, tuple(sorted(getattr(ty, "vma", ()))))
    return (jnp.dtype(leaf.dtype), ())


def _bucketize(leaves: Sequence[jax.Array],
               threshold_bytes: int) -> list[list[int]]:
    """Greedy same-kind packing in leaf order; returns index buckets."""
    buckets: list[list[int]] = []
    cur: list[int] = []
    cur_bytes = 0
    cur_kind = None
    for i, leaf in enumerate(leaves):
        nbytes = leaf.size * leaf.dtype.itemsize
        if cur and (_leaf_kind(leaf) != cur_kind
                    or cur_bytes + nbytes > threshold_bytes):
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nbytes
        cur_kind = _leaf_kind(leaf)
    if cur:
        buckets.append(cur)
    return buckets


def bucket_census(leaves: Sequence, threshold_bytes: int) -> dict:
    """Deterministic bucketing accounting for a leaf list: per-bucket
    {leaves, bytes, kind} rows + totals.  Pure shape math (works on
    ShapeDtypeStructs) — what the sweep report and the self-check's
    arithmetic leg both consume, so the numbers in
    ``fusion_report_v5e_22.json`` are reproducible from shapes alone."""
    if threshold_bytes <= 0:
        buckets = [[i] for i in range(len(leaves))]
    else:
        buckets = _bucketize(leaves, threshold_bytes)
    rows = []
    for b in buckets:
        rows.append({
            "leaves": len(b),
            "bytes": int(sum(leaves[i].size * leaves[i].dtype.itemsize
                             for i in b)),
            "dtype": str(jnp.dtype(leaves[b[0]].dtype)),
        })
    return {
        "threshold_bytes": int(threshold_bytes),
        "n_leaves": len(leaves),
        "n_buckets": len(rows),
        "buckets": rows,
        "total_bytes": int(sum(r["bytes"] for r in rows)),
    }


def fused_psum(tree: PyTree, axes, *, threshold_bytes: int,
               mean: bool = False) -> PyTree:
    """Cross-replica sum (or mean) of every leaf with Horovod-style fusion.

    ``axes``: mesh axis name or tuple of names (as for ``lax.psum``); must be
    bound (inside ``shard_map``).  Leaves are packed into ≤``threshold_bytes``
    same-dtype buffers, one collective per buffer.  ``threshold_bytes <= 0``
    → one collective per leaf.  Synchronous emission order (pack → reduce →
    unpack per bucket) — the reference :func:`staged_psum` must match.
    """
    leaves, treedef = jax.tree.flatten(tree)
    if not leaves:
        return tree
    denom = _mean_denom(axes) if mean else 1

    if threshold_bytes <= 0:
        out = [lax.psum(l, axes) for l in leaves]
    else:
        out = [None] * len(leaves)
        for bucket in _bucketize(leaves, threshold_bytes):
            if len(bucket) == 1:
                i = bucket[0]
                out[i] = lax.psum(leaves[i], axes)
                continue
            flat = jnp.concatenate([leaves[i].reshape(-1) for i in bucket])
            flat = lax.psum(flat, axes)
            off = 0
            for i in bucket:
                n = leaves[i].size
                out[i] = flat[off:off + n].reshape(leaves[i].shape)
                off += n
    if mean:
        out = [o / denom for o in out]
    return jax.tree.unflatten(treedef, out)


def fused_pmean(tree: PyTree, axes, *, threshold_bytes: int) -> PyTree:
    return fused_psum(tree, axes, threshold_bytes=threshold_bytes, mean=True)


def _mean_denom(axes) -> int:
    denom = 1
    for a in ((axes,) if isinstance(axes, str) else tuple(axes)):
        denom *= lax.axis_size(a)
    return denom


def staged_psum(tree: PyTree, axes, *, threshold_bytes: int,
                mean: bool = False) -> PyTree:
    """Overlapped bucketed reduction — same buckets and same math as
    :func:`fused_psum`, pipelined emission order.

    Issue stage: every bucket is packed and its reduction issued in tree
    order, nothing consumed.  Consume stage: bucket k is unpacked only
    after bucket k+1's reduction exists, pinned by an
    ``optimization_barrier`` chain (an op ``collective_graph`` chases
    through, so async pairing survives it).  On an async-capable backend
    each all-reduce's start→done window therefore contains the later
    buckets' packing + collectives; on sync-only CPU XLA the program is
    byte-identical traffic in a fixed order (PERF §26's measured caveat).
    """
    leaves, treedef = jax.tree.flatten(tree)
    if not leaves:
        return tree
    denom = _mean_denom(axes) if mean else 1
    if threshold_bytes <= 0:
        buckets = [[i] for i in range(len(leaves))]
    else:
        buckets = _bucketize(leaves, threshold_bytes)

    # Issue: pack + reduce every bucket before any unpack.  (When a lax
    # async psum form exists this is where the starts go; see
    # _HAS_ASYNC_PSUM above.)
    reduced = []
    for bucket in buckets:
        if len(bucket) == 1:
            flat = leaves[bucket[0]].reshape(-1)
        else:
            flat = jnp.concatenate([leaves[i].reshape(-1) for i in bucket])
        reduced.append(lax.psum(flat, axes))

    # Consume: unpack bucket k strictly after bucket k+1's reduction.
    out = [None] * len(leaves)
    for b, bucket in enumerate(buckets):
        flat = reduced[b]
        if _HAS_BARRIER and b + 1 < len(buckets):
            flat, reduced[b + 1] = lax.optimization_barrier(
                (flat, reduced[b + 1]))
        if mean:
            flat = flat / denom
        off = 0
        for i in bucket:
            n = leaves[i].size
            out[i] = flat[off:off + n].reshape(leaves[i].shape)
            off += n
    return jax.tree.unflatten(treedef, out)


def staged_pmean(tree: PyTree, axes, *, threshold_bytes: int) -> PyTree:
    return staged_psum(tree, axes, threshold_bytes=threshold_bytes, mean=True)


# ---------------------------------------------------------------------------
# Shard-aligned packing for the zero1 (reduce-scatter/all-gather) seam.
# ---------------------------------------------------------------------------


def pack_for_scatter(flats: Sequence[jax.Array], n: int) -> jax.Array:
    """Pack already-padded flat leaves (each length a multiple of ``n``)
    so a reduce-scatter of the result hands every member the
    concatenation of its OWN per-leaf shards.

    A naive concat would give member k one contiguous [total/n] chunk
    that straddles leaf boundaries; reshaping each leaf to (n, len/n)
    and concatenating along axis 1 makes row k exactly concat(leaf
    shards k) — the layout zero1's per-leaf [padded/n] opt state needs.
    """
    return jnp.concatenate([f.reshape(n, -1) for f in flats],
                           axis=1).reshape(-1)


def split_scattered(shard: jax.Array,
                    chunk_sizes: Sequence[int]) -> list[jax.Array]:
    """Undo :func:`pack_for_scatter` on the scattered side: member k's
    [total/n] shard back into per-leaf [padded/n] shards."""
    out, off = [], 0
    for c in chunk_sizes:
        out.append(lax.dynamic_slice(shard, (off,), (int(c),)))
        off += int(c)
    return out


def split_gathered(full: jax.Array, n: int,
                   chunk_sizes: Sequence[int]) -> list[jax.Array]:
    """Undo :func:`pack_for_scatter` after an all-gather of the packed
    shards: the full [total] vector back into per-leaf [padded] flats."""
    rows = full.reshape(n, -1)
    out, off = [], 0
    for c in chunk_sizes:
        out.append(lax.dynamic_slice_in_dim(
            rows, off, int(c), axis=1).reshape(-1))
        off += int(c)
    return out


# ---------------------------------------------------------------------------
# Resolution chain: env > generation-gated tune DB > default.
# ---------------------------------------------------------------------------


def validate_threshold(raw) -> int:
    """Parse/validate a threshold value.  Any int is legal (<= 0 means
    packing off, per the HOROVOD_FUSION_THRESHOLD=0 convention)."""
    try:
        return int(raw)
    except (TypeError, ValueError) as e:
        raise ValueError(
            f"invalid fusion threshold {raw!r}; expected an integer byte "
            f"count ({ENV_VAR})") from e


def threshold_from_env(env=os.environ) -> int | None:
    """The explicit ``TPUFRAME_FUSION_THRESHOLD`` override, or None."""
    raw = env.get(ENV_VAR, "").strip()
    return validate_threshold(raw) if raw else None


def resolve(program: str | None = None, family: str | None = None,
            default: int | None = None) -> tuple:
    """``(threshold_bytes | None, source)`` for a step program: env
    override > tuning-DB winner (generation-gated; family
    ``fusion_threshold`` persisted by ``tune sweep --fusion``) >
    ``default``.  ``source`` is ``env``/``tune_db``/``default`` — emitted
    in the ``fusion_threshold`` run event so knob provenance is always on
    record.  None means fusion off (gradient reduction stays with the
    autodiff transpose + XLA combiner)."""
    env_val = threshold_from_env()
    if env_val is not None:
        return env_val, "env"
    if program or family:
        from tpuframe.tune import db as tune_db

        db_val = tune_db.resolve_fusion_threshold(program or "",
                                                  family=family)
        if db_val is not None:
            try:
                return validate_threshold(db_val), "tune_db"
            except ValueError:
                pass  # a stale DB row must never break a run
    return default, "default"


# ---------------------------------------------------------------------------
# Analysis-gate self-check.
# ---------------------------------------------------------------------------

# A minimal scheduled module shaped like a DEGENERATE fused strategy: two
# async bucket all-reduces, each consumed back-to-back (zero ops inside
# both start->done windows) even though each bucket's window could legally
# hold the other's work.  A strategy that declares its collectives
# overlapped MUST fail detect_exposed_comm on this program — the live
# gate's own positive, proving it is not blind to a fusion pass that
# issues windows and then wastes them.
_SEEDED_ZERO_OVERLAP_HLO = """\
HloModule seeded_fused_zero_overlap, is_scheduled=true

%add (a: f32[], b: f32[]) -> f32[] {
  %a = f32[] parameter(0)
  %b = f32[] parameter(1)
  ROOT %r = f32[] add(f32[] %a, f32[] %b)
}

ENTRY %main (p0: f32[32768], p1: f32[32768]) -> (f32[32768], f32[32768]) {
  %p0 = f32[32768]{0} parameter(0)
  %p1 = f32[32768]{0} parameter(1)
  %b0s = f32[32768]{0} all-reduce-start(f32[32768]{0} %p0), replica_groups={}, to_apply=%add
  %b0d = f32[32768]{0} all-reduce-done(f32[32768]{0} %b0s)
  %b1s = f32[32768]{0} all-reduce-start(f32[32768]{0} %p1), replica_groups={}, to_apply=%add
  %b1d = f32[32768]{0} all-reduce-done(f32[32768]{0} %b1s)
  ROOT %out = (f32[32768]{0}, f32[32768]{0}) tuple(%b0d, %b1d)
}
"""


def seeded_overlap_positive() -> list[str]:
    """jax-free positive: the seeded all-exposed fused program must FAIL
    the exposed-comm gate under a declared-overlapped strategy and stay
    report-only under an undeclared one."""
    from tpuframe.analysis import collective_graph as cg
    from tpuframe.analysis import shardflow

    problems: list[str] = []
    graph = cg.parse_graph(_SEEDED_ZERO_OVERLAP_HLO)
    found = shardflow.detect_exposed_comm(graph, True)
    if len(found) != 2 or any("back-to-back" not in f for f in found):
        problems.append(
            f"seeded fused zero-overlap positive: expected 2 zero-window "
            f"findings (both buckets consumed back-to-back) under a "
            f"declared-overlapped strategy, got {found!r} — the live gate "
            f"is blind")
    if shardflow.detect_exposed_comm(graph, False):
        problems.append(
            "seeded fused zero-overlap positive: an UNdeclared strategy "
            "must not fail on exposure (report-only contract broken)")
    return problems


def _census_problems() -> list[str]:
    """Bucket-census arithmetic over a synthetic mixed-dtype leaf list —
    pure shape math, no jax trace."""
    import numpy as np

    problems: list[str] = []
    leaves = [np.zeros((n,), dt) for n, dt in
              ((100, np.float32), (100, np.float32), (7, np.float32),
               (64, np.int8), (300, np.float32), (1, np.float32))]
    threshold = 512
    buckets = _bucketize(leaves, threshold)
    flat = [i for b in buckets for i in b]
    if flat != list(range(len(leaves))):
        problems.append(
            f"bucketize broke tree order: {buckets!r} is not an ordered "
            f"partition of {len(leaves)} leaves")
    for b in buckets:
        kinds = {_leaf_kind(leaves[i]) for i in b}
        if len(kinds) != 1:
            problems.append(f"bucket {b!r} mixes leaf kinds {kinds!r}")
        nbytes = sum(leaves[i].size * leaves[i].dtype.itemsize for i in b)
        if len(b) > 1 and nbytes > threshold:
            problems.append(
                f"bucket {b!r} holds {nbytes} B > threshold {threshold}")
    census = bucket_census(leaves, threshold)
    if census["n_buckets"] != len(buckets):
        problems.append("bucket_census disagrees with _bucketize on count")
    if census["total_bytes"] != sum(
            l.size * l.dtype.itemsize for l in leaves):
        problems.append("bucket_census lost bytes")
    if bucket_census(leaves, 0)["n_buckets"] != len(leaves):
        problems.append("threshold<=0 must census one bucket per leaf")
    return problems


def check_static() -> list[str]:
    """The jax-free legs of :func:`check` — safe for ``--selfcheck``:
    env parsing, bucket-census arithmetic, and the seeded zero-overlap
    positive that proves the declared_overlapped gate has teeth."""
    problems: list[str] = []
    try:
        threshold_from_env()
    except ValueError as e:
        problems.append(f"{ENV_VAR} is set to an invalid value: {e}")
    problems.extend(_census_problems())
    problems.extend(seeded_overlap_positive())
    return problems


def check() -> list[str]:
    """Self-check for the ``python -m tpuframe.analysis`` CI gate.
    Returns problem strings; [] means healthy.  Adds the psum-linearity
    identity (fused == staged == per-leaf under a real 8-member
    shard_map) on top of the static legs."""
    import numpy as np

    from tpuframe.parallel import mesh as mesh_lib
    from tpuframe.parallel import step as step_lib

    problems = check_static()
    if len(jax.devices()) < 2:
        problems.append(
            "fusion psum-linearity check needs a multi-device backend "
            "(run under the analysis CLI's forced-device child)")
        return problems
    n = len(jax.devices())
    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec(data=n))
    rng = np.random.default_rng(7)
    tree = {
        "a": jnp.asarray(rng.normal(size=(2, 12)), jnp.float32),
        "b": jnp.asarray(rng.normal(size=(5,)), jnp.float32),
        "c": jnp.asarray(rng.normal(size=(3, 2)), jnp.float32),
    }

    def body(x):
        plain = jax.tree.map(lambda l: lax.psum(l, "data"), x)
        fused = fused_psum(x, "data", threshold_bytes=1 << 20)
        staged = staged_psum(x, "data", threshold_bytes=1 << 20)
        return plain, fused, staged

    from jax.sharding import PartitionSpec as P

    mapped = step_lib._shard_map(body, mesh=mesh, in_specs=P(),
                                 out_specs=P())
    plain, fused, staged = jax.jit(mapped)(tree)
    for k in tree:
        if not np.allclose(np.asarray(plain[k]), np.asarray(fused[k]),
                           rtol=1e-6, atol=1e-6):
            problems.append(
                f"psum linearity broken: fused_psum leaf {k!r} diverged "
                f"from per-leaf psum")
        if not np.allclose(np.asarray(plain[k]), np.asarray(staged[k]),
                           rtol=1e-6, atol=1e-6):
            problems.append(
                f"staged emission changed the math: staged_psum leaf "
                f"{k!r} diverged from per-leaf psum")
    return problems
