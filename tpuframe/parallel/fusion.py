"""Explicit gradient-fusion buffers — the guaranteed HOROVOD_FUSION_THRESHOLD
mechanism (SURVEY.md §3b, tensor-fusion-buffer row).

Horovod packs many small gradient tensors into one 64–128 MB buffer per
cycle so each NCCL ring pays its latency once (key for the BERT workload's
~200 small tensors, SURVEY.md §1 config 4 [B:10]).  Under XLA the same role
is normally played by the compiler's all-reduce combiner, but that pass is
backend-internal: the GPU pipeline honors the DebugOptions threshold
(tpuframe.parallel.tuning maps the env knob onto it), the CPU pipeline does
not run it at all, and libtpu's combiner is tuned by private flags.  This
module therefore implements the fusion buffer *in the program itself*, where
it is visible, testable and backend-independent:

  grads are flattened leaf-by-leaf in deterministic tree order, greedily
  packed into same-dtype buckets of up to ``threshold_bytes``, each bucket
  concatenated into one 1-D buffer, ONE ``lax.psum`` issued per bucket, and
  the results split/reshaped back.

``threshold_bytes <= 0`` disables packing (one collective per leaf — the
HOROVOD_FUSION_THRESHOLD=0 semantics).  The compiled-HLO effect is directly
assertable: the all-reduce op count drops from n_leaves to n_buckets
(tests/test_fusion.py).  Semantics are unchanged — psum is linear, so
psum(concat(gs)) == concat(psum(g) for g in gs) — which the golden-loss test
asserts against the implicit pmean-of-loss path.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp
from jax import lax

PyTree = Any


def _leaf_kind(leaf) -> tuple:
    """Bucket compatibility key: dtype + vma (concat needs both to match)."""
    ty = jax.typeof(leaf)
    return (ty.dtype, tuple(sorted(getattr(ty, "vma", ()))))


def _bucketize(leaves: Sequence[jax.Array],
               threshold_bytes: int) -> list[list[int]]:
    """Greedy same-kind packing in leaf order; returns index buckets."""
    buckets: list[list[int]] = []
    cur: list[int] = []
    cur_bytes = 0
    cur_kind = None
    for i, leaf in enumerate(leaves):
        nbytes = leaf.size * leaf.dtype.itemsize
        if cur and (_leaf_kind(leaf) != cur_kind
                    or cur_bytes + nbytes > threshold_bytes):
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nbytes
        cur_kind = _leaf_kind(leaf)
    if cur:
        buckets.append(cur)
    return buckets


def fused_psum(tree: PyTree, axes, *, threshold_bytes: int,
               mean: bool = False) -> PyTree:
    """Cross-replica sum (or mean) of every leaf with Horovod-style fusion.

    ``axes``: mesh axis name or tuple of names (as for ``lax.psum``); must be
    bound (inside ``shard_map``).  Leaves are packed into ≤``threshold_bytes``
    same-dtype buffers, one collective per buffer.  ``threshold_bytes <= 0``
    → one collective per leaf.
    """
    leaves, treedef = jax.tree.flatten(tree)
    if not leaves:
        return tree
    denom = 1
    if mean:
        ax_tuple = (axes,) if isinstance(axes, str) else tuple(axes)
        for a in ax_tuple:
            denom *= lax.axis_size(a)

    if threshold_bytes <= 0:
        out = [lax.psum(l, axes) for l in leaves]
    else:
        out = [None] * len(leaves)
        for bucket in _bucketize(leaves, threshold_bytes):
            if len(bucket) == 1:
                i = bucket[0]
                out[i] = lax.psum(leaves[i], axes)
                continue
            flat = jnp.concatenate([leaves[i].reshape(-1) for i in bucket])
            flat = lax.psum(flat, axes)
            off = 0
            for i in bucket:
                n = leaves[i].size
                out[i] = flat[off:off + n].reshape(leaves[i].shape)
                off += n
    if mean:
        out = [o / denom for o in out]
    return jax.tree.unflatten(treedef, out)


def fused_pmean(tree: PyTree, axes, *, threshold_bytes: int) -> PyTree:
    return fused_psum(tree, axes, threshold_bytes=threshold_bytes, mean=True)
