"""Pipeline-parallel training step for the ScanBlockLM — the model-level
integration of tpuframe.parallel.pp (GPipe over the ``pipe`` mesh axis).

Layout: the model's layer-stacked ``blocks`` params (and their optimizer
state) shard their leading layer dim over ``pipe`` — S stages each own
``num_layers / S`` contiguous layers — while the embedding/head stay
replicated and are computed on every stage (cheap relative to the blocks;
keeps the SPMD program identical everywhere, and the ``where``-gating in
pipeline_apply routes embed cotangents to stage 0 only).  Data parallelism
composes on the ``data`` axis: the batch shards over it, gradients arrive
data-presummed from the pmean-of-loss transpose.

Constraints (documented, asserted): ``num_layers % pp_stages == 0``; the
optimizer must not couple parameters across leaves with global statistics
using only local values — per-leaf transforms (adam/adamw/sgd) are fine,
and global-norm clipping is provided by ``pp_clip_by_global_norm`` (the
cross-stage psum'd norm; the harness wires it for ``grad_clip_norm``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpuframe.models import losses
from tpuframe.parallel import mesh as mesh_lib, pp
from tpuframe.parallel.step import TrainState, _shard_map


def state_partition(state: TrainState) -> TrainState:
    """PartitionSpec tree over a ScanBlockLM TrainState: every leaf whose
    tree path passes through ``blocks`` shards its leading (layer) dim over
    ``pipe``; everything else is replicated."""

    def spec_for(path, leaf) -> P:
        in_blocks = any(getattr(k, "key", getattr(k, "name", None)) == "blocks"
                        for k in path)
        return P("pipe") if in_blocks else P()

    return jax.tree_util.tree_map_with_path(spec_for, state)


def pp_clip_by_global_norm(max_norm: float) -> optax.GradientTransformation:
    """Global-norm clipping that is correct on a pipe-sharded grad tree.

    ``optax.clip_by_global_norm`` computes the norm from the LOCAL leaf
    values; under the pipeline layout each stage holds only its slice of
    the ``blocks`` leaves, so the local norm is a per-stage statistic and
    the resulting clip scales diverge across stages (the reason the
    harness refused grad_clip_norm with pp).  Here the square-sums of
    pipe-VARYING leaves are psum-ed over the pipe axis (each stage's slice
    counted once), replicated leaves (embed/head) are counted once without
    the psum, and every stage applies the same global scale."""

    def sq_sum(g):
        return jnp.sum(jnp.square(g.astype(jnp.float32)))

    def init_fn(params):
        del params
        return optax.EmptyState()

    def update_fn(grads, state, params=None, **extra):
        del params, extra
        varying = jnp.zeros((), jnp.float32)
        invariant = jnp.zeros((), jnp.float32)
        pipe_bound = any(
            "pipe" in getattr(jax.typeof(g), "vma", frozenset())
            for g in jax.tree.leaves(grads))
        for g in jax.tree.leaves(grads):
            if "pipe" in getattr(jax.typeof(g), "vma", frozenset()):
                varying = varying + sq_sum(g)
            else:
                invariant = invariant + sq_sum(g)
        if pipe_bound:
            # psum of the pipe-varying total is pipe-INVARIANT — it joins
            # the replicated leaves' total directly, keeping the clip
            # scale provably replicated (replicated-leaf updates must not
            # become pipe-varying).
            varying = lax.psum(varying, "pipe")
        norm = jnp.sqrt(varying + invariant)
        scale = jnp.minimum(1.0, max_norm / jnp.maximum(norm, 1e-16))
        return jax.tree.map(lambda g: (g * scale).astype(g.dtype),
                            grads), state

    return optax.GradientTransformation(init_fn, update_fn)


def _head_loss_acc(model, fused_xent: bool, params, x_last, labels):
    """(mean CE loss, token accuracy) from the last pipeline stage's hidden
    states — dense head, or the chunked fused softmax-xent path
    (tpuframe.ops.fused_xent; logits never materialize).  One definition
    shared by the train and eval pipeline steps so the two cannot drift."""
    data_axes = tuple(mesh_lib.BATCH_AXES)
    if fused_xent:
        from tpuframe.ops import fused_xent as fx

        hidden = model.apply({"params": params}, x_last,
                             head_only=True, hidden_only=True)
        return fx.mean_xent_and_accuracy(
            hidden, params["lm_head"]["kernel"], labels, ignore_index=-100,
            reduce_axis=data_axes)
    logits = model.apply({"params": params}, x_last, head_only=True)
    return (losses.softmax_cross_entropy(logits, labels, ignore_index=-100,
                                         reduce_axis=data_axes),
            losses.accuracy(logits, labels, ignore_index=-100,
                            reduce_axis=data_axes))


def make_pp_lm_step(model, tx: optax.GradientTransformation, mesh: Mesh, *,
                    n_micro: int, fused_xent: bool = False,
                    remat_policy: str | None = None):
    """Compiled train step: ScanBlockLM forward through the microbatch
    pipeline, CE loss, one optimizer update.  Returns ``(step_fn,
    place_state, place_batch)`` where the placers put a host-built
    TrainState / batch onto the mesh with the pp shardings.

    ``fused_xent``: compute the head + loss with the chunked fused
    softmax-xent (tpuframe.ops.fused_xent) — the [B,S,V] logits never
    materialize; same loss/gradients as the dense path.

    ``remat_policy``: a :mod:`tpuframe.mem` policy name applied to the
    per-shard loss before differentiation — same registry/seams as
    ``make_train_step`` (the ScanBlockLM names its block seams, so
    ``per_block``/``save_named`` work here too)."""
    n_stages = int(mesh.shape["pipe"])
    num_layers = model.cfg.num_layers
    if num_layers % n_stages:
        raise ValueError(f"num_layers={num_layers} not divisible by "
                         f"pipe={n_stages}")
    if model.cfg.dropout > 0:
        # The pipeline step does not thread dropout rngs through the scan
        # yet; refusing beats silently training unregularized.
        raise ValueError("make_pp_lm_step does not support dropout>0 yet; "
                         "set dropout=0.0 in the LMConfig")
    layers_per_stage = num_layers // n_stages
    data_axes = tuple(a for a in mesh_lib.BATCH_AXES)

    def body(state: TrainState, batch):
        def loss_fn(params):
            x = model.apply({"params": params}, batch["input_ids"],
                            embed_only=True)
            micro = pp.microbatch(x, n_micro)
            stage_fn = lambda blocks, xm: model.apply(  # noqa: E731
                {"params": {"blocks": blocks}}, xm, stage=True,
                stage_layers=layers_per_stage)
            out = pp.pipeline_apply(stage_fn, params["blocks"], micro)
            x_last = pp.last_stage_value(out).reshape(x.shape)
            loss, acc = _head_loss_acc(model, fused_xent, params, x_last,
                                       batch["labels"])
            return lax.pmean(loss, data_axes), acc

        if remat_policy:
            from tpuframe.mem import policy as mem_policy

            loss_fn = mem_policy.wrap(loss_fn, remat_policy)
        (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params)
        # The PP step owns its update: params live stage-sharded here, so
        # the dp-only zero1 seam does not apply (stage shards already split
        # optimizer state pipe-ways).
        updates, opt_state = tx.update(grads, state.opt_state, state.params)  # tf-lint: ok[TF110]
        params = optax.apply_updates(state.params, updates)  # tf-lint: ok[TF110]
        metrics = {"loss": loss, "accuracy": lax.pmean(acc, data_axes)}
        new_state = TrainState(step=state.step + 1, params=params,
                               opt_state=opt_state,
                               model_state=state.model_state, rng=state.rng)
        return new_state, metrics

    spec_tree = None

    def specs(state):
        nonlocal spec_tree
        if spec_tree is None:
            spec_tree = state_partition(state)
        return spec_tree

    def step_fn_factory(state):
        sp = specs(state)
        batch_part = P(mesh_lib.BATCH_AXES)
        mapped = _shard_map(
            body, mesh=mesh,
            in_specs=(sp, {"input_ids": batch_part, "labels": batch_part}),
            out_specs=(sp, P()),
        )
        # Donate the TrainState like make_train_step: pipeline parallelism
        # exists for models near the memory limit, so don't double-buffer
        # params + optimizer state.
        return jax.jit(mapped, donate_argnums=(0,))

    def place_state(state: TrainState) -> TrainState:
        return jax.tree.map(
            lambda t, s: mesh_lib.host_device_put(t, NamedSharding(mesh, s)),
            state, specs(state))

    def place_batch(batch):
        sh = NamedSharding(mesh, P(mesh_lib.BATCH_AXES))
        return jax.tree.map(lambda a: jax.device_put(a, sh), batch)

    return step_fn_factory, place_state, place_batch


def make_pp_lm_eval(model, mesh: Mesh, *, n_micro: int,
                    fused_xent: bool = False):
    """Forward-only pipeline step returning mean-able eval metrics
    (tpuframe.parallel.step.make_eval_step's contract), for the harness's
    evaluate() loop on a pp-sharded state."""
    n_stages = int(mesh.shape["pipe"])
    layers_per_stage = model.cfg.num_layers // n_stages
    data_axes = tuple(mesh_lib.BATCH_AXES)

    def body(state: TrainState, batch):
        params = state.params
        x = model.apply({"params": params}, batch["input_ids"],
                        embed_only=True)
        micro = pp.microbatch(x, n_micro)
        stage_fn = lambda blocks, xm: model.apply(  # noqa: E731
            {"params": {"blocks": blocks}}, xm, stage=True,
            stage_layers=layers_per_stage)
        out = pp.pipeline_apply(stage_fn, params["blocks"], micro)
        x_last = pp.last_stage_value(out).reshape(x.shape)
        loss, acc = _head_loss_acc(model, fused_xent, params, x_last,
                                   batch["labels"])
        metrics = {"loss": loss, "accuracy": acc,
                   "perplexity": jnp.exp(loss)}
        return jax.tree.map(lambda m: lax.pmean(m, data_axes), metrics)

    spec_tree = None

    def eval_fn_factory(state):
        nonlocal spec_tree
        if spec_tree is None:
            spec_tree = state_partition(state)
        batch_part = P(mesh_lib.BATCH_AXES)
        mapped = _shard_map(
            body, mesh=mesh,
            in_specs=(spec_tree,
                      {"input_ids": batch_part, "labels": batch_part}),
            out_specs=P(),
        )
        return jax.jit(mapped)

    return eval_fn_factory
