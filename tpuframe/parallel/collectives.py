"""Collective primitives — XLA replacements for Horovod's op set.

Reference capability (SURVEY.md §3b): Horovod exposes allreduce / allgather /
broadcast / alltoall, executed by a C++ background runtime over NCCL rings
with tensor fusion.  Under XLA SPMD none of that is runtime code: these
helpers trace to ``lax`` collective HLOs inside a compiled program, XLA's
combiner pass does the fusion (see ``tpuframe.parallel.tuning``), and the TPU
ICI torus provides bandwidth-optimal routing in hardware.

Two usage modes, mirroring how the reference uses Horovod:
  - inside a ``shard_map``-ed step function (per-grad allreduce, metric
    averaging) — call these directly with an axis name;
  - at the harness level on host values (eval metric averaging, parameter
    broadcast at init) — use ``cross_replica_mean`` / ``host_broadcast`` which
    jit a tiny collective program over a mesh.

Axis names may be a single name or a tuple (e.g. ``("data", "fsdp")``).
"""

from __future__ import annotations

from typing import Any, Sequence

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpuframe.parallel import mesh as mesh_lib

AxisName = str | Sequence[str]
PyTree = Any


if not hasattr(lax, "axis_size"):
    # jax < 0.4.38 never shipped ``lax.axis_size``.  ``psum`` of the literal
    # ``1`` over an axis is the classic static-size idiom: it folds to a plain
    # ``int`` at trace time and raises the same ``NameError`` on unbound names
    # that the modern API does, so ``_bound_axes``'s probe keeps working.
    # Installed on ``lax`` once so every caller in this package (fusion,
    # seq_parallel, pp, zero1) resolves the same way on legacy jax.
    def _legacy_axis_size(axis_name: AxisName) -> int:
        if isinstance(axis_name, (tuple, list)):
            n = 1
            for a in axis_name:
                n *= _legacy_axis_size(a)
            return n
        return lax.psum(1, axis_name)

    lax.axis_size = _legacy_axis_size


def _bound_axes(axis: AxisName) -> tuple[str, ...]:
    """The subset of ``axis`` names bound by an enclosing shard_map/pmap trace.

    Collectives here reduce over whichever requested axes exist, so the same
    step function runs under a full mesh, a pmap with only ``data`` bound, or
    completely unmapped (single-process config 1) — the laptop-to-pod property
    the reference gets from Horovod's size()==1 no-op mode.
    """
    names = (axis,) if isinstance(axis, str) else tuple(axis)
    bound = []
    for n in names:
        try:
            lax.axis_size(n)
        except NameError:
            continue
        bound.append(n)
    return tuple(bound)


def _in_mapped_context(axis: AxisName) -> bool:
    """True when every name in ``axis`` is bound by an enclosing trace."""
    names = (axis,) if isinstance(axis, str) else tuple(axis)
    return len(_bound_axes(names)) == len(names)


def allreduce(x: PyTree, axis: AxisName = "data", *, average: bool = True) -> PyTree:
    """Sum (or mean) a pytree across the mapped axis.

    Reference parity: ``hvd.allreduce(tensor, average=True)`` (SURVEY.md §3a
    "Distributed glue").  Degrades to identity when the axis is not bound —
    so the same step function runs unmapped in config 1's single-process mode
    (SURVEY.md §7 build order step 1).
    """
    return _elementwise_reduce(x, axis, lax.pmean if average else lax.psum)


def average_gradients(grads: PyTree, axis: AxisName = "data") -> PyTree:
    """Make ``grads`` the cross-replica *average* regardless of how they were
    produced.

    Two arrival states inside a shard_map trace (jax's vma semantics):
      - varying leaves (grad of a per-shard loss w.r.t. ``pvary``-ed params,
        or hand-built values): need an explicit ``pmean``;
      - unvarying leaves (grad w.r.t. replicated params — autodiff's transpose
        of the implicit pbroadcast already inserted the ``psum``): the sum is
        done; divide by the world size.

    This is the exact semantic of Horovod's averaged grad allreduce, which is
    why ``hvd.DistributedOptimizer`` routes through here (SURVEY.md §4.1).
    """
    names = _bound_axes(axis)
    if not names:
        return grads

    def _avg(g):
        vma = _leaf_vma(g, names)
        varying = [a for a in names if a in vma]
        presummed = [a for a in names if a not in vma]
        out = lax.pmean(g, varying) if varying else g
        size_presummed = 1
        for name in presummed:
            size_presummed *= lax.axis_size(name)
        return out / size_presummed if size_presummed > 1 else out

    return _maybe_fused_reduce(grads, names, _avg, mean=True)


def sum_gradients(grads: PyTree, axis: AxisName = "data") -> PyTree:
    """Cross-replica *sum* with the same vma-awareness as
    ``average_gradients``: pre-summed (unvarying) leaves pass through instead
    of being double-counted by another psum."""
    names = _bound_axes(axis)
    if not names:
        return grads

    def _sum(g):
        vma = _leaf_vma(g, names)
        varying = [a for a in names if a in vma]
        return lax.psum(g, varying) if varying else g

    return _maybe_fused_reduce(grads, names, _sum, mean=False)


def _maybe_fused_reduce(grads: PyTree, names, per_leaf, *, mean: bool) -> PyTree:
    """Knob routing shared by average_/sum_gradients: with
    TPUFRAME_FUSION_THRESHOLD set, fully-varying leaves reduce through the
    packed fusion buffers (tpuframe.parallel.fusion) so the hvd facade's
    DistributedOptimizer has the same knob semantics as the step builder;
    mixed/presummed leaves (and the knob-unset default) keep the per-leaf
    vma-aware path."""
    from tpuframe.parallel import tuning

    threshold = tuning.step_threshold()
    if not threshold or threshold <= 0:
        return jax.tree.map(per_leaf, grads)
    from tpuframe.parallel import fusion

    leaves, treedef = jax.tree.flatten(grads)
    fused_idx = [i for i, g in enumerate(leaves)
                 if all(a in _leaf_vma(g, names) for a in names)]
    out = {i: per_leaf(leaves[i])
           for i in set(range(len(leaves))) - set(fused_idx)}
    if fused_idx:
        reduced = fusion.fused_psum([leaves[i] for i in fused_idx], names,
                                    threshold_bytes=threshold, mean=mean)
        out.update(dict(zip(fused_idx, reduced)))
    return jax.tree.unflatten(treedef, [out[i] for i in range(len(leaves))])


def allgather(x: jax.Array, axis: AxisName = "data", *, tiled: bool = True) -> jax.Array:
    """Concatenate each shard's value along dim 0 (Horovod allgather).
    Unmapped (world of 1): identity, matching the other collectives'
    single-process no-op contract."""
    bound = _bound_axes(axis)
    if not bound:
        return x
    return lax.all_gather(x, bound, axis=0, tiled=tiled)


# jax >= 0.6 vma machinery (mirrors zero1._HAS_VMA): all_gather_invariant
# exists and can mark a gather's result replication-invariant.
_HAS_VMA = hasattr(jax, "typeof") and hasattr(lax, "pcast")


def _leaf_vma(g, names):
    """The axes ``g`` is varying over, for the gradient-reduce routing.
    On the pre-vma legacy shard_map (check_rep=False) nothing tracks
    replication, and every leaf arrives local — i.e. varying over every
    bound axis — so the compat answer is ``names`` itself."""
    if _HAS_VMA:
        return jax.typeof(g).vma
    return frozenset(names)


def allgather_invariant(x: jax.Array, axis: AxisName = "data", *,
                        gather_axis: int = 0, tiled: bool = True) -> jax.Array:
    """Tiled all-gather whose result is marked replication-INVARIANT where
    this jax can express it: every replica gathers the identical full
    array, so the output is legal under a replicated out_spec (the zero1
    param regather and the quantwire int8 gather both rely on this).
    Falls back to a plain ``lax.all_gather`` on legacy jax, where
    check_rep=False tracks nothing anyway.  Unmapped: identity."""
    bound = _bound_axes(axis)
    if not bound:
        return x
    gather = getattr(lax, "all_gather_invariant", None)
    if gather is not None and _HAS_VMA:
        return gather(x, bound, axis=gather_axis, tiled=tiled)
    return lax.all_gather(x, bound, axis=gather_axis, tiled=tiled)


def _linear_index(bound: tuple[str, ...]) -> jax.Array:
    """Row-major linearized replica index over the bound axes — the single
    rank space Horovod exposes (``hvd.rank()`` in its one-process-per-GPU
    model), reconstructed from the mesh position.

    Size-1 axes are skipped: their index is identically 0, and touching
    ``axis_index`` on them would mark the result varying over axes it
    cannot actually vary over (breaking callers' out_specs inference).
    """
    sized = _sized_axes(bound)
    if not sized:
        return jnp.zeros((), jnp.int32)
    if len(sized) == 1:
        return lax.axis_index(sized[0])
    idx = jnp.zeros((), jnp.int32)
    for name in sized:
        idx = idx * lax.axis_size(name) + lax.axis_index(name)
    return idx


def _sized_axes(bound: tuple[str, ...]) -> tuple[str, ...]:
    """Bound axes with size > 1 — the axes a reduction can actually act on.
    Size-1 axes are no-ops whose inclusion only confuses vma inference."""
    return tuple(n for n in bound if lax.axis_size(n) > 1)


def _vary_over(t, axes: tuple[str, ...]):
    """Make ``t`` vma-varying over every axis in ``axes`` so a collective can
    legally reduce over all of them at once (a replicated leaf counts once
    per mesh position — Horovod's rank-space semantics, where duplicate
    values on distinct ranks are still distinct contributions)."""
    missing = tuple(a for a in axes if a not in jax.typeof(t).vma)
    return lax.pcast(t, missing, to="varying") if missing else t


def _clear_unit_axes(t, bound: tuple[str, ...]):
    """Mark ``t`` reduced over any size-1 bound axes it is vma-varying on.

    Reductions here act only on the >1-sized axes, but a reduction over the
    whole ``bound`` tuple must still come back replicated over ALL of it —
    callers' ``out_specs`` rely on that (the single-device "config 1" mode
    maps a size-1 data axis).  psum over a size-1 axis is a value identity
    the compiler elides; it exists purely to update the vma state.
    """
    small = tuple(a for a in bound
                  if lax.axis_size(a) == 1 and a in jax.typeof(t).vma)
    return lax.psum(t, small) if small else t


def broadcast(x: PyTree, axis: AxisName = "data", *, root: int = 0) -> PyTree:
    """Every member takes root's value (Horovod broadcast).

    Implemented as select+psum rather than a dedicated HLO: XLA pattern-matches
    this to a broadcast-like collective, and it stays differentiable.
    """
    bound = _bound_axes(axis)
    if not bound:
        return x
    sized = _sized_axes(bound)
    if not sized:
        return jax.tree.map(lambda t: _clear_unit_axes(t, bound), x)
    _check_ranks(bound, (root,))  # an unmatched root would psum to zeros
    idx = _linear_index(bound)

    def _bcast(t):
        masked = jnp.where(idx == root, _vary_over(t, sized),
                           jnp.zeros_like(t))
        return _clear_unit_axes(lax.psum(masked, sized), bound)

    return jax.tree.map(_bcast, x)


def alltoall(x: jax.Array, axis: AxisName = "data", *, split_axis: int = 0,
             concat_axis: int = 0) -> jax.Array:
    """Horovod alltoall: scatter dim ``split_axis``, gather along ``concat_axis``.

    On TPU this lowers to the ICI AllToAll used by sequence/expert parallelism
    (kept first-class so a seq/expert axis can ride it later, SURVEY.md §5.7).
    Unmapped: identity (a 1-member alltoall is a copy).
    """
    bound = _bound_axes(axis)
    if not bound:
        return x
    return lax.all_to_all(x, bound, split_axis=split_axis, concat_axis=concat_axis,
                          tiled=True)


def ring_permute(x: jax.Array, axis: AxisName = "data", *, shift: int = 1) -> jax.Array:
    """Send each shard to its ring neighbor (basis of ring-attention-style
    pipelining; maps to CollectivePermute on neighbor ICI links).
    Unmapped: identity (a 1-ring permute is a self-send)."""
    bound = _bound_axes(axis)
    if not bound:
        return x
    if len(bound) != 1:
        raise ValueError(f"ring_permute needs exactly one axis, got {bound}")
    n = lax.axis_size(bound[0])
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, bound[0], perm=perm)


def reduce_scatter(x: jax.Array, axis: AxisName = "data", *, scatter_axis: int = 0,
                   average: bool = False) -> jax.Array:
    """psum_scatter — the building block of sharded-optimizer updates
    (cross-replica weight-update sharding, PAPERS.md:5; the zero1 path's
    gradient reduction).  Unmapped: identity (reduce over a world of 1).

    ``x.shape[scatter_axis]`` must divide evenly by the member count —
    psum_scatter has no remainder path, and the shape error it raises
    from deep inside lowering is unreadable; callers that need uneven
    leaves pad first (``zero1``'s pad-to-multiple layout)."""
    bound = _bound_axes(axis)
    if not bound:
        return x
    n = 1
    for name in bound:
        n *= lax.axis_size(name)
    dim = x.shape[scatter_axis] if x.ndim else 0
    if dim % n:
        raise ValueError(
            f"reduce_scatter: dim {scatter_axis} of shape {tuple(x.shape)} "
            f"({dim}) is not divisible by the {n}-member axis {bound}; "
            f"pad the leading dim to a multiple of {n} first (see "
            f"tpuframe.parallel.zero1's pad-to-multiple layout)")
    out = lax.psum_scatter(x, bound, scatter_dimension=scatter_axis, tiled=True)
    if average:
        out = out / n
    return out


# ---------------------------------------------------------------------------
# Host-level (outside shard_map) collectives over a mesh
# ---------------------------------------------------------------------------

def cross_replica_mean(tree: PyTree, mesh: Mesh | None = None) -> PyTree:
    """Average genuinely per-process host values across all processes.

    Reference parity: the eval-loop ``hvd.allreduce(metric_tensor)`` one-shot
    collective (SURVEY.md §4.5).  Every process calls this with its OWN local
    value (e.g. a per-host eval accuracy); the result is the cross-process
    mean, identical on every process.  Single-process: identity (Horovod's
    size()==1 no-op contract).  ``mesh`` is accepted for signature
    compatibility but unused — the reduction runs over a one-device-per-
    process mesh built here, so it works regardless of the caller's mesh.
    """
    del mesh
    nproc = jax.process_count()
    if nproc == 1:
        return jax.tree.map(lambda t: jnp.asarray(t, jnp.float32), tree)

    import numpy as np

    # One device per process, in process order — each process contributes one
    # row of the stacked array via make_array_from_process_local_data.
    per_proc: dict[int, Any] = {}
    for d in jax.devices():
        per_proc.setdefault(d.process_index, d)
    devs = [per_proc[i] for i in sorted(per_proc)]
    # One-device-per-process host mesh for cross-process gathers — a
    # degenerate transport detail, not a training-axis mesh.
    pmesh = Mesh(np.asarray(devs), ("proc",))  # tf-lint: ok[TF119]
    sharding = NamedSharding(pmesh, P("proc"))

    def _mean(leaf):
        local = np.asarray(leaf, np.float32)[None]
        garr = jax.make_array_from_process_local_data(
            sharding, local, (nproc, *local.shape[1:]))
        return jnp.mean(garr, axis=0)

    return jax.tree.map(_mean, tree)


def primary_device_put(x, sharding: NamedSharding) -> jax.Array:
    """Replicate process-0's host value onto every device, shipping the bytes
    over the device interconnect (ICI/DCN) instead of having each host supply
    its own copy.

    The checkpoint-restore counterpart of the reference's rank-0
    ``torch.load`` + ``hvd.broadcast_parameters`` (SURVEY.md §4.4): the
    primary host reads from storage once and the fabric fans the data out —
    storage traffic is O(bytes), not O(hosts × bytes).  Non-primary
    processes pass a same-shape/dtype placeholder (contents ignored).

    ``sharding`` must be fully replicated over a mesh spanning all devices.
    Mechanism: one row per device, process-0's first-device row carries the
    payload and every other row is zero, then an on-device sum over the row
    axis replicates the payload everywhere (one all-reduce-shaped transfer).
    """
    if jax.process_count() == 1:
        return jax.device_put(x, sharding)
    if not sharding.is_fully_replicated:
        raise ValueError("primary_device_put needs a fully-replicated "
                         f"sharding, got {sharding}")
    if hasattr(x, "dtype") and jax.dtypes.issubdtype(x.dtype, jax.dtypes.extended):
        data = primary_device_put(jax.random.key_data(x), sharding)
        return jax.random.wrap_key_data(data, impl=jax.random.key_impl(x))

    arr = np.asarray(x)
    as_bool = arr.dtype == np.bool_
    if as_bool:
        arr = arr.view(np.uint8)
    # Row mesh built from the TARGET sharding's own device order — on real
    # TPU slices jax.make_mesh reorders devices to the ICI torus, so
    # jax.devices() order and the caller's mesh order differ; deriving both
    # sides from one order keeps the jit's input and output compatible.
    devs = list(sharding.mesh.devices.flat)
    # Broadcast-row host mesh in the caller's device order — transport
    # detail, same class as the proc mesh above.
    pmesh = Mesh(np.asarray(devs), ("bcast",))  # tf-lint: ok[TF119]
    rows = NamedSharding(pmesh, P("bcast"))
    payload_row = min(i for i, d in enumerate(devs) if d.process_index == 0)
    # One shared zero row (not a local_devices×leaf buffer): host RAM stays
    # O(leaf), and only the payload row carries real data.
    zero_row = np.zeros((1, *arr.shape), arr.dtype)
    pieces = [
        jax.device_put(arr[None] if i == payload_row else zero_row, d)
        for i, d in enumerate(devs)
        if d.process_index == jax.process_index()
    ]
    garr = jax.make_array_from_single_device_arrays(
        (len(devs), *arr.shape), rows, pieces)
    out = _bcast_sum(sharding)(garr)
    return out.astype(jnp.bool_) if as_bool else out


@functools.lru_cache(maxsize=64)
def _bcast_sum(sharding: NamedSharding):
    """One jitted sum-over-rows program per target sharding — restore calls
    primary_device_put once per leaf; a fresh jit per call would recompile
    the same trivial program hundreds of times per restart."""
    return jax.jit(lambda a: a.sum(axis=0), out_shardings=sharding)


def quantized_mean(tree: PyTree, axis: AxisName = "data") -> PyTree:
    """REMOVED — raises with the replacement spelled out.

    The original shared-scale int16-accumulated psum prototype grew into
    the block-quantized ``int8-block`` wire format (per-block scales, s8
    payload over all-to-all + all-gather — arXiv:2506.17615), resolved
    per strategy through ``TPUFRAME_WIRE_FORMAT`` / the tune DB on the
    step path.  The warn-once shim rode along for two release cycles;
    with the spec grammar closed there is exactly one quantized-wire
    seam, and a silent alias to it hides the per-strategy resolution.
    """
    raise RuntimeError(
        "collectives.quantized_mean was removed: call "
        "tpuframe.parallel.quantwire.all_reduce_mean(tree, axis, "
        "min_elems=0) for the old always-quantized semantics, or — the "
        "supported path — select the wire per strategy via "
        "TPUFRAME_WIRE_FORMAT='int8-block' / the tune DB on the "
        "make_train_step path")


def host_broadcast(tree: PyTree, mesh: Mesh) -> PyTree:
    """Replicate host-0-computed values onto every device of the mesh
    (reference parity: ``hvd.broadcast_parameters`` from rank 0 at start,
    SURVEY.md §4.1).  Under SPMD every process must call this with the same
    structure; data content is taken from the fully-replicated device copy."""
    sharding = mesh_lib.replicated_sharding(mesh)
    return jax.tree.map(lambda t: jax.device_put(t, sharding), tree)


def device_count(axis_env_size: int | None = None) -> int:
    return axis_env_size or jax.device_count()


def psum_scalar(value: float | jax.Array, axis: AxisName = "data") -> jax.Array:
    """Scalar psum usable in metric dicts inside step functions."""
    if not _in_mapped_context(axis):
        return jnp.asarray(value)
    return lax.psum(jnp.asarray(value), axis)


def reduce_min(x: PyTree, axis: AxisName = "data") -> PyTree:
    """Elementwise cross-replica minimum (Horovod ``op=hvd.Min``)."""
    return _elementwise_reduce(x, axis, lax.pmin)


def reduce_max(x: PyTree, axis: AxisName = "data") -> PyTree:
    """Elementwise cross-replica maximum (Horovod ``op=hvd.Max``)."""
    return _elementwise_reduce(x, axis, lax.pmax)


def _elementwise_reduce(x: PyTree, axis: AxisName, op) -> PyTree:
    """Shared guard chain for psum/pmean/pmin/pmax-style reductions.

    ``_vary_over``: a leaf replicated along one sized axis but varying along
    another would otherwise present a mixed vma state the collective
    rejects; counting it once per mesh position is Horovod's rank-space
    semantics.  ``_clear_unit_axes``: outputs come back replicated over the
    size-1 bound axes too, preserving callers' out_specs expectations.
    """
    bound = _bound_axes(axis)
    if not bound:
        return x
    sized = _sized_axes(bound)
    if not sized:
        return jax.tree.map(lambda t: _clear_unit_axes(t, bound), x)
    return jax.tree.map(
        lambda t: _clear_unit_axes(op(_vary_over(t, sized), sized), bound), x)


def reduce_prod(x: PyTree, axis: AxisName = "data") -> PyTree:
    """Elementwise cross-replica product (Horovod ``op=hvd.Product``).

    XLA has no product all-reduce HLO; the sound formulation (zeros and
    negative values included — a log/exp trick would not be) is all_gather
    then a local product over the gathered axis.  Product reductions are a
    metrics-sized verb in practice, so the gather's N× wire traffic does
    not matter.
    """
    bound = _bound_axes(axis)
    if not bound:
        return x
    sized = _sized_axes(bound)
    if not sized:
        return jax.tree.map(lambda t: _clear_unit_axes(t, bound), x)

    def _prod(t):
        gathered = lax.all_gather(_vary_over(t, sized), sized, axis=0,
                                  tiled=False)
        # Every replica computes the identical product from the gathered
        # copies, but vma can't see through all_gather: pmax of identical
        # values is a bit-exact identity that marks the result reduced.
        return _clear_unit_axes(lax.pmax(jnp.prod(gathered, axis=0), sized),
                                bound)

    return jax.tree.map(_prod, x)


def adasum(tree: PyTree, axis: AxisName = "data") -> PyTree:
    """Adaptive summation (Horovod ``op=hvd.Adasum``, arXiv:2006.02924).

    The pairwise combine is scale-insensitive: for gradients ``a, b``

        adasum(a, b) = (1 - a.b / 2|a|^2) a  +  (1 - a.b / 2|b|^2) b

    which is the *mean* when a == b (each coefficient becomes 1/2) and the
    *sum* when a ⟂ b — interpolating between LR-scaling regimes, which is
    the whole point of the op.  Horovod runs it as a recursive-halving
    tree in its C++ runtime; the SPMD-native realization is a ppermute
    BUTTERFLY: at stage k every replica exchanges with ``index XOR 2^k``
    and applies the (symmetric) combine, so all replicas hold the identical
    reduction after log2(N) stages — same pairing tree, no runtime thread.

    Norm/dot accumulation is f32 regardless of input dtype.  Requires a
    power-of-two replica count (TPU mesh axes are powers of two); the
    butterfly pairing has no remainder path.

    Arrival-state caveat (cf. ``average_gradients``): Adasum needs the RAW
    per-replica gradients.  Under shard_map autodiff, grads of replicated
    (unvarying) params arrive ALREADY psum'd — identical on every replica —
    and adasum of identical vectors is the identity, so a pre-summed leaf
    passes through as the cross-replica SUM, not the adaptive combine.  To
    get true Adasum semantics compute per-shard losses against ``pvary``-ed
    params so grads stay varying (the harness's step builder does).
    """
    names = _bound_axes(axis)
    if not names:
        return tree
    # Multiple bound axes: sequential per-axis butterflies (equivalent to
    # one big butterfly up to Adasum's own pairing-tree dependence — the op
    # is not associative, and Horovod's own result likewise depends on its
    # reduction-tree shape).
    if len(names) > 1:
        out = tree
        for a in names:
            out = adasum(out, a)
        return out
    (name,) = names
    n = lax.axis_size(name)
    if n & (n - 1):
        raise ValueError(f"adasum butterfly needs a power-of-two replica "
                         f"count, got {n} over {name!r}")
    if n == 1:
        return jax.tree.map(lambda t: _clear_unit_axes(t, names), tree)

    def _ada(x):
        # Pre-summed (unvarying) leaves enter the butterfly as identical
        # vectors and come out unchanged — the documented degrade-to-sum;
        # without the cast, ppermute rejects the unvarying operand outright.
        # Trace-time warning (PORTING.md Adasum caveat 2): statically
        # detectable, and silent sum-semantics is exactly the surprise a
        # porting user hits — the harness's local-grads path never does.
        if name not in jax.typeof(x).vma:
            import warnings

            warnings.warn(
                f"adasum over {name!r}: leaf is unvarying (already reduced "
                f"over the axis) — the butterfly is an identity on it, so "
                f"you get SUM semantics, not the adaptive combine. Feed "
                f"adasum the raw per-replica gradients (see PORTING.md).",
                stacklevel=3)
        v = _vary_over(x.astype(jnp.float32), (name,))
        for k in range(n.bit_length() - 1):
            dist = 1 << k
            perm = [(i, i ^ dist) for i in range(n)]
            other = lax.ppermute(v, name, perm)
            dot = jnp.vdot(v, other)
            na = jnp.vdot(v, v)
            nb = jnp.vdot(other, other)
            ca = jnp.where(na > 0, dot / (2.0 * na), 0.0)
            cb = jnp.where(nb > 0, dot / (2.0 * nb), 0.0)
            v = (1.0 - ca) * v + (1.0 - cb) * other
        # All replicas now hold the identical combined value, but the vma
        # system cannot infer that through ppermute.  pmax of identical
        # values is a BIT-EXACT identity (unlike pmean, whose re-summation
        # can round) and marks the leaf reduced over the axis — at the cost
        # of one extra gradient-sized collective, which is in the spirit of
        # the op (Horovod's Adasum tree is likewise pricier than a ring).
        return lax.pmax(v, name).astype(x.dtype)

    return jax.tree.map(_ada, tree)


def _member_mask(bound: tuple[str, ...], ranks: Sequence[int]) -> jax.Array:
    """Boolean scalar: is this replica's linearized rank in ``ranks``?"""
    idx = _linear_index(bound)
    member = jnp.zeros((), bool)
    for r in ranks:
        member = member | (idx == r)
    return member


def _check_ranks(bound: tuple[str, ...], ranks: Sequence[int]) -> None:
    """Trace-time validation: every rank must exist in the linearized rank
    space, else masked/rooted collectives silently drop contributions (an
    out-of-range or negative rank never matches any replica's index) —
    Horovod raises for invalid ranks too."""
    world = 1
    for a in _sized_axes(bound):
        world *= lax.axis_size(a)
    bad = [int(r) for r in ranks if int(r) >= world or int(r) < 0]
    if bad:
        raise ValueError(f"ranks {bad} out of range for a "
                         f"{world}-replica axis {bound}")


def masked_allreduce(x: PyTree, axis: AxisName, ranks: Sequence[int], *,
                     average: bool = True) -> PyTree:
    """Allreduce restricted to the replicas in ``ranks`` (Horovod
    ``process_set=``): members receive the subgroup sum/mean, NON-members
    keep their input unchanged — Horovod's op simply never runs on ranks
    outside the set.

    Realized as a masked reduction over the full axis (zero contributions
    from non-members, static divisor ``len(ranks)``) — one full-axis psum
    instead of a subgroup communicator, which XLA then routes over the same
    ICI links a subgroup ring would use.
    """
    bound = _bound_axes(axis)
    if not bound:
        return x
    sized = _sized_axes(bound)
    if not sized:
        return jax.tree.map(lambda t: _clear_unit_axes(t, bound), x)
    _check_ranks(bound, ranks)
    m = _member_mask(bound, ranks)
    count = len(set(int(r) for r in ranks))

    def _f(t):
        contrib = jnp.where(m, _vary_over(t, sized), jnp.zeros_like(t))
        total = lax.psum(contrib, sized)
        if average:
            total = (total.astype(jnp.float32) / count).astype(t.dtype)
        return _clear_unit_axes(jnp.where(m, total, t), bound)

    return jax.tree.map(_f, x)


def masked_broadcast(x: PyTree, axis: AxisName, ranks: Sequence[int], *,
                     root: int) -> PyTree:
    """Broadcast ``root``'s value to the replicas in ``ranks`` only; others
    keep their input (Horovod ``broadcast(..., process_set=...)``)."""
    bound = _bound_axes(axis)
    if not bound:
        return x
    sized = _sized_axes(bound)
    if not sized:
        return jax.tree.map(lambda t: _clear_unit_axes(t, bound), x)
    if root not in set(int(r) for r in ranks):
        raise ValueError(f"root {root} is not a member of the process set "
                         f"{sorted(set(int(r) for r in ranks))}")
    _check_ranks(bound, ranks)
    m = _member_mask(bound, ranks)
    idx = _linear_index(bound)

    def _f(t):
        rooted = lax.psum(
            jnp.where(idx == root, _vary_over(t, sized), jnp.zeros_like(t)),
            sized)
        return _clear_unit_axes(jnp.where(m, rooted, t), bound)

    return jax.tree.map(_f, x)


def global_norm(tree: PyTree, axis: AxisName | None = None) -> jax.Array:
    """L2 norm of a pytree; if ``axis`` given, the norm of the *global*
    (allreduced) gradient — used by grad-clipping parity with the reference's
    pre-allreduce clipping semantics."""
    sq = sum(jnp.sum(jnp.square(t)) for t in jax.tree.leaves(tree))
    if axis is not None and _in_mapped_context(axis):
        sq = lax.psum(sq, axis)
    return jnp.sqrt(sq)
