"""Process bootstrap — the TPU-native replacement for ``hvd.init()``.

Reference capability (SURVEY.md §4.3): ``hvd.init()`` starts Horovod's C++
background thread, exchanges rank/size/local_rank over MPI or Gloo, and lazily
creates NCCL communicators.  On TPU none of that machinery exists as user-level
runtime: ``jax.distributed.initialize()`` performs a GRPC-coordinator
rendezvous, after which ``jax.devices()`` sees every chip in the slice and the
XLA runtime owns communicator setup.  This module wraps that in a single
idempotent call that is a no-op for single-process runs, so the same
``train.py`` works from a laptop CPU to a multi-host pod (the Horovod property
the reference leans on).
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass

import jax

logger = logging.getLogger(__name__)

_STATE = {"initialized": False, "multi_process": False}


@dataclass(frozen=True)
class DistConfig:
    """Explicit multi-process wiring; every field defaults from the standard
    env vars the launcher (tpuframe.launch) exports on each worker."""

    coordinator_address: str | None = None  # host:port of process 0
    num_processes: int | None = None
    process_id: int | None = None
    local_device_ids: tuple[int, ...] | None = None

    @classmethod
    def from_env(cls) -> "DistConfig":
        def _int(name: str) -> int | None:
            v = os.environ.get(name)
            return int(v) if v is not None else None

        return cls(
            coordinator_address=os.environ.get("TPUFRAME_COORDINATOR"),
            num_processes=_int("TPUFRAME_NUM_PROCESSES"),
            process_id=_int("TPUFRAME_PROCESS_ID"),
        )


def initialize(config: DistConfig | None = None) -> None:
    """Idempotent distributed bootstrap.

    Single-process (no coordinator configured, not on a multi-host TPU): no-op.
    Multi-process: calls ``jax.distributed.initialize`` so all hosts join one
    XLA runtime; afterwards ``jax.devices()`` is global and meshes can span
    the full slice.
    """
    if _STATE["initialized"]:
        return
    from tpuframe.parallel import tuning

    tuning.apply_from_env()  # HOROVOD_FUSION_THRESHOLD parity (must precede
    # first backend touch; no-op unless TPUFRAME_FUSION_THRESHOLD is set)
    cfg = config or DistConfig.from_env()
    explicit = cfg.coordinator_address is not None
    # On Cloud TPU VMs jax.distributed.initialize() can autodetect everything
    # from the metadata server; TPUFRAME_MULTIHOST=1 opts in to that path.
    autodetect = os.environ.get("TPUFRAME_MULTIHOST") == "1"
    if explicit or autodetect:
        kwargs = {}
        if explicit:
            kwargs = dict(
                coordinator_address=cfg.coordinator_address,
                num_processes=cfg.num_processes,
                process_id=cfg.process_id,
            )
            if cfg.local_device_ids is not None:
                kwargs["local_device_ids"] = list(cfg.local_device_ids)
        jax.distributed.initialize(**kwargs)
        _STATE["multi_process"] = True
        logger.info(
            "distributed initialized: process %d/%d, %d local / %d global devices",
            jax.process_index(),
            jax.process_count(),
            jax.local_device_count(),
            jax.device_count(),
        )
    _STATE["initialized"] = True


def is_initialized() -> bool:
    return _STATE["initialized"]


def host_barrier(tag: str) -> None:
    """Cross-host sync point.  ``tag`` names the rendezvous: concurrent
    UNRELATED barriers must carry different tags so a mispairing fails
    loudly (hangs both) instead of silently releasing each other."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(tag)


def shutdown() -> None:
    """Tear down the coordinator channel (used by launcher on clean exit)."""
    if _STATE["multi_process"]:
        jax.distributed.shutdown()
        _STATE["multi_process"] = False
    _STATE["initialized"] = False


def process_index() -> int:
    """This host's index (== Horovod's node-level rank for the harness's
    rank-0-gated logging; per-chip rank lives inside compiled programs as
    ``lax.axis_index``)."""
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def is_primary() -> bool:
    """True on the host that should own logging/eval-summary duties
    (reference: ``if hvd.rank() == 0`` gates, SURVEY.md §4.4/§5.5)."""
    return jax.process_index() == 0
