"""Process bootstrap — the TPU-native replacement for ``hvd.init()``.

Reference capability (SURVEY.md §4.3): ``hvd.init()`` starts Horovod's C++
background thread, exchanges rank/size/local_rank over MPI or Gloo, and lazily
creates NCCL communicators.  On TPU none of that machinery exists as user-level
runtime: ``jax.distributed.initialize()`` performs a GRPC-coordinator
rendezvous, after which ``jax.devices()`` sees every chip in the slice and the
XLA runtime owns communicator setup.  This module wraps that in a single
idempotent call that is a no-op for single-process runs, so the same
``train.py`` works from a laptop CPU to a multi-host pod (the Horovod property
the reference leans on).
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass

import jax

logger = logging.getLogger(__name__)

_STATE = {"initialized": False, "multi_process": False}


@dataclass(frozen=True)
class DistConfig:
    """Explicit multi-process wiring; every field defaults from the standard
    env vars the launcher (tpuframe.launch) exports on each worker."""

    coordinator_address: str | None = None  # host:port of process 0
    num_processes: int | None = None
    process_id: int | None = None
    local_device_ids: tuple[int, ...] | None = None

    @classmethod
    def from_env(cls) -> "DistConfig":
        def _int(name: str) -> int | None:
            v = os.environ.get(name)
            return int(v) if v is not None else None

        return cls(
            coordinator_address=os.environ.get("TPUFRAME_COORDINATOR"),
            num_processes=_int("TPUFRAME_NUM_PROCESSES"),
            process_id=_int("TPUFRAME_PROCESS_ID"),
        )


def initialize(config: DistConfig | None = None) -> None:
    """Idempotent distributed bootstrap.

    Single-process (no coordinator configured, not on a multi-host TPU): no-op.
    Multi-process: calls ``jax.distributed.initialize`` so all hosts join one
    XLA runtime; afterwards ``jax.devices()`` is global and meshes can span
    the full slice.
    """
    if _STATE["initialized"]:
        return
    from tpuframe.parallel import tuning

    tuning.apply_from_env()  # HOROVOD_FUSION_THRESHOLD parity (must precede
    # first backend touch; no-op unless TPUFRAME_FUSION_THRESHOLD is set)
    cfg = config or DistConfig.from_env()
    explicit = cfg.coordinator_address is not None
    # On Cloud TPU VMs jax.distributed.initialize() can autodetect everything
    # from the metadata server; TPUFRAME_MULTIHOST=1 opts in to that path.
    autodetect = os.environ.get("TPUFRAME_MULTIHOST") == "1"
    if explicit or autodetect:
        kwargs = {}
        if explicit:
            kwargs = dict(
                coordinator_address=cfg.coordinator_address,
                num_processes=cfg.num_processes,
                process_id=cfg.process_id,
            )
            if cfg.local_device_ids is not None:
                kwargs["local_device_ids"] = list(cfg.local_device_ids)
        jax.distributed.initialize(**kwargs)
        _STATE["multi_process"] = True
        logger.info(
            "distributed initialized: process %d/%d, %d local / %d global devices",
            jax.process_index(),
            jax.process_count(),
            jax.local_device_count(),
            jax.device_count(),
        )
    _STATE["initialized"] = True


def is_initialized() -> bool:
    return _STATE["initialized"]


def host_barrier(tag: str) -> None:
    """Cross-host sync point.  ``tag`` names the rendezvous: concurrent
    UNRELATED barriers must carry different tags so a mispairing fails
    loudly (hangs both) instead of silently releasing each other."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(tag)


def shutdown() -> None:
    """Tear down the coordinator channel (used by launcher on clean exit)."""
    if _STATE["multi_process"]:
        jax.distributed.shutdown()
        _STATE["multi_process"] = False
    _STATE["initialized"] = False


def process_index() -> int:
    """This host's index (== Horovod's node-level rank for the harness's
    rank-0-gated logging; per-chip rank lives inside compiled programs as
    ``lax.axis_index``)."""
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def is_primary() -> bool:
    """True on the host that should own logging/eval-summary duties
    (reference: ``if hvd.rank() == 0`` gates, SURVEY.md §4.4/§5.5)."""
    return jax.process_index() == 0


def broadcast_object(obj, root: int = 0):
    """Send a picklable host object from ``root`` to every process
    (Horovod ``hvd.broadcast_object`` — sampler state, config dicts,
    vocabulary metadata).  Two-phase: the payload LENGTH is broadcast at
    a fixed shape first, then the pickled bytes at that shape — every
    process must call this collectively, like the Horovod original.

    Pickle is the wire format, as in Horovod/torch.distributed: peers of
    a training job are mutually trusted by construction.
    """
    if not 0 <= root < jax.process_count():
        raise ValueError(f"broadcast_object root {root} out of range for "
                         f"{jax.process_count()} processes")
    if jax.process_count() == 1:
        return obj
    import pickle

    import numpy as np
    from jax.experimental import multihost_utils

    is_root = jax.process_index() == root
    payload = (np.frombuffer(pickle.dumps(obj), np.uint8) if is_root
               else np.zeros((0,), np.uint8))
    n = multihost_utils.broadcast_one_to_all(
        np.array([payload.size], np.int64), is_source=is_root)
    buf = np.zeros((int(n[0]),), np.uint8)
    if is_root:
        buf[:] = payload
    data = multihost_utils.broadcast_one_to_all(buf, is_source=is_root)
    return pickle.loads(np.asarray(data).tobytes())


def allgather_object(obj) -> list:
    """Gather one picklable object per process, returning the list in
    process order on EVERY process (Horovod ``hvd.allgather_object``).
    Ragged payloads are length-gathered first, padded to the global max,
    gathered, then sliced back."""
    if jax.process_count() == 1:
        return [obj]
    import pickle

    import numpy as np
    from jax.experimental import multihost_utils

    payload = np.frombuffer(pickle.dumps(obj), np.uint8)
    lengths = multihost_utils.process_allgather(
        np.array([payload.size], np.int64))
    lengths = np.asarray(lengths).reshape(-1)
    buf = np.zeros((int(lengths.max()),), np.uint8)
    buf[:payload.size] = payload
    rows = np.asarray(multihost_utils.process_allgather(buf))
    rows = rows.reshape(jax.process_count(), -1)
    return [pickle.loads(rows[i, :int(lengths[i])].tobytes())
            for i in range(jax.process_count())]
