"""Declarative parallelism spec — one string lowered onto the mesh.

``TPUFRAME_SPEC="dp=4,fsdp=2,tp=1;slices=2"`` names a complete
parallelism layout: the comma part declares the ICI axes of ONE slice
(in grammar keys — ``dp``/``fsdp``/``tp``/``pp``/``sp``/``ep``; values
are positive degrees, ``*`` on ``dp`` means "all remaining chips"), and
the optional ``;slices=N`` tail declares N such slices joined by DCN.
:func:`parse_spec` validates the grammar, :meth:`ParallelSpec.mesh_spec`
turns it into the hierarchical :class:`~tpuframe.parallel.mesh.MeshSpec`
(slice axis outermost, so only genuinely cross-slice collectives ride
the slow fabric), and :func:`lower` maps it onto the existing
``make_train_step`` seams — dp/zero1/wire-format/fusion stay orthogonal
modifiers instead of eight hand-wired strategies (ROADMAP item 2; the
composition view of arXiv:1909.09756 / arXiv:2011.03641).

Layer contract: this module imports only :mod:`tpuframe.parallel.mesh`
at the top level.  The analysis plane (shardflow's detectors and the
ICI/DCN byte split) is imported lazily inside :func:`check` — the gate
self-check — never at import time.
"""

from __future__ import annotations

import dataclasses
import os

SPEC_ENV = "TPUFRAME_SPEC"

#: grammar key -> mesh axis name (the order here is the canonical
#: formatting order; mesh axis order itself is fixed by mesh.AXES).
AXIS_KEYS = {
    "dp": "data",
    "fsdp": "fsdp",
    "tp": "model",
    "pp": "pipe",
    "sp": "seq",
    "ep": "expert",
}


class SpecError(ValueError):
    """A malformed, overcommitted, or unlowerable parallelism spec."""


@dataclasses.dataclass(frozen=True)
class ParallelSpec:
    """A parsed ``TPUFRAME_SPEC``.  ``dp == -1`` is the ``*`` wildcard
    ("all remaining chips"); every other degree must be positive."""

    dp: int = 1
    fsdp: int = 1
    tp: int = 1
    pp: int = 1
    sp: int = 1
    ep: int = 1
    slices: int = 1

    def __post_init__(self):
        for key in AXIS_KEYS:
            v = getattr(self, key)
            if key == "dp" and v == -1:
                continue
            if not isinstance(v, int) or v < 1:
                raise SpecError(
                    f"axis {key}={v!r} must be a positive integer"
                    + (" (or * for all remaining chips)"
                       if key == "dp" else ""))
        if not isinstance(self.slices, int) or self.slices < 1:
            raise SpecError(f"slices={self.slices!r} must be a positive "
                            f"integer — a mesh spans at least one slice")

    def canonical(self) -> str:
        """Minimal round-trippable spelling: ``dp`` always prints (the
        spec is meaningless without a batch axis statement), other axes
        only at degree > 1, ``;slices=N`` only when hierarchical."""
        parts = [f"dp={'*' if self.dp == -1 else self.dp}"]
        parts += [f"{k}={getattr(self, k)}" for k in AXIS_KEYS
                  if k != "dp" and getattr(self, k) != 1]
        text = ",".join(parts)
        if self.slices > 1:
            text += f";slices={self.slices}"
        return text

    def mesh_spec(self):
        """The hierarchical :class:`MeshSpec` this spec declares."""
        from tpuframe.parallel import mesh as mesh_lib

        kw = {AXIS_KEYS[k]: getattr(self, k) for k in AXIS_KEYS}
        return mesh_lib.MeshSpec(slices=self.slices, **kw)

    def sizes(self, n_devices: int) -> dict:
        """Resolved per-axis sizes (mesh axis names), wildcard filled.
        Raises :class:`SpecError` on over/under-committed specs."""
        import numpy as np

        try:
            return self.mesh_spec().sizes(n_devices)
        except ValueError as e:
            fixed = int(np.prod([getattr(self, k) for k in AXIS_KEYS
                                 if getattr(self, k) != -1])) * self.slices
            if fixed > n_devices:
                raise SpecError(
                    f"spec '{self.canonical()}' is overcommitted: axis "
                    f"product {fixed} exceeds the {n_devices} available "
                    f"devices") from e
            raise SpecError(f"spec '{self.canonical()}' does not fit "
                            f"{n_devices} devices: {e}") from e

    def make_mesh(self, devices=None):
        """Build the declared hierarchical mesh over ``devices`` (default:
        every visible chip)."""
        from tpuframe.parallel import mesh as mesh_lib

        return mesh_lib.make_mesh(self.mesh_spec(), devices=devices)


def parse_spec(text: str) -> ParallelSpec:
    """Parse ``"dp=4,fsdp=2,tp=1;slices=2"`` into a :class:`ParallelSpec`.

    Grammar errors are :class:`SpecError` with the offending token named
    — an explicit spec (env or CLI) must fail loudly, never degrade."""
    if not isinstance(text, str) or not text.strip():
        raise SpecError("empty parallelism spec — expected e.g. "
                        "'dp=4,fsdp=2;slices=2'")
    text = "".join(text.split())  # whitespace is never meaningful
    head, sep, tail = text.partition(";")
    kw: dict[str, int] = {}
    if sep:
        skey, seq, sval = tail.partition("=")
        if skey != "slices" or not seq:
            raise SpecError(f"after ';' only 'slices=N' is allowed, "
                            f"got {tail!r}")
        try:
            kw["slices"] = int(sval)
        except ValueError:
            raise SpecError(f"slices={sval!r} is not an integer") from None
    if not head:
        raise SpecError(f"spec {text!r} has no axis part before ';'")
    for token in head.split(","):
        key, eq, val = token.partition("=")
        if not eq or not key or not val:
            raise SpecError(f"malformed axis token {token!r} — expected "
                            f"key=value")
        if key not in AXIS_KEYS:
            raise SpecError(f"unknown axis {key!r}; expected one of "
                            f"{sorted(AXIS_KEYS)}")
        if key in kw:
            raise SpecError(f"duplicate axis {key!r} in spec {text!r}")
        if val == "*":
            if key != "dp":
                raise SpecError(f"wildcard '*' is only allowed on dp, "
                                f"not {key!r}")
            kw[key] = -1
            continue
        try:
            kw[key] = int(val)
        except ValueError:
            raise SpecError(f"axis {key}={val!r} is not an integer "
                            f"(or * on dp)") from None
    return ParallelSpec(**kw)


def format_spec(spec: ParallelSpec) -> str:
    return spec.canonical()


def resolve(explicit: str | None = None) -> tuple:
    """``(ParallelSpec | None, source)`` with the framework's resolution
    discipline: an explicit argument wins, then the ``TPUFRAME_SPEC``
    env var, then ``(None, "default")`` — and an explicit ask that fails
    to parse raises (never a silent fallback)."""
    if explicit is not None:
        return parse_spec(explicit), "arg"
    raw = os.environ.get(SPEC_ENV)
    if raw is not None and raw.strip():
        return parse_spec(raw), "env"
    return None, "default"


# ---------------------------------------------------------------------------
# Lowering onto the make_train_step seams.
# ---------------------------------------------------------------------------


def lower(spec: ParallelSpec, mesh, state=None, *,
          weight_update: str = "replicated", wire_format: str | None = None,
          fusion_threshold: int | None = None, tp_rules=None,
          grad_reduce: str | None = None, hier: str | None = None,
          wire_format_dcn: str | None = None) -> dict:
    """Map a spec onto ``make_train_step`` kwargs.

    Three lowering classes exist, matching the step factory's own modes:

      * pure data-parallel (only ``dp``/``slices`` > 1) lowers to the
        shard_map path, where ``weight_update`` (zero1), ``wire_format``
        (int8-block), ``fusion_threshold``, ``grad_reduce``
        (``"adasum"``) and the two-level lowering (``hier`` +
        ``wire_format_dcn``, :mod:`tpuframe.parallel.hier`) remain
        orthogonal modifiers — exactly the knobs ``zero1.resolve`` /
        ``quantwire.resolve`` / ``hier.resolve`` already feed.  adasum
        is its own wire pattern (the ppermute butterfly) and refuses the
        other modifiers, mirroring ``make_train_step``'s rules;
      * sequence-parallel specs (``sp`` > 1, weights replicated) stay on
        the shard_map path but partition the batch's sequence dim over
        the ``seq`` axis and widen the loss reduction to span it —
        activations shard, weights do not, so the shard_map modifiers
        whose byte accounting assumes batch-only sharding (zero1 /
        int8-block / fusion / adasum) do not compose;
      * weight-sharded specs (``fsdp``/``tp``/``ep`` > 1) lower to the
        auto-SPMD path via :func:`tpuframe.parallel.fsdp.state_shardings`
        over the declared (possibly hierarchical) mesh — ``state`` (a
        TrainState or its eval_shape) is required to build the sharding
        tree, ``tp``/``ep`` additionally require ``tp_rules`` (else the
        model/expert axis would silently replicate), and the
        shard_map-only modifiers do not compose (the partitioner owns
        the collectives).

    ``pp`` keeps its dedicated GPipe harness — declaring it here is a
    :class:`SpecError` pointing at :func:`lower_pp`, not a silent
    approximation.

    Returns the kwargs dict to splat into ``make_train_step(loss_fn,
    tx, mesh, **kwargs)``.
    """
    from tpuframe.parallel import mesh as mesh_lib

    declared = spec.sizes(mesh.devices.size)
    for axis, size in declared.items():
        if int(mesh.shape.get(axis, 1)) != int(size):
            raise SpecError(
                f"mesh axis {axis!r} has size {mesh.shape.get(axis, 1)} "
                f"but spec '{spec.canonical()}' declares {size} — lower "
                f"the spec onto the mesh it built (spec.make_mesh())")
    if spec.pp > 1:
        raise SpecError(
            f"spec '{spec.canonical()}': pp does not lower through "
            f"make_train_step — use lower_pp(), which drives the pp_lm "
            f"GPipe harness")
    wire_format = wire_format or "fp"
    grad_reduce = grad_reduce or "mean"
    hier = hier or "flat"
    wire_format_dcn = wire_format_dcn or "fp"
    if grad_reduce not in ("mean", "adasum"):
        raise SpecError(f"grad_reduce={grad_reduce!r} — expected 'mean' "
                        f"or 'adasum'")
    modified = (weight_update != "replicated" or wire_format != "fp"
                or fusion_threshold is not None or hier != "flat"
                or wire_format_dcn != "fp")
    if spec.fsdp > 1 or spec.tp > 1 or spec.ep > 1:
        if spec.sp > 1:
            raise SpecError(
                f"spec '{spec.canonical()}': sp is a shard_map batch "
                f"partition and does not compose with the auto-SPMD "
                f"weight-sharded lowering")
        if modified or grad_reduce != "mean":
            raise SpecError(
                f"spec '{spec.canonical()}': weight-sharded lowering is "
                f"auto-SPMD — zero1/wire_format/fusion_threshold/adasum/"
                f"hier are shard_map modifiers and do not compose")
        if (spec.tp > 1 or spec.ep > 1) and tp_rules is None:
            raise SpecError(
                f"spec '{spec.canonical()}' shards weights over the "
                f"model/expert axis — pass tp_rules (e.g. "
                f"tp.rules_for_model(...)); without them the axis would "
                f"silently replicate")
        if state is None:
            raise SpecError(
                f"spec '{spec.canonical()}' shards weights — lowering "
                f"needs the TrainState (or its eval_shape) to build the "
                f"sharding tree")
        from tpuframe.parallel import fsdp as fsdp_lib

        shardings = fsdp_lib.state_shardings(state, mesh,
                                             tp_rules=tp_rules)
        return {
            "state_shardings": shardings,
            "batch_partition": mesh_lib.batch_spec(mesh=mesh),
        }
    if spec.sp > 1:
        if modified or grad_reduce != "mean":
            raise SpecError(
                f"spec '{spec.canonical()}': sp shards activations, not "
                f"weights — zero1/wire_format/fusion_threshold/adasum/"
                f"hier assume batch-only sharding and do not compose")
        from jax.sharding import PartitionSpec as P

        axes = mesh_lib.batch_axes(mesh)
        return {
            "weight_update": weight_update,
            "wire_format": wire_format,
            "fusion_threshold": fusion_threshold,
            "reduce_axes": (*axes, "seq"),
            "batch_partition": P(axes, "seq"),
        }
    if grad_reduce == "adasum" and modified:
        raise SpecError(
            f"spec '{spec.canonical()}': adasum's ppermute butterfly is "
            f"its own wire pattern — zero1/wire_format/fusion_threshold/"
            f"hier do not compose")
    if wire_format_dcn != "fp" and hier != "hier":
        raise SpecError(
            f"spec '{spec.canonical()}': wire_format_dcn="
            f"{wire_format_dcn!r} is the DCN leg of the two-level "
            f"lowering — it needs hier='hier'")
    return {
        "weight_update": weight_update,
        "wire_format": wire_format,
        "fusion_threshold": fusion_threshold,
        "grad_reduce": grad_reduce,
        "hier": hier,
        "wire_format_dcn": wire_format_dcn,
        "reduce_axes": mesh_lib.batch_axes(mesh),
        "batch_partition": mesh_lib.batch_spec(mesh=mesh),
    }


def lower_pp(spec: ParallelSpec, mesh, model, tx, *, n_micro: int = 2,
             fused_xent: bool = False, remat_policy=None):
    """Lower a ``pp>1`` spec onto the GPipe harness.

    Pipeline parallelism cannot be expressed as ``make_train_step``
    kwargs — the microbatch loop restructures the step itself — so the
    spec grammar lowers it through :func:`tpuframe.parallel.pp_lm.
    make_pp_lm_step` instead.  ``model`` must be a ScanBlockLM whose
    ``num_layers`` is divisible by the declared ``pp`` degree (the
    harness re-checks and raises).  Returns the harness triple
    ``(step_fn_factory, place_state, place_batch)``."""
    declared = spec.sizes(mesh.devices.size)
    for axis, size in declared.items():
        if int(mesh.shape.get(axis, 1)) != int(size):
            raise SpecError(
                f"mesh axis {axis!r} has size {mesh.shape.get(axis, 1)} "
                f"but spec '{spec.canonical()}' declares {size} — lower "
                f"the spec onto the mesh it built (spec.make_mesh())")
    if spec.pp <= 1:
        raise SpecError(f"spec '{spec.canonical()}' declares no pipeline "
                        f"axis — lower_pp needs pp > 1")
    if spec.fsdp > 1 or spec.tp > 1 or spec.ep > 1 or spec.sp > 1:
        raise SpecError(
            f"spec '{spec.canonical()}': the GPipe harness composes pp "
            f"with dp only — fsdp/tp/ep/sp do not lower through it")
    from tpuframe.parallel import pp_lm

    return pp_lm.make_pp_lm_step(model, tx, mesh, n_micro=n_micro,
                                 fused_xent=fused_xent,
                                 remat_policy=remat_policy)


# ---------------------------------------------------------------------------
# Compile-only multi-slice topologies (the PR 3 trick, extended).
# ---------------------------------------------------------------------------


def topology_devices(topology: str = "v5e:2x2", *, slices: int = 1):
    """Compile-only TPU devices for a (possibly multi-slice) topology.

    Extends the ``TPU_SKIP_MDS_QUERY`` + ``get_topology_desc`` trick the
    tune sweeps use (single v5e:2x2) with PJRT's ``num_slices`` so
    cross-slice HLO is compilable on a machine with no TPU at all.
    Raises the underlying jax/PJRT error when this jax cannot express
    multi-slice topologies — callers gate with their capability idiom."""
    if slices < 1:
        raise SpecError(f"slices must be >= 1, got {slices}")
    os.environ.setdefault("TPU_SKIP_MDS_QUERY", "1")
    from jax.experimental import topologies

    kwargs = {"num_slices": int(slices)} if slices > 1 else {}
    return topologies.get_topology_desc(
        topology, platform="tpu", **kwargs).devices


# ---------------------------------------------------------------------------
# Gate self-check: grammar fuzz + a seeded replica-group-mismatch
# positive (the shardflow idiom — the gate refuses to run blind).
# ---------------------------------------------------------------------------

#: (text, canonical) pairs the grammar must round-trip byte-exactly.
_ROUNDTRIP_CASES = (
    ("dp=8", "dp=8"),
    ("dp=*", "dp=*"),
    (" dp = 4 , fsdp = 2 ", "dp=4,fsdp=2"),
    ("dp=4,fsdp=2,tp=1;slices=2", "dp=4,fsdp=2;slices=2"),
    ("dp=2,fsdp=2;slices=2", "dp=2,fsdp=2;slices=2"),
    ("fsdp=2", "dp=1,fsdp=2"),
    ("dp=1,tp=4;slices=4", "dp=1,tp=4;slices=4"),
    ("dp=*,ep=2", "dp=*,ep=2"),
    ("dp=2,tp=4", "dp=2,tp=4"),
    ("tp=2,dp=2", "dp=2,tp=2"),
    ("dp=*,tp=2", "dp=*,tp=2"),
    ("dp=2,pp=4", "dp=2,pp=4"),
    ("pp=2", "dp=1,pp=2"),
    ("dp=*,pp=2;slices=2", "dp=*,pp=2;slices=2"),
    ("dp=2,sp=4", "dp=2,sp=4"),
    ("sp=2,dp=*", "dp=*,sp=2"),
    ("ep=2,dp=4", "dp=4,ep=2"),
    ("dp=2,sp=2,ep=1,pp=1", "dp=2,sp=2"),
    ("dp=2,tp=2,pp=2;slices=2", "dp=2,tp=2,pp=2;slices=2"),
)

#: specs the parser must REJECT (malformed grammar).
_MALFORMED_CASES = (
    "", "   ", ";slices=2", "dp", "dp=", "=4", "dp=4,", "dp=x",
    "dp=0", "dp=-2", "fsdp=*", "bogus=2", "dp=2,dp=4",
    "dp=2;slices=0", "dp=2;slices=x", "dp=2;foo=2", "dp=2;slices=",
    "tp=*", "pp=*", "sp=*", "ep=*", "tp=0", "pp=-1", "sp=x",
    "ep=", "dp=2,tp=2,tp=4", "dp=2,sp=1.5",
)

#: (spec, n_devices) pairs that parse but must fail validation.
_OVERCOMMITTED_CASES = (
    ("dp=16", 8),
    ("dp=4,fsdp=4", 8),
    ("dp=4;slices=4", 8),
    ("dp=3", 8),
    ("tp=4,pp=4", 8),
    ("dp=2,sp=8", 8),
    ("dp=2,tp=2,ep=4", 8),
    ("dp=*,pp=16", 8),
    ("dp=2,tp=2;slices=4", 8),
)

# A hand-written program whose all-reduce groups ({0,1,2},{3,4,5},{6,7})
# cannot decompose over ANY product of the declared slice=2 x data=2 x
# fsdp=2 mesh axes — sizes are unequal AND 3 is no axis product.  The
# replica-group detector must flag it; if it stays quiet the gate is
# blind to exactly the mismatch the hierarchical mesh exists to catch.
_SEEDED_MISMATCH_HLO = """\
HloModule seeded_pspec_group_mismatch

%add (a: f32[], b: f32[]) -> f32[] {
  %a = f32[] parameter(0)
  %b = f32[] parameter(1)
  ROOT %r = f32[] add(f32[] %a, f32[] %b)
}

ENTRY %main (p0: f32[65536]) -> f32[65536] {
  %p0 = f32[65536]{0} parameter(0)
  ROOT %ar = f32[65536]{0} all-reduce(f32[65536]{0} %p0), replica_groups={{0,1,2},{3,4,5},{6,7}}, to_apply=%add
}
"""

# The honest twin: a cross-slice program whose groups DO decompose over
# the same mesh — one 8-wide all-reduce (spans both slices) and one
# strided iota all-gather over the slice axis ({0,4},{1,5},{2,6},{3,7}).
# The detector must stay quiet AND the ICI/DCN split must put both on
# the DCN side (each group crosses the slice boundary at inner=4).
_SEEDED_CROSS_SLICE_HLO = """\
HloModule seeded_pspec_cross_slice

%add (a: f32[], b: f32[]) -> f32[] {
  %a = f32[] parameter(0)
  %b = f32[] parameter(1)
  ROOT %r = f32[] add(f32[] %a, f32[] %b)
}

ENTRY %main (p0: f32[65536]) -> f32[131072] {
  %p0 = f32[65536]{0} parameter(0)
  %ar = f32[65536]{0} all-reduce(f32[65536]{0} %p0), replica_groups=[1,8]<=[8], to_apply=%add
  ROOT %ag = f32[131072]{0} all-gather(f32[65536]{0} %ar), replica_groups=[4,2]<=[2,4]T(1,0), dimensions={0}
}
"""

_SEEDED_MESH = {"slice": 2, "data": 2, "fsdp": 2}


def _grammar_problems() -> list:
    problems = []
    for text, want in _ROUNDTRIP_CASES:
        try:
            spec = parse_spec(text)
        except SpecError as e:
            problems.append(f"pspec grammar: {text!r} must parse, "
                            f"got SpecError: {e}")
            continue
        got = spec.canonical()
        if got != want:
            problems.append(f"pspec grammar: {text!r} formats to {got!r}, "
                            f"expected {want!r}")
        elif parse_spec(got) != spec:
            problems.append(f"pspec grammar: {got!r} does not round-trip")
    for text in _MALFORMED_CASES:
        try:
            parse_spec(text)
        except SpecError:
            continue
        problems.append(f"pspec grammar: malformed {text!r} parsed "
                        f"without error — the validator is blind")
    for text, n in _OVERCOMMITTED_CASES:
        try:
            parse_spec(text).sizes(n)
        except SpecError:
            continue
        problems.append(f"pspec grammar: {text!r} validated on {n} "
                        f"devices — overcommit must be rejected")
    return problems


def check() -> list:
    """Gate self-check leg (``python -m tpuframe.analysis``): grammar
    fuzz over the pinned case tables, then the seeded replica-group
    positives against the hierarchical mesh — mismatch must be flagged,
    the valid cross-slice twin must be clean, and the ICI/DCN split must
    attribute the cross-slice bytes to DCN.  Any problem string means
    the pspec plane cannot be trusted and the gate fails."""
    problems = _grammar_problems()

    from tpuframe.analysis import collective_graph as cg
    from tpuframe.analysis import shardflow

    graph = cg.parse_graph(_SEEDED_MISMATCH_HLO)
    found = shardflow.detect_replica_groups(graph, _SEEDED_MESH)
    if not found:
        problems.append(
            "pspec seeded positive: groups {0,1,2},{3,4,5},{6,7} "
            "validated against the slice=2,data=2,fsdp=2 mesh — the "
            "replica-group detector is blind to the slice axis")
    clean_graph = cg.parse_graph(_SEEDED_CROSS_SLICE_HLO)
    noise = shardflow.detect_replica_groups(clean_graph, _SEEDED_MESH)
    if noise:
        problems.append(
            f"pspec seeded negative: the valid cross-slice program was "
            f"flagged — detector over-fires on the slice axis: {noise}")
    split = shardflow.comm_split(clean_graph, None,
                                 mesh_shape=_SEEDED_MESH, n_devices=8)
    if split["dcn_bytes"] <= 0:
        problems.append(
            f"pspec seeded split: cross-slice collectives attributed "
            f"{split['dcn_bytes']} DCN bytes — the ICI/DCN split is "
            f"blind to the slice boundary ({split})")
    if split["ici_bytes"] != 0:
        problems.append(
            f"pspec seeded split: a program whose every collective "
            f"crosses slices charged {split['ici_bytes']} bytes to ICI")
    return problems
