"""Pipeline parallelism over the ``pipe`` mesh axis — GPipe-style microbatch
pipelining, SPMD-formulated.

Not a reference capability (SURVEY.md §3c: PP absent); this closes the last
reserved mesh axis so every axis the framework names is a real strategy.

TPU-native design (no per-stage processes, no send/recv runtime): all
stages run the SAME compiled program under ``shard_map``; stage s holds its
slice of the layer-stacked parameters (``P('pipe')`` on the leading stage
dim), and activations advance one stage per tick through a single
``lax.ppermute`` inside a ``lax.scan``:

  tick t: every stage applies its layers to the activation it holds, then
  the ring rotates outputs forward.  Stage s computes microbatch m at tick
  t = m + s; with M microbatches and S stages the scan runs M + S - 1
  ticks — the classic GPipe bubble of (S-1)/(M+S-1) idle fraction.

The whole pipeline is one differentiable program: ``ppermute`` transposes
to the reverse ``ppermute``, ``scan`` transposes to the reverse-order scan,
so ``jax.grad`` through :func:`pipeline_apply` IS the backward pipeline —
no hand-written schedule.  XLA overlaps the permute DMAs with stage compute
(the collective rides ICI between neighbor chips).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

PyTree = jax.Array | dict | tuple | list


def microbatch(x: jax.Array, n_micro: int) -> jax.Array:
    """[B, ...] → [n_micro, B/n_micro, ...]."""
    if x.shape[0] % n_micro:
        raise ValueError(f"batch {x.shape[0]} not divisible by {n_micro}")
    return x.reshape(n_micro, x.shape[0] // n_micro, *x.shape[1:])


def pipeline_apply(
    stage_fn: Callable[[PyTree, jax.Array], jax.Array],
    stage_params: PyTree,
    micro_x: jax.Array,
    *,
    axis: str = "pipe",
) -> jax.Array:
    """Run the microbatched pipeline; call INSIDE ``shard_map``.

    Args:
      stage_fn: ``(params_for_this_stage, x) -> y`` with ``y.shape ==
        x.shape`` (equal-width stages — the transformer-block case).
      stage_params: this stage's parameter slice.  Callers stack per-stage
        params on a leading dim and pass ``in_specs=P('pipe')`` so shard_map
        delivers stage s its ``[1, ...]`` slice; ``stage_fn`` receives the
        slice with that leading 1 intact (squeeze inside if needed).
      micro_x: ``[n_micro, mb, ...]`` microbatches, replicated over the pipe
        axis (only stage 0 consumes them; replication keeps the SPMD program
        identical on every device).

    Returns ``[n_micro, mb, ...]`` outputs, valid on the LAST stage and
    zeros elsewhere — combine with :func:`last_stage_value` or reduce with a
    ``where``-gated ``psum`` (see tpuframe.parallel.step's pp loss path).
    """
    s = lax.axis_index(axis)
    n_stages = lax.axis_size(axis)
    n_micro = micro_x.shape[0]
    ticks = n_micro + n_stages - 1
    fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    mb_shape = micro_x.shape[1:]
    zero = jnp.zeros(mb_shape, micro_x.dtype)
    # Scan carries must be varying over the pipe axis from the start (each
    # stage holds different activations after one tick, and scan requires a
    # stable carry type) plus whatever axes micro_x already varies over.
    full_vma = dict.fromkeys((*jax.typeof(micro_x).vma, axis))

    def vary(a):
        need = tuple(n for n in full_vma if n not in jax.typeof(a).vma)
        return lax.pcast(a, need, to="varying") if need else a

    def tick(carry, t):
        held, out = carry
        # Stage 0 ingests microbatch t (zeros once the feed is exhausted);
        # everyone else works on what the ring delivered last tick.
        feed = lax.dynamic_index_in_dim(
            micro_x, jnp.minimum(t, n_micro - 1), keepdims=False)
        feed = jnp.where(t < n_micro, feed, zero)
        x = jnp.where(s == 0, feed, held)
        y = stage_fn(stage_params, x)
        # Micro index this stage just finished: m = t - s (valid window
        # 0 <= m < n_micro; the bubble ticks compute on zeros and are
        # discarded by the where below).
        m = t - s
        valid = jnp.logical_and(m >= 0, m < n_micro)
        out = lax.dynamic_update_index_in_dim(
            out, jnp.where(valid, y, lax.dynamic_index_in_dim(
                out, jnp.clip(m, 0, n_micro - 1), keepdims=False)),
            jnp.clip(m, 0, n_micro - 1), axis=0)
        held = lax.ppermute(y, axis, fwd)
        return (held, out), None

    out0 = vary(jnp.zeros_like(micro_x))
    (_, out), _ = lax.scan(tick, (vary(zero), out0), jnp.arange(ticks))
    return out


def last_stage_value(value: jax.Array, *, axis: str = "pipe") -> jax.Array:
    """Replicate the last pipeline stage's ``value`` to every stage (the
    pipeline's outputs live on stage S-1; losses/metrics need them
    everywhere).  select + psum — XLA lowers it to a broadcast from root."""
    s = lax.axis_index(axis)
    n_stages = lax.axis_size(axis)
    masked = jnp.where(s == n_stages - 1, value, jnp.zeros_like(value))
    return lax.psum(masked, axis)
