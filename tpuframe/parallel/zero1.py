"""ZeRO-1 weight-update sharding for the plain data-parallel step.

The flagship DP configs all-reduce gradients and then run a fully
REPLICATED optimizer update: every chip stores the whole optimizer state
(2x param bytes for Adam moments) and applies the whole update — work and
memory that is identical on all n replicas.  *Automatic Cross-Replica
Sharding of Weight Update in Data-Parallel Training* (arXiv:2004.13336,
PAPERS.md) gives the standard fix, ZeRO stage 1:

    all-reduce(grads); update(all params)          # replicated update
        ⇓
    g_i = reduce-scatter(grads)                    # same wire bytes
    p_i = update(param shard i, g_i)               # 1/n compute + state
    params = all-gather(p_i)                       # param bytes out

Same update math (the optimizer must be ELEMENT-WISE — sgd/momentum/
adam(w) qualify; anything coupling across elements of one leaf, e.g.
LARS' per-layer trust ratio or global-norm clipping folded into the
transform, is out of scope and documented so), same total wire traffic
class, but the optimizer state lives sharded — HBM residency drops by
(n-1)/n — and the update compute is 1/n per chip.  This is ROADMAP open
item 1 and the discipline arXiv:2011.03641 credits for DP scaling to pod
sizes.

Layout
------
Each parameter leaf is flattened to 1-D and zero-padded to a multiple of
the weight-update world size ``n`` (pad-to-multiple, so EVERY param tree
takes the sharded path, not just divisible ones — :func:`padding_census`
reports the waste, typically <<1%).  The optimizer state is built by
``tx.init`` over flat ``[padded]`` zero templates (element-wise
optimizers initialize moments to zeros, so this is exactly the replicated
init reshaped) and placed sharded over dim 0; it is NEVER materialized
replicated.  Inside the shard_map'd step each replica then holds:

  - params: the full replicated tree (unchanged — ZeRO-1 shards only the
    update, not the forward/backward);
  - opt_state: flat ``[padded/n]`` moment shards + replicated scalars;
  - grads: local per-replica gradients (the step builder arranges this).

:func:`sharded_update` runs reduce-scatter(mean) → per-shard ``tx.update``
→ ``optax.apply_updates`` → tiled all-gather, slicing each replica's
param shard with ``dynamic_slice`` at the same row-major linear index
``lax.psum_scatter(tiled=True)`` scatters to (so scatter, slice and
gather all agree on who owns which rows).  The gradient norm comes from
shard-local sums of squares + one scalar psum — the padding contributes
zeros, so it is bit-comparable to ``optax.global_norm`` of the averaged
global gradient.

Selection
---------
Per run via ``TPUFRAME_WEIGHT_UPDATE=zero1|replicated`` with the PR 3/5
resolution chain (:func:`resolve`): env > generation-gated tuning DB
(family ``weight_update_*``, searched offline by ``python -m
tpuframe.tune sweep --zero1``) > ``replicated`` default.  The analysis
gate proves the collective swap per build: the ``dp-zero1`` strategy's
HLO audit must show zero all-reduces above the scalar floor and
reduce-scatter + all-gather bytes exactly matching
:func:`tpuframe.analysis.budgets.zero1_budget`.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpuframe.parallel import collectives
from tpuframe.parallel import mesh as mesh_lib

PyTree = Any

MODES = ("replicated", "zero1")
ENV_VAR = "TPUFRAME_WEIGHT_UPDATE"

# jax >= 0.6 vma machinery: params must be pcast varying for local grads
# and gathers can be marked invariant.  On legacy jax (no jax.shard_map)
# check_rep=False already yields local grads and skips replication checks.
_HAS_VMA = hasattr(jax, "typeof") and hasattr(lax, "pcast")


# ---------------------------------------------------------------------------
# Mode selection: env > tuning DB > default (mem.policy.resolve's chain).
# ---------------------------------------------------------------------------


def validate_mode(mode: str) -> str:
    mode = (mode or "replicated").strip().lower()
    if mode not in MODES:
        raise ValueError(f"unknown weight-update mode {mode!r}; "
                         f"expected one of {MODES} ({ENV_VAR})")
    return mode


def mode_from_env(env=os.environ) -> str | None:
    """The explicit ``TPUFRAME_WEIGHT_UPDATE`` override, or None."""
    raw = env.get(ENV_VAR, "").strip()
    return validate_mode(raw) if raw else None


def resolve(program: str | None = None, family: str | None = None,
            default: str = "replicated") -> tuple:
    """``(mode, source)`` for a step program: env override > tuning-DB
    winner (generation-gated; family ``weight_update_*`` persisted by the
    offline sweep) > ``default``.  ``source`` is ``env``/``tune_db``/
    ``default`` — emitted in the ``weight_update`` run event so mode
    provenance is always on record."""
    env_val = mode_from_env()
    if env_val is not None:
        return env_val, "env"
    if program or family:
        from tpuframe.tune import db as tune_db

        db_val = tune_db.resolve_weight_update(program or "", family=family)
        if db_val is not None:
            try:
                return validate_mode(str(db_val)), "tune_db"
            except ValueError:
                pass  # a stale DB row must never break a run
    return validate_mode(default), "default"


# ---------------------------------------------------------------------------
# Pad-to-multiple layout helpers.
# ---------------------------------------------------------------------------


def _size(leaf) -> int:
    return int(np.prod(leaf.shape)) if leaf.shape else 1


def _padded(size: int, n: int) -> int:
    return -(-size // n) * n


def padded_len(size: int, n: int) -> int:
    """Public face of the pad-to-multiple layout: the flat length a
    ``size``-element leaf occupies when sharded ``n`` ways.

    This is also the elastic-resize contract (:mod:`tpuframe.elastic`):
    the pad region is zero at init (``tx.init`` over zero templates) and
    stays zero forever (``flat_pad`` pads grads with zeros; the mean of
    zeros reduce-scatters to zero; element-wise optimizers keep zero
    moments on zero grads), so resharding a flat moment vector n→n′ is
    EXACTLY truncate-or-zero-pad to ``padded_len(size, n')`` — no data
    beyond the true ``size`` ever carries state.  ``elastic.check()``
    cross-checks its own mirror of this arithmetic against this function
    so the two layouts can never drift apart."""
    return _padded(int(size), int(n))


def world_size(mesh: Mesh, axes=None) -> int:
    """Number of weight-update shards: the product of ``axes`` sizes.
    The default is the mesh's own data-parallel axes (slice-aware: on a
    hierarchical multi-slice mesh the DCN ``slice`` axis shards too)."""
    if axes is None:
        axes = mesh_lib.batch_axes(mesh)
    return int(np.prod([mesh.shape[a] for a in axes if a in mesh.shape]))


def padded_bytes(params: PyTree, n: int) -> int:
    """Total bytes of the flat pad-to-``n`` layout — the exact operand
    bytes of the step's reduce-scatter AND result bytes of its all-gather
    (grads are cast to param dtype before the scatter)."""
    return int(sum(_padded(_size(p), n) * np.dtype(p.dtype).itemsize
                   for p in jax.tree.leaves(params)))


def padding_census(params: PyTree, n: int) -> dict:
    """Per-leaf padding accounting for the pad-to-multiple layout.

    Returned dict: ``leaves`` rows (name/shape/dtype/size/padded/
    pad_waste/padded_bytes) + totals and ``waste_frac``.  Committed with
    the sweep report so the documented-padding-census requirement is an
    artifact, not a claim."""
    rows = []
    total = padded_total = total_b = padded_b = 0
    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    for path, leaf in flat:
        size, padded = _size(leaf), _padded(_size(leaf), n)
        item = np.dtype(leaf.dtype).itemsize
        rows.append({
            "name": jax.tree_util.keystr(path),
            "shape": tuple(int(d) for d in leaf.shape),
            "dtype": str(np.dtype(leaf.dtype)),
            "size": size,
            "padded": padded,
            "pad_waste": padded - size,
            "padded_bytes": padded * item,
        })
        total += size
        padded_total += padded
        total_b += size * item
        padded_b += padded * item
    return {
        "n_shards": int(n),
        "leaves": rows,
        "total_elems": total,
        "padded_elems": padded_total,
        "total_bytes": total_b,
        "padded_bytes": padded_b,
        "waste_frac": (padded_total - total) / max(total, 1),
    }


# ---------------------------------------------------------------------------
# Sharded optimizer state: built in the flat [padded] layout, placed
# sharded, never materialized replicated.
# ---------------------------------------------------------------------------


def init_opt_state(tx: optax.GradientTransformation, params: PyTree,
                   n: int) -> PyTree:
    """``tx.init`` over flat ``[pad-to-n]`` zero templates of ``params``.

    Element-wise optimizers (sgd/momentum/adam(w)) initialize moments to
    zeros independent of param values, so this is the replicated init in
    the sharded layout — the exact-equivalence property the golden-loss
    tests pin.  ``params`` may be real arrays or ShapeDtypeStructs (for
    ``jax.eval_shape`` callers)."""
    return tx.init(jax.tree.map(
        lambda p: jnp.zeros((_padded(_size(p), n),), p.dtype), params))


def _is_opt_leaf_path(path) -> bool:
    head = path[0] if path else None
    return getattr(head, "name", None) == "opt_state"


def state_partition_specs(state, axes=mesh_lib.BATCH_AXES) -> PyTree:
    """Per-leaf PartitionSpec tree over a TrainState in ZeRO-1 layout:
    opt_state moment vectors shard dim 0 over ``axes``; everything else
    (params, step, rng, model_state, opt scalars) is replicated.  Built
    per-leaf because ``tx.init``'s tree structure is optimizer-dependent
    — the step builder calls this inside its jit trace."""
    axes = tuple(axes)

    def spec(path, leaf):
        if _is_opt_leaf_path(path) and getattr(leaf, "ndim", 0) >= 1:
            return P(axes)
        return P()

    return jax.tree_util.tree_map_with_path(spec, state)


def state_shardings(state, mesh: Mesh,
                    axes=mesh_lib.BATCH_AXES) -> PyTree:
    """NamedSharding twin of :func:`state_partition_specs`."""
    return jax.tree.map(lambda s: NamedSharding(mesh, s),
                        state_partition_specs(state, axes))


def check_state_layout(state, n: int):
    """Trace-time guard: a replicated ``TrainState.create`` opt_state
    reaching the zero1 step would shard param-shaped moments down dim 0
    and fail later with an opaque shape error — catch it here instead."""
    sizes = {_padded(_size(p), n) for p in jax.tree.leaves(state.params)}
    for leaf in jax.tree.leaves(state.opt_state):
        if getattr(leaf, "ndim", 0) == 0:
            continue
        if leaf.ndim != 1 or _size(leaf) not in sizes:
            raise ValueError(
                f"opt_state leaf {tuple(leaf.shape)} is not in the ZeRO-1 "
                f"flat pad-to-{n} layout — build the state with "
                f"zero1.make_state (or init_opt_state), not "
                f"TrainState.create, when weight_update='zero1'")
    return state


def make_state(params: PyTree, tx: optax.GradientTransformation,
               mesh: Mesh | None = None, *, axes=None,
               model_state: PyTree | None = None,
               rng: jax.Array | None = None):
    """``TrainState.create`` twin for the zero1 path: the optimizer state
    is created directly in the sharded layout — with a mesh, a jitted
    init with sharded ``out_shardings`` so the ``[padded]`` moments are
    born distributed and no replicated copy ever exists; params/step/rng/
    model_state are placed replicated (ZeRO-1 keeps them so).  ``axes``
    defaults to the mesh's own data-parallel axes (slice-aware)."""
    from tpuframe.parallel import step as step_lib

    if axes is None:
        axes = mesh_lib.BATCH_AXES if mesh is None \
            else mesh_lib.batch_axes(mesh)
    n = world_size(mesh, axes) if mesh is not None else 1
    if mesh is None:
        opt = init_opt_state(tx, params, n)
    else:
        struct = jax.eval_shape(lambda: init_opt_state(tx, params, n))
        out_sh = jax.tree.map(
            lambda l: NamedSharding(
                mesh, P(tuple(axes)) if l.ndim >= 1 else P()), struct)
        opt = jax.jit(lambda: init_opt_state(tx, params, n),
                      out_shardings=out_sh)()
    state = step_lib.TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        opt_state=opt,
        model_state={} if model_state is None else model_state,
        rng=jax.random.key(0) if rng is None else rng,
    )
    if mesh is None:
        return state
    repl = mesh_lib.replicated_sharding(mesh)

    def place(path, leaf):
        if _is_opt_leaf_path(path):
            return leaf  # already sharded by the jitted init
        return mesh_lib.host_device_put(leaf, repl)

    return jax.tree_util.tree_map_with_path(place, state)


# ---------------------------------------------------------------------------
# The sharded update itself (runs inside the shard_map'd step body).
# ---------------------------------------------------------------------------


def _psum_marked(x, bound: tuple[str, ...]):
    """psum over the axes ``x`` actually varies on (vma-aware on new jax;
    sized-axes on legacy, where check_rep=False tracks nothing)."""
    if _HAS_VMA:
        ax = tuple(a for a in bound if a in jax.typeof(x).vma)
    else:
        ax = collectives._sized_axes(bound)
    # Scalar grad-norm reduction: always under every wire's size floor.
    return lax.psum(x, ax) if ax else x  # tf-lint: ok[TF115] scalar reduce


def _gather_full(shard: jax.Array, bound: tuple[str, ...]) -> jax.Array:
    """Tiled all-gather of the updated param shard, marked replication-
    invariant where this jax can express it (every replica gathers the
    identical full vector)."""
    return collectives.allgather_invariant(shard, bound)


def sharded_update(tx: optax.GradientTransformation, axes,
                   params: PyTree, opt_state: PyTree,
                   grads: PyTree, *,
                   wire_format: str = "fp",
                   fusion_threshold: int | None = None,
                   hier: bool = False,
                   wire_format_dcn: str = "fp",
                   ) -> tuple[PyTree, PyTree, jax.Array]:
    """reduce-scatter → 1/n optimizer update → all-gather.

    Called from the step tail with LOCAL per-replica gradients (the step
    builder keeps them unreduced on the zero1 path).  Returns
    ``(new_params, new_opt_state, grad_norm)``; ``opt_state`` is the
    per-replica shard view (``[padded/n]`` moments) and comes back in the
    same layout.  The reduce-scatter averages, so the update consumes the
    same global mean gradient as the replicated path.

    ``wire_format="int8-block"`` (tpuframe.parallel.quantwire,
    arXiv:2506.17615) swaps both gradient-sized collectives for their
    block-quantized twins.  The scatter quantizes the local gradient —
    ordinary gradient noise.  The gather CANNOT quantize the raw params:
    the gathered vector overwrites the replicated master copy, so 8-bit
    re-gridding there would quantize the *weights* themselves every
    step.  Instead it gathers the quantized update DELTA
    (``new_shard - shard``) and adds it to the replicated old params —
    masters keep full-precision accumulation, the per-step wire error is
    bounded by one quantization step of the (small) update, and the
    invariant-old + invariant-gather sum stays replication-invariant.
    Leaves under ``quantwire.MIN_QUANT_ELEMS`` keep the fp wire on both
    sides (the derived-budget floors are sized to ignore them).

    ``fusion_threshold`` (fp wire only — ``make_train_step`` rejects the
    int8 combination) buckets BOTH gradient-sized collectives Horovod-
    style (:mod:`tpuframe.parallel.fusion`): padded flat grads pack
    shard-aligned (``fusion.pack_for_scatter``) into ≤threshold-byte
    buffers, ONE reduce-scatter per bucket in, ONE all-gather per bucket
    out, every bucket's collective issued before any bucket is consumed.
    Wire bytes are EXACTLY the per-leaf path's pad-to-multiple totals
    (the zero1 budget holds unchanged); only the op count drops from
    n_leaves to n_buckets.

    ``hier=True`` on a multi-slice mesh (``axes`` includes the slice
    axis) swaps both gradient-sized collectives for their two-stage
    twins (:mod:`tpuframe.parallel.hier`, arXiv:1909.09756): the scatter
    runs in-slice over ICI first then cross-slice over DCN on the
    1/n_inner chunk, the gather inverts slice-first — so only 1/n_inner
    of the bytes touch the slow fabric, at the SAME total padded bytes.
    Chunk ownership becomes INNER-MAJOR (member (slice s, inner j) owns
    chunk ``j*n_slice + s``): the on-disk order of a sharded opt-state
    dump therefore permutes vs the flat lowering, but the flat
    ``[padded]`` global layout — what elastic resize and checkpoints
    address — is unchanged.  ``wire_format_dcn="int8-block"`` quantizes
    the DCN legs alone (scatter payload + update-delta gather; the fp
    master invariant above holds leg-wise), gated per leaf on the
    CHUNK clearing ``quantwire.MIN_QUANT_ELEMS`` — the chunk is what
    rides the wire.  Single-slice (or ``n_inner == 1``) meshes
    degenerate to the flat lowering."""
    bound = collectives._bound_axes(axes)
    if not bound:
        # World of 1 (unmapped): the sharded path degenerates to the
        # replicated update on the flat layout's single shard.
        updates, new_opt = tx.update(grads, opt_state, params)
        return (optax.apply_updates(params, updates), new_opt,
                optax.global_norm(grads))
    n = 1
    for a in bound:
        n *= lax.axis_size(a)

    from tpuframe.parallel import hier as hier_lib
    from tpuframe.parallel import quantwire

    inner, has_slice = hier_lib.split_axes(bound)
    n_inner = quantwire._axis_prod(collectives._sized_axes(inner)) \
        if inner else 1
    # Two-stage only when both levels are real; otherwise the flat
    # lowering IS the hierarchy (one level is trivial).
    two_stage = bool(hier) and has_slice and n_inner > 1
    idx = hier_lib.linear_index(inner) if two_stage \
        else collectives._linear_index(bound)

    def flat_pad(t):
        flat = t.reshape(-1)
        pad = _padded(flat.size, n) - flat.size
        return jnp.pad(flat, (0, pad)) if pad else flat

    def quantized(g):
        return (wire_format == "int8-block"
                and _padded(_size(g), n) >= quantwire.MIN_QUANT_ELEMS)

    def dcn_quantized(g):
        # Gate on the CHUNK — the payload the DCN legs actually carry.
        return (two_stage and wire_format_dcn == "int8-block"
                and _padded(_size(g), n) // n_inner
                >= quantwire.MIN_QUANT_ELEMS)

    # Grads in: ONE reduce-scatter per leaf (operand = padded grad bytes
    # — the wire cost the dp-zero1 CommBudget declares), averaging over
    # the world.  Zero padding reduces to zero.  On the int8 wire the
    # operand is the s8 payload + scales instead (~1/4 the bytes).
    # With ``fusion_threshold`` the leaves pack into shard-aligned
    # buckets first — one scatter per bucket, all issued before any
    # shard is unpacked.
    def scatter_fp(flat):
        if two_stage:
            return hier_lib.scatter_mean(flat, inner)
        return collectives.reduce_scatter(flat, bound, average=True)

    def scatter(g):
        if two_stage:
            return hier_lib.scatter_mean(
                flat_pad(g), inner,
                wire_format_dcn=("int8-block" if dcn_quantized(g)
                                 else "fp"))
        if quantized(g):
            return quantwire.reduce_scatter_mean(flat_pad(g), bound)
        return collectives.reduce_scatter(flat_pad(g), bound, average=True)

    fused = (fusion_threshold is not None and wire_format == "fp"
             and wire_format_dcn == "fp")
    if fused:
        from tpuframe.parallel import fusion

        g_leaves, g_def = jax.tree.flatten(grads)
        g_flat = [flat_pad(g) for g in g_leaves]
        buckets = fusion._bucketize(g_flat, fusion_threshold)
        issued = []
        for bucket in buckets:
            if len(bucket) == 1:
                issued.append(scatter_fp(g_flat[bucket[0]]))
            else:
                issued.append(scatter_fp(
                    fusion.pack_for_scatter([g_flat[i] for i in bucket],
                                            n)))
        g_out = [None] * len(g_leaves)
        for shard, bucket in zip(issued, buckets):
            if len(bucket) == 1:
                g_out[bucket[0]] = shard
                continue
            parts = fusion.split_scattered(
                shard, [g_flat[i].size // n for i in bucket])
            for i, part in zip(bucket, parts):
                g_out[i] = part
        gshard = jax.tree.unflatten(g_def, g_out)
    else:
        gshard = jax.tree.map(scatter, grads)
    # Params are replicated, so each replica's shard is a free local
    # slice at the same row-major linear index the scatter used.
    def param_shard(t):
        flat = flat_pad(t)
        chunk = flat.size // n
        return lax.dynamic_slice(flat, (idx * chunk,), (chunk,))

    pshard = jax.tree.map(param_shard, params)
    updates, new_opt = tx.update(gshard, opt_state, pshard)
    new_pshard = optax.apply_updates(pshard, updates)

    # ||mean grad||: shard-local sum of squares + one scalar psum (under
    # every audit floor).  Padding contributes exact zeros.
    sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
             for g in jax.tree.leaves(gshard))
    grad_norm = jnp.sqrt(_psum_marked(sq, bound))

    # Params out: tiled all-gather (result = padded param bytes), then
    # un-pad and fold back to the original shapes.  On the int8 wire the
    # update DELTA is gathered quantized and added to the replicated old
    # params (see docstring — masters never lose precision).
    def gather_fp(shard):
        if two_stage:
            return hier_lib.gather(shard, inner)
        return _gather_full(shard, bound)

    def regather(old_shard, shard, like):
        if two_stage and dcn_quantized(like):
            # Two-stage delta gather: quantized over DCN, fp over ICI.
            delta = hier_lib.gather_delta(shard - old_shard, inner)
            full = flat_pad(like) + delta.astype(like.dtype)
        elif two_stage:
            full = hier_lib.gather(shard, inner)
        elif quantized(like):
            delta = quantwire.all_gather(shard - old_shard, bound)
            full = flat_pad(like) + delta.astype(like.dtype)
        else:
            full = _gather_full(shard, bound)
        return full[:_size(like)].reshape(like.shape)

    if fused:
        # Params out, bucketed: the same buckets the scatter used (grads
        # were cast to param dtype upstream, so kinds match), one
        # all-gather per bucket, every gather issued before any unpack.
        p_leaves = jax.tree.leaves(params)
        s_leaves, s_def = jax.tree.flatten(new_pshard)
        gathered = []
        for bucket in buckets:
            if len(bucket) == 1:
                gathered.append(gather_fp(s_leaves[bucket[0]]))
            else:
                gathered.append(gather_fp(
                    jnp.concatenate([s_leaves[i] for i in bucket])))
        p_out = [None] * len(p_leaves)
        for full, bucket in zip(gathered, buckets):
            if len(bucket) == 1:
                i = bucket[0]
                p_out[i] = full[:_size(p_leaves[i])].reshape(
                    p_leaves[i].shape)
                continue
            parts = fusion.split_gathered(
                full, n, [g_flat[i].size // n for i in bucket])
            for i, part in zip(bucket, parts):
                p_out[i] = part[:_size(p_leaves[i])].reshape(
                    p_leaves[i].shape)
        new_params = jax.tree.unflatten(s_def, p_out)
    else:
        new_params = jax.tree.map(regather, pshard, new_pshard, params)
    return new_params, new_opt, grad_norm


# ---------------------------------------------------------------------------
# Analysis-gate self-check.
# ---------------------------------------------------------------------------

# Files whose optimizer updates must route through the make_train_step /
# zero1 seam — TF110's scope, self-linted so the gate fails closed if a
# stray tx.update/apply_updates sneaks into harness or parallel code and
# silently bypasses the weight-update layout decision.
_TF110_SELF_LINT = (
    "parallel",
    "train.py",
)


def check() -> list:
    """Self-check for the ``python -m tpuframe.analysis`` CI gate.
    Returns problem strings; [] means healthy."""
    problems: list[str] = []
    # 1. the mode registry and env parsing agree
    for m in MODES:
        try:
            validate_mode(m)
        except Exception as e:  # noqa: BLE001 — report, don't crash CI
            problems.append(f"mode {m!r} failed validation: {e}")
    try:
        mode_from_env()
    except ValueError as e:
        problems.append(f"{ENV_VAR} is set to an invalid mode: {e}")
    # 2. pad-to-multiple layout arithmetic stays self-consistent
    probe = {"w": jax.ShapeDtypeStruct((3, 5), jnp.float32),
             "b": jax.ShapeDtypeStruct((7,), jnp.float32)}
    census = padding_census(probe, 8)
    if any(row["padded"] % 8 for row in census["leaves"]):
        problems.append("padding census produced a non-multiple shard")
    if census["padded_bytes"] != padded_bytes(probe, 8):
        problems.append("padding census / padded_bytes disagree")
    # 3. TF110 self-lint: optimizer updates stay at the seam
    from tpuframe.analysis.source_lint import lint_paths

    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    paths = [os.path.join(pkg_root, p) for p in _TF110_SELF_LINT]
    for f in lint_paths([p for p in paths if os.path.exists(p)]):
        if f.rule == "TF110":
            problems.append(f"self-lint: {f}")
    return problems
