"""Distributed core: bootstrap, mesh, collectives, Horovod-compatible facade.

TPU-native replacement for the reference's L0–L2 stack (SURVEY.md §2):
Horovod's C++ op queue / coordinator / fusion buffer and its NCCL/MPI/Gloo
transports become (a) a one-call process bootstrap (``initialize``), (b) a
named device mesh (``make_mesh``), and (c) XLA collectives emitted inside
compiled SPMD programs (``collectives``, ``step``).
"""

from tpuframe.parallel.bootstrap import (  # noqa: F401
    initialize,
    is_initialized,
    process_count,
    process_index,
    shutdown,
)
from tpuframe.parallel.mesh import (  # noqa: F401
    SLICE_AXIS,
    MeshSpec,
    batch_axes,
    best_effort_mesh,
    make_mesh,
)
from tpuframe.parallel.pspec import (  # noqa: F401
    ParallelSpec,
    parse_spec,
)
from tpuframe.parallel.collectives import (  # noqa: F401
    allgather,
    allreduce,
    alltoall,
    broadcast,
    cross_replica_mean,
    ring_permute,
)
