"""Slice-aware two-level gradient collectives (the DCN-crushing lowering).

PERF §23 priced the pod-scale cost structure: on the composed
``dp=2,fsdp=2;slices=2`` spec 21% of the wire bytes ride the ~32x
slower DCN fabric and account for 87% of modeled comm time.  The
MLPerf-pods recipe (*Scale MLPerf-0.6 models on Google TPU-v3 Pods*,
arXiv:1909.09756) attacks exactly that term by restructuring the flat
cross-slice gradient mean into three fabric-matched phases:

  reduce-scatter(mean) over the in-slice axes      [ICI, full bytes]
  all-reduce(mean) over the slice axis on the      [DCN, 1/n_inner of
      1/n_inner shard                               the bytes]
  all-gather over the in-slice axes                [ICI, full bytes]

Only the middle leg crosses the data-center network, and it carries
``1/n_inner`` of the payload — the DCN byte column drops by the
in-slice world size.  Because the DCN leg is its own collective, the
wire format becomes *per-fabric*: the EQuARX int8-block wire
(:mod:`tpuframe.parallel.quantwire`), an honest loss at ICI speeds
(PERF §20), rides the slow leg alone for another ~4x while ICI stays
full precision.

Numerically the two-level mean equals the flat mean up to float
reassociation: the in-slice reduce-scatter divides by ``n_inner``, the
cross-slice mean by ``n_slice``, so every element is the sum over all
``N`` replicas divided by ``N`` — the golden-loss tests pin hier ==
flat to tight tolerance (fp DCN leg) and to the §20 int8 tolerance
(quantized DCN leg).

Like every other gradient-path modifier, the lowering is resolved per
program (env ``TPUFRAME_HIER`` > generation-gated tune DB, family
``hier_collectives`` > flat) and this module is a *seam*: the TF124
lint keeps collectives that name the ``slice`` axis out of every other
module, so cross-slice traffic is always the two-level shape (or a
signed exception).
"""

from __future__ import annotations

import os
from typing import Any, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from tpuframe.parallel import collectives
from tpuframe.parallel import mesh as mesh_lib
from tpuframe.parallel import quantwire

AxisName = str | Sequence[str]
PyTree = Any

MODES = ("flat", "hier")
ENV_VAR = "TPUFRAME_HIER"
#: tune-DB family ``tune sweep --hier`` persists winners under.
DB_FAMILY = "hier_collectives"

SLICE_AXIS = mesh_lib.SLICE_AXIS

# Pre-vma jax (< 0.6, legacy shard_map with check_rep=False) tracks no
# replication state — same compat split as quantwire.
_HAS_VMA = quantwire._HAS_VMA


# ---------------------------------------------------------------------------
# Mode selection: env > tuning DB > default (the modifier chain idiom).
# ---------------------------------------------------------------------------


def validate_mode(mode: str) -> str:
    mode = (mode or "flat").strip().lower()
    if mode not in MODES:
        raise ValueError(f"unknown hierarchical-collective mode {mode!r}; "
                         f"expected one of {MODES} ({ENV_VAR})")
    return mode


def mode_from_env(env=os.environ) -> str | None:
    """The explicit ``TPUFRAME_HIER`` override, or None."""
    raw = env.get(ENV_VAR, "").strip()
    return validate_mode(raw) if raw else None


def resolve(program: str | None = None, family: str | None = None,
            default: str = "flat") -> tuple:
    """``(mode, source)`` for a step program: env override > tuning-DB
    winner (generation-gated; family ``hier_collectives`` persisted by
    ``python -m tpuframe.tune sweep --hier``) > ``default``.  ``source``
    is ``env``/``tune_db``/``default``."""
    env_val = mode_from_env()
    if env_val is not None:
        return env_val, "env"
    if program or family:
        from tpuframe.tune import db as tune_db

        db_val = tune_db.resolve_hier(program or "", family=family)
        if db_val is not None:
            try:
                return validate_mode(str(db_val)), "tune_db"
            except ValueError:
                pass  # a stale DB row must never break a run
    return validate_mode(default), "default"


# ---------------------------------------------------------------------------
# The two-level mean.
# ---------------------------------------------------------------------------


def split_axes(axes: AxisName) -> tuple[tuple[str, ...], bool]:
    """``(inner_axes, has_slice)`` — the bound reduction axes with the
    slice axis factored out.  ``has_slice`` False means the mesh is
    single-slice and the two-level lowering degenerates to flat."""
    bound = collectives._bound_axes(axes)
    inner = tuple(a for a in bound if a != SLICE_AXIS)
    return inner, SLICE_AXIS in bound


def _dcn_mean(shard: jax.Array, *, wire_format_dcn: str, block: int,
              min_elems: int) -> jax.Array:
    """The cross-slice leg: mean over the slice axis in the resolved
    DCN wire format.  The int8-block wire keeps quantwire's own size
    floor — a sub-floor shard stays fp there too."""
    if wire_format_dcn == "int8-block":
        return quantwire.all_reduce_mean(shard, SLICE_AXIS, block=block,
                                         min_elems=min_elems)
    return lax.pmean(shard, SLICE_AXIS)


def hier_mean(tree: PyTree, axes: AxisName, *,
              wire_format_dcn: str = "fp",
              block: int = quantwire.DEFAULT_BLOCK,
              min_elems: int = quantwire.MIN_QUANT_ELEMS) -> PyTree:
    """Two-level cross-replica gradient mean over ``axes``.

    Per leaf: pad to a multiple of the in-slice world, reduce-scatter
    (mean) over the ICI axes, mean the 1/n_inner shard over the slice
    axis in ``wire_format_dcn``, all-gather the shard back over ICI,
    unpad.  Leaves under ``min_elems`` (and any reduction whose inner
    world is 1) fall back to a flat mean — for a sub-floor leaf the
    two-level shape doubles the collective count for no byte win, and
    with ``n_inner == 1`` every byte crosses DCN regardless (the DCN
    wire format still applies there).

    The result is invariant over all bound axes, matching
    ``average_gradients``' contract."""
    inner, has_slice = split_axes(axes)
    if not has_slice:
        # Single-slice mesh: nothing crosses DCN, flat is the lowering.
        return collectives.average_gradients(tree, axis=inner)
    wire_format_dcn = quantwire.validate_format(wire_format_dcn)

    def _hmean(g):
        vma = jax.typeof(g).vma if _HAS_VMA else frozenset((*inner,
                                                            SLICE_AXIS))
        varying_inner = tuple(a for a in inner if a in vma)
        sized = collectives._sized_axes(varying_inner)
        n_inner = quantwire._axis_prod(sized)
        if n_inner == 1 or g.size < max(min_elems, 1):
            out = _dcn_mean(g, wire_format_dcn=wire_format_dcn,
                            block=block, min_elems=min_elems)
            if varying_inner:
                out = lax.pmean(out, varying_inner)
            elif _HAS_VMA:
                out = collectives._clear_unit_axes(out, inner)
            return out.astype(g.dtype)
        flat = quantwire._pad_to(g.astype(jnp.float32).reshape(-1),
                                 n_inner)
        if _HAS_VMA:
            flat = collectives._vary_over(flat, sized)
        # ICI: in-slice reduce-scatter(mean) — divides by n_inner.
        shard = collectives.reduce_scatter(flat, sized, average=True)
        # DCN: mean the 1/n_inner shard across slices — divides by
        # n_slice, completing the /N of the flat mean.
        shard = _dcn_mean(shard, wire_format_dcn=wire_format_dcn,
                          block=block, min_elems=min_elems)
        # ICI: gather the meaned shard back; tiled concat inverts the
        # scatter's contiguous chunk ownership exactly.
        full = collectives.allgather_invariant(shard, sized)
        out = full[:g.size].reshape(g.shape)
        if _HAS_VMA:
            out = collectives._clear_unit_axes(out, (*inner, SLICE_AXIS))
        return out.astype(g.dtype)

    return jax.tree.map(_hmean, tree)


# ---------------------------------------------------------------------------
# Fused (bucketed) two-level mean — the fusion_threshold compose.
# ---------------------------------------------------------------------------


def fused_hier_mean(tree: PyTree, axes: AxisName, *,
                    threshold_bytes: int,
                    wire_format_dcn: str = "fp",
                    block: int = quantwire.DEFAULT_BLOCK,
                    min_elems: int = quantwire.MIN_QUANT_ELEMS) -> PyTree:
    """Two-level mean with Horovod-style fusion buckets: leaves pack into
    ≤``threshold_bytes`` same-kind buffers (``fusion._bucketize``'s exact
    buckets) and each buffer takes ONE three-phase lowering — rs(mean)
    over ICI, cross-slice mean of the 1/n_inner shard over DCN, ag back —
    so the collective count drops from 3·n_leaves to 3·n_buckets at the
    same wire bytes.  ``threshold_bytes <= 0`` → one lowering per leaf.
    Degenerates to ``fusion.staged_pmean`` on a single-slice mesh."""
    from tpuframe.parallel import fusion

    inner, has_slice = split_axes(axes)
    if not has_slice:
        return fusion.staged_pmean(tree, axes,
                                   threshold_bytes=threshold_bytes)
    leaves, treedef = jax.tree.flatten(tree)
    if not leaves:
        return tree
    if threshold_bytes <= 0:
        buckets = [[i] for i in range(len(leaves))]
    else:
        buckets = fusion._bucketize(leaves, threshold_bytes)
    out: list = [None] * len(leaves)
    for bucket in buckets:
        if len(bucket) == 1:
            i = bucket[0]
            out[i] = hier_mean(leaves[i], axes,
                               wire_format_dcn=wire_format_dcn,
                               block=block, min_elems=min_elems)
            continue
        flat = jnp.concatenate([leaves[i].reshape(-1) for i in bucket])
        red = hier_mean(flat, axes, wire_format_dcn=wire_format_dcn,
                        block=block, min_elems=min_elems)
        off = 0
        for i in bucket:
            sz = leaves[i].size
            out[i] = red[off:off + sz].reshape(leaves[i].shape)
            off += sz
    return jax.tree.unflatten(treedef, out)


# ---------------------------------------------------------------------------
# ZeRO-1 seam: two-stage scatter/gather primitives.  They live HERE, not
# in zero1.py, so TF124 holds — every collective naming the slice axis
# stays at this seam.
# ---------------------------------------------------------------------------


def linear_index(inner_axes: tuple[str, ...]):
    """Chunk index member (slice ``s``, inner ``j``) owns under the
    two-stage scatter: ``j * n_slice + s`` — inner-major, because the
    in-slice scatter runs first and the cross-slice scatter subdivides
    each in-slice chunk.  :func:`gather` inverts in slice-then-inner
    order so the same index recovers the same rows."""
    return collectives._linear_index((*tuple(inner_axes), SLICE_AXIS))


def scatter_mean(flat: jax.Array, inner_axes: tuple[str, ...], *,
                 wire_format_dcn: str = "fp",
                 block: int = quantwire.DEFAULT_BLOCK) -> jax.Array:
    """Two-stage reduce-scatter(mean) of a flat operand padded to a
    multiple of the FULL world ``n_inner * n_slice``: in-slice rs(mean)
    over ICI (divides by n_inner, full bytes on the fast fabric), then
    cross-slice rs(mean) of the 1/n_inner chunk over DCN in the resolved
    DCN wire format.  Member (s, j) receives chunk
    ``linear_index(inner_axes)`` of the n chunks — zero1's dynamic-slice
    index math works unchanged with that index."""
    chunk = collectives.reduce_scatter(flat, inner_axes, average=True)
    if wire_format_dcn == "int8-block":
        return quantwire.reduce_scatter_mean(chunk, SLICE_AXIS, block=block)
    return collectives.reduce_scatter(chunk, SLICE_AXIS, average=True)


def gather(shard: jax.Array, inner_axes: tuple[str, ...]) -> jax.Array:
    """Inverse of :func:`scatter_mean`'s ownership: all-gather over the
    slice axis FIRST (DCN, 1/n_inner of the bytes, reassembling each
    in-slice chunk), then over the inner axes (ICI, full bytes)."""
    chunk = collectives.allgather_invariant(shard, SLICE_AXIS)
    return collectives.allgather_invariant(chunk, inner_axes)


def gather_delta(delta_shard: jax.Array, inner_axes: tuple[str, ...], *,
                 block: int = quantwire.DEFAULT_BLOCK) -> jax.Array:
    """int8-DCN twin of :func:`gather` for zero1's update-delta trick:
    the cross-slice (DCN) leg gathers the quantized delta shard, the
    in-slice (ICI) leg stays fp — masters accumulate full precision and
    only the slow leg pays the one-quantization-step error."""
    chunk = quantwire.all_gather(delta_shard, SLICE_AXIS, block=block)
    return collectives.allgather_invariant(chunk, inner_axes)


# ---------------------------------------------------------------------------
# Gate self-check: seeded flat-vs-hier positives against the ICI/DCN
# split, numeric hier == flat, and the TF124 seam self-lint.
# ---------------------------------------------------------------------------

# The anti-pattern this module exists to remove: one flat all-reduce
# whose single group spans both slices of an 8-device slice=2 mesh.
# comm_split must charge its FULL bytes to DCN — if it reads as ICI the
# gate is blind to the very term the lowering crushes.
_SEEDED_FLAT_HLO = """\
HloModule seeded_hier_flat_cross_slice

%add (a: f32[], b: f32[]) -> f32[] {
  %a = f32[] parameter(0)
  %b = f32[] parameter(1)
  ROOT %r = f32[] add(f32[] %a, f32[] %b)
}

ENTRY %main (p0: f32[65536]) -> f32[65536] {
  %p0 = f32[65536]{0} parameter(0)
  ROOT %ar = f32[65536]{0} all-reduce(f32[65536]{0} %p0), replica_groups={{0,1,2,3,4,5,6,7}}, to_apply=%add
}
"""

# Its two-level twin: in-slice reduce-scatter ({0..3},{4..7} — iota
# [2,4]<=[8]), cross-slice all-reduce on the 1/4 shard ({0,4},{1,5},
# {2,6},{3,7} — strided iota), in-slice all-gather back.  Only the
# shard-sized middle leg may land in the DCN column.
_SEEDED_HIER_HLO = """\
HloModule seeded_hier_two_level

%add (a: f32[], b: f32[]) -> f32[] {
  %a = f32[] parameter(0)
  %b = f32[] parameter(1)
  ROOT %r = f32[] add(f32[] %a, f32[] %b)
}

ENTRY %main (p0: f32[65536]) -> f32[65536] {
  %p0 = f32[65536]{0} parameter(0)
  %rs = f32[16384]{0} reduce-scatter(f32[65536]{0} %p0), replica_groups=[2,4]<=[8], dimensions={0}, to_apply=%add
  %ar = f32[16384]{0} all-reduce(f32[16384]{0} %rs), replica_groups=[4,2]<=[2,4]T(1,0), to_apply=%add
  ROOT %ag = f32[65536]{0} all-gather(f32[16384]{0} %ar), replica_groups=[2,4]<=[8], dimensions={0}
}
"""

_SEEDED_MESH = {"slice": 2, "data": 4}
_SEEDED_N_DEVICES = 8


def _seeded_split_problems() -> list:
    from tpuframe.analysis import collective_graph as cg
    from tpuframe.analysis import shardflow

    problems = []
    flat = shardflow.comm_split(cg.parse_graph(_SEEDED_FLAT_HLO), None,
                                mesh_shape=_SEEDED_MESH,
                                n_devices=_SEEDED_N_DEVICES)
    hier = shardflow.comm_split(cg.parse_graph(_SEEDED_HIER_HLO), None,
                                mesh_shape=_SEEDED_MESH,
                                n_devices=_SEEDED_N_DEVICES)
    if flat["dcn_bytes"] != 65536 * 4:
        problems.append(
            f"hier seeded positive: the flat cross-slice all-reduce "
            f"charged {flat['dcn_bytes']} bytes to DCN, expected "
            f"{65536 * 4} — comm_split is blind to the flat anti-pattern")
    if hier["dcn_bytes"] != 16384 * 4:
        problems.append(
            f"hier seeded twin: the two-level lowering charged "
            f"{hier['dcn_bytes']} bytes to DCN, expected {16384 * 4} "
            f"(the 1/n_inner shard) — the split mis-attributes a level")
    # Census ruler: a collective is priced at its RESULT bytes when no
    # hlo_audit report is supplied — the rs row is shard-sized, the ag
    # row full-sized.
    if hier["ici_bytes"] != (16384 + 65536) * 4:
        problems.append(
            f"hier seeded twin: the in-slice scatter+gather charged "
            f"{hier['ici_bytes']} bytes to ICI, expected "
            f"{(16384 + 65536) * 4}")
    if not problems and flat["dcn_bytes"] != 4 * hier["dcn_bytes"]:
        problems.append(
            f"hier seeded pair: DCN ratio flat/hier is "
            f"{flat['dcn_bytes']}/{hier['dcn_bytes']}, expected the "
            f"n_inner=4 reduction")
    return problems


def _numeric_problems() -> list:
    """hier_mean == flat pmean on the real multi-device backend (the
    fusion gate's psum-linearity idiom).  Skips quietly below 4 devices
    — the analysis child always runs with 8."""
    import numpy as np
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    if jax.device_count() < 4 or jax.device_count() % 2:
        return []
    n = jax.device_count()
    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec(data=n // 2, slices=2))
    axes = mesh_lib.batch_axes(mesh)
    x = np.linspace(-2.0, 2.0, n * 2048, dtype=np.float32).reshape(n, 2048)

    def _flat(v):
        return jax.tree.map(lambda g: lax.pmean(g, axes), v)

    def _hier(v):
        return hier_mean(v, axes)

    spec = P(axes)
    problems = []
    try:
        want = jax.jit(shard_map(_flat, mesh=mesh, in_specs=spec,
                                 out_specs=spec, check_rep=False))(x)
        got = jax.jit(shard_map(_hier, mesh=mesh, in_specs=spec,
                                out_specs=spec, check_rep=False))(x)
    except Exception as e:  # noqa: BLE001 — report, don't crash CI
        return [f"hier numeric check failed to run: "
                f"{type(e).__name__}: {e}"]
    err = float(np.max(np.abs(np.asarray(want) - np.asarray(got))))
    if err > 1e-6:
        problems.append(
            f"hier numeric check: two-level mean deviates from the flat "
            f"mean by {err:.3e} (> 1e-6) on the {n}-device slice=2 mesh")
    return problems


def check() -> list:
    """Self-check for the ``python -m tpuframe.analysis`` CI gate.
    Returns problem strings; [] means healthy."""
    problems: list[str] = []
    # 1. the mode registry and env parsing agree
    for m in MODES:
        try:
            validate_mode(m)
        except Exception as e:  # noqa: BLE001 — report, don't crash CI
            problems.append(f"mode {m!r} failed validation: {e}")
    try:
        mode_from_env()
    except ValueError as e:
        problems.append(f"{ENV_VAR} is set to an invalid mode: {e}")
    # 2. seeded flat/two-level pair against the ICI/DCN split
    problems += _seeded_split_problems()
    # 3. the two-level mean is numerically the flat mean
    problems += _numeric_problems()
    # 4. TF124 self-lint: cross-slice collectives stay at this seam
    from tpuframe.analysis.source_lint import lint_paths, lint_source

    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for f in lint_paths([pkg_root]):
        if f.rule == "TF124":
            problems.append(f"self-lint: {f}")
    # 5. seeded positive: the rule itself is alive (a known-bad snippet
    # outside the seam MUST fire, and the suppression MUST silence it) —
    # without this, a refactor that breaks the rule reads as a clean tree.
    bad = 'def f(g):\n    return lax.pmean(g, ("data", "slice"))\n'
    if not any(f.rule == "TF124"
               for f in lint_source(bad, path="parallel/step.py")):
        problems.append("TF124 seeded positive did not fire: a raw "
                        "cross-slice lax.pmean outside parallel/hier.py "
                        "went unflagged")
    ok = ('def f(g):\n    return lax.pmean(g, ("data", "slice"))'
          '  # tf-lint: ok[TF124]\n')
    if any(f.rule == "TF124"
           for f in lint_source(ok, path="parallel/step.py")):
        problems.append("TF124 suppression comment (# tf-lint: "
                        "ok[TF124]) did not silence the seeded positive")
    return problems
