"""``tpuframe.parallel.hvd`` — a Horovod-compatible facade.

The reference's entire distributed API surface is the handful of
``horovod.torch`` calls named in SURVEY.md §3a "Distributed glue":

    hvd.init(); hvd.size(); hvd.rank(); hvd.local_rank()
    hvd.allreduce(t, average=True)
    hvd.broadcast_parameters(state_dict, root_rank=0)
    hvd.broadcast_optimizer_state(opt, root_rank=0)
    opt = hvd.DistributedOptimizer(opt, named_parameters=...)

This module provides the same verbs with TPU-native semantics so a reference
user can port ``train.py`` mechanically.  The key semantic shift: Horovod has
one rank space (one process per GPU); SPMD JAX has two. ``size()`` is the
GLOBAL CHIP COUNT — the LR-scaling denominator, Horovod's ``hvd.size()``
equivalent. ``rank()`` is the HOST/process index — use it only for
rank-0-gated logging and per-host data sharding (pair it with
``jax.process_count()``, not ``size()``). The per-chip rank inside a step
function is the mesh position bound by ``shard_map`` (``lax.axis_index``).

``DistributedOptimizer`` wraps an optax GradientTransformation and performs
the gradient averaging Horovod did in its C++ runtime — but as a traced
``pmean`` that XLA fuses/overlaps (SURVEY.md §2 L1 mapping).  When the step is
not mapped (config 1, single process), it is the identity wrapper, matching
``hvd``'s behavior with size()==1.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import optax

from tpuframe.parallel import bootstrap, collectives
from tpuframe.parallel import mesh as mesh_lib

PyTree = Any

_DEFAULT_AXIS = mesh_lib.BATCH_AXES  # grads reduce over all batch-like axes


def init(config: bootstrap.DistConfig | None = None) -> None:
    """Reference parity: ``hvd.init()`` (SURVEY.md §4.3)."""
    bootstrap.initialize(config)


def size() -> int:
    """Global device count — the LR-scaling denominator the reference uses
    (``scale LR by hvd.size()``, SURVEY.md §3a)."""
    return jax.device_count()


def rank() -> int:
    """Host/process index — NOT the chip index; pair with
    ``jax.process_count()`` for host-level sharding. Per-chip rank inside a
    step fn is ``lax.axis_index``."""
    return jax.process_index()


def local_rank() -> int:
    """Reference used this to pin a GPU; on TPU device pinning is automatic,
    kept for port compatibility (always 0 within a host's first device)."""
    return 0


def local_size() -> int:
    return jax.local_device_count()


def is_primary() -> bool:
    return bootstrap.is_primary()


class _ReduceOp:
    """Reduction-op sentinel, mirroring ``horovod.torch``'s op constants."""

    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = name

    def __repr__(self):
        return f"hvd.{self.name}"


Average = _ReduceOp("Average")
Sum = _ReduceOp("Sum")
Adasum = _ReduceOp("Adasum")
Min = _ReduceOp("Min")
Max = _ReduceOp("Max")
Product = _ReduceOp("Product")


class ProcessSet:
    """Subgroup for collectives (Horovod ``hvd.ProcessSet``).

    Horovod builds a sub-communicator per set; under SPMD the set is a
    static membership list over the linearized replica index, and the
    collective is a masked full-axis reduction (non-members keep their
    input untouched, matching Horovod's "op never runs outside the set").
    """

    def __init__(self, ranks):
        ranks = tuple(sorted(set(int(r) for r in ranks)))
        if not ranks:
            raise ValueError("ProcessSet needs at least one rank")
        if any(r < 0 for r in ranks):
            raise ValueError(f"negative rank in ProcessSet: {ranks}")
        self.ranks = ranks

    def size(self) -> int:
        return len(self.ranks)

    def __repr__(self):
        return f"ProcessSet(ranks={list(self.ranks)})"


def allreduce(tensor: PyTree, average: bool | None = None,
              name: str | None = None, axis=_DEFAULT_AXIS,
              op: _ReduceOp | None = None,
              process_set: ProcessSet | None = None) -> PyTree:
    """``hvd.allreduce`` — inside a mapped step fn this is a traced collective;
    outside, identity (single-host value already global under SPMD).

    ``op`` selects the reduction (``hvd.Average`` default / ``Sum`` /
    ``Adasum`` / ``Min`` / ``Max`` / ``Product``); the legacy ``average=``
    boolean is honored but, as in Horovod, may not be combined with ``op``.
    ``process_set`` restricts the op to a replica subgroup — members get the
    subgroup result, non-members keep their input.
    """
    del name  # Horovod used names for its fusion table; XLA needs none.
    if average is not None and op is not None:
        raise ValueError("specify either average= or op=, not both "
                         "(Horovod raises here too)")
    if op is None:
        op = Sum if average is False else Average
    if process_set is not None:
        if op is Average or op is Sum:
            return collectives.masked_allreduce(
                tensor, axis, process_set.ranks, average=op is Average)
        raise NotImplementedError(
            f"process_set is supported for Average/Sum, not {op!r}")
    if op is Average:
        return collectives.allreduce(tensor, axis=axis, average=True)
    if op is Sum:
        return collectives.allreduce(tensor, axis=axis, average=False)
    if op is Adasum:
        return collectives.adasum(tensor, axis=axis)
    if op is Min:
        return collectives.reduce_min(tensor, axis=axis)
    if op is Max:
        return collectives.reduce_max(tensor, axis=axis)
    if op is Product:
        return collectives.reduce_prod(tensor, axis=axis)
    raise ValueError(f"unknown reduction op {op!r}")


def broadcast_parameters(params: PyTree, root_rank: int = 0, axis=_DEFAULT_AXIS,
                         process_set: ProcessSet | None = None) -> PyTree:
    """``hvd.broadcast_parameters`` — under SPMD initialization, parameters are
    created identically on every chip from a shared PRNG key, so the broadcast
    is only needed when a caller deliberately diverged state; we honor the
    call inside mapped contexts and no-op otherwise."""
    if process_set is not None:
        return collectives.masked_broadcast(params, axis, process_set.ranks,
                                            root=root_rank)
    return collectives.broadcast(params, axis=axis, root=root_rank)


def broadcast_optimizer_state(opt_state: PyTree, root_rank: int = 0,
                              axis=_DEFAULT_AXIS) -> PyTree:
    return collectives.broadcast(opt_state, axis=axis, root=root_rank)


class Compression:
    """Horovod's ``hvd.Compression`` namespace: scripts pass
    ``compression=hvd.Compression.fp16`` — map the members onto
    ``DistributedOptimizer``'s string knob (fp16 → bf16, the TPU-native
    half precision; see the compression docs below)."""

    none = None
    fp16 = "bf16"


class _DistState(NamedTuple):
    inner: Any


def DistributedOptimizer(
    tx: optax.GradientTransformation,
    *,
    axis=_DEFAULT_AXIS,
    average: bool | None = None,
    compression: str | None = None,
    op: _ReduceOp | None = None,
) -> optax.GradientTransformation:
    """Wrap ``tx`` so updates see cross-replica-averaged gradients.

    Reference parity: ``hvd.DistributedOptimizer`` hooks ``loss.backward()``'s
    per-grad callbacks to enqueue async fused NCCL allreduces and waits in
    ``opt.step()`` (SURVEY.md §4.1 hot loop).  Under XLA the entire step is one
    program: the ``pmean`` below is scheduled/overlapped with backward compute
    by the compiler, which is the same overlap Horovod implements by hand.

    ``compression``: None, "bf16" or "int8".  "bf16" mirrors Horovod's fp16
    gradient compression (cast down for the wire, restored after
    reduction); "int8" is the EQuARX-style further step (PAPERS.md:7) —
    per-block-scaled int8 payloads on an all-to-all + all-gather wire
    (quantwire.all_reduce_mean; requires ``average=True``).  The same
    implementation backs ``make_train_step(wire_format="int8-block")``;
    this knob exists for Horovod API parity.

    ``op=hvd.Adasum`` selects adaptive summation (collectives.adasum) in
    place of the mean — Horovod's scale-insensitive large-batch reduction.
    Adasum's combine is norm-based, so wire compression is disallowed with
    it (as in Horovod, where Adasum + fp16 compression is unsupported).
    """
    if average is not None and op is not None:
        raise ValueError("specify either average= or op=, not both "
                         "(same contract as hvd.allreduce)")
    if op is None:
        op = Sum if average is False else Average
    if op not in (Average, Sum, Adasum):
        raise ValueError(f"DistributedOptimizer supports Average/Sum/Adasum, "
                         f"got {op!r}")
    if op is Adasum and compression is not None:
        raise ValueError("Adasum's norm-based combine does not compose with "
                         "wire compression")
    average = op is Average

    def init_fn(params):
        return _DistState(inner=tx.init(params))

    def update_fn(grads, state, params=None, **extra):
        if op is Adasum:
            updates, inner = tx.update(
                collectives.adasum(grads, axis=axis), state.inner, params,
                **extra)
            return updates, _DistState(inner=inner)
        if compression == "int8":
            # Quantized wire path (EQuARX-style): per-block-scaled int8
            # payloads on an all-to-all + all-gather wire (quantwire) —
            # structurally different from the cast-reduce-cast flow, so it
            # replaces the reduction outright.  min_elems=0: this knob is
            # an explicit per-optimizer ask, no size floor.
            if not average:
                raise ValueError("compression='int8' implements a quantized "
                                 "mean; use average=True")
            from tpuframe.parallel import quantwire

            grads = quantwire.all_reduce_mean(grads, axis, min_elems=0)
            updates, inner = tx.update(grads, state.inner, params, **extra)
            return updates, _DistState(inner=inner)
        grads, orig_dtypes = _maybe_compress(grads, compression)
        # vma-aware: reduces varying leaves, passes through already-psum'd
        # ones (gradients of replicated params arrive pre-summed under jax's
        # shard_map autodiff) — see collectives.average_gradients.
        if average:
            grads = collectives.average_gradients(grads, axis=axis)
        else:
            grads = collectives.sum_gradients(grads, axis=axis)
        grads = _maybe_decompress(grads, orig_dtypes)
        updates, inner = tx.update(grads, state.inner, params, **extra)
        return updates, _DistState(inner=inner)

    return optax.GradientTransformation(init_fn, update_fn)


def allgather(tensor, name: str | None = None, axis=_DEFAULT_AXIS):
    """``hvd.allgather`` — concatenate per-replica tensors along dim 0."""
    del name
    return collectives.allgather(tensor, axis=axis)


def alltoall(tensor, splits=None, name: str | None = None,
             axis=_DEFAULT_AXIS):
    """``hvd.alltoall`` with equal splits (dim 0 scattered, gathered back).
    Horovod's ragged ``splits`` have no XLA equivalent — static shapes are
    the compilation model; pre-pad to equal splits instead."""
    del name
    if splits is not None:
        uniform = len({int(x) for x in splits}) == 1
        if not uniform or sum(int(x) for x in splits) != tensor.shape[0]:
            raise NotImplementedError(
                "alltoall with UNEQUAL splits is ragged; XLA collectives "
                "are static-shape — pad to equal splits")
        # equal splits covering dim 0 == exactly the static case
    return collectives.alltoall(tensor, axis=axis)


def grouped_allreduce(tensors, average: bool = True, name: str | None = None,
                      axis=_DEFAULT_AXIS):
    """``hvd.grouped_allreduce`` — one fused reduction for a list of
    tensors.  Horovod groups to control its fusion buffer; XLA's combiner
    fuses adjacent reductions regardless, so this is allreduce mapped over
    the list (the group arrives at the wire fused either way)."""
    del name
    return [collectives.allreduce(t, axis=axis, average=average)
            for t in tensors]


def barrier() -> None:
    """``hvd.barrier`` — host-level process barrier (checkpoint/teardown
    sync; NOT needed around compiled steps, which order themselves)."""
    bootstrap.host_barrier("tpuframe_hvd_barrier")


def join() -> int:
    """``hvd.join`` — Horovod's elastic straggler drain.  tpuframe's
    failure model is slice-restart + checkpoint resume (SURVEY.md §5.3):
    pods fail as a unit, so there is no partial-membership state to drain.
    Provided as a host barrier for porting compatibility; returns -1 like
    Horovod does when no rank is joining."""
    barrier()
    return -1


def shutdown() -> None:
    """``hvd.shutdown`` — tear down the distributed runtime (idempotent:
    bootstrap tracks init state, so a later ``hvd.init()`` re-initializes
    and the launcher's own clean-exit shutdown doesn't double-teardown)."""
    bootstrap.shutdown()


def allreduce_async_(tensor: PyTree, average: bool | None = None,
                     name: str | None = None, axis=_DEFAULT_AXIS,
                     op: _ReduceOp | None = None,
                     process_set: ProcessSet | None = None) -> PyTree:
    """``hvd.allreduce_async_`` — returns a "handle" to pass to
    ``synchronize``.  Under XLA the handle IS the traced value: inside a
    compiled program every collective is already asynchronous until a
    consumer needs it (the scheduler overlaps it with compute — the
    overlap Horovod's handle API exists to expose), so the pair maps to
    allreduce + identity."""
    return allreduce(tensor, average=average, name=name, axis=axis, op=op,
                     process_set=process_set)


def synchronize(handle: PyTree) -> PyTree:
    """``hvd.synchronize`` — wait on an ``allreduce_async_`` handle.
    Inside jit: identity (tracers pass through — the data dependency is
    the synchronization).  Outside: blocks until the device value is
    ready, and surfaces any deferred execution error HERE, matching
    Horovod's semantics of synchronize being where failures appear."""
    if any(isinstance(leaf, jax.core.Tracer)
           for leaf in jax.tree.leaves(handle)):
        return handle
    return jax.block_until_ready(handle)


def mpi_built() -> bool:
    """Horovod build introspection.  tpuframe has no MPI dependency —
    bootstrap is jax.distributed's GRPC coordinator (SURVEY.md §4.3)."""
    return False


def nccl_built() -> bool:
    """No NCCL: collectives are XLA HLOs over ICI/DCN (SURVEY.md §3b)."""
    return False


def gloo_built() -> bool:
    """No Gloo: host-level rendezvous is the GRPC coordinator."""
    return False


def cuda_built() -> bool:
    return False


def rocm_built() -> bool:
    return False


def mpi_enabled() -> bool:
    return False


def broadcast_object(obj, root_rank: int = 0, name: str | None = None):
    """``hvd.broadcast_object`` — picklable host object from ``root_rank``
    to every process (collective; see bootstrap.broadcast_object)."""
    del name  # Horovod tags; no fusion table here
    return bootstrap.broadcast_object(obj, root=root_rank)


def allgather_object(obj, name: str | None = None) -> list:
    """``hvd.allgather_object`` — one picklable object per process,
    returned in process order everywhere."""
    del name
    return bootstrap.allgather_object(obj)


def _maybe_compress(grads: PyTree, compression: str | None):
    """Cast float32 leaves down for the reduction; returns the original
    dtypes so decompression restores exactly what arrived (bf16-native
    gradients stay bf16 throughout)."""
    if compression is None:
        return grads, None
    if compression == "bf16":
        import jax.numpy as jnp

        orig_dtypes = jax.tree.map(lambda g: g.dtype, grads)
        compressed = jax.tree.map(
            lambda g: g.astype(jnp.bfloat16) if g.dtype == jnp.float32 else g, grads
        )
        return compressed, orig_dtypes
    raise ValueError(f"unknown compression {compression!r}")


def _maybe_decompress(grads: PyTree, orig_dtypes: PyTree | None) -> PyTree:
    if orig_dtypes is None:
        return grads
    return jax.tree.map(lambda g, dt: g.astype(dt), grads, orig_dtypes)
