"""Candidate enumeration + the offline AOT sweep driver.

The sweep compiles every candidate on a compile-only TPU topology
(``jax.experimental.topologies.get_topology_desc``, PERF.md §7) on the CPU
host — real XLA:TPU lowering, real ``cost_analysis``/``memory_analysis``,
no chip, no relay — scores each with the roofline tables, and writes the
ranked results into the persistent tuning DB plus a human-readable report.

Candidate axes:

  - flash-attention block sizes (``TPUFRAME_FA_BLOCK_Q/K``), pruned against
    the Mosaic VMEM double-buffer budget BEFORE compiling — the §11 v4
    lesson: Mosaic double-buffers every grid-blocked operand, and the real
    compiler rejects tilings the interpret-mode tests happily accept.
  - ``TPUFRAME_XLA_OPTS`` compiler-option sets (latency-hiding scheduler,
    scoped vmem, all-reduce combiner thresholds via parallel/tuning.py's
    flag templates) applied through per-compile ``compiler_options`` —
    they travel inside the compile request, so no XLA_FLAGS env mutation
    (which TF106 now lints) is ever needed.
  - batch shapes for the bench ResNet-50 step.
  - rematerialization policies (``tpuframe.mem`` registry names) for the
    donated ResNet-50 train step, ranked on ``cost_analysis`` bytes
    accessed against the PERF.md §6 HBM touch model (``remat_sweep``).

jax is imported lazily inside functions: the candidate enumeration + VMEM
model are pure and feed the fast test tier.
"""

from __future__ import annotations

import fcntl
import json
import os
import sys

from tpuframe.tune import db as tune_db
from tpuframe.tune import roofline

# §11: fused_conv_bn budgets 10 MB for its single blocked operand pair;
# flash-attention runs three kernels with up to 8 blocked refs each, and
# v5e VMEM is 128 MiB/core — 16 MiB per kernel twin-buffer set more than
# clears compilation while leaving headroom for Mosaic's own spills.
DEFAULT_VMEM_BUDGET = 16 * 1024 * 1024

_F32 = 4


def _padded_bytes(shape, dtype_bytes: int) -> int:
    """Mosaic VMEM footprint of one block: minor dim pads to 128 lanes,
    next-minor to 8 sublanes (the (8,128) tile — same rule as
    perf/_common.hlo_nbytes)."""
    dims = list(shape)
    if not dims:
        return dtype_bytes
    dims[-1] = (dims[-1] + 127) // 128 * 128
    if len(dims) > 1:
        dims[-2] = (dims[-2] + 7) // 8 * 8
    n = 1
    for d in dims:
        n *= d
    return n * dtype_bytes


def fa_vmem_bytes(block_q: int, block_k: int, head_dim: int, *,
                  dtype_bytes: int = 2) -> int:
    """Worst-kernel VMEM estimate for one (block_q, block_k) tiling of the
    flash-attention fwd/bwd kernel trio: 2x every grid-blocked operand
    (Mosaic double-buffers them all) + f32 accumulator scratch.  Block
    shapes mirror ops/flash_attention.py's BlockSpecs exactly."""
    bq, bk, d = block_q, block_k, head_dim

    def kernel(blocked, scratch):
        dbl = 2 * sum(_padded_bytes(s, b) for s, b in blocked)
        return dbl + sum(_padded_bytes(s, b) for s, b in scratch)

    q = ((1, bq, d), dtype_bytes)
    kv = ((1, bk, d), dtype_bytes)
    row = ((1, bq, 1), _F32)  # lse / delta rows
    fwd = kernel([q, kv, kv, q, row],
                 [((bq, d), _F32), ((bq, 128), _F32), ((bq, 128), _F32)])
    dq = kernel([q, kv, kv, q, row, row, q],
                [((bq, d), _F32)])
    dkv = kernel([q, kv, kv, q, row, row, kv, kv],
                 [((bk, d), _F32), ((bk, d), _F32)])
    return max(fwd, dq, dkv)


def fa_block_candidates(seq_len: int, head_dim: int, *,
                        blocks=(128, 256, 512),
                        budget: int = DEFAULT_VMEM_BUDGET):
    """(kept, pruned) candidate lists.  Each entry:
    {"fa_block_q", "fa_block_k", "vmem_bytes"}.  Pruning happens HERE,
    before any compile is attempted — over-budget tilings and tilings the
    kernel's static grid cannot express (seq not divisible) never reach
    the compiler."""
    kept, pruned = [], []
    for bq in blocks:
        for bk in blocks:
            cand = {"fa_block_q": bq, "fa_block_k": bk,
                    "vmem_bytes": fa_vmem_bytes(bq, bk, head_dim)}
            if seq_len % bq or seq_len % bk:
                cand["pruned"] = "seq_not_divisible"
                pruned.append(cand)
            elif cand["vmem_bytes"] > budget:
                cand["pruned"] = "vmem_over_budget"
                pruned.append(cand)
            else:
                kept.append(cand)
    return kept, pruned


def fa_analytic_cost(seq: int, head_dim: int, heads: int, batch: int,
                     block_q: int, block_k: int, *, causal: bool = True,
                     dtype_bytes: int = 2):
    """Touch-model (flops, bytes) for the flash fwd+bwd kernel trio, used
    when the kernel cannot compile in the host's jax (SKIP-not-PASS: the
    record says ``source: analytic``, never passing itself off as compiler
    output).  Matmul work: fwd QK^T + PV (4*e*s), bwd dV/dP/dS/dQ/dK
    (10*e*s); the causal trichotomy skips ~half the blocks.  HBM touches:
    streamed operands re-read once per opposing block row (fwd+dq stream
    K/V seq/block_q times, dkv streams Q/dO seq/block_k times), residents
    once — so bigger blocks mean fewer re-reads, the axis the analytic
    ranking actually discriminates on."""
    e = batch * seq * heads * head_dim
    frac = 0.5 if causal else 1.0
    flops = frac * 14.0 * e * seq
    n_q, n_k = seq // block_q, seq // block_k
    bytes_accessed = dtype_bytes * e * (6 + frac * (4 * n_q + 2 * n_k))
    return flops, bytes_accessed


def xla_opts_candidate_sets() -> list:
    """Named ``compiler_options`` dicts for the sweep.  The combiner
    threshold reuses parallel/tuning.py's flag template (single source for
    the flag spelling) converted from --flag=v to option form."""
    from tpuframe.parallel import tuning

    combiner = {}
    for flag in tuning.fusion_flags(64 * 1024 * 1024):
        k, _, v = flag.lstrip("-").partition("=")
        combiner[k] = v
    return [
        ("baseline", {}),
        ("latency_hiding",
         {"xla_tpu_enable_latency_hiding_scheduler": "true"}),
        ("scoped_vmem_64m",
         {"xla_tpu_scoped_vmem_limit_kib": "65536"}),
        ("combine_64m", combiner),
    ]


def remat_policy_candidates() -> tuple:
    """The remat policies the offline sweep scores.  Every entry is a
    :mod:`tpuframe.mem` registry name, so a sweep winner written to the DB
    is directly consumable by ``TPUFRAME_REMAT_POLICY``/``mem.resolve``.

    ``everything`` is omitted: under ``jax.checkpoint`` it saves every
    residual the un-wrapped program saves, so its compiled step is
    byte-identical to ``none`` and would only double the (4-minute) compile
    bill for a guaranteed tie."""
    return ("none", "dots", "dots_no_batch", "per_block",
            "save_named(block_out)", "full")


# ---------------------------------------------------------------------------
# AOT lock (same lockfile as perf/_common.hold_aot_lock — libtpu ABORTS when
# two compile-only processes initialize concurrently, so the tuner and the
# census scripts must serialize against each other)
# ---------------------------------------------------------------------------

_AOT_LOCK_HANDLE = None


def hold_aot_lock() -> None:
    global _AOT_LOCK_HANDLE
    if _AOT_LOCK_HANDLE is not None:
        return
    fh = open(os.path.join(tune_db.repo_root(), ".aot_compile.lock"), "w")
    fcntl.flock(fh, fcntl.LOCK_EX)  # blocks until the current holder exits
    _AOT_LOCK_HANDLE = fh


def _log(msg, log=None):
    (log or (lambda m: print(f"[tune] {m}", file=sys.stderr, flush=True)))(msg)


# ---------------------------------------------------------------------------
# sweep
# ---------------------------------------------------------------------------


def _fa_compile(topo_devices, seq, head_dim, heads, batch, bq, bk):
    """AOT-compile flash-attention fwd+bwd at one tiling; returns the
    compiled object + a stable program desc for fingerprinting."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from tpuframe.ops import flash_attention as fa

    # Single-device topology probe, not a training mesh — no axis-name
    # contract to honour.
    mesh = Mesh(np.array(topo_devices[:1]), ("d",))  # tf-lint: ok[TF119]
    repl = NamedSharding(mesh, P())
    x = jax.ShapeDtypeStruct((batch, seq, heads, head_dim), jnp.bfloat16,
                             sharding=repl)

    def fwd(q, k, v):
        out = fa.flash_mha(q, k, v, causal=True, block_q=bq, block_k=bk,
                           interpret=False)
        return jnp.sum(out.astype(jnp.float32))

    lowered = jax.jit(jax.grad(fwd, argnums=(0, 1, 2))).lower(x, x, x)
    compiled = lowered.compile()
    text = compiled.as_text()
    if "tpu_custom_call" not in text:
        raise RuntimeError("flash kernel did not lower to a Mosaic custom "
                           "call — interpret mode leaked in (§11)")
    desc = {"program": f"flash_mha_s{seq}_d{head_dim}",
            "shape": list(x.shape), "causal": True,
            "block_q": bq, "block_k": bk}
    return compiled, desc


def _bench_step_compile(topo_devices, batch_per_chip, xla_opts):
    """AOT-compile the bench ResNet-50 train step (the program bench.py
    runs) over the full topology with one compiler-option set."""
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpuframe import models
    from tpuframe.models import losses
    from tpuframe.parallel import mesh as mesh_lib
    from tpuframe.parallel import step as step_lib

    n = len(topo_devices)
    # The framework mesh (all six axes, only data sized) so the step's
    # default batch partition P(('data','fsdp')) resolves — same idiom as
    # perf/exp_offline_ab.dp32.
    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec(data=n),
                              devices=list(topo_devices))
    repl = NamedSharding(mesh, P())
    data = NamedSharding(mesh, mesh_lib.batch_spec())
    global_batch = batch_per_chip * n

    model = models.ResNet50(num_classes=1000, dtype=jnp.bfloat16)
    tx = optax.sgd(0.1, momentum=0.9, nesterov=True)

    def loss_fn(params, model_state, batch, step_rng):
        logits, mutated = model.apply(
            {"params": params, **model_state}, batch["image"], train=True,
            mutable=["batch_stats"])
        loss = losses.softmax_cross_entropy(logits, batch["label"],
                                            label_smoothing=0.1)
        return loss, (dict(mutated), {})

    variables = jax.eval_shape(
        lambda k: model.init(k, jnp.zeros((2, 224, 224, 3), jnp.bfloat16)),
        jax.random.key(0))
    state = jax.eval_shape(
        lambda v: step_lib.TrainState.create(
            v["params"], tx,
            model_state={"batch_stats": v["batch_stats"]}), variables)

    def _repl(tree):
        return jax.tree.map(
            lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=repl),
            tree)

    state = _repl(state)
    batch = {"image": jax.ShapeDtypeStruct(
                 (global_batch, 224, 224, 3), jnp.bfloat16, sharding=data),
             "label": jax.ShapeDtypeStruct(
                 (global_batch,), jnp.int32, sharding=data)}

    step = step_lib.make_train_step(loss_fn, tx, mesh, donate=False,
                                    compiler_options=xla_opts or None)
    lowered = step.lower(state, batch)
    compiled = lowered.compile()
    desc = {"program": f"bench_resnet50_b{batch_per_chip}",
            "n_chips": n, "global_batch": global_batch}
    return compiled, desc


def _remat_step_compile(topo_devices, batch, remat_policy):
    """AOT-compile the DONATED ResNet-50 train step on ONE compile-only
    device under one remat policy.  Single-chip + global batch so the
    bytes-accessed totals line up with the PERF.md §2 anchor (1.435e11 B at
    b=512) and the §6 touch model; donation matches what train.py/bench.py
    actually run, unlike the bench sweep's donate=False A/B rig."""
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpuframe import models
    from tpuframe.models import losses
    from tpuframe.parallel import mesh as mesh_lib
    from tpuframe.parallel import step as step_lib

    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec(data=1),
                              devices=list(topo_devices[:1]))
    repl = NamedSharding(mesh, P())
    data = NamedSharding(mesh, mesh_lib.batch_spec())

    model = models.ResNet50(num_classes=1000, dtype=jnp.bfloat16)
    tx = optax.sgd(0.1, momentum=0.9, nesterov=True)

    def loss_fn(params, model_state, batch, step_rng):
        logits, mutated = model.apply(
            {"params": params, **model_state}, batch["image"], train=True,
            mutable=["batch_stats"])
        loss = losses.softmax_cross_entropy(logits, batch["label"],
                                            label_smoothing=0.1)
        return loss, (dict(mutated), {})

    variables = jax.eval_shape(
        lambda k: model.init(k, jnp.zeros((2, 224, 224, 3), jnp.bfloat16)),
        jax.random.key(0))
    state = jax.eval_shape(
        lambda v: step_lib.TrainState.create(
            v["params"], tx,
            model_state={"batch_stats": v["batch_stats"]}), variables)

    def _repl(tree):
        return jax.tree.map(
            lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=repl),
            tree)

    state = _repl(state)
    batch_structs = {
        "image": jax.ShapeDtypeStruct((batch, 224, 224, 3), jnp.bfloat16,
                                      sharding=data),
        "label": jax.ShapeDtypeStruct((batch,), jnp.int32, sharding=data)}

    step = step_lib.make_train_step(
        loss_fn, tx, mesh, donate=True,
        remat_policy=None if remat_policy == "none" else remat_policy)
    compiled = step.lower(state, batch_structs).compile()
    desc = {"program": f"train_resnet50_b{batch}", "n_chips": 1,
            "global_batch": batch, "donate": True,
            "remat_policy": remat_policy}
    return compiled, desc


def remat_sweep(topology: str = "v5e:2x2", *, db_path: str | None = None,
                report_path: str | None = None, batch: int = 512,
                policies=None, log=None) -> dict:
    """Offline remat-policy search: AOT-compile the donated ResNet-50
    train step once per :mod:`tpuframe.mem` policy, rank on
    ``cost_analysis`` bytes accessed (the §6 HBM-traffic objective — this
    program is bandwidth-bound, so bytes IS the step-time lever), persist
    every candidate to the tuning DB, and write a report with each
    policy's bytes delta vs ``none``."""
    import jax  # noqa: F401 — fail fast before holding the lock
    from jax.experimental import topologies

    from tpuframe import mem

    policies = tuple(policies or remat_policy_candidates())
    for pol in policies:
        mem.validate_policy(pol)  # typo'd candidate fails before the lock

    hold_aot_lock()
    os.environ.setdefault("TPU_SKIP_MDS_QUERY", "1")
    gen = roofline.generation_from_topology(topology)
    topo = topologies.get_topology_desc(topology, platform="tpu")
    _log(f"remat sweep on {topology}: {len(policies)} policies, "
         f"ResNet-50 b={batch} donated train step", log)

    db_path = db_path or tune_db.default_db_path()
    db = tune_db.TuningDB.open(db_path) if os.path.exists(db_path) \
        else tune_db.TuningDB(db_path)
    program = f"train_resnet50_b{batch}"
    report = {"topology": topology, "generation": gen, "batch": batch,
              "objective": "bytes_accessed",
              "remat": {"rows": [], "compile_errors": []}}

    baseline_bytes = None
    for pol in policies:
        try:
            compiled, desc = _remat_step_compile(topo.devices, batch, pol)
        except Exception as e:  # noqa: BLE001 — record, keep sweeping
            row = {"policy": pol,
                   "error": f"{type(e).__name__}: {e}"[:300]}
            report["remat"]["compile_errors"].append(row)
            _log(f"  remat {pol}: COMPILE ERROR {row['error'][:80]}", log)
            continue
        pred = roofline.score_compiled(compiled, gen)
        pred["source"] = "compiled"
        temp_gb = None
        try:
            temp_gb = round(
                compiled.memory_analysis().temp_size_in_bytes / 1e9, 2)
        except Exception:  # noqa: BLE001 — best-effort, like score_compiled
            pass
        if pol == "none":
            baseline_bytes = pred["bytes"]
        drop = None
        if baseline_bytes:
            drop = round(100.0 * (1.0 - pred["bytes"] / baseline_bytes), 1)
        pred["bytes_drop_vs_none_pct"] = drop
        db.add({"program": program, "family": "remat_resnet50",
                "fingerprint": tune_db.fingerprint(desc),
                "topology": topology, "generation": gen,
                "config": {"remat_policy": pol, "batch": batch},
                "predicted": pred})
        row = {"policy": pol, "gb": round(pred["bytes"] / 1e9, 2),
               "tflops": round(pred["flops"] / 1e12, 2),
               "predicted_ms": pred["predicted_ms"], "bound": pred["bound"],
               "temp_gb": temp_gb, "drop_vs_none_pct": drop}
        report["remat"]["rows"].append(row)
        _log(f"  remat {pol}: {row['gb']} GB accessed "
             f"({row['predicted_ms']} ms {row['bound']}-bound, "
             f"temp {temp_gb} GB, drop {drop}%)", log)

    # Rank on the sweep objective.  ``none`` compiles first, so every row
    # has its drop; re-derive drops if the caller reordered policies.
    rows = report["remat"]["rows"]
    if baseline_bytes:
        for row in rows:
            row["drop_vs_none_pct"] = round(
                100.0 * (1.0 - row["gb"] * 1e9 / baseline_bytes), 1)
    rows.sort(key=lambda r: r["gb"])
    report["winner"] = rows[0] if rows else None
    db.save()
    _log(f"tuning DB: {db.path} ({len(db.data['records'])} records)", log)
    if report_path is None:
        tag = topology.replace(":", "_").replace("x", "")
        report_path = os.path.join(tune_db.repo_root(), "perf", "results",
                                   f"remat_report_{tag}.json")
    os.makedirs(os.path.dirname(report_path), exist_ok=True)
    with open(report_path, "w") as f:
        json.dump(report, f, indent=1, sort_keys=True)
        f.write("\n")
    _log(f"report: {report_path}", log)
    return report


def _zero1_step_compile(topo_devices, program: str, batch: int,
                        weight_update: str, wire_format: str = "fp",
                        fusion_threshold: int | None = None,
                        slices: int = 1, hier: str = "flat",
                        wire_format_dcn: str = "fp"):
    """AOT-compile one donated train step over the FULL topology under one
    weight-update mode.  Unlike the remat sweep's single-chip rig, the
    collective swap is the whole point here — the reduce-scatter /
    all-gather pair only exists with every chip in the mesh.  With
    ``slices > 1`` the devices (from ``pspec.topology_devices``) are laid
    out on a hierarchical slice×data mesh so the hier sweep's two-level
    candidates lower their real cross-slice collectives.  Returns
    ``(compiled, desc, opt_state_bytes_per_chip, census)``."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpuframe import models
    from tpuframe.models import losses
    from tpuframe.parallel import mesh as mesh_lib
    from tpuframe.parallel import step as step_lib
    from tpuframe.parallel import zero1 as zero1_lib

    n = len(topo_devices)
    if slices > 1 and n % slices:
        raise ValueError(f"{n} devices do not tile {slices} slices")
    mesh = mesh_lib.make_mesh(
        mesh_lib.MeshSpec(data=n // max(slices, 1), slices=slices),
        devices=list(topo_devices))
    repl = NamedSharding(mesh, P())
    data = NamedSharding(mesh, mesh_lib.batch_spec(mesh=mesh))

    if program == "resnet50":
        model = models.ResNet50(num_classes=1000, dtype=jnp.bfloat16)
        tx = optax.sgd(0.1, momentum=0.9, nesterov=True)

        def loss_fn(params, model_state, batch, step_rng):
            logits, mutated = model.apply(
                {"params": params, **model_state}, batch["image"],
                train=True, mutable=["batch_stats"])
            loss = losses.softmax_cross_entropy(logits, batch["label"],
                                                label_smoothing=0.1)
            return loss, (dict(mutated), {})

        variables = jax.eval_shape(
            lambda k: model.init(
                k, jnp.zeros((2, 224, 224, 3), jnp.bfloat16)),
            jax.random.key(0))
        model_state = {"batch_stats": variables["batch_stats"]}
        batch_structs = {
            "image": jax.ShapeDtypeStruct((batch, 224, 224, 3),
                                          jnp.bfloat16, sharding=data),
            "label": jax.ShapeDtypeStruct((batch,), jnp.int32,
                                          sharding=data)}
    elif program == "bert":
        model = models.get_model("bert-base", num_classes=2)
        tx = optax.adamw(2e-5)  # the GLUE fine-tune recipe — 2 moments

        def loss_fn(params, model_state, batch, step_rng):
            logits = model.apply(
                {"params": params}, batch["input_ids"], train=True,
                rngs={"dropout": step_rng})
            loss = losses.softmax_cross_entropy(logits, batch["label"])
            return loss, (model_state, {})

        variables = jax.eval_shape(
            lambda k: model.init(k, jnp.zeros((2, 128), jnp.int32)),
            jax.random.key(0))
        model_state = {}
        batch_structs = {
            "input_ids": jax.ShapeDtypeStruct((batch, 128), jnp.int32,
                                              sharding=data),
            "label": jax.ShapeDtypeStruct((batch,), jnp.int32,
                                          sharding=data)}
    elif program == "lm":
        # A mid-size TransformerLM (~3.8M params, ~15 MB of f32 grads on
        # the wire) — big enough that every fabric column in the hier
        # sweep carries honest megabytes, small enough that the
        # compile-only multi-slice lowering stays in seconds where the
        # conv stack costs ~4 min per candidate (resnet50) and BERT's
        # 110M-param step takes longer still on this backend.
        seq = 128
        model = models.get_model(
            "transformer-lm", tiny=True, vocab_size=2048, max_seq=seq,
            hidden_size=256, num_layers=4, num_heads=8,
            intermediate_size=1024)
        tx = optax.adamw(1e-3)

        def loss_fn(params, model_state, batch, step_rng):
            logits = model.apply(
                {"params": params}, batch["input_ids"], train=True,
                rngs={"dropout": step_rng})
            loss = losses.softmax_cross_entropy(logits, batch["labels"])
            return loss, (model_state, {})

        variables = jax.eval_shape(
            lambda k: model.init(k, jnp.zeros((2, seq), jnp.int32)),
            jax.random.key(0))
        model_state = {}
        ids = jax.ShapeDtypeStruct((batch, seq), jnp.int32, sharding=data)
        batch_structs = {"input_ids": ids, "labels": ids}
    else:
        raise ValueError(f"unknown zero1 sweep program {program!r}")

    params = variables["params"]
    state = jax.eval_shape(
        lambda v: step_lib.TrainState.create(v["params"], tx,
                                             model_state=model_state),
        variables)

    census = zero1_lib.padding_census(params, n)
    if weight_update == "zero1":
        opt_state = jax.eval_shape(
            lambda p: zero1_lib.init_opt_state(tx, p, n), params)
        state = dataclasses.replace(state, opt_state=opt_state)
        shardings = zero1_lib.state_shardings(state, mesh)
        state = jax.tree.map(
            lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype,
                                               sharding=sh),
            state, shardings)
        opt_bytes = sum(
            s.size * s.dtype.itemsize
            for s in jax.tree.leaves(opt_state)) // n
    else:
        state = jax.tree.map(
            lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype,
                                           sharding=repl), state)
        opt_bytes = sum(s.size * s.dtype.itemsize
                        for s in jax.tree.leaves(state.opt_state))

    step = step_lib.make_train_step(loss_fn, tx, mesh, donate=True,
                                    weight_update=weight_update,
                                    wire_format=wire_format,
                                    fusion_threshold=fusion_threshold,
                                    hier=hier,
                                    wire_format_dcn=wire_format_dcn)
    lowered = step.lower(state, batch_structs)
    if fusion_threshold is not None:
        # The staged pass owns bucketing: hand the XLA all-reduce
        # combiner off per-compile (strategies._overlap_compile_opts —
        # same contract).  Honored where the generic DebugOptions field
        # is read (CPU XLA); the v5e libtpu pin accepts-but-ignores it
        # and re-merges the buckets regardless, which is why the sweep's
        # thresholds tie on that backend (PERF.md §26).
        compiled = lowered.compile(compiler_options={
            "xla_gpu_all_reduce_combine_threshold_bytes": 0})
    else:
        compiled = lowered.compile()
    desc = {"program": f"train_{program}_b{batch}", "n_chips": n,
            "global_batch": batch, "donate": True,
            "weight_update": weight_update, "wire_format": wire_format}
    if fusion_threshold is not None:
        desc["fusion_threshold"] = int(fusion_threshold)
    # Only stamp the hierarchical fields on multi-slice compiles so the
    # single-slice sweeps' fingerprints stay byte-identical to the DB
    # rows they already persisted.
    if slices > 1:
        desc["slices"] = int(slices)
        desc["hier"] = hier
        desc["wire_format_dcn"] = wire_format_dcn
    return compiled, desc, opt_bytes, census


def zero1_sweep(topology: str = "v5e:2x2", *, db_path: str | None = None,
                report_path: str | None = None, batch: int = 512,
                bert_batch: int = 256, log=None) -> dict:
    """Offline weight-update sharding search: AOT-compile the donated
    ResNet-50 and BERT train steps once per ``tpuframe.parallel.zero1``
    mode over the full topology, rank on ``cost_analysis`` bytes accessed
    plus per-chip optimizer-state HBM residency, and persist every
    candidate to the ``weight_update_*`` DB families.  ZeRO-1
    (arXiv:2004.13336) trades the all-reduce for a reduce-scatter +
    all-gather at equal wire bytes; the win it is searched for here is the
    (n-1)/n cut in optimizer-state residency and the update-math HBM
    traffic that goes with it."""
    import jax  # noqa: F401 — fail fast before holding the lock
    from jax.experimental import topologies

    hold_aot_lock()
    os.environ.setdefault("TPU_SKIP_MDS_QUERY", "1")
    gen = roofline.generation_from_topology(topology)
    topo = topologies.get_topology_desc(topology, platform="tpu")
    n = len(topo.devices)
    programs = (("resnet50", batch), ("bert", bert_batch))
    _log(f"zero1 sweep on {topology} ({n} chips): "
         f"{[p for p, _ in programs]} x ('replicated', 'zero1')", log)

    db_path = db_path or tune_db.default_db_path()
    db = tune_db.TuningDB.open(db_path) if os.path.exists(db_path) \
        else tune_db.TuningDB(db_path)
    report = {"topology": topology, "generation": gen, "n_chips": n,
              "objective": "bytes_accessed + opt_state_residency",
              "weight_update": {"rows": [], "compile_errors": [],
                                "padding_census": {}}}

    for program, b in programs:
        baseline = {}
        for mode in ("replicated", "zero1"):
            try:
                compiled, desc, opt_bytes, census = _zero1_step_compile(
                    topo.devices, program, b, mode)
            except Exception as e:  # noqa: BLE001 — record, keep sweeping
                row = {"program": program, "weight_update": mode,
                       "error": f"{type(e).__name__}: {e}"[:300]}
                report["weight_update"]["compile_errors"].append(row)
                _log(f"  {program}/{mode}: COMPILE ERROR "
                     f"{row['error'][:80]}", log)
                continue
            pred = roofline.score_compiled(compiled, gen)
            pred["source"] = "compiled"
            temp_gb = None
            try:
                temp_gb = round(
                    compiled.memory_analysis().temp_size_in_bytes / 1e9, 2)
            except Exception:  # noqa: BLE001 — best-effort
                pass
            if mode == "replicated":
                baseline = {"bytes": pred["bytes"], "opt": opt_bytes}
                report["weight_update"]["padding_census"][program] = {
                    "total_param_bytes": census["total_bytes"],
                    "padded_bytes": census["padded_bytes"],
                    "waste_frac": census["waste_frac"],
                    "n_shards": n}
            row = {"program": program, "weight_update": mode,
                   "global_batch": b,
                   "gb": round(pred["bytes"] / 1e9, 3),
                   "predicted_ms": pred["predicted_ms"],
                   "bound": pred["bound"], "temp_gb": temp_gb,
                   "opt_state_resident_mb": round(opt_bytes / 1e6, 2)}
            if baseline.get("opt"):
                row["opt_residency_drop_pct"] = round(
                    100.0 * (1.0 - opt_bytes / baseline["opt"]), 1)
            if baseline.get("bytes"):
                row["bytes_drop_vs_replicated_pct"] = round(
                    100.0 * (1.0 - pred["bytes"] / baseline["bytes"]), 1)
            pred["opt_state_resident_bytes"] = int(opt_bytes)
            db.add({"program": desc["program"],
                    "family": f"weight_update_{program}",
                    "fingerprint": tune_db.fingerprint(desc),
                    "topology": topology, "generation": gen,
                    "config": {"weight_update": mode, "batch": b},
                    "predicted": pred})
            report["weight_update"]["rows"].append(row)
            _log(f"  {program}/{mode}: {row['gb']} GB accessed "
                 f"({row['predicted_ms']} ms {row['bound']}-bound), "
                 f"opt state {row['opt_state_resident_mb']} MB/chip", log)

    rows = report["weight_update"]["rows"]
    winners = {}
    for program, _ in programs:
        prog_rows = [r for r in rows if r["program"] == program]
        prog_rows.sort(key=lambda r: (r["predicted_ms"] or float("inf"),
                                      r["opt_state_resident_mb"]))
        if prog_rows:
            winners[program] = prog_rows[0]
    report["winners"] = winners
    db.save()
    _log(f"tuning DB: {db.path} ({len(db.data['records'])} records)", log)
    if report_path is None:
        tag = topology.replace(":", "_").replace("x", "")
        report_path = os.path.join(tune_db.repo_root(), "perf", "results",
                                   f"zero1_report_{tag}.json")
    os.makedirs(os.path.dirname(report_path), exist_ok=True)
    with open(report_path, "w") as f:
        json.dump(report, f, indent=1, sort_keys=True)
        f.write("\n")
    _log(f"report: {report_path}", log)
    return report


def wire_sweep(topology: str = "v5e:2x2", *, db_path: str | None = None,
               report_path: str | None = None, batch: int = 512,
               bert_batch: int = 256, log=None) -> dict:
    """Offline wire-format search: AOT-compile the donated ResNet-50
    (plain DP) and BERT (ZeRO-1) train steps once per
    ``tpuframe.parallel.quantwire`` format over the full topology, rank
    on the roofline's predicted step time PLUS the ICI comm model's
    predicted collective time, and persist every candidate to the
    ``wire_format_*`` DB families.  The comm bytes per row come from the
    compiled HLO itself (``hlo_audit`` — an s8 payload counts one byte
    per element), which is what makes the int8-block rows honest: the
    quantized wire's ~4x byte drop shows up exactly where the program
    put it (dp's grad all-reduce; ZeRO-1's param all-gather, the +9%
    BERT leg of PERF §18)."""
    import jax  # noqa: F401 — fail fast before holding the lock
    from jax.experimental import topologies

    from tpuframe.analysis import hlo_audit

    hold_aot_lock()
    os.environ.setdefault("TPU_SKIP_MDS_QUERY", "1")
    gen = roofline.generation_from_topology(topology)
    topo = topologies.get_topology_desc(topology, platform="tpu")
    n = len(topo.devices)
    # dp exercises the all-reduce -> quantized a2a+ag swap; dp-zero1
    # exercises the rs+ag -> quantized a2a + s8 delta-gather swap.
    configs = (("resnet50", batch, "replicated"),
               ("bert", bert_batch, "zero1"))
    _log(f"wire sweep on {topology} ({n} chips): "
         f"{[(p, m) for p, _, m in configs]} x ('fp', 'int8-block')", log)

    db_path = db_path or tune_db.default_db_path()
    db = tune_db.TuningDB.open(db_path) if os.path.exists(db_path) \
        else tune_db.TuningDB(db_path)
    report = {"topology": topology, "generation": gen, "n_chips": n,
              "objective": "predicted_ms + t_ici_ms (comm model on "
                           "HLO-parsed wire bytes)",
              "wire_format": {"rows": [], "compile_errors": []}}

    for program, b, mode in configs:
        baseline = {}
        for fmt in ("fp", "int8-block"):
            try:
                compiled, desc, _opt_bytes, _census = _zero1_step_compile(
                    topo.devices, program, b, mode, wire_format=fmt)
            except Exception as e:  # noqa: BLE001 — record, keep sweeping
                row = {"program": program, "wire_format": fmt,
                       "weight_update": mode,
                       "error": f"{type(e).__name__}: {e}"[:300]}
                report["wire_format"]["compile_errors"].append(row)
                _log(f"  {program}/{fmt}: COMPILE ERROR "
                     f"{row['error'][:80]}", log)
                continue
            pred = roofline.score_compiled(compiled, gen)
            pred["source"] = "compiled"
            coll = hlo_audit.parse_collectives(compiled.as_text())
            comm = roofline.comm_score(gen, coll.filter(1024), n)
            pred["comm"] = comm
            total_ms = round(pred["predicted_ms"] + comm["t_ici_ms"], 3)
            pred["predicted_total_ms"] = total_ms
            row = {"program": program, "wire_format": fmt,
                   "weight_update": mode, "global_batch": b,
                   "predicted_ms": pred["predicted_ms"],
                   "t_ici_ms": comm["t_ici_ms"],
                   "predicted_total_ms": total_ms,
                   "comm_bytes": comm["comm_bytes"],
                   "comm_rows": comm["rows"], "bound": pred["bound"]}
            if fmt == "fp":
                baseline = {"comm_bytes": comm["comm_bytes"],
                            "total_ms": total_ms}
            if baseline.get("comm_bytes"):
                row["wire_bytes_ratio_vs_fp"] = round(
                    comm["comm_bytes"] / baseline["comm_bytes"], 3)
            db.add({"program": desc["program"],
                    "family": f"wire_format_{program}",
                    "fingerprint": tune_db.fingerprint(desc),
                    "topology": topology, "generation": gen,
                    "config": {"wire_format": fmt, "batch": b,
                               "weight_update": mode},
                    "predicted": pred})
            report["wire_format"]["rows"].append(row)
            _log(f"  {program}/{fmt}: {row['predicted_total_ms']} ms "
                 f"total ({row['predicted_ms']} step + {row['t_ici_ms']} "
                 f"ICI), {comm['comm_bytes'] / 1e6:.2f} MB on the wire",
                 log)

    rows = report["wire_format"]["rows"]
    winners = {}
    for program, _, _ in configs:
        prog_rows = [r for r in rows if r["program"] == program]
        prog_rows.sort(
            key=lambda r: r.get("predicted_total_ms") or float("inf"))
        if prog_rows:
            winners[program] = prog_rows[0]
    report["winners"] = winners
    db.save()
    _log(f"tuning DB: {db.path} ({len(db.data['records'])} records)", log)
    if report_path is None:
        tag = topology.replace(":", "_").replace("x", "")
        report_path = os.path.join(tune_db.repo_root(), "perf", "results",
                                   f"wire_report_{tag}.json")
    os.makedirs(os.path.dirname(report_path), exist_ok=True)
    with open(report_path, "w") as f:
        json.dump(report, f, indent=1, sort_keys=True)
        f.write("\n")
    _log(f"report: {report_path}", log)
    return report


def hier_sweep(topology: str = "v5e:2x2", *, slices: int = 2,
               db_path: str | None = None, report_path: str | None = None,
               batch: int = 512, zero1_batch: int = 256, log=None) -> dict:
    """Offline two-level-collective search: AOT-compile the donated
    TransformerLM train step (plain DP and ZeRO-1 arms — see the ``lm``
    program note in ``_zero1_step_compile`` for why not the conv/BERT
    pair the other sweeps use) on a compile-only MULTI-SLICE topology
    (``pspec.topology_devices`` — PJRT ``num_slices``, no chip needed)
    once per (hier, wire_format_dcn) candidate, attribute every
    collective's wire bytes to its fabric
    with shardflow's replica-group splitter, price the two columns with
    ``roofline.comm_split_score`` (ICI over the device ring, DCN over
    the slice ring — the ~32x bandwidth gap is the whole game), and
    persist every candidate to the ``hier_collectives`` DB family.

    Candidates: flat/fp (the baseline everything is ratioed against),
    hier/fp (PERF §23's two-level lowering — DCN carries 1/n_inner of
    the bytes), and hier/int8-block (EQuARX's quantized wire on the DCN
    leg only — ICI stays fp).  flat/int8-block is structurally invalid
    (the DCN wire format IS the cross-slice leg; pspec rejects it) and
    is recorded as skipped rather than silently absent.

    DB rows store the comm-aware total (step + ICI + DCN ms) as their
    ``predicted_ms`` so ``db.best`` / ``resolve_hier`` elect the
    candidate the split model actually favors — the raw roofline step
    time ties across hier modes by construction (same compute), and a
    tie would elect noise.

    Each candidate compiles in its OWN worker subprocess
    (``python -m tpuframe.tune _hier-probe``): the compile-only
    multi-slice backend's compiles are nondeterministically slow — the
    same candidate that compiles in seconds in one run can wedge libtpu
    for tens of minutes in the next — and isolation plus a timeout
    turns a wedged compile into a retried (then recorded) row instead
    of hanging the whole sweep."""
    import subprocess
    import tempfile

    import jax  # noqa: F401 — fail fast before holding the lock

    hold_aot_lock()
    os.environ.setdefault("TPU_SKIP_MDS_QUERY", "1")
    gen = roofline.generation_from_topology(topology)
    n = roofline.n_chips_from_topology(topology) * max(int(slices), 1)
    candidates = (("flat", "fp"), ("hier", "fp"), ("hier", "int8-block"))
    configs = (("lm", batch, "replicated"),
               ("lm", zero1_batch, "zero1"))
    _log(f"hier sweep on {topology} x{slices} slices ({n} chips): "
         f"{[(p, m) for p, _, m in configs]} x {list(candidates)}", log)

    db_path = db_path or tune_db.default_db_path()
    db = tune_db.TuningDB.open(db_path) if os.path.exists(db_path) \
        else tune_db.TuningDB(db_path)
    report = {"topology": topology, "slices": slices, "generation": gen,
              "n_chips": n,
              "objective": "t_step_ms + t_ici_ms + t_dcn_ms "
                           "(comm_split_score on shardflow's "
                           "replica-group fabric attribution)",
              "skipped": [{"hier": "flat", "wire_format_dcn": "int8-block",
                           "reason": "structurally invalid — the DCN "
                                     "wire format is the cross-slice "
                                     "leg of the two-level lowering"}],
              "hier": {"rows": [], "compile_errors": []}}

    for program, b, mode in configs:
        baseline = {}
        for hier_mode, fmt in candidates:
            payload, err, rc = None, None, 0
            for attempt in (1, 2):
                with tempfile.NamedTemporaryFile(suffix=".json",
                                                 delete=False) as tf:
                    out_path = tf.name
                cmd = [sys.executable, "-m", "tpuframe.tune",
                       "_hier-probe", "--topology", topology,
                       "--slices", str(slices), "--program", program,
                       "--batch", str(b), "--mode", mode,
                       "--hier", hier_mode, "--wire-format-dcn", fmt,
                       "--out", out_path]
                try:
                    proc = subprocess.run(cmd, capture_output=True,
                                          text=True, timeout=480)
                    rc, stderr = proc.returncode, proc.stderr
                except subprocess.TimeoutExpired:
                    rc, stderr = -1, "probe timed out after 480 s"
                try:
                    if rc == 0:
                        with open(out_path) as f:
                            payload = json.load(f)
                        break
                    err = _crash_reason(stderr, rc)
                    if rc != -1:
                        break  # deterministic failure — retry won't help
                    _log(f"  {program}/{hier_mode}/{fmt}: wedged compile "
                         f"(attempt {attempt}), "
                         + ("retrying" if attempt == 1 else "giving up"),
                         log)
                finally:
                    if os.path.exists(out_path):
                        os.unlink(out_path)
            if payload is None:
                row = {"program": program, "hier": hier_mode,
                       "wire_format_dcn": fmt, "weight_update": mode,
                       "returncode": rc, "error": err}
                report["hier"]["compile_errors"].append(row)
                _log(f"  {program}/{hier_mode}/{fmt}: COMPILE ERROR "
                     f"{(err or '')[:80]}", log)
                continue
            row, desc, pred = (payload["row"], payload["desc"],
                               payload["pred"])
            css = pred["comm_split"]
            total_ms = row["predicted_total_ms"]
            if hier_mode == "flat" and fmt == "fp":
                baseline = {"dcn_bytes": css["dcn_bytes"],
                            "t_dcn_ms": css["t_dcn_ms"],
                            "total_ms": total_ms}
            if baseline.get("dcn_bytes"):
                row["dcn_bytes_ratio_vs_flat"] = round(
                    css["dcn_bytes"] / baseline["dcn_bytes"], 4)
            if baseline.get("t_dcn_ms"):
                row["t_dcn_ratio_vs_flat"] = round(
                    css["t_dcn_ms"] / baseline["t_dcn_ms"], 4)
            db.add({"program": desc["program"],
                    "family": "hier_collectives",
                    "fingerprint": tune_db.fingerprint(desc),
                    "topology": topology, "generation": gen,
                    "config": {"hier": hier_mode, "wire_format_dcn": fmt,
                               "batch": b, "weight_update": mode,
                               "slices": slices},
                    "predicted": pred})
            report["hier"]["rows"].append(row)
            _log(f"  {program}/{hier_mode}/{fmt}: "
                 f"{row['predicted_total_ms']} ms total "
                 f"({row['t_step_ms']} step + {row['t_ici_ms']} ICI + "
                 f"{row['t_dcn_ms']} DCN), "
                 f"{css['dcn_bytes'] / 1e6:.2f} MB on DCN", log)

    rows = report["hier"]["rows"]
    winners = {}
    for program, _, mode in configs:
        arm_rows = [r for r in rows if r["program"] == program
                    and r["weight_update"] == mode]
        arm_rows.sort(
            key=lambda r: r.get("predicted_total_ms") or float("inf"))
        if arm_rows:
            winners[f"{program}/{mode}"] = arm_rows[0]
    report["winners"] = winners
    db.save()
    _log(f"tuning DB: {db.path} ({len(db.data['records'])} records)", log)
    if report_path is None:
        tag = topology.replace(":", "_").replace("x", "")
        report_path = os.path.join(tune_db.repo_root(), "perf", "results",
                                   f"hier_report_{tag}.json")
    os.makedirs(os.path.dirname(report_path), exist_ok=True)
    with open(report_path, "w") as f:
        json.dump(report, f, indent=1, sort_keys=True)
        f.write("\n")
    _log(f"report: {report_path}", log)
    return report


def _hier_probe_row(topology: str, slices: int, program: str, batch: int,
                    mode: str, hier: str, wire_format_dcn: str) -> dict:
    """Compile + score ONE two-level-collective candidate; returns the
    report row, its DB descriptor, and the comm-aware predicted dict as
    one JSON payload.

    Runs inside a worker subprocess spawned by ``hier_sweep`` (see its
    docstring for why isolation).  The parent holds the AOT lock; this
    helper must not re-take it."""
    from tpuframe.analysis import collective_graph as cg
    from tpuframe.analysis import hlo_audit, shardflow
    from tpuframe.parallel import pspec

    os.environ.setdefault("TPU_SKIP_MDS_QUERY", "1")
    gen = roofline.generation_from_topology(topology)
    devices = pspec.topology_devices(topology, slices=slices)
    n = len(devices)
    compiled, desc, _opt_bytes, _census = _zero1_step_compile(
        devices, program, batch, mode, slices=slices,
        hier=hier, wire_format_dcn=wire_format_dcn)
    hlo = compiled.as_text()
    pred = roofline.score_compiled(compiled, gen)
    pred["source"] = "compiled"
    coll = hlo_audit.parse_collectives(hlo)
    split = shardflow.comm_split(
        cg.parse_graph(hlo), coll.filter(1024),
        mesh_shape={"slice": slices, "data": n // slices}, n_devices=n)
    # The TPU backend routes the cross-slice hop through the MegaScale
    # transport (host-transfer send/recv), not HLO collectives — fold
    # those bytes into the DCN column or the sweep scores DCN as free.
    for kind, nbytes in shardflow.megascale_split(hlo).items():
        split["dcn"][kind] = split["dcn"].get(kind, 0) + int(nbytes)
    css = roofline.comm_split_score(gen, split, n_devices=n,
                                    n_slices=slices)
    total_ms = round(pred["predicted_ms"] + css["t_ici_ms"]
                     + css["t_dcn_ms"], 3)
    pred["comm_split"] = css
    pred["t_step_ms"] = pred["predicted_ms"]
    pred["predicted_ms"] = total_ms  # comm-aware rank (see hier_sweep)
    row = {"program": program, "hier": hier,
           "wire_format_dcn": wire_format_dcn, "weight_update": mode,
           "global_batch": batch,
           "t_step_ms": pred["t_step_ms"],
           "t_ici_ms": css["t_ici_ms"],
           "t_dcn_ms": css["t_dcn_ms"],
           "predicted_total_ms": total_ms,
           "ici_bytes": css["ici_bytes"],
           "dcn_bytes": css["dcn_bytes"], "bound": pred["bound"]}
    return {"row": row, "desc": desc, "pred": pred}


def _fusion_probe_row(topology: str, program: str, batch: int,
                      threshold: int | None, floor: int) -> dict:
    """Compile + score ONE fusion candidate and return its report row.

    Runs inside a worker subprocess spawned by ``fusion_sweep`` — a
    bucket shape can abort libtpu's fusion emitter outright (a CHECK
    failure in ``fusion_emitter.cc``, observed at 256 KiB+ buckets on
    the ResNet-50 step, PERF §26), and a SIGABRT in-process would take
    the whole sweep and its partial report down with it.  The parent
    holds the AOT lock; this helper must not re-take it."""
    from jax.experimental import topologies

    from tpuframe.analysis import collective_graph as cg
    from tpuframe.analysis import hlo_audit, shardflow

    os.environ.setdefault("TPU_SKIP_MDS_QUERY", "1")
    gen = roofline.generation_from_topology(topology)
    topo = topologies.get_topology_desc(topology, platform="tpu")
    n = len(topo.devices)
    compiled, _desc, _opt, _census = _zero1_step_compile(
        topo.devices, program, batch, "replicated",
        fusion_threshold=threshold)
    txt = compiled.as_text()
    pred = roofline.score_compiled(compiled, gen)
    coll = hlo_audit.parse_collectives(txt)
    comm = roofline.comm_score(gen, coll.filter(floor), n)
    total_ms = round(pred["predicted_ms"] + comm["t_ici_ms"], 3)
    graph = cg.parse_graph(txt)
    entry = shardflow.derive_schedule_entry(graph, ignore_below=floor)
    score = shardflow.overlap_score(graph, coll, n_devices=n,
                                    ignore_below=floor, generation=gen)
    return {"program": program, "fusion_threshold": threshold,
            "global_batch": batch,
            "collectives_above_floor": score["collectives_above_floor"],
            "comm_bytes": comm["comm_bytes"],
            "overlap_potential": score["overlap_potential"],
            "comm_ms": score["comm_ms"],
            "hideable_ms": score["hideable_ms"],
            "interleavable_bytes": entry["interleavable_bytes"],
            "async_pairs": entry["async_pairs"],
            "predicted_ms": pred["predicted_ms"],
            "t_ici_ms": comm["t_ici_ms"],
            "predicted_total_ms": total_ms}


def _crash_reason(stderr: str, returncode: int) -> str:
    """Condense a dead probe's stderr to the line that names the abort."""
    lines = [ln.strip() for ln in (stderr or "").splitlines() if ln.strip()]
    for ln in reversed(lines):
        if "Check failed" in ln or "CHECK failed" in ln:
            return ln[:300]
    for ln in reversed(lines):
        if "Error" in ln or "error" in ln:
            return ln[:300]
    tail = lines[-1][:200] if lines else ""
    return f"probe exited {returncode}" + (f": {tail}" if tail else "")


def fusion_sweep(topology: str = "v5e:2x2", *, db_path: str | None = None,
                 report_path: str | None = None, batch: int = 512,
                 thresholds=(16384, 32768, 65536, 131072, 262144),
                 log=None) -> dict:
    """Offline gradient-fusion bucket-threshold search: AOT-compile the
    donated ResNet-50 DP train step once per ``threshold_bytes`` over
    the full topology, rank on the schedule plane's ``overlap_score``
    (how much of each bucket's wire time has legally interleavable
    compute to hide behind it) plus the compiled wire bytes, and persist
    the winner to the ``fusion_threshold`` DB family.  Small buckets
    give the scheduler more interior windows but pay more per-collective
    latency; huge buckets degenerate to the end-of-backprop sync pack
    (one window, nothing left to overlap) — the sweep finds the knee.
    An unfused per-leaf baseline row rides along for comparison but is
    never the winner.

    Each candidate compiles in its OWN worker subprocess
    (``python -m tpuframe.tune _fusion-probe``): libtpu's fusion
    emitter can hard-abort (CHECK failure, SIGABRT) on some bucket
    shapes, and isolation turns a compiler crash into a recorded
    ``compile_errors`` row instead of losing the sweep."""
    import subprocess
    import tempfile

    import jax  # noqa: F401 — fail fast before holding the lock

    hold_aot_lock()
    os.environ.setdefault("TPU_SKIP_MDS_QUERY", "1")
    gen = roofline.generation_from_topology(topology)
    n = roofline.n_chips_from_topology(topology)
    floor = 1024  # fused_dp_budget's floor — every bucket counts
    program = "resnet50"
    _log(f"fusion sweep on {topology} ({n} chips): {program} dp x "
         f"{list(thresholds)} + unfused baseline", log)

    db_path = db_path or tune_db.default_db_path()
    db = tune_db.TuningDB.open(db_path) if os.path.exists(db_path) \
        else tune_db.TuningDB(db_path)
    report = {"topology": topology, "generation": gen, "n_chips": n,
              "objective": "overlap_potential desc, then wire bytes "
                           "and predicted_total_ms asc",
              "ignore_below": floor,
              "fusion": {"rows": [], "compile_errors": []}}

    candidates = [None] + [int(t) for t in thresholds]
    for threshold in candidates:
        tag = "unfused" if threshold is None else str(threshold)
        with tempfile.NamedTemporaryFile(suffix=".json",
                                         delete=False) as tf:
            out_path = tf.name
        cmd = [sys.executable, "-m", "tpuframe.tune", "_fusion-probe",
               "--topology", topology, "--program", program,
               "--batch", str(batch), "--floor", str(floor),
               "--out", out_path]
        if threshold is not None:
            cmd += ["--threshold", str(threshold)]
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=1800)
            rc, stderr = proc.returncode, proc.stderr
        except subprocess.TimeoutExpired:
            rc, stderr = -1, "probe timed out after 1800 s"
        try:
            if rc == 0:
                with open(out_path) as f:
                    row = json.load(f)
                report["fusion"]["rows"].append(row)
                _log(f"  {program}/{tag}: overlap "
                     f"{row['overlap_potential']}, "
                     f"{row['collectives_above_floor']} collective(s) "
                     f"{row['comm_bytes'] / 1e6:.2f} MB, "
                     f"{row['predicted_total_ms']} ms total", log)
            else:
                err = {"program": program, "fusion_threshold": threshold,
                       "returncode": rc,
                       "error": _crash_reason(stderr, rc)}
                report["fusion"]["compile_errors"].append(err)
                _log(f"  {program}/{tag}: COMPILE CRASH (rc {rc}) "
                     f"{err['error'][:80]}", log)
        finally:
            if os.path.exists(out_path):
                os.unlink(out_path)

    fused_rows = [r for r in report["fusion"]["rows"]
                  if r["fusion_threshold"] is not None]
    fused_rows.sort(key=lambda r: (-(r["overlap_potential"] or 0.0),
                                   r["comm_bytes"],
                                   r["predicted_total_ms"]))
    if fused_rows:
        w = fused_rows[0]
        report["winner"] = w
        pred_w = {"predicted_ms": w["predicted_ms"],
                  "predicted_total_ms": w["predicted_total_ms"],
                  "overlap_potential": w["overlap_potential"],
                  "comm_bytes": w["comm_bytes"], "source": "compiled"}
        # One winner per program: db.add keys on config, so a re-sweep
        # electing a different threshold would otherwise leave the old
        # winner behind and make resolve_fusion_threshold ambiguous.
        db.data["records"] = [
            r for r in db.data["records"]
            if not (r.get("family") == "fusion_threshold"
                    and r.get("program") == f"train_{program}_b{batch}")]
        db.add({"program": f"train_{program}_b{batch}",
                "family": "fusion_threshold",
                "fingerprint": tune_db.fingerprint(
                    {"program": f"train_{program}_b{batch}",
                     "n_chips": n, "global_batch": batch}),
                "topology": topology, "generation": gen,
                "config": {"fusion_threshold": w["fusion_threshold"],
                           "batch": batch},
                "predicted": pred_w})
        db.save()
        _log(f"winner: threshold {w['fusion_threshold']} "
             f"(overlap {w['overlap_potential']}) -> {db.path} "
             f"({len(db.data['records'])} records)", log)
    if report_path is None:
        tag = topology.replace(":", "_").replace("x", "")
        report_path = os.path.join(tune_db.repo_root(), "perf", "results",
                                   f"fusion_report_{tag}.json")
    os.makedirs(os.path.dirname(report_path), exist_ok=True)
    with open(report_path, "w") as f:
        json.dump(report, f, indent=1, sort_keys=True)
        f.write("\n")
    _log(f"report: {report_path}", log)
    return report


def sweep(topology: str = "v5e:2x2", *, db_path: str | None = None,
          report_path: str | None = None, seq: int = 2048,
          head_dim: int = 64, heads: int = 8, fa_batch: int = 4,
          blocks=(128, 256, 512), bench_batches=(256,),
          vmem_budget: int = DEFAULT_VMEM_BUDGET, log=None) -> dict:
    """Run the full offline sweep; returns the report dict (also written
    to ``report_path``) and persists every scored candidate into the DB."""
    import jax  # noqa: F401 — fail fast before holding the lock
    from jax.experimental import topologies

    hold_aot_lock()
    # off-GCP hosts: without this, libtpu's topology init polls the GCE
    # metadata server 30x per variable (minutes of 403s) before giving up
    os.environ.setdefault("TPU_SKIP_MDS_QUERY", "1")
    gen = roofline.generation_from_topology(topology)
    topo = topologies.get_topology_desc(topology, platform="tpu")
    _log(f"topology {topology}: {len(topo.devices)} compile-only devices",
         log)

    db_path = db_path or tune_db.default_db_path()
    db = tune_db.TuningDB.open(db_path) if os.path.exists(db_path) \
        else tune_db.TuningDB(db_path)
    report = {"topology": topology, "generation": gen,
              "fa": {"kept": [], "pruned": [], "compile_errors": []},
              "bench": {"rows": [], "compile_errors": []}}

    # -- flash-attention block grid ---------------------------------------
    kept, pruned = fa_block_candidates(seq, head_dim, blocks=blocks,
                                       budget=vmem_budget)
    report["fa"]["pruned"] = pruned
    _log(f"fa grid: {len(kept)} candidates, {len(pruned)} pruned "
         f"pre-compile (budget {vmem_budget >> 20} MiB)", log)
    program = f"flash_mha_s{seq}_d{head_dim}"
    # flash_mha's shard_map-aware out_shape needs jax.typeof (jax>=0.6);
    # without it the kernel cannot compile AT ALL in this host's jax —
    # same SKIP-not-PASS contract as tests/test_aot_tpu_compile.py: fall
    # back to the analytic touch model, recorded as such.
    fa_can_compile = hasattr(jax, "typeof")
    if kept and not fa_can_compile:
        _log("fa: jax.typeof unavailable — scoring the grid with the "
             "analytic touch model instead of compiled cost analysis "
             "(records tagged source=analytic)", log)
    for cand in kept:
        bq, bk = cand["fa_block_q"], cand["fa_block_k"]
        if fa_can_compile:
            try:
                compiled, desc = _fa_compile(topo.devices, seq, head_dim,
                                             heads, fa_batch, bq, bk)
            except Exception as e:  # noqa: BLE001 — record, keep sweeping
                row = {"fa_block_q": bq, "fa_block_k": bk,
                       "error": f"{type(e).__name__}: {e}"[:300]}
                report["fa"]["compile_errors"].append(row)
                _log(f"  fa {bq}x{bk}: COMPILE ERROR {row['error'][:80]}",
                     log)
                continue
            pred = roofline.score_compiled(compiled, gen)
            pred["source"] = "compiled"
        else:
            flops, nbytes = fa_analytic_cost(seq, head_dim, heads,
                                             fa_batch, bq, bk)
            pred = roofline.score(gen, flops=flops, bytes_accessed=nbytes)
            pred["source"] = "analytic"
            desc = {"program": program,
                    "shape": [fa_batch, seq, heads, head_dim],
                    "causal": True, "block_q": bq, "block_k": bk}
        pred["vmem_bytes"] = cand["vmem_bytes"]
        db.add({"program": program, "family": "flash_attention",
                "fingerprint": tune_db.fingerprint(desc),
                "topology": topology, "generation": gen,
                "config": {"fa_block_q": bq, "fa_block_k": bk},
                "predicted": pred})
        row = dict(cand)
        row.update(predicted_ms=pred["predicted_ms"], bound=pred["bound"])
        report["fa"]["kept"].append(row)
        _log(f"  fa {bq}x{bk}: {pred['predicted_ms']} ms ({pred['bound']}-"
             f"bound, vmem {cand['vmem_bytes'] >> 10} KiB)", log)

    # -- bench ResNet-50 step x compiler-option sets x batch --------------
    for batch_per_chip in bench_batches:
        for name, opts in xla_opts_candidate_sets():
            try:
                compiled, desc = _bench_step_compile(
                    topo.devices, batch_per_chip, opts)
            except Exception as e:  # noqa: BLE001
                row = {"opts_name": name, "batch": batch_per_chip,
                       "error": f"{type(e).__name__}: {e}"[:300]}
                report["bench"]["compile_errors"].append(row)
                _log(f"  bench b{batch_per_chip} {name}: COMPILE ERROR "
                     f"{row['error'][:80]}", log)
                continue
            pred = roofline.score_compiled(compiled, gen)
            db.add({"program": desc["program"],
                    "family": "bench_resnet50",
                    "fingerprint": tune_db.fingerprint(desc, opts),
                    "topology": topology, "generation": gen,
                    "config": {"xla_opts": opts, "opts_name": name,
                               "batch": batch_per_chip},
                    "predicted": pred})
            row = {"opts_name": name, "batch": batch_per_chip,
                   "predicted_ms": pred["predicted_ms"],
                   "bound": pred["bound"], "fits": pred["fits"],
                   "gb": round(pred["bytes"] / 1e9, 1)}
            report["bench"]["rows"].append(row)
            _log(f"  bench b{batch_per_chip} {name}: "
                 f"{pred['predicted_ms']} ms ({pred['bound']}-bound, "
                 f"fits={pred['fits']})", log)

    # -- rank + persist ---------------------------------------------------
    report["fa"]["kept"].sort(key=lambda r: (r["predicted_ms"],
                                             -r["vmem_bytes"]))
    report["bench"]["rows"].sort(key=lambda r: r["predicted_ms"])
    report["ranked"] = {
        "flash_attention": [
            {"config": r.config, "predicted_ms":
             r.predicted.get("predicted_ms"),
             "vmem_bytes": r.predicted.get("vmem_bytes")}
            for r in db.top_k(5, family="flash_attention", generation=gen)],
        "bench_resnet50": [
            {"config": r.config, "predicted_ms":
             r.predicted.get("predicted_ms")}
            for r in db.top_k(5, family="bench_resnet50", generation=gen)],
    }
    db.save()
    _log(f"tuning DB: {db.path} ({len(db.data['records'])} records)", log)
    if report_path is None:
        tag = topology.replace(":", "_").replace("x", "")
        report_path = os.path.join(tune_db.repo_root(), "perf", "results",
                                   f"tune_report_{tag}.json")
    os.makedirs(os.path.dirname(report_path), exist_ok=True)
    with open(report_path, "w") as f:
        json.dump(report, f, indent=1, sort_keys=True)
        f.write("\n")
    _log(f"report: {report_path}", log)
    return report


# ---------------------------------------------------------------------------
# Serving sweep: decode block sizes x slot counts for the serve_lm family.
# ---------------------------------------------------------------------------

def serve_bucket_sets(block: int, *, context_blocks: int = 4) -> tuple:
    """Prompt buckets derived from one decode block: powers of two up to
    the capacity (``context_blocks * block``) — the closed shape set the
    engine compiles for this block choice."""
    capacity = context_blocks * block
    buckets, b = [], block
    while b <= capacity:
        buckets.append(b)
        b *= 2
    return tuple(buckets), capacity


def _serve_decode_compile(topo_devices, cfg, slots: int, capacity: int):
    """AOT-compile the serving decode step (query length 1, donated KV)
    on ONE compile-only device — the exact program serve/engine.py
    builds, so the scored bytes are the served bytes."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpuframe.models.transformer_lm import TransformerLM
    from tpuframe.parallel import mesh as mesh_lib
    from tpuframe.serve import engine as engine_lib
    from tpuframe.serve import kv_cache as kv

    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec(data=1),
                              devices=list(topo_devices[:1]))
    repl = NamedSharding(mesh, P())
    model = TransformerLM(cfg)
    spec = kv.spec_for_model(cfg, slots=slots, capacity=capacity)
    decode_fn = engine_lib.make_decode_fn(model)

    variables = jax.eval_shape(model.init, jax.random.key(0),
                               jax.ShapeDtypeStruct((1, 8), jnp.int32))

    def _sds(s):
        return jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=repl)

    p_sds = jax.tree.map(_sds, variables["params"])
    param_bytes = sum(
        int(_prod(s.shape)) * jnp.dtype(s.dtype).itemsize
        for s in jax.tree_util.tree_leaves(variables["params"]))
    dtype = jnp.dtype(spec.dtype)

    def sds(shape, dt):
        return jax.ShapeDtypeStruct(shape, dt, sharding=repl)

    cache_sds = tuple((sds(spec.layer_shape(), dtype),
                       sds(spec.layer_shape(), dtype))
                      for _ in range(cfg.num_layers))
    compiled = jax.jit(decode_fn, donate_argnums=(1, 2, 3)).lower(
        p_sds, sds((slots, 1), jnp.int32), sds((slots,), jnp.int32),
        cache_sds).compile()
    desc = {"program": f"serve_decode_h{cfg.hidden_size}_"
                       f"l{cfg.num_layers}",
            "slots": slots, "capacity": capacity, "n_chips": 1,
            "dtype": cfg.dtype, "donate": True}
    return compiled, desc, param_bytes, spec


def _prod(shape) -> int:
    out = 1
    for d in shape:
        out *= int(d)
    return out


def serve_sweep(topology: str = "v5e:2x2", *, db_path: str | None = None,
                report_path: str | None = None,
                blocks=(64, 128, 256), slots_grid=(8, 16),
                context_blocks: int = 4, log=None) -> dict:
    """Offline serving sweep: decode block sizes x slot counts for the
    ``serve_lm`` family, on a mid-size decoder (the smallest config
    where the params-vs-KV traffic split is representative).

    Objective is predicted ms PER TOKEN (step roofline / slots) — lower
    is better and ranks identically to tokens/sec/chip, but fits the
    DB's ``predicted_ms``-ascending ``_rank()`` contract directly.  Each
    row carries both the compiled ``cost_analysis`` roofline (when this
    jax can AOT-compile for the topology) and the analytic decode model
    (``roofline.decode_score``); compile failures degrade to the
    analytic row tagged ``source="analytic"`` — same SKIP-not-lie
    contract as the flash-attention grid above.
    """
    import jax  # noqa: F401 — fail fast before holding the lock
    from jax.experimental import topologies

    from tpuframe.models.transformer_lm import LMConfig
    from tpuframe.serve import kv_cache as kv_lib

    hold_aot_lock()
    os.environ.setdefault("TPU_SKIP_MDS_QUERY", "1")
    gen = roofline.generation_from_topology(topology)
    topo = topologies.get_topology_desc(topology, platform="tpu")
    _log(f"serve sweep on {topology}: blocks {tuple(blocks)} x slots "
         f"{tuple(slots_grid)}", log)

    cfg = LMConfig(vocab_size=8192, hidden_size=512, num_layers=4,
                   num_heads=8, intermediate_size=2048,
                   max_seq=context_blocks * max(blocks),
                   dtype="bfloat16", attn_impl="xla")
    program = f"serve_decode_h{cfg.hidden_size}_l{cfg.num_layers}"

    db_path = db_path or tune_db.default_db_path()
    db = tune_db.TuningDB.open(db_path) if os.path.exists(db_path) \
        else tune_db.TuningDB(db_path)
    report = {"topology": topology, "generation": gen, "program": program,
              "objective": "predicted_ms_per_token",
              "model": {"hidden": cfg.hidden_size,
                        "layers": cfg.num_layers, "heads": cfg.num_heads,
                        "dtype": cfg.dtype},
              "serve": {"rows": [], "compile_errors": []}}

    for block in blocks:
        buckets, capacity = serve_bucket_sets(
            block, context_blocks=context_blocks)
        for slots in slots_grid:
            spec = kv_lib.spec_for_model(cfg, slots=slots,
                                         capacity=capacity)
            analytic = roofline.decode_score(
                param_bytes=_model_param_bytes(cfg),
                kv_bytes_per_token=spec.bytes_per_token(),
                slots=slots, context=capacity, generation=gen,
                param_dtype_bytes=2)
            pred = None
            try:
                compiled, desc, pb, _ = _serve_decode_compile(
                    topo.devices, cfg, slots, capacity)
                pred = roofline.score_compiled(compiled, gen)
                pred["source"] = "compiled"
            except Exception as e:  # noqa: BLE001 — record, keep sweeping
                err = f"{type(e).__name__}: {e}"[:300]
                report["serve"]["compile_errors"].append(
                    {"decode_block": block, "slots": slots, "error": err})
                _log(f"  serve block={block} slots={slots}: COMPILE "
                     f"FALLBACK {err[:80]}", log)
                desc = {"program": program, "slots": slots,
                        "capacity": capacity, "dtype": cfg.dtype}
                pred = roofline.score(
                    gen, flops=analytic.flops_per_step,
                    bytes_accessed=analytic.bytes_per_step)
                pred["source"] = "analytic"
            # Per-token objective + the throughput bound the report and
            # obs comparisons use.
            pred["predicted_ms"] = round(pred["predicted_ms"]
                                         / max(slots, 1), 4)
            pred["tokens_per_s_per_chip"] = round(
                slots / (pred["predicted_ms"] * 1e-3 * slots), 2) \
                if pred["predicted_ms"] > 0 else None
            pred["analytic_tokens_per_s_per_chip"] = \
                analytic.tokens_per_s_per_chip
            config = {"decode_block": int(block),
                      "prompt_buckets": [int(b) for b in buckets],
                      "slots": int(slots)}
            db.add({"program": program, "family": "serve_lm",
                    "fingerprint": tune_db.fingerprint(desc),
                    "topology": topology, "generation": gen,
                    "config": config, "predicted": pred})
            row = dict(config)
            row.update(capacity=capacity, source=pred["source"],
                       predicted_ms_per_token=pred["predicted_ms"],
                       bound=pred["bound"],
                       tokens_per_s_per_chip=pred["tokens_per_s_per_chip"],
                       analytic_tokens_per_s_per_chip=(
                           analytic.tokens_per_s_per_chip))
            report["serve"]["rows"].append(row)
            _log(f"  serve block={block} slots={slots}: "
                 f"{pred['predicted_ms']} ms/token "
                 f"({pred['bound']}-bound, "
                 f"{pred['tokens_per_s_per_chip']} tok/s/chip, "
                 f"{pred['source']})", log)

    report["serve"]["rows"].sort(key=lambda r: r["predicted_ms_per_token"])
    report["winner"] = (report["serve"]["rows"][0]
                        if report["serve"]["rows"] else None)
    report["ranked"] = [
        {"config": r.config,
         "predicted_ms_per_token": r.predicted.get("predicted_ms"),
         "source": r.predicted.get("source")}
        for r in db.top_k(5, family="serve_lm", generation=gen)]
    db.save()
    _log(f"tuning DB: {db.path} ({len(db.data['records'])} records)", log)
    if report_path is None:
        tag = topology.replace(":", "_").replace("x", "")
        report_path = os.path.join(tune_db.repo_root(), "perf", "results",
                                   f"serve_report_{tag}.json")
    os.makedirs(os.path.dirname(report_path), exist_ok=True)
    with open(report_path, "w") as f:
        json.dump(report, f, indent=1, sort_keys=True)
        f.write("\n")
    _log(f"report: {report_path}", log)
    return report


def _model_param_bytes(cfg) -> int:
    """Parameter bytes of a TransformerLM without building arrays."""
    import jax
    import jax.numpy as jnp

    from tpuframe.models.transformer_lm import TransformerLM

    variables = jax.eval_shape(TransformerLM(cfg).init, jax.random.key(0),
                               jax.ShapeDtypeStruct((1, 8), jnp.int32))
    return sum(int(_prod(s.shape)) * jnp.dtype(s.dtype).itemsize
               for s in jax.tree_util.tree_leaves(variables["params"]))
